#!/usr/bin/env python
"""Grep-based lint: every registered metric name is Prometheus-legal.

The telemetry registry (trino_tpu/telemetry/metrics.py) validates names at
registration time, but a misnamed metric in a lazily-imported module only
blows up when that code path first runs — long after CI went green.  This
lint finds every ``REGISTRY.counter("...")`` / ``.gauge("...")`` /
``.distribution("...")`` registration site statically and enforces the
naming scheme up front:

- names match the Prometheus data model (``[a-zA-Z_:][a-zA-Z0-9_:]*``)
- every name carries the mandatory ``trino_`` prefix (one flat namespace,
  greppable across coordinator and worker scrapes)
- counters end in ``_total`` (Prometheus counter convention; the registry
  appends no suffix itself)
- no metric name literal is registered at two distinct sites (two sites
  silently sharing one cell is almost always a copy-paste bug; share the
  module-level handle instead)

A justified exception carries a ``# metric-ok`` pragma.  Like
tools/lint_host_sync.py this is deliberately dumb — regex over lines, no
AST — so it runs in milliseconds and is obvious to extend.

Run directly (``python tools/lint_metric_names.py``; exit 1 on findings) or
via the tier-1 test tests/test_metric_lint.py.
"""

from __future__ import annotations

import os
import re
import sys

# one registration site: .counter("name" / .gauge("name" / .distribution("name
REGISTRATION = re.compile(
    r"\.(?P<kind>counter|gauge|distribution)\(\s*[\"'](?P<name>[^\"']*)[\"']")
LEGAL = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
PREFIX = "trino_"
SCAN_DIR = "trino_tpu"
PRAGMA = "metric-ok"


def _logical_lines(path: str):
    """(lineno, line) pairs, with a registration call split across the
    black-style line break — ``REGISTRY.counter(`` then the name on the
    next line — rejoined so the per-line regex still sees it."""
    with open(path, encoding="utf-8") as f:
        lines = f.readlines()
    i = 0
    while i < len(lines):
        line = lines[i]
        if line.rstrip().endswith("(") and i + 1 < len(lines):
            yield i + 1, line.rstrip() + lines[i + 1].lstrip()
            i += 2
            continue
        yield i + 1, line
        i += 1


def lint_file(path: str) -> list[tuple[str, int, str, str]]:
    """-> [(path, lineno, metric_name, problem)] for one file."""
    findings = []
    for lineno, line in _logical_lines(path):
        if PRAGMA in line:
            continue
        for m in REGISTRATION.finditer(line):
            kind, name = m.group("kind"), m.group("name")
            if not LEGAL.match(name):
                findings.append((path, lineno, name,
                                 "illegal Prometheus metric name"))
            elif not name.startswith(PREFIX):
                findings.append((path, lineno, name,
                                 f"missing mandatory {PREFIX!r} prefix"))
            elif kind == "counter" and not name.endswith("_total"):
                findings.append((path, lineno, name,
                                 "counter name must end in '_total'"))
    return findings


def registrations(root: str) -> dict[str, list[tuple[str, int]]]:
    """metric name -> [(path, lineno)] across the tree (duplicate check)."""
    sites: dict[str, list[tuple[str, int]]] = {}
    for dirpath, _dirs, files in os.walk(os.path.join(root, SCAN_DIR)):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            for lineno, line in _logical_lines(path):
                if PRAGMA in line:
                    continue
                for m in REGISTRATION.finditer(line):
                    sites.setdefault(m.group("name"), []).append(
                        (path, lineno))
    return sites


# metric families the observability plane is contractually expected to
# expose (PR 11 flight recorder, PR 12 cache plane): at least one
# registration of each must exist, so a refactor can't silently drop the
# profiler/journal/cache telemetry
REQUIRED_FAMILIES = ("trino_profile_", "trino_journal_", "trino_cache_",
                     "trino_adaptive_")


def run(root: str, require_families: bool = False
        ) -> list[tuple[str, int, str, str]]:
    findings = []
    for dirpath, _dirs, files in os.walk(os.path.join(root, SCAN_DIR)):
        for fn in sorted(files):
            if fn.endswith(".py"):
                findings.extend(lint_file(os.path.join(dirpath, fn)))
    sites_by_name = registrations(root)
    for name, sites in sorted(sites_by_name.items()):
        if len(sites) > 1:
            for path, lineno in sites[1:]:
                findings.append((path, lineno, name,
                                 f"duplicate registration (first at "
                                 f"{sites[0][0]}:{sites[0][1]})"))
    if require_families:
        for fam in REQUIRED_FAMILIES:
            if not any(n.startswith(fam) for n in sites_by_name):
                findings.append(
                    (os.path.join(root, SCAN_DIR), 0, fam + "*",
                     "required metric family has no registration site"))
    return findings


def main() -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = run(root, require_families=True)
    for path, lineno, name, problem in findings:
        rel = os.path.relpath(path, root)
        print(f"{rel}:{lineno}: {name!r}: {problem}")
    if findings:
        print(f"\n{len(findings)} metric naming violation(s); "
              f"annotate justified exceptions with  # {PRAGMA}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
