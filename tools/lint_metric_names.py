#!/usr/bin/env python3
"""Legacy entry point — the metric-names lint now lives in the tpulint
framework (tools/analysis/rules/metric_names.py) as an AST rule over
``REGISTRY.counter/gauge/distribution`` call sites.

This shim keeps the historical CLI (``python tools/lint_metric_names.py``)
and module API (``lint_file``, ``run``) stable for
tests/test_metric_lint.py.  Prefer ``python -m tools.analysis``.
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.analysis.rules.metric_names import (  # noqa: E402,F401
    LEGAL,
    PREFIX,
    REQUIRED_FAMILIES,
    lint_file,
    main,
    run,
)

if __name__ == "__main__":
    sys.exit(main())
