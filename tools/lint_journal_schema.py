#!/usr/bin/env python3
"""Legacy entry point — the journal-schema lint now lives in the tpulint
framework (tools/analysis/rules/journal_schema.py).  Still the one
dynamic rule: it imports trino_tpu/telemetry/journal.py and exercises
``sample_records()`` because the schema contract lives in code.

This shim keeps the historical CLI (``python tools/lint_journal_schema.py``)
and module API (``lint_record``, ``run``) stable for
tests/test_journal.py.  Prefer ``python -m tools.analysis``.
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.analysis.rules.journal_schema import (  # noqa: E402,F401
    lint_record,
    main,
    run,
)

if __name__ == "__main__":
    sys.exit(main())
