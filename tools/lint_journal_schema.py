#!/usr/bin/env python
"""Journal schema lint: every record the query journal can emit is sound.

The durable query journal (trino_tpu/telemetry/journal.py) is read back by
``system.runtime.query_history`` and by the admission estimator's restart
seeding, so a record that doesn't round-trip through JSON — or drops the
versioned ``schema`` field — corrupts consumers long after the write went
green.  This lint materializes one representative record per event type
(``journal.sample_records()``) and enforces the contract up front:

- the record JSON-serializes AND parses back to an equal dict (no sets,
  no raw dataclasses, no NaN round-trip surprises)
- ``schema`` is present and equals ``journal.SCHEMA_VERSION`` (readers
  key forward-compat decisions off it)
- every ``journal.REQUIRED_FIELDS`` key is present
- field values stay JSON-scalar (str/int/float/bool/None) — nested
  containers would break the flat query_history column mapping

Run directly (``python tools/lint_journal_schema.py``; exit 1 on findings)
or via the tier-1 test in tests/test_journal.py.
"""

from __future__ import annotations

import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_SCALARS = (str, int, float, bool, type(None))


def lint_record(rec: dict) -> list[str]:
    problems = []
    from trino_tpu.telemetry import journal

    event = rec.get("event", "<unknown>")
    try:
        line = json.dumps(rec, allow_nan=False)
    except (TypeError, ValueError) as e:
        return [f"{event}: record does not JSON-serialize: {e}"]
    back = json.loads(line)
    if back != rec:
        problems.append(f"{event}: record does not round-trip through JSON")
    if rec.get("schema") != journal.SCHEMA_VERSION:
        problems.append(
            f"{event}: schema field is {rec.get('schema')!r}, expected "
            f"{journal.SCHEMA_VERSION}")
    for field in journal.REQUIRED_FIELDS:
        if field not in rec:
            problems.append(f"{event}: missing required field {field!r}")
    for k, v in rec.items():
        if not isinstance(v, _SCALARS):
            problems.append(
                f"{event}: field {k!r} is {type(v).__name__}, not a "
                f"JSON scalar")
        if isinstance(v, float) and not math.isfinite(v):
            problems.append(f"{event}: field {k!r} is non-finite ({v})")
    return problems


def run() -> list[str]:
    from trino_tpu.telemetry import journal

    problems = []
    records = journal.sample_records()
    if not records:
        return ["journal.sample_records() returned no records"]
    events = {r.get("event") for r in records}
    for required in ("query_created", "query_completed"):
        if required not in events:
            problems.append(f"no sample record for event {required!r}")
    for rec in records:
        problems.extend(lint_record(rec))
    return problems


def main() -> int:
    problems = run()
    for p in problems:
        print(p)
    if problems:
        print(f"\n{len(problems)} journal schema violation(s)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
