"""error-taxonomy: failures on the query path are classified, not generic.

The resilience layer (spi/errors.py) only works when every failure the
coordinator acts on carries an ErrorCode: USER errors must never retry,
EXTERNAL ones must blacklist the implicated worker, INSUFFICIENT_RESOURCES
must grow the budget.  A ``raise RuntimeError`` on the query path — or a
handler that swallows ``Exception`` whole — punches a hole in that
contract: the failure degrades to GENERIC_INTERNAL_ERROR (retrying user
bugs) or vanishes entirely.  Three checks over ``trino_tpu/execution/``
and ``trino_tpu/exec/``:

- **bare except** — ``except:`` catches SystemExit/KeyboardInterrupt and
  is never right; flagged everywhere in scope.
- **blind swallow** — ``except Exception: pass`` (body only pass/constant)
  silently discards a failure the taxonomy should have classified.
  Narrow swallows (``except FileNotFoundError: pass``) are fine.
- **generic raise** — ``raise RuntimeError/ValueError/... (...)`` on the
  query path must be a :class:`TrinoError` with a real code, or routed
  through ``spi.errors.classify``.  ``NotImplementedError`` (feature
  gaps classified NOT_SUPPORTED at the boundary) and ``AssertionError``
  (invariants) stay allowed.

Deliberate exceptions carry ``# tpulint: disable=error-taxonomy --
reason``; grandfathered pre-registry sites live in the committed baseline.
"""

from __future__ import annotations

import ast

from ..core import Finding, ProjectIndex
from . import Rule

NAME = "error-taxonomy"
SCAN = ("trino_tpu/execution/", "trino_tpu/exec/")

# generic builtins that erase classification when raised on the query path
GENERIC_RAISES = {
    "Exception", "BaseException", "RuntimeError", "ValueError", "TypeError",
    "KeyError", "IndexError", "OSError", "IOError", "SystemError",
    "StopIteration", "ArithmeticError", "ZeroDivisionError",
}
BROAD_CATCHES = {"Exception", "BaseException"}


def _handler_names(handler: ast.ExceptHandler) -> set:
    t = handler.type
    if t is None:
        return set()
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    names = set()
    for e in elts:
        if isinstance(e, ast.Name):
            names.add(e.id)
        elif isinstance(e, ast.Attribute):
            names.add(e.attr)
    return names


def _body_swallows(body: list) -> bool:
    """True when the handler body does nothing with the failure: only
    pass/Ellipsis/docstring statements."""
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue
        return False
    return True


def check(index: ProjectIndex) -> list:
    findings = []
    for sf in index.iter_files(SCAN):
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ExceptHandler):
                if node.type is None:
                    findings.append(Finding(
                        NAME, sf.rel, node.lineno,
                        "bare 'except:' catches SystemExit/"
                        "KeyboardInterrupt — name the exception and "
                        "classify it (spi.errors.classify)",
                        sf.line(node.lineno).strip()))
                elif (_handler_names(node) & BROAD_CATCHES
                      and _body_swallows(node.body)):
                    findings.append(Finding(
                        NAME, sf.rel, node.lineno,
                        "blind 'except Exception: pass' swallows a "
                        "failure the error taxonomy should classify — "
                        "narrow the type, log it, or re-raise classified",
                        sf.line(node.lineno).strip()))
            elif isinstance(node, ast.Raise):
                exc = node.exc
                if not isinstance(exc, ast.Call):
                    continue
                fn = exc.func
                name = fn.id if isinstance(fn, ast.Name) else (
                    fn.attr if isinstance(fn, ast.Attribute) else None)
                if name in GENERIC_RAISES:
                    findings.append(Finding(
                        NAME, sf.rel, node.lineno,
                        f"raise {name} on the query path erases error "
                        f"classification — raise TrinoError with a real "
                        f"ErrorCode or route through spi.errors.classify",
                        sf.line(node.lineno).strip()))
    return findings


RULES = [Rule(NAME, "no bare/blind excepts or generic unclassified raises "
              "on the query path", check)]
