"""journal-schema: every record the query journal can emit is sound.

Framework home of tools/lint_journal_schema.py.  The durable query journal
(trino_tpu/telemetry/journal.py) is read back by
``system.runtime.query_history`` and by the admission estimator's restart
seeding, so a record that doesn't round-trip through JSON — or drops the
versioned ``schema`` field — corrupts consumers long after the write went
green.  This rule materializes one representative record per event type
(``journal.sample_records()``) and enforces the contract up front.

Unlike the pure-AST rules this one is *dynamic*: it imports the journal
module and exercises its sample-record factory.  That is the point — the
schema contract lives in code, and the only faithful check runs it.
"""

from __future__ import annotations

import json
import math

from ..core import Finding, ProjectIndex
from . import Rule

NAME = "journal-schema"
JOURNAL_REL = "trino_tpu/telemetry/journal.py"

_SCALARS = (str, int, float, bool, type(None))


def lint_record(rec: dict) -> list:
    """-> [problem] for one journal record (compat with the old tool)."""
    problems = []
    from trino_tpu.telemetry import journal

    event = rec.get("event", "<unknown>")
    try:
        line = json.dumps(rec, allow_nan=False)
    except (TypeError, ValueError) as e:
        return [f"{event}: record does not JSON-serialize: {e}"]
    back = json.loads(line)
    if back != rec:
        problems.append(f"{event}: record does not round-trip through JSON")
    if rec.get("schema") != journal.SCHEMA_VERSION:
        problems.append(
            f"{event}: schema field is {rec.get('schema')!r}, expected "
            f"{journal.SCHEMA_VERSION}")
    for field in journal.REQUIRED_FIELDS:
        if field not in rec:
            problems.append(f"{event}: missing required field {field!r}")
    for k, v in rec.items():
        if event == "plan_stats" and k == "nodes":
            # the one sanctioned nested field (schema v2): fingerprint ->
            # {rows/bytes/groups/skew scalars}
            problems.extend(_lint_plan_stats_nodes(v))
            continue
        if not isinstance(v, _SCALARS):
            problems.append(
                f"{event}: field {k!r} is {type(v).__name__}, not a "
                f"JSON scalar")
        if isinstance(v, float) and not math.isfinite(v):
            problems.append(f"{event}: field {k!r} is non-finite ({v})")
    return problems


def _lint_plan_stats_nodes(nodes) -> list:
    from trino_tpu.telemetry import journal

    if not isinstance(nodes, dict):
        return [f"plan_stats: nodes is {type(nodes).__name__}, not a dict"]
    problems = []
    for fp, st in nodes.items():
        if not isinstance(fp, str):
            problems.append(f"plan_stats: fingerprint {fp!r} is not a str")
        if not isinstance(st, dict):
            problems.append(f"plan_stats: nodes[{fp!r}] is not a dict")
            continue
        if not st:
            problems.append(f"plan_stats: nodes[{fp!r}] is empty")
        for k, v in st.items():
            if k not in journal.PLAN_STATS_FIELDS:
                problems.append(
                    f"plan_stats: nodes[{fp!r}] has unknown field {k!r} "
                    f"(allowed: {journal.PLAN_STATS_FIELDS})")
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                problems.append(
                    f"plan_stats: nodes[{fp!r}][{k!r}] is "
                    f"{type(v).__name__}, not a number")
            elif isinstance(v, float) and not math.isfinite(v):
                problems.append(
                    f"plan_stats: nodes[{fp!r}][{k!r}] is non-finite")
    return problems


def run() -> list:
    """-> [problem] across all sample records (compat with the old tool)."""
    from trino_tpu.telemetry import journal

    problems = []
    records = journal.sample_records()
    if not records:
        return ["journal.sample_records() returned no records"]
    events = {r.get("event") for r in records}
    for required in ("query_created", "query_completed", "plan_stats"):
        if required not in events:
            problems.append(f"no sample record for event {required!r}")
    for rec in records:
        problems.extend(lint_record(rec))
    return problems


def check(index: ProjectIndex) -> list:
    import sys

    if index.root not in sys.path:
        sys.path.insert(0, index.root)
    try:
        problems = run()
    except Exception as e:  # import/sample failure IS a finding, not a crash
        problems = [f"journal schema check failed to run: "
                    f"{type(e).__name__}: {e}"]
    return [Finding(NAME, JOURNAL_REL, 0, p) for p in problems]


def main() -> int:
    from . import rule_main
    return rule_main(NAME, epilogue="fix the record factory in "
                     "trino_tpu/telemetry/journal.py")


RULES = [Rule(NAME, "journal records JSON round-trip with versioned "
              "schema and scalar fields", check)]
