"""tpulint rule registry.

Each rule module exports ``RULES`` — a list of :class:`Rule` whose
``check(index)`` returns findings.  Order here is presentation order.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable


@dataclass(frozen=True)
class Rule:
    name: str
    doc: str
    check: Callable

    def __call__(self, index):
        return self.check(index)


def all_rules() -> list:
    # imported lazily so a syntax error in one rule module names itself
    # instead of breaking the registry import
    from . import (cache_bounds, error_taxonomy, host_sync, hygiene,
                   journal_schema, knob_registry, metric_names, net_timeout,
                   thread_safety)

    rules: list = []
    for mod in (host_sync, thread_safety, knob_registry, error_taxonomy,
                net_timeout, metric_names, cache_bounds, journal_schema,
                hygiene):
        rules.extend(mod.RULES)
    return rules


def rules_by_name() -> dict:
    return {r.name: r for r in all_rules()}


def rule_main(*names, epilogue: str = "") -> int:
    """Shared CLI body for the legacy ``tools/lint_*.py`` shims: run the
    named rule(s) through the full pipeline (suppressions + baseline), so
    a shim invocation agrees exactly with ``python -m tools.analysis``."""
    import sys

    from .. import run_analysis

    report = run_analysis(rule_names=list(names))
    for f in report.findings:
        print(f.format(), file=sys.stderr)
    if report.findings and epilogue:
        print(f"{len(report.findings)} finding(s) — {epilogue}",
              file=sys.stderr)
    return 0 if report.clean else 1
