"""cache-bounds: no new unbounded memoization outside the registry.

AST successor of the grep lint tools/lint_cache_bounds.py.  PR 12
centralized every jitted-program memo behind
``trino_tpu/caching/executable_cache.jit_memo`` — bounded, observable via
``system.runtime.caches``, evictable, and journaled for boot-time warming.
An ad-hoc ``@lru_cache(maxsize=None)`` on a jit-wrapper builder silently
reintroduces the pre-PR-12 failure mode.  Rejected forms:

- bare ``@lru_cache`` / ``@functools.lru_cache`` (unbounded)
- ``lru_cache()`` / ``lru_cache(maxsize=None)`` anywhere (not just as a
  decorator — the AST sees ``f = lru_cache(maxsize=None)(f)`` too)
- ``@functools.cache`` / ``@cache`` (always unbounded)

Bounded ``lru_cache(maxsize=N)`` passes.  The registry module itself
(caching/executable_cache.py) is exempt: the ``TRINO_TPU_EXEC_CACHE=0``
kill switch intentionally falls back to the bit-for-bit legacy unbounded
memo there.  A justified exception elsewhere carries the legacy
``# cache-ok`` pragma or a ``# tpulint: disable=cache-bounds`` directive.
"""

from __future__ import annotations

import ast

from ..core import Finding, ProjectIndex
from . import Rule

NAME = "cache-bounds"
SCAN_DIR = "trino_tpu"
EXEMPT = "trino_tpu/caching/executable_cache.py"
LEGACY_PRAGMA = "cache-ok"
MESSAGE = ("unbounded memo cache — use caching.executable_cache.jit_memo "
           "(bounded, observable, warm-journaled) or lru_cache(maxsize=N)")


def _is_cache_name(node: ast.AST, names: tuple) -> bool:
    return ((isinstance(node, ast.Name) and node.id in names)
            or (isinstance(node, ast.Attribute) and node.attr in names
                and isinstance(node.value, ast.Name)
                and node.value.id == "functools"))


def _unbounded_nodes(tree: ast.Module) -> list:
    """-> [lineno] of every unbounded-memo form in one parsed module."""
    out = []
    decorator_calls = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            for dec in node.decorator_list:
                if _is_cache_name(dec, ("lru_cache", "cache")):
                    # bare @lru_cache / @cache — always unbounded
                    out.append(dec.lineno)
                elif isinstance(dec, ast.Call):
                    decorator_calls.add(id(dec))
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and _is_cache_name(node.func, ("lru_cache",))):
            continue
        maxsize = None
        for kw in node.keywords:
            if kw.arg == "maxsize":
                maxsize = kw.value
        if node.args:
            maxsize = node.args[0]
        unbounded = (maxsize is None
                     or (isinstance(maxsize, ast.Constant)
                         and maxsize.value is None))
        if unbounded:
            out.append(node.lineno)
    return sorted(set(out))


def _file_findings(tree: ast.Module, lines: list) -> list:
    return [lineno for lineno in _unbounded_nodes(tree)
            if LEGACY_PRAGMA not in (lines[lineno - 1]
                                     if lineno <= len(lines) else "")]


def check(index: ProjectIndex) -> list:
    findings = []
    for sf in index.iter_files((SCAN_DIR + "/",)):
        if sf.tree is None or sf.rel == EXEMPT:
            continue
        for lineno in _file_findings(sf.tree, sf.lines):
            findings.append(Finding(NAME, sf.rel, lineno, MESSAGE,
                                    sf.line(lineno).strip()))
    return findings


# ----------------------------------------------------- legacy shim surface

def lint_file(path: str) -> list:
    """Compat: -> [(path, lineno, problem)] for one file."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    tree = ast.parse(text, filename=path)
    return [(path, lineno, MESSAGE)
            for lineno in _file_findings(tree, text.splitlines())]


def run(root: str) -> list:
    import os

    findings = []
    for dirpath, _dirs, files in os.walk(os.path.join(root, SCAN_DIR)):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            if path.replace(os.sep, "/").endswith(EXEMPT):
                continue
            findings.extend(lint_file(path))
    return findings


def main() -> int:
    from . import rule_main
    return rule_main(NAME, epilogue="bound the memo or route it through "
                     "caching.executable_cache.jit_memo")


RULES = [Rule(NAME, "no unbounded lru_cache/cache memos outside the "
              "executable registry", check)]
