"""test-hygiene: debug-leftover test files cannot reappear.

PR 14 removed tests/test_dbg_tmp.py — a printing, assert-free scratch file
that rode along in tier-1 for five PR generations.  This rule keeps the
class out: any test module named like a debug leftover (``test_dbg_*``,
``*_tmp``, ``*_scratch``) fails the lint, as does a test module containing
no assertions at all (a test that can't fail is debris).
"""

from __future__ import annotations

import ast
import fnmatch
import os

from ..core import Finding, ProjectIndex
from . import Rule

NAME = "test-hygiene"
SCAN = ("tests/",)
DEBUG_NAME_PATTERNS = ("test_dbg_*.py", "test_debug_*.py", "*_tmp.py",
                       "*_scratch.py")


def _has_assertions(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Assert):
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            # pytest.raises / pytest.warns / unittest assert* count
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            if name.startswith("assert") or name in ("raises", "warns",
                                                     "approx"):
                return True
    return False


def check(index: ProjectIndex) -> list:
    findings = []
    for sf in index.iter_files(SCAN):
        base = os.path.basename(sf.rel)
        if not base.startswith("test_"):
            continue
        for pat in DEBUG_NAME_PATTERNS:
            if fnmatch.fnmatch(base, pat):
                findings.append(Finding(
                    NAME, sf.rel, 1,
                    f"debug-leftover test file (name matches {pat!r}) — "
                    f"fold real assertions into the owning suite and "
                    f"delete this"))
                break
        else:
            if sf.tree is not None and not _has_assertions(sf.tree):
                findings.append(Finding(
                    NAME, sf.rel, 1,
                    "test module contains no assertions — a test that "
                    "cannot fail is debug debris"))
    return findings


RULES = [Rule(NAME, "no debug-leftover or assertion-free test modules",
              check)]
