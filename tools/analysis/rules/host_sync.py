"""host-sync: no implicit device->host syncs on the exec hot path.

Two layers, replacing and subsuming the grep lint tools/lint_host_sync.py:

**Pattern layer** (the old grep, kept verbatim): raw sync spellings
(``int(np.asarray(...))``, ``.item()``, ``jax.device_get``,
``block_until_ready``) anywhere in the sync-free-contract directories.
Text-level, catches even code the AST layer cannot type.

**Dataflow layer** (new): the grep misses *aliased* and *implicit* syncs —
``bool(mask)`` where ``mask`` is a jax array, ``if total:`` truthiness on a
device scalar, ``np.asarray(dev)`` — because nothing in the spelling says
"device".  This layer infers which locals hold device values (assigned
from ``jnp.*`` / ``jax.*`` calls, arithmetic over device operands, device
method chains, params annotated as arrays), then flags implicit-sync
constructs on them: ``bool()/int()/float()/len()``, ``.item()`` /
``.tolist()``, ``np.asarray()``, and truthiness branches.  It runs only in
functions *reachable from SyncGuard hot regions* via the project callgraph
(``with SG.hot_region():`` call sites are the roots), so a cold config
path can truthiness-test a device flag without noise while the same code
reachable from the steady-state loop is flagged.

A justified exception carries the legacy ``# sync-ok`` pragma or a
``# tpulint: disable=host-sync`` directive.  exec/syncguard.py is exempt —
it IS the sanctioned wrapper.
"""

from __future__ import annotations

import ast
import os
import re

from ..core import Finding, FuncInfo, ProjectIndex
from . import Rule

NAME = "host-sync"

# ---------------------------------------------------------- pattern layer
# each pattern is (regex, human label); kept deliberately dumb — greppable
# — so the legacy shim behaves bit-for-bit like the old grep lint
PATTERNS: list = [
    (re.compile(r"\bint\(np\.asarray\("), "int(np.asarray(...)) blocking sync"),
    (re.compile(r"\bbool\(np\.asarray\("),
     "bool(np.asarray(...)) blocking sync"),
    (re.compile(r"\bfloat\(np\.asarray\("),
     "float(np.asarray(...)) blocking sync"),
    (re.compile(r"\.item\(\)"), ".item() blocking sync"),
    (re.compile(r"\bjax\.device_get\("), "raw jax.device_get (use SG.fetch)"),
    (re.compile(r"block_until_ready\("),
     "block_until_ready blocking sync (use SG.fetch / SG.async_scalar)"),
]

# parallel/ rides along: static_agg and the shard_map pipelines promise
# sync-free bodies, so raw fetches there are as load-bearing a bug as in exec
SCAN_DIRS = ("trino_tpu/exec", "trino_tpu/ops", "trino_tpu/parallel")
# the fused-stage path promises ZERO host syncs between input deposit and
# output take, the collective exchange is its legacy twin, and the
# resident-plan driver loop extends the same promise over whole subtrees
SCAN_FILES = ("trino_tpu/execution/stage_compiler.py",
              "trino_tpu/execution/collective_exchange.py",
              "trino_tpu/execution/plan_compiler.py")
EXEMPT_FILES = ("syncguard.py",)  # the sanctioned wrapper itself
PRAGMA = "sync-ok"


def lint_file(path: str) -> list:
    """Pattern layer over one file (compat with the old grep lint):
    -> [(path, lineno, label, source_line)]."""
    findings = []
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            if PRAGMA in line:
                continue
            for pat, label in PATTERNS:
                if pat.search(line):
                    findings.append((path, lineno, label, line.strip()))
    return findings


def run(root: str) -> list:
    """Pattern layer over the sync-free-contract tree (compat)."""
    findings = []
    paths = []
    for d in SCAN_DIRS:
        base = os.path.join(root, d)
        for dirpath, _dirnames, filenames in os.walk(base):
            for fn in sorted(filenames):
                if fn.endswith(".py") and fn not in EXEMPT_FILES:
                    paths.append(os.path.join(dirpath, fn))
    for f in SCAN_FILES:
        paths.append(os.path.join(root, f))
    for path in paths:
        if os.path.exists(path):
            findings.extend(lint_file(path))
    return findings


# --------------------------------------------------------- dataflow layer

# sync-forcing builtins: truthiness/scalarization of a device value blocks
# on the device round trip
SYNC_BUILTINS = {"bool", "int", "float", "len"}
# device methods whose CALL is itself a host materialization
SYNC_METHODS = {"item", "tolist", "to_py"}


def _jax_aliases(index: ProjectIndex, rel: str) -> set:
    """Local names that denote the jax / jax.numpy modules."""
    mod = index.modules[rel]
    out = set()
    for alias, dotted in mod.module_aliases.items():
        if dotted in ("jax", "jax.numpy"):
            out.add(alias)
    for alias, (pkg, orig) in mod.from_imports.items():
        if (pkg, orig) == ("jax", "numpy"):
            out.add(alias)
    return out


def _np_aliases(index: ProjectIndex, rel: str) -> set:
    mod = index.modules[rel]
    return {a for a, dotted in mod.module_aliases.items()
            if dotted == "numpy"}


_ARRAY_ANNOTATIONS = ("jnp.ndarray", "jax.Array", "Array", "ArrayLike")


class _DeviceInference:
    """Flow-insensitive per-function inference of which local names hold
    device (jax array) values.  Deliberately an under-approximation: only
    values provably rooted in a jax call/annotation are device, so every
    flag the dataflow layer raises is rooted in evidence.

    ``isinstance(x, np.ndarray)`` narrowing: a name the function guards
    with an explicit numpy-ndarray check is host by construction — the
    spi/batch.py pattern (``Column.__post_init__`` normalizing all-valid
    masks only when ``isinstance(self._valid, np.ndarray)``, ``maybe_rle``
    probing host pages) truthiness-tests ``.all()`` on exactly such values,
    and that never syncs a device array.  Flow-insensitively, any name so
    guarded anywhere in the function is dropped from the device set: the
    guard is evidence the author already split the host/device cases."""

    def __init__(self, fn: ast.AST, jax_names: set, np_names: set = ()):
        self.jax = jax_names
        self.np = set(np_names) | {"np", "numpy"}
        self.device: set = set()
        self.host_narrowed: set = set()
        args = fn.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            if a.annotation is not None:
                ann = ast.unparse(a.annotation)
                if any(t in ann for t in _ARRAY_ANNOTATIONS):
                    self.device.add(a.arg)
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "isinstance"
                    and len(node.args) == 2
                    and isinstance(node.args[0], ast.Name)
                    and self._is_np_ndarray(node.args[1])):
                self.host_narrowed.add(node.args[0].id)
        # two passes so a name assigned late still taints earlier reads
        # (loops re-bind; flow-insensitivity is the safe direction here)
        for _ in range(2):
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    if self.is_device(node.value):
                        for t in node.targets:
                            self._bind(t)
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    if node.value is not None and self.is_device(node.value):
                        self._bind(node.target)

    def _is_np_ndarray(self, e: ast.AST) -> bool:
        return (isinstance(e, ast.Attribute) and e.attr == "ndarray"
                and isinstance(e.value, ast.Name) and e.value.id in self.np)

    def _bind(self, target: ast.AST) -> None:
        if isinstance(target, ast.Name):
            self.device.add(target.id)

    def is_device(self, e: ast.AST) -> bool:
        if isinstance(e, ast.Name):
            return e.id in self.device and e.id not in self.host_narrowed
        if isinstance(e, ast.BinOp):
            return self.is_device(e.left) or self.is_device(e.right)
        if isinstance(e, ast.UnaryOp):
            return self.is_device(e.operand)
        if isinstance(e, ast.Compare):
            return (self.is_device(e.left)
                    or any(self.is_device(c) for c in e.comparators))
        if isinstance(e, ast.Subscript):
            return self.is_device(e.value)
        if isinstance(e, ast.Call):
            fn = e.func
            if isinstance(fn, ast.Attribute):
                base = fn.value
                # jnp.sum(...) / jax.lax.select(...) — rooted in jax
                root = base
                while isinstance(root, ast.Attribute):
                    root = root.value
                if isinstance(root, ast.Name) and root.id in self.jax:
                    return True
                # method chain on a device value stays device, except the
                # sync methods which land on the host (and are flagged)
                if fn.attr not in SYNC_METHODS and self.is_device(base):
                    return True
            return False
        if isinstance(e, ast.IfExp):
            return self.is_device(e.body) or self.is_device(e.orelse)
        return False


def _hot_region_roots(index: ProjectIndex) -> tuple:
    """-> (regions, roots): each region is (rel, FuncInfo, with_node) for a
    ``with SG.hot_region():`` block; roots are the callgraph qualnames of
    calls made inside those blocks."""
    regions = []
    roots = set()
    for sf in index.iter_files(("trino_tpu/",)):
        if sf.tree is None or "hot_region" not in sf.text:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.With):
                continue
            if not any("hot_region" in ast.unparse(item.context_expr)
                       for item in node.items):
                continue
            owner = index.enclosing_function(sf.rel, node)
            if owner is None:
                continue
            regions.append((sf.rel, owner, node))
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    callee = index.resolve_call(sf.rel, owner, sub)
                    if callee:
                        roots.add(callee)
    return regions, roots


def _flag_nodes(fi: FuncInfo, inf: _DeviceInference, np_names: set,
                within: ast.AST) -> list:
    """-> [(lineno, message)] implicit-sync constructs under ``within``."""
    out = []

    def flag(node, msg):
        out.append((node.lineno, msg))

    for node in ast.walk(within):
        if isinstance(node, ast.Call):
            fn = node.func
            if (isinstance(fn, ast.Name) and fn.id in SYNC_BUILTINS
                    and len(node.args) == 1
                    and inf.is_device(node.args[0])):
                flag(node, f"{fn.id}() on a device value forces a host "
                     "sync — route through SG.fetch / SG.async_scalar")
            elif (isinstance(fn, ast.Attribute)
                  and fn.attr in SYNC_METHODS
                  and inf.is_device(fn.value)):
                flag(node, f".{fn.attr}() on a device value forces a host "
                     "sync — route through SG.fetch / SG.async_scalar")
            elif (isinstance(fn, ast.Attribute) and fn.attr == "asarray"
                  and isinstance(fn.value, ast.Name)
                  and fn.value.id in np_names
                  and node.args and inf.is_device(node.args[0])):
                flag(node, "np.asarray() on a device value forces a host "
                     "sync — route through SG.fetch")
        elif isinstance(node, (ast.If, ast.While)):
            if inf.is_device(node.test):
                kind = "if" if isinstance(node, ast.If) else "while"
                flag(node, f"truthiness of a device value in '{kind}' "
                     "forces a host sync — fetch via SG first or keep the "
                     "branch on-device (jnp.where / lax.cond)")
        elif isinstance(node, ast.Assert):
            if inf.is_device(node.test):
                flag(node, "assert on a device value forces a host sync — "
                     "fetch via SG first or use checkify-style lanes")
    return out


def check(index: ProjectIndex) -> list:
    findings = []
    seen = set()                        # (rel, lineno) dedupe across layers

    # pattern layer — same scope as the old grep lint
    prefixes = tuple(d + "/" for d in SCAN_DIRS) + SCAN_FILES
    for sf in index.iter_files(prefixes):
        if os.path.basename(sf.rel) in EXEMPT_FILES:
            continue
        for _path, lineno, label, line in lint_file(sf.path):
            findings.append(Finding(NAME, sf.rel, lineno, label, line))
            seen.add((sf.rel, lineno))

    # dataflow layer — hot-region bodies + everything reachable from them
    regions, roots = _hot_region_roots(index)
    reachable = index.reachable(roots)
    targets: list = []          # (FuncInfo, node-to-scan)
    for rel, owner, with_node in regions:
        targets.append((owner, with_node))
    for q in sorted(reachable):
        fi = index.functions[q]
        targets.append((fi, fi.node))
    for fi, scope in targets:
        sf = index.files[fi.rel]
        if os.path.basename(fi.rel) in EXEMPT_FILES or sf.tree is None:
            continue
        np_names = _np_aliases(index, fi.rel)
        inf = _DeviceInference(fi.node, _jax_aliases(index, fi.rel),
                               np_names)
        if not inf.device:
            continue
        for lineno, msg in _flag_nodes(fi, inf, np_names, scope):
            if (fi.rel, lineno) in seen:
                continue
            line = sf.line(lineno)
            if PRAGMA in line:
                continue
            seen.add((fi.rel, lineno))
            findings.append(Finding(NAME, fi.rel, lineno, msg, line.strip()))
    return findings


def main() -> int:
    from . import rule_main
    return rule_main(NAME, epilogue="route the transfer through "
                     "exec/syncguard.py (SG.fetch / SG.async_scalar) or "
                     "justify with a '# sync-ok' pragma")


RULES = [Rule(NAME, "no raw or implicit device->host syncs on the exec "
              "hot path", check)]
