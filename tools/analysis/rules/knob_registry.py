"""knob-registry / knob-docs: every TRINO_TPU_* knob is declared and
documented.

The engine reads ~45 ``TRINO_TPU_*`` env knobs; before the registry
(trino_tpu/spi/knobs.py) each was declared nowhere but its read site, so a
typo'd name silently fell back to the default and nothing enumerated what
operators can tune.  Two rules hold the line:

**knob-registry** — any string literal in the tree that *is* a knob name
(full match of ``TRINO_TPU_[A-Z0-9_]+``) must be declared in the registry.
This catches undeclared additions, misspellings (``TRINO_TPU_PREFECTH``),
and dynamically-concatenated prefixes (a literal ending in ``_`` fails the
exact-name lookup).  tests/ are scanned too: a test monkeypatching a
misspelled knob silently tests nothing.

**knob-docs** — docs/KNOBS.md must equal a fresh render from the registry
byte-for-byte (``python -m tools.analysis --write-knob-docs``), so docs
cannot drift stale or carry hand edits.

Both read the registry with ``ast`` — no trino_tpu import, no jax.
"""

from __future__ import annotations

import ast
import os
import re

from ..core import Finding, ProjectIndex
from ..knobdocs import DOCS_REL, KNOBS_REL, extract, render
from . import Rule

NAME = "knob-registry"
DOCS_NAME = "knob-docs"

KNOB_LITERAL = re.compile(r"^TRINO_TPU_[A-Z0-9_]+$")
# the registry declares knobs; the docs generator/check lives off-tree
EXEMPT = (KNOBS_REL,)


def _declared(index: ProjectIndex) -> set:
    try:
        return {name for name, *_ in extract(index.root)}
    except (OSError, ValueError, SyntaxError):
        return set()


def check(index: ProjectIndex) -> list:
    declared = _declared(index)
    findings = []
    if not declared:
        findings.append(Finding(
            NAME, KNOBS_REL, 0,
            "knob registry missing or unreadable — every TRINO_TPU_* knob "
            "must be declared in trino_tpu/spi/knobs.py"))
        return findings
    for sf in index.iter_files():
        if sf.rel in EXEMPT or sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and KNOB_LITERAL.match(node.value)):
                continue
            if node.value in declared:
                continue
            findings.append(Finding(
                NAME, sf.rel, node.lineno,
                f"undeclared env knob {node.value!r} — declare it in "
                f"trino_tpu/spi/knobs.py (typo? nearest declared: "
                f"{_nearest(node.value, declared)})",
                sf.line(node.lineno).strip()))
    return findings


def _nearest(name: str, declared: set) -> str:
    """Cheap typo hint: declared knob sharing the longest common prefix."""
    best, best_len = "<none>", -1
    for d in sorted(declared):
        n = len(os.path.commonprefix([name, d]))
        if n > best_len:
            best, best_len = d, n
    return best


def check_docs(index: ProjectIndex) -> list:
    try:
        expected = render(extract(index.root))
    except (OSError, ValueError, SyntaxError) as e:
        return [Finding(DOCS_NAME, KNOBS_REL, 0,
                        f"knob registry unreadable for docs check: {e}")]
    path = os.path.join(index.root, DOCS_REL)
    if not os.path.exists(path):
        return [Finding(DOCS_NAME, DOCS_REL, 0,
                        "docs/KNOBS.md missing — generate it with "
                        "'python -m tools.analysis --write-knob-docs'")]
    with open(path, encoding="utf-8") as f:
        actual = f.read()
    if actual != expected:
        # name the first drifted knob row for a human-sized message
        exp_lines, act_lines = expected.splitlines(), actual.splitlines()
        detail = "content differs"
        for i, (e, a) in enumerate(zip(exp_lines, act_lines), 1):
            if e != a:
                detail = f"first drift at line {i}: {a[:60]!r} != {e[:60]!r}"
                break
        else:
            detail = (f"line count {len(act_lines)} != {len(exp_lines)} "
                      f"(knob added or removed without regenerating)")
        return [Finding(DOCS_NAME, DOCS_REL, 0,
                        f"docs/KNOBS.md is stale vs the registry ({detail})"
                        " — regenerate with 'python -m tools.analysis "
                        "--write-knob-docs'")]
    return []


RULES = [
    Rule(NAME, "every TRINO_TPU_* string literal names a registry-declared "
         "knob", check),
    Rule(DOCS_NAME, "docs/KNOBS.md matches a fresh render of the knob "
         "registry", check_docs),
]
