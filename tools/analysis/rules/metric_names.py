"""metric-names: every registered metric name is Prometheus-legal.

AST successor of the grep lint tools/lint_metric_names.py.  The telemetry
registry (trino_tpu/telemetry/metrics.py) validates names at registration
time, but a misnamed metric in a lazily-imported module only blows up when
that code path first runs — long after CI went green.  This rule finds
every ``REGISTRY.counter("...")`` / ``.gauge("...")`` /
``.distribution("...")`` site statically (line-wrapped or not — the AST
does not care) and enforces:

- names match the Prometheus data model (``[a-zA-Z_:][a-zA-Z0-9_:]*``)
- every name carries the mandatory ``trino_`` prefix
- counters end in ``_total``
- no metric name literal is registered at two distinct sites
- the contractually-required families (profiler/journal/cache/adaptive
  telemetry) each have at least one registration site

A justified exception carries the legacy ``# metric-ok`` pragma or a
``# tpulint: disable=metric-names`` directive.
"""

from __future__ import annotations

import ast
import os
import re

from ..core import Finding, ProjectIndex
from . import Rule

NAME = "metric-names"
LEGAL = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
PREFIX = "trino_"
SCAN_DIR = "trino_tpu"
LEGACY_PRAGMA = "metric-ok"
KINDS = ("counter", "gauge", "distribution")

# metric families the observability plane is contractually expected to
# expose (PR 11 flight recorder, PR 12 cache plane, PR 13 adaptive, PR 15
# fault-tolerant execution, PR 16 compressed execution, PR 17 resident
# plans, PR 18 iterative optimizer + history-based optimization): at least
# one registration of each must exist, so a refactor can't silently drop
# that telemetry
REQUIRED_FAMILIES = ("trino_profile_", "trino_journal_", "trino_cache_",
                     "trino_adaptive_", "trino_fte_", "trino_encoding_",
                     "trino_resident_", "trino_optimizer_", "trino_hbo_",
                     "trino_ha_")


def _registrations(tree: ast.Module, lines: list) -> list:
    """-> [(lineno, kind, name)] — every literal-named registration call,
    minus lines carrying the legacy pragma."""
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in KINDS and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if LEGACY_PRAGMA in line:
            continue
        out.append((node.lineno, node.func.attr, node.args[0].value))
    return out


def _name_problems(kind: str, name: str) -> list:
    if not LEGAL.match(name):
        return ["illegal Prometheus metric name"]
    problems = []
    if not name.startswith(PREFIX):
        problems.append(f"missing mandatory {PREFIX!r} prefix")
    if kind == "counter" and not name.endswith("_total"):
        problems.append("counter name must end in '_total'")
    return problems


def check(index: ProjectIndex) -> list:
    findings = []
    sites: dict = {}                    # name -> [(rel, lineno)]
    for sf in index.iter_files((SCAN_DIR + "/",)):
        if sf.tree is None:
            continue
        for lineno, kind, name in _registrations(sf.tree, sf.lines):
            sites.setdefault(name, []).append((sf.rel, lineno))
            for problem in _name_problems(kind, name):
                findings.append(Finding(NAME, sf.rel, lineno,
                                        f"{name!r}: {problem}",
                                        sf.line(lineno).strip()))
    for name, where in sorted(sites.items()):
        if len(where) > 1:
            first = f"{where[0][0]}:{where[0][1]}"
            for rel, lineno in where[1:]:
                findings.append(Finding(
                    NAME, rel, lineno,
                    f"{name!r}: duplicate registration (first at {first})"))
    for fam in REQUIRED_FAMILIES:
        if not any(n.startswith(fam) for n in sites):
            findings.append(Finding(
                NAME, SCAN_DIR, 0,
                f"required metric family {fam}* has no registration site"))
    return findings


# ----------------------------------------------------- legacy shim surface

def lint_file(path: str) -> list:
    """Compat: -> [(path, lineno, metric_name, problem)] for one file."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    tree = ast.parse(text, filename=path)
    findings = []
    for lineno, kind, name in _registrations(tree, text.splitlines()):
        for problem in _name_problems(kind, name):
            findings.append((path, lineno, name, problem))
    return findings


def run(root: str, require_families: bool = False) -> list:
    """Compat: filesystem-walking variant returning 4-tuples (naming +
    duplicate checks; families opt-in like the old tool)."""
    findings = []
    sites: dict = {}
    for dirpath, _dirs, files in os.walk(os.path.join(root, SCAN_DIR)):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            findings.extend(lint_file(path))
            with open(path, encoding="utf-8") as f:
                text = f.read()
            for lineno, _kind, name in _registrations(
                    ast.parse(text, filename=path), text.splitlines()):
                sites.setdefault(name, []).append((path, lineno))
    for name, where in sorted(sites.items()):
        if len(where) > 1:
            for path, lineno in where[1:]:
                findings.append(
                    (path, lineno, name,
                     f"duplicate registration (first at "
                     f"{where[0][0]}:{where[0][1]})"))
    if require_families:
        for fam in REQUIRED_FAMILIES:
            if not any(n.startswith(fam) for n in sites):
                findings.append(
                    (os.path.join(root, SCAN_DIR), 0, fam + "*",
                     "required metric family has no registration site"))
    return findings


def main() -> int:
    from . import rule_main
    return rule_main(NAME, epilogue="annotate justified exceptions with "
                     f"# {LEGACY_PRAGMA}")


RULES = [Rule(NAME, "metric registrations are Prometheus-legal, "
              "trino_-prefixed, unique", check)]
