"""thread-safety: lock-consistency + lock-ordering for thread-shared classes.

The concurrent runtime (heartbeat, prefetch, exchange buffers, resource
manager, speculation) grew races that only the chaos soak caught *after*
they shipped (PR 9 flushed three).  This rule catches the dominant class
statically, RacerD-style, with two analyses:

**Inconsistent locking.**  A class is *thread-shared* when its own code
hands a bound method to ``threading.Thread(target=self._x)`` or an
executor ``submit(self._x, ...)``, or when outside code spawns a thread on
a method of an instance it just constructed.  Within a shared class, an
attribute that is mutated at least once while holding one of the class's
locks (``with self._lock:`` — any attr bound to ``threading.Lock / RLock /
Condition``) is *lock-guarded*; any other mutation of that attribute
outside a lock scope (excluding ``__init__``/``__del__``, which run before
publication / after quiescence) is a finding.  The guarded-attr framing
self-limits false positives: an attribute never locked anywhere is
presumed single-threaded and never flagged.

**Lock ordering.**  Every nested acquisition (``with self._a:`` then
``with self._b:``, directly or through one level of self-method call)
becomes an edge A->B in a lock-order graph over (class, lock-attr) and
module-level lock nodes.  A cycle in that graph is a potential deadlock;
one finding is emitted per cycle at its lexicographically-first edge.

Suppress deliberate exceptions with ``# tpulint: disable=thread-safety --
reason`` on the mutation line.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional

from ..core import ClassInfo, Finding, ProjectIndex
from . import Rule

NAME = "thread-safety"
SCAN = ("trino_tpu/",)

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
# mutating container-method calls on an attribute count as writes to it
MUTATOR_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "update", "remove", "discard", "pop", "popleft", "popitem", "clear",
    "setdefault", "sort", "reverse",
}


def _is_lock_factory(call: ast.Call) -> bool:
    fn = call.func
    name = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else None)
    return name in LOCK_FACTORIES


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


@dataclass
class _Mutation:
    method: str
    attr: str
    lineno: int
    locked: bool


@dataclass
class _ClassFacts:
    info: ClassInfo
    lock_attrs: set = field(default_factory=set)
    spawned_methods: set = field(default_factory=set)   # evidence of sharing
    mutations: list = field(default_factory=list)
    # (held_key, acquired_key, lineno) nested-acquisition edges
    lock_edges: list = field(default_factory=list)
    # method name -> set of lock keys it acquires directly
    acquires: dict = field(default_factory=dict)


def _lock_key(cls_qual: str, attr: str) -> str:
    return f"{cls_qual}.{attr}"


class _MethodWalker:
    """One pass over a method body tracking the held-lock stack."""

    def __init__(self, facts: _ClassFacts, module_locks: dict, rel: str,
                 method: str):
        self.facts = facts
        self.module_locks = module_locks        # name -> key
        self.rel = rel
        self.method = method
        self.held: list = []

    def _lock_key_for(self, expr: ast.AST) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is not None and attr in self.facts.lock_attrs:
            return _lock_key(self.facts.info.qualname, attr)
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return self.module_locks[expr.id]
        return None

    def walk_body(self, body: list) -> None:
        for stmt in body:
            self.walk_stmt(stmt)

    def walk_stmt(self, stmt: ast.AST) -> None:
        if isinstance(stmt, ast.With):
            keys = []
            for item in stmt.items:
                key = self._lock_key_for(item.context_expr)
                if key is not None:
                    for held in self.held:
                        if held != key:
                            self.facts.lock_edges.append(
                                (held, key, stmt.lineno))
                    self.held.append(key)
                    keys.append(key)
                    self.facts.acquires.setdefault(self.method,
                                                   set()).add(key)
            for sub in stmt.body:
                self.walk_stmt(sub)
            for _ in keys:
                self.held.pop()
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def runs later (thread target / callback): its body
            # does NOT inherit the currently-held locks
            saved, self.held = self.held, []
            for sub in stmt.body:
                self.walk_stmt(sub)
            self.held = saved
            return

        self._record_effects(stmt)

        for sub in ast.iter_child_nodes(stmt):
            if isinstance(sub, ast.stmt):
                self.walk_stmt(sub)

    def _record_effects(self, stmt: ast.AST) -> None:
        locked = bool(self.held)
        targets: list = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Delete):
            targets = stmt.targets
        for t in targets:
            base = t
            if isinstance(base, ast.Subscript):
                base = base.value           # self.a[k] = v mutates self.a
            attr = _self_attr(base)
            if attr is not None:
                self.facts.mutations.append(
                    _Mutation(self.method, attr, stmt.lineno, locked))
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            fn = call.func
            if isinstance(fn, ast.Attribute):
                # self.attr.append(...) — container mutation
                attr = _self_attr(fn.value)
                if attr is not None and fn.attr in MUTATOR_METHODS:
                    self.facts.mutations.append(
                        _Mutation(self.method, attr, stmt.lineno, locked))
                # manual self._lock.acquire(): held for the rest of the
                # method (coarse, errs toward fewer findings)
                if fn.attr == "acquire":
                    key = self._lock_key_for(fn.value)
                    if key is not None:
                        self.held.append(key)
            # thread-spawn evidence
            self._record_spawn(call)

    def _record_spawn(self, call: ast.Call) -> None:
        for m in _spawn_targets(call):
            self.facts.spawned_methods.add(m)


def _spawn_targets(call: ast.Call):
    """Methods of ``self`` handed to a thread/executor by this call."""
    fn = call.func
    callee = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else None)
    if callee == "Thread":
        for kw in call.keywords:
            if kw.arg == "target":
                attr = _self_attr(kw.value)
                if attr is not None:
                    yield attr
    elif callee == "submit" and call.args:
        attr = _self_attr(call.args[0])
        if attr is not None:
            yield attr


def _module_locks(tree: ast.Module, rel: str) -> dict:
    """Top-level ``_LOCK = threading.Lock()`` bindings -> lock-node keys."""
    out = {}
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and _is_lock_factory(node.value)):
            out[node.targets[0].id] = f"{rel}::{node.targets[0].id}"
    return out


def _collect_lock_attrs(ci: ClassInfo) -> set:
    attrs = set()
    for fi in ci.methods.values():
        for node in ast.walk(fi.node):
            if (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)
                    and _is_lock_factory(node.value)):
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        attrs.add(attr)
    return attrs


def _external_spawns(index: ProjectIndex) -> dict:
    """Classes shared by *outside* code: ``obj = Cls(...)`` then
    ``Thread(target=obj.m)`` / ``pool.submit(obj.m)`` in the same function.
    -> {class qualname: {method, ...}}"""
    shared: dict = {}
    for q, fi in index.functions.items():
        # local var -> class qualname for constructor calls
        ctor: dict = {}
        for node in ast.walk(fi.node):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)):
                callee = index.resolve_call(fi.rel, fi, node.value)
                if callee and callee.endswith(".__init__"):
                    ctor[node.targets[0].id] = callee[:-len(".__init__")]
                else:
                    fn = node.value.func
                    if isinstance(fn, ast.Name):
                        local = f"{fi.rel}::{fn.id}"
                        if local in index.classes:
                            ctor[node.targets[0].id] = local
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            callee = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            cands = []
            if callee == "Thread":
                cands = [kw.value for kw in node.keywords
                         if kw.arg == "target"]
            elif callee == "submit" and node.args:
                cands = [node.args[0]]
            for v in cands:
                if (isinstance(v, ast.Attribute)
                        and isinstance(v.value, ast.Name)
                        and v.value.id in ctor):
                    shared.setdefault(ctor[v.value.id],
                                      set()).add(v.attr)
    return shared


def _find_cycles(edges: dict) -> list:
    """-> list of cycles (each a list of node keys) via DFS; deterministic
    order, each cycle reported once from its smallest node."""
    cycles = []
    seen_cycles = set()
    nodes = sorted(edges)

    def dfs(start, node, path, on_path):
        for nxt in sorted(edges.get(node, ())):
            if nxt == start:
                cyc = tuple(path)
                canon = min(tuple(cyc[i:] + cyc[:i]) for i in range(len(cyc)))
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    cycles.append(list(canon))
            elif nxt not in on_path and nxt > start:
                dfs(start, nxt, path + [nxt], on_path | {nxt})

    for n in nodes:
        dfs(n, n, [n], {n})
    return cycles


def check(index: ProjectIndex) -> list:
    findings = []
    ext_shared = _external_spawns(index)
    all_edges: list = []        # (held, acquired, rel, lineno)

    for cq in sorted(index.classes):
        ci = index.classes[cq]
        if not ci.rel.startswith(SCAN):
            continue
        sf = index.files[ci.rel]
        if sf.tree is None:
            continue
        facts = _ClassFacts(ci)
        facts.lock_attrs = _collect_lock_attrs(ci)
        mlocks = _module_locks(sf.tree, ci.rel)
        for mname in sorted(ci.methods):
            fi = ci.methods[mname]
            w = _MethodWalker(facts, mlocks, ci.rel, mname)
            w.walk_body(fi.node.body)
        facts.spawned_methods |= ext_shared.get(cq, set())

        # one level of call-through for lock ordering: holding A, calling
        # self.m() where m acquires B => A -> B
        for mname in sorted(ci.methods):
            fi = ci.methods[mname]
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.With):
                    continue
                held = [k for item in node.items
                        for k in [_MethodWalker(facts, mlocks, ci.rel,
                                                mname)._lock_key_for(
                                                    item.context_expr)]
                        if k is not None]
                if not held:
                    continue
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and isinstance(sub.func.value, ast.Name)
                            and sub.func.value.id == "self"):
                        for acquired in facts.acquires.get(
                                sub.func.attr, ()):
                            for h in held:
                                if h != acquired:
                                    facts.lock_edges.append(
                                        (h, acquired, sub.lineno))

        for held, acq, lineno in facts.lock_edges:
            all_edges.append((held, acq, ci.rel, lineno))

        if not facts.spawned_methods or not facts.lock_attrs:
            continue
        guarded = {m.attr for m in facts.mutations if m.locked}
        guarded -= facts.lock_attrs
        evidence = ", ".join(sorted(facts.spawned_methods))
        for m in facts.mutations:
            if (m.attr in guarded and not m.locked
                    and m.method not in ("__init__", "__del__")):
                findings.append(Finding(
                    NAME, ci.rel, m.lineno,
                    f"unlocked mutation of lock-guarded attribute "
                    f"'self.{m.attr}' in thread-shared class '{ci.name}' "
                    f"(shared via thread target(s): {evidence}; attribute "
                    f"is mutated under a lock elsewhere)",
                    sf.line(m.lineno).strip()))

    # lock-order cycles across everything recorded
    graph: dict = {}
    sites: dict = {}
    for held, acq, rel, lineno in all_edges:
        graph.setdefault(held, set()).add(acq)
        sites.setdefault((held, acq), (rel, lineno))
    for cyc in _find_cycles(graph):
        ring = cyc + [cyc[0]]
        edge = (ring[0], ring[1])
        rel, lineno = sites[edge]
        pretty = " -> ".join(_short(k) for k in ring)
        findings.append(Finding(
            NAME, rel, lineno,
            f"lock-order cycle (potential deadlock): {pretty}"))
    return findings


def _short(key: str) -> str:
    # "trino_tpu/x.py::Cls.attr" -> "Cls.attr"; module locks keep the name
    return key.split("::", 1)[1] if "::" in key else key


RULES = [Rule(NAME, "unlocked mutations of guarded state in thread-shared "
              "classes; lock-order deadlock cycles", check)]
