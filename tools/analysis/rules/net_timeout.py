"""net-timeout: no unbounded network waits in trino_tpu/execution/.

AST successor of the grep lint tools/lint_net_timeout.py.  A ``urlopen``/
socket call without an explicit ``timeout=`` blocks forever when the peer
wedges — exactly the silent-stall class the resilience layer (spi/errors.py
Backoff, execution/failure_detector.py) exists to eliminate.  The AST form
sees the whole argument list at once, so multi-line calls and aliased
imports need no balanced-paren heuristics, and a ``timeout`` passed
positionally counts too.

A justified exception carries the legacy ``# net-ok`` pragma or a
``# tpulint: disable=net-timeout`` directive on the call line.
"""

from __future__ import annotations

import ast

from ..core import Finding, ProjectIndex
from . import Rule

NAME = "net-timeout"
SCAN_DIRS = ("trino_tpu/execution/",)
LEGACY_PRAGMA = "net-ok"

# callee name -> 0-based positional index where ``timeout`` may also appear
# (urlopen(url, data, timeout), create_connection(addr, timeout),
#  HTTPConnection(host, port, timeout))
NETWORK_CALLS = {
    "urlopen": 2,
    "create_connection": 1,
    "HTTPConnection": 2,
    "HTTPSConnection": 2,
}


def _callee_name(call: ast.Call):
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _violations(tree: ast.Module, lines: list) -> list:
    """-> [(lineno, label, source_line)] for one parsed module."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _callee_name(node)
        if name not in NETWORK_CALLS:
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if LEGACY_PRAGMA in line:
            continue
        has_kw = any(kw.arg == "timeout" for kw in node.keywords)
        has_pos = len(node.args) > NETWORK_CALLS[name]
        if not (has_kw or has_pos):
            out.append((node.lineno, f"{name} without timeout",
                        line.strip()))
    return out


def check(index: ProjectIndex) -> list:
    findings = []
    for sf in index.iter_files(SCAN_DIRS):
        if sf.tree is None:
            continue
        for lineno, label, src in _violations(sf.tree, sf.lines):
            findings.append(Finding(NAME, sf.rel, lineno, label, src))
    return findings


# ----------------------------------------------------- legacy shim surface

def lint_file(path: str) -> list:
    """Compat with the old tools/lint_net_timeout.py API:
    -> [(path, lineno, label, source_line)]."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    tree = ast.parse(text, filename=path)
    return [(path, lineno, label, src)
            for lineno, label, src in _violations(tree, text.splitlines())]


def main() -> int:
    from . import rule_main
    return rule_main(NAME, epilogue="pass an explicit timeout= or justify "
                     "with a '# net-ok' pragma")


RULES = [Rule(NAME, "network calls in execution/ must carry a timeout",
              check)]
