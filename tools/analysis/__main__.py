"""tpulint CLI: ``python -m tools.analysis``.

Exit 0 only when the tree is clean: zero non-baselined findings, zero
stale baseline entries, zero unused suppressions.  ``--update-baseline``
rewrites the committed grandfather file from the live run;
``--write-knob-docs`` regenerates docs/KNOBS.md from the knob registry.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    from . import repo_root, run_analysis
    from . import baseline as bl
    from . import knobdocs

    p = argparse.ArgumentParser(
        prog="python -m tools.analysis",
        description="tpulint — AST/dataflow static analysis for the "
                    "trino-tpu engine")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output (findings + stats)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule subset (default: all)")
    p.add_argument("--list-rules", action="store_true",
                   help="list registered rules and exit")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the committed baseline from this run")
    p.add_argument("--write-knob-docs", action="store_true",
                   help="regenerate docs/KNOBS.md from the knob registry "
                        "and exit")
    p.add_argument("--root", default=None, help=argparse.SUPPRESS)
    p.add_argument("--baseline", default=None, help=argparse.SUPPRESS)
    p.add_argument("--stats-out", default=None,
                   help="also write run stats JSON to this path")
    args = p.parse_args(argv)

    root = args.root or repo_root()

    if args.write_knob_docs:
        out = knobdocs.write(root)
        print(f"wrote {out}")
        return 0

    if args.list_rules:
        from .rules import all_rules
        for r in all_rules():
            print(f"{r.name:20s} {r.doc}")
        return 0

    rule_names = ([r.strip() for r in args.rules.split(",") if r.strip()]
                  if args.rules else None)
    report = run_analysis(root, rule_names, args.baseline)

    if args.update_baseline:
        path = args.baseline or bl.DEFAULT_PATH
        bl.write(report.findings + report.baselined, path)
        print(f"baseline updated: {path} "
              f"({len(report.findings) + len(report.baselined)} entries)")
        return 0

    if args.stats_out:
        with open(args.stats_out, "w", encoding="utf-8") as f:
            json.dump(report.stats(), f, indent=1, sort_keys=True)
            f.write("\n")

    if args.json:
        print(json.dumps({
            "findings": [f.to_json() for f in report.findings],
            "stale_baseline": [
                {"rule": r, "path": p_, "message": m, "count": c}
                for r, p_, m, c in report.stale_baseline],
            "stats": report.stats(),
        }, indent=1, sort_keys=True))
        return 0 if report.clean else 1

    for f in report.findings:
        print(f.format())
    for rule, path, message, count in report.stale_baseline:
        print(f"{path}: [baseline] stale entry ({rule}: {message!r} "
              f"x{count}) — violation fixed, run --update-baseline")
    s = report.stats()
    status = "clean" if report.clean else (
        f"{len(report.findings)} finding(s), "
        f"{len(report.stale_baseline)} stale baseline entr(ies)")
    print(f"tpulint: {status} — {s['files_scanned']} files, "
          f"{len(report.rules_run)} rules, {s['wall_seconds']}s "
          f"({len(report.baselined)} baselined, "
          f"{len(report.suppressed)} suppressed)", file=sys.stderr)
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main())
