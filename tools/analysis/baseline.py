"""tpulint baseline: committed grandfathered findings.

The baseline is an *exact* contract, not a ratchet that only counts: the
committed file must match the current run key-for-key.  A finding not in
the baseline fails the run (new violation); a baseline entry with no
matching finding ALSO fails the run (stale entry — the violation was fixed
but the grandfather clause lingers).  ``--update-baseline`` rewrites the
file from the live run; review the diff like any other code change.

Keys are (rule, path, message) with multiplicity — no line numbers, so an
unrelated edit above a grandfathered finding does not churn the file.
"""

from __future__ import annotations

import json
import os
from collections import Counter

DEFAULT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "baseline.json")
VERSION = 1


def load(path: str = DEFAULT_PATH) -> Counter:
    """-> Counter[(rule, path, message)] of grandfathered findings."""
    if not os.path.exists(path):
        return Counter()
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    counts: Counter = Counter()
    for e in data.get("entries", []):
        counts[(e["rule"], e["path"], e["message"])] += int(e.get("count", 1))
    return counts


def write(findings: list, path: str = DEFAULT_PATH) -> None:
    counts: Counter = Counter(f.key() for f in findings)
    entries = [{"rule": r, "path": p, "message": m, "count": c}
               for (r, p, m), c in sorted(counts.items())]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": VERSION, "entries": entries}, f, indent=1,
                  sort_keys=True)
        f.write("\n")


def diff(findings: list, baseline: Counter) -> tuple:
    """-> (new_findings, stale_entries).  ``new_findings`` are Finding
    objects beyond the baselined multiplicity; ``stale_entries`` are
    (rule, path, message, count) tuples the baseline grants but the run no
    longer produces."""
    remaining = Counter(baseline)
    new = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        if remaining[f.key()] > 0:
            remaining[f.key()] -= 1
        else:
            new.append(f)
    stale = [(r, p, m, c) for (r, p, m), c in sorted(remaining.items())
             if c > 0]
    return new, stale
