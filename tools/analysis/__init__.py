"""tpulint — AST/dataflow static analysis for the trino-tpu engine.

One command (``python -m tools.analysis``), one shared parse/symbol/
callgraph core (:mod:`tools.analysis.core`), pluggable rules
(:mod:`tools.analysis.rules`), file/line suppressions with an
unused-suppression check, and an exact committed baseline
(:mod:`tools.analysis.baseline`).

Programmatic entry point: :func:`run_analysis`.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


@dataclass
class Report:
    """Everything one analysis run produced, pre-rendered decisions only —
    the CLI and the tier-1 test both consume this."""

    findings: list            # non-baselined, non-suppressed (the failures)
    baselined: list           # findings excused by the committed baseline
    suppressed: list          # findings excused by inline pragmas
    stale_baseline: list      # (rule, path, message, count) no longer firing
    rule_counts: dict         # rule -> raw finding count (pre-baseline)
    rule_seconds: dict        # rule -> wall seconds
    files_scanned: int
    wall_seconds: float
    rules_run: list

    @property
    def clean(self) -> bool:
        return not self.findings and not self.stale_baseline

    def stats(self) -> dict:
        return {
            "files_scanned": self.files_scanned,
            "wall_seconds": round(self.wall_seconds, 3),
            "rules_run": list(self.rules_run),
            "rule_counts": dict(sorted(self.rule_counts.items())),
            "rule_seconds": {k: round(v, 3) for k, v in
                             sorted(self.rule_seconds.items())},
            "findings": len(self.findings),
            "baselined": len(self.baselined),
            "suppressed": len(self.suppressed),
            "stale_baseline": len(self.stale_baseline),
            "clean": self.clean,
        }


def run_analysis(root: str = None, rule_names: list = None,
                 baseline_path: str = None) -> Report:
    from . import baseline as bl
    from .core import ProjectIndex, apply_suppressions
    from .rules import all_rules

    t0 = time.monotonic()
    root = root or repo_root()
    index = ProjectIndex.build(root)
    rules = all_rules()
    if rule_names:
        unknown = set(rule_names) - {r.name for r in rules}
        if unknown:
            raise SystemExit(f"unknown rule(s): {', '.join(sorted(unknown))}"
                             f" (try --list-rules)")
        rules = [r for r in rules if r.name in rule_names]

    raw, rule_counts, rule_seconds = [], {}, {}
    for rule in rules:
        r0 = time.monotonic()
        out = rule.check(index)
        rule_seconds[rule.name] = time.monotonic() - r0
        rule_counts[rule.name] = len(out)
        raw.extend(out)

    ran = {r.name for r in rules} | {"unused-suppression"}
    kept, suppressed = apply_suppressions(index, raw, ran)
    unused = [f for f in kept if f.rule == "unused-suppression"]
    rule_counts["unused-suppression"] = len(unused)

    base = bl.load(baseline_path or bl.DEFAULT_PATH)
    if rule_names:
        # subset run: other rules' grandfathered entries are out of scope,
        # not stale
        base = type(base)({k: v for k, v in base.items() if k[0] in ran})
    new, stale = bl.diff(kept, base)
    baselined = [f for f in kept if f not in new]
    return Report(findings=new, baselined=baselined, suppressed=suppressed,
                  stale_baseline=stale, rule_counts=rule_counts,
                  rule_seconds=rule_seconds,
                  files_scanned=len(index.files),
                  wall_seconds=time.monotonic() - t0,
                  rules_run=[r.name for r in rules])
