"""docs/KNOBS.md generation from the knob registry, and its drift check.

The registry (``trino_tpu/spi/knobs.py``) keeps its declarations as pure
literals so this module can read them with ``ast`` — no jax import, no
side effects — and render the operator-facing table deterministically.
``python -m tools.analysis --write-knob-docs`` writes the file; the
``knob-docs`` tpulint rule fails when the committed file differs
byte-for-byte from a fresh render, so a knob added (or retyped, or
re-documented) without regenerating the docs fails the lint.
"""

from __future__ import annotations

import ast
import os

KNOBS_REL = "trino_tpu/spi/knobs.py"
DOCS_REL = "docs/KNOBS.md"

HEADER = """\
# TRINO_TPU_* environment knobs

<!-- GENERATED FILE — do not edit by hand.
     Source of truth: trino_tpu/spi/knobs.py
     Regenerate with:  python -m tools.analysis --write-knob-docs
     Drift fails the knob-docs tpulint rule. -->

Every environment knob the engine reads, generated from the central
registry in `trino_tpu/spi/knobs.py`.  An empty default means *unset*
(the code-side fallback documented in the description applies).  Boolean
knobs accept `1/true/yes/on` and `0/false/no/off`.

| Knob | Type | Default | Description |
|------|------|---------|-------------|
"""


def extract(root: str) -> list:
    """-> [(name, type, default, doc, choices)] from the registry AST."""
    path = os.path.join(root, KNOBS_REL)
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    entries = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "Knob"):
            continue
        args = [a.value for a in node.args if isinstance(a, ast.Constant)]
        if len(args) < 4 or not isinstance(args[0], str):
            raise ValueError(
                f"{KNOBS_REL}:{node.lineno}: Knob declaration is not pure "
                f"literals — the registry must stay statically readable")
        choices = None
        for kw in node.keywords:
            if kw.arg == "choices" and isinstance(kw.value, ast.Tuple):
                choices = tuple(e.value for e in kw.value.elts
                                if isinstance(e, ast.Constant))
        entries.append((args[0], args[1], args[2], args[3], choices))
    entries.sort()
    return entries


def render(entries: list) -> str:
    rows = []
    for name, type_, default, doc, choices in entries:
        shown_type = type_
        if choices:
            shown_type = f"enum({', '.join(choices)})"
        shown_default = f"`{default}`" if default else "*(unset)*"
        rows.append(f"| `{name}` | {shown_type} | {shown_default} "
                    f"| {doc} |")
    return HEADER + "\n".join(rows) + f"\n\n{len(entries)} knobs.\n"


def write(root: str) -> str:
    out = os.path.join(root, DOCS_REL)
    os.makedirs(os.path.dirname(out), exist_ok=True)
    text = render(extract(root))
    with open(out, "w", encoding="utf-8") as f:
        f.write(text)
    return out
