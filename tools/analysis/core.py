"""tpulint core: shared parse / symbol / callgraph infrastructure.

The five grep lints under ``tools/`` match *text*; they miss aliased calls,
multi-line forms, and whole invariant classes (thread-safety, knob
registry) that only an AST view can express.  This module is the shared
substrate every tpulint rule builds on:

- :class:`ProjectIndex` — every repo python file parsed once (``ast``),
  with a module-level symbol table (functions, classes, import aliases)
  and a best-effort static callgraph over qualified names;
- :class:`Finding` — one diagnostic, stable-keyed for baselining;
- :class:`Suppression` — ``# tpulint: disable=RULE[,RULE] -- reason``
  pragmas, same-line or own-line-above, plus ``disable-file=`` for module
  scope; unused suppressions are themselves findings so stale pragmas
  cannot accumulate.

Rules live in ``tools/analysis/rules/`` and receive the index; they return
findings and never print.  Output, baselining, and exit codes are owned by
``tools/analysis/__main__.py``.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Iterator, Optional

# directories/files indexed by default (repo-relative).  tests/ rides along
# for the hygiene rule; tools/ itself is NOT indexed — the lint does not
# lint itself (its fixtures would trip every rule).
DEFAULT_INCLUDE = ("trino_tpu", "tests", "bench.py", "__graft_entry__.py")

DIRECTIVE = re.compile(
    r"#\s*tpulint:\s*(?P<verb>disable-file|disable)\s*=\s*"
    r"(?P<rules>[a-z0-9_\-]+(?:\s*,\s*[a-z0-9_\-]+)*)"
    r"(?:\s*--\s*(?P<reason>.*))?")


@dataclass
class Finding:
    """One diagnostic.  ``key()`` deliberately excludes the line number so a
    committed baseline survives unrelated edits above the finding; the
    baseline stores (rule, path, message) with multiplicity instead."""

    rule: str
    path: str          # repo-relative, posix separators
    line: int
    message: str
    snippet: str = ""

    def key(self) -> tuple:
        return (self.rule, self.path, self.message)

    def format(self) -> str:
        tail = f"  | {self.snippet}" if self.snippet else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tail}"

    def to_json(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "snippet": self.snippet}


@dataclass
class Suppression:
    """One parsed ``# tpulint: disable=...`` pragma.  ``target_line`` is the
    line findings must sit on for it to apply (None = whole file).  A rule
    listed here that suppressed nothing is an unused-suppression finding —
    pragmas must not outlive the violation they excuse."""

    path: str
    directive_line: int
    target_line: Optional[int]      # None => file scope
    rules: tuple
    reason: str
    used: set = field(default_factory=set)

    def applies(self, finding: Finding) -> bool:
        if finding.path != self.path or finding.rule not in self.rules:
            return False
        return self.target_line is None or finding.line == self.target_line


@dataclass
class SourceFile:
    rel: str
    path: str
    text: str
    lines: list
    tree: Optional[ast.Module]
    parse_error: Optional[str]
    suppressions: list

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


def _parse_suppressions(rel: str, lines: list) -> list:
    sups = []
    for i, raw in enumerate(lines, 1):
        m = DIRECTIVE.search(raw)
        if not m:
            continue
        rules = tuple(r.strip() for r in m.group("rules").split(","))
        reason = (m.group("reason") or "").strip()
        if m.group("verb") == "disable-file":
            target = None
        elif raw[:m.start()].strip():
            target = i                      # trailing pragma: same line
        else:
            target = i + 1                  # own-line pragma: line below
        sups.append(Suppression(rel, i, target, rules, reason))
    return sups


@dataclass
class FuncInfo:
    qualname: str                   # "<rel>::<name>" or "<rel>::<Cls>.<name>"
    rel: str
    name: str
    cls: Optional[str]
    node: ast.AST                   # FunctionDef / AsyncFunctionDef


@dataclass
class ClassInfo:
    qualname: str                   # "<rel>::<Cls>"
    rel: str
    name: str
    node: ast.ClassDef
    methods: dict = field(default_factory=dict)     # name -> FuncInfo
    bases: list = field(default_factory=list)       # base-class name strings


class ModuleInfo:
    """Per-module import aliases + top-level symbol map, the raw material
    for callgraph edge resolution."""

    def __init__(self, rel: str, tree: Optional[ast.Module]):
        self.rel = rel
        # alias -> dotted module path ("import trino_tpu.exec.kernels as K")
        self.module_aliases: dict = {}
        # name -> (dotted module path, original name)   ("from x import y")
        self.from_imports: dict = {}
        if tree is not None:
            self._collect(tree)

    def _dots_to_package(self, level: int) -> str:
        """Resolve a relative-import level against this module's location."""
        parts = self.rel[:-3].split("/")        # strip .py
        # level=1 → same package: drop the module filename
        keep = len(parts) - level
        return ".".join(parts[:keep]) if keep > 0 else ""

    def _collect(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        self.module_aliases[a.asname] = a.name
                    else:
                        # "import a.b.c" binds only the top name "a"
                        top = a.name.split(".")[0]
                        self.module_aliases[top] = top
                        # but "a.b.c.f()" is resolvable through full paths:
                        # keep the dotted form reachable under itself
                        self.module_aliases.setdefault(a.name, a.name)
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    pkg = self._dots_to_package(node.level)
                    base = f"{pkg}.{base}".strip(".") if base else pkg
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.from_imports[a.asname or a.name] = (base, a.name)


def _module_rel(dotted: str, files: dict) -> Optional[str]:
    """Dotted module path -> repo-relative file, if indexed."""
    cand = dotted.replace(".", "/") + ".py"
    if cand in files:
        return cand
    init = dotted.replace(".", "/") + "/__init__.py"
    if init in files:
        return init
    return None


class ProjectIndex:
    """Every indexed file parsed once, plus symbols and a callgraph."""

    def __init__(self, root: str, files: dict):
        self.root = root
        self.files = files                      # rel -> SourceFile
        self.functions: dict = {}               # qualname -> FuncInfo
        self.classes: dict = {}                 # qualname -> ClassInfo
        self.modules: dict = {}                 # rel -> ModuleInfo
        self._callgraph: Optional[dict] = None
        self._build_symbols()

    # ---------------------------------------------------------------- build

    @classmethod
    def build(cls, root: str, include=DEFAULT_INCLUDE) -> "ProjectIndex":
        files: dict = {}
        for entry in include:
            abs_entry = os.path.join(root, entry)
            if os.path.isfile(abs_entry):
                cls._load(files, root, entry)
                continue
            for dirpath, dirnames, filenames in os.walk(abs_entry):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__")
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        rel = os.path.relpath(os.path.join(dirpath, fn),
                                              root).replace(os.sep, "/")
                        cls._load(files, root, rel)
        return cls(root, files)

    @staticmethod
    def _load(files: dict, root: str, rel: str) -> None:
        path = os.path.join(root, rel)
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except OSError as e:
            files[rel] = SourceFile(rel, path, "", [], None, str(e), [])
            return
        lines = text.splitlines()
        tree, err = None, None
        try:
            tree = ast.parse(text, filename=rel)
        except SyntaxError as e:
            err = f"syntax error: {e.msg} (line {e.lineno})"
        files[rel] = SourceFile(rel, path, text, lines, tree, err,
                                _parse_suppressions(rel, lines))

    def _build_symbols(self) -> None:
        for rel, sf in self.files.items():
            self.modules[rel] = ModuleInfo(rel, sf.tree)
            if sf.tree is None:
                continue
            for node in sf.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fi = FuncInfo(f"{rel}::{node.name}", rel, node.name,
                                  None, node)
                    self.functions[fi.qualname] = fi
                elif isinstance(node, ast.ClassDef):
                    ci = ClassInfo(f"{rel}::{node.name}", rel, node.name,
                                   node)
                    ci.bases = [ast.unparse(b) for b in node.bases]
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            fi = FuncInfo(f"{rel}::{node.name}.{sub.name}",
                                          rel, sub.name, node.name, sub)
                            ci.methods[sub.name] = fi
                            self.functions[fi.qualname] = fi
                    self.classes[ci.qualname] = ci

    # ------------------------------------------------------------ iteration

    def iter_files(self, prefixes=None) -> Iterator[SourceFile]:
        for rel in sorted(self.files):
            if prefixes is None or any(rel.startswith(p) or rel == p
                                       for p in prefixes):
                yield self.files[rel]

    def suppressions(self) -> Iterator[Suppression]:
        for sf in self.files.values():
            yield from sf.suppressions

    # ------------------------------------------------------------ callgraph

    def resolve_call(self, rel: str, caller: FuncInfo,
                     call: ast.Call) -> Optional[str]:
        """Best-effort static resolution of a call site to a qualname in
        this index.  Handles: plain names (module-local or ``from``-import),
        ``self.method`` within a class, and ``mod.func`` through an import
        alias.  Unresolvable dynamic dispatch returns None — the callgraph
        is deliberately an under-approximation; rules that need reachability
        accept that trade against false-positive floods."""
        mod = self.modules[rel]
        fn = call.func
        if isinstance(fn, ast.Name):
            local = f"{rel}::{fn.id}"
            if local in self.functions:
                return local
            if fn.id in mod.from_imports:
                dotted, orig = mod.from_imports[fn.id]
                target_rel = _module_rel(dotted, self.files)
                if target_rel:
                    q = f"{target_rel}::{orig}"
                    if q in self.functions:
                        return q
                # "from .mod import Cls" then Cls(...) — constructor edge
                if target_rel:
                    cq = f"{target_rel}::{orig}"
                    if cq in self.classes:
                        init = self.classes[cq].methods.get("__init__")
                        return init.qualname if init else None
            return None
        if isinstance(fn, ast.Attribute):
            base = fn.value
            if isinstance(base, ast.Name) and base.id == "self" and caller.cls:
                cls = self.classes.get(f"{rel}::{caller.cls}")
                if cls and fn.attr in cls.methods:
                    return cls.methods[fn.attr].qualname
                return None
            if isinstance(base, ast.Name):
                dotted = None
                if base.id in mod.module_aliases:
                    dotted = mod.module_aliases[base.id]
                elif base.id in mod.from_imports:
                    # "from trino_tpu.exec import kernels" → module object
                    pkg, orig = mod.from_imports[base.id]
                    dotted = f"{pkg}.{orig}".strip(".")
                if dotted:
                    target_rel = _module_rel(dotted, self.files)
                    if target_rel:
                        q = f"{target_rel}::{fn.attr}"
                        if q in self.functions:
                            return q
        return None

    def callgraph(self) -> dict:
        """qualname -> set of callee qualnames (cached)."""
        if self._callgraph is not None:
            return self._callgraph
        graph: dict = {}
        for q, fi in self.functions.items():
            out = set()
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Call):
                    callee = self.resolve_call(fi.rel, fi, node)
                    if callee:
                        out.add(callee)
            graph[q] = out
        self._callgraph = graph
        return graph

    def reachable(self, roots) -> set:
        """Transitive closure over the callgraph from ``roots`` qualnames."""
        graph = self.callgraph()
        seen = set()
        stack = [r for r in roots if r in graph]
        while stack:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            stack.extend(graph.get(q, ()) - seen)
        return seen

    def enclosing_function(self, rel: str, node: ast.AST) -> Optional[FuncInfo]:
        """The FuncInfo whose source span contains ``node`` (innermost)."""
        best = None
        for q, fi in self.functions.items():
            if fi.rel != rel:
                continue
            end = getattr(fi.node, "end_lineno", fi.node.lineno)
            if fi.node.lineno <= node.lineno <= end:
                if best is None or fi.node.lineno > best.node.lineno:
                    best = fi
        return best


def apply_suppressions(index: ProjectIndex, findings: list,
                       ran_rules: set) -> tuple:
    """Split findings into (kept, suppressed); mark pragmas used; append
    unused-suppression findings for pragmas naming a rule that ran but
    excused nothing."""
    sups = list(index.suppressions())
    kept, suppressed = [], []
    for f in findings:
        hit = None
        for s in sups:
            if s.applies(f):
                s.used.add(f.rule)
                hit = s
                break
        (suppressed if hit else kept).append(f)
    for s in sups:
        for rule in s.rules:
            if rule in ran_rules and rule != "unused-suppression" \
                    and rule not in s.used:
                kept.append(Finding(
                    "unused-suppression", s.path, s.directive_line,
                    f"suppression for '{rule}' matches no finding — remove "
                    f"the stale pragma"))
    return kept, suppressed
