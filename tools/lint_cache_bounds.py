#!/usr/bin/env python3
"""Legacy entry point — the cache-bounds lint now lives in the tpulint
framework (tools/analysis/rules/cache_bounds.py) as an AST rule over
decorator lists and ``lru_cache(...)`` call forms.

This shim keeps the historical CLI (``python tools/lint_cache_bounds.py``)
and module API (``lint_file``, ``run``) stable for tests/test_caching.py.
Prefer ``python -m tools.analysis``.
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.analysis.rules.cache_bounds import (  # noqa: E402,F401
    EXEMPT,
    lint_file,
    main,
    run,
)

if __name__ == "__main__":
    sys.exit(main())
