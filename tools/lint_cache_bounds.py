#!/usr/bin/env python
"""Grep-based lint: no new unbounded memoization outside the registry.

PR 12 centralized every jitted-program memo behind
``trino_tpu/caching/executable_cache.jit_memo`` — bounded, observable via
``system.runtime.caches``, evictable, and journaled for boot-time warming.
An ad-hoc ``@lru_cache(maxsize=None)`` on a jit-wrapper builder silently
reintroduces the pre-PR-12 failure mode: an invisible, unbounded pile of
compiled executables that no memory accounting sees and no restart can
re-warm.  This lint statically rejects the unbounded forms:

- ``@lru_cache`` / ``@functools.lru_cache`` (bare decorator — unbounded)
- ``lru_cache()`` / ``lru_cache(maxsize=None)``
- ``@functools.cache`` / ``@cache`` (always unbounded)

Bounded ``lru_cache(maxsize=N)`` is allowed — it can't grow without limit,
only unobserved, and some non-jit uses (parsing, schema lookups) are fine.
The registry module itself (caching/executable_cache.py) is exempt: the
``TRINO_TPU_EXEC_CACHE=0`` kill switch intentionally falls back to the
bit-for-bit legacy ``lru_cache(maxsize=None)`` there.  A justified
exception elsewhere carries a ``# cache-ok`` pragma.

Like tools/lint_metric_names.py this is deliberately dumb — regex over
lines, no AST — so it runs in milliseconds and is obvious to extend.

Run directly (``python tools/lint_cache_bounds.py``; exit 1 on findings)
or via the tier-1 test tests/test_caching.py.
"""

from __future__ import annotations

import os
import re
import sys

# unbounded memo forms; bounded lru_cache(maxsize=N) deliberately passes
UNBOUNDED = re.compile(
    r"(?:functools\s*\.\s*)?lru_cache\s*\(\s*(?:maxsize\s*=\s*None\s*)?\)"
    r"|@\s*(?:functools\s*\.\s*)?lru_cache\s*$"
    r"|@\s*(?:functools\s*\.\s*)?cache\s*$")
SCAN_DIR = "trino_tpu"
EXEMPT = os.path.join("caching", "executable_cache.py")
PRAGMA = "cache-ok"


def lint_file(path: str) -> list[tuple[str, int, str]]:
    """-> [(path, lineno, problem)] for one file."""
    findings = []
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            if PRAGMA in line:
                continue
            if UNBOUNDED.search(line.rstrip()):
                findings.append(
                    (path, lineno,
                     "unbounded memo cache — use "
                     "caching.executable_cache.jit_memo (bounded, "
                     "observable, warm-journaled) or lru_cache(maxsize=N)"))
    return findings


def run(root: str) -> list[tuple[str, int, str]]:
    findings = []
    for dirpath, _dirs, files in os.walk(os.path.join(root, SCAN_DIR)):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            if path.endswith(EXEMPT):
                continue
            findings.extend(lint_file(path))
    return findings


def main() -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = run(root)
    for path, lineno, problem in findings:
        rel = os.path.relpath(path, root)
        print(f"{rel}:{lineno}: {problem}")
    if findings:
        print(f"{len(findings)} cache-bound violation(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
