"""SF1 correctness net on the real device: oracle-diff a TPC-H subset at
scale factor 1 (6M lineitem rows) — the scale where shape-bucket cliffs,
collective edges and masked aggregation paths actually engage (round-4
VERDICT item #8; run: python tools/sf1_check.py [q,q,...])."""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    queries = [int(q) for q in (sys.argv[1] if len(sys.argv) > 1
                                else "1,3,5,6,10,12,14,19").split(",")]
    sf = float(os.environ.get("SF", "1"))
    import jax

    from trino_tpu.connectors.catalog import default_catalog
    from trino_tpu.connectors.tpch_queries import QUERIES
    from trino_tpu.runner import Session, StandaloneQueryRunner
    from trino_tpu.testing.oracle import SqliteOracle, assert_same_rows

    try:
        jax.config.update("jax_compilation_cache_dir", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception:
        pass

    catalog = default_catalog(scale_factor=sf)
    runner = StandaloneQueryRunner(catalog, session=Session())
    oracle = SqliteOracle()
    conn = catalog.connector("tpch")
    t0 = time.time()
    for t in ["nation", "region", "supplier", "customer", "part", "partsupp",
              "orders", "lineitem"]:
        schema = conn.get_table_schema(t)
        cols = schema.column_names()
        batches = []
        for s in conn.get_splits(t, 4, 1):
            src = conn.create_page_source(s, cols)
            while not src.is_finished():
                b = src.get_next_batch()
                if b is not None:
                    batches.append(b)
        oracle.load_table(t, batches)
        print(f"loaded {t} into oracle ({time.time() - t0:.0f}s)", flush=True)
    for q in queries:
        sql = QUERIES[q]
        t0 = time.time()
        got = runner.execute(sql).rows()
        engine_s = time.time() - t0
        t0 = time.time()
        want = oracle.query(sql)
        oracle_s = time.time() - t0
        assert_same_rows(got, want, ordered="order by" in sql.lower())
        print(f"q{q:02d} OK rows={len(got)} engine={engine_s:.1f}s "
              f"sqlite={oracle_s:.1f}s", flush=True)
    print("SF1 ORACLE CHECK PASSED", flush=True)


if __name__ == "__main__":
    main()
