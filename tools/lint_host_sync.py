#!/usr/bin/env python3
"""Legacy entry point — the host-sync lint now lives in the tpulint
framework (tools/analysis/rules/host_sync.py), which adds a dataflow
layer (implicit syncs on inferred device values reachable from SyncGuard
hot regions) on top of the original grep patterns kept there verbatim.

This shim keeps the historical CLI (``python tools/lint_host_sync.py``)
and module API (``PATTERNS``, ``lint_file``, ``run``) stable for
tests/test_sync_lint.py.  Prefer ``python -m tools.analysis``.
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.analysis.rules.host_sync import (  # noqa: E402,F401
    EXEMPT_FILES,
    PATTERNS,
    PRAGMA,
    SCAN_DIRS,
    SCAN_FILES,
    lint_file,
    main,
    run,
)

if __name__ == "__main__":
    sys.exit(main())
