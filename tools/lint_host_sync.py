#!/usr/bin/env python
"""Grep-based lint: no raw device->host scalar syncs in the exec hot path.

Blocking scalar materializations (``int(np.asarray(dev))``, ``.item()``,
``bool(np.asarray(dev))`` ...) cost a full device round trip (~120 ms over a
tunneled TPU) and dominated the r4 join profile when they hid inside
per-batch operator code.  The sync-free rework routes every DELIBERATE host
transfer through exec/syncguard.py (``SG.fetch`` / ``SG.async_scalar``) so
it is counted, attributed to a tag, and forbidden inside hot regions under
test enforcement.  This lint keeps raw patterns from creeping back into
``trino_tpu/exec/`` and ``trino_tpu/ops/``.

A line that is a justified exception carries a ``# sync-ok`` pragma (with a
reason, ideally).  The SyncGuard module itself is exempt — it IS the
sanctioned wrapper.

Run directly (``python tools/lint_host_sync.py``; exit 1 on findings) or via
the tier-1 test tests/test_sync_lint.py.
"""

from __future__ import annotations

import os
import re
import sys

# each pattern is (regex, human label); kept deliberately dumb — greppable,
# no AST — so the lint runs in milliseconds and is obvious to extend
PATTERNS: list[tuple[re.Pattern, str]] = [
    (re.compile(r"\bint\(np\.asarray\("), "int(np.asarray(...)) blocking sync"),
    (re.compile(r"\bbool\(np\.asarray\("),
     "bool(np.asarray(...)) blocking sync"),
    (re.compile(r"\bfloat\(np\.asarray\("),
     "float(np.asarray(...)) blocking sync"),
    (re.compile(r"\.item\(\)"), ".item() blocking sync"),
    (re.compile(r"\bjax\.device_get\("), "raw jax.device_get (use SG.fetch)"),
    (re.compile(r"block_until_ready\("),
     "block_until_ready blocking sync (use SG.fetch / SG.async_scalar)"),
]

# parallel/ rides along: static_agg and the shard_map pipelines promise
# sync-free bodies, so raw fetches there are as load-bearing a bug as in exec
SCAN_DIRS = ("trino_tpu/exec", "trino_tpu/ops", "trino_tpu/parallel")
# the fused-stage path promises ZERO host syncs between input deposit and
# output take (SyncGuard hot_region asserted by tests/test_fused_stage.py),
# and the collective exchange is its legacy twin — both scan file-by-file
SCAN_FILES = ("trino_tpu/execution/stage_compiler.py",
              "trino_tpu/execution/collective_exchange.py")
EXEMPT_FILES = ("syncguard.py",)  # the sanctioned wrapper itself
PRAGMA = "sync-ok"


def lint_file(path: str) -> list[tuple[str, int, str, str]]:
    findings = []
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            if PRAGMA in line:
                continue
            for pat, label in PATTERNS:
                if pat.search(line):
                    findings.append((path, lineno, label, line.strip()))
    return findings


def run(root: str) -> list[tuple[str, int, str, str]]:
    findings = []
    for d in SCAN_DIRS:
        base = os.path.join(root, d)
        for dirpath, _dirnames, filenames in os.walk(base):
            for fn in sorted(filenames):
                if not fn.endswith(".py") or fn in EXEMPT_FILES:
                    continue
                findings.extend(lint_file(os.path.join(dirpath, fn)))
    for rel in SCAN_FILES:
        path = os.path.join(root, rel)
        if os.path.exists(path):
            findings.extend(lint_file(path))
    return findings


def main() -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = run(root)
    for path, lineno, label, line in findings:
        rel = os.path.relpath(path, root)
        print(f"{rel}:{lineno}: {label}: {line}", file=sys.stderr)
    if findings:
        print(f"{len(findings)} raw host sync(s) in the exec hot path — "
              "route them through exec/syncguard.py (SG.fetch / "
              "SG.async_scalar) or justify with a '# sync-ok' pragma",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
