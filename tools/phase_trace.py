"""Blocking per-phase timer: wraps the engine's jitted entry points with
block_until_ready so device time is attributed to the program that spent it
(the async dispatch model otherwise charges everything to the next sync)."""

from __future__ import annotations

import collections
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

PHASES = collections.defaultdict(lambda: [0, 0.0])


def _force(out):
    """block_until_ready is a no-op on the tunneled axon backend; pulling a
    scalar derived from one output leaf forces real completion (~110ms RPC
    floor per call — subtract that when reading results)."""
    import jax.numpy as jnp

    for leaf in jax.tree_util.tree_leaves(out):
        if hasattr(leaf, "ravel") and getattr(leaf, "size", 0):
            jax.device_get(jnp.sum(leaf.ravel()[:1]))
            return


def timed(name, fn):
    def wrapper(*a, **kw):
        t0 = time.perf_counter()
        out = fn(*a, **kw)
        try:
            _force(out)
        except Exception:
            pass
        dt = time.perf_counter() - t0
        s = PHASES[name]
        s[0] += 1
        s[1] += dt
        return out

    return wrapper


def main() -> None:
    sf = float(os.environ.get("SF", "0.2"))
    import bench

    bench._enable_compile_cache()

    import trino_tpu.exec.join_exec as JX
    import trino_tpu.exec.kernels as K
    from trino_tpu.exec.operators import FilterProjectOperator

    for mod, name in [(JX, "_build_fn"), (JX, "_ranges_fn")]:
        orig = getattr(mod, name)

        def make(orig, label):
            def cached(*a, **kw):
                return timed(label, orig(*a, **kw))

            return cached

        setattr(mod, name, make(orig, name))

    # pair programs
    orig_make_pair = JX._make_pair_fn

    def make_pair(*a, **kw):
        return timed("pair_program", orig_make_pair(*a, **kw))

    JX._make_pair_fn = make_pair
    JX._PAIR_CACHE.clear()

    for name in ["_group_ids_fn", "_reduce_fn", "_keys_out_fn",
                 "_finalize_fn", "_device_sort_fn", "_domain_fn"]:
        orig = getattr(K, name)

        def mk(orig, label):
            def cached(*a, **kw):
                return timed(label, orig(*a, **kw))

            return cached

        setattr(K, name, mk(orig, name))

    orig_compile = FilterProjectOperator._compile

    def compile_wrap(self, batch):
        run, projs = orig_compile(self, batch)
        return timed("filter_project", run), projs

    FilterProjectOperator._compile = compile_wrap

    catalog = bench._stage_memory_tables(sf)
    from trino_tpu.runner import Session, StandaloneQueryRunner

    runner = StandaloneQueryRunner(
        catalog, session=Session(default_catalog="memory", splits_per_node=1))

    for qname in os.environ.get("QUERIES", "q1,q3").split(","):
        sql = bench.QUERIES[qname]
        runner.execute(sql)  # warmup
        PHASES.clear()
        t0 = time.perf_counter()
        r = runner.execute(sql)
        for c in r.batch.columns:
            jax.block_until_ready(c.data)
        wall = time.perf_counter() - t0
        print(f"\n### {qname}: wall {wall * 1e3:.1f}ms")
        for name, (n, secs) in sorted(PHASES.items(), key=lambda kv: -kv[1][1]):
            print(f"  {secs * 1e3:8.1f}ms  n={n:<4d} {name}")


if __name__ == "__main__":
    main()
