"""Host-sync / dispatch profiler for engine queries on the tunneled TPU.

Counts and times every blocking device interaction (jax.device_get,
ArrayImpl.__array__ pulls, scalar int()/bool() syncs) plus jit dispatches,
attributed to call sites.  Usage:

    python tools/perf_trace.py [--sf 0.05] [--queries q1,q3]

Each blocking RPC through the axon tunnel costs ~120ms; the point of the
round-4 perf work is to drive these counts to ~1 scalar sync per blocking
operator and zero bulk D2H on the hot path.
"""

from __future__ import annotations

import argparse
import collections
import time
import traceback

import jax
import numpy as np

STATS = collections.defaultdict(lambda: [0, 0.0, 0])  # site -> [count, secs, bytes]
ENABLED = {"on": False}


def _site() -> str:
    for fr in reversed(traceback.extract_stack(limit=25)):
        fn = fr.filename
        if "/trino_tpu/" in fn:
            return f"{fn.split('/trino_tpu/')[-1]}:{fr.lineno}"
    return "external"


def _wrap(obj, name, kind):
    orig = getattr(obj, name)

    def wrapper(*a, **kw):
        if not ENABLED["on"]:
            return orig(*a, **kw)
        t0 = time.perf_counter()
        out = orig(*a, **kw)
        dt = time.perf_counter() - t0
        s = STATS[(kind, _site())]
        s[0] += 1
        s[1] += dt
        try:
            if kind == "device_get":
                leaves = jax.tree_util.tree_leaves(out)
                s[2] += sum(getattr(x, "nbytes", 0) for x in leaves)
            elif kind == "to_np":
                s[2] += getattr(out, "nbytes", 0)
        except Exception:
            pass
        return out

    setattr(obj, name, wrapper)


def install() -> None:
    from jax._src.array import ArrayImpl

    _wrap(jax, "device_get", "device_get")
    _wrap(ArrayImpl, "__array__", "to_np")
    _wrap(ArrayImpl, "__int__", "scalar")
    _wrap(ArrayImpl, "__bool__", "scalar")
    _wrap(ArrayImpl, "__float__", "scalar")
    _wrap(ArrayImpl, "__index__", "scalar")
    _wrap(ArrayImpl, "block_until_ready", "block")
    import jax._src.pjit as _pjit

    if hasattr(_pjit, "_python_pjit_helper"):
        _wrap(_pjit, "_python_pjit_helper", "jit_call")


def report(title: str) -> None:
    print(f"\n== {title} ==")
    rows = sorted(STATS.items(), key=lambda kv: -kv[1][1])
    total_t = sum(v[1] for v in STATS.values())
    total_n = sum(v[0] for v in STATS.values())
    for (kind, site), (n, secs, nbytes) in rows[:30]:
        mb = f" {nbytes / 1e6:8.1f}MB" if nbytes else "           "
        print(f"  {secs * 1e3:8.1f}ms  n={n:<5d}{mb}  {kind:10s} {site}")
    print(f"  TOTAL blocking+dispatch: {total_t * 1e3:.1f}ms over {total_n} events")
    STATS.clear()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", type=float, default=0.05)
    ap.add_argument("--queries", default="q1,q3")
    args = ap.parse_args()

    install()

    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench

    bench._enable_compile_cache()
    catalog = bench._stage_memory_tables(args.sf)
    from trino_tpu.runner import Session, StandaloneQueryRunner

    runner = StandaloneQueryRunner(
        catalog, session=Session(default_catalog="memory", splits_per_node=1))

    for name in args.queries.split(","):
        sql = bench.QUERIES[name]
        runner.execute(sql)  # warmup/compile
        STATS.clear()
        ENABLED["on"] = True
        t0 = time.perf_counter()
        r = runner.execute(sql)
        for c in r.batch.columns:
            jax.block_until_ready(c.data)
        wall = time.perf_counter() - t0
        ENABLED["on"] = False
        print(f"\n### {name}: wall {wall * 1e3:.1f}ms")
        report(name)


if __name__ == "__main__":
    main()
