#!/usr/bin/env python
"""Grep-based lint: no unbounded network waits in trino_tpu/execution/.

A ``urlopen``/socket call without an explicit ``timeout=`` blocks forever
when the peer wedges — exactly the silent-stall class the resilience layer
(spi/errors.py Backoff, execution/failure_detector.py) exists to eliminate.
This lint keeps timeout-less network calls from regressing into the
coordinator/worker execution code.

A call site is flagged when the call's argument span (the balanced-paren
region starting at the call, capped at a few lines) contains no ``timeout``
keyword.  A justified exception carries a ``# net-ok`` pragma on the call
line (with a reason, ideally).

Run directly (``python tools/lint_net_timeout.py``; exit 1 on findings) or
via the tier-1 test tests/test_net_lint.py.
"""

from __future__ import annotations

import os
import re
import sys

# each pattern opens a network call whose argument span must name a timeout;
# deliberately dumb — greppable, no AST — so the lint runs in milliseconds
PATTERNS: list[tuple[re.Pattern, str]] = [
    (re.compile(r"\burlopen\s*\("), "urlopen without timeout"),
    (re.compile(r"\bsocket\.create_connection\s*\("),
     "socket.create_connection without timeout"),
    (re.compile(r"\bHTTPConnection\s*\("), "HTTPConnection without timeout"),
    (re.compile(r"\bHTTPSConnection\s*\("),
     "HTTPSConnection without timeout"),
]

SCAN_DIRS = ("trino_tpu/execution",)
PRAGMA = "net-ok"
# how many lines a call's argument list may span before we give up and flag
MAX_CALL_SPAN = 10


def _call_span(lines: list[str], lineno: int, col: int) -> str:
    """The text from the call's opening paren to its balanced close (or the
    span cap) — the region a ``timeout=`` keyword must appear in."""
    depth = 0
    chunks = []
    for i in range(lineno - 1, min(lineno - 1 + MAX_CALL_SPAN, len(lines))):
        text = lines[i][col:] if i == lineno - 1 else lines[i]
        for j, ch in enumerate(text):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    chunks.append(text[:j + 1])
                    return "".join(chunks)
        chunks.append(text)
        col = 0
    return "".join(chunks)


def lint_file(path: str) -> list[tuple[str, int, str, str]]:
    findings = []
    with open(path, encoding="utf-8") as f:
        lines = f.read().splitlines()
    for lineno, line in enumerate(lines, 1):
        if PRAGMA in line:
            continue
        for pat, label in PATTERNS:
            m = pat.search(line)
            if m is None:
                continue
            span = _call_span(lines, lineno, m.start())
            if "timeout" not in span:
                findings.append((path, lineno, label, line.strip()))
    return findings


def run(root: str) -> list[tuple[str, int, str, str]]:
    findings = []
    for d in SCAN_DIRS:
        base = os.path.join(root, d)
        for dirpath, _dirnames, filenames in os.walk(base):
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                findings.extend(lint_file(os.path.join(dirpath, fn)))
    return findings


def main() -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = run(root)
    for path, lineno, label, line in findings:
        rel = os.path.relpath(path, root)
        print(f"{rel}:{lineno}: {label}: {line}", file=sys.stderr)
    if findings:
        print(f"{len(findings)} unbounded network call(s) in "
              "trino_tpu/execution/ — pass an explicit timeout= or justify "
              "with a '# net-ok' pragma", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
