#!/usr/bin/env python3
"""Legacy entry point — the net-timeout lint now lives in the tpulint
framework (tools/analysis/rules/net_timeout.py) as an AST rule: it sees
whole argument lists (multi-line calls, positional timeouts) instead of
balanced-paren text heuristics.

This shim keeps the historical CLI (``python tools/lint_net_timeout.py``)
and module API (``lint_file``) stable for tests/test_net_lint.py.
Prefer ``python -m tools.analysis``.
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.analysis.rules.net_timeout import (  # noqa: E402,F401
    NETWORK_CALLS,
    lint_file,
    main,
)

if __name__ == "__main__":
    sys.exit(main())
