// Native page-file reader/writer for the file connector.
//
// The IO subsystem of the engine in C++ (the role Trino's native readers /
// writers play for the Hive connector — reference:
// lib/trino-parquet, lib/trino-orc native-style columnar IO): a table is a
// directory of page files; each page is the engine's serde frame
// (execution/serde.py, magic "TTP1") with a zlib-compressed payload.  The
// hot paths — frame scan, zlib inflate/deflate, validity bitmap
// pack/unpack — run here; Python binds via ctypes (no pybind11 in the
// image) and falls back to the pure-Python serde when the library is not
// built.
//
// Build: c++ -O3 -shared -fPIC -o libpagefile.so pagefile.cpp -lz
// (driven by setup.py / trino_tpu/native.py on demand)

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <zlib.h>

extern "C" {

// ---------------------------------------------------------------------------
// zlib framing: compress/decompress one page payload

// Returns compressed size, or -1 on error.  dst must hold compressBound(n).
int64_t ttp_deflate(const uint8_t* src, int64_t n, uint8_t* dst,
                    int64_t dst_cap, int level) {
  uLongf out_len = static_cast<uLongf>(dst_cap);
  int rc = compress2(dst, &out_len, src, static_cast<uLong>(n), level);
  if (rc != Z_OK) return -1;
  return static_cast<int64_t>(out_len);
}

int64_t ttp_deflate_bound(int64_t n) {
  return static_cast<int64_t>(compressBound(static_cast<uLong>(n)));
}

// Returns decompressed size, or -1 on error.
int64_t ttp_inflate(const uint8_t* src, int64_t n, uint8_t* dst,
                    int64_t dst_cap) {
  uLongf out_len = static_cast<uLongf>(dst_cap);
  int rc = uncompress(dst, &out_len, src, static_cast<uLong>(n));
  if (rc != Z_OK) return -1;
  return static_cast<int64_t>(out_len);
}

// ---------------------------------------------------------------------------
// validity bitmaps (np.packbits big-endian layout)

void ttp_pack_bits(const uint8_t* bools, int64_t n, uint8_t* out) {
  int64_t nbytes = (n + 7) / 8;
  memset(out, 0, static_cast<size_t>(nbytes));
  for (int64_t i = 0; i < n; i++) {
    if (bools[i]) out[i >> 3] |= static_cast<uint8_t>(0x80u >> (i & 7));
  }
}

void ttp_unpack_bits(const uint8_t* bits, int64_t n, uint8_t* out) {
  for (int64_t i = 0; i < n; i++) {
    out[i] = (bits[i >> 3] >> (7 - (i & 7))) & 1;
  }
}

// ---------------------------------------------------------------------------
// page-file scan: read every length-prefixed frame's (offset, length)
//
// File layout: repeated [u32 little-endian frame_len][frame bytes].
// Returns the number of frames found (written as (offset,len) int64 pairs
// into out, capacity max_frames), or -1 on IO error / truncated file.

int64_t ttp_scan_frames(const char* path, int64_t* out, int64_t max_frames) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  int64_t count = 0;
  int64_t pos = 0;
  uint8_t hdr[4];
  while (fread(hdr, 1, 4, f) == 4) {
    uint32_t len = static_cast<uint32_t>(hdr[0]) |
                   (static_cast<uint32_t>(hdr[1]) << 8) |
                   (static_cast<uint32_t>(hdr[2]) << 16) |
                   (static_cast<uint32_t>(hdr[3]) << 24);
    if (count < max_frames) {
      out[2 * count] = pos + 4;
      out[2 * count + 1] = static_cast<int64_t>(len);
    }
    count++;
    if (fseek(f, static_cast<long>(len), SEEK_CUR) != 0) {
      fclose(f);
      return -1;
    }
    pos += 4 + static_cast<int64_t>(len);
  }
  long end = ftell(f);
  fclose(f);
  if (end != pos) return -1;  // trailing garbage / truncated frame
  return count;
}

// Read one frame's bytes into dst (caller sized it from ttp_scan_frames).
int64_t ttp_read_frame(const char* path, int64_t offset, int64_t len,
                       uint8_t* dst) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  if (fseek(f, static_cast<long>(offset), SEEK_SET) != 0) {
    fclose(f);
    return -1;
  }
  size_t got = fread(dst, 1, static_cast<size_t>(len), f);
  fclose(f);
  return got == static_cast<size_t>(len) ? len : -1;
}

}  // extern "C"
