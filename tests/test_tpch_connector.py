"""TPC-H generator connector tests: determinism, FK integrity, split union,
spec-shaped distributions; memory/blackhole connectors; oracle harness."""

import numpy as np
import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.connectors.memory import BlackholeConnector, MemoryConnector
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.spi import BIGINT, VARCHAR, ColumnBatch, ColumnSchema, TableSchema
from trino_tpu.testing.oracle import SqliteOracle, assert_same_rows, transpile


@pytest.fixture(scope="module")
def conn():
    return TpchConnector(scale_factor=0.01)


def read_all(conn, table, columns, splits_per_node=4):
    splits = conn.get_splits(table, splits_per_node, 1)
    batches = []
    for s in splits:
        src = conn.create_page_source(s, columns)
        while not src.is_finished():
            b = src.get_next_batch()
            if b is not None:
                batches.append(b)
    return ColumnBatch.concat(batches)


def test_cardinalities(conn):
    assert conn.row_count("nation") == 25
    assert conn.row_count("region") == 5
    assert conn.row_count("supplier") == 100
    assert conn.row_count("customer") == 1500
    assert conn.row_count("orders") == 15000
    li = conn.row_count("lineitem")
    assert 15000 * 3 < li < 15000 * 5  # ~4 lines/order


def test_determinism_and_split_union(conn):
    whole = read_all(conn, "orders", ["o_orderkey", "o_custkey"], splits_per_node=1)
    parts = read_all(conn, "orders", ["o_orderkey", "o_custkey"], splits_per_node=3)
    assert whole.num_rows == parts.num_rows == 15000
    a = np.sort(np.asarray(whole.column("o_orderkey").data))
    b = np.sort(np.asarray(parts.column("o_orderkey").data))
    assert (a == b).all()
    assert (a == np.arange(1, 15001)).all()
    # same values regardless of split layout
    wa = np.asarray(whole.column("o_custkey").data)
    pa = np.asarray(parts.column("o_custkey").data)
    order_w = np.argsort(np.asarray(whole.column("o_orderkey").data))
    order_p = np.argsort(np.asarray(parts.column("o_orderkey").data))
    assert (wa[order_w] == pa[order_p]).all()


def test_fk_integrity(conn):
    li = read_all(conn, "lineitem", ["l_orderkey", "l_partkey", "l_suppkey"])
    ps = read_all(conn, "partsupp", ["ps_partkey", "ps_suppkey"])
    # every lineitem (partkey, suppkey) must exist in partsupp (Q9 joins on it)
    li_pairs = set(zip(np.asarray(li.column("l_partkey").data).tolist(),
                       np.asarray(li.column("l_suppkey").data).tolist()))
    ps_pairs = set(zip(np.asarray(ps.column("ps_partkey").data).tolist(),
                       np.asarray(ps.column("ps_suppkey").data).tolist()))
    assert li_pairs <= ps_pairs
    # suppkeys within range
    sk = np.asarray(li.column("l_suppkey").data)
    assert sk.min() >= 1 and sk.max() <= conn.row_count("supplier")
    # orderkeys dense 1..N
    ok = np.asarray(li.column("l_orderkey").data)
    assert set(np.unique(ok)) == set(range(1, 15001))


def test_customers_without_orders(conn):
    o = read_all(conn, "orders", ["o_custkey"])
    ck = np.asarray(o.column("o_custkey").data)
    assert (ck % 3 != 0).all()  # every third customer never orders
    assert ck.min() >= 1 and ck.max() <= 1500


def test_date_correlations_and_flags(conn):
    li = read_all(conn, "lineitem",
                  ["l_shipdate", "l_commitdate", "l_receiptdate",
                   "l_returnflag", "l_linestatus"])
    ship = np.asarray(li.column("l_shipdate").data)
    rec = np.asarray(li.column("l_receiptdate").data)
    assert ((rec > ship) & (rec <= ship + 30)).all()
    flags = li.column("l_returnflag").to_pylist()
    status = li.column("l_linestatus").to_pylist()
    assert set(flags) == {"A", "N", "R"}
    assert set(status) == {"F", "O"}
    # Q1 predicate keeps ~98% of rows
    import datetime

    cut = (datetime.date(1998, 9, 2) - datetime.date(1970, 1, 1)).days
    frac = (ship <= cut).mean()
    assert 0.95 < frac < 1.0


def test_dictionaries_shared_across_splits(conn):
    parts = []
    for s in conn.get_splits("lineitem", 3, 1):
        src = conn.create_page_source(s, ["l_shipmode"])
        while not src.is_finished():
            b = src.get_next_batch()
            if b is not None:
                parts.append(b.column("l_shipmode"))
    assert all(p.dictionary is parts[0].dictionary for p in parts[1:])
    assert list(parts[0].dictionary) == sorted(parts[0].dictionary)


def test_orderstatus_consistency(conn):
    """o_orderstatus must agree with the lineitems' linestatus."""
    o = read_all(conn, "orders", ["o_orderkey", "o_orderstatus"])
    li = read_all(conn, "lineitem", ["l_orderkey", "l_linestatus"])
    status = dict(zip(np.asarray(o.column("o_orderkey").data).tolist(),
                      o.column("o_orderstatus").to_pylist()))
    from collections import defaultdict

    by_order = defaultdict(set)
    for okey, ls in zip(np.asarray(li.column("l_orderkey").data).tolist(),
                        li.column("l_linestatus").to_pylist()):
        by_order[okey].add(ls)
    for okey, statuses in list(by_order.items())[:2000]:
        expect = "F" if statuses == {"F"} else ("O" if statuses == {"O"} else "P")
        assert status[okey] == expect, okey


def test_memory_connector_roundtrip():
    mem = MemoryConnector()
    schema = TableSchema("t", (ColumnSchema("a", BIGINT), ColumnSchema("s", VARCHAR)))
    mem.create_table(schema)
    sink = mem.create_page_sink("t")
    b = ColumnBatch.from_pydict({"a": (BIGINT, [1, 2]), "s": (VARCHAR, ["x", None])})
    sink.append(b)
    mem.finish_insert("t", sink.finish())
    splits = mem.get_splits("t", 2, 1)
    out = []
    for s in splits:
        src = mem.create_page_source(s, ["s", "a"])
        while not src.is_finished():
            nb = src.get_next_batch()
            if nb is not None:
                out.append(nb)
    got = ColumnBatch.concat(out)
    assert got.names == ["s", "a"]
    assert got.to_pylist() == [("x", 1), (None, 2)]


def test_blackhole_sink():
    bh = BlackholeConnector()
    bh.create_table(TableSchema("sink", (ColumnSchema("a", BIGINT),)))
    s = bh.create_page_sink("sink")
    s.append(ColumnBatch.from_pydict({"a": (BIGINT, [1, 2, 3])}))
    assert s.finish() == [3]
    assert bh.get_splits("sink", 4, 2) == []


def test_catalog_resolution():
    cat = default_catalog(0.01)
    c, t, schema = cat.resolve_table("lineitem", "tpch")
    assert (c, t) == ("tpch", "lineitem") and len(schema.columns) == 16
    c, t, _ = cat.resolve_table("tpch.orders", "memory")
    assert (c, t) == ("tpch", "orders")
    with pytest.raises(KeyError):
        cat.resolve_table("nope.orders", "tpch")


def test_oracle_transpile_and_query(conn):
    oracle = SqliteOracle()
    oracle.load_table("nation", [read_all(conn, "nation",
                                          ["n_nationkey", "n_name", "n_regionkey"])])
    sql = transpile("select n_name from nation where n_regionkey = 3")
    assert "interval" not in sql
    rows = oracle.query("select count(*) from nation where n_regionkey = 1")
    assert rows == [(5,)]
    # date literal + interval arithmetic
    t = transpile("select * from x where d < date '1993-07-01' + interval '3' month")
    assert "add_months(8582, 3)" in t
    t = transpile("select * from x where d <= date '1998-12-01' - interval '90' day")
    assert "(10561 + -90)" in t
    rows = oracle.query("select tpch_year(9000), tpch_quarter(9000)")
    assert rows == [(1994, 3)]


def test_oracle_assert_same_rows():
    import datetime
    import decimal

    assert_same_rows(
        [(decimal.Decimal("1.50"), datetime.date(1995, 1, 1), "x")],
        [(1.5, 9131, "x")],
    )
    with pytest.raises(AssertionError):
        assert_same_rows([(1,)], [(2,)])
