"""Tier-1 wiring for tools/lint_host_sync.py: the exec hot path must not
grow raw device->host scalar syncs (``int(np.asarray(...))``, ``.item()``,
raw ``jax.device_get``) — every deliberate transfer goes through
exec/syncguard.py where it is counted and hot-loop-enforced."""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(ROOT, "tools", "lint_host_sync.py")


def test_no_raw_host_syncs_in_exec():
    proc = subprocess.run([sys.executable, LINT], capture_output=True,
                          text=True, timeout=60)
    assert proc.returncode == 0, \
        f"raw host syncs crept into the exec hot path:\n{proc.stderr}"


def test_lint_catches_planted_violation(tmp_path):
    """The lint actually fires (guards against pattern rot)."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import lint_host_sync as L
    finally:
        sys.path.pop(0)
    bad = tmp_path / "bad.py"
    bad.write_text(
        "x = int(np.asarray(jnp.sum(a)))\n"
        "y = a.item()\n"
        "z = int(np.asarray(b))  # sync-ok: test pragma\n")
    findings = L.lint_file(str(bad))
    assert len(findings) == 2  # the pragma line is exempt
    labels = {f[2] for f in findings}
    assert any("int(np.asarray" in s for s in labels)
    assert any(".item()" in s for s in labels)


@pytest.mark.parametrize("pattern", [
    "int(np.asarray(", "bool(np.asarray(", "float(np.asarray(",
    ".item()", "jax.device_get(",
])
def test_patterns_cover_issue_list(pattern):
    """Every pattern the sync-free contract names is covered."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import lint_host_sync as L
    finally:
        sys.path.pop(0)
    line = f"v = {pattern}x)" if not pattern.startswith(".") else f"v = x{pattern}"
    assert any(p.search(line) for p, _ in L.PATTERNS), pattern
