"""Dynamic filtering: build-side key domains prune probe scans without
changing results (reference: server/DynamicFilterService.java:105,
operator/DynamicFilterSourceOperator.java:44)."""

import numpy as np
import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.exec.dynamic_filter import DynamicFilterHolder
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.runner import Session, StandaloneQueryRunner
from trino_tpu.testing.oracle import SqliteOracle, assert_same_rows

TABLES = ["nation", "region", "part", "lineitem", "orders", "customer"]


@pytest.fixture(scope="module")
def harness():
    catalog = default_catalog(scale_factor=0.01)
    runner = StandaloneQueryRunner(catalog)
    oracle = SqliteOracle()
    conn = catalog.connector("tpch")
    for t in TABLES:
        schema = conn.get_table_schema(t)
        cols = schema.column_names()
        batches = []
        for s in conn.get_splits(t, 2, 1):
            src = conn.create_page_source(s, cols)
            while not src.is_finished():
                b = src.get_next_batch()
                if b is not None:
                    batches.append(b)
        oracle.load_table(t, batches)
    return runner, oracle


def test_holder_numeric_set_and_range():
    h = DynamicFilterHolder()
    h.fill(np.array([5, 7, 7, 9]), None, None)
    mask = h.probe_mask(np.array([4, 5, 6, 7, 9, 10]), None, None)
    assert list(mask) == [False, True, False, True, True, False]


def test_holder_null_probe_keys_dropped():
    h = DynamicFilterHolder()
    h.fill(np.array([1, 2]), None, None)
    mask = h.probe_mask(np.array([1, 2]), np.array([True, False]), None)
    assert list(mask) == [True, False]


def test_holder_empty_build():
    h = DynamicFilterHolder()
    h.fill(np.array([], dtype=np.int64), None, None)
    assert h.empty
    assert not h.probe_mask(np.array([1, 2, 3]), None, None).any()


def test_holder_dictionary_values():
    h = DynamicFilterHolder()
    d = np.array(["AFRICA", "ASIA"], dtype=object)
    h.fill(np.array([0, 1, 1]), None, d)
    probe_dict = np.array(["AMERICA", "ASIA", "EUROPE"], dtype=object)
    mask = h.probe_mask(np.array([0, 1, 2]), None, probe_dict)
    assert list(mask) == [False, True, False]


SELECTIVE_JOINS = [
    # selective build (one region) prunes the nation probe
    "select n_name from nation, region "
    "where n_regionkey = r_regionkey and r_name = 'ASIA'",
    # Q17-flavored: small part subset prunes lineitem
    "select sum(l_extendedprice) from lineitem, part "
    "where l_partkey = p_partkey and p_brand = 'Brand#23' "
    "and p_container = 'MED BOX'",
    # chained joins: both filters apply
    "select count(*) from lineitem, orders, customer "
    "where l_orderkey = o_orderkey and o_custkey = c_custkey "
    "and c_mktsegment = 'BUILDING' and o_orderdate < date '1993-01-01'",
]


@pytest.mark.parametrize("sql", SELECTIVE_JOINS)
def test_results_unchanged(harness, sql):
    runner, oracle = harness
    expected = oracle.query(sql)
    assert_same_rows(runner.execute(sql).rows(), expected)
    # and identical with dynamic filtering off
    off = StandaloneQueryRunner(
        runner.catalog, session=Session(dynamic_filtering=False))
    assert_same_rows(off.execute(sql).rows(), expected)


def test_probe_rows_actually_pruned(harness):
    """EXPLAIN ANALYZE shows the probe scan emitting far fewer rows than the
    table when the build side is selective."""
    runner, oracle = harness
    sql = ("explain analyze select sum(l_extendedprice) from lineitem, part "
           "where l_partkey = p_partkey and p_brand = 'Brand#23' "
           "and p_container = 'MED BOX'")
    out = "\n".join(r[0] for r in runner.execute(sql).rows())
    # lineitem at SF0.01 has ~60k rows; a 1-of-brands x 1-of-containers
    # part filter keeps well under a tenth of them
    import re

    scans = [int(m) for m in re.findall(
        r"ScanOperator.*?out (\d+) rows", out)]
    assert scans, out
    assert min(scans) < 6000, out


def test_distributed_results_unchanged(harness):
    _, oracle = harness
    catalog = default_catalog(scale_factor=0.01)
    dist = DistributedQueryRunner(catalog, worker_count=3)
    for sql in SELECTIVE_JOINS:
        assert_same_rows(dist.execute(sql).rows(), oracle.query(sql))
