"""Statement breadth: views, materialized views, SET SESSION, CALL
procedures, ANALYZE (round-4 VERDICT missing item #9; reference:
execution/CreateViewTask.java, CreateMaterializedViewTask.java,
SetSessionTask.java, spi/procedure/Procedure.java,
StatisticsWriterOperator.java:35)."""

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.runner import Session, StandaloneQueryRunner


@pytest.fixture()
def runner():
    return StandaloneQueryRunner(default_catalog(scale_factor=0.01),
                                 session=Session(default_catalog="tpch"))


def test_create_view_and_query(runner):
    runner.execute("create view big_nations as "
                   "select n_name, n_regionkey from nation where n_nationkey > 20")
    rows = runner.execute("select count(*) from big_nations").rows()
    assert rows == [(4,)]
    rows = runner.execute(
        "select v.n_name from big_nations v join region r "
        "on v.n_regionkey = r.r_regionkey where r.r_name = 'ASIA' "
        "order by 1").rows()
    assert all(isinstance(r[0], str) for r in rows)
    # view shows up in SHOW TABLES
    tables = [r[0] for r in runner.execute("show tables").rows()]
    assert "big_nations" in tables
    with pytest.raises(ValueError):
        runner.execute("create view big_nations as select 1")
    runner.execute("create or replace view big_nations as "
                   "select n_name from nation")
    assert runner.execute("select count(*) from big_nations").rows() == [(25,)]
    runner.execute("drop view big_nations")
    with pytest.raises(Exception):
        runner.execute("select * from big_nations")
    runner.execute("drop view if exists big_nations")  # idempotent


def test_materialized_view_refresh(runner):
    runner.execute("create table memory.mv_src (x bigint)")
    runner.execute("insert into memory.mv_src values (1), (2)")
    runner.execute("create materialized view mv_sum as "
                   "select sum(x) as s from memory.mv_src")
    assert runner.execute("select s from mv_sum").rows() == [(3,)]
    # stale until refreshed (the materialized read hits the backing table)
    runner.execute("insert into memory.mv_src values (10)")
    assert runner.execute("select s from mv_sum").rows() == [(3,)]
    runner.execute("refresh materialized view mv_sum")
    assert runner.execute("select s from mv_sum").rows() == [(13,)]
    runner.execute("drop materialized view mv_sum")


def test_set_session(runner):
    out = runner.execute("set session dynamic_filtering = false").rows()
    assert runner.session.dynamic_filtering is False
    assert "false" in str(out[0][0]).lower()
    runner.execute("set session splits_per_node = 2")
    assert runner.session.splits_per_node == 2
    with pytest.raises(KeyError):
        runner.execute("set session no_such_knob = 1")


def test_call_procedure(runner):
    runner.execute("create table memory.pt (x bigint)")
    runner.execute("insert into memory.pt values (1), (2), (3)")
    out = runner.execute("call memory.truncate_table('pt')").rows()
    assert "truncated" in out[0][0]
    assert runner.execute("select count(*) from memory.pt").rows() == [(0,)]
    with pytest.raises(KeyError):
        runner.execute("call memory.no_such_proc()")


def test_analyze_feeds_stats(runner):
    runner.execute("create table memory.an (k bigint, s varchar)")
    runner.execute("insert into memory.an values (1, 'a'), (2, 'b'), "
                   "(2, 'b'), (3, null)")
    rows = runner.execute("analyze memory.an").rows()
    assert rows == [(4,)]
    stats = runner.catalog.connector("memory").get_table_statistics("an")
    assert stats.row_count == 4.0
    assert stats.ndv["k"] == 3.0
    assert stats.ndv["s"] == 2.0


def test_views_and_session_distributed():
    dist = DistributedQueryRunner(
        default_catalog(scale_factor=0.01), worker_count=2,
        session=Session(node_count=2))
    dist.execute("create view rv as select r_name from region")
    assert dist.execute("select count(*) from rv").rows() == [(5,)]
    dist.execute("set session use_collectives = false")
    assert dist.session.use_collectives is False
