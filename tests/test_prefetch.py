"""Async scan-ingest pipeline (exec/prefetch.py + ScanOperator async path).

Covers the ingest contracts: split-order preservation under concurrent
prefetch, queue backpressure under a tiny budget, early close on a satisfied
pushed-down LIMIT with splits in flight, coalescer correctness across
dictionary columns and dynamic-filter interaction, crash propagation from a
prefetch thread, and prefetch on/off result equivalence on real queries.
"""

import threading
import time

import numpy as np
import pytest

from trino_tpu.exec.operators import ScanOperator
from trino_tpu.exec.prefetch import (
    BatchCoalescer,
    IngestConfig,
    PrefetchingPageSource,
    coalesce_pad,
)
from trino_tpu.spi.batch import Column, ColumnBatch
from trino_tpu.spi.connector import Connector, ConnectorPageSource, Split
from trino_tpu.spi.types import BIGINT, VARCHAR


def _bigint_batch(values):
    return ColumnBatch(["v"], [Column(BIGINT, np.asarray(values, np.int64))])


class _SlowSource(ConnectorPageSource):
    def __init__(self, batches, delay=0.0, fail_at=None):
        self._batches = list(batches)
        self._i = 0
        self._delay = delay
        self._fail_at = fail_at

    def get_next_batch(self):
        if self._fail_at is not None and self._i >= self._fail_at:
            raise RuntimeError("connector exploded")
        if self._delay:
            time.sleep(self._delay)
        b = self._batches[self._i]
        self._i += 1
        return b

    def is_finished(self):
        return self._i >= len(self._batches) and self._fail_at is None


class _FakeConnector(Connector):
    """N splits, each yielding its batches through a throttled source."""

    name = "fake"

    def __init__(self, per_split_batches, delay=0.0, fail_split=None,
                 fail_at=0):
        self._per_split = per_split_batches
        self._delay = delay
        self._fail_split = fail_split
        self._fail_at = fail_at
        self.opened = []

    def splits(self):
        return [Split("fake", "t", i) for i in range(len(self._per_split))]

    def create_page_source(self, split, columns):
        self.opened.append(split.info)
        fail = self._fail_at if split.info == self._fail_split else None
        return _SlowSource(self._per_split[split.info],
                           delay=self._delay, fail_at=fail)


def _drain(src):
    out = []
    while True:
        b = src.get_next_batch()
        if b is None:
            return out
        out.append(b)


def test_split_order_preserved():
    # split k contributes values [100k, 100k+5); concurrent workers must not
    # reorder them on the consumer side
    conn = _FakeConnector([
        [_bigint_batch([s * 100 + i]) for i in range(5)]
        for s in range(6)
    ], delay=0.002)
    cfg = IngestConfig(threads=3, queue_depth=4)
    src = PrefetchingPageSource(conn, conn.splits(), ["v"], config=cfg)
    got = [int(b.columns[0].data[0]) for b in _drain(src)]
    assert got == [s * 100 + i for s in range(6) for i in range(5)]
    assert src.stats.splits_opened == 6
    assert src.stats.scan_rows == 30


def test_backpressure_small_budget():
    conn = _FakeConnector([
        [_bigint_batch(list(range(64))) for _ in range(8)]
        for _ in range(4)
    ])
    cfg = IngestConfig(threads=2, queue_depth=2, queue_bytes=1)
    src = PrefetchingPageSource(conn, conn.splits(), ["v"], config=cfg)
    seen = 0
    while True:
        b = src.get_next_batch()
        if b is None:
            break
        seen += 1
        time.sleep(0.002)  # slow consumer: producers must park, not pile up
    assert seen == 32
    # bound = budget + one in-flight insert per producer thread + the
    # starved-consumer exemption
    assert src.stats.queue_depth_max <= cfg.queue_depth + cfg.threads + 1


def test_early_close_drops_unclaimed_splits():
    conn = _FakeConnector([
        [_bigint_batch([i]) for i in range(4)] for _ in range(8)
    ], delay=0.02)
    cfg = IngestConfig(threads=1, queue_depth=2)
    src = PrefetchingPageSource(conn, conn.splits(), ["v"], config=cfg)
    assert src.get_next_batch() is not None
    src.close()
    for t in src._threads:
        t.join(timeout=5.0)
    assert not any(t.is_alive() for t in src._threads)
    assert src.stats.splits_opened < 8  # unclaimed splits never opened
    assert src.get_next_batch() is None


def test_scan_limit_early_close(monkeypatch):
    monkeypatch.setenv("TRINO_TPU_PREFETCH", "1")
    monkeypatch.setenv("TRINO_TPU_COALESCE_TARGET_ROWS", "8")
    monkeypatch.setenv("TRINO_TPU_STAGE_DEVICE", "0")
    conn = _FakeConnector([
        [_bigint_batch(list(range(8))) for _ in range(2)] for _ in range(8)
    ], delay=0.02)
    scan = ScanOperator(conn, conn.splits(), ["v"], limit=8)
    rows = 0
    while not scan.is_finished():
        b = scan.get_output()
        if b is None:
            break
        rows += b.live_count
    scan.close()
    assert rows >= 8
    for t in scan._prefetcher._threads:
        t.join(timeout=5.0)
    # LIMIT satisfied after the first split: the prefetcher must not have
    # churned through all 8
    assert scan.ingest_stats.splits_opened < 8


def test_crash_in_prefetch_thread_propagates():
    conn = _FakeConnector(
        [[_bigint_batch([1])] for _ in range(3)],
        fail_split=1, fail_at=1)
    src = PrefetchingPageSource(conn, conn.splits(), ["v"],
                                config=IngestConfig(threads=2))
    with pytest.raises(RuntimeError, match="scan prefetch thread failed"):
        _drain(src)


def test_coalesce_pad_dictionary_and_valid():
    b1 = ColumnBatch.from_pydict({
        "s": (VARCHAR, ["apple", "pear", None]),
        "n": (BIGINT, [1, None, 3]),
    })
    b2 = ColumnBatch.from_pydict({
        "s": (VARCHAR, ["pear", "zebra"]),
        "n": (BIGINT, [4, 5]),
    })
    out = coalesce_pad([b1, b2])
    assert out.num_rows == 8  # 5 rows -> bucket 8
    assert out.live is not None and int(out.live.sum()) == 5
    assert out.compact().to_pylist() == [
        ("apple", 1), ("pear", None), (None, 3), ("pear", 4), ("zebra", 5)]


def test_coalescer_merges_to_target():
    c = BatchCoalescer(target_rows=16)
    for i in range(5):
        c.add(_bigint_batch(list(range(i * 6, i * 6 + 6))))
        if c.ready():
            break
    assert c.ready()
    out = c.flush()
    assert out.live_count == 18 and out.num_rows == 32
    assert c.flush() is None


def test_scan_dynamic_filter_with_coalescing(monkeypatch):
    from trino_tpu.exec.dynamic_filter import DynamicFilterHolder

    monkeypatch.setenv("TRINO_TPU_PREFETCH", "1")
    monkeypatch.setenv("TRINO_TPU_COALESCE_TARGET_ROWS", "32")
    monkeypatch.setenv("TRINO_TPU_STAGE_DEVICE", "0")
    conn = _FakeConnector([
        [_bigint_batch(list(range(s * 10, s * 10 + 10)))] for s in range(4)
    ])
    holder = DynamicFilterHolder()
    holder.fill(np.asarray([2, 3, 11, 35], np.int64), None, None)
    scan = ScanOperator(conn, conn.splits(), ["v"],
                        dynamic_filters=[(0, holder)])
    vals = []
    while not scan.is_finished():
        b = scan.get_output()
        if b is None:
            break
        vals.extend(v for (v,) in b.to_pylist())
    # range pruning keeps [2..35]; exact set keeps the 4 build values
    assert vals == [2, 3, 11, 35]
    assert holder.rows_pruned > 0
    assert scan.ingest_stats.coalesced_batches >= 1


def test_prefetch_off_matches_on(monkeypatch):
    from trino_tpu.runner import StandaloneQueryRunner

    sql = ("select l_returnflag, count(*), sum(l_quantity) from lineitem "
           "where l_quantity < 30 group by l_returnflag order by l_returnflag")
    monkeypatch.setenv("TRINO_TPU_PREFETCH", "0")
    sync_rows = StandaloneQueryRunner().execute(sql).rows()
    monkeypatch.setenv("TRINO_TPU_PREFETCH", "1")
    monkeypatch.setenv("TRINO_TPU_COALESCE_TARGET_ROWS", "4096")
    async_rows = StandaloneQueryRunner().execute(sql).rows()
    assert sync_rows == async_rows


def test_scan_stats_in_query_stats(monkeypatch):
    from trino_tpu.runner import StandaloneQueryRunner

    monkeypatch.setenv("TRINO_TPU_PREFETCH", "1")
    r = StandaloneQueryRunner()
    rows = r.execute(
        "explain analyze select count(*) from orders").rows()
    text = "\n".join(str(v) for (v,) in rows)
    assert "scan[prefetch]" in text and "GB/s" in text
    # the execution span carries the trino.scan.* attributes
    spans = [s for root in r.tracer.finished for s in _walk(root)]
    scan_spans = [s for s in spans
                  if "trino.scan.gb-per-s" in s.attributes]
    assert scan_spans and any(
        s.attributes.get("trino.scan.prefetch") for s in scan_spans)


def _walk(span):
    yield span
    for c in span.children:
        yield from _walk(c)


def test_backpressure_threads_exit_on_consumer_abandon():
    # a consumer that stops pulling and closes must unpark parked producers
    conn = _FakeConnector([
        [_bigint_batch(list(range(64))) for _ in range(4)]
        for _ in range(4)
    ])
    cfg = IngestConfig(threads=2, queue_depth=1, queue_bytes=1)
    src = PrefetchingPageSource(conn, conn.splits(), ["v"], config=cfg)
    assert src.get_next_batch() is not None
    src.close()
    for t in src._threads:
        t.join(timeout=5.0)
    assert not any(t.is_alive() for t in src._threads)
