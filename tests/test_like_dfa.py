"""Vectorized LIKE (bit-parallel NFA over the dictionary) vs the exact
re-based oracle (reference: likematcher/DenseDfaMatcher.java:23)."""

import random
import re
import string

import numpy as np
import pytest

from trino_tpu.ops.like_dfa import VECTOR_THRESHOLD, like_mask
from trino_tpu.ops.expr import like_to_regex


def _dict(values):
    out = np.empty(len(values), dtype=object)
    for i, v in enumerate(values):
        out[i] = v
    return out


def _oracle(dictionary, pattern, escape=None):
    rx = re.compile(like_to_regex(pattern, escape), re.DOTALL)
    return np.array([rx.fullmatch(str(v)) is not None for v in dictionary])


PATTERNS = [
    "abc", "%", "%%", "a%", "%a", "%bc%", "a_c", "_", "__", "a%b%c",
    "%a_b%", "", "%%a%%", "a%%_b", "ab_", "%xyz", "x%y%z%", "a",
]


@pytest.mark.parametrize("pattern", PATTERNS)
def test_vector_matches_re(pattern):
    rng = random.Random(42)
    alphabet = "abcxyz_%"
    vals = ["".join(rng.choice(alphabet) for _ in range(rng.randrange(0, 12)))
            for _ in range(VECTOR_THRESHOLD + 500)]
    vals.extend(["", "a", "abc", "aXc", "abcabc", "ab", "a" * 70])
    d = _dict(sorted(set(vals)))
    got = like_mask(d, pattern)
    want = _oracle(d, pattern)
    diff = np.nonzero(got != want)[0]
    assert not len(diff), (pattern, [d[i] for i in diff[:5]])


def test_escape_and_unicode_fallback():
    d = _dict(["100%", "100x", "naïve", "a_c", "abc"])
    got = like_mask(d, "100\\%", "\\")
    assert got.tolist() == [True, False, False, False, False]
    # unicode literal falls back to re (codepoint >= 255 guard)
    big = _dict(sorted({f"naïve{i}" if i % 3 else f"x{i}"
                        for i in range(VECTOR_THRESHOLD + 10)}))
    got = like_mask(big, "naïve%")
    want = _oracle(big, "naïve%")
    assert (got == want).all()


def test_engine_like_still_correct():
    from trino_tpu.connectors.catalog import default_catalog
    from trino_tpu.runner import StandaloneQueryRunner

    r = StandaloneQueryRunner(default_catalog(scale_factor=0.01))
    rows = r.execute("select count(*) from customer "
                     "where c_mktsegment like 'BUILD%'").rows()
    rows2 = r.execute("select count(*) from customer "
                      "where c_mktsegment = 'BUILDING'").rows()
    assert rows == rows2 and rows[0][0] > 0


def test_embedded_nul_falls_back_to_exact():
    """Strings containing '\\x00' can't be measured from the codepoint
    matrix (padding is also 0) — the vector path must defer to re
    (advisor r4 low)."""
    vals = {f"k{i}" for i in range(VECTOR_THRESHOLD + 10)}
    vals |= {"a\x00b", "a\x00", "\x00", "ab", "a", "a\x00bXtail", "k1\x00"}
    d = _dict(sorted(vals))
    for pattern in ["a_b", "a%", "_", "ab", "a\x00b%"]:
        got = like_mask(d, pattern)
        want = _oracle(d, pattern)
        diff = np.nonzero(got != want)[0]
        assert not len(diff), (pattern, [repr(d[i]) for i in diff[:5]])
