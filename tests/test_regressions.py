"""Regression tests for review findings: scalar-subquery semantics, string
join dictionaries, null-aware NOT IN, distinct-agg NULL collisions, GROUP BY
validation, oracle transpile precedence."""

import numpy as np
import pytest

from trino_tpu.exec import kernels as K
from trino_tpu.exec.operators import JoinBridge, JoinBuildSink, SemiJoinOperator
from trino_tpu.runner import StandaloneQueryRunner
from trino_tpu.spi import BIGINT, BOOLEAN, VARCHAR, Column, ColumnBatch
from trino_tpu.sql.analyzer import AnalysisError
from trino_tpu.testing.oracle import transpile


@pytest.fixture(scope="module")
def runner():
    return StandaloneQueryRunner()


def test_correlated_count_subquery_returns_zero(runner):
    # every order matches zero lineitems under quantity < 0: count must be
    # 0 (not NULL), so the equality keeps all rows
    rows = runner.execute(
        "select count(*) from orders o where 0 = "
        "(select count(*) from lineitem l "
        " where l.l_orderkey = o.o_orderkey and l.l_quantity < 0)"
    ).rows()
    assert rows == [(15000,)]


def test_uncorrelated_empty_scalar_subquery_yields_null(runner):
    # empty scalar subquery -> NULL (not zero rows): IS NULL keeps all 25
    rows = runner.execute(
        "select count(*) from nation where "
        "(select r_regionkey from region where r_name = 'NOPE') is null"
    ).rows()
    assert rows == [(25,)]


def test_multirow_scalar_subquery_raises(runner):
    with pytest.raises(RuntimeError, match="multiple rows"):
        runner.execute(
            "select count(*) from nation where n_regionkey = "
            "(select r_regionkey from region)")


def test_string_join_across_dictionaries(runner):
    runner.execute("create table memory.nat_names as select n_name from nation "
                   "where n_regionkey = 2")
    rows = runner.execute(
        "select count(*) from nation a, memory.nat_names b "
        "where a.n_name = b.n_name").rows()
    assert rows == [(5,)]


def test_group_by_validation(runner):
    with pytest.raises(AnalysisError, match="GROUP BY"):
        runner.execute(
            "select o_custkey, count(*) from orders group by o_orderkey")


def _mark_of(source_batch, build_batch, build_keys, source_keys, null_aware):
    bridge = JoinBridge()
    sink = JoinBuildSink(bridge, build_keys, build_batch.types, build_batch.names)
    sink.add_input(build_batch)
    sink.finish_input()
    op = SemiJoinOperator(bridge, source_keys, null_aware, None,
                          list(source_batch.names) + ["mark"],
                          list(source_batch.types) + [BOOLEAN])
    op.add_input(source_batch)
    out = op.get_output()
    mark = out.columns[-1]
    return mark.to_pylist()


def test_not_in_empty_set_with_null_probe():
    probe = ColumnBatch(["x"], [Column.from_values(BIGINT, [1, None, 3])])
    build = ColumnBatch(["y"], [Column.from_values(BIGINT, [])])
    # x IN (empty) is FALSE for every row, even NULL x
    assert _mark_of(probe, build, [0], [0], null_aware=True) == [False, False, False]


def test_not_in_with_build_null():
    probe = ColumnBatch(["x"], [Column.from_values(BIGINT, [1, 2, None])])
    build = ColumnBatch(["y"], [Column.from_values(BIGINT, [1, None])])
    # 1 IN (1, NULL) -> TRUE; 2 IN (1, NULL) -> UNKNOWN; NULL IN ... -> UNKNOWN
    assert _mark_of(probe, build, [0], [0], null_aware=True) == [True, None, None]


def test_distinct_count_null_storage_collision():
    # group has a NULL (storage fill 0) AND a genuine value 0: count(distinct)
    # must count the real 0 and ignore the NULL
    data = np.array([0, 0, 5], dtype=np.int64)
    valid = np.array([False, True, True])
    gidk = np.zeros(3, dtype=np.int64)
    perm, gid, n = K.group_ids([(gidk, None)])
    (res,) = K.grouped_reduce(perm, gid, n,
                              [("count", data, valid, np.int64, True)])
    assert list(res[0]) == [2]  # distinct {0, 5}


def test_any_value_skips_nulls():
    # group [7 (valid), NULL (storage fill 0)]: any_value must return 7
    data = np.array([7, 0], dtype=np.int64)
    valid = np.array([True, False])
    gidk = np.zeros(2, dtype=np.int64)
    perm, gid, n = K.group_ids([(gidk, None)])
    (res,) = K.grouped_reduce(perm, gid, n,
                              [("any_value", data, valid, np.int64, False)])
    vals, v = res
    assert list(vals) == [7] and list(v) == [True]


def test_correlated_count_in_expression(runner):
    # count wrapped in an expression: default value is the expression at
    # count=0, i.e. 0+1=1 for every order with no matching lineitem
    rows = runner.execute(
        "select count(*) from orders o where 1 = "
        "(select count(*) + 1 from lineitem l "
        " where l.l_orderkey = o.o_orderkey and l.l_quantity < 0)"
    ).rows()
    assert rows == [(15000,)]


def test_distributed_varchar_repartition():
    from trino_tpu.execution.distributed_runner import DistributedQueryRunner

    # count(distinct) forces a repartition keyed on a VARCHAR column; the
    # routing must hash string values, not per-producer dictionary codes
    sql = ("select n_name, count(distinct s_suppkey) from supplier, nation "
           "where s_nationkey = n_nationkey group by n_name")
    from trino_tpu.connectors.catalog import default_catalog

    cat = default_catalog(0.01)
    dist = DistributedQueryRunner(cat, worker_count=3)
    sa = StandaloneQueryRunner(cat)
    from trino_tpu.testing.oracle import assert_same_rows

    assert_same_rows(dist.execute(sql).rows(), sa.execute(sql).rows())


def test_transpile_fold_is_context_limited():
    assert "0.05" in transpile("x >= 0.06 - 0.01")
    assert "0.07" in transpile("x between 0.06 - 0.01 and 0.06 + 0.01")
    # precedence traps must NOT fold
    assert "1.0" not in transpile("select 0.5 + 0.5 * x from t")
    assert "0.1" not in transpile("select 1 - 0.5 - 0.4 from t")


# --- round-2 advisor findings ------------------------------------------------


def test_correlated_sum_coalesce_zero_rows(runner):
    # coalesce(sum(..), 0) over a zero-match correlated subquery must be 0,
    # not NULL (advisor: decorrelation only restored count-family defaults)
    rows = runner.execute(
        "select count(*) from orders o where 0 = "
        "(select coalesce(sum(l.l_quantity), 0) from lineitem l "
        " where l.l_orderkey = o.o_orderkey and l.l_quantity < 0)"
    ).rows()
    assert rows == [(15000,)]


def test_correlated_sum_zero_rows_is_null(runner):
    # bare sum over zero matches stays NULL
    rows = runner.execute(
        "select count(*) from orders o where "
        "(select sum(l.l_quantity) from lineitem l "
        " where l.l_orderkey = o.o_orderkey and l.l_quantity < 0) is null"
    ).rows()
    assert rows == [(15000,)]


def test_keyless_semijoin_residual_only():
    # EXISTS decorrelated to a semi-join with no equi keys (residual only)
    # crashed probe_join_table with an empty key list (advisor finding)
    build = ColumnBatch(["b"], [Column(BIGINT, np.asarray([5, 7], np.int64))])
    bridge = JoinBridge()
    sink = JoinBuildSink(bridge, [], [BIGINT], ["b"])
    sink.add_input(build)
    sink.finish_input()
    op = SemiJoinOperator(bridge, [], False, None, ["a", "m"], [BIGINT, BOOLEAN])
    op.add_input(ColumnBatch(["a"], [Column(BIGINT, np.asarray([1, 2, 3], np.int64))]))
    out = op.get_output()
    assert list(np.asarray(out.columns[1].data)) == [True, True, True]


def test_sort_desc_int64_min():
    perm = K.sort_perm([
        (np.asarray([5, np.iinfo(np.int64).min, -3], np.int64), None, False, False)
    ])
    assert list(perm) == [0, 2, 1]  # INT64_MIN last in descending order


def test_float_zero_hash_and_group():
    # -0.0 and +0.0 must hash/group/partition identically
    d = np.asarray([0.0, -0.0, 1.5], np.float64)
    h = np.asarray(K.hash_combine([d]))
    assert h[0] == h[1]
    perm, gid, n = K.group_ids([(d, None)])
    assert n == 2
    p = K.partition_assignments([(d, None)], 7)
    assert p[0] == p[1]


def test_float_nan_single_group():
    nan1 = np.uint64(0x7FF8000000000001).view(np.float64)
    d = np.asarray([np.nan, nan1, 2.0], np.float64)
    perm, gid, n = K.group_ids([(d, None)])
    assert n == 2
    h = np.asarray(K.hash_combine([d]))
    assert h[0] == h[1]


def test_float_join_nan_and_negzero_match():
    build = [(np.asarray([np.nan, -0.0], np.float64), None)]
    table = K.build_join_table(build)
    probe = [(np.asarray([np.nan, 0.0, 3.0], np.float64), None)]
    pi, bi = K.probe_join_table(table, probe)
    pairs = sorted(zip(pi.tolist(), bi.tolist()))
    assert pairs == [(0, 0), (1, 1)]


def test_failed_task_aborts_peers_quickly():
    import time

    from trino_tpu.execution.distributed_runner import DistributedQueryRunner

    r = DistributedQueryRunner(worker_count=2)
    t0 = time.time()
    with pytest.raises(Exception):
        # multi-row scalar subquery: cardinality violation raises inside a
        # task at runtime (jnp arithmetic never traps, so use this instead)
        r.execute("select (select r_regionkey from region) from orders")
    assert time.time() - t0 < 120  # peers unwind promptly, not via timeout


def test_float_hash_full_entropy():
    # doubles that collide when rounded to float32 must hash differently
    # (hash_combine decomposes the full 53-bit significand arithmetically);
    # on TPU the x64 emulation has f32 exponent range, so the contract there
    # is consistency with device equality instead — covered by kernel checks
    base = 1.7e15
    d = np.asarray([base + 1, base + 2, 1.5e300, 1.6e300], np.float64)
    h = np.asarray(K.hash_combine([d])).tolist()
    assert len(set(h)) == 4


def test_sort_nan_vs_inf():
    # NaN sorts after +inf ascending, before it descending (Trino convention)
    d = np.asarray([np.nan, np.inf, 1.0, -np.inf], np.float64)
    asc = K.sort_perm([(d, None, True, False)])
    assert [d[i] for i in asc[:3]] == [-np.inf, 1.0, np.inf] and np.isnan(d[asc[3]])
    desc = K.sort_perm([(d, None, False, False)])
    assert np.isnan(d[desc[0]]) and [d[i] for i in desc[1:]] == [np.inf, 1.0, -np.inf]
