"""Regression tests for review findings: scalar-subquery semantics, string
join dictionaries, null-aware NOT IN, distinct-agg NULL collisions, GROUP BY
validation, oracle transpile precedence."""

import numpy as np
import pytest

from trino_tpu.exec import kernels as K
from trino_tpu.exec.operators import JoinBridge, JoinBuildSink, SemiJoinOperator
from trino_tpu.runner import StandaloneQueryRunner
from trino_tpu.spi import BIGINT, BOOLEAN, VARCHAR, Column, ColumnBatch
from trino_tpu.sql.analyzer import AnalysisError
from trino_tpu.testing.oracle import transpile


@pytest.fixture(scope="module")
def runner():
    return StandaloneQueryRunner()


def test_correlated_count_subquery_returns_zero(runner):
    # every order matches zero lineitems under quantity < 0: count must be
    # 0 (not NULL), so the equality keeps all rows
    rows = runner.execute(
        "select count(*) from orders o where 0 = "
        "(select count(*) from lineitem l "
        " where l.l_orderkey = o.o_orderkey and l.l_quantity < 0)"
    ).rows()
    assert rows == [(15000,)]


def test_uncorrelated_empty_scalar_subquery_yields_null(runner):
    # empty scalar subquery -> NULL (not zero rows): IS NULL keeps all 25
    rows = runner.execute(
        "select count(*) from nation where "
        "(select r_regionkey from region where r_name = 'NOPE') is null"
    ).rows()
    assert rows == [(25,)]


def test_multirow_scalar_subquery_raises(runner):
    with pytest.raises(RuntimeError, match="multiple rows"):
        runner.execute(
            "select count(*) from nation where n_regionkey = "
            "(select r_regionkey from region)")


def test_string_join_across_dictionaries(runner):
    runner.execute("create table memory.nat_names as select n_name from nation "
                   "where n_regionkey = 2")
    rows = runner.execute(
        "select count(*) from nation a, memory.nat_names b "
        "where a.n_name = b.n_name").rows()
    assert rows == [(5,)]


def test_group_by_validation(runner):
    with pytest.raises(AnalysisError, match="GROUP BY"):
        runner.execute(
            "select o_custkey, count(*) from orders group by o_orderkey")


def _mark_of(source_batch, build_batch, build_keys, source_keys, null_aware):
    bridge = JoinBridge()
    sink = JoinBuildSink(bridge, build_keys, build_batch.types, build_batch.names)
    sink.add_input(build_batch)
    sink.finish_input()
    op = SemiJoinOperator(bridge, source_keys, null_aware, None,
                          list(source_batch.names) + ["mark"],
                          list(source_batch.types) + [BOOLEAN])
    op.add_input(source_batch)
    out = op.get_output()
    mark = out.columns[-1]
    return mark.to_pylist()


def test_not_in_empty_set_with_null_probe():
    probe = ColumnBatch(["x"], [Column.from_values(BIGINT, [1, None, 3])])
    build = ColumnBatch(["y"], [Column.from_values(BIGINT, [])])
    # x IN (empty) is FALSE for every row, even NULL x
    assert _mark_of(probe, build, [0], [0], null_aware=True) == [False, False, False]


def test_not_in_with_build_null():
    probe = ColumnBatch(["x"], [Column.from_values(BIGINT, [1, 2, None])])
    build = ColumnBatch(["y"], [Column.from_values(BIGINT, [1, None])])
    # 1 IN (1, NULL) -> TRUE; 2 IN (1, NULL) -> UNKNOWN; NULL IN ... -> UNKNOWN
    assert _mark_of(probe, build, [0], [0], null_aware=True) == [True, None, None]


def test_distinct_count_null_storage_collision():
    # group has a NULL (storage fill 0) AND a genuine value 0: count(distinct)
    # must count the real 0 and ignore the NULL
    data = np.array([0, 0, 5], dtype=np.int64)
    valid = np.array([False, True, True])
    gidk = np.zeros(3, dtype=np.int64)
    perm, gid, n = K.group_ids([(gidk, None)])
    (res,) = K.grouped_reduce(perm, gid, n,
                              [("count", data, valid, np.int64, True)])
    assert list(res[0]) == [2]  # distinct {0, 5}


def test_any_value_skips_nulls():
    # group [7 (valid), NULL (storage fill 0)]: any_value must return 7
    data = np.array([7, 0], dtype=np.int64)
    valid = np.array([True, False])
    gidk = np.zeros(2, dtype=np.int64)
    perm, gid, n = K.group_ids([(gidk, None)])
    (res,) = K.grouped_reduce(perm, gid, n,
                              [("any_value", data, valid, np.int64, False)])
    vals, v = res
    assert list(vals) == [7] and list(v) == [True]


def test_correlated_count_in_expression(runner):
    # count wrapped in an expression: default value is the expression at
    # count=0, i.e. 0+1=1 for every order with no matching lineitem
    rows = runner.execute(
        "select count(*) from orders o where 1 = "
        "(select count(*) + 1 from lineitem l "
        " where l.l_orderkey = o.o_orderkey and l.l_quantity < 0)"
    ).rows()
    assert rows == [(15000,)]


def test_distributed_varchar_repartition():
    from trino_tpu.execution.distributed_runner import DistributedQueryRunner

    # count(distinct) forces a repartition keyed on a VARCHAR column; the
    # routing must hash string values, not per-producer dictionary codes
    sql = ("select n_name, count(distinct s_suppkey) from supplier, nation "
           "where s_nationkey = n_nationkey group by n_name")
    from trino_tpu.connectors.catalog import default_catalog

    cat = default_catalog(0.01)
    dist = DistributedQueryRunner(cat, worker_count=3)
    sa = StandaloneQueryRunner(cat)
    from trino_tpu.testing.oracle import assert_same_rows

    assert_same_rows(dist.execute(sql).rows(), sa.execute(sql).rows())


def test_transpile_fold_is_context_limited():
    assert "0.05" in transpile("x >= 0.06 - 0.01")
    assert "0.07" in transpile("x between 0.06 - 0.01 and 0.06 + 0.01")
    # precedence traps must NOT fold
    assert "1.0" not in transpile("select 0.5 + 0.5 * x from t")
    assert "0.1" not in transpile("select 1 - 0.5 - 0.4 from t")
