"""Streaming-path resilience fault matrix (ISSUE 5).

Deterministic drills over the error-classification + retry_policy=QUERY +
heartbeat-detection + worker-replacement machinery, driven by the existing
engine-level FailureInjector on the CPU mesh:

- classified PROCESS_EXIT mid-stage recovers under ``retry_policy="QUERY"``
  with bit-identical results and a logged worker replacement;
- USER-classified errors fail fast with ZERO retries, everywhere;
- an unreachable producer trips the exchange Backoff's
  ``max_failure_duration`` as a classified EXTERNAL error in bounded time;
- the failure detector walks ACTIVE -> UNRESPONSIVE -> GONE (drain and
  authoritative-death shortcuts included) and GONE is sticky;
- worker replacement honors ``Session.max_worker_replacements``.
"""

import os
import time

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.connectors.tpch_queries import QUERIES
from trino_tpu.execution import remote
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.execution.failure_detector import (
    ACTIVE,
    GONE,
    SHUTTING_DOWN,
    UNRESPONSIVE,
    NodeGoneError,
    WorkerFailureDetector,
)
from trino_tpu.execution.failure_injector import (
    PROCESS_EXIT,
    TASK_FAILURE,
    FailureInjector,
    InjectedFailure,
)
from trino_tpu.execution.remote import (
    HttpExchangeClient,
    ProcessDistributedQueryRunner,
    WorkerProcess,
)
from trino_tpu.runner import Session, StandaloneQueryRunner
from trino_tpu.spi.errors import (
    EXTERNAL,
    INSUFFICIENT_RESOURCES,
    INTERNAL,
    USER,
    Backoff,
    TrinoError,
    classify,
)
from trino_tpu.spi.memory import ExceededMemoryLimitError

CATALOG_SPEC = {
    "factory": "trino_tpu.connectors.catalog:default_catalog",
    "kwargs": {"scale_factor": 0.01},
}

_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}

DIV_BY_ZERO_SQL = \
    "select o_orderkey / (o_orderkey - o_orderkey) from orders"


# --------------------------------------------------------------- unit layer
def test_backoff_is_deterministic():
    """Delays are a pure function of the failure count (no jitter), the
    duration budget measures from the FIRST failure of a streak, and
    success() resets everything."""
    now = [0.0]
    b = Backoff(min_delay_s=0.1, max_delay_s=0.8,
                max_failure_duration_s=10.0, clock=lambda: now[0])
    assert b.delay_s == 0.0 and b.ready()
    assert b.failure() is False  # a single blip never trips the budget
    assert b.delay_s == pytest.approx(0.1)
    assert not b.ready()
    now[0] = 0.1
    assert b.ready()
    assert b.failure() is False
    assert b.delay_s == pytest.approx(0.2)
    b.failure()
    assert b.delay_s == pytest.approx(0.4)
    b.failure()
    assert b.delay_s == pytest.approx(0.8)
    b.failure()
    assert b.delay_s == pytest.approx(0.8)  # capped at max_delay
    now[0] = 10.0
    assert b.failure() is True  # budget exceeded: declare the peer failed
    b.success()
    assert b.failure_count == 0 and b.delay_s == 0.0 and b.ready()


@pytest.mark.parametrize("exc,expected_type,retryable", [
    (ExceededMemoryLimitError("pool", 1, 1), INSUFFICIENT_RESOURCES, True),
    (InjectedFailure("boom"), INTERNAL, True),
    (ConnectionError("refused"), EXTERNAL, True),
    (TimeoutError("late"), EXTERNAL, True),
    (RuntimeError("anything else"), INTERNAL, True),
])
def test_classification_table(exc, expected_type, retryable):
    te = classify(exc)
    assert te.error_type == expected_type
    assert te.is_retryable() is retryable


def test_classification_user_errors_never_retry():
    from trino_tpu.ops.expr import QueryError
    from trino_tpu.sql.analyzer import AnalysisError

    div = classify(QueryError("DIVISION_BY_ZERO: division by zero"))
    assert div.error_type == USER and not div.is_retryable()
    assert div.code.name == "DIVISION_BY_ZERO"
    bad = classify(AnalysisError("no such column"))
    assert bad.error_type == USER and not bad.is_retryable()


def test_classification_is_identity_on_trino_error():
    te = TrinoError(classify(ConnectionError("x")).code, "wrapped",
                    remote_host="http://w:1")
    assert classify(te) is te


# ---------------------------------------------------------- failure detector
def test_detector_state_machine():
    events = []
    det = WorkerFailureDetector(heartbeat_interval_s=0.0,
                                failure_threshold=2, events=events)
    mode = {"w": "ok"}

    def probe():
        m = mode["w"]
        if m == "ok":
            return {"state": "ACTIVE", "tasks": {}}
        if m == "drain":
            return {"state": "SHUTTING_DOWN", "tasks": {}}
        if m == "dead":
            raise NodeGoneError("process exited rc=17")
        raise ConnectionError("refused")

    det.monitor("w", probe)
    det.sweep_once()
    assert det.state_of("w") == ACTIVE and det.active() == ["w"]

    # one miss: UNRESPONSIVE, excluded from placement, tasks not yet lost
    mode["w"] = "fail"
    det.sweep_once()
    assert det.state_of("w") == UNRESPONSIVE and det.active() == []
    # recovery before the threshold resets the miss counter
    mode["w"] = "ok"
    det.sweep_once()
    assert det.state_of("w") == ACTIVE

    # threshold consecutive misses: GONE, and GONE is sticky
    mode["w"] = "fail"
    det.sweep_once()
    det.sweep_once()
    assert det.state_of("w") == GONE and det.gone() == ["w"]
    mode["w"] = "ok"
    det.sweep_once()
    assert det.state_of("w") == GONE  # terminal for this incarnation

    transitions = [e for e in events if e[0] == "heartbeat"]
    assert [(e[2], e[3]) for e in transitions] == [
        (ACTIVE, UNRESPONSIVE), (UNRESPONSIVE, ACTIVE),
        (ACTIVE, UNRESPONSIVE), (UNRESPONSIVE, GONE)]
    assert det.transitions == 4


def test_detector_drain_and_authoritative_death():
    det = WorkerFailureDetector(failure_threshold=3)
    det.monitor("draining", lambda: {"state": "SHUTTING_DOWN", "tasks": {}})

    def dead_probe():
        raise NodeGoneError("process exited rc=17")

    det.monitor("dead", dead_probe)
    det.sweep_once()
    # draining: responsive but gets no new tasks
    assert det.state_of("draining") == SHUTTING_DOWN
    assert det.active() == []
    # authoritative death skips the miss-counting path entirely
    assert det.state_of("dead") == GONE
    assert "exited" in det.last_error("dead")


# ----------------------------------------------------------- exchange client
def test_unreachable_producer_trips_backoff_in_bounded_time():
    """An unreachable producer surfaces as a classified EXTERNAL failure
    once failures persist past max_failure_duration — not a silent stall
    until the 600 s query deadline."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()  # nothing listens here now
    client = HttpExchangeClient(
        [f"http://127.0.0.1:{port}/v1/task/ghost"], 0,
        backoff={"min_delay_s": 0.01, "max_delay_s": 0.05,
                 "max_failure_duration_s": 0.3})
    t0 = time.monotonic()
    with pytest.raises(TrinoError) as ei:
        while time.monotonic() - t0 < 30.0:
            client.poll(timeout=0.0)
            time.sleep(0.005)
    elapsed = time.monotonic() - t0
    assert elapsed < 5.0, f"backoff trip took {elapsed:.1f}s"
    assert ei.value.code.name == "PAGE_TRANSPORT_TIMEOUT"
    assert ei.value.error_type == EXTERNAL
    assert ei.value.remote_host == f"http://127.0.0.1:{port}"
    assert client.stats["fetch_failures"] >= 2
    assert client.stats["backoff_trips"] == 1
    assert client.stats["backoff_skips"] >= 1  # delay gate actually closed


def test_fetch_honors_caller_poll_timeout(monkeypatch):
    """A non-blocking poll must NOT be silently promoted to a 5 s long-poll
    (the old ``timeout=max(timeout, 5.0)``); the requested wait travels to
    the server as ?maxwait= and the socket timeout only adds grace."""
    captured = []

    class FakeResp:
        status = 200
        headers = {"X-Next-Token": "0", "X-Done": "1"}

        def read(self):
            return b""

        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    def fake_http(method, url, data=None, timeout=30.0, headers=None):
        captured.append((url, timeout))
        return FakeResp()

    monkeypatch.setattr(remote, "_http", fake_http)
    HttpExchangeClient(["http://w/v1/task/t"], 0).poll(timeout=0.0)
    url, timeout = captured[0]
    assert "maxwait=0" in url
    assert timeout < 5.5  # grace only, not a hidden long-poll floor
    HttpExchangeClient(["http://w/v1/task/t"], 0).poll(timeout=3.0)
    url, timeout = captured[1]
    assert "maxwait=3" in url
    assert timeout == pytest.approx(8.0)  # asked-for long-poll + grace


# ------------------------------------------------------- in-process QUERY
def test_query_retry_in_process_recovers_task_failure():
    sql = ("select o_orderstatus, count(*) from orders "
           "group by o_orderstatus order by o_orderstatus")
    expected = StandaloneQueryRunner(
        default_catalog(scale_factor=0.01)).execute(sql).rows()
    inj = FailureInjector()
    inj.inject(TASK_FAILURE, fragment_id=None, task_index=0, attempt=0,
               times=1)
    r = DistributedQueryRunner(
        worker_count=2,
        session=Session(node_count=2, retry_policy="QUERY",
                        failure_injector=inj, retry_initial_delay_s=0.01))
    assert r.execute(sql).rows() == expected
    assert r.resilience.query_retries == 1
    assert [e[0] for e in r.resilience_events] == ["query_retry"]


def test_query_retry_exhausts_attempt_budget():
    inj = FailureInjector()
    # injected failure on EVERY attempt: 1 initial + 2 retries, then raise
    inj.inject(TASK_FAILURE, fragment_id=None, task_index=0, attempt=None,
               times=100)
    r = DistributedQueryRunner(
        worker_count=2,
        session=Session(node_count=2, retry_policy="QUERY",
                        query_retry_attempts=2, failure_injector=inj,
                        retry_initial_delay_s=0.01))
    with pytest.raises(InjectedFailure):
        r.execute("select count(*) from nation")
    assert r.resilience.query_retries == 2


def test_user_error_fails_fast_in_process():
    r = DistributedQueryRunner(
        worker_count=2, session=Session(node_count=2, retry_policy="QUERY"))
    t0 = time.monotonic()
    with pytest.raises(Exception, match="DIVISION_BY_ZERO"):
        r.execute(DIV_BY_ZERO_SQL)
    assert time.monotonic() - t0 < 5.0
    assert r.resilience.query_retries == 0
    assert r.resilience_events == []


def test_fte_fails_fast_on_user_error():
    """The FTE retry chain also consults classification: a USER error gets
    NO retry attempts (re-running re-runs the same bug)."""
    from trino_tpu.execution.fte import TaskFailure

    r = DistributedQueryRunner(
        worker_count=2,
        session=Session(node_count=2, retry_policy="TASK",
                        task_retry_attempts=5))
    t0 = time.monotonic()
    with pytest.raises(TaskFailure, match="after 1 attempts"):
        r.execute(DIV_BY_ZERO_SQL)
    assert time.monotonic() - t0 < 5.0


def test_resilience_session_knobs_settable():
    r = DistributedQueryRunner(worker_count=1, session=Session())
    r.execute("set session query_retry_attempts = 5")
    assert r.session.query_retry_attempts == 5
    r.execute("set session retry_policy = 'QUERY'")
    assert r.session.retry_policy == "QUERY"
    with pytest.raises(KeyError):
        r.execute("set session failure_injector = 1")


# ------------------------------------------------------------ process layer
def test_worker_boot_failure_raises_with_stderr():
    """A worker that dies before printing LISTENING surfaces as a bounded
    RuntimeError carrying its stderr — not an eternal readline() hang."""
    env = dict(_ENV)
    env["TRINO_TPU_TEST_BOOT_FAIL"] = "1"
    t0 = time.monotonic()
    with pytest.raises(RuntimeError) as ei:
        WorkerProcess(env_overrides=env, boot_timeout_s=60.0)
    assert time.monotonic() - t0 < 60.0
    msg = str(ei.value)
    assert "failed to boot" in msg
    assert "TRINO_TPU_TEST_BOOT_FAIL" in msg  # the captured stderr


def test_worker_status_endpoint_reports_all_tasks():
    """GET /v1/status returns node state + EVERY task's classified state in
    one payload — the one-poll-per-worker sweep's data source."""
    import json
    import urllib.request

    w = WorkerProcess(env_overrides=_ENV)
    try:
        with urllib.request.urlopen(f"{w.url}/v1/status",
                                    timeout=10) as resp:
            st = json.loads(resp.read())
        assert st["state"] == "ACTIVE"
        assert st["tasks"] == {}
    finally:
        w.kill()


def test_streaming_process_exit_recovers_bit_identical():
    """THE acceptance drill: PROCESS_EXIT kills a worker mid-stage in
    STREAMING mode; retry_policy=QUERY blacklists it, replaces it, re-runs,
    and the rows are bit-identical to a fault-free run — with the
    replacement in the event log."""
    sql = QUERIES[3]
    expected = StandaloneQueryRunner(
        default_catalog(scale_factor=0.01)).execute(sql).rows()
    inj = FailureInjector()
    r = ProcessDistributedQueryRunner(
        CATALOG_SPEC, worker_count=2,
        session=Session(node_count=2, retry_policy="QUERY",
                        failure_injector=inj, retry_initial_delay_s=0.05,
                        heartbeat_interval_s=0.2),
        env_overrides=_ENV)
    try:
        leaf = r.create_subplan(sql).all_fragments()[0]
        inj.inject(PROCESS_EXIT, fragment_id=leaf.id, task_index=0,
                   attempt=0)
        rows = r.execute(sql).rows()
        assert rows == expected  # bit-identical, order included
        kinds = [e[0] for e in r.resilience_events]
        assert "worker_replaced" in kinds
        assert "blacklist" in kinds
        assert "query_retry" in kinds
        assert r.resilience.query_retries >= 1
        assert r.resilience.worker_replacements == 1
        assert r.resilience.heartbeat_transitions >= 1
        # capacity self-healed: both slots live again
        assert [w.alive() for w in r.workers].count(True) == 2
    finally:
        r.close()


def test_streaming_user_error_fails_fast_across_processes():
    """The same drill with a USER-classified error: < 5 s, ZERO retries —
    the worker's error_type survives the wire."""
    r = ProcessDistributedQueryRunner(
        CATALOG_SPEC, worker_count=1,
        session=Session(node_count=1, retry_policy="QUERY",
                        heartbeat_interval_s=0.2),
        env_overrides=_ENV)
    try:
        t0 = time.monotonic()
        with pytest.raises(Exception, match="DIVISION_BY_ZERO"):
            r.execute(DIV_BY_ZERO_SQL)
        assert time.monotonic() - t0 < 5.0
        assert r.resilience.query_retries == 0
        assert not [e for e in r.resilience_events
                    if e[0] in ("query_retry", "blacklist")]
    finally:
        r.close()


def test_worker_replacement_cap_honored():
    """max_worker_replacements=0: the dead worker is NOT respawned; the
    retry still succeeds on the survivor and the cap refusal is logged."""
    inj = FailureInjector()
    r = ProcessDistributedQueryRunner(
        CATALOG_SPEC, worker_count=2,
        session=Session(node_count=2, retry_policy="QUERY",
                        failure_injector=inj, retry_initial_delay_s=0.05,
                        heartbeat_interval_s=0.2,
                        max_worker_replacements=0),
        env_overrides=_ENV)
    try:
        leaf = r.create_subplan(
            "select count(*) from orders").all_fragments()[0]
        inj.inject(PROCESS_EXIT, fragment_id=leaf.id, task_index=0,
                   attempt=0)
        rows = r.execute("select count(*) from orders").rows()
        assert rows == [(15000,)]
        kinds = [e[0] for e in r.resilience_events]
        assert "worker_replaced" not in kinds
        assert "replacement_cap" in kinds
        assert r.resilience.worker_replacements == 0
        assert [w.alive() for w in r.workers].count(True) == 1
    finally:
        r.close()


# ----------------------------- satellite: fleet-shared durable blacklist
def test_shared_blacklist_two_writers_merge_and_ttl(tmp_path):
    """Two coordinators pointing TRINO_TPU_BLACKLIST_PATH at one file:
    strikes recorded under A are visible (and additive) under B — no
    last-writer-wins clobbering — and TTL decay applies fleet-wide."""
    from trino_tpu.execution.speculation import ClusterBlacklist

    shared = str(tmp_path / "blacklist.jsonl")
    a = ClusterBlacklist(ttl_s=3600.0, threshold=2.0, persist=True,
                         path=shared)
    b = ClusterBlacklist(ttl_s=3600.0, threshold=2.0, persist=True,
                         path=shared)

    a.record_failure("worker-1", reason="REMOTE_HOST_GONE", query_id="qa")
    assert a.score("worker-1") == 1.0
    assert b.score("worker-1") == 1.0, "A's strike must merge into B"
    assert not b.is_blacklisted("worker-1")
    # the second strike comes from the OTHER coordinator: the scores fold
    b.record_failure("worker-1", reason="REMOTE_TASK_ERROR", query_id="qb")
    assert b.is_blacklisted("worker-1")
    assert a.is_blacklisted("worker-1"), \
        "the blacklisting must be cluster-wide, not per-coordinator"
    # no double counting of a writer's own appends
    assert a.score("worker-1") == 2.0
    assert b.score("worker-1") == 2.0

    # a third coordinator booting later merges the whole history on load
    c = ClusterBlacklist(ttl_s=3600.0, threshold=2.0, persist=True,
                         path=shared)
    assert c.is_blacklisted("worker-1")

    # TTL decay: to a tiny-TTL member every recorded strike is expired
    tiny = ClusterBlacklist(ttl_s=1e-9, threshold=2.0, persist=True,
                            path=shared)
    import time as _t
    _t.sleep(0.01)
    assert tiny.score("worker-1") == 0.0


def test_shared_blacklist_survives_interleaved_subprocess_writers(tmp_path):
    """Cross-process: two real subprocesses interleave O_APPEND strikes
    into the same file; a fresh reader folds every record."""
    import subprocess
    import sys

    shared = str(tmp_path / "bl.jsonl")
    child = (
        "import sys\n"
        "from trino_tpu.execution.resilience import SharedBlacklistStore\n"
        "s = SharedBlacklistStore(sys.argv[1])\n"
        "for i in range(50):\n"
        "    s.append('worker-x', 1.0, 'REMOTE_TASK_ERROR', sys.argv[2])\n"
    )
    procs = [subprocess.run([sys.executable, "-c", child, shared, tag],
                            cwd=os.path.dirname(os.path.dirname(
                                os.path.abspath(__file__))),
                            capture_output=True, text=True, timeout=300)
             for tag in ("qa", "qb")]
    for p in procs:
        assert p.returncode == 0, p.stderr[-2000:]

    from trino_tpu.execution.resilience import SharedBlacklistStore
    recs = SharedBlacklistStore(shared).poll()
    assert len(recs) == 100, "no torn or clobbered records"
    assert {r["query_id"] for r in recs} == {"qa", "qb"}
