"""History-based optimization: fingerprint invariances, journal round
trip, second-run planning, fan-out shrink, plan-cache epoch keying, and
the iterative-vs-legacy TPC-H row-identity oracle (reference: Trino's
HBO design — io.trino.cost.HistoryBasedPlanStatisticsCalculator — and
AbstractTestQueryFramework.assertQuery)."""

import os

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.connectors.tpch_queries import QUERIES
from trino_tpu.planner import history
from trino_tpu.planner.plan import Filter, Join, Project, TableScan
from trino_tpu.runner import Session, StandaloneQueryRunner
from trino_tpu.sql.ir import Call, InputRef, Literal
from trino_tpu.spi.types import BIGINT, BOOLEAN
from trino_tpu.telemetry import journal
from trino_tpu.testing.oracle import assert_same_rows

pytestmark = pytest.mark.filterwarnings("ignore::DeprecationWarning")


def _reset_planning_caches():
    """Plan + result tiers and the history table: everything keyed on
    journal state.  Jitted-program memos stay warm — recompiling every
    kernel per test would dominate the suite's wall clock."""
    from trino_tpu.caching import plan_cache, result_cache

    plan_cache.reset_for_test()
    result_cache.reset_for_test()
    history.reset_for_test()


@pytest.fixture
def journal_env(tmp_path, monkeypatch):
    """Isolated journal + HBO on; every cache that could leak state
    across tests is reset on the way in AND out."""
    monkeypatch.setenv("TRINO_TPU_JOURNAL_DIR", str(tmp_path / "journal"))
    monkeypatch.setenv("TRINO_TPU_HBO", "1")
    journal.reset_for_test()
    _reset_planning_caches()
    yield
    journal.reset_for_test()
    _reset_planning_caches()


# ------------------------------------------------------- fingerprints


def _scan(table="nation", cols=("a", "b")):
    return TableScan(cols, (BIGINT,) * len(cols), catalog="tpch",
                     table=table, columns=tuple("c_" + c for c in cols))


def _gt(ch, lit):
    return Call(BOOLEAN, "gt", (InputRef(BIGINT, ch), Literal(BIGINT, lit)))


def test_fingerprint_ignores_inner_join_side_order():
    l, r = _scan("customer"), _scan("orders", cols=("x", "y"))
    ab = Join(l.output_names + r.output_names, (BIGINT,) * 4,
              l, r, "INNER", (0,), (0,), None)
    ba = Join(r.output_names + l.output_names, (BIGINT,) * 4,
              r, l, "INNER", (0,), (0,), None)
    assert history.logical_fingerprint(ab) == history.logical_fingerprint(ba)
    # an outer join is NOT side-symmetric
    lab = Join(ab.output_names, ab.output_types, l, r, "LEFT",
               (0,), (0,), None)
    lba = Join(ba.output_names, ba.output_types, r, l, "LEFT",
               (0,), (0,), None)
    assert (history.logical_fingerprint(lab)
            != history.logical_fingerprint(lba))


def test_fingerprint_ignores_distribution_and_projections():
    l, r = _scan("customer"), _scan("orders", cols=("x", "y"))
    j = Join(l.output_names + r.output_names, (BIGINT,) * 4,
             l, r, "INNER", (0,), (0,), None, distribution="BROADCAST")
    from dataclasses import replace
    assert (history.logical_fingerprint(j) ==
            history.logical_fingerprint(
                replace(j, distribution="PARTITIONED")))
    ident = Project(j.output_names, j.output_types, j,
                    tuple(InputRef(BIGINT, i) for i in range(4)))
    assert (history.logical_fingerprint(ident)
            == history.logical_fingerprint(j))


def test_fingerprint_is_channel_remap_stable():
    """The same named predicate fingerprints identically whether it sits
    on the scan or above a channel-shuffling projection."""
    s = _scan()
    direct = Filter(s.output_names, s.output_types, s, _gt(0, 5))
    swapped = Project(("b", "a"), (BIGINT, BIGINT), s,
                      (InputRef(BIGINT, 1), InputRef(BIGINT, 0)))
    remapped = Filter(swapped.output_names, swapped.output_types,
                      swapped, _gt(1, 5))  # channel 1 is still column "a"
    assert (history.logical_fingerprint(direct)
            == history.logical_fingerprint(remapped))


def test_fingerprint_sorts_conjuncts():
    s = _scan()
    p12 = Call(BOOLEAN, "$and", (_gt(0, 1), _gt(1, 2)))
    p21 = Call(BOOLEAN, "$and", (_gt(1, 2), _gt(0, 1)))
    f12 = Filter(s.output_names, s.output_types, s, p12)
    f21 = Filter(s.output_names, s.output_types, s, p21)
    assert (history.logical_fingerprint(f12)
            == history.logical_fingerprint(f21))
    # different constants are different plans
    other = Filter(s.output_names, s.output_types, s, _gt(0, 99))
    assert (history.logical_fingerprint(f12)
            != history.logical_fingerprint(other))


# ------------------------------------------------- journal round trip


def test_provider_round_trips_through_journal(journal_env):
    j = journal.get_journal()
    j.plan_stats("q1", "sqlfp", {"fp_a": {"rows": 1000, "bytes": 5000}},
                 ts=1.0)
    j.plan_stats("q2", "sqlfp", {"fp_a": {"rows": 2000},
                                 "fp_b": {"groups": 7}}, ts=2.0)
    history.reset_for_test()
    provider = history.provider_if_enabled()
    assert provider is not None
    st = provider.table["fp_a"]
    assert st.rows == 2000      # newest record wins
    assert st.bytes == 5000     # fields merge, not clobber
    assert provider.table["fp_b"].groups == 7
    assert history.history_epoch() != ""


def test_hbo_off_disables_provider_and_epoch(journal_env, monkeypatch):
    journal.get_journal().plan_stats("q1", "f", {"fp": {"rows": 5}}, ts=1.0)
    history.reset_for_test()
    assert history.provider_if_enabled() is not None
    monkeypatch.setenv("TRINO_TPU_HBO", "0")
    assert history.provider_if_enabled() is None
    assert history.history_epoch() == ""


def test_history_epoch_tracks_recorded_stats(journal_env):
    assert history.history_epoch() == ""  # no observations yet
    journal.get_journal().plan_stats("q1", "f", {"fp": {"rows": 5}}, ts=1.0)
    history.reset_for_test()
    e1 = history.history_epoch()
    assert e1 != ""
    journal.get_journal().plan_stats("q2", "f", {"fp": {"rows": 9}}, ts=2.0)
    history.reset_for_test()
    e2 = history.history_epoch()
    assert e2 not in ("", e1)


def test_plan_cache_key_includes_history_epoch(journal_env):
    from trino_tpu.caching.plan_cache import _key

    catalog = default_catalog(scale_factor=0.01)
    session = Session()
    k1 = _key("select 1", session, catalog, "plan")
    journal.get_journal().plan_stats("q1", "f", {"fp": {"rows": 5}}, ts=1.0)
    history.reset_for_test()
    k2 = _key("select 1", session, catalog, "plan")
    assert k1 != k2  # stale history must not serve a cached plan


# ------------------------------------------- second-run planning (e2e)


_WRONG_SQL = """
select c.c_mktsegment, count(*) n
from customer c
join (select o_custkey from orders
      where o_orderkey > -1 and o_orderkey > -2
        and o_orderkey > -3 and o_orderkey > -4) o
  on c.c_custkey = o.o_custkey
group by c.c_mktsegment order by c.c_mktsegment
"""


def _walk(node):
    yield node
    for c in node.children:
        yield from _walk(c)


def _join_plan(runner, sql):
    """(distribution, base tables feeding the build side) of the sole
    join, following remote exchanges."""
    from trino_tpu.planner.plan import RemoteSource

    frags = runner.create_subplan(sql).all_fragments()
    by_id = {f.id: f for f in frags}
    join = next(n for f in frags for n in _walk(f.root)
                if isinstance(n, Join))

    def tables(node, seen):
        out = set()
        for n in _walk(node):
            if isinstance(n, TableScan):
                out.add(n.table)
            elif isinstance(n, RemoteSource) and n.fragment_id not in seen:
                seen.add(n.fragment_id)
                out |= tables(by_id[n.fragment_id].root, seen)
        return out

    return join.distribution, sorted(tables(join.right, set()))


def _fresh_distributed(workers=2, sf=0.02):
    from trino_tpu.execution.distributed_runner import DistributedQueryRunner

    _reset_planning_caches()
    return DistributedQueryRunner(
        default_catalog(scale_factor=sf), worker_count=workers,
        session=Session(node_count=workers, adaptive="0"))


def test_second_run_plans_correct_build_side(journal_env, monkeypatch):
    """The BENCH_r13 mis-estimate in miniature: run 1 broadcasts the big
    orders side off a 0.4^4 selectivity underestimate; after its observed
    stats land in the journal, a fresh runner must NOT plan orders as a
    broadcast build — and rows stay identical."""
    monkeypatch.setenv("TRINO_TPU_BROADCAST_ROW_LIMIT", "1000")

    r1 = _fresh_distributed()
    dist1, build1 = _join_plan(r1, _WRONG_SQL)
    assert (dist1, build1) == ("BROADCAST", ["orders"])  # the wrong plan
    rows1 = r1.execute(_WRONG_SQL).rows()

    r2 = _fresh_distributed()
    dist2, build2 = _join_plan(r2, _WRONG_SQL)
    assert not (dist2 == "BROADCAST" and "orders" in build2), \
        f"history did not fix the build side: {dist2} {build2}"
    rows2 = r2.execute(_WRONG_SQL).rows()
    assert rows1 == rows2

    # HBO=0 must reproduce the static (history-free) plan bit-for-bit
    monkeypatch.setenv("TRINO_TPU_HBO", "0")
    r3 = _fresh_distributed()
    assert _join_plan(r3, _WRONG_SQL) == (dist1, build1)
    assert r3.execute(_WRONG_SQL).rows() == rows1


def test_history_shrinks_task_fanout(journal_env, monkeypatch):
    """A HASH stage whose observed input is far below
    TRINO_TPU_HBO_ROWS_PER_TASK gets its task count shrunk on the next
    run, and the decision is tagged on the query record."""
    from trino_tpu.telemetry import runtime as rt

    # keep every producer -> consumer seam on real sink buffers: fused
    # and collective edges bypass the counters the recorder reads, so
    # the scan stage's row count would never land in the journal
    monkeypatch.setenv("TRINO_TPU_FUSED_STAGE", "0")
    from trino_tpu.execution.distributed_runner import DistributedQueryRunner

    def fresh():
        _reset_planning_caches()
        return DistributedQueryRunner(
            default_catalog(scale_factor=0.01), worker_count=2,
            session=Session(node_count=2, adaptive="0",
                            use_collectives=False))

    sql = ("select o_custkey, count(*) c from orders "
           "group by o_custkey order by o_custkey limit 5")
    r1 = fresh()
    rows1 = r1.execute(sql).rows()

    r2 = fresh()
    rows2 = r2.execute(sql).rows()
    assert rows1 == rows2
    assert "hbo_fanout" in rt.queries()[-1].adaptive_decisions


# --------------------------------- iterative vs legacy row identity


_ORDERED = {1, 2, 3, 5, 7, 8, 9, 10, 11, 12, 13, 14, 16, 18, 21, 22}


@pytest.fixture(scope="module")
def oracle_catalog():
    return default_catalog(scale_factor=0.01)


def _mode_rows(catalog, sql, mode, monkeypatch):
    """Plan-cache keys include TRINO_TPU_OPTIMIZER, so modes can't serve
    each other's plans; only the result tier must not short-circuit the
    second leg (jitted-program memos stay warm — they are mode-blind)."""
    from trino_tpu.caching import result_cache

    monkeypatch.setenv("TRINO_TPU_OPTIMIZER", mode)
    monkeypatch.setenv("TRINO_TPU_HBO", "0")
    with result_cache.disabled():
        return StandaloneQueryRunner(catalog).execute(sql).rows()


def _mode_plan(catalog, sql, mode, monkeypatch):
    """create_plan plans fresh every call (the plan-cache tier sits in
    execute()), so no cache bypass is needed here."""
    monkeypatch.setenv("TRINO_TPU_OPTIMIZER", mode)
    monkeypatch.setenv("TRINO_TPU_HBO", "0")
    return StandaloneQueryRunner(catalog).create_plan(sql)


@pytest.mark.parametrize("q", sorted(QUERIES))
def test_iterative_matches_legacy_tpch(q, oracle_catalog, monkeypatch):
    """Row-identity oracle: every TPC-H query planned by the iterative
    engine returns exactly what the legacy pipeline returns.

    When both optimizers converge on the *same* optimized plan (13 of 22
    queries at this writing), executing it twice proves nothing plan
    equality doesn't already prove — and test_queries runs every query
    end-to-end under the iterative default.  Rows are compared only for
    the queries whose plans genuinely diverge; this also keeps ~26
    redundant TPC-H executions (and their jitted programs) out of the
    tier-1 suite."""
    legacy_plan = _mode_plan(oracle_catalog, QUERIES[q], "legacy",
                             monkeypatch)
    iterative_plan = _mode_plan(oracle_catalog, QUERIES[q], "iterative",
                                monkeypatch)
    if legacy_plan == iterative_plan:
        return
    legacy = _mode_rows(oracle_catalog, QUERIES[q], "legacy", monkeypatch)
    iterative = _mode_rows(oracle_catalog, QUERIES[q], "iterative",
                           monkeypatch)
    assert_same_rows(iterative, legacy, ordered=q in _ORDERED)
