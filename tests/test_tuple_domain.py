"""TupleDomain predicate model + pushdown (reference:
spi/predicate/TupleDomain.java:56, Domain.java:41, DomainTranslator,
PushPredicateIntoTableScan)."""

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.runner import Session, StandaloneQueryRunner
from trino_tpu.spi.predicate import Domain, Range, TupleDomain, ValueSet


# ---------------------------------------------------------------- algebra
def test_valueset_points_and_ranges():
    vs = ValueSet.of([3, 1, 2, 2])
    assert vs.points() == [1, 2, 3]
    assert vs.contains_value(2) and not vs.contains_value(4)
    r = ValueSet((Range(5, True, 10, False),))
    assert r.contains_value(5) and r.contains_value(9)
    assert not r.contains_value(10) and not r.contains_value(4)


def test_valueset_intersect_union():
    a = ValueSet((Range(0, True, 10, True),))
    b = ValueSet((Range(5, True, 20, True),))
    i = a.intersect(b)
    assert i.contains_value(5) and i.contains_value(10)
    assert not i.contains_value(4) and not i.contains_value(11)
    u = a.union(b)
    assert u.contains_value(0) and u.contains_value(20)


def test_domain_null_handling():
    d = Domain(ValueSet.of([1]), null_allowed=True)
    assert d.contains_value(None) and d.contains_value(1)
    assert not d.contains_value(2)
    n = d.intersect(Domain(ValueSet.all(), False))
    assert not n.contains_value(None)


def test_tuple_domain_intersect_to_none():
    a = TupleDomain({"x": Domain.single_value(1)})
    b = TupleDomain({"x": Domain.single_value(2)})
    assert a.intersect(b).is_none


def test_overlaps_stats():
    td = TupleDomain({"x": Domain(
        ValueSet((Range(100, True, None, False),)), False)})
    assert not td.overlaps_stats({"x": 0}, {"x": 50})
    assert td.overlaps_stats({"x": 0}, {"x": 150})
    # all-NULL batch against a NOT NULL domain
    assert not td.overlaps_stats({"x": None}, {"x": None})


# ------------------------------------------------------------- extraction
def test_extract_from_predicate():
    from trino_tpu.planner.domains import extract_tuple_domain
    from trino_tpu.spi.types import BIGINT, BOOLEAN
    from trino_tpu.sql.ir import Call, InputRef, Literal

    x = InputRef(BIGINT, 0)
    pred = Call(BOOLEAN, "$and", (
        Call(BOOLEAN, "ge", (x, Literal(BIGINT, 10))),
        Call(BOOLEAN, "lt", (x, Literal(BIGINT, 20))),
        Call(BOOLEAN, "$in", (InputRef(BIGINT, 1), Literal(BIGINT, 1),
                              Literal(BIGINT, 2))),
    ))
    td = extract_tuple_domain(pred, {0: "x", 1: "y"})
    assert td.domain("x").contains_value(10)
    assert not td.domain("x").contains_value(20)
    assert td.domain("y").values.points() == [1, 2]
    assert td.domain("z").is_all


# ---------------------------------------------------------------- engine
@pytest.fixture(scope="module")
def harness():
    cat = default_catalog(scale_factor=0.01)
    runner = StandaloneQueryRunner(cat, session=Session(
        default_catalog="memory"))
    # many small batches so zone-map pruning is observable
    runner.execute("create table zd (k bigint, s varchar)")
    for i in range(8):
        runner.execute(
            f"insert into zd values ({i * 10}, 'v{i}'), ({i * 10 + 5}, 'w{i}')")
    return runner, cat.connector("memory")


def test_scan_constraint_attached(harness):
    runner, _ = harness
    txt = runner.execute("explain select * from zd where k >= 70").rows()
    plan = "\n".join(r[0] for r in txt)
    assert "constraint=['k']" in plan


def test_batch_pruning_and_correctness(harness):
    runner, mem = harness
    before = mem.batches_pruned
    assert runner.execute(
        "select k from zd where k >= 70 order by k").rows() == [(70,), (75,)]
    assert mem.batches_pruned > before  # zone maps skipped low batches


def test_string_domain_correctness(harness):
    runner, _ = harness
    assert runner.execute(
        "select k from zd where s = 'v3'").rows() == [(30,)]
    assert runner.execute(
        "select k from zd where s in ('w0', 'v7') order by k").rows() == [
        (5,), (70,)]


def test_or_domain(harness):
    runner, _ = harness
    assert runner.execute(
        "select k from zd where k = 5 or k = 75 order by k").rows() == [
        (5,), (75,)]


def test_null_comparisons_unchanged(harness):
    runner, _ = harness
    runner.execute("create table zn (k bigint)")
    runner.execute("insert into zn values (1), (null), (3)")
    assert runner.execute(
        "select k from zn where k > 1").rows() == [(3,)]
    assert runner.execute(
        "select count(*) from zn where k is null").rows() == [(1,)]
