"""Fault-tolerant execution: spooled stage-by-stage scheduling + task retry
with fault injection (reference: EventDrivenFaultTolerantQueryScheduler,
spi/exchange ExchangeManager spooling)."""

import threading

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.connectors.tpch_queries import QUERIES
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.execution.fte import TaskFailure
from trino_tpu.runner import Session
from trino_tpu.testing.oracle import SqliteOracle, assert_same_rows

TABLES = ["nation", "region", "customer", "orders", "lineitem", "supplier"]


class FlakyConnector:
    """Delegates to a real connector but fails page-source creation the
    first ``failures`` times (simulating worker/task crashes).  Pure
    delegation wrapper (not a Connector subclass: inherited default methods
    would shadow __getattr__)."""

    name = "tpch"

    def __init__(self, inner, failures: int):
        self._inner = inner
        self._remaining = failures
        self._lock = threading.Lock()
        self.injected = 0

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def create_page_source(self, split, columns):
        with self._lock:
            if self._remaining > 0:
                self._remaining -= 1
                self.injected += 1
                raise RuntimeError("injected task failure")
        return self._inner.create_page_source(split, columns)


def _flaky_catalog(failures: int):
    catalog = default_catalog(scale_factor=0.01)
    flaky = FlakyConnector(catalog.connector("tpch"), failures)
    catalog.register("tpch", flaky)
    return catalog, flaky


@pytest.fixture(scope="module")
def oracle():
    catalog = default_catalog(scale_factor=0.01)
    orc = SqliteOracle()
    conn = catalog.connector("tpch")
    for t in TABLES:
        schema = conn.get_table_schema(t)
        cols = schema.column_names()
        batches = []
        for s in conn.get_splits(t, 2, 1):
            src = conn.create_page_source(s, cols)
            while not src.is_finished():
                b = src.get_next_batch()
                if b is not None:
                    batches.append(b)
        orc.load_table(t, batches)
    return orc


def test_fte_matches_streaming(oracle):
    catalog = default_catalog(scale_factor=0.01)
    fte = DistributedQueryRunner(
        catalog, worker_count=3,
        session=Session(node_count=3, retry_policy="TASK"))
    for q in (1, 3, 6):
        assert_same_rows(fte.execute(QUERIES[q]).rows(),
                         oracle.query(QUERIES[q]), ordered=q in (1, 3))


def test_fte_survives_injected_failures(oracle):
    catalog, flaky = _flaky_catalog(failures=3)
    fte = DistributedQueryRunner(
        catalog, worker_count=3,
        session=Session(node_count=3, retry_policy="TASK",
                        task_retry_attempts=3))
    sql = ("select l_returnflag, count(*), sum(l_quantity) from lineitem "
           "group by l_returnflag")
    assert_same_rows(fte.execute(sql).rows(), oracle.query(sql))
    assert flaky.injected == 3  # the failures actually happened


def test_streaming_scheduler_dies_without_retry(oracle):
    catalog, _ = _flaky_catalog(failures=1)
    streaming = DistributedQueryRunner(
        catalog, worker_count=3, session=Session(node_count=3))
    with pytest.raises(RuntimeError, match="injected"):
        streaming.execute("select count(*) from lineitem")


def test_fte_gives_up_after_attempts(oracle):
    catalog, _ = _flaky_catalog(failures=1000)
    fte = DistributedQueryRunner(
        catalog, worker_count=2,
        session=Session(node_count=2, retry_policy="TASK",
                        task_retry_attempts=1))
    with pytest.raises(TaskFailure, match="failed after"):
        fte.execute("select count(*) from lineitem")


def test_fte_with_serde_and_joins(oracle):
    catalog = default_catalog(scale_factor=0.01)
    fte = DistributedQueryRunner(
        catalog, worker_count=3,
        session=Session(node_count=3, retry_policy="TASK",
                        exchange_serde=True))
    sql = ("select c_mktsegment, count(*) from customer, orders "
           "where c_custkey = o_custkey group by c_mktsegment")
    assert_same_rows(fte.execute(sql).rows(), oracle.query(sql))


def test_engine_failure_injector_task_and_reads():
    """Engine-level FailureInjector (execution/failure_injector.py —
    FailureInjector.java:35): injected task-body and spool-read failures
    are retried against the durable on-disk spool and the query still
    answers correctly."""
    from trino_tpu.execution.failure_injector import (
        GET_RESULTS_FAILURE,
        TASK_FAILURE,
        FailureInjector,
    )
    from trino_tpu.runner import StandaloneQueryRunner

    catalog = default_catalog(scale_factor=0.01)
    inj = FailureInjector()
    inj.inject(TASK_FAILURE, task_index=0, attempt=0, times=2)
    inj.inject(GET_RESULTS_FAILURE, task_index=1, attempt=0, times=2)
    dist = DistributedQueryRunner(
        catalog, worker_count=3,
        session=Session(node_count=3, retry_policy="TASK",
                        failure_injector=inj))
    sql = QUERIES[3]
    expected = StandaloneQueryRunner(catalog).execute(sql).rows()
    assert_same_rows(dist.execute(sql).rows(), expected, ordered=True)
    assert any(r.fired for r in inj.rules), "injection never fired"


def test_durable_spool_survives_on_disk(tmp_path):
    """Stage outputs are really on disk: committed attempt directories with
    page files exist while the query runs (FileSystemExchangeManager.java:40
    semantics — the spool IS the checkpoint)."""
    import os

    from trino_tpu.execution import fte as fte_mod

    catalog = default_catalog(scale_factor=0.01)
    seen = []
    orig = fte_mod.make_spool_root

    def spy(base=None):
        d = orig(str(tmp_path))
        seen.append(d)
        return d

    fte_mod.make_spool_root = spy
    committed_checks = []
    try:
        dist = DistributedQueryRunner(
            catalog, worker_count=2,
            session=Session(node_count=2, retry_policy="TASK"))
        orig_attempt = type(dist).fte_run_attempt

        def spy_attempt(self, *a, **kw):
            path = orig_attempt(self, *a, **kw)
            # the committed attempt dir holds real page files on disk
            parts = [p for p in os.listdir(path) if p.startswith("part-")]
            nbytes = sum(os.path.getsize(os.path.join(path, p))
                         for p in parts)
            committed_checks.append((path, len(parts), nbytes))
            return path

        type(dist).fte_run_attempt = spy_attempt
        try:
            dist.execute("select count(*) from lineitem")
        finally:
            type(dist).fte_run_attempt = orig_attempt
    finally:
        fte_mod.make_spool_root = orig
    assert seen, "durable spool root never created"
    assert committed_checks, "no attempts committed"
    assert any(nb > 0 for _, nparts, nb in committed_checks), \
        "committed spools held no page bytes on disk"
    # cleaned up after the query
    assert not os.path.exists(seen[0])


def test_fte_speculative_beats_straggler():
    """A stalled task attempt is overtaken by a SPECULATIVE attempt (first
    committed wins — TaskExecutionClass.java:19 + the event-driven
    scheduler's speculation): the query finishes well before the stall
    expires, and the speculative commit is observable."""
    import time as _time

    from trino_tpu.execution.failure_injector import TASK_STALL, FailureInjector
    from trino_tpu.runner import StandaloneQueryRunner

    catalog = default_catalog(scale_factor=0.01)
    inj = FailureInjector()
    # stall attempt 0 of task 0 in the first two (multi-task) stages; the
    # speculative chain runs attempt_base=1000 and never matches the rule.
    # (single-task stages cannot speculate — the trigger needs half the
    # stage committed for a median duration estimate — so the root stays
    # unstalled.)
    inj.inject(TASK_STALL, task_index=0, attempt=0, times=2, stall_s=30.0)
    session = Session(node_count=3, retry_policy="TASK",
                      failure_injector=inj,
                      fte_speculative_delay_s=0.1)
    session.fte_events = []
    dist = DistributedQueryRunner(catalog, worker_count=3, session=session)
    sql = ("select o_orderpriority, count(*) c from orders "
           "group by o_orderpriority order by 1")
    expected = StandaloneQueryRunner(catalog).execute(sql).rows()
    t0 = _time.perf_counter()
    rows = dist.execute(sql).rows()
    wall = _time.perf_counter() - t0
    assert rows == expected
    assert wall < 25.0, f"speculation never rescued the stall ({wall:.1f}s)"
    kinds = [e[0] for e in session.fte_events]
    assert "speculative_start" in kinds
    assert any(e[0] == "commit" and e[3] == "SPECULATIVE"
               for e in session.fte_events)


def test_fte_memory_aware_retry():
    """An attempt that dies on ExceededMemoryLimitError retries with an
    exponentially larger memory budget
    (ExponentialGrowthPartitionMemoryEstimator.java:55)."""
    from trino_tpu.execution.failure_injector import TASK_OOM, FailureInjector
    from trino_tpu.runner import StandaloneQueryRunner

    catalog = default_catalog(scale_factor=0.01)
    inj = FailureInjector()
    inj.inject(TASK_OOM, task_index=0, attempt=0, times=1)
    session = Session(node_count=2, retry_policy="TASK",
                      failure_injector=inj)
    session.fte_events = []
    dist = DistributedQueryRunner(catalog, worker_count=2, session=session)
    sql = "select count(*), sum(o_totalprice) from orders"
    expected = StandaloneQueryRunner(catalog).execute(sql).rows()
    assert dist.execute(sql).rows() == expected
    mem_events = [e for e in session.fte_events if e[0] == "memory_retry"]
    assert mem_events, "memory retry never escalated the budget"
    assert mem_events[0][3] == 2.0  # default growth factor


def test_fte_memory_multiplier_reaches_planner(monkeypatch):
    """The grown budget really lands in the task's memory context."""
    from trino_tpu.execution.distributed_runner import DistributedQueryRunner
    import trino_tpu.execution.distributed_runner as dr

    seen = []
    orig = dr.LocalPlanner

    class SpyPlanner(orig):
        def __init__(self, *a, **kw):
            seen.append(kw.get("hbm_limit_bytes"))
            super().__init__(*a, **kw)

    monkeypatch.setattr(dr, "LocalPlanner", SpyPlanner)
    catalog = default_catalog(scale_factor=0.01)
    session = Session(node_count=2, retry_policy="TASK",
                      hbm_limit_bytes=1 << 20)
    runner = DistributedQueryRunner(catalog, worker_count=2, session=session)
    subplan = runner.create_subplan("select count(*) from nation")
    frag = subplan.all_fragments()[0]
    runner.fte_run_attempt(frag, 0, 1, 1, {}, __import__("tempfile").mkdtemp(),
                           0, None, memory_multiplier=4.0)
    assert (1 << 22) in seen
