"""L0 tests: types, batch encoding, memory accounting."""

import datetime

import numpy as np
import pytest

from trino_tpu.spi import (
    BIGINT,
    BOOLEAN,
    DATE,
    DOUBLE,
    INTEGER,
    VARCHAR,
    AggregatedMemoryContext,
    Column,
    ColumnBatch,
    DecimalType,
    ExceededMemoryLimitError,
    MemoryPool,
    common_super_type,
    parse_type,
    unify_dictionaries,
)


def test_parse_type():
    assert parse_type("bigint") is BIGINT
    assert parse_type("varchar(25)") is VARCHAR
    t = parse_type("decimal(15,2)")
    assert isinstance(t, DecimalType) and t.precision == 15 and t.scale == 2


def test_common_super_type():
    assert common_super_type(INTEGER, BIGINT) is BIGINT
    assert common_super_type(BIGINT, DOUBLE) is DOUBLE
    d = common_super_type(DecimalType(12, 2), DecimalType(10, 4))
    assert isinstance(d, DecimalType) and d.scale == 4
    assert common_super_type(DecimalType(12, 2), DOUBLE) is DOUBLE
    assert common_super_type(BOOLEAN, BIGINT) is None


def test_string_column_roundtrip():
    vals = ["banana", "apple", None, "cherry", "apple"]
    c = Column.from_values(VARCHAR, vals)
    assert c.dictionary is not None
    # dictionary sorted => code order == lexical order
    assert list(c.dictionary) == sorted(set(["banana", "apple", "cherry", ""]))
    assert c.to_pylist() == vals


def test_date_decimal_roundtrip():
    d = Column.from_values(DATE, ["1995-03-15", None, datetime.date(1992, 1, 2)])
    assert d.to_pylist() == [datetime.date(1995, 3, 15), None, datetime.date(1992, 1, 2)]
    dec = Column.from_values(DecimalType(12, 2), [1.5, None, "3.25"])
    assert np.asarray(dec.data)[0] == 150
    assert dec.to_pylist() == [1.5, None, 3.25]


def test_batch_ops():
    b = ColumnBatch.from_pydict(
        {
            "k": (BIGINT, [1, 2, 3, 4]),
            "s": (VARCHAR, ["a", "b", "a", None]),
        }
    )
    f = b.filter(np.array([True, False, True, True]))
    assert f.num_rows == 3
    assert f.column("k").to_pylist() == [1, 3, 4]
    t = b.take(np.array([3, 0]))
    assert t.column("s").to_pylist() == [None, "a"]
    c = ColumnBatch.concat([b, t])
    assert c.num_rows == 6
    assert c.column("s").to_pylist() == ["a", "b", "a", None, None, "a"]


def test_unify_dictionaries():
    a = Column.from_values(VARCHAR, ["x", "y"])
    b = Column.from_values(VARCHAR, ["y", "z"])
    ua, ub = unify_dictionaries([a, b])
    assert list(ua.dictionary) == list(ub.dictionary)
    assert ua.to_pylist() == ["x", "y"]
    assert ub.to_pylist() == ["y", "z"]


def test_memory_accounting():
    pool = MemoryPool("host", 1000)
    root = AggregatedMemoryContext(pool=pool)
    task = root.new_child()
    op1 = task.new_local("op1")
    op2 = task.new_local("op2")
    op1.set_bytes(300)
    op2.set_bytes(500)
    assert pool.reserved == 800
    op1.set_bytes(100)
    assert pool.reserved == 600
    with pytest.raises(ExceededMemoryLimitError):
        op2.set_bytes(1000)
    # failed reservation must not corrupt accounting
    assert pool.reserved == 600
    op1.close()
    op2.close()
    task.close()
    root.close()
    assert pool.reserved == 0
    # use-after-close must raise, not drive the pool negative
    with pytest.raises(RuntimeError):
        op1.set_bytes(50)
    assert pool.reserved == 0


def test_decimal_exact_and_timestamp():
    import decimal

    big = 9007199254740993  # 2**53 + 1: not float64-representable
    c = Column.from_values(DecimalType(18, 0), [big])
    assert c.to_pylist()[0] == decimal.Decimal(big)
    c2 = Column.from_values(DecimalType(10, 2), ["1.005"])
    assert c2.to_pylist()[0] == decimal.Decimal("1.01")  # half-up
    from trino_tpu.spi import TIMESTAMP

    ts = Column.from_values(TIMESTAMP, ["2020-01-02 03:04:05.000006", None])
    assert int(np.asarray(ts.data)[0]) == 1577934245000006
    assert ts.to_pylist()[1] is None


def test_concat_empty_list_raises():
    with pytest.raises(ValueError):
        ColumnBatch.concat([])
