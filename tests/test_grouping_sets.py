"""GROUPING SETS / ROLLUP / CUBE + grouping() + VALUES body (reference:
sql/planner/plan/GroupIdNode.java, operator/GroupIdOperator.java:32,
sql/tree/Values.java; behavior per AbstractTestAggregations grouping-set
cases).  sqlite has no GROUPING SETS, so expectations are equivalence
against the engine's own UNION ALL expansion plus hand-checked rows."""

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.runner import Session, StandaloneQueryRunner


@pytest.fixture(scope="module")
def runner():
    r = StandaloneQueryRunner(default_catalog(scale_factor=0.01),
                              session=Session(default_catalog="memory"))
    r.execute("create table gs (k1 varchar, k2 varchar, v bigint)")
    r.execute("insert into gs values ('a','x',1),('a','y',2),('b','x',3),"
              "('b','y',4),('a','x',5),('a',null,6)")
    return r


def rows(runner, sql):
    return runner.execute(sql).rows()


def test_rollup(runner):
    assert rows(runner,
                "select k1, k2, sum(v) from gs group by rollup(k1, k2) "
                "order by 1, 2") == [
        ("a", "x", 6), ("a", "y", 2), ("a", None, 6), ("a", None, 14),
        ("b", "x", 3), ("b", "y", 4), ("b", None, 7), (None, None, 21)]


def test_cube_with_grouping_fn(runner):
    got = rows(runner,
               "select k1, k2, sum(v), grouping(k1, k2) from gs "
               "group by cube(k1, k2) order by 4, 1, 2")
    assert got == [
        ("a", "x", 6, 0), ("a", "y", 2, 0), ("a", None, 6, 0),
        ("b", "x", 3, 0), ("b", "y", 4, 0),
        ("a", None, 14, 1), ("b", None, 7, 1),
        (None, "x", 9, 2), (None, "y", 6, 2), (None, None, 6, 2),
        (None, None, 21, 3)]


def test_grouping_sets_explicit(runner):
    assert rows(runner,
                "select k1, sum(v) from gs "
                "group by grouping sets ((k1), ()) order by 1") == [
        ("a", 14), ("b", 7), (None, 21)]


def test_cross_product_element(runner):
    # GROUP BY k1, ROLLUP(k2) = sets {k1,k2}, {k1}
    assert rows(runner,
                "select k1, k2, count(*) from gs group by k1, rollup(k2) "
                "order by 1, 2") == [
        ("a", "x", 2), ("a", "y", 1), ("a", None, 1), ("a", None, 4),
        ("b", "x", 1), ("b", "y", 1), ("b", None, 2)]


def test_key_also_aggregate_argument(runner):
    # v is both a grouping column and an aggregate argument: the GroupId
    # passthrough copy must keep values un-nulled for the () set
    assert rows(runner,
                "select v, sum(v), count(*) from gs "
                "group by grouping sets ((v), ()) order by 1") == [
        (1, 1, 1), (2, 2, 1), (3, 3, 1), (4, 4, 1), (5, 5, 1), (6, 6, 1),
        (None, 21, 6)]


def test_union_all_equivalence(runner):
    gs = rows(runner,
              "select k1, k2, sum(v), count(*) from gs "
              "group by grouping sets ((k1, k2), (k1), ()) order by 1, 2, 3")
    ua = rows(runner,
              "select k1, k2, sum(v), count(*) from gs group by k1, k2 "
              "union all "
              "select k1, null, sum(v), count(*) from gs group by k1 "
              "union all "
              "select null, null, sum(v), count(*) from gs order by 1, 2, 3")
    assert gs == ua


def test_having_on_grouping_sets(runner):
    assert rows(runner,
                "select k1, sum(v) from gs group by rollup(k1) "
                "having sum(v) > 10 order by 1") == [
        ("a", 14), (None, 21)]


def test_tpch_rollup_distributed():
    catalog = default_catalog(scale_factor=0.01)
    single = StandaloneQueryRunner(catalog)
    dist = DistributedQueryRunner(catalog, worker_count=3)
    sql = ("select n_regionkey, count(*) c from tpch.nation "
           "group by rollup(n_regionkey) order by 1")
    assert dist.execute(sql).rows() == single.execute(sql).rows()


def test_values_body(runner):
    assert rows(runner,
                "select a, b from (values (1, 'p'), (2, 'q'), (3, null)) "
                "as v(a, b) order by a") == [(1, "p"), (2, "q"), (3, None)]


def test_values_computed_row(runner):
    assert rows(runner,
                "select x + 1 from (values (1 + 1), (10)) as v(x) "
                "order by 1") == [(3,), (11,)]


def test_grouping_fn_requires_group_column(runner):
    with pytest.raises(Exception):
        rows(runner, "select grouping(v) from gs group by k1")


def test_grouping_fn_in_order_by_only(runner):
    # grouping() appearing ONLY in ORDER BY must still be rewritten
    assert rows(runner,
                "select k1, sum(v) from gs group by rollup(k1) "
                "order by grouping(k1), k1") == [
        ("a", 14), ("b", 7), (None, 21)]


def test_grouping_fn_plain_group_by(runner):
    # single grouping set: grouping() is constant 0 (ORDER BY path)
    assert rows(runner,
                "select k1, count(*) from gs group by k1 "
                "order by grouping(k1), k1") == [("a", 4), ("b", 2)]


def test_sort_null_nan_payload_ties():
    # NULL slots backed by NaN garbage (x/0-style) must tie exactly: the
    # secondary key decides (kernels.sort_perm canonicalization order)
    import numpy as np

    from trino_tpu.exec import kernels as K

    perm = K.sort_perm([
        (np.array([np.nan, 7.0]), np.array([False, False]), True, False),
        (np.array([1, 2]), None, True, False)])
    assert list(np.asarray(perm)) == [0, 1]
