"""Worker PROCESSES + wire protocol: tasks created over HTTP
(POST /v1/task), pages pulled with the token-ack results protocol, full
TPC-H correctness across a real process boundary, and fail-fast when a
worker dies (reference: server/TaskResource.java:140,
server/remotetask/HttpRemoteTask.java:132,
operator/HttpPageBufferClient.java:355)."""

import os

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.connectors.tpch_queries import QUERIES
from trino_tpu.execution.remote import ProcessDistributedQueryRunner
from trino_tpu.runner import Session, StandaloneQueryRunner
from trino_tpu.testing.oracle import assert_same_rows

_ORDERED = {1, 2, 3, 5, 7, 8, 9, 10, 11, 12, 13, 14, 16, 18, 21, 22}

CATALOG_SPEC = {
    "factory": "trino_tpu.connectors.catalog:default_catalog",
    "kwargs": {"scale_factor": 0.01},
}

_ENV = {
    "JAX_PLATFORMS": "cpu",
    # workers need no multi-device mesh; keep their compiles light
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}


@pytest.fixture(scope="module")
def runners():
    dist = ProcessDistributedQueryRunner(
        CATALOG_SPEC, worker_count=2,
        session=Session(node_count=2), env_overrides=_ENV)
    standalone = StandaloneQueryRunner(default_catalog(scale_factor=0.01))
    yield dist, standalone
    dist.close()


@pytest.mark.parametrize("q", sorted(QUERIES))
def test_tpch_over_processes(runners, q):
    dist, standalone = runners
    actual = dist.execute(QUERIES[q]).rows()
    expected = standalone.execute(QUERIES[q]).rows()
    assert_same_rows(actual, expected, ordered=q in _ORDERED)


def test_worker_death_fails_fast(runners):
    """A dead worker is routed around by task placement (node-selector
    behavior), and a task pinned to a killed worker reports GONE so the
    coordinator fails fast instead of hanging (recovery itself is FTE's
    durable-spool job)."""
    from trino_tpu.execution.remote import HttpRemoteTask

    dist, _ = runners
    victim = ProcessDistributedQueryRunner(
        CATALOG_SPEC, worker_count=2,
        session=Session(node_count=2), env_overrides=_ENV)
    try:
        # sanity: works before the kill
        assert victim.execute("select count(*) from nation").rows() == [(25,)]
        dead = victim.workers[1]
        rt = HttpRemoteTask(dead.url, "probe")
        dead.kill()
        assert rt.status()["state"] == "GONE"
        # the scheduler avoids the dead worker: queries still succeed and
        # stay correct on the survivor
        rows = victim.execute(
            "select count(*), sum(o_totalprice) from orders").rows()
        assert rows[0][0] == 15000
        assert [w.alive() for w in victim.workers].count(True) == 1
    finally:
        victim.close()


def test_graceful_shutdown(runners):
    """PUT /v1/shutdown drains and exits the worker process
    (server/GracefulShutdownHandler.java:42)."""
    dist, _ = runners
    solo = ProcessDistributedQueryRunner(
        CATALOG_SPEC, worker_count=1,
        session=Session(node_count=1), env_overrides=_ENV)
    try:
        assert solo.execute("select count(*) from region").rows() == [(5,)]
        solo.workers[0].shutdown()
        assert not solo.workers[0].alive()
    finally:
        solo.close()


def test_fte_worker_kill_recovers(runners):
    """THE durable-FTE proof (round-4 VERDICT item #4): a worker PROCESS is
    hard-killed mid-stage by an injected PROCESS_EXIT; the attempt's
    consumers retry on the surviving worker, reading earlier stages'
    committed on-disk spools — the query completes correctly with one
    worker genuinely dead."""
    from trino_tpu.execution.failure_injector import (
        PROCESS_EXIT,
        FailureInjector,
    )

    dist, standalone = runners
    inj = FailureInjector()
    fte = ProcessDistributedQueryRunner(
        CATALOG_SPEC, worker_count=2,
        session=Session(node_count=2, retry_policy="TASK",
                        failure_injector=inj),
        env_overrides=_ENV)
    try:
        sql = QUERIES[3]
        leaf = fte.create_subplan(sql).all_fragments()[0]
        inj.inject(PROCESS_EXIT, fragment_id=leaf.id, task_index=0,
                   attempt=0)
        rows = fte.execute(sql).rows()
        expected = standalone.execute(sql).rows()
        assert_same_rows(rows, expected, ordered=True)
        assert [w.alive() for w in fte.workers].count(True) == 1, \
            "the injected PROCESS_EXIT did not actually kill a worker"
    finally:
        fte.close()


def test_internal_secret_required(runners):
    """Mutating/descriptor-decoding endpoints reject requests that lack the
    per-spawn shared secret (reference: InternalCommunicationConfig
    sharedSecret); /v1/info stays open for liveness probes."""
    import json
    import urllib.error
    import urllib.request

    dist, _ = runners
    url = dist.workers[0].url
    with urllib.request.urlopen(f"{url}/v1/info", timeout=10) as resp:
        assert json.loads(resp.read())["state"] in ("ACTIVE", "SHUTTING_DOWN")
    req = urllib.request.Request(
        f"{url}/v1/task/evil", data=b"\x00" * 8, method="POST")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 401
    req = urllib.request.Request(
        f"{url}/v1/task/evil/results/0/0", method="GET")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 401


def test_cross_process_trace_tree(runners):
    """The coordinator's query span contains the worker task spans shipped
    back over HTTP: remote-parented via the traceparent header on task
    create, serialized with task completion, re-attached as one tree."""
    dist, _ = runners
    dist.execute("select count(*) from nation")
    root = dist.tracer.finished[-1]
    assert root.name == "trino.query"
    tasks = [c for c in root.children if c.name == "trino.task"]
    assert tasks, "no remote task spans re-attached under the query span"
    for t in tasks:
        assert t.trace_id == root.trace_id
        assert t.parent_id == root.span_id
        assert t.attributes["trino.task.worker"].startswith("127.0.0.1:")
    scanned = sum(t.attributes.get("trino.scan.rows", 0) for t in tasks)
    assert scanned == 25
    # the /v1/metrics scrape on a live worker shows its own task counters
    import urllib.request

    url = dist.workers[0].url
    body = urllib.request.urlopen(f"{url}/v1/metrics").read().decode()
    assert "trino_tasks_created_total" in body
