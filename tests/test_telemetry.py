"""PR 7 observability surface: the metrics registry (telemetry/metrics.py),
the /v1/metrics Prometheus endpoints on coordinator and worker, distributed
trace assembly (coordinator-rooted query span containing worker task spans),
the ``system`` catalog's runtime/metrics tables, and the enriched
QueryCompletedEvent."""

import json
import threading
import urllib.request

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.runner import StandaloneQueryRunner
from trino_tpu.telemetry import metrics as tm
from trino_tpu.telemetry.metrics import MetricsRegistry


# ------------------------------------------------------------ registry units


def test_counter_thread_local_cells_fold():
    r = MetricsRegistry()
    c = r.counter("trino_things_total", "things")
    c.inc()
    c.inc(4)

    def work():
        for _ in range(100):
            c.inc()

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # dead threads' cells fold into the retired total; value() is exact
    assert c.value() == 805
    assert c.value() == 805  # folding is idempotent


def test_distribution_percentiles_and_merge():
    r = MetricsRegistry()
    d = r.distribution("trino_lat_seconds", "latency", lo=1e-3)
    for ms in range(1, 101):  # 1ms..100ms
        d.record(ms / 1e3)
    snap = d.snapshot()
    assert snap["count"] == 100
    assert abs(snap["sum"] - sum(ms / 1e3 for ms in range(1, 101))) < 1e-9
    # log-spaced buckets: percentiles are interpolated, so allow 2x slack
    assert 0.02 < snap["p50"] < 0.1
    assert snap["p50"] <= snap["p90"] <= snap["p99"]
    assert snap["p99"] <= snap["max"] + 1e-12

    # cross-process merge: a second registry's snapshot folds in
    r2 = MetricsRegistry()
    d2 = r2.distribution("trino_lat_seconds", "latency", lo=1e-3)
    for _ in range(50):
        d2.record(0.5)
    d.merge(d2.snapshot())
    snap = d.snapshot()
    assert snap["count"] == 150
    assert snap["max"] >= 0.5


def test_registry_kind_conflict_raises():
    r = MetricsRegistry()
    r.counter("trino_x_total", "x")
    with pytest.raises(ValueError):
        r.gauge("trino_x_total", "x as gauge")


def test_prometheus_render_shape():
    r = MetricsRegistry()
    r.counter("trino_c_total", "a counter").inc(3)
    r.gauge("trino_g", "a gauge").set(7.5)
    d = r.distribution("trino_h_seconds", "a histogram")
    d.record(0.01)
    text = r.render_prometheus()
    assert "# HELP trino_c_total a counter" in text
    assert "# TYPE trino_c_total counter" in text
    assert "trino_c_total 3" in text
    assert "trino_g 7.5" in text
    assert "# TYPE trino_h_seconds histogram" in text
    assert 'trino_h_seconds_bucket{le="+Inf"} 1' in text
    assert "trino_h_seconds_count 1" in text


def test_traceparent_roundtrip():
    from trino_tpu.execution.tracing import (
        Span,
        parse_traceparent,
        traceparent,
    )

    s = Span("trino.query")
    header = traceparent(s)
    got = parse_traceparent(header)
    assert got == (s.trace_id, s.span_id)
    assert parse_traceparent(None) is None
    assert parse_traceparent("junk") is None
    assert parse_traceparent("00-short-id-01") is None


def test_span_dict_roundtrip_preserves_tree():
    from trino_tpu.execution.tracing import Span

    root = Span("trino.task", {"trino.scan.rows": 25},
                trace_id="t" * 32, span_id="a" * 16, parent_id="b" * 16)
    root.end = root.start + 0.5
    child = Span("trino.operator", trace_id="t" * 32, span_id="c" * 16,
                 parent_id="a" * 16)
    child.end = child.start + 0.1
    root.children.append(child)
    back = Span.from_dict(root.to_dict())
    assert back.name == "trino.task"
    assert back.attributes["trino.scan.rows"] == 25
    assert back.trace_id == root.trace_id
    assert back.parent_id == root.parent_id
    assert len(back.children) == 1
    assert back.children[0].parent_id == back.span_id
    assert abs(back.duration_ms - 500) < 1.0


# --------------------------------------------------- /v1/metrics endpoints


def test_coordinator_metrics_endpoint():
    from trino_tpu.server import TrinoTpuServer

    runner = StandaloneQueryRunner(default_catalog(scale_factor=0.001))
    runner.execute("select count(*) from nation")
    srv = TrinoTpuServer(runner, port=0).start()
    try:
        host, port = srv.address
        resp = urllib.request.urlopen(f"http://{host}:{port}/v1/metrics")
        body = resp.read().decode()
        assert resp.headers["Content-Type"].startswith("text/plain")
        # scan, resilience and fused counters are all pre-registered
        for name in ("trino_scan_bytes_total",
                     "trino_resilience_query_retries_total",
                     "trino_fused_compiles_total",
                     "trino_queries_started_total"):
            assert name in body, name
        # the query above actually moved the scan counter
        line = [ln for ln in body.splitlines()
                if ln.startswith("trino_scan_bytes_total")][0]
        assert float(line.split()[-1]) > 0
    finally:
        srv.stop()


def test_worker_metrics_endpoint():
    from trino_tpu.execution.worker import TaskServer

    s = TaskServer(0)
    th = threading.Thread(target=s.serve_forever, daemon=True)
    th.start()
    try:
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{s.port}/v1/metrics")
        body = resp.read().decode()
        assert resp.headers["Content-Type"].startswith("text/plain")
        for name in ("trino_scan_bytes_total", "trino_tasks_created_total",
                     "trino_exchange_bytes_total"):
            assert name in body, name
    finally:
        s.httpd.shutdown()


# ------------------------------------------- distributed trace assembly


def test_distributed_query_single_trace_tree():
    """Satellite 3: a 2-worker distributed query yields ONE root span whose
    descendants include the worker task spans, with parent/child linkage
    and the trino.scan.* attributes intact."""
    d = DistributedQueryRunner(worker_count=2)
    r = d.execute("select count(*) from nation")
    assert r.rows() == [(25,)]
    root = d.tracer.finished[-1]
    assert root.name == "trino.query"
    assert root.trace_id and root.span_id
    tasks = [c for c in root.children if c.name == "trino.task"]
    assert tasks, "no worker task spans under the query span"
    for t in tasks:
        assert t.trace_id == root.trace_id
        assert t.parent_id == root.span_id
    scan_rows = sum(t.attributes.get("trino.scan.rows", 0) for t in tasks)
    assert scan_rows == 25
    # renderable as one tree
    text = root.text()
    assert "trino.query" in text and "trino.task" in text


def test_task_spans_not_duplicated_as_roots():
    """Cross-thread-parented task spans live ONLY in the query tree — they
    must not also surface as separate roots in tracer.finished."""
    d = DistributedQueryRunner(worker_count=2)
    d.execute("select count(*) from region")
    names = [s.name for s in d.tracer.finished]
    assert "trino.task" not in names


# -------------------------------------------------------- system catalog


def test_system_runtime_queries_sql():
    d = DistributedQueryRunner(worker_count=2)
    d.execute("select count(*) from nation")
    r = d.execute("select query_id, state from system.runtime.queries")
    rows = r.rows()
    assert any(state == "FINISHED" for _qid, state in rows)
    # the introspection query itself shows up as RUNNING
    assert any(state == "RUNNING" for _qid, state in rows)


def test_system_runtime_tasks_sql():
    d = DistributedQueryRunner(worker_count=2)
    d.execute("select count(*) from nation")
    r = d.execute("select worker, state from system.runtime.tasks")
    rows = r.rows()
    assert rows and all(w == "local" for w, _ in rows)
    assert any(state == "FINISHED" for _, state in rows)


def test_system_metrics_counters_sql():
    d = DistributedQueryRunner(worker_count=2)
    d.execute("select count(*) from nation")
    r = d.execute("select name, kind, value from system.metrics.counters")
    by_name = {name: (kind, value) for name, kind, value in r.rows()}
    assert by_name["trino_scan_bytes_total"][0] == "counter"
    assert by_name["trino_scan_bytes_total"][1] > 0
    assert by_name["trino_tasks_created_total"][1] > 0
    # distributions flatten to summary rows
    assert "trino_query_wall_seconds_p50" in by_name
    assert "trino_query_wall_seconds_count" in by_name


def test_system_tables_standalone_runner():
    runner = StandaloneQueryRunner(default_catalog(scale_factor=0.001))
    runner.execute("select count(*) from nation")
    rows = runner.execute(
        "select query_id, state, input_rows from system.runtime.queries"
    ).rows()
    fin = [r for r in rows if r[1] == "FINISHED"]
    assert fin and fin[-1][2] == 25  # nation scan counted as input


# -------------------------------------------------- event enrichment


def test_query_completed_event_enriched():
    from trino_tpu.spi.eventlistener import EventListener

    captured = []

    class Capture(EventListener):
        def query_completed(self, event):
            captured.append(event)

    runner = StandaloneQueryRunner(default_catalog(scale_factor=0.001))
    runner.event_listeners.add(Capture())
    runner.execute("select count(*) from nation")
    ev = captured[-1]
    assert ev.state == "FINISHED"
    assert ev.wall_ms > 0
    assert ev.cpu_ms >= 0
    assert ev.input_rows == 25
    assert ev.input_bytes > 0
    assert ev.retry_count == 0
    assert ev.peak_memory_bytes >= 0


def test_query_wall_distribution_records():
    before = tm.QUERY_WALL_SECONDS.snapshot()["count"]
    runner = StandaloneQueryRunner(default_catalog(scale_factor=0.001))
    runner.execute("select 1")
    runner.execute("select 2")
    after = tm.QUERY_WALL_SECONDS.snapshot()["count"]
    assert after >= before + 2


# -------------------------------------------- cluster-wide metric fold units


def test_merge_snapshot_sums_counters_and_folds_distributions():
    r1, r2 = MetricsRegistry(), MetricsRegistry()
    for r, n in ((r1, 3), (r2, 5)):
        c = r.counter("trino_widgets_total", "w")
        c.inc(n)
        d = r.distribution("trino_lat_seconds", "l", lo=1e-3)
        for _ in range(n):
            d.record(0.01)
    snap = r1.snapshot()
    tm.merge_snapshot(snap, r2.snapshot())
    assert snap["trino_widgets_total"]["value"] == 8
    assert snap["trino_lat_seconds"]["count"] == 8
    assert abs(snap["trino_lat_seconds"]["sum"] - 0.08) < 1e-9
    # unknown names are adopted; mismatched bucket layouts are skipped
    r3 = MetricsRegistry()
    r3.counter("trino_other_total", "o").inc()
    d3 = r3.distribution("trino_lat_seconds", "l", lo=1e-1)
    d3.record(0.5)
    tm.merge_snapshot(snap, r3.snapshot())
    assert snap["trino_other_total"]["value"] == 1
    assert snap["trino_lat_seconds"]["count"] == 8  # skew-safe: skipped


def test_render_snapshot_prometheus_matches_live_histogram_shape():
    r = MetricsRegistry()
    d = r.distribution("trino_lat_seconds", "latency", lo=1e-3)
    d.record(0.002)
    d.record(1e9)  # lands in the +Inf overflow bucket
    text = tm.render_snapshot_prometheus(r.snapshot())
    lines = text.splitlines()
    assert "# TYPE trino_lat_seconds histogram" in lines
    buckets = [l for l in lines if l.startswith("trino_lat_seconds_bucket")]
    assert buckets[-1] == 'trino_lat_seconds_bucket{le="+Inf"} 2'
    assert "trino_lat_seconds_count 2" in lines
    # cumulative: counts never decrease down the bucket ladder
    counts = [int(b.rsplit(" ", 1)[1]) for b in buckets]
    assert counts == sorted(counts)
