"""Sync-free probe/expand hot loop: padded-expand equivalence against the
legacy blocking paths, overflow->retry correctness, capacity planning, the
deferred-commit OverflowQueue, and SyncGuard enforcement that steady-state
probe batches perform ZERO blocking host syncs.

Legacy switches kept precisely for these tests:
  TRINO_TPU_LEGACY_EXPAND=1  kernels.probe_join_table two-fetch expand
  TRINO_TPU_SYNC_FREE=0      operators.py per-batch blocking total sync
"""

import numpy as np
import pytest

from trino_tpu.exec import join_exec as JX
from trino_tpu.exec import kernels as K
from trino_tpu.exec import syncguard as SG
from trino_tpu.exec.operators import JoinBridge, JoinBuildSink, LookupJoinOperator
from trino_tpu.spi import BIGINT, Column, ColumnBatch


def _keys(arr, valid=None):
    return [(np.asarray(arr), None if valid is None else np.asarray(valid))]


def _pair_set(pi, bi):
    return set(zip(np.asarray(pi).tolist(), np.asarray(bi).tolist()))


def _expected_pairs(build, probe, bvalid=None, pvalid=None):
    out = set()
    for p, pv in enumerate(probe):
        if pvalid is not None and not pvalid[p]:
            continue
        for b, bv in enumerate(build):
            if bvalid is not None and not bvalid[b]:
                continue
            if pv == bv:
                out.add((p, b))
    return out


# ---------------------------------------------------------------------------
# kernels.probe_join_table: padded single-fetch vs legacy two-fetch


def test_probe_join_table_padded_vs_legacy(monkeypatch):
    rng = np.random.default_rng(3)
    build = rng.integers(0, 50, size=300).astype(np.int64)  # heavy dups
    bvalid = rng.random(300) > 0.1
    probe = rng.integers(0, 60, size=257).astype(np.int64)  # some no-match
    pvalid = rng.random(257) > 0.1
    table = K.build_join_table([(build, bvalid)])

    pi, bi = K.probe_join_table(table, [(probe, pvalid)])
    monkeypatch.setenv("TRINO_TPU_LEGACY_EXPAND", "1")
    pi_l, bi_l = K.probe_join_table(table, [(probe, pvalid)])

    expected = _expected_pairs(build, probe, bvalid, pvalid)
    assert _pair_set(pi, bi) == expected
    assert _pair_set(pi_l, bi_l) == expected


def test_probe_join_table_zero_match_and_empty(monkeypatch):
    table = K.build_join_table(_keys(np.arange(10, dtype=np.int64)))
    for env in ("0", "1"):
        monkeypatch.setenv("TRINO_TPU_LEGACY_EXPAND", env)
        # zero matches: every probe key outside the build domain
        pi, bi = K.probe_join_table(
            table, _keys(np.array([100, 200], dtype=np.int64)))
        assert len(pi) == 0 and len(bi) == 0
        # empty probe
        pi, bi = K.probe_join_table(
            table, _keys(np.empty(0, dtype=np.int64)))
        assert len(pi) == 0 and len(bi) == 0


def test_probe_join_table_overflow_retry():
    # 4 probe rows * 64-duplicate build runs = 256 candidates, far beyond
    # the speculative bucket(4) * _PAIR_PAD = 32 cap: the padded path must
    # detect overflow and re-run at the exact bucket, never truncate
    build = np.repeat(np.arange(2, dtype=np.int64), 64)
    probe = np.array([0, 1, 0, 1], dtype=np.int64)
    table = K.build_join_table(_keys(build))
    before = SG.snapshot()
    pi, bi = K.probe_join_table(table, _keys(probe))
    delta = SG.take_delta(before)
    assert delta.expand_overflows >= 1
    assert _pair_set(pi, bi) == _expected_pairs(build, probe)


# ---------------------------------------------------------------------------
# join_exec.run_pairs: provable / estimated caps vs the legacy host total


def _run_pairs_at(table, keys, cap, donate=False, total=None):
    lo, counts, total_a = JX.probe_ranges_device(table, keys, [None])
    t = total_a if total is None else total
    probe = keys[0][0]
    pairs, ok, matched, maxc, bid, overflow = JX.run_pairs(
        table, lo, counts, t, keys, [None],
        [(probe, None)], [(table.key_datas[0], None)],
        [BIGINT, BIGINT], [None, None],
        residual=None, need_matched=True, cap=cap, donate=donate)
    return pairs, ok, bid, overflow


def test_run_pairs_provable_cap_matches_legacy():
    rng = np.random.default_rng(11)
    # dup runs of 4 keep bucket(n_probe * max_run) within PROVABLE_SLACK of
    # the probe width: the planner must prove the cap and skip the flag
    build = np.repeat(np.arange(50, dtype=np.int64), 4)
    probe = rng.integers(0, 60, size=128).astype(np.int64)
    table = JX.build_table(_keys(build))
    keys = _keys(probe)
    expected = _expected_pairs(build, probe)

    # legacy: blocking total sync picks the exact bucket
    lo, counts, total = JX.probe_ranges(table, keys, [None])
    pairs_l, ok_l, bid_l, _ = _run_pairs_at(table, keys, cap=None, total=total)
    ok_l = np.asarray(ok_l)
    # slot -> probe id comes back via the gathered probe column
    pi_l = np.asarray(pairs_l[0][0])[ok_l]  # probe VALUES, so map via pairs
    # reconstruct (probe_idx, build_idx) from gathered values + device ids
    bid_l = np.asarray(bid_l)[ok_l]

    # sync-free: planner cap from build-side stats (max_run), no total sync
    planner = JX.ExpandPlanner()
    cap, provable = planner.plan(len(probe), table.max_run)
    assert provable  # run 4 * 128 probes = 512 lanes <= 8 * bucket(128)
    pairs_s, ok_s, bid_s, overflow = _run_pairs_at(
        table, keys, cap=cap, donate=provable)
    ok_s = np.asarray(ok_s)
    bid_s = np.asarray(bid_s)[ok_s]
    assert not bool(np.asarray(overflow))

    # both paths produce the same (probe value, build row) multiset, and
    # the build rows of each must be exactly the expected pair set's
    assert sorted(bid_l.tolist()) == sorted(bid_s.tolist())
    assert set(bid_s.tolist()) == {b for _, b in expected}
    assert sorted(np.asarray(pairs_s[0][0])[ok_s].tolist()) == \
        sorted(pi_l.tolist())


def test_run_pairs_overflow_flag_and_retry():
    build = np.repeat(np.arange(4, dtype=np.int64), 32)  # runs of 32
    probe = np.arange(4, dtype=np.int64)  # total = 4 * 32 = 128
    table = JX.build_table(_keys(build))
    keys = _keys(probe)

    _, ok_t, _, overflow = _run_pairs_at(table, keys, cap=16)
    assert bool(np.asarray(overflow))  # 128 candidates > 16 lanes: flagged
    # the retry contract: re-run at the exact (now host-known) bucket
    lo, counts, total_a = JX.probe_ranges_device(table, keys, [None])
    total = int(total_a.get())
    assert total == 128
    pairs, ok, bid, overflow2 = _run_pairs_at(
        table, keys, cap=K.bucket(total))
    assert not bool(np.asarray(overflow2))
    ok = np.asarray(ok)
    assert int(ok.sum()) == 128
    assert set(np.asarray(bid)[ok].tolist()) == set(range(len(build)))


def test_run_pairs_empty_probe_zero_match():
    build = np.arange(16, dtype=np.int64)
    table = JX.build_table(_keys(build))
    keys = _keys(np.array([100, 101], dtype=np.int64))
    pairs, ok, bid, overflow = _run_pairs_at(table, keys, cap=8)
    assert int(np.asarray(ok).sum()) == 0
    assert not bool(np.asarray(overflow))


# ---------------------------------------------------------------------------
# capacity planning


def test_planner_provable_for_unique_build():
    cap, provable = JX.ExpandPlanner().plan(1024, max_run=1)
    assert provable and cap == 1024


def test_planner_estimates_then_crosses_bound():
    p = JX.ExpandPlanner()
    # bound = 16 * 1000 lanes >> PROVABLE_SLACK * bucket(16): not provable,
    # first estimate falls back to the probe width
    cap, provable = p.plan(16, max_run=1000)
    assert not provable and cap == K.bucket(16)
    # a landed total pushes the estimate past the provable bound: the
    # planner snaps to the bound (never exceeds what can be proven needed)
    p.observe(16000)
    cap, provable = p.plan(16, max_run=1000)
    assert provable and cap == K.bucket(16 * 1000)


def test_planner_unknown_max_run_never_provable():
    p = JX.ExpandPlanner()
    cap, provable = p.plan(64, max_run=None)
    assert not provable and cap == K.bucket(64)


def test_plan_unique_cap():
    assert JX.plan_unique_cap(1024, 10) == K.bucket(10)  # sparse: compact
    assert JX.plan_unique_cap(1024, 800) is None  # dense: stay wide
    assert JX.plan_unique_cap(1024, None) is None  # unknown: stay wide


# ---------------------------------------------------------------------------
# OverflowQueue: deferred commits, retry on landed-True flags


def test_overflow_queue_commits_in_order_and_retries():
    import jax.numpy as jnp

    q = JX.OverflowQueue()
    committed = []
    retried = []

    def entry(i, overflow):
        def retry():
            retried.append(i)
            return f"retry-{i}"

        q.push(SG.async_scalar(jnp.asarray(overflow), f"t{i}"),
               f"spec-{i}", retry, committed.append)

    before = SG.snapshot()
    entry(0, False)
    entry(1, True)  # truncated: must re-run, never commit the speculation
    entry(2, False)
    q.drain(block=True)
    assert committed == ["spec-0", "retry-1", "spec-2"]
    assert retried == [1]
    assert SG.take_delta(before).expand_retries == 1
    assert len(q) == 0


def test_overflow_queue_blocks_past_max_inflight():
    import jax.numpy as jnp

    q = JX.OverflowQueue()
    committed = []
    for i in range(JX.MAX_INFLIGHT + 2):
        q.push(SG.async_scalar(jnp.asarray(False), "t"), i, lambda: None,
               committed.append)
        q.drain()  # non-blocking: may or may not commit yet
    assert len(q) <= JX.MAX_INFLIGHT + 1  # backpressure bound
    q.drain(block=True)
    assert committed == list(range(JX.MAX_INFLIGHT + 2))


# ---------------------------------------------------------------------------
# SyncGuard: steady-state probe batches are sync-free, and violations raise


def test_forbidden_raises_inside_hot_region():
    import jax.numpy as jnp

    with SG.forbidden():
        with SG.hot_region():
            with pytest.raises(SG.SyncViolation):
                SG.count_sync("test.tag", blocking=True)
        # outside the hot region the same sync is fine
        SG.count_sync("test.tag", blocking=True)
    # non-blocking polls never violate
    with SG.forbidden(), SG.hot_region():
        h = SG.async_scalar(jnp.asarray(1), "test.poll")
        h.get_if_ready()


def _probe_driver(op, batch):
    op.add_input(batch)
    out = []
    while (b := op.get_output()) is not None:
        out.append(b.compact())
    return out


def test_lookup_join_steady_state_zero_hot_syncs():
    """The acceptance contract: after warm-up, probe batches flow through
    LookupJoinOperator with ZERO blocking host syncs — SyncGuard forbidden
    mode raises on any violation, and the per-region counter stays 0."""
    rng = np.random.default_rng(5)
    nb = 3200
    build_keys = np.repeat(np.arange(100, dtype=np.int64), 32)  # dup runs
    build_vals = rng.integers(0, 1000, size=nb).astype(np.int64)
    bridge = JoinBridge()
    sink = JoinBuildSink(bridge, [0], [BIGINT, BIGINT], ["bk", "bv"])
    sink.add_input(ColumnBatch(
        ["bk", "bv"], [Column.from_values(BIGINT, build_keys.tolist()),
                       Column.from_values(BIGINT, build_vals.tolist())]))
    sink.finish_input()
    op = LookupJoinOperator(bridge, [0], "INNER", None,
                            ["pk", "pv", "bk", "bv"], [BIGINT] * 4)

    def batch(seed):
        r = np.random.default_rng(seed)
        pk = r.integers(0, 110, size=1024).astype(np.int64)
        return pk, ColumnBatch(
            ["pk", "pv"], [Column.from_values(BIGINT, pk.tolist()),
                           Column.from_values(BIGINT, pk.tolist())])

    total_rows = 0
    expected = 0
    hits = np.bincount(build_keys, minlength=110)
    # warm-up: jit compiles, planner converges, build scalars land
    for seed in range(3):
        pk, b = batch(seed)
        expected += int(hits[pk].sum())
        total_rows += sum(o.num_rows for o in _probe_driver(op, b))

    # steady state: same shapes — any blocking sync inside the hot loop
    # now raises SyncViolation, and the tally must stay at zero
    before = SG.snapshot()
    with SG.forbidden():
        for seed in range(3, 8):
            pk, b = batch(seed)
            expected += int(hits[pk].sum())
            total_rows += sum(o.num_rows for o in _probe_driver(op, b))
    assert SG.take_delta(before).hot_loop_syncs == 0

    op.finish_input()
    while not op.is_finished():
        b = op.get_output()
        if b is not None:
            total_rows += b.compact().num_rows
    assert total_rows == expected


# ---------------------------------------------------------------------------
# query-level equivalence + observability


@pytest.mark.parametrize("sql,expected_via", [
    ("select count(*) from orders o join lineitem l "
     "on o.o_orderkey = l.l_orderkey", None),
    ("select count(*) from nation a join nation b "
     "on a.n_regionkey = b.n_regionkey", [(125,)]),
])
def test_query_equivalence_sync_free_vs_legacy(monkeypatch, sql, expected_via):
    from trino_tpu.runner import StandaloneQueryRunner

    results = {}
    for mode in ("1", "0"):
        monkeypatch.setenv("TRINO_TPU_SYNC_FREE", mode)
        results[mode] = StandaloneQueryRunner().execute(sql).rows()
    assert results["1"] == results["0"]
    if expected_via is not None:
        assert results["1"] == expected_via


def test_explain_analyze_reports_sync_stats():
    from trino_tpu.runner import StandaloneQueryRunner

    r = StandaloneQueryRunner()
    out = "\n".join(str(row[0]) for row in r.execute(
        "explain analyze select count(*) from nation a join nation b "
        "on a.n_regionkey = b.n_regionkey").rows())
    assert "host syncs" in out
