"""Device-side TPC-H generation must be bit-identical to the host generator.

The bench stages orders/lineitem via trino_tpu.connectors.tpch.
generate_table_device (columns born in accelerator memory, no tunnel
transfer); correctness of every oracle-diffed query depends on both
generators producing the same values from the same splitmix64 arithmetic.
"""

import numpy as np
import pytest

from trino_tpu.connectors.tpch import TpchConnector, generate_table_device

SF = 0.01


def _host_table(conn, table, cols):
    batches = []
    for s in conn.get_splits(table, 4, 1):
        src = conn.create_page_source(s, cols)
        while not src.is_finished():
            b = src.get_next_batch()
            if b is not None:
                batches.append(b)
    from trino_tpu.spi.batch import ColumnBatch

    return ColumnBatch.concat(batches)


def _decode(col, n):
    data = np.asarray(col.data)[:n]
    if col.dictionary is not None:
        return col.dictionary[data]
    return data


@pytest.mark.parametrize("table", ["orders", "lineitem"])
def test_device_matches_host(table):
    conn = TpchConnector(scale_factor=SF)
    cols = conn.get_table_schema(table).column_names()
    dev = generate_table_device(conn, table, cols)
    assert dev is not None
    host = _host_table(TpchConnector(scale_factor=SF), table, cols)
    n = host.num_rows
    live = np.asarray(dev.live) if dev.live is not None else None
    if live is not None:
        assert int(live.sum()) == n
        assert live[:n].all()
    for name in cols:
        d = _decode(dev.column(name), n)
        h = _decode(host.column(name), n)
        np.testing.assert_array_equal(
            d, h, err_msg=f"{table}.{name} device/host mismatch")


def test_unsupported_table_returns_none():
    conn = TpchConnector(scale_factor=SF)
    assert generate_table_device(conn, "customer", ["c_custkey"]) is None
