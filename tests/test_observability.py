"""Tracing spans, event listeners, access control, plugin loading
(reference: tracing/TracingMetadata.java:121, spi/eventlistener/
EventListener.java:16, security/AccessControlManager.java:97,
server/PluginManager.java)."""

import os
import textwrap

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.plugin import PluginManager
from trino_tpu.runner import Session, StandaloneQueryRunner
from trino_tpu.spi.eventlistener import EventListener
from trino_tpu.spi.security import (
    AccessDeniedError,
    DenyAllAccessControl,
    RuleBasedAccessControl,
    TableRule,
)


@pytest.fixture()
def runner():
    return StandaloneQueryRunner(default_catalog(scale_factor=0.01))


class Capture(EventListener):
    def __init__(self):
        self.created = []
        self.completed = []

    def query_created(self, e):
        self.created.append(e)

    def query_completed(self, e):
        self.completed.append(e)


def test_event_listener_success_and_failure(runner):
    cap = Capture()
    runner.event_listeners.add(cap)
    runner.execute("select count(*) from nation")
    assert len(cap.created) == 1 and len(cap.completed) == 1
    done = cap.completed[0]
    assert done.state == "FINISHED" and done.output_rows == 1
    assert done.wall_ms > 0
    with pytest.raises(Exception):
        runner.execute("select no_such_col from nation")
    assert cap.completed[-1].state == "FAILED"
    assert cap.completed[-1].error


def test_listener_exceptions_never_fail_queries(runner):
    class Broken(EventListener):
        def query_completed(self, e):
            raise RuntimeError("boom")

    runner.event_listeners.add(Broken())
    assert runner.execute("select 1").rows() == [(1,)]


def test_tracer_span_tree(runner):
    runner.execute("select count(*) from nation")
    root = runner.tracer.finished[-1]
    assert root.name == "trino.query"
    names = [c.name for c in root.children]
    assert "trino.planner" in names and "trino.execution" in names
    assert root.duration_ms >= max(c.duration_ms for c in root.children)
    assert "query_id" in root.attributes


def test_deny_all_access_control(runner):
    runner.access_control.add(DenyAllAccessControl())
    with pytest.raises(AccessDeniedError):
        runner.execute("select * from nation")


def test_rule_based_access_control():
    runner = StandaloneQueryRunner(
        default_catalog(scale_factor=0.01),
        session=Session(user="alice", default_catalog="memory"))
    runner.execute("create table t (v bigint)")  # allowed: default AllowAll
    runner.access_control.add(RuleBasedAccessControl([
        TableRule("alice", "tpch", "nation", {"SELECT"}),
        TableRule("alice", "memory", "*", {"ALL"}),
    ]))
    assert runner.execute(
        "select count(*) from tpch.nation").rows() == [(25,)]
    with pytest.raises(AccessDeniedError):
        runner.execute("select count(*) from tpch.region")
    runner.execute("insert into t values (1)")  # ALL on memory.*
    with pytest.raises(AccessDeniedError):
        runner.execute("insert into tpch.nation select * from tpch.nation")


def test_distributed_runner_observability():
    d = DistributedQueryRunner(default_catalog(scale_factor=0.01),
                               worker_count=2)
    cap = Capture()
    d.event_listeners.add(cap)
    d.execute("select count(*) from tpch.region")
    assert cap.completed[-1].state == "FINISHED"
    assert d.tracer.finished[-1].name == "trino.query"
    d.access_control.add(DenyAllAccessControl())
    with pytest.raises(AccessDeniedError):
        d.execute("select * from tpch.region")
    assert cap.completed[-1].state == "FAILED"


PLUGIN_SRC = textwrap.dedent('''
    from trino_tpu.plugin import Plugin
    from trino_tpu.connectors.memory import MemoryConnector

    class TinyPlugin(Plugin):
        def get_connector_factories(self):
            return {"tiny_memory": lambda config: MemoryConnector()}

    def plugin():
        return TinyPlugin()
''')


def test_plugin_loading(tmp_path):
    path = os.path.join(tmp_path, "tiny_plugin.py")
    with open(path, "w") as f:
        f.write(PLUGIN_SRC)
    cat = default_catalog(scale_factor=0.01)
    pm = PluginManager(cat)
    pm.load(path)
    assert "tiny_memory" in pm.connector_factories()
    pm.create_catalog("extra", "tiny_memory")
    runner = StandaloneQueryRunner(cat, session=Session(
        default_catalog="extra"))
    runner.execute("create table p (v bigint)")
    runner.execute("insert into p values (7)")
    assert runner.execute("select v from p").rows() == [(7,)]
    with pytest.raises(KeyError):
        pm.create_catalog("x", "nope")
