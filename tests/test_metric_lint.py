"""Tier-1 wiring for tools/lint_metric_names.py: every metric registration
in the tree carries a Prometheus-legal, ``trino_``-prefixed name (counters
end in ``_total``) and no name literal is registered at two sites."""

import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(ROOT, "tools", "lint_metric_names.py")


def _mod():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import lint_metric_names as L
    finally:
        sys.path.pop(0)
    return L


def test_metric_names_lint_clean():
    proc = subprocess.run([sys.executable, LINT], capture_output=True,
                          text=True, timeout=60)
    assert proc.returncode == 0, \
        f"metric naming violations:\n{proc.stdout}\n{proc.stderr}"


def test_lint_catches_planted_violations(tmp_path):
    """The lint actually fires (guards against pattern rot)."""
    L = _mod()
    bad = tmp_path / "bad.py"
    bad.write_text(
        'a = REGISTRY.counter("trino_good_total", "fine")\n'
        'b = REGISTRY.counter("scan_bytes_total", "no prefix")\n'
        'c = REGISTRY.counter("trino_scan_bytes", "no _total")\n'
        'd = REGISTRY.gauge("trino_bad-name", "illegal char")\n'
        'e = REGISTRY.gauge("nope", "exempt")  # metric-ok: test pragma\n')
    findings = L.lint_file(str(bad))
    assert len(findings) == 3  # good line + pragma line pass
    problems = {f[3] for f in findings}
    assert any("prefix" in p for p in problems)
    assert any("_total" in p for p in problems)
    assert any("illegal" in p for p in problems)


def test_lint_catches_duplicate_registration(tmp_path):
    L = _mod()
    pkg = tmp_path / "trino_tpu"
    pkg.mkdir()
    (pkg / "one.py").write_text(
        'a = REGISTRY.counter("trino_dup_total", "first")\n')
    (pkg / "two.py").write_text(
        'b = REGISTRY.counter("trino_dup_total", "second")\n')
    findings = L.run(str(tmp_path))
    assert len(findings) == 1
    assert "duplicate registration" in findings[0][3]


def test_real_registry_agrees_with_lint():
    """The lint's naming rules are the registry's own: names the lint
    rejects are names the registry raises on."""
    from trino_tpu.telemetry.metrics import MetricsRegistry

    import pytest

    r = MetricsRegistry()
    for bad, kind in [("scan_bytes_total", "counter"),
                      ("trino_scan_bytes", "counter"),
                      ("trino_bad-name", "gauge")]:
        with pytest.raises(ValueError):
            getattr(r, kind)(bad, "help")
