"""SQL parser tests: structure checks + the full TPC-H corpus."""

import pytest

from trino_tpu.connectors.tpch_queries import QUERIES
from trino_tpu.sql import ast
from trino_tpu.sql.parser import ParseError, parse_query, parse_statement


def test_simple_select():
    q = parse_query("select a, b + 1 as c from t where a > 10 order by c desc limit 5")
    spec = q.body
    assert len(spec.select) == 2
    assert spec.select[1].alias == "c"
    assert isinstance(spec.select[1].expr, ast.BinaryOp)
    assert isinstance(spec.where, ast.Comparison)
    assert q.limit == 5
    assert not q.order_by[0].ascending


def test_precedence():
    q = parse_query("select * from t where a = 1 or b = 2 and c < 3 + 4 * 5")
    w = q.body.where
    assert isinstance(w, ast.LogicalOp) and w.op == "OR"
    rhs = w.terms[1]
    assert isinstance(rhs, ast.LogicalOp) and rhs.op == "AND"
    cmp = rhs.terms[1]
    assert isinstance(cmp, ast.Comparison)
    add = cmp.right
    assert isinstance(add, ast.BinaryOp) and add.op == "+"
    assert isinstance(add.right, ast.BinaryOp) and add.right.op == "*"


def test_joins_and_aliases():
    q = parse_query(
        "select n1.n_name from nation n1 join nation n2 on n1.n_regionkey = n2.n_regionkey"
        " left join region on n1.n_regionkey = r_regionkey"
    )
    j = q.body.from_
    assert isinstance(j, ast.Join) and j.join_type == "LEFT"
    inner = j.left
    assert isinstance(inner, ast.Join) and inner.join_type == "INNER"
    assert inner.left == ast.Table("nation", "n1")


def test_implicit_cross_join():
    q = parse_query("select * from a, b, c where a.x = b.y")
    j = q.body.from_
    assert isinstance(j, ast.Join) and j.join_type == "CROSS"
    assert isinstance(j.left, ast.Join) and j.left.join_type == "CROSS"


def test_case_cast_extract_interval():
    q = parse_query(
        "select case when x = 1 then 'one' else 'other' end,"
        " cast(x as double), extract(year from d),"
        " d + interval '3' month from t"
    )
    c, cast, ext, add = [i.expr for i in q.body.select]
    assert isinstance(c, ast.Case) and c.operand is None and c.default is not None
    assert isinstance(cast, ast.Cast) and cast.type_name == "double"
    assert isinstance(ext, ast.Extract) and ext.field_ == "YEAR"
    assert isinstance(add, ast.BinaryOp) and isinstance(add.right, ast.IntervalLiteral)
    assert add.right.unit == "MONTH"


def test_not_binding():
    q = parse_query("select * from t where not a like 'x%' and b not in (1, 2)")
    w = q.body.where
    assert isinstance(w, ast.LogicalOp) and w.op == "AND"
    assert isinstance(w.terms[0], ast.Not)
    assert isinstance(w.terms[0].operand, ast.Like)
    assert isinstance(w.terms[1], ast.InList) and w.terms[1].negated


def test_exists_subqueries():
    q = parse_query(
        "select * from t where exists (select 1 from u where u.a = t.a)"
        " and x = (select max(y) from v)"
    )
    w = q.body.where
    assert isinstance(w.terms[0], ast.Exists)
    assert isinstance(w.terms[1].right, ast.ScalarSubquery)


def test_with_clause():
    q = parse_query("with r as (select a from t) select * from r where a > 0")
    assert len(q.with_) == 1 and q.with_[0].name == "r"


def test_quoted_identifiers_and_strings():
    q = parse_query('select "my col" from "my table" where s = \'it\'\'s\'')
    assert q.body.select[0].expr == ast.ColumnRef(("my col",))
    assert q.body.where.right == ast.StringLiteral("it's")


def test_errors_have_position():
    with pytest.raises(ParseError, match="line 1"):
        parse_query("select from t")
    with pytest.raises(ParseError):
        parse_query("select a from t where")
    with pytest.raises(ParseError, match="trailing"):
        parse_query("select a from t garbage garbage")


def test_statements():
    s = parse_statement("explain analyze select 1")
    assert isinstance(s, ast.Explain) and s.analyze
    s = parse_statement("create table x as select * from y")
    assert isinstance(s, ast.CreateTableAsSelect) and s.table == "x"
    s = parse_statement("insert into x select * from y")
    assert isinstance(s, ast.InsertInto)
    assert isinstance(parse_statement("show tables"), ast.ShowTables)


@pytest.mark.parametrize("qnum", sorted(QUERIES))
def test_parses_all_tpch(qnum):
    q = parse_query(QUERIES[qnum])
    assert isinstance(q, ast.Query)
    assert len(q.body.select) >= 1


def test_tpch_q1_shape():
    q = parse_query(QUERIES[1])
    assert len(q.body.select) == 10
    assert len(q.body.group_by) == 2
    assert len(q.order_by) == 2
    # where: l_shipdate <= date - interval
    w = q.body.where
    assert isinstance(w, ast.Comparison) and w.op == "<="
    assert isinstance(w.right, ast.BinaryOp) and w.right.op == "-"


def test_tpch_q19_or_of_ands():
    q = parse_query(QUERIES[19])
    w = q.body.where
    assert isinstance(w, ast.LogicalOp) and w.op == "OR" and len(w.terms) == 3
