"""Pallas kernels (ops/pallas_kernels.py) + the REAL-sum engine fast path
(exec/kernels.grouped_reduce).  Kernels run in interpret mode on the CPU
test mesh; the same programs compile for real TPU lanes."""

import numpy as np
import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.ops import pallas_kernels as PK
from trino_tpu.runner import StandaloneQueryRunner
from trino_tpu.testing.oracle import SqliteOracle, assert_same_rows

pytestmark = pytest.mark.skipif(
    not PK.pallas_available(), reason="pallas not importable")


def test_masked_segment_sum_matches_numpy():
    rng = np.random.default_rng(0)
    n, g = 5000, 7
    vals = rng.standard_normal(n).astype(np.float32)
    gid = rng.integers(0, g, n).astype(np.int32)
    live = rng.random(n) > 0.3
    out = np.asarray(PK.masked_segment_sum_f32(
        vals, gid, live, g, interpret=True))
    expected = np.array([
        vals[(gid == k) & live].sum() for k in range(g)], np.float32)
    np.testing.assert_allclose(out[:g], expected, rtol=1e-4)


def test_masked_segment_sum_dead_rows_beyond_groups():
    # dead rows carry gid >= num_groups (the grouping kernel's contract)
    vals = np.ones(2048, np.float32)
    gid = np.full(2048, 9, np.int32)
    gid[:100] = 0
    out = np.asarray(PK.masked_segment_sum_f32(
        vals, gid, None, 4, interpret=True))
    assert out[0] == 100.0
    assert out[1:4].sum() == 0.0


def test_engine_real_sum_uses_pallas(monkeypatch):
    import trino_tpu.exec.kernels as K

    calls = []
    orig = K._pallas_f32_sum

    def spy(*a, **kw):
        r = orig(*a, **kw)
        calls.append(r is not None)
        return r

    monkeypatch.setattr(K, "_pallas_f32_sum", spy)
    monkeypatch.setenv("TRINO_TPU_PALLAS", "force")  # interpret mode on CPU
    monkeypatch.setitem(K._PALLAS_STATE, "enabled", None)
    catalog = default_catalog(scale_factor=0.01)
    runner = StandaloneQueryRunner(catalog)
    oracle = SqliteOracle()
    conn = catalog.connector("tpch")
    schema = conn.get_table_schema("lineitem")
    cols = schema.column_names()
    batches = []
    for s in conn.get_splits("lineitem", 2, 1):
        src = conn.create_page_source(s, cols)
        while not src.is_finished():
            b = src.get_next_batch()
            if b is not None:
                batches.append(b)
    oracle.load_table("lineitem", batches)
    # group on a NUMERIC key: dictionary-coded keys now take the masked
    # small-group path (kernels.small_grouped_aggregate) and never reach
    # the pallas f32 segment-sum; a non-dictionary key keeps the sort-based
    # path where the pallas fast lane lives
    sql = ("select l_linenumber, sum(cast(l_quantity as real)) "
           "from lineitem group by l_linenumber")
    result = runner.execute(sql).rows()
    assert calls and any(calls), "REAL sum did not route through pallas"
    assert_same_rows(result, oracle.query(sql))
