"""ORDER BY expressions outside the select list (hidden sort channels,
pruned after the sort — Trino QueryPlanner orderingScheme)."""

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.runner import StandaloneQueryRunner
from trino_tpu.testing.oracle import SqliteOracle, assert_same_rows


@pytest.fixture(scope="module")
def harness():
    catalog = default_catalog(scale_factor=0.01)
    runner = StandaloneQueryRunner(catalog)
    dist = DistributedQueryRunner(catalog, worker_count=3)
    oracle = SqliteOracle()
    conn = catalog.connector("tpch")
    for t in ("nation", "orders"):
        schema = conn.get_table_schema(t)
        cols = schema.column_names()
        batches = []
        for s in conn.get_splits(t, 2, 1):
            src = conn.create_page_source(s, cols)
            while not src.is_finished():
                b = src.get_next_batch()
                if b is not None:
                    batches.append(b)
        oracle.load_table(t, batches)
    return runner, dist, oracle


QUERIES = [
    "select n_name from nation order by n_regionkey, n_name limit 7",
    "select n_name from nation order by n_regionkey * 2 + n_nationkey desc limit 5",
    "select o_orderdate from orders order by o_orderkey limit 3",
    # mix of projected and hidden keys
    "select n_regionkey, n_name from nation order by n_comment limit 4",
]


@pytest.mark.parametrize("sql", QUERIES)
def test_hidden_order_by(harness, sql):
    runner, dist, oracle = harness
    expected = oracle.query(sql)
    assert_same_rows(runner.execute(sql).rows(), expected, ordered=True)
    assert_same_rows(dist.execute(sql).rows(), expected, ordered=True)


def test_distinct_rejects_hidden_keys(harness):
    runner, _, _ = harness
    with pytest.raises(Exception, match="DISTINCT"):
        runner.execute("select distinct n_name from nation order by n_regionkey")
