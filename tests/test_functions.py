"""Scalar + aggregate function breadth vs the sqlite oracle
(reference: operator/scalar/*, operator/aggregation/*)."""

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.runner import StandaloneQueryRunner
from trino_tpu.testing.oracle import SqliteOracle, assert_same_rows

TABLES = ["nation", "region", "orders", "lineitem"]


@pytest.fixture(scope="module")
def harness():
    catalog = default_catalog(scale_factor=0.01)
    runner = StandaloneQueryRunner(catalog)
    dist = DistributedQueryRunner(catalog, worker_count=3)
    oracle = SqliteOracle()
    conn = catalog.connector("tpch")
    for t in TABLES:
        schema = conn.get_table_schema(t)
        cols = schema.column_names()
        batches = []
        for s in conn.get_splits(t, 2, 1):
            src = conn.create_page_source(s, cols)
            while not src.is_finished():
                b = src.get_next_batch()
                if b is not None:
                    batches.append(b)
        oracle.load_table(t, batches)
    return runner, dist, oracle


SCALAR_QUERIES = [
    # string functions through dictionary transforms
    "select n_name || '-' || n_comment from nation where n_regionkey = 1",
    "select concat(n_name, '/', r_name) from nation, region "
    "where n_regionkey = r_regionkey and n_nationkey < 5",
    "select replace(n_name, 'A', 'x') from nation",
    "select strpos(n_name, 'AN'), n_name from nation",
    "select n_name from nation where starts_with(n_name, 'I')",
    "select reverse(n_name) from nation where n_regionkey = 2",
    # date functions
    "select date_trunc('month', o_orderdate), count(*) from orders "
    "group by date_trunc('month', o_orderdate)",
    "select date_trunc('year', o_orderdate), date_trunc('quarter', o_orderdate), "
    "date_trunc('week', o_orderdate) from orders limit 50",
    "select day_of_week(o_orderdate), day_of_year(o_orderdate) from orders "
    "limit 50",
    # math
    "select sign(o_totalprice - 100000), mod(o_orderkey, 7) from orders limit 100",
    "select greatest(o_orderkey, o_custkey), least(o_orderkey, o_custkey) "
    "from orders limit 100",
    "select round(sin(o_orderkey), 4), round(cos(o_orderkey), 4) from orders limit 20",
    # conditional
    "select if(o_orderpriority = '1-URGENT', 1, 0), o_orderkey from orders limit 50",
]

AGG_QUERIES = [
    "select stddev(l_quantity), variance(l_quantity) from lineitem",
    "select var_pop(l_quantity), stddev_pop(l_quantity), var_samp(l_quantity), "
    "stddev_samp(l_quantity) from lineitem",
    "select l_returnflag, stddev(l_extendedprice), var_pop(l_discount) "
    "from lineitem group by l_returnflag",
    # single-row groups: var_samp NULL, var_pop 0
    "select o_orderkey, var_samp(o_totalprice), var_pop(o_totalprice) "
    "from orders where o_orderkey < 100 group by o_orderkey",
    "select bool_and(o_totalprice > 1000), bool_or(o_orderpriority = '1-URGENT') "
    "from orders",
    "select o_orderstatus, count_if(o_totalprice > 150000) from orders "
    "group by o_orderstatus",
]


@pytest.mark.parametrize("sql", SCALAR_QUERIES)
def test_scalar_functions(harness, sql):
    runner, _, oracle = harness
    assert_same_rows(runner.execute(sql).rows(), oracle.query(sql))


@pytest.mark.parametrize("sql", AGG_QUERIES)
def test_agg_functions(harness, sql):
    runner, _, oracle = harness
    assert_same_rows(runner.execute(sql).rows(), oracle.query(sql))


@pytest.mark.parametrize("sql", AGG_QUERIES)
def test_agg_functions_distributed(harness, sql):
    _, dist, oracle = harness
    assert_same_rows(dist.execute(sql).rows(), oracle.query(sql))


def test_approx_distinct(harness):
    """approx_distinct is implemented as an exact distinct count (valid
    within any approximation budget)."""
    runner, _, oracle = harness
    actual = runner.execute(
        "select o_orderstatus, approx_distinct(o_custkey) from orders "
        "group by o_orderstatus").rows()
    expected = oracle.query(
        "select o_orderstatus, count(distinct o_custkey) from orders "
        "group by o_orderstatus")
    assert_same_rows(actual, expected)


def test_geometric_mean(harness):
    runner, _, oracle = harness
    actual = runner.execute(
        "select geometric_mean(l_quantity) from lineitem").rows()
    expected = oracle.query(
        "select exp(avg(ln(l_quantity))) from lineitem")
    assert_same_rows(actual, expected)


def test_arbitrary_every(harness):
    runner, _, _ = harness
    rows = runner.execute(
        "select arbitrary(n_regionkey), every(n_regionkey >= 0) from nation").rows()
    assert rows[0][1] == 1 or rows[0][1] is True


def test_fromless_scalars(harness):
    runner, _, _ = harness
    rows = runner.execute(
        "select round(pi(), 4), round(e(), 4), round(degrees(pi()), 1), "
        "truncate(2.71), round(cbrt(27.0), 6), log2(8)").rows()
    assert [float(x) for x in rows[0]] == [3.1416, 2.7183, 180.0, 2.0, 3.0, 3.0]


def test_string_breadth_literals(harness):
    """split_part/lpad/rpad/repeat/translate/codepoint/position (no sqlite
    equivalents; literal expectations; reference: operator/scalar/
    StringFunctions)."""
    runner, dist, _ = harness
    sql = ("select split_part('a-b-c', '-', 2), lpad('x', 4, '*'), "
           "rpad('x', 3, 'ab'), repeat('ab', 3), "
           "translate('hello', 'el', 'ip'), codepoint('A')")
    # repeat(element, count) -> array(T) (RepeatFunction.java semantics)
    expect = [("b", "***x", "xab", ["ab", "ab", "ab"], "hippo", 65)]
    assert runner.execute(sql).rows() == expect
    assert dist.execute(sql).rows() == expect
    assert runner.execute(
        "select n_name from nation where split_part(n_name, ' ', 1) = 'UNITED' "
        "order by 1").rows() == [("UNITED KINGDOM",), ("UNITED STATES",)]
    # truncation + 1-based position semantics
    assert runner.execute(
        "select lpad('abcdef', 3), position('AN', n_name) from nation "
        "where n_name = 'CANADA'").rows() == [("abc", 2)]


def test_string_breadth_trino_semantics(harness):
    runner, _, _ = harness
    import pytest as _pytest

    # split_part: NULL past the last field; empty delimiter rejected
    assert runner.execute(
        "select split_part('a-b', '-', 3)").rows() == [(None,)]
    with _pytest.raises(Exception):
        runner.execute("select split_part('abc', '', 1)")
    # translate: first duplicate wins
    assert runner.execute(
        "select translate('a', 'aa', 'bc')").rows() == [("b",)]
    # pad: negative size / empty fill rejected
    with _pytest.raises(Exception):
        runner.execute("select lpad('abc', -2, '*')")
    with _pytest.raises(Exception):
        runner.execute("select rpad('x', 5, '')")
    # codepoint: NULL unless exactly one character
    assert runner.execute(
        "select codepoint('AB'), codepoint('A')").rows() == [(None, 65)]
