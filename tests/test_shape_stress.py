"""Shape-bucket boundary stress (SURVEY §7 hard part 1: bucketed static
shapes + masked overflow are the single biggest divergence risk).

Exercises exact power-of-two bucket edges (n, n±1), group counts crossing
the masked-aggregation and small-codes caps, empty mesh partitions, and
join fan-outs at expansion-bucket edges — all oracle-checked."""

import numpy as np
import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.exec import kernels as K
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.runner import Session, StandaloneQueryRunner


def _runner():
    return StandaloneQueryRunner(default_catalog(scale_factor=0.01),
                                 session=Session(default_catalog="memory"))


@pytest.mark.parametrize("n", [7, 8, 9, 127, 128, 129, 4095, 4096, 4097])
def test_row_counts_at_bucket_edges(n):
    r = _runner()
    r.execute(f"create table be{n} (k bigint, v bigint)")
    rows = ", ".join(f"({i % 5}, {i})" for i in range(n))
    r.execute(f"insert into be{n} values {rows}")
    got = r.execute(f"select k, count(*), sum(v), min(v), max(v) "
                    f"from be{n} group by k order by k").rows()
    ks = [i % 5 for i in range(n)]
    for k, cnt, s, lo, hi in got:
        idx = [i for i in range(n) if ks[i] == k]
        assert cnt == len(idx) and s == sum(idx)
        assert lo == min(idx) and hi == max(idx)
    # filters leaving exactly 0 / 1 / n-1 live rows
    assert r.execute(f"select count(*) from be{n} where v < 0").rows() == [(0,)]
    assert r.execute(f"select count(*) from be{n} where v = 0").rows() == [(1,)]
    assert r.execute(
        f"select count(*) from be{n} where v > 0").rows() == [(n - 1,)]


@pytest.mark.parametrize("g", [
    K.MASKED_AGG_LIMIT - 1, K.MASKED_AGG_LIMIT, K.MASKED_AGG_LIMIT + 1])
def test_group_counts_across_masked_cap(g):
    """Dictionary-key group spaces at the masked-reduction cap boundary:
    the masked, codes-sort and general lexsort paths must agree."""
    r = _runner()
    r.execute("create table gc (s varchar, v bigint)")
    n = 3 * g
    rows = ", ".join(f"('k{i % g:05d}', {i})" for i in range(n))
    r.execute(f"insert into gc values {rows}")
    got = r.execute("select s, count(*), sum(v) from gc group by s").rows()
    assert len(got) == g
    total = sum(c for _, c, _ in got)
    assert total == n
    byk = {s: (c, sv) for s, c, sv in got}
    expect0 = [i for i in range(n) if i % g == 0]
    assert byk["k00000"] == (len(expect0), sum(expect0))
    r.execute("drop table gc")


def test_empty_partitions_on_mesh():
    """8 tasks over a 3-row table: most tasks see zero splits/rows; the
    PARTIAL->FINAL pipeline must still produce exact results."""
    dist = DistributedQueryRunner(
        default_catalog(scale_factor=0.01), worker_count=8,
        session=Session(default_catalog="memory", node_count=8))
    dist.execute("create table tiny (k bigint)")
    dist.execute("insert into tiny values (1), (2), (2)")
    assert dist.execute(
        "select k, count(*) from tiny group by k order by k").rows() == [
        (1, 1), (2, 2)]
    assert dist.execute("select count(*), sum(k) from tiny").rows() == [(3, 5)]
    # empty input to a global aggregate on every task
    assert dist.execute(
        "select count(*), sum(k) from tiny where k > 99").rows() == [(0, None)]


@pytest.mark.parametrize("fanout", [1, 2, 7, 8, 9])
def test_join_fanout_at_expansion_edges(fanout):
    """Join candidate totals right at the pair-expansion bucket edges."""
    r = _runner()
    r.execute(f"create table jl{fanout} (k bigint)")
    r.execute(f"insert into jl{fanout} values (1), (2)")
    r.execute(f"create table jr{fanout} (k bigint, v bigint)")
    rows = ", ".join(f"(1, {i})" for i in range(fanout)) + ", (3, 99)"
    r.execute(f"insert into jr{fanout} values {rows}")
    got = r.execute(
        f"select count(*), sum(v) from jl{fanout} l join jr{fanout} r "
        f"on l.k = r.k").rows()
    assert got == [(fanout, sum(range(fanout)))]


def test_distinct_and_topn_at_edges():
    r = _runner()
    r.execute("create table de (k bigint)")
    n = 1024  # exactly a bucket
    rows = ", ".join(f"({i % 256})" for i in range(n))
    r.execute(f"insert into de values {rows}")
    assert r.execute("select count(distinct k) from de").rows() == [(256,)]
    top = r.execute("select k from de order by k desc limit 8").rows()
    assert [t[0] for t in top] == [255] * 4 + [254] * 4
