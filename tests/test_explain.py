"""EXPLAIN / EXPLAIN ANALYZE / SHOW statements (reference:
operator/ExplainAnalyzeOperator.java, sql/planner/planprinter/PlanPrinter)."""

from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.runner import StandaloneQueryRunner


def _text(result):
    return "\n".join(r[0] for r in result.rows())


def test_explain_plan_text():
    r = StandaloneQueryRunner()
    out = _text(r.execute("explain select n_name from nation where n_regionkey = 1"))
    assert "TableScan" in out and "Output" in out
    assert "ms" not in out  # no timings without ANALYZE


def test_explain_analyze_standalone():
    r = StandaloneQueryRunner()
    out = _text(r.execute(
        "explain analyze select n_regionkey, count(*) from nation "
        "group by n_regionkey"))
    assert "Aggregate" in out
    assert "HashAggregationOperator" in out
    assert "total:" in out
    assert "out 5 rows" in out  # 5 region groups


def test_explain_analyze_distributed():
    d = DistributedQueryRunner(worker_count=2)
    out = _text(d.execute(
        "explain analyze select n_regionkey, count(*) from nation "
        "group by n_regionkey"))
    assert "Fragment" in out
    assert "fragment 0 task 0" in out
    assert "RemoteExchangeSourceOperator" in out


def test_show_tables_and_columns():
    r = StandaloneQueryRunner()
    tables = [row[0] for row in r.execute("show tables").rows()]
    assert "nation" in tables and "lineitem" in tables
    cols = _text(r.execute("show columns from nation"))
    assert "n_nationkey bigint" in cols
    assert "n_name varchar" in cols
