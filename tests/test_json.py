"""JSON functions (reference: operator/scalar/JsonFunctions,
json/JsonPathEvaluator.java): path evaluation over dictionary-encoded
varchar, NULL-on-error semantics."""

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.ops.json_fns import eval_json_path, parse_json_path
from trino_tpu.runner import Session, StandaloneQueryRunner


@pytest.fixture(scope="module")
def runner():
    r = StandaloneQueryRunner(default_catalog(scale_factor=0.01),
                              session=Session(default_catalog="memory"))
    r.execute("create table j (id bigint, doc varchar)")
    r.execute("""insert into j values
        (1, '{"a": 1, "b": {"c": "x"}, "arr": [10, 20, 30]}'),
        (2, '{"a": 2.5, "b": {"c": "y"}, "arr": []}'),
        (3, 'not json'),
        (4, null)""")
    return r


def test_path_parser():
    assert parse_json_path("$.a.b") == ["a", "b"]
    assert parse_json_path("$.arr[2].x") == ["arr", 2, "x"]
    assert parse_json_path('$["k"]') == ["k"]
    with pytest.raises(ValueError):
        parse_json_path("a.b")


def test_eval_path():
    doc = '{"a": {"b": [1, 2]}}'
    assert eval_json_path(doc, ["a", "b", 1]) == 2
    assert eval_json_path(doc, ["a", "x"]) is None
    assert eval_json_path("garbage", ["a"]) is None


def test_json_extract_scalar(runner):
    assert runner.execute(
        "select id, json_extract_scalar(doc, '$.b.c') from j order by id"
    ).rows() == [(1, "x"), (2, "y"), (3, None), (4, None)]
    # numbers render as text; integral floats without trailing .0
    assert runner.execute(
        "select json_extract_scalar(doc, '$.a') from j where id <= 2 "
        "order by id").rows() == [("1",), ("2.5",)]
    # objects/arrays -> NULL for the scalar variant
    assert runner.execute(
        "select json_extract_scalar(doc, '$.b') from j where id = 1"
    ).rows() == [(None,)]


def test_json_extract(runner):
    assert runner.execute(
        "select json_extract(doc, '$.b') from j where id = 1"
    ).rows() == [('{"c": "x"}',)]
    assert runner.execute(
        "select json_extract(doc, '$.arr[1]') from j where id = 1"
    ).rows() == [("20",)]


def test_json_array_length(runner):
    assert runner.execute(
        "select id, json_array_length(json_extract(doc, '$.arr')) from j "
        "order by id").rows() == [(1, 3), (2, 0), (3, None), (4, None)]


def test_json_in_predicate(runner):
    assert runner.execute(
        "select id from j where json_extract_scalar(doc, '$.b.c') = 'y'"
    ).rows() == [(2,)]
