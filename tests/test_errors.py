"""Masked-lane expression errors (reference: StandardErrorCode +
AbstractTestQueries error cases).  Vectorized evaluation computes every lane
of every branch, so DIVISION_BY_ZERO / overflow surface through a deferred
error channel: lanes record errors, conditionals mask unselected branches,
and the runner raises before returning any result.  The sqlite oracle cannot
check these (sqlite yields NULL), hence explicit cases."""

import numpy as np
import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.runner import Session, StandaloneQueryRunner


@pytest.fixture(scope="module")
def runner():
    return StandaloneQueryRunner(default_catalog(scale_factor=0.01))


def test_integer_division_by_zero_raises(runner):
    with pytest.raises(Exception, match="DIVISION_BY_ZERO"):
        runner.execute("select 1 / 0")


def test_decimal_division_by_zero_raises(runner):
    # a DECIMAL operand keeps exact-arithmetic semantics (raise), even
    # though the engine folds decimal division to double lanes; bare
    # numeric literals type as DOUBLE here and follow double semantics
    with pytest.raises(Exception, match="DIVISION_BY_ZERO"):
        runner.execute(
            "select o_totalprice / (o_totalprice - o_totalprice) from orders")


def test_explain_of_failing_query_plans_without_evaluating(runner):
    # EXPLAIN never runs the lanes, so a query whose execution raises
    # DIVISION_BY_ZERO still yields a plan
    rows = runner.execute(
        "explain select o_totalprice / (o_totalprice - o_totalprice) "
        "from orders").rows()
    assert rows and any("orders" in str(r[0]) for r in rows)


def test_modulus_by_zero_raises(runner):
    with pytest.raises(Exception, match="DIVISION_BY_ZERO"):
        runner.execute("select 7 % 0")


def test_division_by_zero_in_table_expression(runner):
    with pytest.raises(Exception, match="DIVISION_BY_ZERO"):
        runner.execute(
            "select o_orderkey / (o_orderkey - o_orderkey) from orders")


def test_null_operand_is_null_not_error(runner):
    assert runner.execute("select 1 / null").rows() == [(None,)]
    assert runner.execute("select null / 0").rows() == [(None,)]


def test_case_masks_unselected_branch(runner):
    # every x = 0 lane takes the THEN branch; 1/x must not raise there
    rows = runner.execute(
        "select sum(case when o_shippriority = 0 then 0 "
        "else 100 / o_shippriority end) from orders").rows()
    assert rows == [(0,)]


def test_if_branch_error_still_raises_when_selected(runner):
    with pytest.raises(Exception, match="DIVISION_BY_ZERO"):
        runner.execute(
            "select case when o_shippriority = 0 then 1 / o_shippriority "
            "else 0 end from orders")


def test_where_clause_masks_projection_errors(runner):
    # rows with o_shippriority = 0 are filtered before the projection runs
    rows = runner.execute(
        "select count(*) from (select 1 / o_shippriority x from orders "
        "where o_shippriority <> 0)").rows()
    assert rows == [(0,)]


def test_failing_where_clause_raises(runner):
    with pytest.raises(Exception, match="DIVISION_BY_ZERO"):
        runner.execute(
            "select count(*) from orders where 1 / o_shippriority > 0")


def test_and_short_circuit_masks_right_term(runner):
    rows = runner.execute(
        "select count(*) from orders "
        "where o_shippriority <> 0 and 10 / o_shippriority > 0").rows()
    assert rows == [(0,)]


def test_bigint_overflow_raises(runner):
    with pytest.raises(Exception, match="NUMERIC_VALUE_OUT_OF_RANGE"):
        runner.execute(
            "select 9223372036854775807 + o_orderkey from orders")


def test_bigint_multiply_overflow_raises(runner):
    with pytest.raises(Exception, match="NUMERIC_VALUE_OUT_OF_RANGE"):
        runner.execute(
            "select (o_orderkey + 4611686018427387904) * 4 from orders")


def test_error_in_million_row_masked_batch():
    """The error channel works at scale inside a live-masked batch: exactly
    one poisoned lane in ~60k rows (bucket-padded with dead lanes) raises."""
    catalog = default_catalog(scale_factor=0.01)
    r = StandaloneQueryRunner(catalog)
    with pytest.raises(Exception, match="DIVISION_BY_ZERO"):
        r.execute(
            "select sum(100 / (l_orderkey - 7)) from lineitem")
    # and the guarded variant completes
    ok = r.execute(
        "select count(*) from lineitem "
        "where l_orderkey <> 7 and 100 / (l_orderkey - 7) >= 0").rows()
    assert ok[0][0] > 0


def test_distributed_division_error_propagates():
    from trino_tpu.execution.distributed_runner import DistributedQueryRunner

    catalog = default_catalog(scale_factor=0.01)
    dist = DistributedQueryRunner(
        catalog, worker_count=2, session=Session(node_count=2))
    with pytest.raises(Exception, match="DIVISION_BY_ZERO"):
        dist.execute("select o_orderkey / (o_orderkey * 0) from orders")
