"""Memory accounting + HBM->host revocation (reference:
memory/MemoryPool.java:44, execution/MemoryRevokingScheduler.java:47,
lib/trino-memory-context)."""

import numpy as np
import pytest

from trino_tpu.exec.operators import SortOperator
from trino_tpu.exec.revoking import TaskMemoryContext, batch_device_nbytes
from trino_tpu.planner.plan import SortKey
from trino_tpu.runner import Session, StandaloneQueryRunner
from trino_tpu.spi.batch import Column, ColumnBatch
from trino_tpu.spi.memory import (
    AggregatedMemoryContext,
    ExceededMemoryLimitError,
    MemoryPool,
)
from trino_tpu.spi.types import BIGINT


def test_pool_and_context_roundtrip():
    pool = MemoryPool("hbm", 1000)
    root = AggregatedMemoryContext(pool=pool)
    a = root.new_local("a")
    b = root.new_local("b")
    a.set_bytes(400)
    b.set_bytes(500)
    assert pool.reserved == 900
    with pytest.raises(ExceededMemoryLimitError):
        a.set_bytes(600)
    a.set_bytes(0)
    b.set_bytes(0)
    assert pool.reserved == 0


def _device_batch(n):
    import jax.numpy as jnp

    return ColumnBatch(
        ["k"], [Column(BIGINT, jnp.arange(n, dtype=jnp.int64))])


def test_revocation_evicts_device_batches_to_host():
    mem = TaskMemoryContext(hbm_limit_bytes=64 * 1024)
    op = SortOperator([SortKey(0)])
    op.attach_memory(mem)
    # each batch = 8KB on device; 64KB pool forces eviction along the way
    for _ in range(20):
        op.add_input(_device_batch(1024))
    assert getattr(op, "spill_count", 0) >= 1
    assert mem.reserved_bytes() <= 64 * 1024
    # evicted batches are host numpy now
    host = sum(1 for b in op._batches if batch_device_nbytes(b) == 0)
    assert host >= 1
    op.finish_input()
    out = op.get_output()
    # device sort emits a bucket-padded batch; live rows carry the data
    assert out.live_count == 20 * 1024  # nothing lost


def test_disk_spill_tier():
    """Host-buffered batches over the threshold go to a serde spill file
    and come back at finish with identical results."""
    import trino_tpu.exec.operators as OPS

    session = Session(spill_to_disk_bytes=64 * 1024)
    runner = StandaloneQueryRunner(session=session)
    spills = []
    orig = OPS.BufferedInputMixin._maybe_spill_to_disk

    def spy(self):
        orig(self)
        sp = getattr(self, "_spiller", None)
        if sp is not None and sp.pages_spilled:
            spills.append(sp.pages_spilled)

    OPS.BufferedInputMixin._maybe_spill_to_disk = spy
    try:
        rows = runner.execute(
            "select l_orderkey, o_orderdate from lineitem, orders "
            "where l_orderkey = o_orderkey order by l_orderkey, o_orderdate "
            "limit 5").rows()
    finally:
        OPS.BufferedInputMixin._maybe_spill_to_disk = orig
    assert spills, "expected disk spills with a 64KB threshold"
    plain = StandaloneQueryRunner().execute(
        "select l_orderkey, o_orderdate from lineitem, orders "
        "where l_orderkey = o_orderkey order by l_orderkey, o_orderdate "
        "limit 5").rows()
    assert rows == plain


def test_spiller_roundtrip():
    import numpy as np

    from trino_tpu.exec.spill import Spiller
    from trino_tpu.spi.batch import Column, ColumnBatch

    sp = Spiller()
    batches = [
        ColumnBatch(["x"], [Column(BIGINT, np.arange(i, i + 5, dtype=np.int64))])
        for i in range(0, 20, 5)
    ]
    for b in batches:
        sp.spill(b)
    back = list(sp.read_back())
    sp.close()
    assert [b.to_pylist() for b in back] == [b.to_pylist() for b in batches]


def test_query_larger_than_pool_completes():
    """A join+sort query whose device buffers exceed a tiny HBM pool must
    finish (by spilling to host RAM) with correct results."""
    session = Session(hbm_limit_bytes=256 * 1024)  # 256 KB
    runner = StandaloneQueryRunner(session=session)
    rows = runner.execute(
        "select l_orderkey, count(*) from lineitem, orders "
        "where l_orderkey = o_orderkey group by l_orderkey "
        "order by l_orderkey limit 5").rows()
    assert len(rows) == 5
    unlimited = StandaloneQueryRunner()
    assert rows == unlimited.execute(
        "select l_orderkey, count(*) from lineitem, orders "
        "where l_orderkey = o_orderkey group by l_orderkey "
        "order by l_orderkey limit 5").rows()


def test_partitioned_state_spill_agg():
    """Q1-style aggregation at a forced tiny disk budget: the operator
    pre-aggregates to mergeable states, hash-partitions them to spill
    files, and merges partition-by-partition at finish — results exact,
    spill_count > 0 (reference: SpillableHashAggregationBuilder.java)."""
    import trino_tpu.exec.operators as OPS
    from trino_tpu.connectors.catalog import default_catalog
    from trino_tpu.runner import StandaloneQueryRunner
    from trino_tpu.testing.oracle import assert_same_rows

    spills = []
    orig = OPS.HashAggregationOperator._spill_states

    def spy(self):
        orig(self)
        spills.append(self.spill_count)

    session = Session(default_catalog="tpch", spill_to_disk_bytes=1)
    runner = StandaloneQueryRunner(default_catalog(scale_factor=0.05),
                                   session=session)
    baseline = StandaloneQueryRunner(default_catalog(scale_factor=0.05))
    sql = ("select l_returnflag, l_linestatus, sum(l_quantity), "
           "avg(l_extendedprice), count(*), min(l_discount), "
           "max(l_shipdate) from lineitem "
           "group by l_returnflag, l_linestatus order by 1, 2")
    OPS.HashAggregationOperator._spill_states = spy
    try:
        got = runner.execute(sql).rows()
    finally:
        OPS.HashAggregationOperator._spill_states = orig
    assert spills, "agg never spilled despite the 1-byte budget"
    want = baseline.execute(sql).rows()
    assert_same_rows(got, want, ordered=True)


def test_partitioned_spill_high_cardinality():
    """High-cardinality grouped sum under spill: groups cross spill events
    and must merge exactly across partitions."""
    import trino_tpu.exec.operators as OPS
    from trino_tpu.connectors.catalog import default_catalog
    from trino_tpu.runner import StandaloneQueryRunner
    from trino_tpu.testing.oracle import assert_same_rows

    session = Session(default_catalog="tpch", spill_to_disk_bytes=1,
                      splits_per_node=4)
    runner = StandaloneQueryRunner(default_catalog(scale_factor=0.02),
                                   session=session)
    baseline = StandaloneQueryRunner(default_catalog(scale_factor=0.02))
    sql = ("select l_orderkey, sum(l_quantity), count(*) from lineitem "
           "group by l_orderkey")
    got = runner.execute(sql).rows()
    want = baseline.execute(sql).rows()
    assert_same_rows(got, want, ordered=False)
