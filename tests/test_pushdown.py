"""Connector pushdown negotiation: LIMIT into the scan (reference:
iterative/rule/PushLimitIntoTableScan.java + ConnectorMetadata.applyLimit).
The scan stops opening splits once the pushed bound is satisfied; the
engine Limit re-enforces exactness."""

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.planner.plan import Limit, TableScan
from trino_tpu.runner import Session, StandaloneQueryRunner


def _find(node, kind):
    if isinstance(node, kind):
        return node
    for c in node.children:
        got = _find(c, kind)
        if got is not None:
            return got
    return None


def test_limit_lands_on_scan_and_stops_reads():
    catalog = default_catalog(scale_factor=0.01)
    runner = StandaloneQueryRunner(
        catalog, session=Session(splits_per_node=8))
    plan = runner.create_plan("select l_orderkey from lineitem limit 3")
    scan = _find(plan, TableScan)
    assert scan.limit == 3
    assert _find(plan, Limit) is not None  # exactness stays with the engine

    conn = catalog.connector("tpch")
    opened = []
    orig = type(conn).create_page_source

    def spy(self, split, columns, **kw):
        opened.append(split)
        return orig(self, split, columns, **kw)

    type(conn).create_page_source = spy
    try:
        rows = runner.execute("select l_orderkey from lineitem limit 3").rows()
    finally:
        type(conn).create_page_source = orig
    assert len(rows) == 3
    assert len(opened) == 1, f"scan opened {len(opened)} splits for LIMIT 3"


def test_limit_not_pushed_through_filter():
    runner = StandaloneQueryRunner(default_catalog(scale_factor=0.01))
    plan = runner.create_plan(
        "select l_orderkey from lineitem where l_quantity > 10 limit 3")
    scan = _find(plan, TableScan)
    assert scan.limit is None  # a filter between limit and scan blocks it
    rows = runner.execute(
        "select l_orderkey from lineitem where l_quantity > 10 limit 3").rows()
    assert len(rows) == 3


def test_planning_is_side_effect_free():
    """EXPLAIN/plan must not leak the pushed bound anywhere stateful: the
    same runner returns full results after planning a LIMIT query."""
    runner = StandaloneQueryRunner(default_catalog(scale_factor=0.01))
    runner.create_plan("select n_name from nation limit 2")
    assert runner.execute("select count(*) from nation").rows() == [(25,)]
