"""The three-tier cache plane (trino_tpu/caching/): plan-cache hits skip
planning, the versioned result cache never serves a stale row past an
INSERT, planning-env flips miss, the executable registry is bounded and
evictable, warm keys survive a (subprocess-simulated) worker restart, the
``=0`` kill switches reproduce legacy behavior bit-for-bit, the
``system.runtime.caches`` table, and the tools/lint_cache_bounds.py
contract."""

import json
import os
import subprocess
import sys

import pytest

from trino_tpu import caching
from trino_tpu.caching import executable_cache, plan_cache, result_cache
from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.runner import Session, StandaloneQueryRunner
from trino_tpu.telemetry import journal

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_caches(tmp_path, monkeypatch):
    monkeypatch.setenv("TRINO_TPU_JOURNAL_DIR", str(tmp_path / "journal"))
    for knob in ("TRINO_TPU_PLAN_CACHE", "TRINO_TPU_RESULT_CACHE",
                 "TRINO_TPU_HASH_IMPL"):
        monkeypatch.delenv(knob, raising=False)
    caching.reset_for_test()
    journal.reset_for_test()
    yield
    caching.reset_for_test()
    journal.reset_for_test()


@pytest.fixture()
def runner():
    return StandaloneQueryRunner(
        default_catalog(scale_factor=0.01),
        session=Session(default_catalog="memory"))


# ------------------------------------------------------- Tier A: plan cache


def test_repeated_text_hits_plan_and_result_tiers(runner):
    q = "select count(*) from tpch.tiny.region"
    first = runner.execute(q).rows()
    hits0 = plan_cache.stats()["hits"]
    rhits0 = result_cache.stats()["hits"]
    assert runner.execute(q).rows() == first
    assert plan_cache.stats()["hits"] == hits0 + 1
    assert result_cache.stats()["hits"] == rhits0 + 1


def test_planning_env_flip_misses_plan_cache(runner):
    q = "select n_regionkey, count(*) from tpch.tiny.nation " \
        "group by n_regionkey"
    runner.execute(q)
    assert plan_cache.lookup(q, runner.session, runner.catalog) is not None
    # TRINO_TPU_HASH_IMPL steers the optimizer — a cached plan built under
    # the other impl must not be reused
    flipped = "sort" if os.environ.get("TRINO_TPU_HASH_IMPL") != "sort" \
        else "hash"
    os.environ["TRINO_TPU_HASH_IMPL"] = flipped
    try:
        assert plan_cache.lookup(q, runner.session, runner.catalog) is None
    finally:
        del os.environ["TRINO_TPU_HASH_IMPL"]
    assert plan_cache.lookup(q, runner.session, runner.catalog) is not None


def test_ddl_invalidates_cached_plans(runner):
    runner.execute("create table g as select n_nationkey from "
                   "tpch.tiny.nation")
    q = "select count(*) from g"
    assert runner.execute(q).rows() == [(25,)]
    assert plan_cache.lookup(q, runner.session, runner.catalog) is not None
    runner.execute("drop table g")
    # generation bump: the cached plan must not resolve the dropped table
    assert plan_cache.lookup(q, runner.session, runner.catalog) is None


# --------------------------------------------- Tier C: versioned result cache


def test_insert_mutation_never_serves_stale_results(runner):
    runner.execute("create table t as select n_nationkey from "
                   "tpch.tiny.nation")
    q = "select count(*) from t"
    assert runner.execute(q).rows() == [(25,)]
    assert runner.execute(q).rows() == [(25,)]
    assert result_cache.stats()["hits"] >= 1
    runner.execute("insert into t select n_nationkey from tpch.tiny.nation "
                   "where n_regionkey = 1")
    # the version vector moved: a hit here would be a stale serve
    assert runner.execute(q).rows() == [(30,)]
    assert result_cache.stats()["invalidations"] >= 1
    # and the post-mutation result is itself cacheable again
    rhits = result_cache.stats()["hits"]
    assert runner.execute(q).rows() == [(30,)]
    assert result_cache.stats()["hits"] == rhits + 1


def test_result_cache_byte_budget_evicts(runner, monkeypatch):
    monkeypatch.setenv("TRINO_TPU_RESULT_CACHE_BYTES", "4096")
    for i in range(40):
        runner.execute(f"select n_nationkey + {i} from tpch.tiny.nation")
    s = result_cache.stats()
    assert s["bytes"] <= 4096
    assert s["evictions"] > 0


# --------------------------------------------- Tier B: executable registry


def test_exec_registry_is_bounded_and_evicts():
    built = []

    @executable_cache.jit_memo("test.evict_probe", maxsize=2)
    def build(x):
        built.append(x)
        return x * 10

    assert build(1) == 10 and build(2) == 20
    assert build(1) == 10  # hit — no rebuild
    assert built == [1, 2]
    assert build(3) == 30  # evicts key 2 (LRU)
    s = build.stats()
    assert s["entries"] == 2
    assert s["evictions"] == 1
    assert build(2) == 20  # re-built after eviction
    assert built == [1, 2, 3, 2]


def test_warm_keys_survive_restart(tmp_path):
    """Process 1 runs a query and journals its memo keys; process 2 (a
    'rebooted worker') replays them into live wrappers before any query."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               TRINO_TPU_JOURNAL_DIR=os.environ["TRINO_TPU_JOURNAL_DIR"])
    out = subprocess.run([sys.executable, "-c", _CHILD_WARM_WRITE],
                         cwd=REPO, env=env, capture_output=True, text=True,
                         timeout=300)
    assert "WRITE_OK" in out.stdout, out.stderr[-2000:]
    out = subprocess.run([sys.executable, "-c", _CHILD_WARM_BOOT],
                         cwd=REPO, env=env, capture_output=True, text=True,
                         timeout=300)
    assert "BOOT_OK" in out.stdout, out.stderr[-2000:]


_CHILD_WARM_WRITE = r"""
import json, os
from trino_tpu.caching import executable_cache as ec
from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.runner import StandaloneQueryRunner

r = StandaloneQueryRunner(default_catalog(scale_factor=0.01))
r.execute("select r_name, count(*) from tpch.tiny.region group by r_name")
ec.flush_warm_keys()
with open(ec.warm_file_path(), encoding="utf-8") as f:
    assert len(json.load(f)["keys"]) > 0
print("WRITE_OK")
"""

_CHILD_WARM_BOOT = r"""
from trino_tpu.caching import executable_cache as ec

n = ec.warm_at_boot()
assert n > 0, "expected journaled keys to re-instantiate wrappers"
assert sum(r["entries"] for r in ec.registry_stats()) >= n
print("BOOT_OK")
"""


# --------------------------------------------------- kill switches: =0 legacy


def test_disabled_tiers_match_enabled_results(runner):
    """Plan/result knobs are per-lookup; EXEC is decoration-time, so the
    full =0 stack runs in a subprocess and must be bit-for-bit."""
    q = ("select n_regionkey, count(*) from tpch.tiny.nation "
         "group by n_regionkey order by n_regionkey")
    enabled_rows = [list(r) for r in runner.execute(q).rows()]
    assert [list(r) for r in runner.execute(q).rows()] == enabled_rows

    env = dict(os.environ, JAX_PLATFORMS="cpu", TRINO_TPU_PLAN_CACHE="0",
               TRINO_TPU_RESULT_CACHE="0", TRINO_TPU_EXEC_CACHE="0")
    out = subprocess.run(
        [sys.executable, "-c", _CHILD_DISABLED % (q, q)], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=300)
    assert "DISABLED_OK" in out.stdout, out.stderr[-2000:]
    child_rows = json.loads(out.stdout.splitlines()[0])
    assert child_rows == enabled_rows


_CHILD_DISABLED = r"""
import json
from trino_tpu.caching import executable_cache as ec
from trino_tpu.caching import plan_cache, result_cache
from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.runner import StandaloneQueryRunner

r = StandaloneQueryRunner(default_catalog(scale_factor=0.01))
rows = [list(x) for x in r.execute(%r).rows()]
rows2 = [list(x) for x in r.execute(%r).rows()]
assert rows == rows2
# no tier may have engaged: no registry caches, no plan/result activity
assert not ec._REGISTRY
assert plan_cache.stats()["hits"] == plan_cache.stats()["entries"] == 0
assert result_cache.stats()["hits"] == result_cache.stats()["entries"] == 0
print(json.dumps(rows))
print("DISABLED_OK")
"""


def test_in_process_plan_result_kill_switches(runner, monkeypatch):
    monkeypatch.setenv("TRINO_TPU_PLAN_CACHE", "0")
    monkeypatch.setenv("TRINO_TPU_RESULT_CACHE", "0")
    q = "select count(*) from tpch.tiny.region"
    first = runner.execute(q).rows()
    assert runner.execute(q).rows() == first
    assert plan_cache.stats()["entries"] == 0
    assert result_cache.stats()["entries"] == 0


# ------------------------------------------------------------- observability


def test_runtime_caches_table_lists_all_tiers(runner):
    runner.execute("select count(*) from tpch.tiny.region")
    rows = runner.execute(
        "select tier, name, hits, misses from system.runtime.caches").rows()
    tiers = {r[0] for r in rows}
    assert {"plan", "exec", "result"} <= tiers
    plan_row = next(r for r in rows if r[0] == "plan")
    assert plan_row[2] + plan_row[3] > 0  # the probe query was counted


def test_rest_caches_endpoint(runner):
    import urllib.request

    from trino_tpu.server import TrinoTpuServer

    srv = TrinoTpuServer(runner, port=0).start()
    try:
        host, port = srv.address
        doc = json.load(urllib.request.urlopen(
            f"http://{host}:{port}/v1/caches"))
        assert {r["tier"] for r in doc["caches"]} == \
            {"plan", "exec", "result"}
        detail = json.load(urllib.request.urlopen(
            f"http://{host}:{port}/v1/caches?detail=1"))
        assert len(detail["caches"]) >= len(doc["caches"])
    finally:
        srv.stop()


# ------------------------------------------------- lint_cache_bounds contract


def test_cache_bounds_lint_clean():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint_cache_bounds.py")],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, \
        f"unbounded memo caches:\n{proc.stdout}\n{proc.stderr}"


def test_cache_bounds_lint_catches_planted(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import lint_cache_bounds as L
    finally:
        sys.path.pop(0)
    bad = tmp_path / "bad.py"
    bad.write_text(
        "from functools import lru_cache\n"
        "@lru_cache\n"
        "def a(): pass\n"
        "@lru_cache(maxsize=None)\n"
        "def b(): pass\n"
        "@lru_cache(maxsize=32)\n"
        "def c(): pass\n"
        "@lru_cache(maxsize=None)  # cache-ok: test pragma\n"
        "def d(): pass\n")
    findings = L.lint_file(str(bad))
    assert len(findings) == 2  # bounded + pragma lines pass
    assert {lineno for _, lineno, _ in findings} == {2, 4}
