def test_dbg():
    from trino_tpu.connectors.catalog import default_catalog
    from trino_tpu.runner import StandaloneQueryRunner
    r = StandaloneQueryRunner(default_catalog(scale_factor=0.01))
    print(r.execute('explain select o_totalprice / (o_totalprice - o_totalprice) from orders').rows())
    try:
        out = r.execute('select o_totalprice / (o_totalprice - o_totalprice) from orders')
        print('no error, first rows:', out.rows()[:2])
    except Exception as e:
        print('raised:', type(e).__name__, e)
