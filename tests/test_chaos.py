"""Chaos-certified streaming resilience (ISSUE 9).

Deterministic drills over the straggler-speculation + graceful-drain +
chaos-soak machinery:

- ClusterBlacklist unit behavior (TTL expiry, threshold scoring) under a
  fake clock;
- TaskGate first-commit-wins semantics (the loser's writes raise
  SpeculationLost; no double-commit is possible by construction);
- speculation tail-cut: one injected TASK_STALL straggler, speculation
  on cuts the wall to <=0.5x with identical rows and a cancelled loser;
- mid-query drain with TRINO_TPU_FUSED_STAGE=1: the device-resident
  subplan re-runs cleanly on the replacement worker;
- rolling restart under load loses zero queries and
  system.runtime.workers reflects the state transitions;
- a fast fixed-seed chaos smoke (tier-1) and the full 25-scenario soak
  (marked slow; bench.py --chaos records it in BENCH_r09.json).
"""

import threading
import time

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.execution.exchange import OutputBuffer
from trino_tpu.execution.failure_injector import (
    PROCESS_EXIT,
    TASK_STALL,
    FailureInjector,
    sleep_with_cancel,
)
from trino_tpu.execution.speculation import (
    SPECULATIVE,
    STANDARD,
    ClusterBlacklist,
    GatedBuffer,
    SpeculationLost,
    StreamingSpeculation,
    TaskGate,
    drain_timeout_s,
)
from trino_tpu.runner import Session
from trino_tpu.testing.chaos import build_expected, run_scenario

CATALOG_SPEC = {
    "factory": "trino_tpu.connectors.catalog:default_catalog",
    "kwargs": {"scale_factor": 0.01},
}

_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
}


# --------------------------------------------------------------- unit layer
def test_cluster_blacklist_scoring_and_ttl():
    now = [0.0]
    bl = ClusterBlacklist(ttl_s=10.0, threshold=2.0, clock=lambda: now[0])
    assert not bl.is_blacklisted("w1")
    assert bl.record_failure("w1", reason="REMOTE_HOST_GONE") == 1.0
    assert not bl.is_blacklisted("w1")  # below threshold
    bl.record_failure("w1", reason="REMOTE_TASK_ERROR")
    assert bl.is_blacklisted("w1")
    assert bl.blacklisted() == frozenset({"w1"})
    assert bl.snapshot() == {"w1": 2.0}
    now[0] = 10.5  # both entries expired
    assert not bl.is_blacklisted("w1")
    assert bl.score("w1") == 0.0
    # expiry is per-entry, not per-worker
    bl.record_failure("w2")
    now[0] = 15.0
    bl.record_failure("w2")
    now[0] = 20.6  # first w2 entry expired, second still live
    assert bl.score("w2") == 1.0


def test_task_gate_first_commit_wins():
    claims = []
    gate = TaskGate(on_claim=lambda k: claims.append(k),
                    on_finish=lambda k: None)
    assert gate.claim(SPECULATIVE)  # first claimer owns the stream
    assert gate.claim(SPECULATIVE)  # idempotent for the owner
    assert not gate.claim(STANDARD)
    assert gate.owner == SPECULATIVE
    assert claims == [SPECULATIVE]


def test_gated_buffer_loser_raises_not_commits():
    from trino_tpu.spi.batch import Column, ColumnBatch
    from trino_tpu.spi.types import BIGINT

    inner = OutputBuffer(1)
    gate = TaskGate(on_claim=lambda k: None, on_finish=lambda k: None)
    win = GatedBuffer(inner, gate, STANDARD)
    lose = GatedBuffer(inner, gate, SPECULATIVE)
    batch = ColumnBatch(["x"], [Column.from_values(BIGINT, [1, 2])])
    win.enqueue(0, batch)
    with pytest.raises(SpeculationLost):
        lose.enqueue(0, batch)
    with pytest.raises(SpeculationLost):
        lose.set_finished()
    win.set_finished()
    # exactly the winner's page committed; finished but not yet acked
    assert inner.pages_enqueued == 1
    assert not inner.drained
    assert gate.finished


def test_speculation_twin_spawns_only_past_cutoff():
    now = [0.0]
    events = []
    spec = StreamingSpeculation(lag_multiplier=2.0, min_delay_s=0.1,
                                events=events, clock=lambda: now[0])
    spec.register_stage(7, 3)
    gates = [spec.register_task(7, t) for t in range(3)]
    spawned = []

    def spawn(fid, t):
        spawned.append((fid, t))
        return threading.Thread(target=lambda: None)

    assert spec.tick(spawn) == [] and spawned == []  # no medians yet
    now[0] = 0.2
    gates[0].claim(STANDARD)
    gates[0].finish(STANDARD)
    gates[1].claim(STANDARD)
    gates[1].finish(STANDARD)
    # committed 2/3, median 0.2 -> cutoff 0.4; not lagging yet
    assert spec.tick(spawn) == []
    now[0] = 0.5
    threads = spec.tick(spawn)
    assert spawned == [(7, 2)] and len(threads) == 1
    assert spec.tick(spawn) == []  # one twin per task, ever
    assert spec.starts == 1
    assert ("speculative_start", 7, 2) in events


def test_sleep_with_cancel_returns_early():
    flag = threading.Event()
    t = threading.Timer(0.1, flag.set)
    t.start()
    t0 = time.monotonic()
    assert sleep_with_cancel(5.0, flag.is_set) is True
    assert time.monotonic() - t0 < 2.0
    assert sleep_with_cancel(0.05, lambda: False) is False


def test_drain_timeout_knob_resolution(monkeypatch):
    monkeypatch.delenv("TRINO_TPU_DRAIN_TIMEOUT_S", raising=False)
    assert drain_timeout_s(None, 30.0) == 30.0
    monkeypatch.setenv("TRINO_TPU_DRAIN_TIMEOUT_S", "7.5")
    assert drain_timeout_s(None, 30.0) == 7.5
    assert drain_timeout_s(Session(drain_timeout_s=3.0), 30.0) == 3.0


# ------------------------------------------------- speculation (in-process)
def test_speculation_tail_cut_and_loser_cancelled(monkeypatch):
    """THE tail-cut acceptance drill: an injected TASK_STALL straggler on a
    leaf stage; speculation on must finish in <=0.5x the no-speculation
    wall with EXACTLY the same rows (first-commit-wins: a double-commit
    would double the counts) and the loser cancelled in the event log."""
    monkeypatch.setenv("TRINO_TPU_FUSED_STAGE", "0")  # leaf eligibility
    sql = ("select l_returnflag, count(*), sum(l_quantity) from lineitem "
           "group by l_returnflag order by l_returnflag")

    def once(spec):
        inj = FailureInjector()
        # collectives off: a speculative twin cannot join an in-flight
        # all_to_all (every mesh participant must show up), so collective-
        # edge leaves are ineligible by design — on the 8-virtual-device
        # test mesh the leaf REPARTITION edge would otherwise go
        # collective and the drill would never speculate.  lag_multiplier
        # tuned down so the cutoff clears the straggler stall.
        r = DistributedQueryRunner(
            default_catalog(scale_factor=0.01), worker_count=4,
            session=Session(node_count=4, failure_injector=inj,
                            speculation=spec, use_collectives=False,
                            speculation_lag_multiplier=1.2,
                            speculation_min_delay_s=0.25))
        leaf = [f for f in r.create_subplan(sql).all_fragments()
                if not f.source_fragments][0]
        inj.inject(TASK_STALL, fragment_id=leaf.id, task_index=0,
                   attempt=0, stall_s=8.0)
        t0 = time.perf_counter()
        rows = r.execute(sql).rows()
        return time.perf_counter() - t0, rows, r

    wall_off, rows_off, _ = once(False)
    wall_on, rows_on, r = once(True)
    assert wall_on <= 0.5 * wall_off, (wall_on, wall_off)
    assert rows_on == rows_off  # exact: no double-commit, order included
    assert r.speculative_starts >= 1 and r.speculative_wins >= 1
    kinds = [e[0] for e in r.resilience_events]
    assert "speculative_start" in kinds and "speculative_win" in kinds
    assert "speculative_cancelled" in kinds  # the loser was cancelled


def test_speculation_off_by_default():
    r = DistributedQueryRunner(
        default_catalog(scale_factor=0.01), worker_count=2,
        session=Session(node_count=2))
    assert r.execute("select count(*) from nation").rows() == [(25,)]
    assert r.speculative_starts == 0


# ------------------------------------------------------ drain (in-process)
def test_inproc_drain_and_workers_table():
    r = DistributedQueryRunner(
        default_catalog(scale_factor=0.01), worker_count=2,
        session=Session(node_count=2))
    sql = "select worker, state from system.runtime.workers order by worker"
    assert [s for _, s in r.execute(sql).rows()] == ["ACTIVE", "ACTIVE"]
    r.drain_worker("worker-1")
    assert dict(r.execute(sql).rows())["worker-1"] == "SHUTTING_DOWN"
    # draining stops NEW placement but running queries still complete
    assert r.execute("select count(*) from orders").rows() == [(15000,)]
    r.restore_worker("worker-1")
    assert [s for _, s in r.execute(sql).rows()] == ["ACTIVE", "ACTIVE"]
    kinds = [e for e in r.resilience_events if e[0] == "drain"]
    assert [e[2] for e in kinds] == ["started", "drained", "restored"]


# ----------------------------------------------------- process-level drills
@pytest.mark.slow
def test_fused_stage_drain_rerun_on_replacement(monkeypatch):
    """Mid-query drain with whole-stage compilation ON: the device-resident
    subplan's worker is drained away mid-flight; the query re-runs cleanly
    on the replacement worker with oracle-correct rows."""
    from trino_tpu.execution.remote import ProcessDistributedQueryRunner

    env = dict(_ENV, TRINO_TPU_FUSED_STAGE="1")
    monkeypatch.setenv("TRINO_TPU_FUSED_STAGE", "1")
    sql = ("select l_returnflag, l_linestatus, count(*), sum(l_quantity) "
           "from lineitem group by l_returnflag, l_linestatus "
           "order by l_returnflag, l_linestatus")
    expected = build_expected()[sql]
    r = ProcessDistributedQueryRunner(
        CATALOG_SPEC, worker_count=2,
        session=Session(node_count=2, retry_policy="QUERY",
                        retry_initial_delay_s=0.01,
                        heartbeat_interval_s=0.2, drain_timeout_s=5.0),
        env_overrides=env)
    try:
        holder = {}

        def work():
            try:
                holder["rows"] = r.execute(sql).rows()
            except BaseException as e:  # noqa: BLE001
                holder["exc"] = e

        th = threading.Thread(target=work, daemon=True)
        th.start()
        time.sleep(0.1)
        summary = r.drain_worker(r.workers[0], replace=True)
        th.join(90)
        assert not th.is_alive(), "query hung across the drain"
        assert "exc" not in holder, holder.get("exc")
        from trino_tpu.testing.oracle import assert_same_rows
        assert_same_rows(holder["rows"], expected, ordered=False)
        assert summary["replacement"] is not None
        drains = [e for e in r.resilience_events if e[0] == "drain"]
        assert [e[2] for e in drains][0] == "started"
        assert "replaced" in [e[2] for e in drains]
    finally:
        r.close()


@pytest.mark.slow
def test_rolling_restart_loses_zero_queries():
    """Drain every worker one at a time (real shutdown + replacement)
    under sustained load: zero queries lost, and system.runtime.workers
    reflects the transitions (everything ACTIVE again at the end)."""
    from trino_tpu.execution.remote import ProcessDistributedQueryRunner

    r = ProcessDistributedQueryRunner(
        CATALOG_SPEC, worker_count=2,
        session=Session(node_count=2, retry_policy="QUERY",
                        retry_initial_delay_s=0.01,
                        heartbeat_interval_s=0.2, drain_timeout_s=10.0),
        env_overrides=_ENV)
    stop = threading.Event()
    ok, failed = [], []

    def load():
        while not stop.is_set():
            try:
                assert r.execute(
                    "select count(*) from orders").rows() == [(15000,)]
                ok.append(1)
            except Exception as e:  # noqa: BLE001
                failed.append(f"{type(e).__name__}: {e}")

    try:
        r.execute("select count(*) from orders")  # warm up
        th = threading.Thread(target=load, daemon=True)
        th.start()
        summaries = r.rolling_restart()
        stop.set()
        th.join(60)
        assert len(summaries) == 2
        assert failed == [], failed
        assert len(ok) > 0
        # every slot was replaced and is ACTIVE in the workers table again
        states = r.execute(
            "select state from system.runtime.workers").rows()
        assert [s for (s,) in states].count("ACTIVE") == 2
        drains = [e for e in r.resilience_events if e[0] == "drain"]
        assert sum(1 for e in drains if e[2] == "started") == 2
        assert sum(1 for e in drains if e[2] == "drained") == 2
    finally:
        r.close()


# ------------------------------------------------------------- chaos soak
def test_chaos_smoke_fixed_seed():
    """Fast deterministic tier-1 gate: two in-process scenarios (10
    queries) from a fixed seed — every query oracle-correct, retried, or
    correctly classified; zero hangs.  Runs in a subprocess under the
    soak's own single-device env: that replicates exactly the certified
    ``bench.py --chaos`` environment, and keeps the scenarios' extra
    jitted programs out of this process's XLA backend (the accumulated
    compile load otherwise destabilizes later compiles in the suite)."""
    import json
    import os
    import subprocess
    import sys

    from trino_tpu.testing.chaos import _ENV

    prog = (
        "import json\n"
        "from trino_tpu.testing.chaos import build_expected, run_scenario\n"
        "expected = build_expected()\n"
        "recs = [run_scenario(s, mode='inproc', n_queries=5,"
        " expected=expected) for s in (1009, 1010)]\n"
        "print(json.dumps([{'seed': r['seed'], 'counts': r['counts'],"
        " 'n': len(r['outcomes'])} for r in recs]))\n"
    )
    env = {**os.environ, **_ENV}
    out = subprocess.run([sys.executable, "-c", prog], env=env,
                         capture_output=True, text=True, timeout=300,
                         cwd=os.path.dirname(os.path.dirname(__file__)))
    assert out.returncode == 0, out.stderr[-2000:]
    recs = json.loads(out.stdout.splitlines()[-1])
    assert [r["seed"] for r in recs] == [1009, 1010]
    assert sum(r["n"] for r in recs) == 10
    assert sum(r["counts"].get("hang", 0) for r in recs) == 0, \
        "chaos smoke produced a hang"
    assert sum(r["counts"].get("unexpected", 0) for r in recs) == 0, \
        "chaos smoke produced an unaccounted outcome"


@pytest.mark.slow
def test_chaos_soak_full():
    """The full 25-scenario randomized soak (bench.py --chaos writes the
    same campaign to BENCH_r09.json)."""
    from trino_tpu.testing.chaos import run_chaos

    summary = run_chaos(n_scenarios=25, base_seed=1009, verbose=False)
    assert summary["hangs"] == 0
    assert summary["unexpected"] == 0
    assert summary["all_accounted"]
    assert summary["n_queries"] >= 25


# ------------------------------------------- non-leaf speculation (ISSUE 15)
def test_spool_tee_unit(tmp_path):
    """StreamingSpoolTee + SpoolTeeBuffer: winner pages land durably in
    FTE spool layout, a loser never reaches the tee, and ready() answers
    twin eligibility only once EVERY source task committed."""
    from trino_tpu.execution.serde import deserialize_batch, iter_frames
    from trino_tpu.execution.speculation import (SpoolTeeBuffer,
                                                 StreamingSpoolTee)
    from trino_tpu.spi.batch import Column, ColumnBatch
    from trino_tpu.spi.types import BIGINT

    tee = StreamingSpoolTee(str(tmp_path))
    tee.want(3, 2)
    assert tee.wants(3) and not tee.wants(4)
    assert not tee.ready([3])
    assert tee.committed_dirs(3) is None

    batch = ColumnBatch(["x"], [Column.from_values(BIGINT, [1, 2, 3])])
    inner = OutputBuffer(1)
    gate = TaskGate(on_claim=lambda k: None, on_finish=lambda k: None)
    committed = []
    win = SpoolTeeBuffer(GatedBuffer(inner, gate, STANDARD),
                         tee.writer(3, 0, 1),
                         on_commit=lambda d: (tee.mark_committed(3, 0, d),
                                              committed.append(d)))
    lose = SpoolTeeBuffer(GatedBuffer(inner, gate, SPECULATIVE),
                          tee.writer(3, 0, 1, attempt=1000),
                          on_commit=lambda d: tee.mark_committed(3, 0, d))
    win.enqueue(0, batch)
    with pytest.raises(SpeculationLost):
        lose.enqueue(0, batch)  # gate rejects BEFORE the tee sees it
    win.set_finished()
    assert committed and committed[0].endswith("attempt-0")
    assert not tee.ready([3])  # task 1 still missing

    t1 = SpoolTeeBuffer(OutputBuffer(1), tee.writer(3, 1, 1),
                        on_commit=lambda d: tee.mark_committed(3, 1, d))
    t1.set_finished()
    assert tee.ready([3]) and tee.ready([])
    dirs = tee.committed_dirs(3)
    assert [d.split("/")[-2] for d in dirs] == ["f3_t0", "f3_t1"]
    # the committed tee holds exactly the winner's stream
    with open(f"{dirs[0]}/part-0.bin", "rb") as f:
        frames = list(iter_frames(f, "part-0.bin"))
    assert len(frames) == 1
    assert deserialize_batch(frames[0]).num_rows == 3


def test_nonleaf_speculation_rescues_nonleaf_straggler(monkeypatch):
    """The retention payoff (ROADMAP: 'non-leaf speculation needs FTE's
    spool retention'): a TASK_STALL on a NON-leaf stage task — whose
    inputs are ephemeral streaming exchanges — is rescued by a twin that
    re-reads its producers' committed spool tees."""
    monkeypatch.setenv("TRINO_TPU_FUSED_STAGE", "0")
    from trino_tpu.caching import result_cache

    sql = ("select l_returnflag, count(*), sum(l_quantity) from lineitem "
           "group by l_returnflag order by l_returnflag")
    inj = FailureInjector()
    r = DistributedQueryRunner(
        default_catalog(scale_factor=0.01), worker_count=4,
        session=Session(node_count=4, failure_injector=inj,
                        speculation=True, speculation_nonleaf=True,
                        use_collectives=False,
                        speculation_lag_multiplier=1.2,
                        speculation_min_delay_s=0.25))
    frags = r.create_subplan(sql).all_fragments()
    # the middle fragment: consumes the leaf scan, feeds the root output
    mid = [f.id for f in frags if f.source_fragments
           and any(f.id in g.source_fragments for g in frags)]
    assert mid, "plan has no intermediate fragment"
    inj.inject(TASK_STALL, fragment_id=mid[0], task_index=0, attempt=0,
               stall_s=6.0)
    with result_cache.disabled():
        t0 = time.perf_counter()
        rows = r.execute(sql).rows()
        wall = time.perf_counter() - t0
    baseline = DistributedQueryRunner(
        default_catalog(scale_factor=0.01), worker_count=4,
        session=Session(node_count=4, use_collectives=False))
    with result_cache.disabled():
        assert rows == baseline.execute(sql).rows()
    assert r.speculative_wins >= 1
    wins = [e for e in r.resilience_events if e[0] == "speculative_win"]
    assert any(e[1] == mid[0] for e in wins), wins
    assert wall < 6.0, f"twin did not cut the stall ({wall:.1f}s)"


def test_nonleaf_speculation_off_without_knob():
    """Tri-state gating: session None + knob unset → non-leaf stages never
    register for twins (leaf speculation is unaffected)."""
    from trino_tpu.execution.speculation import nonleaf_speculation_enabled

    assert not nonleaf_speculation_enabled(Session())
    assert nonleaf_speculation_enabled(Session(speculation_nonleaf=True))
    assert not nonleaf_speculation_enabled(
        Session(speculation_nonleaf=False))


# ------------------------------------------------- FTE chaos leg (ISSUE 15)
def test_fte_spool_corruption_repaired():
    """A bit-flipped committed spool file is detected (CRC), the attempt
    discarded, and ONLY its producer re-run — oracle-correct rows out."""
    from trino_tpu.caching import result_cache
    from trino_tpu.execution.failure_injector import SPOOL_CORRUPTION
    from trino_tpu.telemetry import metrics as tm

    sql = ("select l_returnflag, count(*), sum(l_quantity) from lineitem "
           "group by l_returnflag order by l_returnflag")
    inj = FailureInjector()
    r = DistributedQueryRunner(
        default_catalog(scale_factor=0.01), worker_count=2,
        session=Session(node_count=2, retry_policy="TASK",
                        failure_injector=inj, task_retry_attempts=3))
    inj.inject(SPOOL_CORRUPTION, fragment_id=None, task_index=0,
               attempt=0, times=1)
    before = tm.FTE_SPOOL_CORRUPTIONS.value()
    with result_cache.disabled():
        rows = r.execute(sql).rows()
    assert tm.FTE_SPOOL_CORRUPTIONS.value() - before >= 1, \
        "injected corruption was never detected"
    baseline = DistributedQueryRunner(
        default_catalog(scale_factor=0.01), worker_count=2,
        session=Session(node_count=2, retry_policy="TASK"))
    with result_cache.disabled():
        assert rows == baseline.execute(sql).rows()


def test_fte_chaos_smoke_fixed_seed():
    """Tier-1 FTE chaos gate: one seeded scenario over the FTE fault menu
    (task failure/stall/OOM, results-fetch failure, spool corruption) —
    every query accounted, zero hangs.  Subprocess for the same XLA-
    isolation reasons as test_chaos_smoke_fixed_seed."""
    import json
    import os
    import subprocess
    import sys

    from trino_tpu.testing.chaos import _ENV

    prog = (
        "import json\n"
        "from trino_tpu.testing.chaos import build_expected, "
        "run_fte_scenario\n"
        "rec = run_fte_scenario(1515, n_queries=6,"
        " expected=build_expected())\n"
        "print(json.dumps({'counts': rec['counts'],"
        " 'n': len(rec['outcomes'])}))\n"
    )
    env = {**os.environ, **_ENV}
    out = subprocess.run([sys.executable, "-c", prog], env=env,
                         capture_output=True, text=True, timeout=300,
                         cwd=os.path.dirname(os.path.dirname(__file__)))
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads(out.stdout.splitlines()[-1])
    assert rec["n"] == 6
    assert rec["counts"].get("hang", 0) == 0, "FTE chaos smoke hung"
    assert rec["counts"].get("unexpected", 0) == 0, \
        "FTE chaos smoke produced an unaccounted outcome"


@pytest.mark.slow
def test_fte_chaos_soak_full():
    """The full FTE chaos leg (bench.py --chaos-fte writes the same
    campaign + the coordinator kill drill to BENCH_r15.json)."""
    from trino_tpu.testing.chaos import run_fte_chaos

    summary = run_fte_chaos(n_scenarios=12, base_seed=1515, verbose=False)
    assert summary["hangs"] == 0
    assert summary["unexpected"] == 0
    assert summary["all_accounted"]
    assert summary["n_queries"] >= 12
