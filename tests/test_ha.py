"""HA control plane (execution/ha.py + server/front_tier.py): rendezvous
ownership, lease lifecycle/expiry/deposition, atomic claim races, WAL-dir
adoption, stateless front-tier routing with failover rerouting, the worker
autoscaler policy, and the system.runtime.coordinators table."""

import json
import os
import threading
import time

import pytest

from trino_tpu.execution import ha, query_state

pytestmark = []


@pytest.fixture()
def ha_env(tmp_path, monkeypatch):
    root = tmp_path / "ha"
    monkeypatch.setenv("TRINO_TPU_HA", "1")
    monkeypatch.setenv("TRINO_TPU_HA_DIR", str(root))
    monkeypatch.setenv("TRINO_TPU_HA_LEASE_TTL_S", "5")
    monkeypatch.setenv("TRINO_TPU_HA_HEARTBEAT_S", "60")  # no async renew
    return str(root)


def _write_lease(root, nid, age_s=0.0, url="", epoch=1.0):
    d = ha.coordinators_dir(root)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, nid + ".json"), "w", encoding="utf-8") as f:
        json.dump({"node_id": nid, "url": url, "pid": 1, "epoch": epoch,
                   "ts": time.time() - age_s, "state": "ACTIVE"}, f)


# ---------------------------------------------------------- rendezvous
def test_owner_of_is_deterministic_and_minimally_disruptive():
    members = ["coord-a", "coord-b", "coord-c"]
    keys = [f"q{i:04d}" for i in range(200)]
    owners = {k: ha.owner_of(k, members) for k in keys}
    assert owners == {k: ha.owner_of(k, list(reversed(members)))
                      for k in keys}, "order must not matter"
    assert set(owners.values()) == set(members), "all members get keys"
    # removing one member remaps ONLY that member's keys
    survivors = ["coord-a", "coord-c"]
    for k in keys:
        if owners[k] != "coord-b":
            assert ha.owner_of(k, survivors) == owners[k]
    assert ha.owner_of("q", []) is None


# --------------------------------------------------------------- lease
def test_lease_register_expiry_and_directory(ha_env):
    lease = ha.CoordinatorLease("coord-x", url="http://h:1", root=ha_env,
                                ttl=5.0, interval=60.0).register()
    try:
        members = ha.read_members(ha_env, ttl=5.0)
        assert [m.node_id for m in members] == ["coord-x"]
        assert members[0].state == "ACTIVE"
        assert members[0].url == "http://h:1"
        assert members[0].age_s < 2.0
        # a lease past the TTL reads as EXPIRED and leaves live_members
        _write_lease(ha_env, "coord-stale", age_s=60.0)
        by_id = {m.node_id: m for m in ha.read_members(ha_env, ttl=5.0)}
        assert by_id["coord-stale"].state == "EXPIRED"
        assert [m.node_id for m in ha.live_members(ha_env, ttl=5.0)] \
            == ["coord-x"]
    finally:
        lease.release()
    assert not os.path.exists(lease.path), "release removes the lease"


def test_lease_deposed_when_claimed_out_from_under(ha_env):
    lease = ha.CoordinatorLease("coord-z", root=ha_env, ttl=5.0,
                                interval=60.0).register()
    try:
        assert lease.renew()
        os.remove(lease.path)  # a peer's claim rename, from our view
        assert not lease.renew()
        assert lease.deposed
        # a deposed lease never rewrites its file (zombie defense)
        assert not os.path.exists(lease.path)
    finally:
        lease.release()


def test_claim_dead_is_exactly_once_and_moves_wal(ha_env):
    _write_lease(ha_env, "coord-dead", age_s=60.0, epoch=7.0)
    wal_dir = ha.node_wal_dir("coord-dead", ha_env)
    os.makedirs(wal_dir)
    with open(os.path.join(wal_dir, "q1.wal"), "w", encoding="utf-8") as f:
        f.write("{}\n")

    wins_a = ha.claim_dead("coord-a", ha_env, ttl=5.0)
    wins_b = ha.claim_dead("coord-b", ha_env, ttl=5.0)
    assert [w[0] for w in wins_a] == ["coord-dead"]
    assert wins_b == [], "second claimant must lose the rename race"
    claimed_dir = wins_a[0][1]
    assert claimed_dir and os.path.isdir(claimed_dir)
    assert not os.path.isdir(wal_dir), "WAL custody moved to the claimant"
    assert os.path.exists(os.path.join(claimed_dir, "q1.wal"))
    assert ha.claimed_wal_dirs("coord-a", ha_env) == [claimed_dir]
    # an ACTIVE peer is never claimed
    _write_lease(ha_env, "coord-live", age_s=0.0)
    assert ha.claim_dead("coord-a", ha_env, ttl=5.0) == []


def test_concurrent_claim_single_winner(ha_env):
    _write_lease(ha_env, "coord-dead", age_s=60.0)
    wins: list = []

    def claim(me):
        wins.extend(ha.claim_dead(me, ha_env, ttl=5.0))

    threads = [threading.Thread(target=claim, args=(f"coord-{i}",))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(wins) == 1, f"exactly one winner, got {wins}"


# ------------------------------------------------------------ adoption
class _StubDispatcher:
    def __init__(self):
        self.adopted: list = []

    def adopt(self, pq) -> bool:
        self.adopted.append(pq.query_id)
        return True

    def in_flight(self) -> int:
        return len(self.adopted)


class _StubServer:
    address = ("127.0.0.1", 0)

    def __init__(self):
        self.dispatcher = _StubDispatcher()


def test_ha_coordinator_step_claims_and_adopts(ha_env):
    # a dead peer with one resumable query in its WAL dir
    _write_lease(ha_env, "coord-dead", age_s=60.0)
    wal = query_state.QueryStateLog(
        "q_orphan", dir=ha.node_wal_dir("coord-dead", ha_env))
    wal.begin("select 1", {"plan": 1}, "/s", None)
    wal.close()
    # and one already-ended query that must NOT be adopted
    wal2 = query_state.QueryStateLog(
        "q_done", dir=ha.node_wal_dir("coord-dead", ha_env))
    wal2.begin("select 2", {"plan": 2}, "/s", None)
    wal2.end("FINISHED")
    wal2.close()

    srv = _StubServer()
    coord = ha.HACoordinator(srv, nid="coord-b", root=ha_env, ttl=5.0,
                             interval=60.0)
    assert coord.step() == ["coord-dead"]
    assert srv.dispatcher.adopted == ["q_orphan"]
    assert coord.takeovers == ["coord-dead"]
    assert coord.step() == [], "a claimed lease cannot be claimed twice"


def test_ha_coordinator_reboot_readopts_claimed_custody(ha_env):
    """A claimant that crashed mid-adoption re-adopts from its claimed
    dirs at the next boot."""
    _write_lease(ha_env, "coord-dead", age_s=60.0)
    wal = query_state.QueryStateLog(
        "q_orphan2", dir=ha.node_wal_dir("coord-dead", ha_env))
    wal.begin("select 3", {"plan": 3}, "/s", None)
    wal.close()
    assert ha.claim_dead("coord-b", ha_env, ttl=5.0)

    srv = _StubServer()
    coord = ha.HACoordinator(srv, nid="coord-b", root=ha_env, ttl=5.0,
                             interval=60.0)
    coord.start()
    try:
        assert srv.dispatcher.adopted == ["q_orphan2"]
    finally:
        coord.stop()


# ---------------------------------------------------------- front tier
@pytest.fixture(scope="module")
def fleet():
    """Two statement servers over ONE shared in-process runner (cheap:
    the catalog builds once), each registered in a fleet directory."""
    import tempfile

    from trino_tpu.connectors.catalog import default_catalog
    from trino_tpu.execution.distributed_runner import DistributedQueryRunner
    from trino_tpu.runner import Session
    from trino_tpu.server.protocol import TrinoTpuServer

    root = tempfile.mkdtemp(prefix="trino-tpu-ha-fleet-")
    runner = DistributedQueryRunner(
        default_catalog(scale_factor=0.01), worker_count=2,
        session=Session(node_count=2))
    servers = {}
    leases = {}
    for nid in ("coord-a", "coord-b"):
        srv = TrinoTpuServer(runner).start()
        host, port = srv.address
        leases[nid] = ha.CoordinatorLease(
            nid, url=f"http://{host}:{port}", root=root, ttl=30.0,
            interval=60.0).register()
        servers[nid] = srv
    yield root, servers
    for lease in leases.values():
        lease.release()
    for srv in servers.values():
        srv.stop()


def _drain(tier, first: dict, timeout_s: float = 60.0) -> tuple:
    """Follow nextUri through the tier until terminal; -> (state, rows)."""
    from urllib.request import urlopen

    host, port = tier.address
    out, rows = first, list(first.get("data", []))
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        state = out.get("stats", {}).get("state")
        nxt = out.get("nextUri")
        if state == "FAILED" or (state == "FINISHED" and not nxt):
            return state, rows
        with urlopen(f"http://{host}:{port}{nxt}", timeout=30) as resp:
            out = json.loads(resp.read())
        rows += out.get("data", [])
    return "TIMEOUT", rows


def test_front_tier_routes_post_to_hash_owner(fleet):
    from urllib.request import Request, urlopen

    from trino_tpu.server.front_tier import FrontTier

    root, servers = fleet
    tier = FrontTier(root=root, ttl=30.0, retry_s=2.0).start()
    try:
        host, port = tier.address
        req = Request(f"http://{host}:{port}/v1/statement",
                      data=b"select count(*) from nation", method="POST")
        with urlopen(req, timeout=60) as resp:
            first = json.loads(resp.read())
        qid = first["id"]
        state, rows = _drain(tier, first)
        assert state == "FINISHED"
        assert rows == [[25]]
        # the query landed on (exactly) the rendezvous owner
        owner = ha.owner_of(qid, ["coord-a", "coord-b"])
        assert servers[owner].dispatcher.get(qid) is not None
        other = "coord-b" if owner == "coord-a" else "coord-a"
        assert servers[other].dispatcher.get(qid) is None
    finally:
        tier.stop()


def test_front_tier_reroutes_when_owner_disowns_query(fleet):
    """A query living on the NON-owner (post-takeover shape: the claimant
    adopted it, the hash still points at the dead node's successor) is
    found by the probe-all-members pass and served."""
    from urllib.request import urlopen

    from trino_tpu.server.front_tier import FrontTier
    from trino_tpu.telemetry import metrics as tm

    root, servers = fleet
    tier = FrontTier(root=root, ttl=30.0, retry_s=2.0).start()
    try:
        host, port = tier.address
        # place a finished query directly on a chosen server, under a qid
        # whose hash owner is the OTHER server
        for probe in range(1000):
            qid = f"reroute{probe:04d}"
            if ha.owner_of(qid, ["coord-a", "coord-b"]) == "coord-a":
                continue
            break
        q = servers["coord-a"].dispatcher.submit(
            "select count(*) from region", qid=qid)
        q.done.wait(timeout=60)
        before = tm.HA_REROUTES.value()
        with urlopen(f"http://{host}:{port}/v1/statement/{qid}/0",
                     timeout=60) as resp:
            out = json.loads(resp.read())
        state, rows = _drain(tier, out)
        assert state == "FINISHED"
        assert rows == [[5]]
        assert tm.HA_REROUTES.value() == before + 1
        # the pin is warm now: the next poll must not re-count a reroute
        with urlopen(f"http://{host}:{port}/v1/statement/{qid}/0",
                     timeout=60) as resp:
            json.loads(resp.read())
        assert tm.HA_REROUTES.value() == before + 1
    finally:
        tier.stop()


def test_front_tier_synthetic_queued_inside_retry_window(fleet):
    """While NO member knows the query (mid-takeover), polls inside the
    retry budget get a synthetic QUEUED page with an unchanged nextUri;
    past the budget the truth (404) surfaces."""
    from urllib.error import HTTPError
    from urllib.request import urlopen

    from trino_tpu.server.front_tier import FrontTier

    root, _servers = fleet
    tier = FrontTier(root=root, ttl=30.0, retry_s=0.4).start()
    try:
        host, port = tier.address
        path = "/v1/statement/nosuchquery00001/0"
        with urlopen(f"http://{host}:{port}{path}", timeout=60) as resp:
            out = json.loads(resp.read())
        assert out["stats"]["state"] == "QUEUED"
        assert out["nextUri"] == path
        time.sleep(0.6)
        with pytest.raises(HTTPError) as exc:
            urlopen(f"http://{host}:{port}{path}", timeout=60)
        assert exc.value.code == 404
    finally:
        tier.stop()


# ---------------------------------------------------------- autoscaler
class _ScalableRunner:
    def __init__(self, n: int):
        self.n = n

    @property
    def active_worker_count(self) -> int:
        return self.n

    def add_worker(self):
        self.n += 1

    def remove_worker(self):
        self.n -= 1
        return f"w{self.n}"


def test_autoscaler_grows_under_queue_pressure_and_respects_ceiling():
    r = _ScalableRunner(1)
    asc = ha.WorkerAutoscaler(r, min_workers=1, max_workers=3,
                              queue_s=0.5, idle_rounds=2, interval_s=999)
    assert asc.step(queued_delta_s=1.0) == "up" and r.n == 2
    assert asc.step(queued_delta_s=1.0) == "up" and r.n == 3
    assert asc.step(queued_delta_s=1.0) is None, "ceiling reached"
    assert r.n == 3


def test_autoscaler_drains_after_idle_rounds_and_respects_floor():
    r = _ScalableRunner(3)
    asc = ha.WorkerAutoscaler(r, min_workers=1, max_workers=3,
                              queue_s=0.5, idle_rounds=2, interval_s=999)
    assert asc.step(queued_delta_s=0.0) is None, "one idle round is not enough"
    assert asc.step(queued_delta_s=0.0) == "down" and r.n == 2
    assert asc.step(queued_delta_s=0.0) is None
    assert asc.step(queued_delta_s=0.0) == "down" and r.n == 1
    for _ in range(4):
        assert asc.step(queued_delta_s=0.0) is None, "floor reached"
    assert r.n == 1
    # pressure resets the idle streak
    r2 = _ScalableRunner(2)
    asc2 = ha.WorkerAutoscaler(r2, min_workers=1, max_workers=3,
                               queue_s=0.5, idle_rounds=2, interval_s=999)
    assert asc2.step(queued_delta_s=0.0) is None
    assert asc2.step(queued_delta_s=9.9) == "up"
    assert asc2.step(queued_delta_s=0.0) is None, "streak was reset"


def test_autoscaler_reads_admission_queue_metric():
    from trino_tpu.telemetry import metrics as tm

    r = _ScalableRunner(1)
    asc = ha.WorkerAutoscaler(r, min_workers=1, max_workers=2,
                              queue_s=0.5, idle_rounds=99, interval_s=999)
    assert asc.step() is None, "no queueing recorded yet"
    tm.ADMISSION_QUEUED_SECONDS.record(0.7)
    assert asc.step() == "up", "queued-seconds delta must trigger growth"
    assert asc.step() is None, "the delta was consumed"


def test_autoscaler_logical_drain_on_inprocess_runner(fleet):
    """Against the real in-process runner the scale-down path is a logical
    drain (NodeManager), and scale-up restores the drained slot."""
    _root, servers = fleet
    runner = servers["coord-a"].dispatcher.runner
    n0 = runner.active_worker_count
    asc = ha.WorkerAutoscaler(runner, min_workers=1, max_workers=n0,
                              queue_s=0.5, idle_rounds=1, interval_s=999)
    try:
        assert asc.step(queued_delta_s=0.0) == "down"
        assert runner.active_worker_count == n0 - 1
        assert asc.step(queued_delta_s=1.0) == "up"
        assert runner.active_worker_count == n0
    finally:
        for nid in list(asc._drained):
            runner.restore_worker(nid)


# ------------------------------------------- system.runtime.coordinators
def test_coordinators_table_without_ha(fleet):
    _root, servers = fleet
    runner = servers["coord-a"].dispatcher.runner
    rows = runner.execute(
        "select coordinator, state, url from system.runtime.coordinators"
    ).rows()
    assert len(rows) == 1
    assert rows[0][1] == "ACTIVE"


def test_coordinators_table_reads_fleet(fleet, monkeypatch):
    root, servers = fleet
    monkeypatch.setenv("TRINO_TPU_HA", "1")
    monkeypatch.setenv("TRINO_TPU_HA_DIR", root)
    monkeypatch.setenv("TRINO_TPU_HA_LEASE_TTL_S", "30")
    runner = servers["coord-a"].dispatcher.runner
    rows = runner.execute(
        "select coordinator, state, lease_age_ms, in_flight_queries, url "
        "from system.runtime.coordinators order by coordinator").rows()
    by_id = {r[0]: r for r in rows}
    assert set(by_id) == {"coord-a", "coord-b"}
    for r in rows:
        assert r[1] == "ACTIVE"
        assert r[2] >= 0.0
        assert r[4].startswith("http://")
