"""tpulint framework tests: per-rule fixtures plus the whole-repo smoke.

Each rule gets positive (fires), negative (stays quiet), suppressed, and
unused-suppression coverage over tiny fixture trees written to tmp_path and
indexed by the same ProjectIndex the real run uses — so every assertion
exercises the production parse/symbol/callgraph core, not a mock.  The
smoke test at the bottom runs the full pipeline over the real repo and
pins the committed baseline: a new finding, a stale baseline entry, or a
stale suppression anywhere in the tree fails tier-1.

Directive and knob literals inside fixture sources are assembled by
concatenation so this file's own source stays invisible to the repo-wide
suppression and knob-registry scans.
"""

import json
import subprocess
import sys

from tools.analysis import baseline as bl
from tools.analysis import knobdocs, repo_root, run_analysis
from tools.analysis.core import ProjectIndex, apply_suppressions
from tools.analysis.rules import all_rules, knob_registry

RULES = {r.name: r for r in all_rules()}

# assembled at runtime so the scans never see a live directive / knob name
# in this file's source
D = "# tpulint" + ": disable"            # -> "# tpulint: disable"
DF = "# tpulint" + ": disable-file"
KNOB_GOOD = "TRINO_TPU_" + "FIXTURE_LANES"
KNOB_BAD = "TRINO_TPU_" + "FIXTURE_LANSE"    # the typo the rule must catch


def project(tmp_path, files):
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(text)
    return ProjectIndex.build(str(tmp_path))


def findings_of(rule, index):
    return RULES[rule].check(index)


# --------------------------------------------------- host-sync (dataflow)

HOT_FLOW = """\
import jax.numpy as jnp
import numpy as np
from .syncguard import SG

def hot(x):
    with SG.hot_region():
        y = jnp.ones(3)
        n = int(y)
        return helper(x) + n

def helper(x):
    total = jnp.sum(x)
    flag = bool(total)
    if total:
        return flag
    host = np.asarray(total)
    return host

def cold(x):
    total = jnp.sum(x)
    return bool(total)
"""


def test_host_sync_dataflow_flags_implicit_syncs(tmp_path):
    idx = project(tmp_path, {"trino_tpu/exec/flow.py": HOT_FLOW})
    found = findings_of("host-sync", idx)
    msgs = [f.message for f in found]
    # inside the hot region itself
    assert any("int() on a device value" in m for m in msgs)
    # in a function reachable from the region via the callgraph
    assert any("bool() on a device value" in m for m in msgs)
    assert any("truthiness of a device value in 'if'" in m for m in msgs)
    assert any("np.asarray() on a device value" in m for m in msgs)
    # none of these are raw sync spellings: the old grep finds zero here
    from tools.analysis.rules.host_sync import lint_file
    assert lint_file(str(tmp_path / "trino_tpu/exec/flow.py")) == []


def test_host_sync_dataflow_ignores_unreachable_cold_code(tmp_path):
    idx = project(tmp_path, {"trino_tpu/exec/flow.py": HOT_FLOW})
    found = findings_of("host-sync", idx)
    # cold() truthiness-tests a device value but is not reachable from any
    # hot region — it must stay quiet
    assert all(f.snippet != "return bool(total)" for f in found)


def test_host_sync_dataflow_without_hot_region_is_quiet(tmp_path):
    quiet = HOT_FLOW.replace("with SG.hot_region():", "if True:")
    idx = project(tmp_path, {"trino_tpu/exec/flow.py": quiet})
    assert findings_of("host-sync", idx) == []


def test_host_sync_pattern_layer_and_pragma(tmp_path):
    src = ("def take(buf):\n"
           "    a = buf.pop().item()\n"
           "    b = buf.pop().item()  # sync" + "-ok: drained after barrier\n"
           "    return a + b\n")
    idx = project(tmp_path, {"trino_tpu/exec/take.py": src})
    found = findings_of("host-sync", idx)
    assert len(found) == 1 and ".item() blocking sync" in found[0].message
    assert found[0].line == 2


def test_host_sync_directive_suppression(tmp_path):
    src = HOT_FLOW.replace(
        "    flag = bool(total)",
        f"    flag = bool(total)  {D}=host-sync -- fixture: cold fallback")
    idx = project(tmp_path, {"trino_tpu/exec/flow.py": src})
    raw = findings_of("host-sync", idx)
    kept, suppressed = apply_suppressions(idx, raw, {"host-sync"})
    assert any("bool() on a device value" in f.message for f in suppressed)
    assert all("bool() on a device value" not in f.message for f in kept)


# ----------------------------------------------------------- thread-safety

TS_SHARED = """\
import threading

class Buf:
    def __init__(self, pool):
        self._pool = pool
        self._lock = threading.Lock()
        self._items = []
        self._free = []

    def start(self):
        self._pool.submit(self._drain)

    def _drain(self):
        with self._lock:
            self._items.append(1)

    def push(self, x):
        self._items.append(x)

    def note(self, x):
        self._free.append(x)
"""


def test_thread_safety_flags_unlocked_mutation_of_guarded_attr(tmp_path):
    idx = project(tmp_path, {"trino_tpu/ts.py": TS_SHARED})
    found = findings_of("thread-safety", idx)
    # push() mutates self._items (guarded — _drain locks it) without the
    # lock; note() touches self._free which is never locked anywhere, so
    # it is presumed single-threaded and stays quiet
    assert len(found) == 1
    f = found[0]
    assert "unlocked mutation of lock-guarded attribute 'self._items'" \
        in f.message
    assert "'Buf'" in f.message and "_drain" in f.message
    assert f.snippet == "self._items.append(x)"


def test_thread_safety_unshared_class_is_quiet(tmp_path):
    solo = TS_SHARED.replace("        self._pool.submit(self._drain)\n", "")
    idx = project(tmp_path, {"trino_tpu/ts.py": solo})
    # same locking pattern, but nothing ever hands a method to a thread —
    # no sharing evidence, no finding
    assert findings_of("thread-safety", idx) == []


def test_thread_safety_external_spawn_counts_as_shared(tmp_path):
    src = """\
import threading

class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = []

    def run(self):
        with self._lock:
            self._q.append(0)

    def bump(self):
        self._q.append(1)

def boot():
    p = Pump()
    t = threading.Thread(target=p.run)
    t.start()
"""
    idx = project(tmp_path, {"trino_tpu/pump.py": src})
    found = findings_of("thread-safety", idx)
    assert len(found) == 1
    assert "'self._q'" in found[0].message and "'Pump'" in found[0].message


def test_thread_safety_lock_order_cycle(tmp_path):
    src = """\
import threading

class AB:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self._pool = None

    def start(self):
        self._pool.submit(self.one)

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._b:
            with self._a:
                pass
"""
    idx = project(tmp_path, {"trino_tpu/ab.py": src})
    found = [f for f in findings_of("thread-safety", idx)
             if "lock-order cycle" in f.message]
    assert len(found) == 1
    assert "AB._a" in found[0].message and "AB._b" in found[0].message
    # consistent ordering everywhere: no cycle, no finding
    fixed = src.replace("        with self._b:\n            with self._a:",
                        "        with self._a:\n            with self._b:")
    idx2 = project(tmp_path / "fixed", {"trino_tpu/ab.py": fixed})
    assert not [f for f in findings_of("thread-safety", idx2)
                if "lock-order cycle" in f.message]


def test_thread_safety_directive_suppression(tmp_path):
    src = TS_SHARED.replace(
        "        self._items.append(x)\n\n    def note",
        f"        self._items.append(x)  {D}=thread-safety -- fixture: "
        "callers hold the lock\n\n    def note")
    idx = project(tmp_path, {"trino_tpu/ts.py": src})
    raw = findings_of("thread-safety", idx)
    kept, suppressed = apply_suppressions(idx, raw, {"thread-safety"})
    assert kept == [] and len(suppressed) == 1


# ------------------------------------------------- knob-registry/knob-docs

KNOBS_FIXTURE = f"""\
def Knob(*args, **kwargs):
    return args

KNOBS = [
    Knob("{KNOB_GOOD}", "int", "8", "fixture lanes per step"),
]
"""


def test_knob_registry_flags_undeclared_literal(tmp_path):
    use = (f'import os\n\n'
           f'GOOD = os.environ.get("{KNOB_GOOD}", "8")\n'
           f'BAD = os.environ.get("{KNOB_BAD}", "")\n')
    idx = project(tmp_path, {"trino_tpu/spi/knobs.py": KNOBS_FIXTURE,
                             "trino_tpu/cfg.py": use})
    found = findings_of("knob-registry", idx)
    assert len(found) == 1
    assert found[0].path == "trino_tpu/cfg.py" and found[0].line == 4
    assert KNOB_BAD in found[0].message
    # the typo hint points at the nearest declared name
    assert KNOB_GOOD in found[0].message


def test_knob_registry_missing_registry_is_a_finding(tmp_path):
    idx = project(tmp_path, {"trino_tpu/cfg.py": "X = 1\n"})
    found = findings_of("knob-registry", idx)
    assert len(found) == 1
    assert "knob registry missing or unreadable" in found[0].message


def test_knob_docs_missing_stale_fresh(tmp_path):
    idx = project(tmp_path, {"trino_tpu/spi/knobs.py": KNOBS_FIXTURE})
    missing = knob_registry.check_docs(idx)
    assert len(missing) == 1 and "docs/KNOBS.md missing" in missing[0].message

    knobdocs.write(str(tmp_path))
    assert knob_registry.check_docs(idx) == []

    docs = tmp_path / "docs" / "KNOBS.md"
    docs.write_text(docs.read_text() + "hand edit\n")
    stale = knob_registry.check_docs(idx)
    assert len(stale) == 1 and "stale vs the registry" in stale[0].message


# ----------------------------------------------------------- error-taxonomy

TAXONOMY_FIXTURE = """\
def risky(g):
    try:
        g()
    except:
        pass
    try:
        g()
    except Exception:
        pass
    raise RuntimeError("boom")

def fine(g):
    try:
        g()
    except FileNotFoundError:
        pass
    try:
        g()
    except Exception as e:
        g(e)
    raise NotImplementedError("feature gap")
"""


def test_error_taxonomy_flags_bare_blind_and_generic(tmp_path):
    idx = project(tmp_path,
                  {"trino_tpu/execution/bad.py": TAXONOMY_FIXTURE})
    found = findings_of("error-taxonomy", idx)
    assert len(found) == 3
    msgs = sorted(f.message for f in found)
    assert any("bare 'except:'" in m for m in msgs)
    assert any("blind 'except Exception: pass'" in m for m in msgs)
    assert any("raise RuntimeError on the query path" in m for m in msgs)
    # everything in fine() — narrow swallow, handled broad catch,
    # NotImplementedError — stays legal
    assert all(f.line <= 10 for f in found)


def test_error_taxonomy_scope_is_the_query_path(tmp_path):
    # the same code outside execution// exec/ is out of contract
    idx = project(tmp_path,
                  {"trino_tpu/connectors/bad.py": TAXONOMY_FIXTURE})
    assert findings_of("error-taxonomy", idx) == []


# ------------------------------------- suppression + baseline mechanics

def _run(tmp_path, **kw):
    return run_analysis(root=str(tmp_path), rule_names=["error-taxonomy"],
                        baseline_path=str(tmp_path / "bl.json"), **kw)


def test_suppression_same_line_and_own_line(tmp_path):
    src = (f'def a():\n'
           f'    raise RuntimeError("x")  {D}=error-taxonomy -- fixture: '
           f'same-line\n'
           f'\n'
           f'def b():\n'
           f'    {D}=error-taxonomy -- fixture: own-line\n'
           f'    raise ValueError("y")\n')
    project(tmp_path, {"trino_tpu/execution/sup.py": src})
    rep = _run(tmp_path)
    assert rep.clean
    assert len(rep.suppressed) == 2 and not rep.findings


def test_suppression_file_scope(tmp_path):
    src = (f'{DF}=error-taxonomy -- fixture: generated file\n'
           f'def a():\n'
           f'    raise RuntimeError("x")\n'
           f'def b():\n'
           f'    raise ValueError("y")\n')
    project(tmp_path, {"trino_tpu/execution/gen.py": src})
    rep = _run(tmp_path)
    assert rep.clean and len(rep.suppressed) == 2


def test_unused_suppression_is_a_finding(tmp_path):
    src = (f'{D}=error-taxonomy -- fixture: excuses nothing\n'
           f'def ok():\n'
           f'    return 1\n')
    project(tmp_path, {"trino_tpu/execution/sup.py": src})
    rep = _run(tmp_path)
    assert not rep.clean
    assert len(rep.findings) == 1
    f = rep.findings[0]
    assert f.rule == "unused-suppression" and "matches no finding" in f.message


def test_baseline_is_exact_not_a_ratchet(tmp_path):
    mod = tmp_path / "trino_tpu" / "execution" / "base.py"
    project(tmp_path, {"trino_tpu/execution/base.py":
                       'def f():\n    raise RuntimeError("grandfathered")\n'})
    rep1 = _run(tmp_path)
    assert len(rep1.findings) == 1 and not rep1.baselined

    # grandfather it: the identical run is clean and accounted baselined
    bl.write(rep1.findings, str(tmp_path / "bl.json"))
    rep2 = _run(tmp_path)
    assert rep2.clean and len(rep2.baselined) == 1

    # a second identical violation exceeds the baselined multiplicity
    mod.write_text('def f():\n    raise RuntimeError("grandfathered")\n'
                   'def g():\n    raise RuntimeError("grandfathered")\n')
    rep3 = _run(tmp_path)
    assert len(rep3.findings) == 1 and len(rep3.baselined) == 1

    # fixing the violation while the entry lingers turns the entry stale —
    # the baseline must shrink with the code, not outlive it
    mod.write_text("def f():\n    return 0\n")
    rep4 = _run(tmp_path)
    assert not rep4.findings and rep4.stale_baseline and not rep4.clean


# ----------------------------------------------- migrated rules (AST wins)

def test_net_timeout_sees_multiline_and_positional(tmp_path):
    src = """\
from urllib.request import urlopen

def fetch(url, data):
    return urlopen(
        url,
        data,
    )

def fetch_pos(url, data):
    return urlopen(url, data, 5.0)

def fetch_kw(url):
    return urlopen(url, timeout=1.0)
"""
    idx = project(tmp_path, {"trino_tpu/execution/net.py": src})
    found = findings_of("net-timeout", idx)
    # only the multi-line call without a timeout fires — the grep-era lint
    # could never see across the line break; positional timeouts count
    assert len(found) == 1
    assert found[0].message == "urlopen without timeout"
    assert found[0].line == 4


def test_cache_bounds_flags_unbounded_exempts_registry(tmp_path):
    src = """\
import functools

@functools.lru_cache
def memo(x):
    return x

@functools.lru_cache(maxsize=128)
def bounded(x):
    return x
"""
    idx = project(tmp_path, {
        "trino_tpu/util/memo.py": src,
        "trino_tpu/caching/executable_cache.py": src,  # sanctioned fallback
    })
    found = findings_of("cache-bounds", idx)
    assert [f.path for f in found] == ["trino_tpu/util/memo.py"]
    assert "unbounded memo cache" in found[0].message


def test_metric_names_framework_checks(tmp_path):
    src = """\
def setup(reg):
    reg.counter("trino_fixture_events_total", "doc")
    reg.counter("bad-name", "doc")
    reg.counter("trino_fixture_drops", "doc")
    reg.gauge("trino_fixture_depth", "doc")
    reg.gauge("trino_fixture_depth", "doc")
"""
    idx = project(tmp_path, {"trino_tpu/telemetry/fx.py": src})
    found = findings_of("metric-names", idx)
    local = [f for f in found if f.path == "trino_tpu/telemetry/fx.py"]
    msgs = sorted(f.message for f in local)
    assert len(local) == 3
    assert any("illegal Prometheus metric name" in m for m in msgs)
    assert any("must end in '_total'" in m for m in msgs)
    assert any("duplicate registration" in m for m in msgs)
    # the fixture tree has none of the contractual families — the
    # completeness check must notice
    assert any("trino_profile_" in f.message for f in found
               if f.path == "trino_tpu")


def test_hygiene_flags_debug_and_assert_free_modules(tmp_path):
    idx = project(tmp_path, {
        "tests/test_dbg_scratchpad.py": "print('hi')\n",
        "tests/test_quiet.py": "def test_x():\n    print(1)\n",
        "tests/test_good.py": "def test_y():\n    assert 1\n",
    })
    found = {f.path: f.message for f in findings_of("test-hygiene", idx)}
    assert "debug-leftover test file" in found["tests/test_dbg_scratchpad.py"]
    assert "no assertions" in found["tests/test_quiet.py"]
    assert "tests/test_good.py" not in found


# ------------------------------------------------------------ CLI + smoke

def test_cli_fixture_roundtrip(tmp_path):
    (tmp_path / "trino_tpu" / "execution").mkdir(parents=True)
    (tmp_path / "trino_tpu" / "execution" / "bad.py").write_text(
        'def f():\n    raise RuntimeError("boom")\n')
    base = [sys.executable, "-m", "tools.analysis",
            "--root", str(tmp_path), "--rules", "error-taxonomy",
            "--baseline", str(tmp_path / "bl.json")]
    dirty = subprocess.run(base + ["--json"], cwd=repo_root(),
                           capture_output=True, text=True)
    assert dirty.returncode == 1, dirty.stderr
    data = json.loads(dirty.stdout)
    assert [f["rule"] for f in data["findings"]] == ["error-taxonomy"]
    assert data["stats"]["clean"] is False

    upd = subprocess.run(base + ["--update-baseline"], cwd=repo_root(),
                         capture_output=True, text=True)
    assert upd.returncode == 0, upd.stderr
    clean = subprocess.run(base + ["--json"], cwd=repo_root(),
                           capture_output=True, text=True)
    assert clean.returncode == 0, clean.stderr
    assert json.loads(clean.stdout)["stats"]["baselined"] == 1


def test_repo_is_tpulint_clean():
    """The tier-1 gate: the whole tree passes every rule, and the committed
    baseline matches the live run entry-for-entry."""
    rep = run_analysis()
    detail = "\n".join(f.format() for f in rep.findings)
    if rep.stale_baseline:
        detail += f"\nstale baseline entries: {rep.stale_baseline}"
    assert rep.clean, f"tpulint violations:\n{detail}"
    # the full rule set ran over the real tree
    assert {"host-sync", "thread-safety", "knob-registry", "knob-docs",
            "error-taxonomy", "net-timeout", "metric-names", "cache-bounds",
            "journal-schema", "test-hygiene"} <= set(rep.rules_run)
    assert rep.files_scanned > 100
    # every committed grandfather entry still fires (exactness), and the
    # deliberate in-tree exceptions are actually exercised
    assert len(rep.baselined) == sum(bl.load().values())
    assert rep.suppressed, "expected at least one used in-tree suppression"
