"""MATCH_RECOGNIZE (reference: sql/planner/rowpattern/ + operator/window/
pattern/ — PatternRecognitionNode.java:47; behavior per SQL:2016 row
pattern recognition; examples follow the docs' stock-ticker cases)."""

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.exec.row_pattern import PatternMatcher, parse_pattern
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.runner import Session, StandaloneQueryRunner


@pytest.fixture(scope="module")
def runner():
    r = StandaloneQueryRunner(default_catalog(scale_factor=0.01),
                              session=Session(default_catalog="memory"))
    r.execute("create table ticker (symbol varchar, day bigint, price bigint)")
    r.execute("insert into ticker values "
              "('a',1,10),('a',2,8),('a',3,6),('a',4,9),('a',5,12),"
              "('a',6,11),('a',7,11),"
              "('b',1,5),('b',2,6),('b',3,4),('b',4,7)")
    return r


def test_v_shape(runner):
    rows = runner.execute("""
        select * from ticker match_recognize (
          partition by symbol order by day
          measures match_number() as mno, first(a.day) as sd,
                   last(down.day) as bd, last(up.day) as ed,
                   last(up.price) as ep
          one row per match after match skip past last row
          pattern (a down+ up+)
          define down as price < prev(price), up as price > prev(price)
        ) order by symbol, mno""").rows()
    assert rows == [("a", 1, 1, 3, 5, 12), ("b", 1, 2, 3, 4, 7)]


def test_classifier_and_aggregates(runner):
    rows = runner.execute("""
        select * from ticker match_recognize (
          partition by symbol order by day
          measures count(*) as n, avg(down.price) as adp,
                   classifier() as last_label
          pattern (a down+ up)
          define down as price < prev(price), up as price > prev(price)
        ) order by symbol""").rows()
    assert rows == [("a", 4, 7.0, "UP"), ("b", 3, 4.0, "UP")]


def test_quantifier_bounds(runner):
    # exactly two DOWN rows required
    rows = runner.execute("""
        select * from ticker match_recognize (
          partition by symbol order by day
          measures first(down.day) as d1, last(down.day) as d2
          pattern (down{2})
          define down as price < prev(price)
        ) order by symbol""").rows()
    assert rows == [("a", 2, 3)]  # b has no two consecutive downs


def test_alternation_and_skip_to_next(runner):
    rows = runner.execute("""
        select * from ticker match_recognize (
          partition by symbol order by day
          measures classifier() as c, last(day) as d
          after match skip to next row
          pattern (up | down)
          define up as price > prev(price), down as price < prev(price)
        ) order by symbol, d""").rows()
    # every strictly-moving day classified (day 1 has no prev; day 7 flat)
    assert [r for r in rows if r[0] == "a"] == [
        ("a", "DOWN", 2), ("a", "DOWN", 3), ("a", "UP", 4), ("a", "UP", 5),
        ("a", "DOWN", 6)]


def test_undefined_label_matches_all(runner):
    rows = runner.execute("""
        select * from ticker match_recognize (
          partition by symbol order by day
          measures count(*) as n
          pattern (x+)
          define x as true
        )""").rows()
    assert sorted(rows) == [("a", 7), ("b", 4)]


def test_distributed_match_recognize():
    d = DistributedQueryRunner(default_catalog(scale_factor=0.01),
                               worker_count=2,
                               session=Session(default_catalog="memory",
                                               node_count=2))
    d.execute("create table mt (g bigint, seq bigint, v bigint)")
    d.execute("insert into mt values (1,1,1),(1,2,2),(1,3,3),"
              "(2,1,5),(2,2,4),(2,3,6)")
    rows = d.execute("""
        select * from mt match_recognize (
          partition by g order by seq
          measures count(*) as rising
          pattern (up+)
          define up as v > prev(v)
        ) order by g""").rows()
    assert rows == [(1, 2), (2, 1)]


def test_alternation_backtracks_into_branches():
    # ((A B | A) B): the first alternative consumes both rows, the trailing
    # B fails, and the matcher must retry the A-only branch
    seq = "AB"

    def pred(l, i, ls):
        return seq[i] == l

    p = parse_pattern("(A B | A) B")
    m = PatternMatcher(p, pred).find_matches(len(seq))
    assert len(m) == 1 and m[0].labels == ["A", "B"]
    p2 = parse_pattern("(A B | A)+ B")
    m2 = PatternMatcher(p2, pred).find_matches(len(seq))
    assert len(m2) == 1 and m2[0].labels == ["A", "B"]


def test_match_number_in_define(runner):
    # MATCH_NUMBER() usable inside DEFINE: only the 2nd match fires
    rows = runner.execute("""
        select * from ticker match_recognize (
          partition by symbol order by day
          measures last(day) as d
          after match skip to next row
          pattern (dn)
          define dn as price < prev(price) and match_number() >= 2
        ) order by symbol, d""").rows()
    # symbol a downs at days 2,3,6: first candidate (day2) is match 1 and is
    # rejected by the predicate, so day2 never matches; days 3 and 6 do...
    # but rejecting match 1 means the counter stays 1 until a match lands.
    assert rows == []


def test_prev_with_label_anchor(runner):
    # PREV(A.price) navigates from the LAST A-labeled row, not current row
    rows = runner.execute("""
        select * from ticker match_recognize (
          partition by symbol order by day
          measures first(a.price) as ap, last(b.price) as bp
          pattern (a b)
          define b as price < prev(a.price, 0) - 1
        ) order by symbol""").rows()
    # b requires price < (last A row's price) - 1: symbol a matches at
    # days 1-2 (8 < 10-1); symbol b at days 2-3 (4 < 6-1 — the scan
    # retries from day 2 after day 1's candidate fails)
    assert rows == [("a", 10, 8), ("b", 6, 4)]


def test_pattern_engine_unit():
    # direct NFA checks: greedy + backtracking
    p = parse_pattern("A B* C")
    seq = "ABBBC"
    m = PatternMatcher(p, lambda l, i, ls: seq[i] == l).find_matches(len(seq))
    assert len(m) == 1 and m[0].labels == ["A", "B", "B", "B", "C"]
    # backtracking: B* must give back a row so C can match
    seq2 = "ABB"
    p2 = parse_pattern("A B* B")
    m2 = PatternMatcher(p2, lambda l, i, ls: seq2[i] == "A" if l == "A"
                        else seq2[i] == "B").find_matches(len(seq2))
    assert len(m2) == 1 and m2[0].end == 3


def test_min_max_at_exact_group_bucket():
    # num_groups == cap (power of two) with dead padded rows: the last
    # group's min/max must not read the trailing dead-row segment
    # (kernels seg_minmax ends side='right' regression)
    import numpy as np

    from trino_tpu.exec import kernels as K
    from trino_tpu.spi.batch import round_up_pow2

    groups = 8  # == bucket(8)
    per = 4
    n = groups * per
    cap_rows = round_up_pow2(n + 5)
    g = np.repeat(np.arange(groups, dtype=np.int64), per)
    v = np.arange(n, dtype=np.int64) + 100
    data = np.concatenate([g, np.zeros(cap_rows - n, np.int64)])
    vals = np.concatenate([v, np.zeros(cap_rows - n, np.int64)])
    live = np.concatenate([np.ones(n, bool), np.zeros(cap_rows - n, bool)])
    perm, gid, num = K.group_ids([(data, None)], live)
    assert num == groups
    out = K.grouped_reduce(perm, gid, num, [
        ("min", vals, live, np.int64, False),
        ("max", vals, live, np.int64, False)])
    assert list(np.asarray(out[0][0])) == [100 + i * per
                                           for i in range(groups)]
    assert list(np.asarray(out[1][0])) == [100 + i * per + per - 1
                                           for i in range(groups)]


def test_bare_day_column_parses(runner):
    # 'day' is a soft keyword (interval unit) AND a legal column name
    assert runner.execute(
        "select day from ticker where symbol = 'b' and day > 2 "
        "order by day").rows() == [(3,), (4,)]
