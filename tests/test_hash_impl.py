"""Hash-vs-sort equivalence for the TRINO_TPU_HASH_IMPL paths.

The open-addressing kernels (ops/pallas_kernels.hash_insert/hash_probe) run
here in interpret mode on the CPU test mesh — the identical programs compile
for real TPU lanes.  Every test drives the same inputs through both the
lexsort implementation and the Pallas hash implementation and asserts the
operator-level contracts agree: same group partitions, same join probe
ranges, bit-identical query output.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from trino_tpu.exec import join_exec as JX
from trino_tpu.exec import kernels as K
from trino_tpu.exec import syncguard as SG
from trino_tpu.ops import pallas_kernels as PK

pytestmark = pytest.mark.skipif(
    not PK.pallas_available(), reason="pallas not importable")


@pytest.fixture(autouse=True)
def _fresh_state(monkeypatch):
    # isolate the auto-mode failure latch and the impl knob per test
    monkeypatch.setitem(K._HASH_IMPL_STATE, "failed", False)
    monkeypatch.delenv("TRINO_TPU_HASH_IMPL", raising=False)
    monkeypatch.delenv("TRINO_TPU_HASH_INTERPRET", raising=False)


def _partition_map(n, perm, gid, num_groups):
    """row -> group id (or None for dead rows), as assigned by one impl."""
    out = [None] * n
    p = np.asarray(perm)
    g = np.asarray(gid)
    for i in range(n):
        out[p[i]] = int(g[i]) if g[i] < num_groups else None
    return out

def assert_same_partition(keys, live, n):
    """group_ids and hash_group_ids agree up to group-id relabeling."""
    p1, g1, ng1 = K.group_ids(keys, live)
    p2, g2, ng2 = K.hash_group_ids(keys, live)
    assert ng1 == ng2
    a = _partition_map(n, p1, g1, ng1)
    b = _partition_map(n, p2, g2, ng2)
    fwd = {}
    for x, y in zip(a, b):
        assert (x is None) == (y is None)
        if x is None:
            continue
        assert fwd.setdefault(x, y) == y, "rows co-grouped by one impl split"
    assert len(fwd) == ng1
    # gid contract holds for the hash impl too: nondecreasing, dead rows last
    g2 = np.asarray(g2)
    assert (np.diff(g2) >= 0).all()
    return ng1


# ---------------------------------------------------------------------------
# kernel level


def test_insert_probe_roundtrip_with_dead_rows():
    rng = np.random.default_rng(0)
    n, S = 3000, 8192
    key = rng.integers(0, 500, n).astype(np.uint32)
    planes = jnp.asarray(key)[None, :]
    h32 = jnp.asarray(key * np.uint32(2654435761), jnp.uint32)
    live = jnp.asarray(rng.random(n) < 0.9)
    gid, count, table, sgid = PK.hash_insert(
        planes, h32, live, S, interpret=True)
    gid, c = np.asarray(gid), int(count)
    lv = np.asarray(live)
    assert c == len(np.unique(key[lv]))
    assert (gid[~lv] == S).all()
    # same key -> same gid; distinct keys -> distinct gids; ids dense
    seen = {}
    for k, g in zip(key[lv], gid[lv]):
        assert seen.setdefault(int(k), int(g)) == int(g)
    assert sorted(seen.values()) == list(range(c))
    # probe: present keys hit their gid, absent keys miss with -1
    pk = np.concatenate([key[:100], np.arange(1000, 1100).astype(np.uint32)])
    ph = jnp.asarray(pk * np.uint32(2654435761), jnp.uint32)
    pg = np.asarray(PK.hash_probe(table, sgid, jnp.asarray(pk)[None, :], ph,
                                  interpret=True))
    for k, g in zip(pk[:100], pg[:100]):
        if int(k) in seen:
            assert g == seen[int(k)]
    assert (pg[100:] == -1).all()


def test_insert_probe_collision_heavy_same_slots():
    # adversarial hash: every key lands in one of FOUR slots, so almost all
    # placements resolve by in-kernel linear probing, not by the hash
    n, S = 2048, 4096
    key = (np.arange(n) % 37).astype(np.uint32)
    h32 = jnp.asarray(key % 4, jnp.uint32)
    planes = jnp.asarray(key)[None, :]
    gid, count, table, sgid = PK.hash_insert(
        planes, h32, None, S, interpret=True)
    gid, c = np.asarray(gid), int(count)
    assert c == 37
    seen = {}
    for k, g in zip(key, gid):
        assert seen.setdefault(int(k), int(g)) == int(g)
    assert sorted(seen.values()) == list(range(37))
    pg = np.asarray(PK.hash_probe(table, sgid, planes, h32, interpret=True))
    assert (pg == gid).all()


# ---------------------------------------------------------------------------
# grouping equivalence


def test_group_ids_equivalence_nullable_ints():
    rng = np.random.default_rng(1)
    n = 4096
    keys = [(jnp.asarray(rng.integers(-40, 40, n).astype(np.int64)),
             jnp.asarray(rng.random(n) < 0.85))]
    live = jnp.asarray(rng.random(n) < 0.9)
    assert_same_partition(keys, live, n)


def test_group_ids_equivalence_float_specials():
    specials = np.array([np.nan, -np.nan, 0.0, -0.0, np.inf, -np.inf,
                         1.5, -1.5, 1e300, 1e-300])
    rng = np.random.default_rng(2)
    n = 2000
    k1 = jnp.asarray(specials[rng.integers(0, len(specials), n)])
    k2 = jnp.asarray(rng.integers(0, 3, n).astype(np.int64))
    ng = assert_same_partition([(k1, None), (k2, None)], None, n)
    # -0 == 0 and NaN is ONE group under SQL grouping: 8 values x 3
    assert ng == 24


def test_group_ids_equivalence_all_duplicates_and_bool():
    n = 1024
    keys = [(jnp.zeros(n, jnp.int64), None)]
    assert assert_same_partition(keys, None, n) == 1
    rng = np.random.default_rng(3)
    keys = [(jnp.asarray(rng.random(n) < 0.5),
             jnp.asarray(rng.random(n) < 0.7))]
    assert assert_same_partition(keys, None, n) == 3  # True / False / NULL


def test_hash_group_ids_empty_input():
    perm, gid, ng = K.hash_group_ids(
        [(jnp.zeros(0, jnp.int64), None)], None)
    assert ng == 0 and perm.shape == (0,) and gid.shape == (0,)


def test_group_ids_auto_routing(monkeypatch):
    n = 512
    keys = [(jnp.asarray(np.arange(n) % 9, ), None)]
    calls = []
    orig = K.hash_group_ids

    def spy(*a, **kw):
        calls.append(1)
        return orig(*a, **kw)

    monkeypatch.setattr(K, "hash_group_ids", spy)
    monkeypatch.setenv("TRINO_TPU_HASH_IMPL", "sort")
    K.group_ids_auto(keys, None)
    assert not calls
    monkeypatch.setenv("TRINO_TPU_HASH_IMPL", "pallas")
    _, _, ng = K.group_ids_auto(keys, None)
    assert calls and ng == 9


# ---------------------------------------------------------------------------
# join probe ranges: value-identical (lo, counts, total) between impls


def _ranges(impl, monkeypatch, bk, bv, blive, pk, pv, plive):
    monkeypatch.setenv("TRINO_TPU_HASH_IMPL", impl)
    t = JX.build_table(
        [(jnp.asarray(bk), None if bv is None else jnp.asarray(bv))],
        live=None if blive is None else jnp.asarray(blive),
        num_rows=len(bk))
    assert (t.hash_idx is not None) == (impl == "pallas" and len(bk) > 0)
    lo, counts, total = JX.probe_ranges_device(
        t, [(jnp.asarray(pk), None if pv is None else jnp.asarray(pv))],
        [None], None if plive is None else jnp.asarray(plive))
    return np.asarray(lo), np.asarray(counts), int(total.get())


def test_join_ranges_equivalence(monkeypatch):
    rng = np.random.default_rng(7)
    nb, npr = 4000, 6000
    bk = rng.integers(0, 500, nb).astype(np.int64)
    bv = rng.random(nb) < 0.9
    blive = rng.random(nb) < 0.95
    pk = rng.integers(0, 700, npr).astype(np.int64)  # some keys miss
    pv = rng.random(npr) < 0.9
    plive = rng.random(npr) < 0.95
    lo1, c1, t1 = _ranges("sort", monkeypatch, bk, bv, blive, pk, pv, plive)
    lo2, c2, t2 = _ranges("pallas", monkeypatch, bk, bv, blive, pk, pv, plive)
    assert t1 == t2
    assert (c1 == c2).all()
    m = c1 > 0
    assert (lo1[m] == lo2[m]).all()  # lo only meaningful where rows match


def test_join_ranges_empty_build_side(monkeypatch):
    empty = np.empty(0, np.int64)
    pk = np.arange(50, dtype=np.int64)
    lo1, c1, t1 = _ranges("sort", monkeypatch, empty, None, None,
                          pk, None, None)
    lo2, c2, t2 = _ranges("pallas", monkeypatch, empty, None, None,
                          pk, None, None)
    assert t1 == t2 == 0
    assert (c1 == 0).all() and (c2 == 0).all()


def test_join_hash_probe_zero_hot_loop_syncs(monkeypatch):
    # steady state: index build + probe ranges never block on the device
    monkeypatch.setenv("TRINO_TPU_HASH_IMPL", "pallas")
    rng = np.random.default_rng(9)
    bk = rng.integers(0, 300, 2000).astype(np.int64)
    t = JX.build_table([(jnp.asarray(bk), None)], num_rows=len(bk))
    assert t.hash_idx is not None
    pk = jnp.asarray(rng.integers(0, 400, 3000).astype(np.int64))
    before = SG.snapshot()
    with SG.hot_region():
        lo, counts, total = JX.probe_ranges_device(t, [(pk, None)], [None])
    delta = SG.take_delta(before)
    assert delta.hot_loop_syncs == 0
    assert delta.blocking_syncs == 0
    assert int(total.get()) > 0  # the one sanctioned fetch, outside the loop


# ---------------------------------------------------------------------------
# operator level: bit-identical query output under both impls


def _query_rows(monkeypatch, impl, sql, runner):
    monkeypatch.setenv("TRINO_TPU_HASH_IMPL", impl)
    return runner.execute(sql).rows()


@pytest.fixture(scope="module")
def tpch_runner():
    from trino_tpu.connectors.catalog import default_catalog
    from trino_tpu.runner import StandaloneQueryRunner

    return StandaloneQueryRunner(default_catalog(scale_factor=0.01))


def test_group_by_query_bit_identical(monkeypatch, tpch_runner):
    # l_partkey is numeric + high-NDV: bypasses the small-codes fast path,
    # so the aggregation genuinely routes through group_ids_auto
    sql = ("select l_partkey, count(*), sum(l_quantity), min(l_extendedprice)"
           " from lineitem group by l_partkey order by l_partkey")
    sort_rows = _query_rows(monkeypatch, "sort", sql, tpch_runner)
    hash_rows = _query_rows(monkeypatch, "pallas", sql, tpch_runner)
    assert sort_rows == hash_rows
    assert len(sort_rows) > 100


def test_join_query_bit_identical(monkeypatch, tpch_runner):
    # duplicate-keyed build side keeps the join off the unique fast path
    sql = ("select o_orderpriority, count(*) from orders, lineitem "
           "where o_orderkey = l_orderkey and l_quantity < 10 "
           "group by o_orderpriority order by o_orderpriority")
    sort_rows = _query_rows(monkeypatch, "sort", sql, tpch_runner)
    hash_rows = _query_rows(monkeypatch, "pallas", sql, tpch_runner)
    assert sort_rows == hash_rows
    assert len(sort_rows) == 5


# ---------------------------------------------------------------------------
# static partial-agg reuse of the same kernels


def test_static_agg_hash_route_equivalence(monkeypatch):
    from trino_tpu.parallel.static_agg import AggSpec, static_grouped_agg

    rng = np.random.default_rng(11)
    n, cap = 3000, 1024
    k1 = jnp.asarray(rng.integers(0, 200, n).astype(np.int64))
    v1 = jnp.asarray(rng.random(n) < 0.9)
    k2 = jnp.asarray(rng.integers(0, 3, n).astype(np.int64))
    data = jnp.asarray(rng.standard_normal(n))
    dval = jnp.asarray(rng.random(n) < 0.85)
    mask = jnp.asarray(rng.random(n) < 0.9)
    aggs = [(AggSpec("sum", jnp.float64), data, dval),
            (AggSpec("count_star", jnp.int64), None, None),
            (AggSpec("min", jnp.float64), data, dval)]

    def run(impl):
        monkeypatch.setenv("TRINO_TPU_HASH_IMPL", impl)
        r = static_grouped_agg([k1, k2], [v1, None], aggs, cap,
                               row_mask=mask)
        ng = int(r.num_groups)
        assert ng <= cap  # stay out of the overflow regime for comparison
        rows = []
        for i in range(ng):
            rows.append((
                int(r.keys[0][i]), bool(r.key_valids[0][i]),
                int(r.keys[1][i]),
                round(float(r.values[0][i]), 9),
                bool(r.value_valids[0][i]),
                int(r.values[1][i]),
                round(float(r.values[2][i]), 9),
                bool(r.value_valids[2][i])))
        return ng, sorted(rows)

    ng1, rows1 = run("sort")
    ng2, rows2 = run("pallas")
    # slot ORDER differs (first occurrence vs key order); content must not
    assert ng1 == ng2
    assert rows1 == rows2


# ---------------------------------------------------------------------------
# bench-scale leg, excluded from tier-1 by the slow marker


@pytest.mark.slow
def test_group_ids_equivalence_1m_ndv():
    rng = np.random.default_rng(42)
    n = 2_000_000
    keys = [(jnp.asarray(rng.integers(0, 1_500_000, n).astype(np.int64)),
             None)]
    p1, g1, ng1 = K.group_ids(keys, None)
    p2, g2, ng2 = K.hash_group_ids(keys, None)
    assert ng1 == ng2
    # spot-check co-grouping on a sample instead of the O(n) python loop
    a = np.empty(n, np.int64)
    b = np.empty(n, np.int64)
    a[np.asarray(p1)] = np.asarray(g1)
    b[np.asarray(p2)] = np.asarray(g2)
    idx = rng.integers(0, n, 50_000)
    k = np.asarray(keys[0][0])
    for i, j in zip(idx[:-1], idx[1:]):
        assert (a[i] == a[j]) == (k[i] == k[j])
        assert (b[i] == b[j]) == (k[i] == k[j])
