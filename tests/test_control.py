"""Control plane: state machines, dispatcher + resource groups, discovery +
heartbeat failure detection (reference: execution/StateMachine.java:43,
QueryState.java:26, dispatcher/DispatchManager.java:72,
resourcegroups/InternalResourceGroup.java:75,
failuredetector/HeartbeatFailureDetector.java:76)."""

import threading
import time

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.execution.control import (
    DispatchManager,
    HeartbeatFailureDetector,
    NodeManager,
    QueryStateMachine,
    ResourceGroup,
    StateMachine,
)
from trino_tpu.execution.distributed_runner import DistributedQueryRunner


def test_state_machine_listeners_and_terminal():
    fsm = StateMachine("t", "A", {"DONE"})
    seen = []
    fsm.add_listener(seen.append)
    fsm.set("B")
    fsm.set("DONE")
    assert not fsm.set("B")  # terminal absorbs
    assert seen == ["A", "B", "DONE"]
    assert fsm.is_terminal()


def test_query_fsm_lifecycle():
    fsm = QueryStateMachine("q1")
    for s in ("WAITING_FOR_RESOURCES", "DISPATCHING", "PLANNING",
              "STARTING", "RUNNING", "FINISHING"):
        assert fsm.set(s)
    fsm.finish()
    assert fsm.state == "FINISHED"
    assert fsm.end_time is not None


def test_resource_group_concurrency_queueing():
    g = ResourceGroup("root", hard_concurrency_limit=1, max_queued=10)
    g.acquire()
    order = []

    def queued_worker(i):
        g.acquire(timeout=10)
        order.append(i)
        g.release()

    ts = [threading.Thread(target=queued_worker, args=(i,)) for i in range(3)]
    for t in ts:
        t.start()
        time.sleep(0.05)  # deterministic FIFO enqueue order
    assert g.queued == 3 and g.running == 1
    g.release()
    for t in ts:
        t.join(timeout=10)
    assert order == [0, 1, 2]  # FIFO admission
    assert g.running == 0


def test_resource_group_queue_full():
    g = ResourceGroup("root", hard_concurrency_limit=1, max_queued=0)
    g.acquire()
    with pytest.raises(RuntimeError):
        g.acquire()
    g.release()


def test_hierarchical_limits():
    root = ResourceGroup("root", hard_concurrency_limit=1)
    a = root.subgroup("a", hard_concurrency_limit=5)
    a.acquire()
    assert root.running == 1 and a.running == 1
    # parent limit binds even though the child has slots
    done = []
    t = threading.Thread(target=lambda: (a.acquire(timeout=10),
                                         done.append(1), a.release()))
    t.start()
    time.sleep(0.1)
    assert not done
    a.release()
    t.join(timeout=10)
    assert done


def test_dispatcher_tracks_queries():
    d = DispatchManager()
    out = d.submit("select 1", None, lambda fsm: 42)
    assert out == 42
    infos = d.queries()
    assert len(infos) == 1 and infos[0].state == "FINISHED"
    with pytest.raises(ValueError):
        d.submit("select boom", None,
                 lambda fsm: (_ for _ in ()).throw(ValueError("x")))
    assert d.queries()[-1].state == "FAILED"


def test_node_manager_heartbeats_and_drain():
    nm = NodeManager(heartbeat_timeout=0.2)
    nm.announce("w0")
    nm.announce("w1")
    assert nm.active_workers() == ["w0", "w1"]
    nm.drain("w1")
    assert nm.active_workers() == ["w0"]
    time.sleep(0.3)
    assert nm.active_workers() == []  # heartbeats expired
    nm.announce("w0")
    assert nm.active_workers() == ["w0"]


def test_failure_detector_marks_and_recovers():
    nm = NodeManager(heartbeat_timeout=60)
    nm.announce("w0")
    alive = {"up": True}
    fd = HeartbeatFailureDetector(nm, interval=0.05)
    fd.monitor("w0", lambda: alive["up"])
    fd.ping_once()
    assert fd.failed_nodes() == set()
    alive["up"] = False
    fd.ping_once()
    assert fd.failed_nodes() == {"w0"}
    alive["up"] = True
    fd.ping_once()
    assert fd.failed_nodes() == set()


def test_runner_routes_through_dispatcher_and_sheds_dead_workers():
    runner = DistributedQueryRunner(default_catalog(scale_factor=0.01),
                                    worker_count=3)
    sql = "select n_regionkey, count(*) from tpch.nation group by n_regionkey order by 1"
    expect = runner.execute(sql).rows()
    assert runner.dispatcher.queries()[-1].state == "FINISHED"
    assert runner.active_worker_count == 3
    # kill one worker's heartbeat: placement shrinks, results unchanged
    runner.failure_detector.monitor("worker-2", lambda: False)
    runner.nodes.remove("worker-2")
    assert runner.active_worker_count == 2
    assert runner.execute(sql).rows() == expect
    # graceful drain of another
    runner.nodes.drain("worker-1")
    assert runner.active_worker_count == 1
    assert runner.execute(sql).rows() == expect
