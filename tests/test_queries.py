"""End-to-end TPC-H query correctness vs the sqlite oracle.

The reference's H2QueryRunner pattern (testing/trino-testing/.../
H2QueryRunner.java:91, AbstractTestQueryFramework.assertQuery:338): every
query runs both on the engine and on sqlite over identical data; results are
compared as (optionally ordered) multisets with float tolerance.
"""

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.connectors.tpch_queries import QUERIES
from trino_tpu.runner import StandaloneQueryRunner
from trino_tpu.testing.oracle import SqliteOracle, assert_same_rows

TABLES = ["nation", "region", "supplier", "customer", "part", "partsupp",
          "orders", "lineitem"]


@pytest.fixture(scope="module")
def harness():
    catalog = default_catalog(scale_factor=0.01)
    runner = StandaloneQueryRunner(catalog)
    oracle = SqliteOracle()
    conn = catalog.connector("tpch")
    for t in TABLES:
        schema = conn.get_table_schema(t)
        cols = schema.column_names()
        splits = conn.get_splits(t, 2, 1)
        batches = []
        for s in splits:
            src = conn.create_page_source(s, cols)
            while not src.is_finished():
                b = src.get_next_batch()
                if b is not None:
                    batches.append(b)
        oracle.load_table(t, batches)
    return runner, oracle


def _check(harness, sql, ordered):
    runner, oracle = harness
    actual = runner.execute(sql).rows()
    expected = oracle.query(sql)
    assert_same_rows(actual, expected, ordered=ordered)


# queries whose results are ORDER BY'd on all output rows
_ORDERED = {1, 2, 3, 5, 7, 8, 9, 10, 11, 12, 13, 14, 16, 18, 21, 22}


@pytest.mark.parametrize("q", sorted(QUERIES))
def test_tpch(harness, q):
    _check(harness, QUERIES[q], ordered=q in _ORDERED)


def test_simple_select(harness):
    _check(harness, "select n_name, n_regionkey from nation where n_regionkey = 1", False)


def test_limit(harness):
    runner, _ = harness
    rows = runner.execute("select o_orderkey from orders limit 7").rows()
    assert len(rows) == 7


def test_global_agg_empty_input(harness):
    runner, _ = harness
    rows = runner.execute(
        "select count(*), sum(o_totalprice) from orders where o_orderkey < 0"
    ).rows()
    assert rows == [(0, None)]


def test_distinct(harness):
    _check(harness, "select distinct o_orderstatus from orders", False)


def test_insert_and_read_memory(harness):
    runner, _ = harness
    runner.execute(
        "create table memory.t1 as select n_nationkey, n_name from nation")
    rows = runner.execute(
        "select n_name from memory.t1 where n_nationkey = 3").rows()
    assert rows == [("CANADA",)]
