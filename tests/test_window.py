"""Window function correctness vs the sqlite oracle.

Mirrors the reference's AbstractTestWindowQueries pattern (testing/
trino-testing/.../AbstractTestWindowQueries.java): every query runs on the
engine and on sqlite (3.25+ window support) over identical TPC-H data.
"""

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.runner import StandaloneQueryRunner
from trino_tpu.testing.oracle import SqliteOracle, assert_same_rows

TABLES = ["nation", "region", "orders", "lineitem"]


@pytest.fixture(scope="module", autouse=True)
def _map_headroom():
    """The full tier-1 run reaches this module (alphabetically last)
    close to the process vm.max_map_count ceiling — each jitted
    executable pins ~20 mapped regions — and the window kernels compiled
    here are among the suite's largest, so the next backend_compile can
    segfault.  Dropping every cached executable first reclaims the maps
    (verified: ~1600 -> ~400 regions) at no downstream cost: nothing runs
    after this module, and this module's own shapes are fresh compiles
    either way.  Held jit wrappers stay callable; they just recompile."""
    import gc

    import jax

    jax.clear_caches()
    gc.collect()


@pytest.fixture(scope="module")
def harness():
    catalog = default_catalog(scale_factor=0.01)
    runner = StandaloneQueryRunner(catalog)
    oracle = SqliteOracle()
    conn = catalog.connector("tpch")
    for t in TABLES:
        schema = conn.get_table_schema(t)
        cols = schema.column_names()
        splits = conn.get_splits(t, 2, 1)
        batches = []
        for s in splits:
            src = conn.create_page_source(s, cols)
            while not src.is_finished():
                b = src.get_next_batch()
                if b is not None:
                    batches.append(b)
        oracle.load_table(t, batches)
    return runner, oracle


def _check(harness, sql, ordered=False):
    runner, oracle = harness
    actual = runner.execute(sql).rows()
    expected = oracle.query(sql)
    assert_same_rows(actual, expected, ordered=ordered)


def test_row_number(harness):
    _check(harness, """
        select n_name, row_number() over (order by n_name) rn from nation
        order by n_name""", ordered=True)


def test_row_number_partitioned(harness):
    _check(harness, """
        select n_name, n_regionkey,
               row_number() over (partition by n_regionkey order by n_name) rn
        from nation order by n_regionkey, n_name""", ordered=True)


def test_rank_dense_rank(harness):
    _check(harness, """
        select o_orderpriority,
               rank() over (order by o_orderpriority) rk,
               dense_rank() over (order by o_orderpriority) drk
        from orders""")


def test_rank_no_order(harness):
    # every row is a peer: rank 1, count = partition size
    _check(harness, """
        select n_name, rank() over (partition by n_regionkey) rk,
               count(*) over (partition by n_regionkey) c
        from nation""")


def test_running_sum_range(harness):
    _check(harness, """
        select o_orderkey, o_custkey,
               sum(o_totalprice) over (partition by o_custkey
                                       order by o_orderkey) s
        from orders""")


def test_running_sum_rows(harness):
    _check(harness, """
        select o_orderkey,
               sum(o_totalprice) over (order by o_orderkey
                   rows between unbounded preceding and current row) s
        from orders""")


def test_sliding_window_sum_avg(harness):
    _check(harness, """
        select o_orderkey,
               sum(o_totalprice) over (order by o_orderkey
                   rows between 3 preceding and 1 following) s,
               avg(o_totalprice) over (order by o_orderkey
                   rows between 2 preceding and 2 following) a,
               count(*) over (order by o_orderkey
                   rows between 3 preceding and 1 following) c
        from orders where o_orderkey < 1000""")


def test_whole_partition_agg(harness):
    _check(harness, """
        select o_orderkey, o_custkey,
               sum(o_totalprice) over (partition by o_custkey) s,
               count(*) over () c
        from orders""")


def test_min_max_running(harness):
    _check(harness, """
        select o_orderkey,
               min(o_totalprice) over (partition by o_orderpriority
                                       order by o_orderkey) mn,
               max(o_totalprice) over (partition by o_orderpriority
                                       order by o_orderkey) mx
        from orders""")


def test_min_max_whole_partition(harness):
    _check(harness, """
        select n_name,
               min(n_name) over (partition by n_regionkey) mn,
               max(n_name) over (partition by n_regionkey) mx
        from nation""")


def test_lag_lead(harness):
    _check(harness, """
        select o_orderkey,
               lag(o_totalprice) over (order by o_orderkey) l1,
               lag(o_totalprice, 2) over (order by o_orderkey) l2,
               lead(o_totalprice) over (order by o_orderkey) d1,
               lag(o_totalprice, 1, 0.0) over (order by o_orderkey) ld
        from orders where o_orderkey < 500""")


def test_lag_partitioned(harness):
    _check(harness, """
        select o_custkey, o_orderkey,
               lag(o_orderkey) over (partition by o_custkey
                                     order by o_orderkey) prev
        from orders""")


def test_first_last_value(harness):
    _check(harness, """
        select o_orderkey,
               first_value(o_totalprice) over (partition by o_orderpriority
                                               order by o_orderkey) f,
               last_value(o_totalprice) over (partition by o_orderpriority
                   order by o_orderkey
                   rows between unbounded preceding
                            and unbounded following) l
        from orders where o_orderkey < 1000""")


def test_nth_value(harness):
    _check(harness, """
        select o_orderkey,
               nth_value(o_totalprice, 3) over (order by o_orderkey
                   rows between unbounded preceding
                            and unbounded following) v
        from orders where o_orderkey < 300""")


def test_ntile(harness):
    _check(harness, """
        select n_name, ntile(4) over (order by n_name) t from nation""")


def test_ntile_more_buckets_than_rows(harness):
    _check(harness, """
        select r_name, ntile(10) over (order by r_name) t from region""")


def test_percent_rank_cume_dist(harness):
    _check(harness, """
        select o_orderpriority,
               percent_rank() over (order by o_orderpriority) pr,
               cume_dist() over (order by o_orderpriority) cd
        from orders where o_orderkey < 2000""")


def test_window_over_group_by(harness):
    _check(harness, """
        select o_orderpriority, count(*) cnt,
               rank() over (order by count(*) desc) rk
        from orders group by o_orderpriority""")


def test_window_with_join(harness):
    _check(harness, """
        select n_name, r_name,
               row_number() over (partition by r_name order by n_name) rn
        from nation, region where n_regionkey = r_regionkey""")


def test_window_then_order_limit(harness):
    runner, oracle = harness
    sql = """
        select o_orderkey,
               rank() over (order by o_totalprice desc) rk
        from orders order by rk, o_orderkey limit 10"""
    assert_same_rows(runner.execute(sql).rows(), oracle.query(sql),
                     ordered=True)


def test_multiple_window_specs(harness):
    _check(harness, """
        select o_orderkey,
               row_number() over (order by o_totalprice desc) a,
               row_number() over (order by o_orderkey) b,
               sum(o_totalprice) over (partition by o_custkey) c
        from orders where o_orderkey < 1000""")


def test_window_desc_order(harness):
    _check(harness, """
        select o_orderkey,
               row_number() over (order by o_totalprice desc, o_orderkey) rn
        from orders where o_orderkey < 500""")


def test_window_in_subquery(harness):
    _check(harness, """
        select o_orderkey, rk from (
            select o_orderkey,
                   rank() over (order by o_totalprice desc) rk
            from orders) t
        where rk <= 5""")


def test_avg_over_decimal(harness):
    _check(harness, """
        select l_orderkey, l_linenumber,
               avg(l_quantity) over (partition by l_orderkey) a
        from lineitem where l_orderkey < 100""")


def test_count_column_with_nulls_semantics(harness):
    # count(col) over counts non-null rows only
    _check(harness, """
        select o_orderkey,
               count(o_clerk) over (order by o_orderkey) c
        from orders where o_orderkey < 300""")


def test_window_requires_over(harness):
    runner, _ = harness
    with pytest.raises(Exception, match="OVER"):
        runner.execute("select rank() from nation")
