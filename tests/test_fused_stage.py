"""Whole-stage GSPMD compilation (execution/stage_compiler.py): fragmenter-
marked PARTIAL->shuffle->FINAL seams run as ONE jitted accumulate call per
batch-bucket plus ONE seam-merge program, equivalent to the legacy
per-operator + collective-exchange path on the 8-device CPU mesh.

Equivalence contract: integer / decimal / string / count outputs are
bit-identical; float64 sums and averages may differ in the last bits
because the fused state merge reassociates the additions ((a+b)+(c+d)
instead of the legacy fold-left) — asserted here at rel 1e-12, far inside
the oracle's 1e-6 envelope.  ``TRINO_TPU_FUSED_STAGE=0`` preserves the
legacy path bit-for-bit (it IS the legacy path)."""

import math

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.connectors.tpch_queries import QUERIES
from trino_tpu.exec import syncguard as SG
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.execution.fragmenter import fragment_plan
from trino_tpu.runner import Session
from trino_tpu.testing.oracle import SqliteOracle, assert_same_rows

TABLES = ["customer", "orders", "lineitem"]


@pytest.fixture(autouse=True)
def _no_result_cache(monkeypatch):
    # these tests introspect execution internals (_fused_edges, SyncGuard
    # deltas) on repeated statements — a served cached result would skip
    # the very path under test
    monkeypatch.setenv("TRINO_TPU_RESULT_CACHE", "0")
    # whole-QUERY resident compilation would absorb the q3 seam this file
    # exercises in isolation (tests/test_resident_plan.py covers it)
    monkeypatch.setenv("TRINO_TPU_RESIDENT_PLAN", "0")

AGG_SQL = """
select l_returnflag, l_linestatus,
       sum(l_quantity), sum(l_extendedprice), min(l_quantity),
       max(l_extendedprice), avg(l_discount), avg(l_quantity),
       count(l_shipdate), count(*)
from lineitem
group by l_returnflag, l_linestatus
"""


@pytest.fixture(scope="module")
def harness():
    catalog = default_catalog(scale_factor=0.01)
    dist = DistributedQueryRunner(
        catalog, worker_count=4, session=Session(node_count=4))
    oracle = SqliteOracle()
    conn = catalog.connector("tpch")
    for t in TABLES:
        schema = conn.get_table_schema(t)
        cols = schema.column_names()
        batches = []
        for s in conn.get_splits(t, 2, 1):
            src = conn.create_page_source(s, cols)
            while not src.is_finished():
                b = src.get_next_batch()
                if b is not None:
                    batches.append(b)
        oracle.load_table(t, batches)
    return dist, oracle


def _rows(result):
    return sorted(map(tuple, result.rows()))


def _assert_equiv(fused_rows, legacy_rows):
    """Bit-identical for everything except f64 (reassociation, see module
    docstring)."""
    assert len(fused_rows) == len(legacy_rows)
    for fr, lr in zip(fused_rows, legacy_rows):
        assert len(fr) == len(lr)
        for fv, lv in zip(fr, lr):
            if isinstance(fv, float) or isinstance(lv, float):
                assert math.isclose(float(fv), float(lv),
                                    rel_tol=1e-12, abs_tol=1e-12), (fv, lv)
            else:
                assert fv == lv, (fv, lv)


def _run_both(dist, monkeypatch, sql):
    monkeypatch.setenv("TRINO_TPU_FUSED_STAGE", "auto")
    fused = dist.execute(sql)
    fused_edges = dict(dist._fused_edges)
    monkeypatch.setenv("TRINO_TPU_FUSED_STAGE", "0")
    legacy = dist.execute(sql)
    assert not dist._fused_edges, "=0 must disable whole-stage compilation"
    return fused, legacy, fused_edges


def test_fragmenter_marks_fused_seam(harness):
    dist, _ = harness
    plan = dist.create_plan(AGG_SQL)
    sp = fragment_plan(plan)
    seams = [f for f in sp.all_fragments() if f.fused_seam is not None]
    assert len(seams) == 1
    f = seams[0]
    assert f.device_resident and f.output_kind == "REPARTITION"
    assert f.fused_seam.nk == 2
    # the seam PartitionSpec contract: both sides shard dim 0 on the mesh axis
    assert f.fused_seam.in_spec == f.fused_seam.out_spec == ("x",)
    assert "fused-seam->" in sp.text() and "device-resident" in sp.text()


def test_agg_only_stage_fused_vs_legacy(harness, monkeypatch):
    """sum/min/max/avg/count (+ decimal-scale avg, date count, string group
    keys) through one fused program per batch-bucket; ragged last batches
    land in pad buckets."""
    dist, oracle = harness
    fused, legacy, edges = _run_both(dist, monkeypatch, AGG_SQL)
    assert edges, "expected a fused stage seam"
    (ex,) = edges.values()
    assert ex.stats.merges == 1, "fused stage must run ONE seam merge"
    assert ex.stats.jit_calls == ex.stats.batches, \
        "fused stage must be ONE jitted call per batch"
    _assert_equiv(_rows(fused), _rows(legacy))
    assert_same_rows(fused.rows(), oracle.query(AGG_SQL))
    assert_same_rows(legacy.rows(), oracle.query(AGG_SQL))


def test_join_fed_stage_fused_vs_legacy(harness, monkeypatch):
    """q3: the fused stage's feed is a join pipeline (build/probe stays on
    the legacy operators, the PARTIAL->shuffle->FINAL tail fuses)."""
    dist, oracle = harness
    fused, legacy, edges = _run_both(dist, monkeypatch, QUERIES[3])
    assert edges, "expected a fused stage over the join feed"
    _assert_equiv(_rows(fused), _rows(legacy))
    assert_same_rows(fused.rows(), oracle.query(QUERIES[3]), ordered=True)


def test_shape_bucket_cache_bounds_retraces(harness, monkeypatch):
    """Compiles are O(#buckets), not O(#batches): a second identical run
    hits the shape-bucket cache for EVERY dispatch."""
    dist, _ = harness
    monkeypatch.setenv("TRINO_TPU_FUSED_STAGE", "auto")
    dist.execute(AGG_SQL)  # warm: traces one program per shape bucket
    dist.execute(AGG_SQL)
    (ex,) = dist._fused_edges.values()
    assert ex.stats.batches > 0
    assert ex.stats.compiles == 0, "steady-state traffic must never retrace"
    assert ex.stats.cache_hits == ex.stats.jit_calls


def test_fused_stage_zero_hot_loop_syncs(harness, monkeypatch):
    """SyncGuard-verified: zero host syncs between input deposit and output
    take.  The one data-dependent scalar (the overflow check) is pulled
    outside the hot region, once per task."""
    dist, _ = harness
    monkeypatch.setenv("TRINO_TPU_FUSED_STAGE", "auto")
    dist.execute(AGG_SQL)  # warm-up: compiles may sync
    before = SG.snapshot()
    with SG.forbidden():
        dist.execute(AGG_SQL)
    assert dist._fused_edges
    assert SG.take_delta(before).hot_loop_syncs == 0


def test_disabled_mode_restores_collective_path(harness, monkeypatch):
    """TRINO_TPU_FUSED_STAGE=0 runs today's behavior exactly: the collective
    exchange takes the REPARTITION edge back."""
    dist, oracle = harness
    monkeypatch.setenv("TRINO_TPU_FUSED_STAGE", "0")
    result = dist.execute(AGG_SQL)
    assert not dist._fused_edges
    assert dist._collective_edges, "legacy collective edge must come back"
    assert_same_rows(result.rows(), oracle.query(AGG_SQL))


def test_overflow_falls_back_to_legacy_path(harness, monkeypatch):
    """More distinct groups than TRINO_TPU_FUSED_CAP: the overflow scalar
    trips at finish and the runner re-runs the subplan on the legacy path
    (which has no group cap) — correct results, fallback counted."""
    dist, oracle = harness
    monkeypatch.setenv("TRINO_TPU_FUSED_STAGE", "auto")
    monkeypatch.setenv("TRINO_TPU_FUSED_CAP", "8")
    sql = ("select l_suppkey, count(*), sum(l_quantity) from lineitem "
           "group by l_suppkey")
    before = dist.fused_fallbacks
    result = dist.execute(sql)
    assert dist.fused_fallbacks == before + 1
    assert_same_rows(result.rows(), oracle.query(sql))
