"""Query flight recorder (telemetry/profiler.py): ring mechanics, context
attribution, the SyncGuard zero-hot-sync invariant at the default level,
full-mode device-time attribution, and the merged coordinator+worker
Chrome trace_event export — in-process (fused-region events included) and
across real worker processes via ``GET /v1/query/{id}/profile``."""

import json
import os
import threading
import urllib.error
import urllib.request

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.exec import syncguard as SG
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.runner import Session, StandaloneQueryRunner
from trino_tpu.telemetry import profiler

AGG_SQL = """
select l_returnflag, l_linestatus, sum(l_quantity), count(*)
from lineitem group by l_returnflag, l_linestatus
"""


@pytest.fixture(autouse=True)
def _fresh_profiler(monkeypatch):
    # profiler tests assert on execution timelines of repeated statements —
    # a served cached result would produce an empty timeline
    monkeypatch.setenv("TRINO_TPU_RESULT_CACHE", "0")
    prev = profiler.set_level(1)
    profiler.reset_for_test()
    yield
    profiler.set_level(prev)
    profiler.reset_for_test()


# ---------------------------------------------------------------- ring units


def test_ring_wraps_at_capacity_and_counts_overwrites():
    r = profiler._Ring(4)
    for i in range(7):
        r.push((float(i), 0.0, "operator", f"op{i}", "q", "", None))
    assert len(r.buf) == 4
    assert r.overwrites == 3
    kept = sorted(ev[0] for ev in r.buf)
    assert kept == [3.0, 4.0, 5.0, 6.0]  # oldest overwritten first


def test_context_stamping_and_restore():
    prev = profiler.set_context("q_ctx", "t_0")
    t0 = profiler.now()
    profiler.event(profiler.OPERATOR, "ScanOperator", t0)
    evs = profiler.collect("q_ctx")
    assert len(evs) == 1 and evs[0]["task"] == "t_0"
    profiler.set_context(*prev)
    profiler.event(profiler.OPERATOR, "after-restore", profiler.now())
    assert len(profiler.collect("q_ctx")) == 1  # restored context ≠ q_ctx


def test_group_threads_inherit_context():
    profiler.set_context("q_inherit", "t_9")
    ctx = profiler.capture_context()

    def work():
        profiler.apply_context(ctx)
        profiler.event(profiler.OPERATOR, "worker-thread-op", profiler.now())

    th = threading.Thread(target=work)
    th.start()
    th.join()
    evs = profiler.collect("q_inherit")
    assert [e["name"] for e in evs] == ["worker-thread-op"]
    assert evs[0]["task"] == "t_9"
    profiler.set_context("", "")


def test_disabled_level_records_nothing():
    profiler.set_level(0)
    profiler.set_context("q_off", "")
    profiler.event(profiler.OPERATOR, "invisible", profiler.now())
    profiler.instant(profiler.SPECULATION, "invisible-too")
    profiler.set_level(1)
    assert profiler.collect("q_off") == []


def test_take_task_events_bounds_and_keeps_tail():
    profiler.set_context("q_tail", "t_0")
    for i in range(50):
        profiler.event(profiler.OPERATOR, f"op{i}", float(i), float(i))
    evs = profiler.take_task_events("q_tail", "t_0", limit=10)
    assert len(evs) == 10
    assert evs[-1]["name"] == "op49"  # newest kept: failures live at the end
    profiler.set_context("", "")


def test_profile_store_is_bounded():
    for i in range(profiler._MAX_PROFILES + 10):
        profiler.add_remote_events(
            f"q_{i}", [{"ts": 0.0, "dur": 0.0, "kind": "operator",
                        "name": "x", "task": "", "pid": 1, "tid": 1,
                        "thread": "t"}])
    with profiler._PROFILES_LOCK:
        assert len(profiler._PROFILES) == profiler._MAX_PROFILES
        assert "q_0" not in profiler._PROFILES  # oldest evicted


# -------------------------------------------------------- chrome trace shape


def _validate_chrome_trace(trace):
    """The subset of the trace_event spec Perfetto/chrome://tracing needs."""
    assert set(trace) >= {"traceEvents", "displayTimeUnit"}
    json.dumps(trace)  # must serialize
    for ev in trace["traceEvents"]:
        assert ev["ph"] in ("X", "M")
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        if ev["ph"] == "X":
            assert ev["ts"] >= 0 and ev["dur"] >= 0  # µs, normalized
            assert ev["name"] and ev["cat"]
        else:
            assert ev["name"] in ("process_name", "thread_name")
    # every X event's process got an M process_name record
    named = {e["pid"] for e in trace["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    used = {e["pid"] for e in trace["traceEvents"] if e["ph"] == "X"}
    assert used <= named


def test_chrome_trace_unit_roundtrip():
    profiler.set_context("q_trace", "t_1")
    t0 = profiler.now()
    profiler.event(profiler.OPERATOR, "ScanOperator", t0 - 0.01, t0,
                   rows=128)
    profiler.harvest("q_trace")
    profiler.set_context("", "")
    trace = profiler.chrome_trace("q_trace")
    _validate_chrome_trace(trace)
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert xs[0]["args"]["rows"] == 128 and xs[0]["args"]["task"] == "t_1"
    assert trace["otherData"]["query_id"] == "q_trace"
    assert profiler.chrome_trace("q_unknown") is None


# ------------------------------------------- engine integration (in-process)


@pytest.fixture(scope="module")
def dist():
    catalog = default_catalog(scale_factor=0.01)
    return DistributedQueryRunner(catalog, worker_count=2,
                                  session=Session(node_count=2))


def test_default_profiling_keeps_hot_regions_sync_free(dist):
    """THE overhead guard: with the flight recorder at its default level,
    a fused-stage query still runs with zero blocking syncs inside
    SyncGuard hot regions (recording is a clock read + a tuple store)."""
    assert profiler.enabled() and not profiler.is_full()
    dist.execute(AGG_SQL)  # warm-up: compiles may sync
    before = SG.snapshot()
    with SG.forbidden():
        dist.execute(AGG_SQL, query_id="q_sync_guard")
    assert SG.take_delta(before).hot_loop_syncs == 0
    assert profiler.chrome_trace("q_sync_guard") is not None


def test_fused_query_timeline_has_all_event_kinds(dist):
    """One in-process 2-worker TPC-H aggregation: operator, fused-region
    AND exchange-wait events land in one merged timeline."""
    dist.execute(AGG_SQL, query_id="q_fused_profile")
    assert dist._fused_edges, "expected the whole-stage compilation path"
    trace = dist.profile("q_fused_profile")
    _validate_chrome_trace(trace)
    cats = {e["cat"] for e in trace["traceEvents"] if e["ph"] == "X"}
    assert {"operator", "fused-region", "exchange-wait"} <= cats
    fused = [e["name"] for e in trace["traceEvents"]
             if e["ph"] == "X" and e["cat"] == "fused-region"]
    assert any(n.startswith("fused-accumulate") for n in fused)
    assert any(n.startswith("fused-merge") for n in fused)


def test_full_mode_syncs_are_attributed_not_hot(dist):
    """TRINO_TPU_PROFILE=full brackets operator output with
    block_until_ready: the syncs happen (tagged ``profiler.full``) but
    never inside a hot region — SyncGuard accounting stays honest."""
    profiler.set_level(2)
    before = SG.snapshot()
    dist.execute(AGG_SQL, query_id="q_full_mode")
    delta = SG.take_delta(before)
    profiler.set_level(1)
    assert delta.by_tag.get("profiler.full", 0) > 0
    assert delta.hot_loop_syncs == 0


def test_runner_profile_unknown_query_returns_none(dist):
    assert dist.profile("never-ran") is None


def test_standalone_runner_profile():
    r = StandaloneQueryRunner(default_catalog(scale_factor=0.01))
    r.execute("select count(*) from tpch.tiny.region",
              query_id="q_standalone")
    trace = r.profile("q_standalone")
    _validate_chrome_trace(trace)
    cats = {e["cat"] for e in trace["traceEvents"] if e["ph"] == "X"}
    assert "operator" in cats


# ---------------------------------- worker processes + coordinator endpoints


@pytest.fixture(scope="module")
def served_cluster():
    """2 real worker processes behind a coordinator HTTP server."""
    from trino_tpu.execution.remote import ProcessDistributedQueryRunner
    from trino_tpu.server.protocol import TrinoTpuServer

    runner = ProcessDistributedQueryRunner(
        {"factory": "trino_tpu.connectors.catalog:default_catalog",
         "kwargs": {"scale_factor": 0.01}},
        worker_count=2, session=Session(node_count=2),
        env_overrides={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1"})
    server = TrinoTpuServer(runner).start()
    host, port = server.address
    yield runner, f"http://{host}:{port}"
    server.stop()
    runner.close()


def _run_statement(base: str, sql: str) -> tuple[str, dict]:
    req = urllib.request.Request(f"{base}/v1/statement",
                                 data=sql.encode(), method="POST")
    with urllib.request.urlopen(req) as resp:
        payload = json.load(resp)
    qid = payload["id"]
    while payload.get("nextUri"):
        with urllib.request.urlopen(base + payload["nextUri"]) as resp:
            payload = json.load(resp)
    return qid, payload


def test_profile_endpoint_merges_worker_timelines(served_cluster):
    """The acceptance path: a 2-worker TPC-H query's profile over HTTP is
    valid Chrome trace JSON with events from the coordinator AND both
    worker pids in one timeline."""
    runner, base = served_cluster
    qid, payload = _run_statement(
        base, "select l_returnflag, count(*) from lineitem "
              "group by l_returnflag order by l_returnflag")
    assert payload["stats"]["state"] == "FINISHED"
    with urllib.request.urlopen(f"{base}/v1/query/{qid}/profile") as resp:
        trace = json.load(resp)
    _validate_chrome_trace(trace)
    cats = {e["cat"] for e in trace["traceEvents"] if e["ph"] == "X"}
    assert {"operator", "exchange-wait"} <= cats
    procs = trace["otherData"]["processes"]
    workers = [p for p in procs.values() if p.startswith("worker:")]
    assert len(workers) == 2, f"expected both worker pids, got {procs}"
    assert "coordinator" in procs.values()
    assert os.getpid() in {e["pid"] for e in trace["traceEvents"]}


def test_profile_endpoint_unknown_query_404(served_cluster):
    _, base = served_cluster
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(f"{base}/v1/query/never-ran/profile")
    assert ei.value.code == 404


def test_cluster_scope_metrics_fold_workers(served_cluster):
    """/v1/metrics?scope=cluster folds both workers' registries into the
    coordinator's: worker-side counters (tasks created) appear summed, and
    merged distributions stay one histogram series."""
    runner, base = served_cluster
    _run_statement(base, "select count(*) from region")
    with urllib.request.urlopen(f"{base}/v1/metrics?scope=cluster") as resp:
        assert resp.headers["Content-Type"].startswith("text/plain")
        cluster = resp.read().decode()
    with urllib.request.urlopen(f"{base}/v1/metrics") as resp:
        local = resp.read().decode()

    def val(text, name):
        for line in text.splitlines():
            if line.startswith(name + " "):
                return float(line.split()[1])
        return 0.0

    # tasks ran in worker processes: invisible to the coordinator-local
    # registry (which may carry counts from in-process runners in this
    # test process), folded in by scope=cluster
    assert val(cluster, "trino_tasks_created_total") >= \
        val(local, "trino_tasks_created_total") + 2
    # merged histogram: one bucket series, cumulative, with +Inf
    buckets = [l for l in cluster.splitlines()
               if l.startswith("trino_task_wall_seconds_bucket")]
    assert buckets and '+Inf' in buckets[-1]
    assert val(cluster, "trino_task_wall_seconds_count") >= 2
