"""Multi-tenant serving plane: weighted-fair resource groups, cluster
memory manager + OOM killer, memory-aware admission
(execution/resource_manager.py, spi/session.py)."""

import json
import threading
import time

import pytest

from trino_tpu.execution.control import DispatchManager
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.execution.resource_manager import (
    ClusterMemoryManager,
    ResourceGroup,
    build_group_tree,
    estimate_peak_memory,
    find_group,
)
from trino_tpu.runner import Session
from trino_tpu.spi.errors import (
    CLUSTER_OUT_OF_MEMORY,
    EXCEEDED_GLOBAL_MEMORY_LIMIT,
    QUERY_QUEUE_FULL,
    QUERY_QUEUED_TIMEOUT,
    TrinoError,
    classify,
)
from trino_tpu.spi.session import GroupSelector


@pytest.fixture(autouse=True)
def _no_result_cache(monkeypatch):
    # this file measures admission/memory/kill machinery on repeated
    # statements (e.g. a 2000-iteration OOM pressure loop) — a served
    # cached result registers no memory handle and would starve the killer
    monkeypatch.setenv("TRINO_TPU_RESULT_CACHE", "0")


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# ----------------------------------------------------------- config parsing


def test_build_group_tree_from_json():
    spec = json.dumps({
        "root": {
            "name": "global", "hard_concurrency_limit": 10,
            "scheduling_policy": "weighted_fair",
            "subgroups": [
                {"name": "etl", "weight": 3, "max_queued": 7,
                 "soft_memory_limit_bytes": 1 << 30},
                {"name": "adhoc", "weight": 1,
                 "soft_concurrency_limit": 2},
            ],
        },
        "selectors": [
            {"user": "etl_.*", "group": "etl"},
            {"source": "dashboard", "group": "adhoc"},
            {"group": ""},
        ],
    })
    root, selector = build_group_tree(spec)
    assert root.hard_concurrency_limit == 10
    assert root.scheduling_policy == "weighted_fair"
    etl = root.children["etl"]
    assert (etl.name, etl.weight, etl.max_queued) == ("global.etl", 3, 7)
    assert etl.soft_memory_limit_bytes == 1 << 30
    assert root.children["adhoc"].soft_concurrency_limit == 2

    class S:
        user = "etl_nightly"
        source = ""
    assert selector("select 1", S()) == "etl"
    S.user, S.source = "alice", "dashboard"
    assert selector("select 1", S()) == "adhoc"
    S.source = "cli"
    assert selector("select 1", S()) == ""  # catch-all -> root


def test_selector_sql_regex_and_missing_group_rejected():
    sel = GroupSelector.from_spec(
        [{"sql": r"(?i)insert\s", "group": "writes"}, {"group": "other"}])

    class S:
        pass
    assert sel.select("INSERT into t values (1)", S()) == "writes"
    assert sel.select("select 1", S()) == "other"
    with pytest.raises(ValueError):
        GroupSelector.from_spec([{"user": "x"}])


def test_find_group_dotted_path():
    root = ResourceGroup("global")
    sub = root.subgroup("etl").subgroup("nightly")
    assert find_group(root, "global.etl.nightly") is sub
    assert find_group(root, "") is None
    assert find_group(root, "nope") is None


# ------------------------------------------------- scheduling policies


def _churn(group, counts, key, stop, work_s=0.002):
    while not stop.is_set():
        try:
            group.acquire(timeout=5.0)
        except TrinoError:
            continue
        try:
            time.sleep(work_s)
            counts[key] += 1
        finally:
            group.release()


def test_weighted_fair_converges_to_share_without_starvation():
    """Under saturation a 3:1 weighted pair completes work 3:1 (+-25%)
    and the light tenant is never starved."""
    root = ResourceGroup("global", hard_concurrency_limit=4,
                         scheduling_policy="weighted_fair")
    heavy = root.subgroup("heavy", weight=3)
    light = root.subgroup("light", weight=1)
    counts = {"heavy": 0, "light": 0}
    stop = threading.Event()
    threads = [threading.Thread(target=_churn,
                                args=(g, counts, k, stop), daemon=True)
               for g, k in ((heavy, "heavy"), (light, "light"))
               for _ in range(5)]
    for t in threads:
        t.start()
    time.sleep(2.0)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert counts["light"] > 0, "light tenant starved"
    ratio = counts["heavy"] / counts["light"]
    assert 3.0 * 0.75 <= ratio <= 3.0 * 1.25, (counts, ratio)


def test_fair_policy_is_fifo():
    """The pre-existing contract: under the default fair policy queued
    queries admit in global arrival order."""
    g = ResourceGroup("global", hard_concurrency_limit=1)
    g.acquire()
    order = []

    def waiter(i):
        g.acquire(timeout=10)
        order.append(i)
        g.release()

    threads = []
    for i in range(3):
        t = threading.Thread(target=waiter, args=(i,), daemon=True)
        t.start()
        threads.append(t)
        time.sleep(0.05)  # deterministic arrival order
    g.release()
    for t in threads:
        t.join(timeout=10)
    assert order == [0, 1, 2]


def test_query_priority_policy_admits_highest_first():
    g = ResourceGroup("global", hard_concurrency_limit=1,
                      scheduling_policy="query_priority")
    g.acquire()
    order = []

    def waiter(i, prio):
        g.acquire(timeout=10, priority=prio)
        order.append(i)

    threads = []
    for i, prio in enumerate([1, 5, 3]):
        t = threading.Thread(target=waiter, args=(i, prio), daemon=True)
        t.start()
        threads.append(t)
        time.sleep(0.05)
    # release one slot at a time; each wakes exactly one waiter
    for _ in range(3):
        g.release()
        time.sleep(0.1)
    for t in threads:
        t.join(timeout=10)
    assert order == [1, 2, 0]  # prio 5, then 3, then 1


def test_cpu_quota_blocks_and_regenerates():
    clock = FakeClock()
    g = ResourceGroup("global", hard_concurrency_limit=4,
                      hard_cpu_limit_s=1.0,
                      cpu_quota_generation_s_per_s=0.5, clock=clock)
    g.acquire()
    g.release(cpu_s=2.0)  # blow the quota
    with pytest.raises(TrinoError) as ei:
        g.acquire(timeout=0.05)
    assert ei.value.code is QUERY_QUEUED_TIMEOUT
    clock.t += 4.0  # regenerates 2.0s of quota -> usage back to 0
    g.refresh()
    g.acquire(timeout=1.0)  # admitted again
    g.release()


def test_soft_cpu_limit_scales_concurrency():
    clock = FakeClock()
    g = ResourceGroup("global", hard_concurrency_limit=4,
                      soft_cpu_limit_s=1.0, hard_cpu_limit_s=3.0,
                      clock=clock)
    g.acquire()
    g.release(cpu_s=2.0)  # halfway between soft and hard -> limit 2
    g.acquire()
    g.acquire()
    with pytest.raises(TrinoError):
        g.acquire(timeout=0.05)


# ------------------------------------------- admission rejection errors


def test_queue_full_is_user_error_and_runtimeerror():
    g = ResourceGroup("global", hard_concurrency_limit=1, max_queued=0)
    g.acquire()
    with pytest.raises(RuntimeError):  # historical contract
        g.acquire(timeout=0.05)
    g2 = ResourceGroup("g2", hard_concurrency_limit=1, max_queued=0)
    g2.acquire()
    with pytest.raises(TrinoError) as ei:
        g2.acquire(timeout=0.05)
    err = ei.value
    assert err.code is QUERY_QUEUE_FULL
    assert err.error_type == "USER"
    assert classify(err) is err


def test_queued_timeout_is_user_error():
    g = ResourceGroup("global", hard_concurrency_limit=1, max_queued=10)
    g.acquire()
    t0 = time.monotonic()
    with pytest.raises(TrinoError) as ei:
        g.acquire(timeout=0.1)
    assert time.monotonic() - t0 < 5.0
    assert ei.value.code is QUERY_QUEUED_TIMEOUT
    assert ei.value.error_type == "USER"
    assert g.queued == 0  # timed-out ticket left the queue


def test_queue_full_not_retried_under_query_retry_policy():
    """A USER admission rejection must surface immediately — the query
    retry loop re-running it would just re-fail (and double-bill)."""
    runner = DistributedQueryRunner(
        worker_count=2,
        session=Session(retry_policy="QUERY", query_concurrency=1,
                        query_max_queued=0, node_count=2))
    runner.dispatcher.root.acquire()  # occupy the only slot
    try:
        with pytest.raises(TrinoError) as ei:
            runner.execute("select count(*) from nation")
        assert ei.value.code is QUERY_QUEUE_FULL
        assert runner.resilience.query_retries == 0
    finally:
        runner.dispatcher.root.release()


# --------------------------------------------------- cluster memory manager


def _mk_handles(mm, specs):
    """specs: [(qid, priority, usage_bytes)] -> handles + synthetic usage."""
    handles = {}
    tasks = {}
    for i, (qid, prio, usage) in enumerate(specs):
        handles[qid] = mm.register_query(qid, priority=prio)
        tasks[f"t{i}"] = {"query_id": qid, "memory_reserved_bytes": usage}
    mm.update_worker("w0", {"tasks": tasks})
    return handles


@pytest.mark.parametrize("policy,victim", [
    ("largest_query", "big"),
    ("lowest_priority", "low"),
    ("youngest", "young"),
])
def test_oom_victim_policy_selection(policy, victim):
    mm = ClusterMemoryManager(capacity_bytes=100, oom_policy=policy,
                              enforce_interval_s=0.0)
    handles = _mk_handles(mm, [
        ("big", 5, 80),     # largest reservation
        ("low", 1, 50),     # lowest priority
        ("young", 9, 40),   # registered last
    ])
    killed = mm.enforce()
    assert killed[0] == victim
    err = handles[victim].killed_error()
    assert err is not None and err.code is CLUSTER_OUT_OF_MEMORY
    assert err.error_type == "INSUFFICIENT_RESOURCES"


def test_oom_killer_skips_zero_usage_and_stops_when_fitting():
    mm = ClusterMemoryManager(capacity_bytes=100,
                              oom_policy="lowest_priority",
                              enforce_interval_s=0.0)
    handles = _mk_handles(mm, [
        ("idle", 0, 0),    # lowest priority but reserves nothing
        ("mid", 5, 90),
        ("top", 9, 60),
    ])
    killed = mm.enforce()
    # killing idle frees nothing -> skipped; killing mid (90) fits 60<=100
    assert killed == ["mid"]
    assert not handles["idle"].killed and not handles["top"].killed
    assert mm.oom_kills == 1


def test_per_query_max_memory_kill():
    mm = ClusterMemoryManager(capacity_bytes=None, enforce_interval_s=0.0)
    h = mm.register_query("q1", max_memory=10)
    mm.update_worker("w0", {"tasks": {
        "t0": {"query_id": "q1", "memory_reserved_bytes": 50}}})
    mm.enforce()
    err = h.killed_error()
    assert err is not None and err.code is EXCEEDED_GLOBAL_MEMORY_LIMIT


def test_worker_snapshot_replacement_and_pool_weakref():
    from trino_tpu.spi.memory import MemoryPool

    mm = ClusterMemoryManager(capacity_bytes=None)
    mm.register_query("q1")
    mm.update_worker("w0", {"tasks": {
        "t0": {"query_id": "q1", "memory_reserved_bytes": 70}}})
    pool = MemoryPool("hbm", 1 << 30)
    pool.reserve(30)
    mm.register_pool("q1", pool)
    assert mm.reserved_by_query() == {"q1": 100}
    # a fresh snapshot replaces the node's view wholesale
    mm.update_worker("w0", {"tasks": {}})
    assert mm.reserved_by_query() == {"q1": 30}
    del pool  # pool dies with its task -> accounting follows
    assert mm.reserved_by_query() == {}


def test_group_memory_rollup_blocks_admission():
    root = ResourceGroup("global", hard_concurrency_limit=8)
    etl = root.subgroup("etl", soft_memory_limit_bytes=100)
    mm = ClusterMemoryManager(capacity_bytes=None, enforce_interval_s=0.0)
    mm.register_query("q1", group=etl)
    mm.update_worker("w0", {"tasks": {
        "t0": {"query_id": "q1", "memory_reserved_bytes": 150}}})
    mm.enforce()
    assert etl.memory_usage_bytes == 150
    assert root.memory_usage_bytes == 150  # rolls up to ancestors
    with pytest.raises(TrinoError):  # over the soft limit -> hold new work
        etl.acquire(timeout=0.05)
    mm.update_worker("w0", {"tasks": {}})
    mm.enforce()
    etl.acquire(timeout=1.0)  # headroom returned -> admitted
    etl.release()


# ------------------------------------------------ killed queries end to end


def _pressure_once(mm, pressure_bytes, done):
    """Kill exactly one registered query via a synthetic worker snapshot,
    then clear the pressure (bench.py's drill pattern)."""
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        with mm._lock:
            live = list(mm._handles.values())
        if live:
            h = live[0]
            mm.update_worker("pressure", {"tasks": {
                "t0": {"query_id": h.query_id,
                       "memory_reserved_bytes": pressure_bytes}}})
            mm.enforce()
            if h.killed:
                break
        time.sleep(0.002)
    mm.forget_worker("pressure")
    done.set()


def test_oom_kill_surfaces_cluster_out_of_memory():
    """The killer fires mid-query, the victim fails with
    CLUSTER_OUT_OF_MEMORY (no hang), and the next query completes."""
    runner = DistributedQueryRunner(worker_count=2,
                                    session=Session(node_count=2))
    runner.memory_manager = ClusterMemoryManager(capacity_bytes=64 << 20,
                                                 enforce_interval_s=0.0)
    done = threading.Event()
    th = threading.Thread(target=_pressure_once,
                          args=(runner.memory_manager, 256 << 20, done),
                          daemon=True)
    th.start()
    with pytest.raises(TrinoError) as ei:
        for _ in range(2000):
            runner.execute("select count(*) from lineitem")
    assert ei.value.code is CLUSTER_OUT_OF_MEMORY
    assert done.wait(30)
    # steady state returns: the cluster runs queries again
    r = runner.execute("select count(*) from nation")
    assert r.rows()[0][0] == 25


def test_oom_killed_query_reruns_under_query_retry():
    """CLUSTER_OUT_OF_MEMORY is INSUFFICIENT_RESOURCES -> retryable: with
    retry_policy=QUERY the killed attempt re-runs and succeeds once the
    memory pressure clears."""
    runner = DistributedQueryRunner(
        worker_count=2,
        session=Session(retry_policy="QUERY", query_retry_attempts=3,
                        retry_initial_delay_s=0.01, node_count=2))
    runner.memory_manager = ClusterMemoryManager(capacity_bytes=64 << 20,
                                                 enforce_interval_s=0.0)
    done = threading.Event()
    th = threading.Thread(target=_pressure_once,
                          args=(runner.memory_manager, 256 << 20, done),
                          daemon=True)
    th.start()
    r = runner.execute("select count(*) from nation")
    assert r.rows()[0][0] == 25
    assert done.wait(30)
    assert runner.resilience.query_retries >= 1
    th.join(timeout=10)


# ------------------------------------------------ memory-aware admission


def test_estimate_peak_memory_from_history():
    from trino_tpu.telemetry import runtime as rt

    sql = "select 'estimate-probe-xyz' as c"
    fp = rt.fingerprint(sql)
    for peak in (100, 500, 300):
        rec = rt.query_started("qh", sql, "u")
        rt.query_finished(rec, "FINISHED", 1.0, 1.0, 1,
                          peak_memory_bytes=peak)
    assert estimate_peak_memory(fp, 42) == 500  # max of recent, not mean
    assert estimate_peak_memory(rt.fingerprint("select 2, 3"), 42) == 42
    # fingerprint normalizes whitespace/case
    assert rt.fingerprint("SELECT   'estimate-probe-xyz' AS c  ") == fp


def test_dispatcher_memory_aware_admission_times_out():
    from trino_tpu.server.protocol import QueryDispatcher, _Query

    class StubRunner:
        memory_manager = ClusterMemoryManager(capacity_bytes=100,
                                              enforce_interval_s=1e9)
        session = Session(query_queued_timeout_s=0.2)
    StubRunner.memory_manager.update_worker("w0", {"tasks": {
        "t0": {"query_id": "hog", "memory_reserved_bytes": 100}}})
    d = QueryDispatcher.__new__(QueryDispatcher)
    d.runner = StubRunner()
    q = _Query("qid1", "select 1")
    with pytest.raises(TrinoError) as ei:
        d._await_memory(q)
    assert ei.value.code is QUERY_QUEUED_TIMEOUT
    # cancellation exits the wait without error
    q.cancelled = True
    d._await_memory(q)


# ------------------------------------------------- system tables + metrics


def test_system_resource_groups_and_queued_time():
    spec = json.dumps({
        "root": {"name": "global", "hard_concurrency_limit": 8,
                 "scheduling_policy": "weighted_fair",
                 "subgroups": [{"name": "etl", "weight": 3}]},
        "selectors": [{"group": "etl"}],
    })
    root, selector = build_group_tree(spec)
    runner = DistributedQueryRunner(worker_count=2,
                                    session=Session(node_count=2))
    runner.dispatcher = DispatchManager(root, selector)
    runner.execute("select count(*) from nation")
    rows = runner.execute(
        "select path, policy, weight, running, queued "
        "from system.runtime.resource_groups").rows()
    by_path = {r[0]: r for r in rows}
    assert by_path["global"][1] == "weighted_fair"
    assert by_path["global.etl"][2] == 3
    assert by_path["global"][3] >= 1  # the introspection query itself
    qrows = runner.execute(
        "select state, queued_time_ms, resource_group "
        "from system.runtime.queries").rows()
    fin = [r for r in qrows if r[0] == "FINISHED" and r[2] == "global.etl"]
    assert fin and all(r[1] >= 0.0 for r in fin)


def test_serving_metrics_registered():
    from trino_tpu.telemetry.metrics import REGISTRY

    g = ResourceGroup("mtest")
    g.acquire()
    g.release()
    mm = ClusterMemoryManager(capacity_bytes=100, enforce_interval_s=0.0)
    mm.enforce()
    snap = REGISTRY.snapshot()
    assert snap["trino_admission_queued_seconds"]["kind"] == "distribution"
    assert snap["trino_oom_kills_total"]["kind"] == "counter"
    assert "trino_cluster_memory_reserved_bytes" in snap
    assert "trino_cluster_memory_free_bytes" in snap
    assert snap["trino_resource_group_running_mtest"]["value"] == 0
    assert "trino_resource_group_queued_mtest" in snap


def test_build_dispatch_manager_env_config(monkeypatch):
    from trino_tpu.execution.resource_manager import build_dispatch_manager

    spec = json.dumps({
        "root": {"name": "global", "hard_concurrency_limit": 3},
        "selectors": [{"source": "etl", "group": "batch"}],
    })
    monkeypatch.setenv("TRINO_TPU_RESOURCE_GROUPS", spec)
    dm = build_dispatch_manager(Session())
    assert dm.root.hard_concurrency_limit == 3
    assert dm._group_for("select 1", Session(source="etl")).name \
        == "global.batch"
    monkeypatch.delenv("TRINO_TPU_RESOURCE_GROUPS")
    dm = build_dispatch_manager(Session(query_concurrency=7))
    assert dm.root.hard_concurrency_limit == 7
