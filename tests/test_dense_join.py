"""Direct-address (dense) join tables: a unique single-int-key build whose
key range is dense gets a dense[key - lo] lookup table — probes are ONE
gather with no hashing, no binary search, no verify.  Every TPC-H PK/FK
edge qualifies; sparse or duplicate keys must fall back to the sorted-hash
paths with identical results."""

import numpy as np

from trino_tpu.exec import join_exec as JX


def _keys(arr, valid=None):
    return [(np.asarray(arr), None if valid is None else np.asarray(valid))]


def test_dense_table_built_for_dense_unique_keys():
    t = JX.build_table(_keys(np.arange(1, 20001, dtype=np.int64)))
    assert t.dense is not None
    assert t.dense_lo == 1
    assert t.unique


def test_dense_rejected_for_sparse_range():
    k = np.arange(0, 20000, dtype=np.int64) * 1000  # range >> 4x rows
    t = JX.build_table(_keys(k))
    assert t.dense is None
    assert t.unique  # still unique: hash path serves it


def test_dense_rejected_for_duplicate_keys():
    k = np.concatenate([np.arange(40000), np.arange(40000)]).astype(np.int64)
    t = JX.build_table(_keys(k))
    assert t.dense is None
    assert not t.unique


def test_dense_probe_matches_hash_probe():
    rng = np.random.default_rng(7)
    build = np.arange(100, 66000, dtype=np.int64)
    probe = rng.integers(0, 70000, size=1 << 15).astype(np.int64)
    dense_t = JX.build_table(_keys(build))
    assert dense_t.dense is not None
    ok, bid, cnt, mr = JX.run_unique_ranges(dense_t, _keys(probe), [None])
    assert mr == 1
    ok = np.asarray(ok)
    bid = np.asarray(bid)
    expected = (probe >= 100) & (probe < 66000)
    np.testing.assert_array_equal(ok, expected)
    np.testing.assert_array_equal(bid[ok], probe[expected] - 100)
    assert cnt == int(expected.sum())


def test_dense_probe_respects_live_and_valid():
    build = np.arange(0, 70000, dtype=np.int64)
    t = JX.build_table(_keys(build))
    assert t.dense is not None
    probe = np.array([0, 1, 2, 3], dtype=np.int64)
    valid = np.array([True, False, True, True])
    live = np.array([True, True, False, True])
    ok, bid, cnt, mr = JX.run_unique_ranges(
        t, _keys(probe, valid), [None], live=live)
    np.testing.assert_array_equal(np.asarray(ok),
                                  [True, False, False, True])
    assert cnt == 2
