"""Distributed execution: all TPC-H queries on a 3-worker in-process
cluster must match the (oracle-validated) standalone runner.

Mirrors the reference's multi-node e2e suites (TestJoinQueries,
TestRepartitionQueries over DistributedQueryRunner.setWorkerCount — SURVEY
§4): real fragment boundaries, partial/final aggregation, broadcast +
repartition exchanges, pull-token buffers, concurrent task threads.
"""

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.connectors.tpch_queries import QUERIES
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.runner import StandaloneQueryRunner
from trino_tpu.testing.oracle import assert_same_rows

_ORDERED = {1, 2, 3, 5, 7, 8, 9, 10, 11, 12, 13, 14, 16, 18, 21, 22}


@pytest.fixture(scope="module")
def runners():
    catalog = default_catalog(scale_factor=0.01)
    return (DistributedQueryRunner(catalog, worker_count=3),
            StandaloneQueryRunner(catalog))


@pytest.mark.parametrize("q", sorted(QUERIES))
def test_tpch_distributed(runners, q):
    dist, standalone = runners
    actual = dist.execute(QUERIES[q]).rows()
    expected = standalone.execute(QUERIES[q]).rows()
    assert_same_rows(actual, expected, ordered=q in _ORDERED)


def test_fragment_shapes(runners):
    dist, _ = runners
    text = dist.explain(QUERIES[3])
    assert "PARTIAL" in text and "FINAL" in text
    assert "BROADCAST" in text and "REPARTITION" in text
    assert text.count("Fragment") >= 4


def test_partial_final_global_agg(runners):
    dist, _ = runners
    # empty input: every worker emits a default partial row; FINAL must
    # still produce count 0 / sum NULL
    rows = dist.execute(
        "select count(*), sum(o_totalprice) from orders where o_orderkey < 0"
    ).rows()
    assert rows == [(0, None)]


def test_distributed_limit_early_close(runners):
    dist, _ = runners
    rows = dist.execute("select o_orderkey from orders limit 5").rows()
    assert len(rows) == 5
