"""Durable query journal (telemetry/journal.py): rotation bounds, torn-line
tolerance, the enriched QueryCompletedEvent round-trip, the
``system.runtime.query_history`` table, journal-seeded admission estimates
across a (subprocess-simulated) coordinator restart, and the
tools/lint_journal_schema.py contract."""

import json
import os
import subprocess
import sys

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.execution.resource_manager import estimate_peak_memory
from trino_tpu.runner import StandaloneQueryRunner
from trino_tpu.spi.eventlistener import QueryCompletedEvent
from trino_tpu.telemetry import journal
from trino_tpu.telemetry import runtime as rt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_journal(tmp_path, monkeypatch):
    monkeypatch.setenv("TRINO_TPU_JOURNAL_DIR", str(tmp_path / "journal"))
    monkeypatch.delenv("TRINO_TPU_JOURNAL", raising=False)
    journal.reset_for_test()
    yield
    journal.reset_for_test()


def _completed(qid: str, sql: str = "SELECT 1", peak: int = 0,
               state: str = "FINISHED", **kw) -> QueryCompletedEvent:
    return QueryCompletedEvent(qid, sql, state=state, user="test",
                               peak_memory_bytes=peak, **kw)


# ----------------------------------------------------------- rotation bounds


def test_rotation_keeps_size_and_file_count_bounded(tmp_path):
    j = journal.QueryJournal(directory=str(tmp_path / "j"),
                            max_bytes=2048, max_files=2)
    for i in range(200):
        j.query_completed(_completed(f"q_{i}"))
    files = j.files()
    assert len(files) <= 3  # current + 2 rotated generations
    for f in files:
        # one record of slack: rotation triggers when a write would overflow
        assert os.path.getsize(f) <= 2048 + 600
    records = j.read()
    ids = [r["query_id"] for r in records]
    assert "q_199" in ids, "newest record must survive"
    assert "q_0" not in ids, "oldest generation must have been dropped"
    assert ids == sorted(ids, key=lambda s: int(s.split("_")[1])), \
        "read() must return records oldest-first"


def test_torn_tail_and_garbage_lines_are_skipped(tmp_path):
    j = journal.QueryJournal(directory=str(tmp_path / "j"))
    j.query_completed(_completed("q_good"))
    with open(j.path, "a", encoding="utf-8") as f:
        f.write("not json at all\n")
        f.write('{"schema": 1, "event": "query_completed", "query_id":')
    # the process crashed mid-write; the restarted journal must detect the
    # torn tail and not corrupt its first record by appending onto it
    j2 = journal.QueryJournal(directory=str(tmp_path / "j"))
    j2.query_completed(_completed("q_after"))
    ids = [r["query_id"] for r in j2.read()]
    assert ids == ["q_good", "q_after"]


def test_disabled_journal_returns_none(monkeypatch):
    monkeypatch.setenv("TRINO_TPU_JOURNAL", "0")
    journal.reset_for_test()
    assert journal.get_journal() is None
    assert journal.history() == []


# ------------------------------------------------- event listener round-trip


def test_completed_event_enrichment_round_trips(tmp_path):
    """The PR's QueryCompletedEvent additions — queued_time_ms,
    resource_group, speculative_wins, error_code — must survive the
    write/read cycle byte-for-byte."""
    j = journal.QueryJournal(directory=str(tmp_path / "j"))
    j.query_completed(_completed(
        "q_rt", sql="SELECT 2", peak=1 << 20, queued_time_ms=12.5,
        resource_group="global.etl", speculative_wins=3,
        wall_ms=99.0, cpu_ms=42.0, output_rows=7, input_rows=100,
        input_bytes=4096, retry_count=1))
    j.query_completed(_completed(
        "q_err", sql="SELECT 1/0", state="FAILED",
        error="division by zero", error_code="DIVISION_BY_ZERO"))
    ok, err = j.read(events=("query_completed",))
    assert ok["queued_time_ms"] == 12.5
    assert ok["resource_group"] == "global.etl"
    assert ok["speculative_wins"] == 3
    assert ok["retry_count"] == 1
    assert ok["fingerprint"] == rt.fingerprint("SELECT 2")
    assert ok["schema"] == journal.SCHEMA_VERSION
    assert err["state"] == "FAILED"
    assert err["error_code"] == "DIVISION_BY_ZERO"


def test_runner_writes_journal_and_classifies_failures():
    """End to end through the engine: FINISHED and FAILED queries both land
    in the journal, the failure with its spi/errors.py error code."""
    r = StandaloneQueryRunner(default_catalog(scale_factor=0.01))
    r.execute("select count(*) from tpch.tiny.region")
    with pytest.raises(Exception):
        r.execute("select 1 / 0")
    recs = journal.history()
    by_state = {rec["state"]: rec for rec in recs}
    assert "FINISHED" in by_state and "FAILED" in by_state
    assert by_state["FINISHED"]["output_rows"] == 1
    assert by_state["FAILED"]["error_code"] == "DIVISION_BY_ZERO"
    created = journal.get_journal().read(events=("query_created",))
    assert len(created) == 2


# ------------------------------------- restart durability + admission seeding


_CHILD = r"""
import os
from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.runner import StandaloneQueryRunner
from trino_tpu.spi.eventlistener import QueryCompletedEvent
from trino_tpu.telemetry import journal

r = StandaloneQueryRunner(default_catalog(scale_factor=0.01))
r.execute("select count(*) from tpch.tiny.region",
          query_id="q_pre_restart")
# a finished run of the estimator's target plan, with a real peak (CPU runs
# report no device watermark, so the peak is stamped via the listener path)
journal.get_journal().query_completed(QueryCompletedEvent(
    "q_heavy", "select * from big", state="FINISHED",
    peak_memory_bytes=7 << 20))
print("CHILD_OK")
"""


def test_restart_preserves_history_and_seeds_admission(tmp_path):
    """The acceptance scenario: a coordinator process runs queries and
    dies; the next process (this one) still lists them in
    system.runtime.query_history, and estimate_peak_memory returns the
    journal-seeded peak instead of the default."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               TRINO_TPU_JOURNAL_DIR=os.environ["TRINO_TPU_JOURNAL_DIR"])
    out = subprocess.run([sys.executable, "-c", _CHILD], cwd=REPO, env=env,
                         capture_output=True, text=True, timeout=300)
    assert "CHILD_OK" in out.stdout, out.stderr[-2000:]

    # "restarted coordinator": fresh singleton + seed cache in this process
    journal.reset_for_test()
    r = StandaloneQueryRunner(default_catalog(scale_factor=0.01))
    rows = r.execute(
        "select query_id, state, output_rows from "
        "system.runtime.query_history").rows()
    assert ("q_pre_restart", "FINISHED", 1) in [tuple(x) for x in rows]

    fp = rt.fingerprint("select * from big")
    assert all(q.fingerprint != fp for q in rt.queries()), \
        "estimator must have no in-memory history for this fingerprint"
    default = 64 << 20
    assert estimate_peak_memory(fp, default) == 7 << 20
    assert estimate_peak_memory("fp_unknown", default) == default


def test_query_history_table_maps_all_columns(tmp_path):
    j = journal.get_journal()
    j.query_completed(_completed(
        "q_cols", sql="SELECT 3", peak=123, queued_time_ms=1.5,
        resource_group="global", speculative_wins=2, wall_ms=10.0,
        output_rows=4, input_rows=40, input_bytes=400))
    r = StandaloneQueryRunner(default_catalog(scale_factor=0.01))
    rows = r.execute(
        "select query_id, fingerprint, peak_memory_bytes, queued_time_ms, "
        "resource_group, speculative_wins, error_code "
        "from system.runtime.query_history where query_id = 'q_cols'").rows()
    assert [tuple(x) for x in rows] == [
        ("q_cols", rt.fingerprint("SELECT 3"), 123, 1.5, "global", 2, None)]


# ------------------------------------------------------------- schema lint


def test_journal_schema_lint_passes():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "lint_journal_schema.py")],
        cwd=REPO, capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, out.stdout + out.stderr


def test_journal_schema_lint_catches_bad_record():
    from tools.lint_journal_schema import lint_record

    assert lint_record({"schema": journal.SCHEMA_VERSION,
                        "event": "query_completed", "ts": 1.0,
                        "query_id": "q"}) == []
    problems = lint_record({"event": "x", "ts": 1.0, "query_id": "q",
                            "stats": {"nested": True}})
    assert any("schema" in p for p in problems)
    assert any("nested" not in p and "stats" in p for p in problems)
    assert lint_record({"schema": journal.SCHEMA_VERSION, "event": "x",
                        "ts": float("nan"), "query_id": "q"})


# ------------------------------------------ satellite: fleet journal fold
def test_fleet_members_write_own_streams_and_readers_fold(tmp_path,
                                                          monkeypatch):
    """Each fleet member appends to its own ``query_journal-<node>.jsonl``
    stream (no cross-process rotation races); every reader folds ALL
    streams — including rotated generations — oldest-first per stream."""
    d = str(tmp_path / "fleet")
    monkeypatch.setenv("TRINO_TPU_HA_NODE_ID", "coordA")
    ja = journal.QueryJournal(directory=d)
    assert ja.path.endswith("query_journal-coordA.jsonl")
    ja.query_completed(_completed("q_a1", peak=1 << 20))
    monkeypatch.setenv("TRINO_TPU_HA_NODE_ID", "coordB")
    jb = journal.QueryJournal(directory=d, max_bytes=256, max_files=2)
    jb.query_completed(_completed("q_b1", peak=2 << 20))
    jb.query_completed(_completed("q_b2", peak=3 << 20))  # forces rotation
    monkeypatch.delenv("TRINO_TPU_HA_NODE_ID")
    jc = journal.QueryJournal(directory=d)  # legacy single-node name
    jc.query_completed(_completed("q_c1", peak=4 << 20))

    ids = {r["query_id"] for r in jc.read()}
    assert ids == {"q_a1", "q_b1", "q_b2", "q_c1"}, \
        "read() must fold every member's stream"
    assert ids == {r["query_id"] for r in ja.read()}, \
        "the fold is symmetric: A sees B and the legacy stream too"
    assert len(jc.fleet_files()) >= 4  # A + B current + B rotated + legacy


def test_peer_journal_append_invalidates_admission_seed(tmp_path,
                                                        monkeypatch):
    """The admission estimator's seed-cache signature covers the FLEET
    file set: a peak recorded by a PEER coordinator reaches this
    process's estimate without any restart."""
    monkeypatch.setenv("TRINO_TPU_JOURNAL_DIR", str(tmp_path / "fj"))
    journal.reset_for_test()
    me = journal.get_journal()
    assert me is not None
    fp = rt.fingerprint("select * from fleet_big")
    default = 64 << 20
    assert estimate_peak_memory(fp, default) == default

    # a peer (distinct node id -> distinct stream) lands a history record
    monkeypatch.setenv("TRINO_TPU_HA_NODE_ID", "coordPeer")
    peer = journal.QueryJournal(directory=me.directory)
    peer.query_completed(_completed("q_peer", sql="select * from fleet_big",
                                    peak=7 << 20))
    monkeypatch.delenv("TRINO_TPU_HA_NODE_ID")

    assert estimate_peak_memory(fp, default) == 7 << 20, \
        "the peer's append must invalidate the local seed cache"
