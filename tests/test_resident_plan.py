"""Whole-query GSPMD compilation (execution/plan_compiler.py): the
fragmenter coalesces a maximal broadcast-join tree under a fusable
PARTIAL->FINAL seam into ONE ResidentPlan, and the runner compiles it as
one jitted program per feed batch — joins, chain, partial agg and state
merge inlined — with the build tables broadcast-replicated in-program.

Equivalence contract mirrors test_fused_stage: integer / decimal /
string / count outputs are bit-identical against the legacy path;
float64 sums/avgs compare at rel 1e-12 (state-merge reassociation).
``TRINO_TPU_RESIDENT_PLAN=0`` IS the task-per-worker path, bit-for-bit.
"""

import json
import math
import os
import socket
import subprocess
import sys
import textwrap

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.connectors.tpch_queries import QUERIES
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.execution.fragmenter import fragment_plan
from trino_tpu.execution.plan_compiler import ResidentPlanExec
from trino_tpu.runner import Session
from trino_tpu.testing.oracle import SqliteOracle, assert_same_rows

TABLES = ["customer", "orders", "lineitem"]


@pytest.fixture(autouse=True)
def _no_result_cache(monkeypatch):
    # these tests introspect execution internals (_resident_edges, rstats)
    # on repeated statements — a served cached result would skip the very
    # path under test
    monkeypatch.setenv("TRINO_TPU_RESULT_CACHE", "0")


@pytest.fixture(scope="module")
def harness():
    catalog = default_catalog(scale_factor=0.01)
    dist = DistributedQueryRunner(
        catalog, worker_count=4, session=Session(node_count=4))
    oracle = SqliteOracle()
    conn = catalog.connector("tpch")
    for t in TABLES:
        schema = conn.get_table_schema(t)
        cols = schema.column_names()
        batches = []
        for s in conn.get_splits(t, 2, 1):
            src = conn.create_page_source(s, cols)
            while not src.is_finished():
                b = src.get_next_batch()
                if b is not None:
                    batches.append(b)
        oracle.load_table(t, batches)
    yield dist, oracle
    # drop this module's compiled resident/build-prep programs: each holds
    # a jitted XLA executable, and the full tier-1 suite runs close enough
    # to the process mmap ceiling that keeping them segfaults a later
    # unrelated compile
    from trino_tpu.caching import executable_cache as ec
    import trino_tpu.execution.plan_compiler as pc

    for name in ("resident._program", "resident._build_prep"):
        cache = ec._REGISTRY.get(name)
        if cache is not None:
            cache.clear()
    with pc._RES_LOCK:
        pc._RES_TRACE_SIGS.clear()


def _rows(result):
    return sorted(map(tuple, result.rows()))


def _assert_equiv(res_rows, legacy_rows):
    assert len(res_rows) == len(legacy_rows)
    for rr, lr in zip(res_rows, legacy_rows):
        assert len(rr) == len(lr)
        for rv, lv in zip(rr, lr):
            if isinstance(rv, float) or isinstance(lv, float):
                assert math.isclose(float(rv), float(lv),
                                    rel_tol=1e-12, abs_tol=1e-12), (rv, lv)
            else:
                assert rv == lv, (rv, lv)


def _resident_execs(dist):
    return [e for e in dist._resident_edges.values()
            if isinstance(e, ResidentPlanExec)]


# ---------------------------------------------------------------------------
# fragmenter: plan coalescing + edge contracts


def test_fragmenter_coalesces_resident_plan(harness):
    dist, _ = harness
    plan = dist.create_plan(QUERIES[3])
    sp = fragment_plan(plan)
    marked = [f for f in sp.all_fragments()
              if getattr(f, "resident_plan", None) is not None]
    assert len(marked) == 1, "q3 must coalesce into ONE resident plan"
    f = marked[0]
    rp = f.resident_plan
    assert rp.core_fid == f.id and f.device_resident
    # q3: customer + orders builds + lineitem probe spine + FINAL consumer
    assert len(rp.fragment_ids) == 4
    assert len(rp.joins) == 2
    assert all(j.join_type == "INNER" for j in rp.joins)
    # per-edge PartitionSpec contracts: builds broadcast to replicated,
    # the terminal seam keeps dim 0 sharded on the mesh axis on BOTH sides
    bcast = [e for e in rp.edges if e.kind == "BROADCAST"]
    seam = [e for e in rp.edges if e.kind == "REPARTITION"]
    assert len(bcast) == 2 and len(seam) == 1
    for e in bcast:
        assert e.in_spec == ("x",) and e.out_spec == ()
    assert seam[0].in_spec == seam[0].out_spec == ("x",)
    assert seam[0].consumer_fid == rp.consumer_fid
    assert "resident-plan[4f/3e]" in sp.text()


# ---------------------------------------------------------------------------
# execution: one dispatch per batch, codes across seams, row equivalence


def test_q3_resident_vs_legacy(harness, monkeypatch):
    """The whole q3 join tree + agg runs as ONE jit dispatch per feed
    batch (launches/batch == 1), dictionary codes cross the customer
    broadcast seam as codes, and rows match the task-per-worker path."""
    dist, oracle = harness
    monkeypatch.setenv("TRINO_TPU_RESIDENT_PLAN", "auto")
    resident = dist.execute(QUERIES[3])
    execs = _resident_execs(dist)
    assert len(execs) == 1, "expected q3 to run as one resident plan"
    rs = execs[0].rstats
    assert rs.plans == 1 and rs.seams == 3
    assert rs.batches > 0
    assert rs.jit_calls == rs.batches, \
        "a resident plan must be ONE jitted call per batch"
    assert rs.launches_per_batch == 1.0
    # c_mktsegment's dict codes crossed the broadcast seam WITHOUT
    # materializing to values
    assert rs.code_seam_columns >= 1
    assert rs.merges == 1 and rs.fallbacks == 0

    monkeypatch.setenv("TRINO_TPU_RESIDENT_PLAN", "0")
    legacy = dist.execute(QUERIES[3])
    assert not dist._resident_edges, "=0 must disable resident compilation"
    assert dist._fused_edges, "=0 must restore the PR 6 fused seam"
    _assert_equiv(_rows(resident), _rows(legacy))
    assert_same_rows(resident.rows(), oracle.query(QUERIES[3]), ordered=True)
    assert_same_rows(legacy.rows(), oracle.query(QUERIES[3]), ordered=True)


def test_build_origin_dict_group_key(harness, monkeypatch):
    """Group key sourced from the BUILD side of an inlined join: the key's
    dictionary is the stable merged build dictionary, pinned for the whole
    query (no per-batch drift remaps)."""
    dist, oracle = harness
    sql = ("select c_mktsegment, count(*), sum(o_totalprice) "
           "from customer, orders where c_custkey = o_custkey "
           "group by c_mktsegment")
    monkeypatch.setenv("TRINO_TPU_RESIDENT_PLAN", "auto")
    result = dist.execute(sql)
    execs = _resident_execs(dist)
    assert execs, "expected a resident plan over the customer build"
    rs = execs[0].rstats
    assert rs.jit_calls == rs.batches and rs.code_seam_columns >= 1
    assert_same_rows(result.rows(), oracle.query(sql))

    monkeypatch.setenv("TRINO_TPU_RESIDENT_PLAN", "0")
    legacy = dist.execute(sql)
    _assert_equiv(_rows(result), _rows(legacy))


def test_steady_state_hits_program_cache(harness, monkeypatch):
    """Second identical run: every dispatch hits the resident program's
    shape-signature cache — compiles are O(#buckets), not O(#batches)."""
    dist, _ = harness
    monkeypatch.setenv("TRINO_TPU_RESIDENT_PLAN", "auto")
    dist.execute(QUERIES[3])  # warm
    dist.execute(QUERIES[3])
    (ex,) = _resident_execs(dist)
    rs = ex.rstats
    assert rs.batches > 0
    assert rs.programs == 0, "steady-state traffic must never retrace"
    assert rs.cache_hits == rs.jit_calls


# ---------------------------------------------------------------------------
# fallbacks: overflow + duplicate build keys re-run the legacy path


def test_overflow_falls_back(harness, monkeypatch):
    """More groups than TRINO_TPU_FUSED_CAP: the overflow scalar trips at
    finish, the runner counts a resident fallback and re-runs the subplan
    on the task-per-worker path (no group cap) — correct results."""
    dist, oracle = harness
    monkeypatch.setenv("TRINO_TPU_RESIDENT_PLAN", "auto")
    monkeypatch.setenv("TRINO_TPU_FUSED_CAP", "8")
    before = dist.resident_fallbacks
    result = dist.execute(QUERIES[3])
    assert dist.resident_fallbacks == before + 1
    assert_same_rows(result.rows(), oracle.query(QUERIES[3]), ordered=True)


def test_duplicate_build_keys_fall_back(harness, monkeypatch):
    """The inlined sorted probe is 1-match; a build side with duplicate
    join keys trips the replicated dup flag at prep and the plan falls
    back to the legacy multi-match join — results stay correct."""
    dist, oracle = harness
    # join keyed on o_custkey: customers place many orders, so the build
    # table carries duplicate live keys
    sql = ("select c_mktsegment, count(*) "
           "from customer, orders where c_nationkey = o_custkey "
           "group by c_mktsegment")
    monkeypatch.setenv("TRINO_TPU_RESIDENT_PLAN", "auto")
    plan = dist.create_plan(sql)
    sp = fragment_plan(plan)
    assert any(getattr(f, "resident_plan", None) is not None
               for f in sp.all_fragments()), \
        "the dup-key query must still COALESCE (dups are a runtime fact)"
    before = dist.resident_fallbacks
    result = dist.execute(sql)
    assert dist.resident_fallbacks == before + 1
    assert_same_rows(result.rows(), oracle.query(sql))


# ---------------------------------------------------------------------------
# gating knobs


def test_mesh_shape_cap_disables(harness, monkeypatch):
    """TRINO_TPU_MESH_SHAPE narrower than the task count: the plan can't
    claim its mesh, the PR 6 fused seam takes the edge back."""
    dist, oracle = harness
    monkeypatch.setenv("TRINO_TPU_RESIDENT_PLAN", "auto")
    monkeypatch.setenv("TRINO_TPU_MESH_SHAPE", "2")
    result = dist.execute(QUERIES[3])
    assert not dist._resident_edges
    assert dist._fused_edges
    assert_same_rows(result.rows(), oracle.query(QUERIES[3]), ordered=True)


def test_max_fragments_gate(harness, monkeypatch):
    """A 4-fragment plan under TRINO_TPU_RESIDENT_MAX_FRAGMENTS=2 stays on
    the fused path."""
    dist, _ = harness
    monkeypatch.setenv("TRINO_TPU_RESIDENT_PLAN", "auto")
    monkeypatch.setenv("TRINO_TPU_RESIDENT_MAX_FRAGMENTS", "2")
    dist.execute(QUERIES[3])
    assert not dist._resident_edges
    assert dist._fused_edges


# ---------------------------------------------------------------------------
# warm journal: resident program keys are JSON-able and replayable


def test_resident_program_memo_key_warms(harness, monkeypatch):
    """The resident accumulate memo keys on a VALUE (base64 plan payload),
    unlike the id()-keyed fused memo — so the key survives json round-trip
    and cache.warm() re-instantiates the program at boot."""
    from trino_tpu.caching import executable_cache as ec

    dist, _ = harness
    monkeypatch.setenv("TRINO_TPU_RESIDENT_PLAN", "auto")
    dist.execute(QUERIES[3])
    with ec._WARM_LOCK:
        keys = [list(key) for (name, key) in ec._WARM_SEEN
                if name == "resident._program"]
    assert keys, "resident._program must journal a warm key"
    round_tripped = json.loads(json.dumps(keys[0]))
    cache = ec._REGISTRY["resident._program"]
    assert cache.warm(tuple(round_tripped)), \
        "boot replay must rebuild the resident program from the journal"


# ---------------------------------------------------------------------------
# multi-process: one program spans two host processes on a CPU mesh


def test_init_distributed_gloo_before_initialize(monkeypatch):
    """The gloo CPU-collectives backend must be selected BEFORE
    jax.distributed.initialize — the default XLA CPU backend rejects
    multi-process collectives outright."""
    import trino_tpu.execution.plan_compiler as pc

    seen = []
    monkeypatch.setattr(pc.jax.config, "update",
                        lambda k, v: seen.append((k, v)))
    monkeypatch.setattr(pc.jax.distributed, "initialize",
                        lambda **kw: seen.append(("initialize", kw)))
    pc.init_distributed("127.0.0.1:9999", num_processes=2, process_id=1)
    assert seen[0] == ("jax_cpu_collectives_implementation", "gloo")
    assert seen[1] == ("initialize", {
        "coordinator_address": "127.0.0.1:9999",
        "num_processes": 2, "process_id": 1})


_CHILD = textwrap.dedent("""
    import sys

    port, pid = sys.argv[1], int(sys.argv[2])

    # worker boot order matters: importing the engine itself traces jax
    # programs, and jax.distributed.initialize refuses to run after ANY
    # computation — so distributed bring-up comes first, with the same
    # gloo-before-initialize recipe as plan_compiler.init_distributed
    import jax
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=2, process_id=pid)

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from trino_tpu.execution.plan_compiler import _AXIS
    from trino_tpu.parallel.compat import shard_map

    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    assert jax.local_device_count() == 4, jax.local_device_count()

    mesh = Mesh(jax.devices(), (_AXIS,))
    per = 3
    local = np.arange(4 * per, dtype=np.int64) + pid * 4 * per
    shards = [jax.device_put(local[i * per:(i + 1) * per], d)
              for i, d in enumerate(jax.local_devices())]
    g = jax.make_array_from_single_device_arrays(
        (8 * per,), NamedSharding(mesh, P(_AXIS)), shards)

    fn = jax.jit(shard_map(
        lambda x: jax.lax.all_gather(x, _AXIS, tiled=True),
        mesh=mesh, in_specs=P(_AXIS), out_specs=P(), check_vma=False))
    rep = np.asarray(fn(g).addressable_shards[0].data)
    assert (rep == np.arange(8 * per)).all(), rep
    print(f"RESIDENT-MP-OK {pid}")
""")


def test_two_process_cpu_mesh_collectives(tmp_path):
    """jax.distributed bring-up with the gloo CPU-collectives backend: two
    host processes, 4 forced devices each, one 8-device global mesh; the
    resident plan's broadcast gather (all_gather P("x") -> P()) produces
    the full replicated table in BOTH processes."""
    script = tmp_path / "resident_mp_child.py"
    script.write_text(_CHILD)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "PYTHONPATH": repo + os.pathsep + env.get("PYTHONPATH", ""),
    })
    # a child inheriting the parent's 8-device forcing would skew the
    # global mesh; the env above overrides it explicitly
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(port), str(pid)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
        for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out}"
        assert f"RESIDENT-MP-OK {pid}" in out
