"""REST statement protocol (L8/L9) + page serde over the exchange
(reference: dispatcher/QueuedStatementResource.java,
client/StatementClientV1.java, buffer/PageSerializer.java)."""

import numpy as np
import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.execution.serde import (
    CODEC_NONE,
    CODEC_ZLIB,
    deserialize_batch,
    serialize_batch,
)
from trino_tpu.runner import Session, StandaloneQueryRunner
from trino_tpu.server import Client, TrinoTpuServer
from trino_tpu.spi.batch import Column, ColumnBatch
from trino_tpu.spi.types import BIGINT, DOUBLE, DecimalType, VARCHAR
from trino_tpu.testing.oracle import assert_same_rows


# ---------------------------------------------------------------- page serde


def _mixed_batch():
    return ColumnBatch(
        ["k", "x", "d", "s"],
        [
            Column(BIGINT, np.array([1, 2, 3], np.int64),
                   np.array([True, False, True])),
            Column(DOUBLE, np.array([1.5, np.nan, -0.0])),
            Column(DecimalType(18, 2), np.array([150, -275, 0], np.int64)),
            Column(VARCHAR, np.array([0, 1, 0], np.int32), None,
                   np.array(["alpha", "beta"], dtype=object)),
        ],
    )


@pytest.mark.parametrize("codec", [CODEC_NONE, CODEC_ZLIB])
def test_serde_roundtrip(codec):
    b = _mixed_batch()
    wire = serialize_batch(b, codec=codec)
    assert isinstance(wire, bytes)
    out = deserialize_batch(wire)
    assert out.names == b.names
    assert [str(t) for t in out.types] == [str(t) for t in b.types]
    assert repr(out.to_pylist()) == repr(b.to_pylist())  # NaN-tolerant


def test_serde_compresses():
    big = ColumnBatch(
        ["x"], [Column(BIGINT, np.zeros(100_000, np.int64))])
    z = serialize_batch(big, codec=CODEC_ZLIB)
    raw = serialize_batch(big, codec=CODEC_NONE)
    assert len(z) < len(raw) / 10


def test_serde_live_mask_compacted():
    b = ColumnBatch(
        ["x"], [Column(BIGINT, np.arange(8, dtype=np.int64))],
        live=np.array([True, False] * 4))
    out = deserialize_batch(serialize_batch(b))
    assert out.to_pylist() == [(0,), (2,), (4,), (6,)]


def test_distributed_with_exchange_serde():
    """TPC-H-shaped queries produce identical results when every exchange
    page crosses a serialize/deserialize wire boundary."""
    catalog = default_catalog(scale_factor=0.01)
    plain = DistributedQueryRunner(
        catalog, worker_count=3,
        session=Session(node_count=3, use_collectives=False))
    wired = DistributedQueryRunner(
        catalog, worker_count=3,
        session=Session(node_count=3, use_collectives=False,
                        exchange_serde=True))
    for sql in [
        "select l_returnflag, count(*), sum(l_quantity) from lineitem "
        "group by l_returnflag",
        "select c_mktsegment, count(*) from customer, orders "
        "where c_custkey = o_custkey group by c_mktsegment",
    ]:
        assert_same_rows(wired.execute(sql).rows(), plain.execute(sql).rows())


# ---------------------------------------------------------- REST protocol


@pytest.fixture(scope="module")
def server():
    runner = StandaloneQueryRunner(default_catalog(scale_factor=0.01))
    srv = TrinoTpuServer(runner, port=0).start()
    yield srv
    srv.stop()


def test_rest_roundtrip(server):
    host, port = server.address
    client = Client(host, port)
    columns, rows = client.execute(
        "select n_regionkey, count(*) as c from nation group by n_regionkey "
        "order by n_regionkey")
    assert [c["name"] for c in columns] == ["n_regionkey", "c"]
    assert rows == [[i, 5] for i in range(5)]


def test_rest_types_encoding(server):
    host, port = server.address
    client = Client(host, port)
    columns, rows = client.execute(
        "select o_orderdate, o_totalprice from orders where o_orderkey = 1")
    assert columns[0]["type"] == "date"
    assert columns[1]["type"].startswith("decimal")
    assert isinstance(rows[0][0], str) and rows[0][0].count("-") == 2
    float(rows[0][1])  # decimal as string


def test_rest_failure_surfaces(server):
    host, port = server.address
    client = Client(host, port)
    from trino_tpu.server.client import QueryFailed

    with pytest.raises(QueryFailed, match="(?i)parse|expected"):
        client.execute("selec broken")


def test_rest_concurrent_queries(server):
    import threading

    host, port = server.address
    results = []

    def go(i):
        _, rows = Client(host, port).execute(
            f"select {i} as tag, count(*) from region")
        results.append(rows[0])

    threads = [threading.Thread(target=go, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert sorted(r[0] for r in results) == list(range(6))
    assert all(r[1] == 5 for r in results)
