"""Adaptive query execution (ISSUE 13): phased stage activation, runtime
join-distribution switching, skew-aware repartitioning — plus the
satellites that ride along (durable cluster blacklist, non-blocking sinks).

The oracle discipline throughout: every adaptive run must return exactly
the rows of an ``adaptive=0`` (bit-for-bit legacy) run of the same query.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from trino_tpu.execution.adaptive import (
    HeavyHitterSketch,
    adaptive_mode,
    broadcast_threshold_bytes,
    reset_memo_for_test,
    skew_factor,
)
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.execution.exchange import OutputBuffer
from trino_tpu.execution.task import PartitionedOutputSink
from trino_tpu.runner import Session
from trino_tpu.telemetry import metrics as tm
from trino_tpu.telemetry import runtime as rt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

JOIN_SQL = ("select c.c_mktsegment, count(*) n, sum(o.o_totalprice) s "
            "from orders o join customer c on o.o_custkey = c.c_custkey "
            "group by c.c_mktsegment order by 1")

# half the probe rows collapse onto key 1: the canonical heavy hitter.
# The sum spans BOTH join sides so the iterative optimizer cannot
# pre-aggregate the probe below the join (which would compact the heavy
# key to one row at plan time and leave the runtime split nothing to do)
SKEW_SQL = ("select count(*) n, sum(p.o_totalprice + b.c_acctbal) s "
            "from (select case when o_orderkey % 2 = 0 then 1 "
            "             else o_custkey end as k, o_totalprice "
            "      from orders) p "
            "join (select c_custkey, c_acctbal from customer) b "
            "on p.k = b.c_custkey")


@pytest.fixture(autouse=True)
def _fresh_memo():
    # result cache off: an adaptive=0 oracle must actually re-execute, not
    # replay the adaptive run's cached rows
    from trino_tpu.caching import result_cache

    reset_memo_for_test()
    with result_cache.disabled():
        yield
    reset_memo_for_test()


@pytest.fixture()
def plain_exchanges(monkeypatch):
    """Adaptive decision sites require plain buffer edges: fused seams and
    device collectives rendezvous producers and consumers (and a fused seam
    plans a snapshot of its feed), so both are out of adaptive scope.  Pin
    them off so the decision-shape tests exercise the plane regardless of
    the 8-device test mesh."""
    monkeypatch.setenv("TRINO_TPU_FUSED_STAGE", "0")
    yield


_LEGACY_MEMO: dict = {}


def _legacy(sql: str):
    # deterministic oracle run; memoized so repeated drills pay it once
    if sql not in _LEGACY_MEMO:
        r = DistributedQueryRunner(
            session=Session(node_count=3, adaptive="0"))
        _LEGACY_MEMO[sql] = r.execute(sql).batch.to_pylist()
    return _LEGACY_MEMO[sql]


def _last_decisions() -> str:
    return rt.queries()[-1].adaptive_decisions


# ------------------------------------------------------------------- knobs
def test_mode_and_threshold_knobs(monkeypatch):
    assert adaptive_mode(Session(adaptive="0")) == "0"
    assert adaptive_mode(Session(adaptive=1)) == "1"
    assert adaptive_mode(Session(adaptive="AUTO")) == "auto"
    monkeypatch.setenv("TRINO_TPU_ADAPTIVE", "off")
    assert adaptive_mode(Session()) == "0"
    monkeypatch.delenv("TRINO_TPU_ADAPTIVE")
    assert adaptive_mode(Session()) == "auto"
    assert broadcast_threshold_bytes(Session()) == 32 << 20
    assert broadcast_threshold_bytes(
        Session(broadcast_threshold_bytes=7)) == 7
    monkeypatch.setenv("TRINO_TPU_SKEW_FACTOR", "3.5")
    assert skew_factor(Session()) == 3.5
    assert skew_factor(Session(skew_factor=1.1)) == 1.1


# ------------------------------------------------------------------ sketch
def test_heavy_hitter_sketch_counts_merges_and_prunes():
    s = HeavyHitterSketch(k=4)
    s.update(np.array([1, 1, 1, 2, 3], dtype=np.uint64))
    s.update(np.array([1, 2], dtype=np.uint64))
    assert s.total == 7
    assert s.counts[1] == 4 and s.counts[2] == 2
    t = HeavyHitterSketch(k=4)
    t.update(np.array([1, 9], dtype=np.uint64))
    s.merge(t)
    assert s.total == 9 and s.counts[1] == 5
    # heavy: above factor x (total / n) — threshold 1.0 x 9/2 = 4.5 < 5
    assert set(s.heavy(1.0, 2).keys()) == {1}
    assert s.heavy(1.5, 2) == {}  # 1.5 x 9/2 = 6.75 > 5: not heavy
    assert s.heavy(0.5, 1) == {}  # single partition: nothing to rebalance
    # pruning keeps the heaviest entries and the exact total
    big = HeavyHitterSketch(k=2)
    for v in range(40):
        big.update(np.full(v + 1, v, dtype=np.uint64))
    assert len(big.counts) <= 8
    assert big.total == sum(range(1, 41))
    assert 39 in big.counts  # the heaviest survives every prune


# ----------------------------------------------------- plan-shape: rewrite
def test_split_probe_fragment_plan_shape():
    """B->P re-fragmentation: probe subtree becomes a REPARTITION fragment
    on the join's left keys; the join is rewritten PARTITIONED with a
    RemoteSource probe."""
    from trino_tpu.execution.fragmenter import split_probe_fragment
    from trino_tpu.planner.plan import Join, RemoteSource

    r = DistributedQueryRunner(session=Session(node_count=3))
    subplan = r.create_subplan(JOIN_SQL)
    frags = subplan.all_fragments()
    consumer = next(
        f for f in frags
        if any(isinstance(n, Join) for n in _walk(f.root)))
    join = next(n for n in _walk(consumer.root) if isinstance(n, Join))
    assert join.distribution == "BROADCAST"  # customer is tiny
    old_sources = list(consumer.source_fragments)
    new_fid = max(f.id for f in frags) + 1
    new_frag = split_probe_fragment(consumer, join, new_fid)
    assert new_frag.output_kind == "REPARTITION"
    assert new_frag.output_keys == tuple(join.left_keys)
    new_join = next(n for n in _walk(consumer.root) if isinstance(n, Join))
    assert new_join.distribution == "PARTITIONED"
    assert isinstance(new_join.left, RemoteSource)
    assert new_join.left.fragment_id == new_fid
    assert new_fid in consumer.source_fragments
    # probe-side producers moved under the new fragment
    assert set(new_frag.source_fragments) <= set(old_sources)


def _walk(node):
    yield node
    for c in node.children:
        yield from _walk(c)


# ------------------------------------------- runtime decisions, both flips
def test_flip_to_partitioned_when_build_exceeds_threshold(plain_exchanges):
    before = tm.ADAPTIVE_PARTITION_FLIPS.value()
    r = DistributedQueryRunner(session=Session(
        node_count=3, adaptive="auto", use_collectives=False,
        broadcast_threshold_bytes=1000))
    rows = r.execute(JOIN_SQL).batch.to_pylist()
    assert "flip_to_partitioned" in _last_decisions()
    assert tm.ADAPTIVE_PARTITION_FLIPS.value() == before + 1
    assert rows == _legacy(JOIN_SQL)


def test_flip_to_broadcast_when_build_is_small(monkeypatch, plain_exchanges):
    monkeypatch.setenv("TRINO_TPU_BROADCAST_ROW_LIMIT", "0")  # mis-estimate
    before = tm.ADAPTIVE_BROADCAST_FLIPS.value()
    r = DistributedQueryRunner(session=Session(
        node_count=3, adaptive="auto", use_collectives=False,
        broadcast_threshold_bytes=1 << 30))
    rows = r.execute(JOIN_SQL).batch.to_pylist()
    assert "flip_to_broadcast" in _last_decisions()
    assert tm.ADAPTIVE_BROADCAST_FLIPS.value() == before + 1
    monkeypatch.delenv("TRINO_TPU_BROADCAST_ROW_LIMIT")
    assert rows == _legacy(JOIN_SQL)


def test_no_flip_when_stats_agree_with_planner(plain_exchanges):
    """Static broadcast + build genuinely under the threshold: the barrier
    confirms the planner and must not rewrite anything."""
    before = (tm.ADAPTIVE_BROADCAST_FLIPS.value(),
              tm.ADAPTIVE_PARTITION_FLIPS.value())
    r = DistributedQueryRunner(session=Session(
        node_count=3, adaptive="auto", use_collectives=False))
    rows = r.execute(JOIN_SQL).batch.to_pylist()
    assert _last_decisions() == "keep[f2]"
    assert (tm.ADAPTIVE_BROADCAST_FLIPS.value(),
            tm.ADAPTIVE_PARTITION_FLIPS.value()) == before
    assert rows == _legacy(JOIN_SQL)


def test_skew_split_on_heavy_probe_key(monkeypatch, plain_exchanges):
    monkeypatch.setenv("TRINO_TPU_BROADCAST_ROW_LIMIT", "0")
    before = tm.ADAPTIVE_SKEW_SPLITS.value()
    r = DistributedQueryRunner(session=Session(
        node_count=3, adaptive="auto", use_collectives=False,
        broadcast_threshold_bytes=1000, skew_factor=1.2))
    rows = r.execute(SKEW_SQL).batch.to_pylist()
    assert "skew_split" in _last_decisions()
    assert tm.ADAPTIVE_SKEW_SPLITS.value() == before + 1
    monkeypatch.delenv("TRINO_TPU_BROADCAST_ROW_LIMIT")
    assert rows == _legacy(SKEW_SQL)


def test_decision_memo_replays_repeated_shapes(plain_exchanges):
    before = tm.ADAPTIVE_MEMO_HITS.value()
    sess = Session(node_count=3, adaptive="auto", use_collectives=False,
                   broadcast_threshold_bytes=1000)
    r = DistributedQueryRunner(session=sess)
    from trino_tpu.caching import result_cache

    with result_cache.disabled():
        a = r.execute(JOIN_SQL).batch.to_pylist()
        b = r.execute(JOIN_SQL).batch.to_pylist()
    assert a == b
    assert tm.ADAPTIVE_MEMO_HITS.value() > before
    assert "flip_to_partitioned" in _last_decisions()


# ----------------------------------------------------------------- oracle
def test_adaptive_oracle_identical_to_legacy_across_mix(monkeypatch, plain_exchanges):
    """adaptive=1 (phased scheduler forced) vs adaptive=0 over the chaos
    query mix + the flip/skew drills: identical rows everywhere, with
    thresholds tuned so every decision kind actually fires somewhere."""
    from trino_tpu.testing.chaos import QUERY_MIX

    monkeypatch.setenv("TRINO_TPU_BROADCAST_ROW_LIMIT", "0")
    # the join + filtered-agg mix entries and the two drills cover every
    # decision site; single-table group-bys have no deferred edges
    queries = [QUERY_MIX[0], QUERY_MIX[4], QUERY_MIX[5], JOIN_SQL, SKEW_SQL]
    on = DistributedQueryRunner(session=Session(
        node_count=3, adaptive="1", use_collectives=False,
        broadcast_threshold_bytes=64 << 10, skew_factor=1.2))
    off = DistributedQueryRunner(session=Session(node_count=3,
                                                 adaptive="0"))
    for sql in queries:
        a = sorted(map(tuple, on.execute(sql).batch.to_pylist()))
        b = sorted(map(tuple, off.execute(sql).batch.to_pylist()))
        assert a == b, f"adaptive result diverged for: {sql}"


def test_explain_analyze_reports_adaptive_decisions(plain_exchanges):
    r = DistributedQueryRunner(session=Session(
        node_count=3, adaptive="auto", use_collectives=False,
        broadcast_threshold_bytes=1000))
    out = r.execute("explain analyze " + JOIN_SQL)
    txt = "\n".join(v[0] for v in out.batch.to_pylist())
    assert "adaptive:" in txt and "flip_to_partitioned" in txt


def test_adaptive_zero_never_builds_the_plane(monkeypatch):
    """adaptive=0 is bit-for-bit legacy: AdaptiveExec is never even
    constructed."""
    import trino_tpu.execution.adaptive as adaptive_mod

    def boom(*a, **k):
        raise AssertionError("AdaptiveExec constructed under adaptive=0")

    monkeypatch.setattr(adaptive_mod, "AdaptiveExec", boom)
    r = DistributedQueryRunner(session=Session(node_count=3, adaptive="0"))
    assert r.execute(JOIN_SQL).batch.num_rows > 0


# ----------------------------------------- chaos interop (fault injection)
def test_adaptive_survives_injected_task_failure_with_query_retry(plain_exchanges):
    from trino_tpu.execution.failure_injector import (
        TASK_FAILURE,
        FailureInjector,
    )

    inj = FailureInjector()
    inj.inject(TASK_FAILURE, fragment_id=None, task_index=0, attempt=0,
               times=1)
    r = DistributedQueryRunner(session=Session(
        node_count=2, adaptive="auto", use_collectives=False,
        broadcast_threshold_bytes=1000, retry_policy="QUERY",
        retry_initial_delay_s=0.01, failure_injector=inj))
    rows = r.execute(JOIN_SQL).batch.to_pylist()
    assert r.resilience.query_retries >= 1
    assert rows == _legacy(JOIN_SQL)


# ------------------------------------ satellite: durable cluster blacklist
_BL_CHILD = """
import os
os.environ["JAX_PLATFORMS"] = "cpu"
from trino_tpu.execution.speculation import ClusterBlacklist
bl = ClusterBlacklist(ttl_s=3600.0, threshold=2.0, persist=True)
bl.record_failure("worker-1", reason="REMOTE_HOST_GONE", query_id="q_a")
bl.record_failure("worker-1", reason="REMOTE_TASK_ERROR", query_id="q_b")
bl.record_failure("worker-2", reason="REMOTE_TASK_ERROR", query_id="q_c")
assert bl.is_blacklisted("worker-1")
print("CHILD_OK")
"""


def test_cluster_blacklist_survives_coordinator_restart(tmp_path,
                                                        monkeypatch):
    """Satellite: blacklist strikes journal through telemetry/journal.py
    and re-seed (TTL-decayed) on the next coordinator boot — simulated
    with a real subprocess, exactly like the query-history restart test."""
    from trino_tpu.execution.speculation import ClusterBlacklist
    from trino_tpu.telemetry import journal

    monkeypatch.setenv("TRINO_TPU_JOURNAL_DIR", str(tmp_path / "journal"))
    monkeypatch.delenv("TRINO_TPU_JOURNAL", raising=False)
    journal.reset_for_test()
    env = dict(os.environ,
               TRINO_TPU_JOURNAL_DIR=str(tmp_path / "journal"))
    out = subprocess.run([sys.executable, "-c", _BL_CHILD], cwd=REPO,
                         env=env, capture_output=True, text=True,
                         timeout=300)
    assert "CHILD_OK" in out.stdout, out.stderr[-2000:]

    journal.reset_for_test()  # "restarted coordinator": fresh singleton
    bl = ClusterBlacklist(ttl_s=3600.0, threshold=2.0, persist=True)
    assert bl.is_blacklisted("worker-1"), "strikes must survive restart"
    assert bl.score("worker-2") == 1.0
    assert not bl.is_blacklisted("worker-2")
    # TTL decay applies to seeded entries: an expired journal is inert
    journal.reset_for_test()
    tiny = ClusterBlacklist(ttl_s=1e-9, threshold=2.0, persist=True)
    assert tiny.score("worker-1") == 0.0
    journal.reset_for_test()


# ------------------------------------- satellite: non-blocking sink enqueue
def test_nonblocking_sink_refuses_input_instead_of_blocking():
    """TIME_SHARING flips ``sink.blocking = False``: a full buffer makes
    ``needs_input`` False (the driver parks) and ``enqueue(block=False)``
    returns immediately instead of pinning the worker."""
    import time

    from trino_tpu.spi.batch import Column, ColumnBatch
    from trino_tpu.spi.types import BIGINT

    buf = OutputBuffer(1, max_bytes=64)
    sink = PartitionedOutputSink(buf, "GATHER")
    sink.blocking = False
    batch = ColumnBatch(["x"], [
        Column(BIGINT, np.arange(64, dtype=np.int64))])
    assert sink.needs_input()
    t0 = time.monotonic()
    sink.add_input(batch)   # overshoots the 64-byte budget
    sink.add_input(batch)   # must NOT block despite the full buffer
    assert time.monotonic() - t0 < 1.0
    assert not buf.has_capacity()
    assert not sink.needs_input(), "full buffer must park the driver"
    # consumer ack frees capacity and un-parks
    pages, token, _ = buf.get(0, 0, timeout=0.1)
    buf.get(0, token, timeout=0.1)
    assert sink.needs_input()


def test_time_sharing_query_with_tiny_sink_cap(monkeypatch):
    """End-to-end: TIME_SHARING + a 1 MiB cap forces real parking cycles;
    the query must still complete with oracle-identical rows (quantum
    pinning was never traded for unbounded buffer growth)."""
    monkeypatch.setenv("TRINO_TPU_SINK_MAX_BYTES", str(1 << 20))
    r = DistributedQueryRunner(session=Session(
        node_count=2, task_scheduler="TIME_SHARING", executor_workers=2))
    rows = sorted(map(tuple, r.execute(JOIN_SQL).batch.to_pylist()))
    monkeypatch.delenv("TRINO_TPU_SINK_MAX_BYTES")
    assert rows == sorted(map(tuple, _legacy(JOIN_SQL)))
