"""Expression IR -> JAX lowering tests (the sql/gen equivalent)."""

import numpy as np
import pytest

from trino_tpu.spi import BIGINT, BOOLEAN, DATE, DOUBLE, VARCHAR, Column, DecimalType
from trino_tpu.sql.ir import Call, InputRef, Literal, call
from trino_tpu.ops.expr import compile_expression

import jax
import jax.numpy as jnp


def _cols(*columns):
    out = []
    for c in columns:
        valid = None if c.valid is None else jnp.asarray(c.valid)
        out.append((jnp.asarray(c.data), valid))
    return out


def test_arith_and_nulls():
    a = Column.from_values(BIGINT, [1, 2, None, 4])
    b = Column.from_values(BIGINT, [10, 0, 30, 40])
    expr = call("add", BIGINT, InputRef(BIGINT, 0), InputRef(BIGINT, 1))
    c = compile_expression(expr, [BIGINT, BIGINT])
    data, valid = c(_cols(a, b))
    assert list(np.asarray(data)[[0, 1, 3]]) == [11, 2, 44]
    assert list(np.asarray(valid)) == [True, True, False, True]


def test_division_by_zero_yields_null():
    a = Column.from_values(BIGINT, [10, 7, -7])
    b = Column.from_values(BIGINT, [0, 2, 2])
    expr = call("divide", BIGINT, InputRef(BIGINT, 0), InputRef(BIGINT, 1))
    data, valid = compile_expression(expr, [BIGINT, BIGINT])(_cols(a, b))
    assert list(np.asarray(valid)) == [False, True, True]
    # SQL integer division truncates toward zero
    assert list(np.asarray(data)[[1, 2]]) == [3, -3]


def test_decimal_arithmetic():
    t = DecimalType(15, 2)
    price = Column.from_values(t, ["100.00", "33.33"])
    disc = Column.from_values(t, ["0.10", "0.05"])
    # price * (1 - disc) -> decimal scale 4
    one = Literal(DecimalType(15, 2), 1)
    sub = call("subtract", DecimalType(15, 2), one, InputRef(t, 1))
    mul = call("multiply", DecimalType(18, 4), InputRef(t, 0), sub)
    data, valid = compile_expression(mul, [t, t])(_cols(price, disc))
    assert valid is None
    assert list(np.asarray(data)) == [900000, 316635]  # 90.0000, 31.6635


def test_three_valued_logic():
    x = Column.from_values(BOOLEAN, [True, False, None])
    # x AND NULL: F->F, T->NULL, NULL->NULL
    expr = call("$and", BOOLEAN, InputRef(BOOLEAN, 0), Literal(BOOLEAN, None))
    data, valid = compile_expression(expr, [BOOLEAN])(_cols(x))
    v = np.asarray(valid)
    d = np.asarray(data)
    assert not v[0] and not v[2]
    assert v[1] and not d[1]
    # x OR NULL: T->T, F->NULL
    expr = call("$or", BOOLEAN, InputRef(BOOLEAN, 0), Literal(BOOLEAN, None))
    data, valid = compile_expression(expr, [BOOLEAN])(_cols(x))
    v, d = np.asarray(valid), np.asarray(data)
    assert v[0] and d[0]
    assert not v[1] and not v[2]


def test_string_compare_like_in():
    col = Column.from_values(VARCHAR, ["MAIL", "SHIP", "AIR", None, "RAIL"])
    dicts = [col.dictionary]
    ref = InputRef(VARCHAR, 0)
    eq = call("eq", BOOLEAN, ref, Literal(VARCHAR, "SHIP"))
    data, valid = compile_expression(eq, [VARCHAR], dicts)(_cols(col))
    assert list(np.asarray(data) & np.asarray(valid)) == [False, True, False, False, False]
    lt = call("lt", BOOLEAN, ref, Literal(VARCHAR, "MAIL"))
    data, _ = compile_expression(lt, [VARCHAR], dicts)(_cols(col))
    assert list(np.asarray(data)) == [False, False, True, True, False]  # AIR, "" < MAIL
    inn = call("$in", BOOLEAN, ref, Literal(VARCHAR, "MAIL"), Literal(VARCHAR, "SHIP"))
    data, _ = compile_expression(inn, [VARCHAR], dicts)(_cols(col))
    assert list(np.asarray(data)) == [True, True, False, False, False]
    like = call("$like", BOOLEAN, ref, Literal(VARCHAR, "%AI%"))
    data, _ = compile_expression(like, [VARCHAR], dicts)(_cols(col))
    assert list(np.asarray(data)) == [True, False, True, False, True]


def test_string_transform_functions():
    col = Column.from_values(VARCHAR, ["13-345", "29-999", "13-222"])
    ref = InputRef(VARCHAR, 0)
    sub = call("substring", VARCHAR, ref, Literal(BIGINT, 1), Literal(BIGINT, 2))
    c = compile_expression(sub, [VARCHAR], [col.dictionary])
    data, _ = c(_cols(col))
    assert [str(c.dictionary[i]) for i in np.asarray(data)] == ["13", "29", "13"]
    ln = call("length", BIGINT, ref)
    data, _ = compile_expression(ln, [VARCHAR], [col.dictionary])(_cols(col))
    assert list(np.asarray(data)) == [6, 6, 6]


def test_dates():
    col = Column.from_values(DATE, ["1995-03-15", "1996-12-31", "2000-02-29"])
    ref = InputRef(DATE, 0)
    yr = call("year", BIGINT, ref)
    data, _ = compile_expression(yr, [DATE])(_cols(col))
    assert list(np.asarray(data)) == [1995, 1996, 2000]
    # date + 3 months with clamping: 1996-12-31 + 2 months = 1997-02-28
    am = call("add_months", DATE, ref, Literal(BIGINT, 2))
    data, _ = compile_expression(am, [DATE])(_cols(col))
    import datetime

    from trino_tpu.spi.types import days_to_date

    assert days_to_date(int(np.asarray(data)[1])) == datetime.date(1997, 2, 28)
    assert days_to_date(int(np.asarray(data)[2])) == datetime.date(2000, 4, 29)
    cmp = call(
        "ge", BOOLEAN, ref, Literal(DATE, "1996-01-01")
    )
    data, _ = compile_expression(cmp, [DATE])(_cols(col))
    assert list(np.asarray(data)) == [False, True, True]


def test_case_if_coalesce():
    x = Column.from_values(BIGINT, [1, 2, None])
    ref = InputRef(BIGINT, 0)
    iff = call(
        "$if", BIGINT, call("eq", BOOLEAN, ref, Literal(BIGINT, 1)),
        Literal(BIGINT, 100), Literal(BIGINT, 200),
    )
    data, valid = compile_expression(iff, [BIGINT])(_cols(x))
    assert list(np.asarray(data)) == [100, 200, 200]
    coal = call("$coalesce", BIGINT, ref, Literal(BIGINT, -1))
    data, valid = compile_expression(coal, [BIGINT])(_cols(x))
    assert valid is None
    assert list(np.asarray(data)) == [1, 2, -1]


def test_cast_and_round():
    t = DecimalType(10, 2)
    x = Column.from_values(t, ["12.345".replace("5", ""), "99.99"])  # 12.34, 99.99
    cast = call("$cast", DOUBLE, InputRef(t, 0))
    data, _ = compile_expression(cast, [t])(_cols(x))
    assert np.allclose(np.asarray(data), [12.34, 99.99])
    rnd = call("round", t, InputRef(t, 0), Literal(BIGINT, 1))
    data, _ = compile_expression(rnd, [t])(_cols(x))
    assert list(np.asarray(data)) == [1230, 10000]


def test_jit_fusion_compiles_once():
    """A filter+project chain compiles into one jitted program."""
    a = Column.from_values(BIGINT, list(range(8)))
    expr = call(
        "multiply", BIGINT,
        call("add", BIGINT, InputRef(BIGINT, 0), Literal(BIGINT, 1)),
        Literal(BIGINT, 2),
    )
    c = compile_expression(expr, [BIGINT])
    jitted = jax.jit(lambda cols: c(cols))
    data, _ = jitted(_cols(a))
    assert list(np.asarray(data)) == [(i + 1) * 2 for i in range(8)]
