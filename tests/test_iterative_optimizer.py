"""Iterative rule engine: plan-shape tests — one fires/does-not-fire pair
per rule — plus memo dedup units and the multi-equality-conjunct
estimate regression (reference: the per-rule *Test classes under
core/trino-main/src/test/.../sql/planner/iterative/rule/ and
TestMemo.java)."""

import os

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.planner.iterative.driver import (IterativeOptimizer,
                                                last_report)
from trino_tpu.planner.iterative.memo import GroupRef, Memo
from trino_tpu.planner.iterative.rule import Context, Trace
from trino_tpu.planner.iterative.rules import (aggregates, decorrelate,
                                               limits, prune, reorder,
                                               simplify)
from trino_tpu.planner.optimizer import estimate_rows
from trino_tpu.planner.plan import (AggCall, Aggregate, CorrelatedJoin,
                                    Filter, Join, Limit, Project, SemiJoin,
                                    Union, Values)
from trino_tpu.sql.ir import Call, InputRef, Literal
from trino_tpu.spi.types import BIGINT, BOOLEAN


@pytest.fixture(autouse=True)
def _iterative_mode():
    saved = os.environ.get("TRINO_TPU_OPTIMIZER")
    os.environ["TRINO_TPU_OPTIMIZER"] = "iterative"
    yield
    if saved is None:
        os.environ.pop("TRINO_TPU_OPTIMIZER", None)
    else:
        os.environ["TRINO_TPU_OPTIMIZER"] = saved


CATALOG = default_catalog(scale_factor=0.01)


def run_rules(root, rules):
    """One-phase fixpoint over the memo; -> (optimized tree, trace)."""
    ctx = Context(catalog=CATALOG, history=None, trace=Trace())
    out = IterativeOptimizer(phases=(("test", tuple(rules)),)).run(root, ctx)
    return out, ctx.trace


def vals(n=10, cols=("k", "v")):
    return Values(tuple(cols), (BIGINT,) * len(cols),
                  tuple(tuple(i * 10 + c for c in range(len(cols)))
                        for i in range(n)))


def gt(ch, lit):
    return Call(BOOLEAN, "gt", (InputRef(BIGINT, ch), Literal(BIGINT, lit)))


def _walk(node):
    yield node
    for c in node.children:
        yield from _walk(c)


# ------------------------------------------------------------- simplify

def test_merge_adjacent_filters_fires():
    v = vals()
    tree = Filter(v.output_names, v.output_types,
                  Filter(v.output_names, v.output_types, v, gt(0, 1)),
                  gt(1, 2))
    out, trace = run_rules(tree, [simplify.MergeAdjacentFilters()])
    assert trace.fired("MergeAdjacentFilters") == 1
    assert isinstance(out, Filter) and isinstance(out.source, Values)


def test_merge_adjacent_filters_does_not_fire_on_single_filter():
    v = vals()
    tree = Filter(v.output_names, v.output_types, v, gt(0, 1))
    out, trace = run_rules(tree, [simplify.MergeAdjacentFilters()])
    assert trace.fired("MergeAdjacentFilters") == 0
    assert out == tree


def test_merge_adjacent_projects_fires_on_trivial_inner():
    v = vals()
    inner = Project(("v", "k"), (BIGINT, BIGINT), v,
                    (InputRef(BIGINT, 1), InputRef(BIGINT, 0)))
    tree = Project(("k",), (BIGINT,), inner, (InputRef(BIGINT, 1),))
    out, trace = run_rules(tree, [simplify.MergeAdjacentProjects()])
    assert trace.fired("MergeAdjacentProjects") == 1
    assert isinstance(out, Project) and isinstance(out.source, Values)
    assert out.expressions == (InputRef(BIGINT, 0),)


def test_merge_adjacent_projects_does_not_fire_on_computed_inner():
    v = vals()
    inner = Project(("s",), (BIGINT,), v,
                    (Call(BIGINT, "add",
                          (InputRef(BIGINT, 0), InputRef(BIGINT, 1))),))
    tree = Project(("a", "b"), (BIGINT, BIGINT), inner,
                   (InputRef(BIGINT, 0), InputRef(BIGINT, 0)))
    _, trace = run_rules(tree, [simplify.MergeAdjacentProjects()])
    assert trace.fired("MergeAdjacentProjects") == 0


def test_inline_projections_fires_when_referenced_once():
    v = vals()
    inner = Project(("s",), (BIGINT,), v,
                    (Call(BIGINT, "add",
                          (InputRef(BIGINT, 0), InputRef(BIGINT, 1))),))
    tree = Project(("s2",), (BIGINT,), inner, (InputRef(BIGINT, 0),))
    out, trace = run_rules(tree, [simplify.InlineProjections()])
    assert trace.fired("InlineProjections") == 1
    assert isinstance(out, Project) and isinstance(out.source, Values)


def test_inline_projections_does_not_fire_when_referenced_twice():
    v = vals()
    inner = Project(("s",), (BIGINT,), v,
                    (Call(BIGINT, "add",
                          (InputRef(BIGINT, 0), InputRef(BIGINT, 1))),))
    tree = Project(("a", "b"), (BIGINT, BIGINT), inner,
                   (InputRef(BIGINT, 0), InputRef(BIGINT, 0)))
    _, trace = run_rules(tree, [simplify.InlineProjections()])
    assert trace.fired("InlineProjections") == 0


def test_remove_redundant_identity_projection_fires():
    v = vals()
    tree = Project(v.output_names, v.output_types, v,
                   (InputRef(BIGINT, 0), InputRef(BIGINT, 1)))
    out, trace = run_rules(tree,
                           [simplify.RemoveRedundantIdentityProjections()])
    assert trace.fired("RemoveRedundantIdentityProjections") == 1
    assert out == v


def test_remove_redundant_identity_projection_keeps_renames():
    v = vals()
    tree = Project(("x", "y"), v.output_types, v,
                   (InputRef(BIGINT, 0), InputRef(BIGINT, 1)))
    out, trace = run_rules(tree,
                           [simplify.RemoveRedundantIdentityProjections()])
    assert trace.fired("RemoveRedundantIdentityProjections") == 0
    assert out == tree


def test_remove_trivial_filters_fires_on_constant_true():
    v = vals()
    tree = Filter(v.output_names, v.output_types, v,
                  Literal(BOOLEAN, True))
    out, trace = run_rules(tree, [simplify.RemoveTrivialFilters()])
    assert trace.fired("RemoveTrivialFilters") == 1
    assert out == v


def test_remove_trivial_filters_false_becomes_empty_values():
    v = vals()
    tree = Filter(v.output_names, v.output_types, v,
                  Literal(BOOLEAN, False))
    out, trace = run_rules(tree, [simplify.RemoveTrivialFilters()])
    assert trace.fired("RemoveTrivialFilters") == 1
    assert isinstance(out, Values) and out.rows == ()
    assert out.output_names == v.output_names


def test_remove_trivial_filters_does_not_fire_on_real_predicate():
    v = vals()
    tree = Filter(v.output_names, v.output_types, v, gt(0, 1))
    _, trace = run_rules(tree, [simplify.RemoveTrivialFilters()])
    assert trace.fired("RemoveTrivialFilters") == 0


def test_evaluate_zero_input_fires_through_row_preserving_chain():
    empty = Values(("k", "v"), (BIGINT, BIGINT), ())
    tree = Filter(empty.output_names, empty.output_types, empty, gt(0, 1))
    out, trace = run_rules(tree, [simplify.EvaluateZeroInput()])
    assert trace.fired("EvaluateZeroInput") == 1
    assert isinstance(out, Values) and out.rows == ()


def test_evaluate_zero_input_empties_inner_join():
    empty = Values(("k",), (BIGINT,), ())
    right = vals(cols=("k2", "w"))
    tree = Join(("k", "k2", "w"), (BIGINT,) * 3, empty, right,
                "INNER", (0,), (0,), None)
    out, trace = run_rules(tree, [simplify.EvaluateZeroInput()])
    assert trace.fired("EvaluateZeroInput") == 1
    assert isinstance(out, Values) and out.rows == ()
    assert out.output_names == ("k", "k2", "w")


def test_evaluate_zero_input_does_not_fire_on_populated_inputs():
    v = vals()
    tree = Filter(v.output_names, v.output_types, v, gt(0, 1))
    _, trace = run_rules(tree, [simplify.EvaluateZeroInput()])
    assert trace.fired("EvaluateZeroInput") == 0


# --------------------------------------------------------------- limits

def test_push_limit_through_project_fires():
    v = vals()
    proj = Project(("v",), (BIGINT,), v, (InputRef(BIGINT, 1),))
    tree = Limit(("v",), (BIGINT,), proj, 5)
    out, trace = run_rules(tree, [limits.PushLimitThroughProject()])
    assert trace.fired("PushLimitThroughProject") == 1
    assert isinstance(out, Project) and isinstance(out.source, Limit)
    assert out.source.count == 5


def test_push_limit_through_project_does_not_fire_elsewhere():
    v = vals()
    tree = Limit(v.output_names, v.output_types, v, 5)
    _, trace = run_rules(tree, [limits.PushLimitThroughProject()])
    assert trace.fired("PushLimitThroughProject") == 0


def _semijoin(source):
    filt = vals(cols=("k2",))
    names = source.output_names + ("mark",)
    types = source.output_types + (BOOLEAN,)
    return SemiJoin(names, types, source, filt, (0,), (0,))


def test_push_limit_through_semijoin_fires_once():
    sj = _semijoin(vals())
    tree = Limit(sj.output_names, sj.output_types, sj, 5)
    out, trace = run_rules(tree, [limits.PushLimitThroughSemiJoin()])
    assert trace.fired("PushLimitThroughSemiJoin") == 1
    assert isinstance(out, SemiJoin)  # outer limit dropped: mark preserves n
    assert isinstance(out.source, Limit) and out.source.count == 5
    # fixpoint: re-running on its own output must not fire again
    _, trace2 = run_rules(out, [limits.PushLimitThroughSemiJoin()])
    assert trace2.fired("PushLimitThroughSemiJoin") == 0


def test_push_limit_through_left_join_fires_and_keeps_outer():
    left, right = vals(), vals(cols=("k2", "w"))
    join = Join(left.output_names + right.output_names, (BIGINT,) * 4,
                left, right, "LEFT", (0,), (0,), None)
    tree = Limit(join.output_names, join.output_types, join, 5)
    out, trace = run_rules(tree, [limits.PushLimitThroughJoin()])
    assert trace.fired("PushLimitThroughJoin") == 1
    assert isinstance(out, Limit)  # outer stays: join may expand rows
    inner = next(n for n in _walk(out) if isinstance(n, Join))
    assert isinstance(inner.left, Limit) and inner.left.count == 5


def test_push_limit_through_inner_join_does_not_fire():
    left, right = vals(), vals(cols=("k2", "w"))
    join = Join(left.output_names + right.output_names, (BIGINT,) * 4,
                left, right, "INNER", (0,), (0,), None)
    tree = Limit(join.output_names, join.output_types, join, 5)
    _, trace = run_rules(tree, [limits.PushLimitThroughJoin()])
    assert trace.fired("PushLimitThroughJoin") == 0


# ---------------------------------------------------------- aggregations

def _agg_over_join(join_type="INNER", fn="sum", arg=1, distinct=False):
    left, right = vals(), vals(cols=("k2", "w"))
    join = Join(left.output_names + right.output_names, (BIGINT,) * 4,
                left, right, join_type, (0,), (0,), None)
    return Aggregate(("k", "a"), (BIGINT, BIGINT), join, (0,),
                     (AggCall(fn, arg, BIGINT, distinct=distinct),))


def test_push_partial_aggregation_through_join_fires():
    tree = _agg_over_join()
    out, trace = run_rules(tree,
                           [aggregates.PushPartialAggregationThroughJoin()])
    assert trace.fired("PushPartialAggregationThroughJoin") == 1
    assert isinstance(out, Aggregate)
    join = next(n for n in _walk(out) if isinstance(n, Join))
    assert isinstance(join.left, Aggregate)  # pre-agg below the join
    assert out.aggregates[0].fn == "sum"     # sum merges as sum


def test_push_partial_aggregation_skips_distinct():
    tree = _agg_over_join(distinct=True)
    _, trace = run_rules(tree,
                         [aggregates.PushPartialAggregationThroughJoin()])
    assert trace.fired("PushPartialAggregationThroughJoin") == 0


def test_push_aggregation_through_outer_join_fires_with_coalesce():
    tree = _agg_over_join(join_type="LEFT", fn="count", arg=3)
    out, trace = run_rules(tree,
                           [aggregates.PushAggregationThroughOuterJoin()])
    assert trace.fired("PushAggregationThroughOuterJoin") == 1
    # all-unmatched groups must read 0, not NULL: a $coalesce lands on top
    assert isinstance(out, Project)
    assert any(isinstance(e, Call) and e.name == "$coalesce"
               for e in out.expressions)
    join = next(n for n in _walk(out) if isinstance(n, Join))
    assert join.join_type == "LEFT" and isinstance(join.right, Aggregate)


def test_push_aggregation_through_outer_join_skips_count_star():
    tree = _agg_over_join(join_type="LEFT", fn="count_star", arg=-1)
    _, trace = run_rules(tree,
                         [aggregates.PushAggregationThroughOuterJoin()])
    assert trace.fired("PushAggregationThroughOuterJoin") == 0


# ----------------------------------------------------------- decorrelate

def test_transform_correlated_in_predicate_fires():
    src, sub = vals(), vals(cols=("k2",))
    names = src.output_names + ("mark",)
    tree = CorrelatedJoin(names, src.output_types + (BOOLEAN,),
                          src, sub, "in", (0,), (0,))
    out, trace = run_rules(tree,
                           [decorrelate.TransformCorrelatedInPredicate()])
    assert trace.fired("TransformCorrelatedInPredicate") == 1
    assert isinstance(out, SemiJoin) and out.null_aware


def test_transform_correlated_scalar_subquery_fires():
    src, sub = vals(), vals(cols=("k2", "agg"))
    names = src.output_names + sub.output_names
    tree = CorrelatedJoin(names, (BIGINT,) * 4, src, sub,
                          "scalar_agg", (0,), (0,))
    out, trace = run_rules(
        tree, [decorrelate.TransformCorrelatedScalarSubquery()])
    assert trace.fired("TransformCorrelatedScalarSubquery") == 1
    assert isinstance(out, Join) and out.join_type == "LEFT"


def test_decorrelate_rules_do_not_fire_without_correlation():
    left, right = vals(), vals(cols=("k2",))
    tree = Join(left.output_names + right.output_names, (BIGINT,) * 3,
                left, right, "INNER", (0,), (0,), None)
    _, trace = run_rules(tree,
                         [decorrelate.TransformCorrelatedInPredicate(),
                          decorrelate.TransformCorrelatedScalarSubquery()])
    assert not trace.fires


# --------------------------------------------------- reorder/distribution

def test_determine_join_distribution_fires_on_right_join():
    left, right = vals(), vals(cols=("k2",))
    tree = Join(left.output_names + right.output_names, (BIGINT,) * 3,
                left, right, "RIGHT", (0,), (0,), None,
                distribution="BROADCAST")
    out, trace = run_rules(tree, [reorder.DetermineJoinDistribution()])
    assert trace.fired("DetermineJoinDistribution") == 1
    # a broadcast RIGHT join would duplicate unmatched build rows per task
    assert out.distribution == "PARTITIONED"


def test_determine_join_distribution_does_not_fire_when_settled():
    left, right = vals(), vals(cols=("k2",))
    tree = Join(left.output_names + right.output_names, (BIGINT,) * 3,
                left, right, "RIGHT", (0,), (0,), None,
                distribution="PARTITIONED")
    _, trace = run_rules(tree, [reorder.DetermineJoinDistribution()])
    assert trace.fired("DetermineJoinDistribution") == 0


def test_reorder_joins_fires_on_three_way_tpch_join():
    from trino_tpu.runner import StandaloneQueryRunner
    runner = StandaloneQueryRunner(CATALOG)
    runner.create_plan(
        "select c_name, o_totalprice, n_name from customer "
        "join orders on c_custkey = o_custkey "
        "join nation on c_nationkey = n_nationkey")
    rep = last_report()
    assert rep is not None and rep.fired("ReorderJoins") >= 1


def test_reorder_joins_does_not_fire_on_single_table():
    from trino_tpu.runner import StandaloneQueryRunner
    runner = StandaloneQueryRunner(CATALOG)
    runner.create_plan(
        "select l_orderkey from lineitem where l_quantity > 10")
    assert last_report().fired("ReorderJoins") == 0


# ----------------------------------------------------------------- prune

def test_prune_join_columns_fires_on_narrow_projection():
    left = vals(cols=("k", "v", "x"))
    right = vals(cols=("k2", "w", "y"))
    join = Join(left.output_names + right.output_names, (BIGINT,) * 6,
                left, right, "INNER", (0,), (0,), None)
    tree = Project(("v",), (BIGINT,), join, (InputRef(BIGINT, 1),))
    out, trace = run_rules(tree, [prune.PruneJoinColumns()])
    assert trace.fired("PruneJoinColumns") == 1
    narrowed = next(n for n in _walk(out) if isinstance(n, Join))
    assert len(narrowed.output_types) < 6  # unused x/w/y are gone
    # layout above the narrowed join is restored
    assert out.output_names == ("v",) and out.output_types == (BIGINT,)


def test_prune_join_columns_does_not_fire_when_all_used():
    left, right = vals(), vals(cols=("k2", "w"))
    join = Join(left.output_names + right.output_names, (BIGINT,) * 4,
                left, right, "INNER", (0,), (0,), None)
    tree = Project(join.output_names, join.output_types, join,
                   tuple(InputRef(BIGINT, i) for i in range(4)))
    _, trace = run_rules(tree, [prune.PruneJoinColumns()])
    assert trace.fired("PruneJoinColumns") == 0


# ------------------------------------------------------------------ memo

def test_memo_interns_identical_subtrees_into_one_group():
    v = vals()
    f1 = Filter(v.output_names, v.output_types, vals(), gt(0, 1))
    f2 = Filter(v.output_names, v.output_types, vals(), gt(0, 1))
    u = Union(v.output_names, v.output_types, (f1, f2))
    memo = Memo(u)
    kids = memo.child_groups(memo.root_group)
    assert len(kids) == 2 and kids[0] == kids[1]
    # distinct subtrees land in distinct groups
    f3 = Filter(v.output_names, v.output_types, vals(), gt(0, 99))
    u2 = Union(v.output_names, v.output_types, (f1, f3))
    memo2 = Memo(u2)
    k2 = memo2.child_groups(memo2.root_group)
    assert k2[0] != k2[1]


def test_memo_extract_round_trips_and_resolves_refs():
    v = vals()
    tree = Filter(v.output_names, v.output_types, v, gt(0, 1))
    memo = Memo(tree)
    assert memo.extract() == tree
    root = memo.node(memo.root_group)
    assert isinstance(root.source, GroupRef)
    assert memo.resolve(root.source) == v


def test_memo_replace_group_rewrites_extraction():
    v = vals()
    tree = Filter(v.output_names, v.output_types, v, gt(0, 1))
    memo = Memo(tree)
    memo.replace_group(memo.root_group, v)
    assert memo.extract() == v


# ----------------------------------------------- estimate_rows regression

def test_extra_equality_conjuncts_tighten_unknown_ndv_estimate():
    """Two-key equi-join over unknown-NDV inputs must estimate BELOW the
    one-key join (the old code multiplied by an implicit 1.0)."""
    left, right = vals(), vals(cols=("k2", "w"))
    one = Join(left.output_names + right.output_names, (BIGINT,) * 4,
               left, right, "INNER", (0,), (0,), None)
    two = Join(left.output_names + right.output_names, (BIGINT,) * 4,
               left, right, "INNER", (0, 1), (0, 1), None)
    est1 = estimate_rows(one, CATALOG)
    est2 = estimate_rows(two, CATALOG)
    assert est2 < est1
    assert est2 == pytest.approx(est1 * 0.9)


def test_single_key_join_estimate_unchanged_by_fix():
    left, right = vals(), vals(cols=("k2", "w"))
    one = Join(left.output_names + right.output_names, (BIGINT,) * 4,
               left, right, "INNER", (0,), (0,), None)
    # unknown NDV on both sides: textbook fallback is max(|L|, |R|)
    assert estimate_rows(one, CATALOG) == 10.0
