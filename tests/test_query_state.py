"""Coordinator crash recovery: the write-ahead query-state log
(execution/query_state.py), in-process resume seeding, dispatcher boot
recovery, and the subprocess kill -9 drill (reference:
EventDrivenFaultTolerantQueryScheduler + the spooling exchange contract —
committed attempts are never re-executed)."""

import os

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.execution import query_state, spool_gc
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.runner import Session
from trino_tpu.testing.oracle import SqliteOracle, assert_same_rows

SQL = ("select l_returnflag, count(*), sum(l_quantity) from lineitem "
       "group by l_returnflag order by l_returnflag")


@pytest.fixture()
def state_env(tmp_path, monkeypatch):
    state = tmp_path / "query-state"
    spool = tmp_path / "spool"
    spool.mkdir()
    monkeypatch.setenv("TRINO_TPU_QUERY_STATE", "1")
    monkeypatch.setenv("TRINO_TPU_QUERY_STATE_DIR", str(state))
    monkeypatch.setenv("TRINO_TPU_SPOOL_DIR", str(spool))
    return str(state), str(spool)


# ------------------------------------------------------------- WAL unit
def test_wal_lifecycle_and_load(tmp_path):
    wal = query_state.QueryStateLog("q1", dir=str(tmp_path))
    wal.begin("select 1", {"plan": 1}, "/spool/root", Session(),
              task_counts={2: 2, 1: 2}, consumer_tasks={2: 2})
    wal.attempt_start(2, 0, 0, "STANDARD")
    wal.attempt_start(2, 1, 0, "STANDARD")
    wal.attempt_committed(2, 1, 0, "/spool/root/f2_t1/attempt-0",
                          "STANDARD")
    wal.close()

    pq = query_state.load(wal.path)
    assert pq.query_id == "q1"
    assert pq.sql == "select 1"
    assert pq.resumable
    assert pq.committed == {(2, 1): {
        "attempt": 0, "dir": "/spool/root/f2_t1/attempt-0",
        "kind": "STANDARD"}}
    assert pq.attempt_counts == {(2, 0): 1, (2, 1): 1}
    assert pq.fingerprint and pq.plan_b64
    assert query_state.decode_plan(pq.plan_b64) == {"plan": 1}
    assert pq.shape_matches({2: 2, 1: 2}, {2: 2})
    assert not pq.shape_matches({2: 4, 1: 2}, {2: 2})

    # terminal state flips resumable off; prune_ended removes the file
    wal2 = query_state.QueryStateLog("q1", dir=str(tmp_path))
    wal2.end("FINISHED")
    wal2.close()
    assert query_state.load(wal.path).ended == "FINISHED"
    assert not query_state.load(wal.path).resumable
    assert query_state.prune_ended(str(tmp_path)) == 1
    assert not os.path.exists(wal.path)


def test_wal_torn_tail_and_discard(tmp_path):
    wal = query_state.QueryStateLog("q2", dir=str(tmp_path))
    wal.begin("select 2", {"plan": 2}, "/s", Session())
    wal.attempt_committed(0, 0, 0, "/s/f0_t0/attempt-0", "STANDARD")
    wal.attempt_committed(1, 0, 0, "/s/f1_t0/attempt-0", "STANDARD")
    wal.attempt_discarded(1, 0, "spool corruption")
    wal.close()
    # torn tail from a kill -9 mid-append: reader must skip it
    with open(wal.path, "a", encoding="utf-8") as f:
        f.write('{"event": "attempt_com')
    pq = query_state.load(wal.path)
    assert pq.resumable
    # the discarded attempt is gone from the committed map
    assert set(pq.committed) == {(0, 0)}
    assert query_state.pending(str(tmp_path))[0].query_id == "q2"
    query_state.discard("q2", str(tmp_path))
    assert query_state.pending(str(tmp_path)) == []


def test_restore_session_replays_only_known_fields(tmp_path):
    wal = query_state.QueryStateLog("q3", dir=str(tmp_path))
    wal.begin("select 3", {"plan": 3}, "/s",
              Session(node_count=7, retry_policy="TASK",
                      task_retry_attempts=9))
    wal.close()
    pq = query_state.load(wal.path)
    pq.session_fields["not_a_field"] = "ignored"
    sess = query_state.restore_session(pq)
    assert sess.node_count == 7
    assert sess.retry_policy == "TASK"
    assert sess.task_retry_attempts == 9


# -------------------------------------------- in-process crash + resume
def _crashing_runner(state_env, inj=None, monkeypatch=None):
    session = Session(node_count=2, retry_policy="TASK",
                      failure_injector=inj, fte_speculative=False,
                      task_retry_attempts=1)
    return DistributedQueryRunner(default_catalog(scale_factor=0.01),
                                  worker_count=2, session=session)


def test_resume_skips_committed_attempts(state_env):
    """Simulated coordinator death mid-FTE-query: fail the query after
    some stages committed while suppressing the WAL's terminal record and
    the spool release (exactly the state a kill -9 leaves behind), then
    resume on a fresh runner — committed attempts must not re-execute."""
    from trino_tpu.caching import result_cache
    from trino_tpu.execution.failure_injector import (TASK_FAILURE,
                                                      FailureInjector)

    state_dir, _spool = state_env
    inj = FailureInjector()
    r1 = _crashing_runner(state_env, inj)
    fragments = r1.create_subplan(SQL).all_fragments()
    root_fid = [f.id for f in fragments if f.source_fragments]
    # kill the FIRST non-leaf stage every attempt: leaves commit, the
    # query dies with retries exhausted — like a coordinator crash, the
    # WAL keeps its committed map (end/release suppressed below)
    inj.inject(TASK_FAILURE, fragment_id=root_fid[-1], task_index=None,
               attempt=None, times=10)
    # a private MonkeyPatch so undo() below does NOT drop state_env's env
    crash = pytest.MonkeyPatch()
    crash.setattr(query_state.QueryStateLog, "end",
                  lambda self, *a, **kw: None)
    crash.setattr(spool_gc, "release", lambda root: 0)
    try:
        with result_cache.disabled():
            with pytest.raises(Exception):
                r1.execute(SQL)
    finally:
        crash.undo()

    pending = query_state.pending(state_dir)
    assert len(pending) == 1
    pq = pending[0]
    assert pq.resumable and len(pq.committed) >= 1
    starts_before = dict(pq.attempt_counts)
    committed = set(pq.committed)

    r2 = DistributedQueryRunner(
        default_catalog(scale_factor=0.01), worker_count=2,
        session=Session(node_count=2, retry_policy="TASK"))
    result = r2.resume_fte_query(pq)

    oracle = SqliteOracle()
    conn = default_catalog(scale_factor=0.01).connector("tpch")
    cols = conn.get_table_schema("lineitem").column_names()
    batches = []
    for s in conn.get_splits("lineitem", 2, 1):
        src = conn.create_page_source(s, cols)
        while not src.is_finished():
            b = src.get_next_batch()
            if b is not None:
                batches.append(b)
    oracle.load_table("lineitem", batches)
    assert_same_rows(result.rows(), oracle.query(SQL), ordered=False)

    final = query_state.load(pq.path)
    assert final.ended == "FINISHED"
    for key in committed:
        assert final.attempt_counts.get(key, 0) == \
            starts_before.get(key, 0), \
            f"committed attempt {key} was re-executed"
    # the resumed run did execute what was NOT committed
    assert any(final.attempt_counts.get(k, 0) > starts_before.get(k, 0)
               for k in final.attempt_counts)


def test_dispatcher_boot_recovery(state_env):
    """QueryDispatcher must rehydrate in-flight WAL queries at boot under
    their original ids so a reattaching client's polling resolves."""
    from trino_tpu.caching import result_cache
    from trino_tpu.execution.failure_injector import (TASK_FAILURE,
                                                      FailureInjector)
    from trino_tpu.server.protocol import QueryDispatcher

    state_dir, _spool = state_env
    inj = FailureInjector()
    r1 = _crashing_runner(state_env, inj)
    fragments = r1.create_subplan(SQL).all_fragments()
    nonleaf = [f.id for f in fragments if f.source_fragments]
    inj.inject(TASK_FAILURE, fragment_id=nonleaf[-1], task_index=None,
               attempt=None, times=10)
    crash = pytest.MonkeyPatch()
    crash.setattr(query_state.QueryStateLog, "end",
                  lambda self, *a, **kw: None)
    crash.setattr(spool_gc, "release", lambda root: 0)
    try:
        with result_cache.disabled():
            with pytest.raises(Exception):
                r1.execute(SQL, query_id="deadbeef00000001")
    finally:
        crash.undo()

    r2 = DistributedQueryRunner(
        default_catalog(scale_factor=0.01), worker_count=2,
        session=Session(node_count=2, retry_policy="TASK"))
    disp = QueryDispatcher(r2)
    assert disp.recovered_query_ids == ["deadbeef00000001"]
    q = disp.get("deadbeef00000001")
    assert q is not None and q.recovered
    assert q.done.wait(120)
    assert q.state == "FINISHED", q.error
    assert len(q.rows) == 3  # A / N / R
    # terminal WALs were pruned at boot; this query's WAL ends FINISHED
    final = query_state.load(os.path.join(state_dir,
                                          "deadbeef00000001.wal"))
    assert final.ended == "FINISHED"


# ------------------------------------------------- subprocess kill -9
def test_coordinator_kill9_restart_resume(tmp_path):
    """The tentpole acceptance: SIGKILL the coordinator process mid-FTE-
    query, restart it, and the query finishes oracle-correct under its
    original id with ZERO re-execution of committed attempts and the
    spool root reclaimed."""
    from trino_tpu.testing.chaos import _DRILL_SQL, run_coordinator_kill_drill

    rec = run_coordinator_kill_drill(workdir=str(tmp_path))
    assert rec["state"] == "FINISHED", rec.get("error")
    assert rec["committed_at_kill"] >= 1
    assert rec["committed_reexecuted"] == {}, \
        "committed attempts were re-executed after the restart"
    assert rec["resumed_attempt_starts"], \
        "the resumed coordinator did no work at all"
    assert rec["wal_ended"] == "FINISHED"
    assert rec["spool_reclaimed"]
    assert rec["pass"]

    # oracle-correct rows through the reattached client surface
    oracle = SqliteOracle()
    conn = default_catalog(scale_factor=0.01).connector("tpch")
    cols = conn.get_table_schema("lineitem").column_names()
    batches = []
    for s in conn.get_splits("lineitem", 2, 1):
        src = conn.create_page_source(s, cols)
        while not src.is_finished():
            b = src.get_next_batch()
            if b is not None:
                batches.append(b)
    oracle.load_table("lineitem", batches)
    expected = oracle.query(_DRILL_SQL)
    got = [tuple(row) for row in rec["rows"]]

    def norm(rows):
        out = []
        for row in rows:
            cells = []
            for v in row:
                try:  # "368805.00" (server JSON) vs 368805.0 (sqlite)
                    cells.append(round(float(v), 2))
                except (TypeError, ValueError):
                    cells.append(str(v))
            out.append(tuple(cells))
        return sorted(out, key=str)

    assert norm(got) == norm(expected)


# ------------------------------------------- subprocess HA lease takeover
def test_ha_peer_takeover_kill9(tmp_path):
    """The HA tentpole acceptance: coordinator A (one of a two-member
    fleet) commits >=1 fsync'd attempt and dies by SIGKILL; peer B claims
    A's expired lease (atomic rename), takes custody of A's WAL directory,
    and finishes the query under its ORIGINAL id — zero re-execution of
    committed attempts, polled the whole time through B's ordinary
    statement surface."""
    from trino_tpu.testing.chaos import run_ha_takeover_drill

    rec = run_ha_takeover_drill(workdir=str(tmp_path))
    assert rec["state"] == "FINISHED", rec.get("error")
    assert rec["committed_at_kill"] >= 1
    assert rec["committed_reexecuted"] == {}, \
        "committed attempts were re-executed after the takeover"
    assert rec["claimed_dirs"], "B never took custody of A's WAL dir"
    assert rec["wal_ended"] == "FINISHED"
    assert rec["lease_a_gone"], "A's lease must leave the directory"
    assert rec["pass"]
    # the adopted query's rows are the drill aggregation (4 flag/status
    # groups at sf=0.01)
    assert len(rec["rows"]) == 4
