"""Test harness config: run JAX on a virtual 8-device CPU mesh.

Mirrors the reference's DistributedQueryRunner trick (SURVEY §4): multi-node
paths are exercised in one process.

Note: this environment preloads jax via sitecustomize (axon TPU tunnel), so
plain JAX_PLATFORMS env vars are read too late — use jax.config instead.
XLA_FLAGS still works because the CPU client is only created on first use.
"""

import os
import tempfile

# History-based optimization makes planning stateful across *processes* by
# design (the journal is durable): a polluted host journal would make every
# plan-shape assertion depend on what ran before.  The suite gets a fresh
# journal per run and pins HBO off; test_hbo opts back in per-fixture.
os.environ["TRINO_TPU_JOURNAL_DIR"] = tempfile.mkdtemp(
    prefix="trino-tpu-test-journal-")
os.environ["TRINO_TPU_HBO"] = "0"

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

assert len(jax.devices()) == 8, "expected 8 virtual CPU devices for tests"


def pytest_configure(config):
    # tier-1 runs -m 'not slow' inside an 870s budget; the >=1M-NDV hash
    # bake-off legs opt out via this marker
    config.addinivalue_line(
        "markers", "slow: long-running bench-scale tests, excluded by tier-1")
