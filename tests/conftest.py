"""Test harness config: run JAX on a virtual 8-device CPU mesh.

Mirrors the reference's DistributedQueryRunner trick (SURVEY §4): multi-node
paths are exercised in one process.  Env vars must be set before jax imports.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")
