"""Test harness config: run JAX on a virtual 8-device CPU mesh.

Mirrors the reference's DistributedQueryRunner trick (SURVEY §4): multi-node
paths are exercised in one process.

Note: this environment preloads jax via sitecustomize (axon TPU tunnel), so
plain JAX_PLATFORMS env vars are read too late — use jax.config instead.
XLA_FLAGS still works because the CPU client is only created on first use.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

assert len(jax.devices()) == 8, "expected 8 virtual CPU devices for tests"


def pytest_configure(config):
    # tier-1 runs -m 'not slow' inside an 870s budget; the >=1M-NDV hash
    # bake-off legs opt out via this marker
    config.addinivalue_line(
        "markers", "slow: long-running bench-scale tests, excluded by tier-1")
