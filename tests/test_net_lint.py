"""Tier-1 wiring for tools/lint_net_timeout.py: no network call in
trino_tpu/execution/ may omit an explicit timeout — an unbounded wait on a
wedged peer is the silent-stall class the resilience layer (Backoff,
WorkerFailureDetector) exists to eliminate."""

import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(ROOT, "tools", "lint_net_timeout.py")


def _mod():
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import lint_net_timeout as L
    finally:
        sys.path.pop(0)
    return L


def test_no_unbounded_network_calls_in_execution():
    proc = subprocess.run([sys.executable, LINT], capture_output=True,
                          text=True, timeout=60)
    assert proc.returncode == 0, \
        f"timeout-less network calls crept into execution/:\n{proc.stderr}"


def test_lint_catches_planted_violation(tmp_path):
    """The lint actually fires (guards against pattern rot)."""
    L = _mod()
    bad = tmp_path / "bad.py"
    bad.write_text(
        "r = urllib.request.urlopen(req)\n"
        "c = socket.create_connection((host, port))\n"
        "ok = urllib.request.urlopen(req, timeout=5.0)\n"
        "exempt = urllib.request.urlopen(req)  # net-ok: test pragma\n")
    findings = L.lint_file(str(bad))
    assert len(findings) == 2
    labels = {f[2] for f in findings}
    assert any("urlopen" in s for s in labels)
    assert any("create_connection" in s for s in labels)


def test_lint_handles_multiline_calls(tmp_path):
    """timeout on a continuation line of the SAME call counts; a
    timeout-less multi-line call is still flagged."""
    L = _mod()
    f = tmp_path / "multi.py"
    f.write_text(
        "good = urllib.request.urlopen(\n"
        "    req,\n"
        "    timeout=30.0)\n"
        "bad = urllib.request.urlopen(\n"
        "    req)\n")
    findings = L.lint_file(str(f))
    assert len(findings) == 1
    assert findings[0][1] == 4
