"""Set operations (UNION/INTERSECT/EXCEPT) + RIGHT/FULL joins vs the
sqlite oracle (the reference covers these in
testing/trino-testing/.../AbstractTestQueries and TestJoinQueries)."""

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.runner import StandaloneQueryRunner
from trino_tpu.testing.oracle import SqliteOracle, assert_same_rows

TABLES = ["nation", "region", "supplier", "customer", "orders", "lineitem"]


@pytest.fixture(scope="module")
def harness():
    catalog = default_catalog(scale_factor=0.01)
    runner = StandaloneQueryRunner(catalog)
    dist = DistributedQueryRunner(catalog, worker_count=3)
    oracle = SqliteOracle()
    conn = catalog.connector("tpch")
    for t in TABLES:
        schema = conn.get_table_schema(t)
        cols = schema.column_names()
        batches = []
        for s in conn.get_splits(t, 2, 1):
            src = conn.create_page_source(s, cols)
            while not src.is_finished():
                b = src.get_next_batch()
                if b is not None:
                    batches.append(b)
        oracle.load_table(t, batches)
    return runner, dist, oracle


SETOP_QUERIES = [
    "select n_regionkey from nation union select r_regionkey from region",
    "select n_regionkey from nation union all select r_regionkey from region",
    "select n_regionkey from nation intersect select r_regionkey from region",
    "select n_regionkey from nation except select r_regionkey from region where r_regionkey < 3",
    # mixed types: bigint vs literal double promotes
    "select n_regionkey from nation union select 1.5",
    # strings through dictionary unification
    "select n_name from nation where n_regionkey = 0 union select r_name from region",
    "select n_name from nation intersect select n_name from nation where n_regionkey > 2",
    # set op under aggregation
    "select count(*) from (select n_regionkey from nation union "
    "select r_regionkey from region)",
    # CTE with set-op body and column aliases
    "with keys(k) as (select n_regionkey from nation union "
    "select r_regionkey + 2 from region) select k from keys where k > 1",
    # NULLs compare equal in set semantics
    "select case when n_regionkey > 2 then null else n_regionkey end from nation "
    "union select null",
]

OUTER_JOIN_QUERIES = [
    "select n_name, r_name from region right join nation on n_regionkey = r_regionkey",
    "select n_name, r_name from region right join nation "
    "on n_regionkey = r_regionkey and r_regionkey < 2",
    "select n_nationkey, r_regionkey from nation full join region "
    "on n_nationkey = r_regionkey",
    "select n_nationkey, r_regionkey from nation full outer join region "
    "on n_nationkey = r_regionkey and n_nationkey <> 1",
    # full join where both sides have unmatched rows
    "select a.n_nationkey, b.n_nationkey from "
    "(select n_nationkey from nation where n_nationkey < 10) a full join "
    "(select n_nationkey from nation where n_nationkey >= 5) b "
    "on a.n_nationkey = b.n_nationkey",
    # right join with aggregation above
    "select r_name, count(n_nationkey) from nation right join region "
    "on n_regionkey = r_regionkey and n_nationkey < 3 group by r_name",
    # larger tables: customers without orders kept by FULL
    "select count(*) from orders full join customer on o_custkey = c_custkey",
    "select count(*) from orders right join customer on o_custkey = c_custkey",
]


@pytest.mark.parametrize("sql", SETOP_QUERIES)
def test_setops_standalone(harness, sql):
    runner, _, oracle = harness
    assert_same_rows(runner.execute(sql).rows(), oracle.query(sql))


@pytest.mark.parametrize("sql", SETOP_QUERIES)
def test_setops_distributed(harness, sql):
    _, dist, oracle = harness
    assert_same_rows(dist.execute(sql).rows(), oracle.query(sql))


@pytest.mark.parametrize("sql", OUTER_JOIN_QUERIES)
def test_outer_joins_standalone(harness, sql):
    runner, _, oracle = harness
    assert_same_rows(runner.execute(sql).rows(), oracle.query(sql))


@pytest.mark.parametrize("sql", OUTER_JOIN_QUERIES)
def test_outer_joins_distributed(harness, sql):
    _, dist, oracle = harness
    assert_same_rows(dist.execute(sql).rows(), oracle.query(sql))


def test_setop_precedence(harness):
    """INTERSECT binds tighter than UNION (SQL standard; sqlite flattens
    left-to-right, so the oracle gets the grouping via a subquery)."""
    runner, _, oracle = harness
    sql = ("select n_regionkey from nation union select r_regionkey from "
           "region intersect select r_regionkey from region where r_regionkey < 2")
    expected = oracle.query(
        "select n_regionkey from nation union select * from (select "
        "r_regionkey from region intersect select r_regionkey from region "
        "where r_regionkey < 2)")
    assert_same_rows(runner.execute(sql).rows(), expected)


def test_parenthesized_query_terms(harness):
    """Each side's ORDER BY/LIMIT applies inside its parens (sqlite cannot
    parse this form, so the oracle gets subquery-wrapped equivalents)."""
    runner, dist, oracle = harness
    sql = ("(select n_nationkey from nation order by n_nationkey limit 3) "
           "union all "
           "(select n_nationkey from nation order by n_nationkey desc limit 2)")
    expected = oracle.query(
        "select * from (select n_nationkey from nation order by n_nationkey "
        "limit 3) union all select * from (select n_nationkey from nation "
        "order by n_nationkey desc limit 2)")
    assert_same_rows(runner.execute(sql).rows(), expected)
    assert_same_rows(dist.execute(sql).rows(), expected)


def test_distributed_union_values_not_duplicated(harness):
    """A Values (FROM-less) union input must not be replayed once per task
    of a multi-task union fragment."""
    _, dist, _ = harness
    rows = dist.execute(
        "select n_regionkey from nation union all select 99").rows()
    assert rows.count((99,)) == 1
    assert len(rows) == 26


def test_fromless_select(harness):
    runner, _, _ = harness
    assert runner.execute("select 1 as x, 'a' as s").rows() == [(1, "a")]


def test_union_column_count_mismatch(harness):
    runner, _, _ = harness
    with pytest.raises(Exception, match="column count"):
        runner.execute("select 1, 2 union select 3")


def _multiset_counts(rows):
    from collections import Counter

    return Counter(tuple(r) for r in rows)


def test_intersect_all(harness):
    """INTERSECT ALL keeps min(left, right) multiplicities (sqlite lacks the
    ALL variants, so the expectation is computed from the two inputs)."""
    runner, dist, oracle = harness
    left = "select n_regionkey from nation"  # 5 copies of each region key
    right = ("select r_regionkey from region union all "
             "select r_regionkey from region where r_regionkey < 2")
    sql = f"{left} intersect all ({right})"
    lc = _multiset_counts(oracle.query(left))
    rc = _multiset_counts(oracle.query(
        "select r_regionkey from region union all "
        "select r_regionkey from region where r_regionkey < 2"))
    expected = []
    for k in lc.keys() & rc.keys():
        expected.extend([k] * min(lc[k], rc[k]))
    assert_same_rows(runner.execute(sql).rows(), expected)
    assert_same_rows(dist.execute(sql).rows(), expected)


def test_except_all(harness):
    runner, dist, oracle = harness
    left = "select n_regionkey from nation"
    right = "select r_regionkey from region where r_regionkey < 3"
    sql = f"{left} except all {right}"
    lc = _multiset_counts(oracle.query(left))
    rc = _multiset_counts(oracle.query(right))
    expected = []
    for k, n in lc.items():
        expected.extend([k] * max(n - rc.get(k, 0), 0))
    assert_same_rows(runner.execute(sql).rows(), expected)
    assert_same_rows(dist.execute(sql).rows(), expected)
