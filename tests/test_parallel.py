"""Static/SPMD aggregation kernels + driver entry points on the 8-device
CPU mesh (the DistributedQueryRunner-style in-process multi-node check)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from trino_tpu.parallel.static_agg import AggSpec, static_grouped_agg
from trino_tpu.parallel.distributed import (
    broadcast_gather,
    distributed_grouped_agg,
    make_mesh,
)


def test_static_agg_matches_numpy():
    rng = np.random.RandomState(1)
    n = 512
    keys = jnp.asarray(rng.randint(0, 7, n).astype(np.int64))
    x = jnp.asarray(rng.rand(n))
    mask = jnp.asarray(rng.rand(n) < 0.8)
    r = static_grouped_agg(
        [keys], [None],
        [(AggSpec("sum", jnp.float64), x, None),
         (AggSpec("min", jnp.float64), x, None),
         (AggSpec("count_star", jnp.int64), None, None)],
        cap=16, row_mask=mask)
    kk = np.asarray(keys)[np.asarray(mask)]
    xx = np.asarray(x)[np.asarray(mask)]
    used = np.asarray(r.slot_used)
    assert int(r.num_groups) == len(np.unique(kk))
    got = {int(k): (float(s), float(m), int(c)) for k, s, m, c, u in zip(
        np.asarray(r.keys[0]), np.asarray(r.values[0]),
        np.asarray(r.values[1]), np.asarray(r.values[2]), used) if u}
    for k in np.unique(kk):
        sel = xx[kk == k]
        s, m, c = got[int(k)]
        assert np.isclose(s, sel.sum()) and np.isclose(m, sel.min())
        assert c == len(sel)


def test_static_agg_overflow_signal():
    keys = jnp.arange(32, dtype=jnp.int64)
    x = jnp.ones(32)
    r = static_grouped_agg([keys], [None],
                           [(AggSpec("sum", jnp.float64), x, None)], cap=8)
    assert int(r.num_groups) == 32  # exceeds cap -> caller re-runs bigger


def test_distributed_agg_8dev():
    mesh = make_mesh(8)
    rng = np.random.RandomState(2)
    n = 256
    keys = jnp.asarray(rng.randint(0, 6, n).astype(np.int64))
    x = jnp.asarray(np.arange(n, dtype=np.float64))
    mask = jnp.ones(n, bool)
    fn = distributed_grouped_agg(
        mesh, "x", [jnp.int64],
        [AggSpec("sum", jnp.float64), AggSpec("count_star", jnp.int64)], cap=8)
    (okeys,), (osums, ocnt), used, overflow = fn(keys, x, x, mask)
    assert int(np.asarray(overflow).max()) <= 8
    got = {}
    for k, s, c, u in zip(*map(np.asarray, (okeys, osums, ocnt, used))):
        if u:
            got[int(k)] = (float(s), int(c))
    kk, xx = np.asarray(keys), np.asarray(x)
    for k in np.unique(kk):
        sel = xx[kk == k]
        assert got[int(k)] == (float(sel.sum()), len(sel))
    assert sum(c for _, c in got.values()) == n


def test_broadcast_gather():
    mesh = make_mesh(8)
    x = jnp.arange(64, dtype=jnp.int64)
    out = broadcast_gather(mesh, "x")(x)
    assert np.asarray(out).shape == (64,)
    assert (np.asarray(out) == np.arange(64)).all()


def test_graft_entry_singlechip():
    import __graft_entry__ as g

    fn, args = g.entry()
    keys, values, used = jax.jit(fn)(*args)
    jax.block_until_ready(values)
    counts = np.asarray(values[-1])
    assert counts[np.asarray(used)].sum() > 0


def test_graft_dryrun_multichip():
    import __graft_entry__ as g

    g.dryrun_multichip(8)


def test_intra_task_parallel_drivers():
    """task_concurrency forks multi-split scans into concurrent source
    driver chains merged through the local gather exchange
    (LocalExchange.java:67 role) — results identical, >1 source chain."""
    from trino_tpu.connectors.catalog import default_catalog
    from trino_tpu.exec.operators import UnionSinkOperator
    from trino_tpu.runner import Session, StandaloneQueryRunner

    catalog = default_catalog(scale_factor=0.01)
    par = StandaloneQueryRunner(
        catalog, session=Session(task_concurrency=4, splits_per_node=8))
    seq = StandaloneQueryRunner(catalog)
    sqls = [
        "select l_returnflag, count(*), sum(l_quantity) from lineitem "
        "group by l_returnflag order by 1",
        "select count(*) from lineitem, orders where l_orderkey = o_orderkey "
        "and o_orderdate < date '1995-01-01'",
        "select max(l_extendedprice) from lineitem where l_discount > 0.05",
    ]
    for sql in sqls:
        assert par.execute(sql).rows() == seq.execute(sql).rows()
    # the plan really forked: count parallel sink chains
    from trino_tpu.exec.local_exchange import LocalExchangeSinkOperator
    from trino_tpu.exec.local_planner import LocalPlanner

    lp = LocalPlanner(catalog, splits_per_node=8, task_concurrency=4)
    plan = lp.plan(par.create_plan(sqls[0]))
    sinks = sum(1 for p in plan.pipelines
                if isinstance(p[-1], LocalExchangeSinkOperator))
    assert sinks >= 2, f"expected parallel source chains, got {sinks}"


def test_parallel_partitioned_aggregation_drivers():
    """Grouped aggregation behind a multi-split scan runs task_concurrency
    PARALLEL aggregation drivers fed by a HASH local exchange
    (AddLocalExchanges.java:111 + LocalExchange.java:67) — not just
    parallel sources; results identical to sequential."""
    from trino_tpu.connectors.catalog import default_catalog
    from trino_tpu.exec.local_exchange import (
        HASH,
        LocalExchangeSourceOperator,
    )
    from trino_tpu.exec.local_planner import LocalPlanner
    from trino_tpu.exec.operators import HashAggregationOperator
    from trino_tpu.runner import Session, StandaloneQueryRunner

    catalog = default_catalog(scale_factor=0.01)
    sql = ("select o_custkey, count(*), sum(o_totalprice) from orders "
           "group by o_custkey order by 2 desc, 1 limit 7")
    lp = LocalPlanner(catalog, splits_per_node=8, task_concurrency=4)
    runner = StandaloneQueryRunner(
        catalog, session=Session(task_concurrency=4, splits_per_node=8))
    plan = lp.plan(runner.create_plan(sql))
    agg_drivers = [
        p for p in plan.pipelines
        if isinstance(p[0], LocalExchangeSourceOperator)
        and any(isinstance(op, HashAggregationOperator) for op in p)
    ]
    assert len(agg_drivers) >= 2, "expected parallel aggregation drivers"
    assert agg_drivers[0][0].exchange.mode == HASH
    seq = StandaloneQueryRunner(catalog)
    assert runner.execute(sql).rows() == seq.execute(sql).rows()


def test_parallel_join_probe_drivers():
    """INNER-join probes clone into every parallel chain (each probing the
    shared build bridge) and a downstream grouped agg still partitions."""
    from trino_tpu.connectors.catalog import default_catalog
    from trino_tpu.runner import Session, StandaloneQueryRunner

    catalog = default_catalog(scale_factor=0.01)
    par = StandaloneQueryRunner(
        catalog, session=Session(task_concurrency=4, splits_per_node=8))
    seq = StandaloneQueryRunner(catalog)
    sql = ("select o_orderpriority, count(*) from lineitem, orders "
           "where l_orderkey = o_orderkey and l_shipdate > date '1996-01-01' "
           "group by o_orderpriority order by 1")
    assert par.execute(sql).rows() == seq.execute(sql).rows()


def test_local_exchange_backpressure_bounded():
    """A producer flooding a bounded local exchange parks instead of
    buffering unboundedly (the isBlocked() contract)."""
    import numpy as np

    from trino_tpu.exec.local_exchange import (
        GATHER,
        LocalExchange,
        LocalExchangeSinkOperator,
    )
    from trino_tpu.spi.batch import Column, ColumnBatch
    from trino_tpu.spi.types import BIGINT

    ex = LocalExchange(1, 1, GATHER, buffer_batches=2)
    sink = LocalExchangeSinkOperator(ex, 0, ["x"])
    b = ColumnBatch(["x"], [Column(BIGINT, np.arange(4))])
    assert sink.needs_input()
    sink.add_input(b)
    sink.add_input(b)
    assert not sink.needs_input()  # full: producer parks
    assert ex.poll(0) is not None
    assert sink.needs_input()  # drained below the bound: resumes


def test_intra_task_parallel_distributed():
    from trino_tpu.connectors.catalog import default_catalog
    from trino_tpu.execution.distributed_runner import DistributedQueryRunner
    from trino_tpu.runner import Session, StandaloneQueryRunner

    catalog = default_catalog(scale_factor=0.01)
    dist = DistributedQueryRunner(
        catalog, worker_count=2,
        session=Session(node_count=2, task_concurrency=2, splits_per_node=4))
    seq = StandaloneQueryRunner(catalog)
    sql = ("select o_orderpriority, count(*) from orders "
           "group by o_orderpriority order by 1")
    assert dist.execute(sql).rows() == seq.execute(sql).rows()
