"""bench.py --qps: the two-tenant sustained-load harness + OOM drill.

The fast leg runs a seconds-scale slice of the harness end to end (real
runners, real admission plane) and asserts the RESULT SHAPE plus the OOM
drill's hard guarantees; the statistical fairness acceptance (3:1 +-25%)
needs a longer window and runs as the slow ladder."""

import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import bench  # noqa: E402


@pytest.fixture(scope="module")
def tiny_catalog():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    return bench._stage_memory_tables(0.01)


def test_qps_smoke_structure_and_drill(tiny_catalog):
    sustained = bench.run_qps_sustained(2.0, tiny_catalog,
                                        clients_per_group=2)
    for g in ("heavy", "light"):
        assert sustained[g]["completed"] > 0
        assert sustained[g]["failed"] == 0
        assert sustained[g]["latency_p99_ms"] >= sustained[g]["latency_p50_ms"]
    assert sustained["fairness_ratio"] > 0
    assert sustained["queue_depth_max"] >= 0

    drill = bench.run_qps_oom_drill(tiny_catalog)
    assert drill["victim_error"] == "CLUSTER_OUT_OF_MEMORY"
    assert not drill["victim_hung"]
    assert drill["oom_kills"] >= 1
    assert drill["post_drill_query_ok"]


@pytest.mark.slow
def test_qps_full_ladder_fairness(tiny_catalog):
    """The acceptance leg: saturating 3:1 run converges to the configured
    share within +-25% with bounded light-group queue wait."""
    sustained = bench.run_qps_sustained(20.0, tiny_catalog,
                                        clients_per_group=5)
    assert 3.0 * 0.75 <= sustained["fairness_ratio"] <= 3.0 * 1.25, sustained
    assert sustained["light"]["completed"] > 0
    # light p99 queue wait bounded: under weighted fair the light tenant
    # waits at most a few service times, never unboundedly
    assert sustained["light"]["queue_wait_p99_ms"] < 20_000
