"""Transactions, SQL routines (CREATE FUNCTION), table functions, scaled
writers (reference: transaction/InMemoryTransactionManager.java:72,
sql/routine/SqlRoutineAnalyzer, operator/table/SequenceFunction.java,
ScaledWriterScheduler / SCALED_WRITER partitionings)."""

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.runner import Session, StandaloneQueryRunner


@pytest.fixture()
def runner():
    return StandaloneQueryRunner(default_catalog(scale_factor=0.01),
                                 session=Session(default_catalog="memory"))


# ------------------------------------------------------------ transactions
def test_rollback_undoes_insert_and_create(runner):
    runner.execute("create table tx (v bigint)")
    runner.execute("insert into tx values (1)")
    runner.execute("start transaction")
    runner.execute("insert into tx values (2), (3)")
    runner.execute("create table tx2 (w bigint)")
    assert runner.execute("select count(*) from tx").rows() == [(3,)]
    runner.execute("rollback")
    assert runner.execute("select count(*) from tx").rows() == [(1,)]
    with pytest.raises(Exception):
        runner.execute("select * from tx2")


def test_commit_keeps_writes(runner):
    runner.execute("create table tc (v bigint)")
    runner.execute("begin")
    runner.execute("insert into tc values (9)")
    runner.execute("commit")
    assert runner.execute("select v from tc").rows() == [(9,)]


def test_transaction_state_errors(runner):
    with pytest.raises(ValueError):
        runner.execute("commit")
    with pytest.raises(ValueError):
        runner.execute("rollback")
    runner.execute("begin")
    with pytest.raises(ValueError):
        runner.execute("begin")
    runner.execute("rollback")


# ------------------------------------------------------------ SQL routines
def test_create_function_and_inline(runner):
    runner.execute(
        "create function double_it(x bigint) returns bigint return x * 2")
    assert runner.execute("select double_it(21)").rows() == [(42,)]
    # routines call routines; arguments are expressions over columns
    runner.execute("create function add5(x bigint) returns bigint "
                   "return double_it(x) + 5 - x")
    assert runner.execute(
        "select add5(n_nationkey) from tpch.nation where n_nationkey = 7"
    ).rows() == [(12,)]


def test_function_over_column_and_where(runner):
    runner.execute("create function sq(x double) returns double return x * x")
    rows = runner.execute(
        "select n_nationkey from tpch.nation where sq(n_nationkey) = 49"
    ).rows()
    assert rows == [(7,)]


def test_drop_function(runner):
    runner.execute("create function f1(x bigint) returns bigint return x")
    runner.execute("drop function f1")
    with pytest.raises(Exception):
        runner.execute("select f1(1)")


def test_recursive_function_rejected(runner):
    runner.execute("create function r1(x bigint) returns bigint return r1(x)")
    with pytest.raises(Exception):
        runner.execute("select r1(1)")


# ---------------------------------------------------------- table functions
def test_sequence(runner):
    assert runner.execute(
        "select count(*), sum(sequential_number) "
        "from table(sequence(1, 100))").rows() == [(100, 5050)]


def test_sequence_negative_step(runner):
    assert runner.execute(
        "select * from table(sequence(5, 1, -2)) as t(n)").rows() == [
        (5,), (3,), (1,)]


def test_sequence_joins(runner):
    rows = runner.execute(
        "select n from table(sequence(0, 4)) as t(n) "
        "join tpch.region on n = r_regionkey order by n").rows()
    assert rows == [(0,), (1,), (2,), (3,), (4,)]


def test_unknown_table_function(runner):
    with pytest.raises(Exception):
        runner.execute("select * from table(nope(1))")


# ------------------------------------------------------------ scaled writers
def test_scaled_writers_round_robin():
    cat = default_catalog(scale_factor=0.01)
    d = DistributedQueryRunner(cat, worker_count=3, session=Session(
        node_count=3, default_catalog="memory", scale_writers=True,
        writer_task_limit=3))
    plan = d.explain("create table li2 as select l_orderkey, l_quantity "
                     "from tpch.lineitem")
    assert "ROUND_ROBIN" in plan and "ARBITRARY" in plan
    n = d.execute("create table li2 as select l_orderkey, l_quantity "
                  "from tpch.lineitem").rows()[0][0]
    single = DistributedQueryRunner(cat, worker_count=3)
    expect = single.execute(
        "select count(*), sum(l_quantity) from tpch.lineitem").rows()
    assert n == expect[0][0]
    assert d.execute(
        "select count(*), sum(l_quantity) from li2").rows() == expect
