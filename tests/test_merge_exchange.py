"""Order-preserving distributed sort: per-task Sort + MERGE exchange
(reference: operator/MergeOperator.java:46; previously the plan gathered
everything and re-sorted)."""

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.runner import Session
from trino_tpu.testing.oracle import SqliteOracle, assert_same_rows

TABLES = ["nation", "orders", "lineitem"]


@pytest.fixture(scope="module")
def harness():
    catalog = default_catalog(scale_factor=0.01)
    dist = DistributedQueryRunner(catalog, worker_count=3,
                                  session=Session(node_count=3))
    oracle = SqliteOracle()
    conn = catalog.connector("tpch")
    for t in TABLES:
        schema = conn.get_table_schema(t)
        cols = schema.column_names()
        batches = []
        for s in conn.get_splits(t, 2, 1):
            src = conn.create_page_source(s, cols)
            while not src.is_finished():
                b = src.get_next_batch()
                if b is not None:
                    batches.append(b)
        oracle.load_table(t, batches)
    return dist, oracle


def test_plan_uses_merge_exchange(harness):
    dist, _ = harness
    text = dist.explain("select o_orderdate from orders order by o_orderdate")
    assert "MERGE" in text
    assert text.count("Sort") == 1  # one per-task sort, no coordinator re-sort


ORDERED_QUERIES = [
    "select o_orderdate, o_totalprice from orders "
    "order by o_orderdate, o_totalprice desc limit 50",
    # NULLS and duplicate keys across producers
    "select n_regionkey, n_name from nation order by n_regionkey desc, n_name",
    # decimals + dates mixed directions
    "select o_totalprice, o_orderdate from orders "
    "order by o_totalprice desc limit 25",
    # strings
    "select o_orderpriority, count(*) from orders group by o_orderpriority "
    "order by o_orderpriority",
]


@pytest.mark.parametrize("sql", ORDERED_QUERIES)
def test_merge_ordering_matches_oracle(harness, sql):
    dist, oracle = harness
    assert_same_rows(dist.execute(sql).rows(), oracle.query(sql), ordered=True)


def test_merge_under_fte(harness):
    dist, oracle = harness
    fte = DistributedQueryRunner(
        dist.catalog, worker_count=3,
        session=Session(node_count=3, retry_policy="TASK"))
    sql = ("select o_orderdate, count(*) from orders group by o_orderdate "
           "order by o_orderdate limit 30")
    assert_same_rows(fte.execute(sql).rows(), oracle.query(sql), ordered=True)
