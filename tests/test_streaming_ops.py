"""Bounded-memory operator behavior: PARTIAL pre-aggregation flush and
streaming TopN (reference: InMemoryHashAggregationBuilder partial flush,
operator/TopNOperator.java)."""

import numpy as np

from trino_tpu.exec.operators import HashAggregationOperator, TopNOperator
from trino_tpu.planner.plan import AggCall, SortKey
from trino_tpu.spi.batch import Column, ColumnBatch
from trino_tpu.spi.types import BIGINT


def _batch(keys, vals):
    return ColumnBatch(
        ["k", "v"],
        [Column(BIGINT, np.asarray(keys, np.int64)),
         Column(BIGINT, np.asarray(vals, np.int64))])


def test_partial_agg_flushes_early():
    op = HashAggregationOperator(
        [0], [AggCall("sum", 1, BIGINT)], ["k", "s"], [BIGINT, BIGINT],
        step="PARTIAL")
    op.FLUSH_ROWS = 100  # tiny window for the test
    for i in range(10):
        op.add_input(_batch(np.arange(50) % 7, np.ones(50)))
    # several flushes must already be available before finish
    flushed = []
    while True:
        b = op.get_output()
        if b is None:
            break
        flushed.append(b)
    assert flushed, "expected pre-finish partial flushes"
    op.finish_input()
    while True:
        b = op.get_output()
        if b is None and op.is_finished():
            break
        if b is not None:
            flushed.append(b)
    # merged totals must equal a single-shot aggregation
    totals = {}
    for b in flushed:
        for k, s in b.to_pylist():
            totals[k] = totals.get(k, 0) + s
    expected = {k: sum(1 for i in range(50) if i % 7 == k) * 10
                for k in range(7)}
    assert totals == expected


def test_partial_agg_buffer_bounded():
    op = HashAggregationOperator(
        [0], [AggCall("sum", 1, BIGINT)], ["k", "s"], [BIGINT, BIGINT],
        step="PARTIAL")
    op.FLUSH_ROWS = 128
    for i in range(100):
        op.add_input(_batch(np.arange(64) % 5, np.ones(64)))
        assert op._buffered_rows <= 128 + 64
        while op.get_output() is not None:
            pass


def test_topn_state_bounded():
    op = TopNOperator(10, [SortKey(1, ascending=False)])
    op._shrink_at = 200
    rng = np.random.default_rng(0)
    seen = []
    for i in range(50):
        vals = rng.integers(0, 1_000_000, 100)
        seen.append(vals)
        op.add_input(_batch(np.arange(100), vals))
        assert op._buffered_rows <= 300  # never more than shrink_at + batch
    op.finish_input()
    out = op.get_output()
    got = sorted((r[1] for r in out.to_pylist()), reverse=True)
    expected = sorted(np.concatenate(seen).tolist(), reverse=True)[:10]
    assert got == expected
