"""ARRAY type + UNNEST + array functions (reference: spi/type/ArrayType.java,
operator/unnest/UnnestOperator.java:42, operator/scalar array functions).
Arrays are host-dictionary values (codes on device) mirroring the varchar
design; sqlite has no arrays, so expectations are hand-checked."""

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.runner import Session, StandaloneQueryRunner
from trino_tpu.spi.batch import Column, unify_dictionaries
from trino_tpu.spi.types import BIGINT, VARCHAR, ArrayType, parse_type


@pytest.fixture(scope="module")
def runner():
    r = StandaloneQueryRunner(default_catalog(scale_factor=0.01),
                              session=Session(default_catalog="memory"))
    r.execute("create table ar (id bigint, tags array(varchar), "
              "nums array(bigint))")
    r.execute("insert into ar values "
              "(1, array['a','b'], array[10, 20]), "
              "(2, array['c'], array[30]), "
              "(3, array[], array[]), "
              "(4, null, null)")
    return r


def rows(runner, sql):
    return runner.execute(sql).rows()


def test_standalone_unnest(runner):
    assert rows(runner, "select * from unnest(array[1,2,3]) as t(x)") == [
        (1,), (2,), (3,)]


def test_unnest_with_ordinality(runner):
    assert rows(runner,
                "select * from unnest(array['a','b']) with ordinality "
                "as t(x, n)") == [("a", 1), ("b", 2)]


def test_lateral_cross_join_unnest(runner):
    assert rows(runner,
                "select id, t.tag from ar cross join unnest(tags) "
                "as t(tag) order by id, tag") == [
        (1, "a"), (1, "b"), (2, "c")]


def test_unnest_zip_pads_to_longest(runner):
    # UNNEST(a, b): shorter array pads with NULL (Trino zip semantics)
    assert rows(runner,
                "select id, t.tag, t.num from ar "
                "cross join unnest(tags, nums) as t(tag, num) "
                "where id = 1 order by num") == [
        (1, "a", 10), (1, "b", 20)]


def test_array_functions(runner):
    assert rows(runner,
                "select cardinality(array[1,2,3]), "
                "element_at(array[5,6,7], 2), array[1,2,3][3], "
                "contains(array[1,2], 2), "
                "array_position(array['x','y'], 'y')") == [
        (3, 6, 3, True, 2)]


def test_cardinality_of_column(runner):
    assert rows(runner,
                "select id, cardinality(tags) from ar order by id") == [
        (1, 2), (2, 1), (3, 0), (4, None)]


def test_element_at_out_of_bounds_is_null(runner):
    assert rows(runner,
                "select element_at(nums, 5), element_at(nums, -1) "
                "from ar where id = 1") == [(None, 20)]


def test_group_by_array_column(runner):
    assert rows(runner,
                "select tags, count(*) from ar group by tags "
                "order by 2 desc, 1") == [
        ([], 1), (["a", "b"], 1), (["c"], 1), (None, 1)]


def test_array_roundtrip_and_null(runner):
    assert rows(runner, "select id, tags from ar order by id") == [
        (1, ["a", "b"]), (2, ["c"]), (3, []), (4, None)]


def test_where_contains(runner):
    assert rows(runner, "select id from ar where contains(tags, 'c')") == [
        (2,)]


def test_unnest_aggregate(runner):
    assert rows(runner,
                "select sum(x) from ar cross join unnest(nums) "
                "as t(x)") == [(60,)]


def test_parse_array_type():
    assert parse_type("array(bigint)") == ArrayType(BIGINT)
    assert parse_type("array(varchar)") == ArrayType(VARCHAR)
    assert parse_type("array(array(bigint))") == ArrayType(ArrayType(BIGINT))


def test_unify_array_dictionaries_with_null_elements():
    # tuple dictionaries containing None are not numpy-sortable: the
    # object-dictionary merge path must handle them
    a = Column.from_values(ArrayType(BIGINT), [[1, None], [2]])
    b = Column.from_values(ArrayType(BIGINT), [[2], [3]])
    ua, ub = unify_dictionaries([a, b])
    assert list(ua.dictionary) == list(ub.dictionary)
    assert [list(x) for x in ua.dictionary[ua.data]] == [[1, None], [2]]
    assert [list(x) for x in ub.dictionary[ub.data]] == [[2], [3]]


def test_array_equality_predicate(runner):
    """Array-vs-literal comparisons must not enter the TupleDomain (tuples
    are not comparable with zone-map stats); the exact Filter handles them
    (round-3 advisor finding, planner/domains.py)."""
    assert rows(runner, "select id from ar where tags = array['a','b']") == [
        (1,)]
    assert rows(runner,
                "select id from ar where tags = array['nope']") == []
