"""DDL breadth: CREATE TABLE (columns), DROP TABLE [IF EXISTS], INSERT,
DELETE (reference: metadata/MetadataManager + SqlBase.g4 statement rules)."""

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.runner import Session, StandaloneQueryRunner


@pytest.fixture()
def runner():
    return StandaloneQueryRunner(
        default_catalog(scale_factor=0.01),
        session=Session(default_catalog="memory"))


def test_create_insert_select_drop(runner):
    runner.execute(
        "create table t (id bigint, name varchar, price decimal(10,2))")
    assert runner.execute("show columns from t").rows() == [
        ("id bigint",), ("name varchar",), ("price decimal(10,2)",)]
    runner.execute("insert into t select n_nationkey, n_name, 1.50 "
                   "from tpch.nation where n_regionkey = 1")
    rows = runner.execute("select count(*), sum(price) from t").rows()
    assert rows[0][0] == 5
    assert float(rows[0][1]) == 7.5
    runner.execute("drop table t")
    with pytest.raises(Exception):
        runner.execute("select * from t")


def test_drop_if_exists(runner):
    runner.execute("drop table if exists nope")  # no error
    with pytest.raises(Exception):
        runner.execute("drop table nope")


def test_delete_where(runner):
    runner.execute("create table d as select n_nationkey, n_regionkey "
                   "from tpch.nation")
    out = runner.execute("delete from d where n_regionkey = 1").rows()
    assert out[0][0] == 5  # 5 nations per region
    assert runner.execute("select count(*) from d").rows() == [(20,)]
    # NULL predicate keeps rows (three-valued semantics)
    runner.execute("delete from d where cast(null as boolean)")
    assert runner.execute("select count(*) from d").rows() == [(20,)]
    # unconditional delete empties the table
    out = runner.execute("delete from d").rows()
    assert out[0][0] == 20
    assert runner.execute("select count(*) from d").rows() == [(0,)]


def test_delete_distributed():
    catalog = default_catalog(scale_factor=0.01)
    d = DistributedQueryRunner(
        catalog, worker_count=2,
        session=Session(node_count=2, default_catalog="memory"))
    d.execute("create table dd as select o_orderkey, o_totalprice "
              "from tpch.orders")
    deleted = d.execute("delete from dd where o_totalprice < 100000").rows()
    remaining = d.execute("select count(*) from dd").rows()[0][0]
    assert deleted[0][0] + remaining == 15000  # orders rows at SF0.01
    assert d.execute(
        "select count(*) from dd where o_totalprice < 100000").rows() == [(0,)]


def test_delete_rejected_on_readonly_connector(runner):
    with pytest.raises(Exception, match="DELETE|sink"):
        runner.execute("delete from tpch.nation")
