"""decimal(38) / MAP / ROW type breadth (round-4 VERDICT item #6).

Long decimals are dictionary-encoded (sorted scaled-int dictionary, int32
codes on device); exact SUM/AVG runs as int64 limb-plane sums recombined
with python bignums (reference: spi/type/Int128Math.java).  MAP/ROW reuse
the array-tuple dictionary model (spi/type/MapType.java, RowType.java).
Expectations are hand-checked with python Decimal (sqlite has no
decimal128/row/map)."""

import decimal
from decimal import Decimal

decimal.getcontext().prec = 80  # expectations need full 38-digit math too

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.runner import Session, StandaloneQueryRunner
from trino_tpu.spi.types import DecimalType, MapType, RowType, parse_type

BIG = [
    "12345678901234567890123456789012.345678",
    "-9999999999999999999999999999.000001",
    "0.000001",
    "777777777777777777777777.500000",
    None,
    "12345678901234567890123456789012.345678",  # duplicate on purpose
]


@pytest.fixture(scope="module")
def runner():
    r = StandaloneQueryRunner(default_catalog(scale_factor=0.01),
                              session=Session(default_catalog="memory"))
    r.execute("create table wide (k bigint, v decimal(38,6))")
    rows = ", ".join(
        f"({i}, {v if v is not None else 'null'})"
        for i, v in enumerate(BIG))
    r.execute(f"insert into wide values {rows}")
    r.execute("create table rm (id bigint, pt row(x bigint, y varchar), "
              "tags map(varchar, bigint))")
    r.execute("insert into rm values "
              "(1, row(10, 'a'), map(array['p','q'], array[1,2])), "
              "(2, row(20, 'b'), map(array['p'], array[7])), "
              "(3, null, null)")
    return r


def test_parse_wide_types():
    t = parse_type("decimal(38,6)")
    assert isinstance(t, DecimalType) and t.is_long and t.scale == 6
    rt = parse_type("row(x bigint, y varchar)")
    assert isinstance(rt, RowType) and rt.fields[0][0] == "x"
    mt = parse_type("map(varchar, bigint)")
    assert isinstance(mt, MapType) and mt.key.name == "varchar"


def test_long_decimal_roundtrip_and_order(runner):
    rows = runner.execute("select v from wide order by v").rows()
    got = [r[0] for r in rows]
    expect = sorted((Decimal(v) for v in BIG if v is not None)) + [None]
    # NULLS LAST for ASC
    assert got == expect


def test_long_decimal_compare_and_group(runner):
    rows = runner.execute(
        "select count(*) from wide where v > 0.5").rows()
    assert rows == [(3,)]
    rows = runner.execute(
        "select v, count(*) from wide group by v order by v").rows()
    assert rows[0][1] == 1 and rows[-2][1] == 2  # the duplicate groups

    rows = runner.execute(
        "select count(*) from wide where v = "
        "12345678901234567890123456789012.345678").rows()
    assert rows == [(2,)]


def test_long_decimal_sum_avg_exact(runner):
    vals = [Decimal(v) for v in BIG if v is not None]
    total = sum(vals)
    rows = runner.execute("select sum(v), avg(v), min(v), max(v), count(v) "
                          "from wide").rows()
    s, a, lo, hi, c = rows[0]
    assert s == total
    assert a == (total / len(vals)).quantize(Decimal("0.000001"))
    assert lo == min(vals) and hi == max(vals) and c == len(vals)


def test_long_decimal_grouped_sum(runner):
    rows = runner.execute(
        "select k % 2, sum(v) from wide group by 1 order by 1").rows()
    even = sum(Decimal(BIG[i]) for i in (0, 2) if BIG[i])
    odd = sum(Decimal(BIG[i]) for i in (1, 3, 5) if BIG[i])
    assert rows[0][1] == even + 0  # k=0,2,4 (4 is NULL)
    assert rows[1][1] == odd


def test_long_decimal_arith_with_literal(runner):
    rows = runner.execute(
        "select v * 2, v + 0.5 from wide where k = 2").rows()
    assert rows[0][0] == Decimal("0.000002")
    assert rows[0][1] == Decimal("0.500001")


def test_long_decimal_casts(runner):
    rows = runner.execute(
        "select cast(v as double), cast(v as varchar) from wide "
        "where k = 3").rows()
    assert abs(rows[0][0] - 7.777777777777778e23) < 1e10
    assert rows[0][1].startswith("777777777777777777777777.5")
    rows = runner.execute(
        "select cast('123.456' as decimal(38,4))").rows()
    assert rows[0][0] == Decimal("123.4560")


def test_long_decimal_distributed():
    catalog = default_catalog(scale_factor=0.01)
    dist = DistributedQueryRunner(
        catalog, worker_count=3,
        session=Session(default_catalog="memory", node_count=3))
    dist.execute("create table w2 (k bigint, v decimal(38,2))")
    dist.execute("insert into w2 values (1, 99999999999999999999.25), "
                 "(2, 0.25), (3, -50000000000000000000.50), (4, null)")
    rows = dist.execute("select sum(v), avg(v), count(v) from w2").rows()
    assert rows[0][0] == Decimal("49999999999999999999.00")
    assert rows[0][1] == Decimal("16666666666666666666.33")
    assert rows[0][2] == 3


def test_row_type_access_and_group(runner):
    rows = runner.execute(
        "select id, pt.x, pt.y from rm order by id").rows()
    assert rows == [(1, 10, "a"), (2, 20, "b"), (3, None, None)]
    rows = runner.execute("select pt from rm where id = 1").rows()
    assert rows == [((10, "a"),)]
    rows = runner.execute(
        "select count(*) from rm where pt = row(10, 'a')").rows()
    assert rows == [(1,)]
    # subscript: 1-based field index
    assert runner.execute("select pt[1] from rm where id = 2").rows() == [
        (20,)]


def test_map_type_functions(runner):
    rows = runner.execute(
        "select id, cardinality(tags), tags['p'], element_at(tags, 'q') "
        "from rm order by id").rows()
    assert rows == [(1, 2, 1, 2), (2, 1, 7, None), (3, None, None, None)]
    rows = runner.execute(
        "select map_keys(tags), map_values(tags) from rm where id = 1").rows()
    assert rows == [(["p", "q"], [1, 2])]
    rows = runner.execute("select tags from rm where id = 2").rows()
    assert rows == [({"p": 7},)]


def test_row_map_serde_roundtrip(runner):
    from trino_tpu.execution.serde import deserialize_batch, serialize_batch
    from trino_tpu.spi.batch import Column, ColumnBatch

    t = parse_type("row(a bigint, b varchar)")
    mt = parse_type("map(varchar, bigint)")
    dt = parse_type("decimal(38,3)")
    b = ColumnBatch(
        ["r", "m", "d"],
        [Column.from_values(t, [(1, "x"), None, (2, "y")]),
         Column.from_values(mt, [{"k": 1}, {"a": 2, "b": 3}, None]),
         Column.from_values(dt, ["123456789012345678901234.5", None, "0.001"])])
    out = deserialize_batch(serialize_batch(b))
    assert out.to_pylist() == b.to_pylist()
