"""Compressed execution: encoding-aware operators end-to-end (ISSUE 16).

Covers the tentpole pillars: Column encoding metadata and its propagation
through batch ops, RLE-aware aggregation (value * run_count, nulls inside
runs), the serde v2 dictionary sidecar + RLE pages, dictionary codes
surviving a repartition exchange undecoded, the collective plane keeping
codes resident, lazy columns that never materialize, and the
TRINO_TPU_ENCODED_EXEC=0/1 equivalence oracle over the TPC-H suite."""

import numpy as np
import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.connectors.tpch_queries import QUERIES
from trino_tpu.exec.operators import HashAggregationOperator
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.execution.serde import (
    CODEC_NONE,
    PageStreamEncoder,
    deserialize_batch,
    serialize_batch,
)
from trino_tpu.planner.plan import AggCall
from trino_tpu.runner import Session, StandaloneQueryRunner
from trino_tpu.spi.batch import (
    Column,
    ColumnBatch,
    maybe_rle,
    pad_to_bucket,
)
from trino_tpu.spi.errors import TrinoError
from trino_tpu.spi.types import BIGINT, DOUBLE, VARCHAR
from trino_tpu.telemetry.metrics import REGISTRY
from trino_tpu.testing.oracle import assert_same_rows


def _enc(name: str) -> int:
    return REGISTRY.snapshot()[f"trino_encoding_{name}_total"]["value"]


# ----------------------------------------------------- encoding propagation


def test_rle_detection_and_propagation():
    const = Column(BIGINT, np.full(128, 7, np.int64))
    rle = maybe_rle(const)
    assert rle.encoding == "RLE" and len(rle) == 128
    assert rle.nbytes < const.nbytes
    assert rle.flat_nbytes == const.nbytes

    # varied data must NOT collapse; short runs are not worth probing
    assert maybe_rle(Column(BIGINT, np.arange(128))).encoding == "FLAT"
    assert maybe_rle(Column(BIGINT, np.full(8, 7, np.int64))).encoding == "FLAT"

    # slice/take/filter/concat keep the run encoded
    assert rle.slice_rows(10, 50).encoding == "RLE"
    assert rle.take(np.array([1, 5, 9])).encoding == "RLE"
    f = rle.filter(np.arange(128) % 2 == 0)
    assert f.encoding == "RLE" and len(f) == 64
    cat = ColumnBatch.concat([
        ColumnBatch(["x"], [Column.rle(BIGINT, 7, 100)]),
        ColumnBatch(["x"], [Column.rle(BIGINT, 7, 28)]),
    ])
    assert cat.columns[0].encoding == "RLE" and len(cat.columns[0]) == 128

    padded = pad_to_bucket(ColumnBatch(["x"], [Column.rle(BIGINT, 7, 100)]))
    assert padded.columns[0].encoding == "RLE"
    assert padded.num_rows >= 100
    # the expanded view is still correct
    assert list(np.asarray(rle.data[:3])) == [7, 7, 7]


def test_rle_mixed_concat_expands_correctly():
    cat = ColumnBatch.concat([
        ColumnBatch(["x"], [Column.rle(BIGINT, 7, 70)]),
        ColumnBatch(["x"], [Column(BIGINT, np.arange(30, dtype=np.int64))]),
    ])
    out = np.asarray(cat.columns[0].data)
    assert len(out) == 100
    assert (out[:70] == 7).all() and (out[70:] == np.arange(30)).all()


def test_lazy_thunk_runs_once_and_pad_composes():
    calls = []

    def thunk():
        calls.append(1)
        return np.arange(100, dtype=np.int64), None

    lz = Column.lazy(BIGINT, 100, thunk, nbytes_hint=800)
    assert lz.encoding == "LAZY" and not lz.is_materialized
    assert lz.nbytes == 800
    padded = pad_to_bucket(ColumnBatch(["x"], [lz]))
    pc = padded.columns[0]
    assert pc.encoding == "LAZY" and not calls, "pad must not materialize"
    out = np.asarray(pc.data)
    assert calls == [1] and (out[:100] == np.arange(100)).all()
    _ = pc.data  # second touch: cached
    assert calls == [1]


def test_lazy_empty_selection_skips_thunk():
    lz = Column.lazy(BIGINT, 100,
                     lambda: (np.arange(100, dtype=np.int64), None))
    empty = lz.filter(np.zeros(100, bool))
    assert len(empty) == 0 and not lz.is_materialized
    empty2 = lz.take(np.empty(0, np.int64))
    assert len(empty2) == 0 and not lz.is_materialized


def test_nbytes_includes_dictionary_bytes():
    d = np.array(["alpha", "beta", "gamma"], dtype=object)
    plain = Column(BIGINT, np.zeros(8, np.int32))
    coded = Column(VARCHAR, np.zeros(8, np.int32), None, d)
    assert coded.nbytes > plain.nbytes, \
        "dictionary bytes must count toward memory accounting"


# -------------------------------------------------------- RLE aggregation


def _agg(aggs, names, types, batches):
    op = HashAggregationOperator([], aggs, names, types)
    for b in batches:
        op.add_input(b)
    op.finish_input()
    return op, op.get_output()


def test_rle_agg_sum_count_min_max_with_nulls_in_runs():
    # run 1: value 5 x 100, rows 10..19 NULL; run 2: value 3 x 50, all valid
    v1 = np.ones(100, bool)
    v1[10:20] = False
    b1 = ColumnBatch(["x"], [Column.rle(BIGINT, 5, 100, v1)])
    b2 = ColumnBatch(["x"], [Column.rle(BIGINT, 3, 50)])
    aggs = [AggCall("sum", 0, BIGINT), AggCall("count", 0, BIGINT),
            AggCall("min", 0, BIGINT), AggCall("max", 0, BIGINT),
            AggCall("count_star", -1, BIGINT)]
    op, out = _agg(aggs, ["s", "c", "lo", "hi", "n"],
                   [BIGINT] * 5, [b1, b2])
    assert out.to_pylist() == [(5 * 90 + 3 * 50, 140, 3, 5, 150)]
    # folded rows are counted per value-aggregate: 4 aggs x 140 live rows
    assert op.encoding_stats.rle_agg_rows == 4 * 140, \
        "fast path must fold runs without expanding"


def test_rle_agg_all_null_run_is_null():
    b = ColumnBatch(["x"], [Column.rle(BIGINT, 9, 64, np.zeros(64, bool))])
    _, out = _agg([AggCall("sum", 0, BIGINT), AggCall("count", 0, BIGINT)],
                  ["s", "c"], [BIGINT, BIGINT], [b])
    assert out.to_pylist() == [(None, 0)]


def test_rle_agg_respects_live_mask():
    live = np.zeros(100, bool)
    live[:30] = True
    b = ColumnBatch(["x"], [Column.rle(BIGINT, 4, 100)], live)
    op, out = _agg([AggCall("sum", 0, BIGINT),
                    AggCall("count_star", -1, BIGINT)],
                   ["s", "n"], [BIGINT, BIGINT], [b])
    assert out.to_pylist() == [(4 * 30, 30)]
    assert op.encoding_stats.rle_agg_rows == 30


def test_rle_agg_fast_path_matches_flat():
    """The fast path and the expanded kernel agree bit-for-bit."""
    valid = np.ones(200, bool)
    valid[7::13] = False
    rle_b = ColumnBatch(["x"], [Column.rle(DOUBLE, 2.5, 200, valid)])
    flat_b = ColumnBatch(
        ["x"], [Column(DOUBLE, np.full(200, 2.5), valid.copy())])
    aggs = [AggCall("sum", 0, DOUBLE), AggCall("count", 0, BIGINT)]
    _, fast = _agg(aggs, ["s", "c"], [DOUBLE, BIGINT], [rle_b])
    _, slow = _agg(aggs, ["s", "c"], [DOUBLE, BIGINT], [flat_b])
    assert fast.to_pylist() == slow.to_pylist()


# ------------------------------------------------------------- serde v2


def _dict_batch():
    d = np.array(["a", "b", "c"], dtype=object)
    return ColumnBatch(
        ["s", "v"],
        [Column(VARCHAR, np.array([0, 1, 2, 1, 0], np.int32), None, d),
         Column(BIGINT, np.arange(5, dtype=np.int64),
                np.array([1, 1, 0, 1, 1], bool))])


def test_serde_v2_dict_sidecar_def_then_ref():
    b = _dict_batch()
    ctx = PageStreamEncoder()
    sent0, reused0 = _enc("dict_sidecar_sent"), _enc("dict_sidecar_reused")
    p1 = serialize_batch(b, codec=CODEC_NONE, ctx=ctx)  # definition page
    p2 = serialize_batch(b, codec=CODEC_NONE, ctx=ctx)  # reference page
    assert p1[:4] == b"TTP2" and len(p2) < len(p1), \
        "reference pages must not re-ship dictionary values"
    o1, o2 = deserialize_batch(p1), deserialize_batch(p2)
    assert o1.to_pylist() == b.to_pylist() == o2.to_pylist()
    assert list(o2.columns[0].dictionary) == ["a", "b", "c"]
    assert _enc("dict_sidecar_sent") == sent0 + 1
    assert _enc("dict_sidecar_reused") == reused0 + 1


def test_serde_v2_rle_column_round_trip():
    b = ColumnBatch(["r", "v"],
                    [Column.rle(BIGINT, 7, 5),
                     Column(BIGINT, np.arange(5, dtype=np.int64))])
    wire = serialize_batch(b, codec=CODEC_NONE, ctx=PageStreamEncoder())
    # the run crosses the wire as ONE value, and comes back still encoded
    out = deserialize_batch(wire)
    assert out.columns[0].encoding == "RLE"
    assert out.to_pylist() == b.to_pylist()


def test_serde_v1_unchanged_without_ctx():
    b = _dict_batch()
    wire = serialize_batch(b)
    assert wire[:4] == b"TTP1"
    assert deserialize_batch(wire).to_pylist() == b.to_pylist()


def test_serde_v2_sidecar_miss_is_transport_error():
    b = _dict_batch()
    ctx = PageStreamEncoder()
    serialize_batch(b, ctx=ctx)            # def consumed nowhere
    ref_page = serialize_batch(b, ctx=ctx)  # ref without its def registered
    with pytest.raises(TrinoError):
        deserialize_batch(ref_page)


# ----------------------------------------------- engine-level integration


@pytest.fixture(scope="module")
def standalone():
    return StandaloneQueryRunner(default_catalog(scale_factor=0.01))


def test_lazy_filter_never_materializes_dropped_batches(standalone):
    """A zero-selectivity filter computes its mask from the predicate
    column only; payload columns stay lazy and are never pulled."""
    lazy0, mat0 = _enc("lazy_columns"), _enc("lazy_materialized")
    res = standalone.execute(
        "select l_comment from lineitem where l_quantity > 1e9")
    assert res.rows() == []
    assert _enc("lazy_columns") > lazy0, "payload column was not lazy-staged"
    assert _enc("lazy_materialized") == mat0, \
        "payload bytes were materialized despite zero survivors"


def test_low_selectivity_filter_skips_payload_bytes(standalone):
    skipped0 = _enc("lazy_skipped_bytes")
    standalone.execute(
        "select l_extendedprice, l_discount from lineitem "
        "where l_orderkey = 1")
    assert _enc("lazy_skipped_bytes") > skipped0


def test_explain_analyze_surfaces_encoding_line(standalone):
    rows = standalone.execute(
        "explain analyze select l_returnflag, count(*) from lineitem "
        "group by l_returnflag").rows()
    text = "\n".join(r[0] for r in rows)
    assert "encoding:" in text, f"no encoding stats in:\n{text}"
    assert "code group-bys" in text


def _oracle_encoded_vs_flat(standalone, monkeypatch, names):
    """TRINO_TPU_ENCODED_EXEC=1 rows identical to =0 (the bit-for-bit
    legacy expand-at-scan path)."""
    for q in names:
        monkeypatch.setenv("TRINO_TPU_ENCODED_EXEC", "1")
        on = standalone.execute(QUERIES[q]).rows()
        monkeypatch.setenv("TRINO_TPU_ENCODED_EXEC", "0")
        off = standalone.execute(QUERIES[q]).rows()
        assert_same_rows(on, off, ordered=False)


def test_encoded_vs_flat_tpch_oracle(standalone, monkeypatch):
    # tier-1 subset spanning the encoded paths: RLE-able scans + dict
    # group-by (q1), joins on codes (q3, q12), selective filter (q6),
    # semi-join + distinct on dict keys (q16), dict CASE projection (q14)
    _oracle_encoded_vs_flat(standalone, monkeypatch, [1, 3, 6, 12, 14, 16])


@pytest.mark.slow
def test_encoded_vs_flat_tpch_oracle_full(standalone, monkeypatch):
    _oracle_encoded_vs_flat(standalone, monkeypatch, sorted(QUERIES))


def test_encoded_exec_off_uses_no_encoded_paths(standalone, monkeypatch):
    monkeypatch.setenv("TRINO_TPU_ENCODED_EXEC", "0")
    before = {k: v["value"] for k, v in REGISTRY.snapshot().items()
              if "encoding" in k}
    standalone.execute(
        "select l_returnflag, count(*) from lineitem group by l_returnflag")
    after = {k: v["value"] for k, v in REGISTRY.snapshot().items()
             if "encoding" in k}
    assert before == after, "=0 must leave every encoded path cold"


def test_dict_codes_survive_repartition_exchange():
    """Acceptance: dictionary codes cross a repartition exchange without a
    decode — the sidecar ships values once per stream and later pages carry
    only codes (trino_encoding_* counters prove it)."""
    catalog = default_catalog(scale_factor=0.01)
    dist = DistributedQueryRunner(
        catalog, worker_count=3,
        session=Session(node_count=3, use_collectives=False,
                        exchange_serde=True))
    sent0, pages0 = _enc("dict_sidecar_sent"), _enc("exchange_code_pages")
    sql = ("select c_mktsegment, count(*) from customer, orders "
           "where c_custkey = o_custkey group by c_mktsegment")
    rows = dist.execute(sql).rows()
    standalone = StandaloneQueryRunner(catalog)
    assert_same_rows(rows, standalone.execute(sql).rows())
    assert _enc("dict_sidecar_sent") > sent0, "no dictionary sidecar shipped"
    assert _enc("exchange_code_pages") > pages0, \
        "no page crossed the exchange as codes"


def test_collective_exchange_keeps_codes_resident(monkeypatch):
    monkeypatch.setenv("TRINO_TPU_FUSED_STAGE", "0")
    catalog = default_catalog(scale_factor=0.01)
    dist = DistributedQueryRunner(
        catalog, worker_count=4, session=Session(node_count=4))
    pages0 = _enc("exchange_code_pages")
    rows = dist.execute(
        "select l_returnflag, count(*), sum(l_quantity) from lineitem "
        "group by l_returnflag").rows()
    assert dist._collective_edges, "expected a collective repartition edge"
    assert len(rows) == 3
    assert _enc("exchange_code_pages") > pages0, \
        "dict key did not stay code-resident through the all_to_all"
