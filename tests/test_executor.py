"""Time-sharing task executor: bounded workers, MLFQ quanta, non-blocking
exchange parking (reference: TimeSharingTaskExecutor.java:85,
MultilevelSplitQueue.java:39)."""

import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.connectors.tpch_queries import QUERIES
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.runner import Session
from trino_tpu.testing.oracle import SqliteOracle, assert_same_rows

TABLES = ["nation", "region", "customer", "orders", "lineitem", "supplier"]


@pytest.fixture(scope="module")
def harness():
    catalog = default_catalog(scale_factor=0.01)
    # 2 workers multiplexing 3-task stages proves tasks time-share a
    # bounded pool instead of each owning a thread
    ts = DistributedQueryRunner(
        catalog, worker_count=3,
        session=Session(node_count=3, task_scheduler="TIME_SHARING",
                        executor_workers=2))
    oracle = SqliteOracle()
    conn = catalog.connector("tpch")
    for t in TABLES:
        schema = conn.get_table_schema(t)
        cols = schema.column_names()
        batches = []
        for s in conn.get_splits(t, 2, 1):
            src = conn.create_page_source(s, cols)
            while not src.is_finished():
                b = src.get_next_batch()
                if b is not None:
                    batches.append(b)
        oracle.load_table(t, batches)
    return ts, oracle


@pytest.mark.parametrize("q", [1, 3, 6])
def test_time_sharing_tpch(harness, q):
    ts, oracle = harness
    assert_same_rows(ts.execute(QUERIES[q]).rows(), oracle.query(QUERIES[q]),
                     ordered=q in (1, 3))


def test_time_sharing_error_propagates(harness):
    ts, _ = harness
    with pytest.raises(Exception, match="bogus"):
        ts.execute("select bogus(1) from nation")


def test_driver_process_quantum_contract():
    """Driver.process returns blocked (not an exception) when a source has
    no input yet, and finished once the pipeline drains."""
    import numpy as np

    from trino_tpu.exec.driver import Driver
    from trino_tpu.exec.operators import (
        JoinBridge,
        LookupJoinOperator,
        OutputCollector,
        ValuesOperator,
    )
    from trino_tpu.spi.batch import Column, ColumnBatch
    from trino_tpu.spi.types import BIGINT

    batch = ColumnBatch(["a"], [Column(BIGINT, np.arange(4, dtype=np.int64))])
    bridge = JoinBridge()  # never becomes ready -> probe stays blocked
    probe = LookupJoinOperator(bridge, [0], "INNER", None, ["a", "b"],
                               [BIGINT, BIGINT])
    d = Driver([ValuesOperator(batch), probe, OutputCollector()])
    assert d.process() == "blocked"

    d2 = Driver([ValuesOperator(batch), OutputCollector()])
    assert d2.process() == "finished"
