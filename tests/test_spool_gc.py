"""Spool retention/GC (execution/spool_gc.py) and CRC-checked v2 spool
framing (execution/serde.py): leases, boot-sweep rules, byte budget, and
corruption detection classified as retryable."""

import os
import struct

import pytest

from trino_tpu.execution import spool_gc
from trino_tpu.execution.serde import (SPOOL_STREAM_MAGIC,
                                       SpoolCorruptionError, iter_frames,
                                       write_frame, write_frame_crc,
                                       write_stream_header)


# --------------------------------------------------------- CRC framing
def test_v2_roundtrip_and_v1_autodetect(tmp_path):
    pages = [b"alpha", b"", b"x" * 4096]
    v2 = tmp_path / "v2.bin"
    with open(v2, "wb") as f:
        write_stream_header(f)
        for p in pages:
            write_frame_crc(f, p)
    with open(v2, "rb") as f:
        assert list(iter_frames(f, str(v2))) == pages

    # pre-existing v1 files (no magic) stay readable through the same API
    v1 = tmp_path / "v1.bin"
    with open(v1, "wb") as f:
        for p in pages:
            write_frame(f, p)
    with open(v1, "rb") as f:
        assert list(iter_frames(f, str(v1))) == pages


def test_v2_bit_flip_detected(tmp_path):
    path = tmp_path / "flip.bin"
    with open(path, "wb") as f:
        write_stream_header(f)
        write_frame_crc(f, b"payload-bytes")
    raw = bytearray(path.read_bytes())
    raw[12] ^= 0x01  # first payload byte (4 magic + 8 header)
    path.write_bytes(bytes(raw))
    with open(path, "rb") as f:
        with pytest.raises(SpoolCorruptionError) as ei:
            list(iter_frames(f, str(path)))
    assert "CRC32" in str(ei.value)
    assert ei.value.path == str(path)
    # EXTERNAL error code → the FTE loop treats it as retryable
    assert ei.value.is_retryable()


def test_v2_torn_write_detected(tmp_path):
    path = tmp_path / "torn.bin"
    with open(path, "wb") as f:
        write_stream_header(f)
        write_frame_crc(f, b"will be cut short")
    path.write_bytes(path.read_bytes()[:-5])
    with open(path, "rb") as f:
        with pytest.raises(SpoolCorruptionError):
            list(iter_frames(f, str(path)))
    # a frame header cut mid-word is also corruption, not EOF
    hdr_only = tmp_path / "hdr.bin"
    hdr_only.write_bytes(SPOOL_STREAM_MAGIC + struct.pack("<I", 9))
    with open(hdr_only, "rb") as f:
        with pytest.raises(SpoolCorruptionError):
            list(iter_frames(f, str(hdr_only)))


def test_durable_spool_writes_v2(tmp_path):
    """DurableSpoolWriter streams carry the CRC header so every FTE spool
    read is integrity-checked end to end."""
    from trino_tpu.execution.durable_spool import DurableSpoolWriter

    w = DurableSpoolWriter(str(tmp_path / "f0_t0"), attempt=0,
                           num_partitions=1)
    w.set_finished()
    part0 = os.path.join(w.committed, "part-0.bin")
    with open(part0, "rb") as f:
        assert f.read(4) == SPOOL_STREAM_MAGIC


# ------------------------------------------------------------ lease/GC
def _mkroot(base, name, nbytes=64, lease=None, mtime=None):
    root = base / name
    root.mkdir()
    (root / "part-0.bin").write_bytes(b"\0" * nbytes)
    if lease is not None:
        spool_gc.acquire(str(root), **lease)
    if mtime is not None:
        os.utime(root, (mtime, mtime))
    return str(root)


def test_release_reclaims_now(tmp_path):
    root = _mkroot(tmp_path, "trino-tpu-spool-a",
                   lease={"query_id": "q1"})
    assert spool_gc.release(root) > 0
    assert not os.path.exists(root)
    assert spool_gc.release(root) == 0  # idempotent


def test_sweep_rules(tmp_path, monkeypatch):
    monkeypatch.setenv("TRINO_TPU_SPOOL_DIR", str(tmp_path))
    monkeypatch.setenv("TRINO_TPU_SPOOL_TTL_S", "3600")
    import time
    now = time.time()

    pinned = _mkroot(tmp_path, "trino-tpu-spool-pinned",
                     lease={"query_id": "qp", "ttl_s": 1.0})
    live = _mkroot(tmp_path, "trino-tpu-spool-live",
                   lease={"query_id": "ql"})  # our own live pid
    dead = _mkroot(tmp_path, "trino-tpu-spool-dead")
    # forge a dead-owner lease (pid from a long-gone process)
    spool_gc.acquire(dead, "qd")
    import json
    lp = os.path.join(dead, spool_gc.LEASE_FILE)
    rec = json.load(open(lp))
    rec["pid"] = 2 ** 22 + 12345
    json.dump(rec, open(lp, "w"))
    expired = _mkroot(tmp_path, "trino-tpu-spool-expired",
                      lease={"query_id": "qe", "ttl_s": 0.001})
    stale = _mkroot(tmp_path, "trino-tpu-spool-stale",
                    mtime=now - 7200)  # no lease, past TTL
    fresh = _mkroot(tmp_path, "trino-tpu-spool-fresh", mtime=now - 10)
    other = tmp_path / "unrelated-dir"
    other.mkdir()

    out = spool_gc.sweep(keep=[pinned], now=now + 5.0)
    assert pinned in out["kept"]        # keep= pins even an expired lease
    assert live in out["kept"]          # live pid + unexpired ttl
    assert fresh in out["kept"]         # no lease but young
    assert dead in out["reclaimed"] and not os.path.exists(dead)
    assert expired in out["reclaimed"] and not os.path.exists(expired)
    assert stale in out["reclaimed"] and not os.path.exists(stale)
    assert other.exists()               # non-spool names untouched
    assert out["live_bytes"] > 0


def test_sweep_byte_budget(tmp_path, monkeypatch):
    monkeypatch.setenv("TRINO_TPU_SPOOL_DIR", str(tmp_path))
    monkeypatch.setenv("TRINO_TPU_SPOOL_TTL_S", "86400")
    monkeypatch.setenv("TRINO_TPU_SPOOL_MAX_BYTES", "1500")
    import time
    now = time.time()
    old = _mkroot(tmp_path, "trino-tpu-spool-old", nbytes=1000,
                  mtime=now - 500)
    new = _mkroot(tmp_path, "trino-tpu-spool-new", nbytes=1000,
                  mtime=now - 100)
    out = spool_gc.sweep(now=now)
    # over budget: the OLDEST unpinned root goes first, the newer survives
    assert old in out["reclaimed"] and not os.path.exists(old)
    assert new in out["kept"] and os.path.exists(new)
    assert out["live_bytes"] == 1000
