"""Engine-integrated collective exchange: REPARTITION edges run as ONE
shard_map all_to_all over the device mesh, with no host round trip between
PARTIAL and FINAL aggregation (SURVEY §2.4 north star; reference equivalent:
operator/output/PagePartitioner.java + HTTP exchange, replaced here by ICI
collectives)."""

import numpy as np
import pytest

from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.connectors.tpch_queries import QUERIES
from trino_tpu.execution import collective_exchange as CE
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.runner import Session
from trino_tpu.testing.oracle import SqliteOracle, assert_same_rows

TABLES = ["nation", "region", "supplier", "customer", "part", "partsupp",
          "orders", "lineitem"]


@pytest.fixture(autouse=True)
def _legacy_collective_path(monkeypatch):
    """This module tests the legacy collective-exchange path; whole-stage
    compilation (which subsumes these edges) has its own suite in
    tests/test_fused_stage.py."""
    monkeypatch.setenv("TRINO_TPU_FUSED_STAGE", "0")


@pytest.fixture(scope="module")
def harness():
    catalog = default_catalog(scale_factor=0.01)
    dist = DistributedQueryRunner(
        catalog, worker_count=4, session=Session(node_count=4))
    oracle = SqliteOracle()
    conn = catalog.connector("tpch")
    for t in TABLES:
        schema = conn.get_table_schema(t)
        cols = schema.column_names()
        batches = []
        for s in conn.get_splits(t, 2, 1):
            src = conn.create_page_source(s, cols)
            while not src.is_finished():
                b = src.get_next_batch()
                if b is not None:
                    batches.append(b)
        oracle.load_table(t, batches)
    return dist, oracle


def test_repartition_edge_uses_collective(harness):
    dist, oracle = harness
    sql = ("select l_returnflag, count(*), sum(l_quantity) from lineitem "
           "group by l_returnflag")
    result = dist.execute(sql)
    assert dist._collective_edges, "REPARTITION edge did not use collectives"
    assert_same_rows(result.rows(), oracle.query(sql))


def test_partial_final_stays_on_device(harness, monkeypatch):
    """The PARTIAL aggregation's deposit into the collective must be
    device-resident (no host numpy between PARTIAL and FINAL)."""
    dist, oracle = harness
    seen = []
    orig = CE.CollectiveRepartitionExchange.deposit

    def spy(self, task_index, batches):
        for b in batches:
            for c in b.columns:
                seen.append(isinstance(c.data, np.ndarray))
        return orig(self, task_index, batches)

    monkeypatch.setattr(CE.CollectiveRepartitionExchange, "deposit", spy)
    sql = ("select l_returnflag, avg(l_quantity) from lineitem "
           "group by l_returnflag")
    result = dist.execute(sql)
    assert seen, "no deposits observed"
    assert not any(seen), "PARTIAL output crossed through host numpy"
    assert_same_rows(result.rows(), oracle.query(sql))


@pytest.mark.parametrize("q", [1, 3])
def test_tpch_via_collectives(harness, q):
    dist, oracle = harness
    result = dist.execute(QUERIES[q])
    assert dist._collective_edges, "expected a collective repartition edge"
    assert_same_rows(result.rows(), oracle.query(QUERIES[q]),
                     ordered=q in (1, 3))


def test_fallback_when_disabled(harness):
    dist, oracle = harness
    off = DistributedQueryRunner(
        dist.catalog, worker_count=4,
        session=Session(node_count=4, use_collectives=False))
    sql = "select l_returnflag, count(*) from lineitem group by l_returnflag"
    assert_same_rows(off.execute(sql).rows(), oracle.query(sql))
    assert not off._collective_edges


def test_partitioned_string_join_routes_consistently(harness, monkeypatch):
    """Both REPARTITION edges of a partitioned string-key join must route
    equal VALUES to the same task even though each edge unifies its own
    dictionary (codes differ per edge)."""
    from trino_tpu.planner import optimizer as O

    monkeypatch.setattr(O, "_BROADCAST_LIMIT", 0)  # force PARTITIONED joins
    dist, oracle = harness
    sql = ("select a.n_name, b.n_regionkey from nation a "
           "join nation b on a.n_name = b.n_name")
    result = dist.execute(sql)
    assert dist._collective_edges, "expected collective repartition edges"
    assert_same_rows(result.rows(), oracle.query(sql))


def test_string_keys_route_by_value(harness):
    """Dictionary-coded group keys must repartition by VALUE (unified
    dictionaries), not raw codes."""
    dist, oracle = harness
    sql = ("select o_orderpriority, count(*) from orders "
           "group by o_orderpriority")
    result = dist.execute(sql)
    assert dist._collective_edges
    assert_same_rows(result.rows(), oracle.query(sql))


@pytest.mark.parametrize("q", [3, 5, 10])
def test_tpch_via_tiled_raw_row_collectives(harness, monkeypatch, q):
    """Raw-row repartition: force every collective edge through the tiled
    sorted-bucket all_to_all (local sort by owner + per-destination tiles)
    instead of the broadcast lane layout; join-heavy TPC-H queries must stay
    oracle-correct with the rows riding the mesh (round-4 VERDICT item #2;
    reference: operator/output/PagePartitioner.java:134)."""
    dist, oracle = harness
    monkeypatch.setattr(CE, "TILED_THRESHOLD_ROWS", 0)
    sql = QUERIES[q]
    result = dist.execute(sql)
    assert dist._collective_edges, "no collective edges in plan"
    assert_same_rows(result.rows(), oracle.query(sql),
                     ordered="order by" in sql.lower())
