"""File connector (persistent columnar storage) + native C++ page-file IO
(native/pagefile.cpp via ctypes; reference role: plugin/trino-hive native
readers + buffer/PageSerializer)."""

import os

import numpy as np
import pytest

from trino_tpu import native
from trino_tpu.connectors.catalog import default_catalog
from trino_tpu.execution.distributed_runner import DistributedQueryRunner
from trino_tpu.runner import Session, StandaloneQueryRunner


@pytest.fixture()
def runner(tmp_path):
    return StandaloneQueryRunner(
        default_catalog(scale_factor=0.01, file_root=str(tmp_path)),
        session=Session(default_catalog="file"))


def test_native_library_builds():
    lib = native.load()
    assert lib is not None, "C++ page-file library failed to build"
    assert os.path.exists(native.lib_path())


def test_native_bitmap_roundtrip():
    import ctypes

    lib = native.load()
    assert lib is not None
    bools = np.array([1, 0, 1, 1, 0, 0, 0, 1, 1, 0, 1], np.uint8)
    packed = np.zeros((len(bools) + 7) // 8, np.uint8)
    lib.ttp_pack_bits(bools.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                      len(bools),
                      packed.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    assert bytes(packed) == np.packbits(bools.astype(bool)).tobytes()
    out = np.zeros(len(bools), np.uint8)
    lib.ttp_unpack_bits(packed.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                        len(bools),
                        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    assert (out == bools).all()


def test_native_zlib_roundtrip():
    import ctypes
    import zlib

    lib = native.load()
    assert lib is not None
    payload = os.urandom(1000) + b"\x00" * 50_000
    src = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
    cap = lib.ttp_deflate_bound(len(payload))
    dst = (ctypes.c_uint8 * cap)()
    n = lib.ttp_deflate(src, len(payload), dst, cap, 1)
    assert 0 < n < len(payload)
    assert zlib.decompress(bytes(dst[:n])) == payload
    back = (ctypes.c_uint8 * len(payload))()
    m = lib.ttp_inflate(dst, n, back, len(payload))
    assert m == len(payload) and bytes(back) == payload


def test_file_table_lifecycle(runner, tmp_path):
    runner.execute("create table ft as select n_nationkey, n_name, n_regionkey "
                   "from tpch.nation")
    assert os.path.exists(tmp_path / "ft" / "schema.json")
    rows = runner.execute(
        "select n_regionkey, count(*) from ft group by n_regionkey").rows()
    assert sorted(rows) == [(i, 5) for i in range(5)]
    # insert appends a second page file
    runner.execute("insert into ft select n_nationkey, n_name, n_regionkey "
                   "from tpch.nation where n_regionkey = 0")
    assert runner.execute("select count(*) from ft").rows() == [(30,)]
    # strings / NULL semantics survive the disk roundtrip
    assert runner.execute(
        "select n_name from ft where n_nationkey = 3 limit 1").rows() == [("CANADA",)]
    runner.execute("drop table ft")
    assert runner.execute("show tables").rows() == []


def test_file_table_survives_new_catalog(tmp_path):
    root = str(tmp_path)
    a = StandaloneQueryRunner(default_catalog(0.01, file_root=root),
                              session=Session(default_catalog="file"))
    a.execute("create table keep as select r_regionkey, r_name from tpch.region")
    # a brand-new catalog over the same root sees the persisted table
    b = StandaloneQueryRunner(default_catalog(0.01, file_root=root),
                              session=Session(default_catalog="file"))
    assert sorted(b.execute("select r_name from keep").rows()) == [
        ("AFRICA",), ("AMERICA",), ("ASIA",), ("EUROPE",), ("MIDDLE EAST",)]


def test_file_scan_distributed(tmp_path):
    catalog = default_catalog(0.01, file_root=str(tmp_path))
    d = DistributedQueryRunner(
        catalog, worker_count=2,
        session=Session(node_count=2, default_catalog="file"))
    d.execute("create table big as select o_orderkey, o_totalprice "
              "from tpch.orders")
    rows = d.execute(
        "select count(*), sum(o_totalprice) from big").rows()
    assert rows[0][0] == 15000


def test_delete_on_file_table(runner):
    runner.execute("create table fd as select n_nationkey, n_regionkey "
                   "from tpch.nation")
    assert runner.execute(
        "delete from fd where n_regionkey < 2").rows() == [(10,)]
    assert runner.execute("select count(*) from fd").rows() == [(15,)]
