"""Telemetry plane: process-wide metrics registry + runtime registries.

- :mod:`metrics` — Counter/Gauge/Distribution with Prometheus text
  exposition (the airlift CounterStat/TimeStat/DistributionStat role).
- :mod:`runtime` — bounded query/task registries feeding the
  ``system.runtime`` connector (connectors/system.py).
"""
