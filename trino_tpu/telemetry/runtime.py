"""Process-wide query/task registries: the system.runtime feed.

The miniature of the reference's DispatchManager query tracker +
SqlTaskManager task list that the ``system.runtime`` connector reads
(connector/system/RuntimeQueriesSystemTable / RuntimeTasksSystemTable
role): bounded deques of live + recently-finished query/task records,
updated by ``runner.run_with_query_events`` and the task execution paths,
queryable in SQL via connectors/system.py.

The registries are process-global on purpose: any runner in the process
(standalone, distributed, server dispatcher) lands in one timeline, and a
query against ``system.runtime.queries`` sees itself RUNNING — the engine
dogfooding its own scan path.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import deque
from typing import Optional

__all__ = [
    "QueryRecord", "TaskRecord", "query_started", "query_finished",
    "current_record", "add_input", "add_retries", "add_adaptive",
    "task_started",
    "task_finished", "queries", "tasks", "fingerprint",
]


def fingerprint(sql: str) -> str:
    """Whitespace/case-normalized SQL hash: the plan-fingerprint key the
    memory-aware admission path uses to find prior runs of the same
    statement (execution/resource_manager.py estimate_peak_memory)."""
    norm = " ".join(sql.strip().lower().split())
    return hashlib.sha1(norm.encode("utf-8")).hexdigest()[:16]


class QueryRecord:
    __slots__ = ("query_id", "sql", "user", "state", "create_time",
                 "end_time", "wall_ms", "cpu_ms", "output_rows", "error",
                 "input_rows", "input_bytes", "retry_count",
                 "peak_memory_bytes", "fingerprint", "queued_ms",
                 "resource_group", "speculative_wins", "adaptive_decisions",
                 "_lock")

    def __init__(self, query_id: str, sql: str, user: str):
        self.query_id = query_id
        self.sql = sql
        self.user = user
        self.state = "RUNNING"
        self.create_time = time.time()
        self.end_time: Optional[float] = None
        self.wall_ms = 0.0
        self.cpu_ms = 0.0
        self.output_rows = -1
        self.error: Optional[str] = None
        self.input_rows = 0
        self.input_bytes = 0
        self.retry_count = 0
        self.peak_memory_bytes = 0
        self.fingerprint = fingerprint(sql)
        self.queued_ms = 0.0
        self.resource_group = ""
        self.speculative_wins = 0
        # compact "kind[site]=choice" list, comma-joined — the
        # system.runtime.queries adaptive_decisions column
        self.adaptive_decisions = ""
        self._lock = threading.Lock()


class TaskRecord:
    __slots__ = ("query_id", "task_id", "fragment", "task_index", "worker",
                 "state", "create_time", "wall_ms", "error")

    def __init__(self, query_id: str, task_id: str, fragment: int,
                 task_index: int, worker: str):
        self.query_id = query_id
        self.task_id = task_id
        self.fragment = fragment
        self.task_index = task_index
        self.worker = worker
        self.state = "RUNNING"
        self.create_time = time.time()
        self.wall_ms = 0.0
        self.error: Optional[str] = None


_LOCK = threading.Lock()
_QUERIES: deque = deque(maxlen=512)
_TASKS: deque = deque(maxlen=2048)
_CURRENT = threading.local()


def query_started(query_id: str, sql: str, user: str) -> QueryRecord:
    rec = QueryRecord(query_id, sql, user)
    with _LOCK:
        _QUERIES.append(rec)
    _CURRENT.record = rec
    return rec


def query_finished(rec: QueryRecord, state: str, wall_ms: float,
                   cpu_ms: float, output_rows: int,
                   error: Optional[str] = None,
                   peak_memory_bytes: int = 0) -> None:
    rec.state = state
    rec.end_time = time.time()
    rec.wall_ms = wall_ms
    rec.cpu_ms = cpu_ms
    rec.output_rows = output_rows
    rec.error = error
    rec.peak_memory_bytes = peak_memory_bytes
    if getattr(_CURRENT, "record", None) is rec:
        _CURRENT.record = None


def current_record() -> Optional[QueryRecord]:
    """The query record of the query running on THIS thread (set between
    query_started and query_finished by run_with_query_events)."""
    return getattr(_CURRENT, "record", None)


def add_input(rec: Optional[QueryRecord], rows: int, nbytes: int) -> None:
    """Credit scanned input to a query record; task threads call this with
    the record captured on the query thread, so it takes the record lock."""
    if rec is None or (not rows and not nbytes):
        return
    with rec._lock:
        rec.input_rows += int(rows)
        rec.input_bytes += int(nbytes)


def add_retries(rec: Optional[QueryRecord], n: int) -> None:
    if rec is None or not n:
        return
    with rec._lock:
        rec.retry_count += int(n)


def add_adaptive(rec: Optional[QueryRecord], decision: str) -> None:
    """Append one adaptive-execution decision tag to the query record."""
    if rec is None or not decision:
        return
    with rec._lock:
        rec.adaptive_decisions = (
            decision if not rec.adaptive_decisions
            else rec.adaptive_decisions + "," + decision)


def task_started(query_id: str, task_id: str, fragment: int,
                 task_index: int, worker: str) -> TaskRecord:
    rec = TaskRecord(query_id, task_id, fragment, task_index, worker)
    with _LOCK:
        _TASKS.append(rec)
    return rec


def task_finished(rec: TaskRecord, state: str,
                  error: Optional[str] = None) -> None:
    rec.state = state
    rec.error = error
    rec.wall_ms = (time.time() - rec.create_time) * 1e3


def queries() -> list:
    with _LOCK:
        return list(_QUERIES)


def tasks() -> list:
    with _LOCK:
        return list(_TASKS)
