"""Query flight recorder: per-thread lock-free timeline profiler.

The device-timeline half of the observability plane (the other half is
telemetry/journal.py): every driver thread owns a fixed-capacity ring of
timestamped events — operator enter/exit (exec/driver.py), batch staged
(exec/prefetch.py DeviceStager), fused-region enter/exit
(execution/stage_compiler.py), exchange/collective waits
(execution/exchange.py, remote.py, collective_exchange.py), spill/revoke
(exec/spill.py) and speculation gates (execution/speculation.py).
Recording is one ``time.time()`` call plus a tuple store into the ring —
no contended locks, no device syncs — so the default level keeps the
SyncGuard zero-hot-sync invariant (tests/test_profiler.py asserts it).

Levels (``TRINO_TPU_PROFILE``):

- ``off``/``0``  — recording disabled entirely.
- ``default``/``1`` (unset) — timestamped wall-time events.  Because the
  exec hot path dispatches asynchronously, an operator event at this level
  credits *dispatch* wall time (exactly like OperatorStats).
- ``full``/``2`` — additionally brackets operator regions with
  ``jax.block_until_ready`` on the produced batch, so the event duration is
  true device time.  This deliberately syncs (counted via SyncGuard under
  the ``profiler.full`` tag) and is opt-in for exactly that reason.

Rings are thread-local; a thread's current (query_id, task_id) context is
stamped onto every event it records, so one worker serving tasks of many
queries still attributes correctly.  Finished queries are *harvested* into
a bounded per-query store, which also accepts remote events shipped back
from worker processes in task status JSON; ``chrome_trace()`` renders the
merged coordinator+worker timeline as Chrome ``trace_event`` JSON
(viewable in Perfetto / chrome://tracing), with real OS pids separating
the processes.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from collections import OrderedDict
from typing import Optional

__all__ = [
    "OPERATOR", "FUSED", "RESIDENT", "EXCHANGE", "STAGE", "SPILL",
    "SPECULATION", "TASK", "ADAPTIVE", "RECOVERY",
    "level", "enabled", "is_full", "set_level", "event", "instant",
    "now", "set_context", "capture_context", "apply_context", "sync_batch",
    "collect", "harvest", "add_remote_events", "take_task_events",
    "events_for", "chrome_trace", "reset_for_test",
]

# event kinds (the ``cat`` field of the chrome trace)
OPERATOR = "operator"
FUSED = "fused-region"
RESIDENT = "resident-plan"  # trino.resident.* whole-plan program track
EXCHANGE = "exchange-wait"
STAGE = "batch-staged"
SPILL = "spill"
SPECULATION = "speculation"
TASK = "task"
ADAPTIVE = "adaptive"
RECOVERY = "recovery"

_OFF, _DEFAULT, _FULL = 0, 1, 2


def _level_from_env() -> int:
    v = os.environ.get("TRINO_TPU_PROFILE", "").strip().lower()
    if v in ("off", "0", "none", "false"):
        return _OFF
    if v in ("full", "2"):
        return _FULL
    return _DEFAULT


_LEVEL = _level_from_env()
_CAP = int(os.environ.get("TRINO_TPU_PROFILE_RING", "4096"))
_MAX_RINGS = 512       # dead-thread rings retained beyond this are pruned
_MAX_PROFILES = 64     # finished-query profiles retained


def level() -> int:
    return _LEVEL


def enabled() -> bool:
    return _LEVEL > _OFF


def is_full() -> bool:
    return _LEVEL >= _FULL


def set_level(lvl: Optional[int]) -> int:
    """Override the profiling level (None re-reads the env); returns the
    previous level so tests can restore it."""
    global _LEVEL
    prev = _LEVEL
    _LEVEL = _level_from_env() if lvl is None else int(lvl)
    return prev


class _Ring:
    """One thread's event ring.  Append is an index store under the GIL —
    no lock; the registry lock is taken once, at ring creation."""

    __slots__ = ("buf", "cap", "idx", "tid", "tname", "thread_ref",
                 "qid", "task", "overwrites")

    def __init__(self, cap: int):
        t = threading.current_thread()
        self.buf: list = []
        self.cap = cap
        self.idx = 0
        self.tid = t.ident or 0
        self.tname = t.name
        self.thread_ref = weakref.ref(t)
        self.qid = ""
        self.task = ""
        self.overwrites = 0

    def push(self, ev: tuple) -> None:
        if len(self.buf) < self.cap:
            self.buf.append(ev)
        else:
            self.buf[self.idx % self.cap] = ev
            self.overwrites += 1
        self.idx += 1


_RINGS: list[_Ring] = []
_RINGS_LOCK = threading.Lock()
_TLS = threading.local()

_PROFILES: "OrderedDict[str, dict]" = OrderedDict()
_PROFILES_LOCK = threading.Lock()


def _ring() -> _Ring:
    r = getattr(_TLS, "ring", None)
    if r is None:
        r = _Ring(_CAP)
        with _RINGS_LOCK:
            _RINGS.append(r)
            if len(_RINGS) > _MAX_RINGS:
                # prune oldest dead-thread rings; live threads always stay
                live = [x for x in _RINGS
                        if (t := x.thread_ref()) is not None and t.is_alive()]
                dead = [x for x in _RINGS if x not in live]
                _RINGS[:] = dead[-(_MAX_RINGS - len(live)):] + live \
                    if len(live) < _MAX_RINGS else live
        _TLS.ring = r
    return r


def now() -> float:
    """Event timebase: epoch seconds (``time.time``) — unlike perf_counter
    it is comparable across coordinator and worker processes on one host,
    which is what lets the merged timeline stitch without offset games."""
    return time.time()


def event(kind: str, name: str, t0: float, t1: Optional[float] = None,
          **args) -> None:
    """Record one complete (begin+duration) event on this thread's ring."""
    if not _LEVEL:
        return
    r = _ring()
    if t1 is None:
        t1 = time.time()
    r.push((t0, t1 - t0, kind, name, r.qid, r.task, args or None))


def instant(kind: str, name: str, **args) -> None:
    if not _LEVEL:
        return
    r = _ring()
    r.push((time.time(), 0.0, kind, name, r.qid, r.task, args or None))


def set_context(query_id: str, task_id: str = "") -> tuple:
    """Stamp the calling thread's (query, task) identity onto subsequent
    events; returns the previous context for restore."""
    r = _ring()
    prev = (r.qid, r.task)
    r.qid, r.task = query_id or "", task_id or ""
    return prev


def capture_context() -> tuple:
    r = getattr(_TLS, "ring", None)
    return (r.qid, r.task) if r is not None else ("", "")


def apply_context(ctx: tuple) -> None:
    """Adopt a context captured on another thread (driver group threads
    inherit the spawning task thread's identity)."""
    r = _ring()
    r.qid, r.task = ctx


def sync_batch(batch) -> None:
    """``TRINO_TPU_PROFILE=full`` only: block until the batch's device
    buffers are ready so the enclosing operator event charges true device
    time instead of async dispatch time.  Deliberately a blocking sync —
    counted through SyncGuard so the cost stays attributed."""
    if _LEVEL < _FULL or batch is None:
        return
    try:
        import jax

        from ..exec import syncguard as SG

        for c in getattr(batch, "columns", ()):
            data = getattr(c, "data", None)
            if data is not None and not hasattr(data, "ctypes"):
                SG.count_sync("profiler.full", blocking=True)
                jax.block_until_ready(data)  # sync-ok: opt-in full profile
    except Exception:  # noqa: BLE001 — profiling never fails a query
        pass


# ------------------------------------------------------------------ export


def _ev_dict(ev: tuple, pid: int, tid: int, tname: str) -> dict:
    d = {"ts": ev[0], "dur": ev[1], "kind": ev[2], "name": ev[3],
         "task": ev[5], "pid": pid, "tid": tid, "thread": tname}
    if ev[6]:
        d["args"] = ev[6]
    return d


def collect(query_id: str, task_id: Optional[str] = None) -> list[dict]:
    """Non-destructive sweep of every ring for one query's events (rings
    keep their contents; wrap-around is the only eviction)."""
    with _RINGS_LOCK:
        rings = list(_RINGS)
    pid = os.getpid()
    out = []
    for r in rings:
        for ev in list(r.buf):
            if ev is not None and ev[4] == query_id and \
                    (task_id is None or ev[5] == task_id):
                out.append(_ev_dict(ev, pid, r.tid, r.tname))
    return out


def _store(query_id: str) -> dict:
    p = _PROFILES.get(query_id)
    if p is None:
        p = {"events": [], "procs": {}}
        _PROFILES[query_id] = p
        while len(_PROFILES) > _MAX_PROFILES:
            _PROFILES.popitem(last=False)
    else:
        _PROFILES.move_to_end(query_id)
    return p


def harvest(query_id: str, process_name: str = "coordinator") -> int:
    """Copy this process's ring events for ``query_id`` into the bounded
    per-query store (run at query completion, before rings wrap)."""
    if not query_id:
        return 0
    evs = collect(query_id)
    overwrites = 0
    with _RINGS_LOCK:
        for r in _RINGS:
            overwrites += r.overwrites
            r.overwrites = 0
    from . import metrics as tm

    if evs:
        tm.PROFILE_EVENTS.inc(len(evs))
    if overwrites:
        tm.PROFILE_DROPPED.inc(overwrites)
    with _PROFILES_LOCK:
        p = _store(query_id)
        p["events"].extend(evs)
        p["procs"][str(os.getpid())] = process_name
    return len(evs)


def add_remote_events(query_id: str, events: list[dict],
                      process_name: str = "worker") -> None:
    """Fold a worker ring (shipped back in task status JSON) into the
    query's profile; events already carry the worker's pid/tid."""
    if not query_id or not events:
        return
    with _PROFILES_LOCK:
        p = _store(query_id)
        p["events"].extend(events)
        for ev in events:
            pid = str(ev.get("pid", ""))
            if pid and pid not in p["procs"]:
                p["procs"][pid] = process_name


def take_task_events(query_id: str, task_id: str,
                     limit: int = 2000) -> list[dict]:
    """A worker task's events, bounded for the status-JSON wire (newest
    kept — the tail of a truncated timeline is where failures live)."""
    evs = collect(query_id, task_id)
    evs.sort(key=lambda e: e["ts"])
    return evs[-limit:]


def events_for(query_id: str) -> list[dict]:
    with _PROFILES_LOCK:
        p = _PROFILES.get(query_id)
        stored = list(p["events"]) if p is not None else []
        procs = dict(p["procs"]) if p is not None else {}
    if not stored:
        # live query: render straight from the rings
        stored = collect(query_id)
        if stored:
            procs[str(os.getpid())] = "coordinator"
    return stored


def chrome_trace(query_id: str) -> Optional[dict]:
    """The merged timeline as Chrome ``trace_event`` JSON ("X" complete
    events, microsecond timestamps normalized to the query's first event),
    or None for an unknown/unprofiled query."""
    with _PROFILES_LOCK:
        p = _PROFILES.get(query_id)
        events = list(p["events"]) if p is not None else []
        procs = dict(p["procs"]) if p is not None else {}
    if not events:
        events = collect(query_id)
        if events:
            procs[str(os.getpid())] = "coordinator"
    if not events:
        return None
    t0 = min(e["ts"] for e in events)
    trace: list[dict] = []
    seen_procs: dict = {}
    seen_threads: set = set()
    for e in sorted(events, key=lambda e: e["ts"]):
        pid = int(e.get("pid", 0))
        tid = int(e.get("tid", 0))
        if pid not in seen_procs:
            name = procs.get(str(pid), "process")
            seen_procs[pid] = name
            trace.append({"ph": "M", "name": "process_name", "pid": pid,
                          "tid": 0, "args": {"name": name}})
        if (pid, tid) not in seen_threads:
            seen_threads.add((pid, tid))
            trace.append({"ph": "M", "name": "thread_name", "pid": pid,
                          "tid": tid,
                          "args": {"name": e.get("thread", str(tid))}})
        out = {"name": e["name"], "cat": e["kind"], "ph": "X",
               "ts": (e["ts"] - t0) * 1e6, "dur": max(e["dur"], 0.0) * 1e6,
               "pid": pid, "tid": tid}
        args = dict(e.get("args") or {})
        if e.get("task"):
            args["task"] = e["task"]
        if args:
            out["args"] = args
        trace.append(out)
    return {"traceEvents": trace, "displayTimeUnit": "ms",
            "otherData": {"query_id": query_id,
                          "processes": {str(k): v
                                        for k, v in seen_procs.items()}}}


def reset_for_test() -> None:
    """Drop all rings, contexts and stored profiles (test isolation)."""
    global _RINGS
    with _RINGS_LOCK:
        _RINGS = []
    with _PROFILES_LOCK:
        _PROFILES.clear()
    _TLS.__dict__.clear()
