"""Durable query journal: rotating JSONL of query lifecycle events.

The persistence half of the flight recorder (the timeline half is
telemetry/profiler.py): a ``QueryJournal`` is an ``EventListener`` plugin
that appends one JSON line per QueryCreated/QueryCompleted event — the
full QueryStats rollup, plan fingerprint, resource group and error code —
to a size-bounded, rotating journal file.  The reference persists the same
record through its event-listener plugins (mysql-event-listener /
http-event-listener); here the sink is local disk because the journal is
also *read back*:

- ``system.runtime.query_history`` (connectors/system.py) scans it through
  the ordinary Connector SPI, so pre-restart queries stay SQL-queryable;
- ``resource_manager.estimate_peak_memory`` falls back to
  :func:`seeded_peak` when the in-process registry has no history for a
  plan fingerprint, turning the PR 8 admission estimator from per-process
  folklore into memory that survives coordinator restarts.

Knobs: ``TRINO_TPU_JOURNAL_DIR`` (location; default a per-uid tempdir),
``TRINO_TPU_JOURNAL_MAX_BYTES`` (rotate threshold per file, default 4 MiB),
``TRINO_TPU_JOURNAL_FILES`` (rotated generations kept, default 3),
``TRINO_TPU_JOURNAL=0`` (disable).  Every record carries a versioned
``schema`` field; tools/lint_journal_schema.py enforces the contract.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Optional

from ..spi.eventlistener import (
    EventListener,
    QueryCompletedEvent,
    QueryCreatedEvent,
)

__all__ = [
    "SCHEMA_VERSION", "REQUIRED_FIELDS", "PLAN_STATS_FIELDS", "QueryJournal",
    "default_dir", "journal_enabled", "get_journal", "history", "seeded_peak",
    "sample_records", "reset_for_test",
]

# v2: adds the per-query ``plan_stats`` event — observed per-plan-node
# stats (rows/bytes/groups/skew keyed by logical node fingerprint) that
# planner/history.py feeds back into the cost model on the next planning
# of the same query shape
SCHEMA_VERSION = 2
# every journal record, of any event type, carries at least these
REQUIRED_FIELDS = ("schema", "event", "ts", "query_id")

# the scalar stats a plan_stats node entry may carry (all optional)
PLAN_STATS_FIELDS = ("rows", "bytes", "groups", "skew")

_FILE = "query_journal.jsonl"


def _safe_node(node: str) -> str:
    return "".join(c if c.isalnum() or c in "_.-" else "_" for c in node)


def default_dir() -> str:
    try:
        uid = os.getuid()
    except AttributeError:  # non-POSIX
        uid = 0
    return os.path.join(tempfile.gettempdir(), f"trino-tpu-journal-{uid}")


def journal_enabled() -> bool:
    return os.environ.get("TRINO_TPU_JOURNAL", "1").strip().lower() \
        not in ("0", "off", "false", "no")


def _record_from_created(ev: QueryCreatedEvent) -> dict:
    return {
        "schema": SCHEMA_VERSION,
        "event": "query_created",
        "ts": ev.create_time,
        "query_id": ev.query_id,
        "sql": ev.sql,
        "user": ev.user,
    }


def _record_from_completed(ev: QueryCompletedEvent) -> dict:
    from . import runtime as rt

    return {
        "schema": SCHEMA_VERSION,
        "event": "query_completed",
        "ts": ev.end_time,
        "query_id": ev.query_id,
        "sql": ev.sql,
        "user": ev.user,
        "state": ev.state,
        "wall_ms": float(ev.wall_ms),
        "cpu_ms": float(ev.cpu_ms),
        "output_rows": int(ev.output_rows),
        "input_rows": int(ev.input_rows),
        "input_bytes": int(ev.input_bytes),
        "retry_count": int(ev.retry_count),
        "peak_memory_bytes": int(ev.peak_memory_bytes),
        "queued_time_ms": float(ev.queued_time_ms),
        "resource_group": ev.resource_group,
        "speculative_wins": int(ev.speculative_wins),
        "error": None if ev.error is None else str(ev.error),
        "error_code": ev.error_code,
        "fingerprint": rt.fingerprint(ev.sql),
    }


def _record_plan_stats(query_id: str, fingerprint: str,
                       nodes: dict, ts: float) -> dict:
    """``nodes`` maps logical plan-node fingerprint (planner/history.py)
    -> {rows, bytes, groups, skew} (each scalar optional)."""
    return {
        "schema": SCHEMA_VERSION,
        "event": "plan_stats",
        "ts": ts,
        "query_id": query_id,
        "fingerprint": fingerprint,
        "nodes": nodes,
    }


def sample_records() -> list[dict]:
    """One representative record per event type the journal can emit —
    the corpus tools/lint_journal_schema.py validates."""
    created = _record_from_created(
        QueryCreatedEvent("q_sample", "SELECT 1", user="lint"))
    ok = _record_from_completed(QueryCompletedEvent(
        "q_sample", "SELECT 1", state="FINISHED", user="lint",
        wall_ms=1.5, output_rows=1, cpu_ms=0.5, peak_memory_bytes=1 << 20,
        input_rows=10, input_bytes=100, retry_count=0, queued_time_ms=0.25,
        resource_group="global.adhoc", speculative_wins=1))
    failed = _record_from_completed(QueryCompletedEvent(
        "q_sample2", "SELECT 1/0", state="FAILED", user="lint",
        error="DIVISION_BY_ZERO: division by zero",
        error_code="DIVISION_BY_ZERO"))
    blacklist = {
        "schema": SCHEMA_VERSION,
        "event": "blacklist_entry",
        "ts": 1700000000.0,
        "query_id": "q_sample2",
        "worker": "worker-1",
        "weight": 1.0,
        "reason": "INTERNAL: injected task failure",
    }
    plan_stats = _record_plan_stats(
        "q_sample", "a2f1c3d4",
        {"e3b0c442": {"rows": 450000, "bytes": 7340032, "skew": 1.25},
         "9f86d081": {"rows": 45000, "bytes": 524288},
         "31b2e8c0": {"groups": 1024}},
        ts=1700000000.0)
    return [created, ok, failed, blacklist, plan_stats]


class QueryJournal(EventListener):
    """Size-bounded rotating JSONL sink + reader."""

    def __init__(self, directory: Optional[str] = None,
                 max_bytes: Optional[int] = None,
                 max_files: Optional[int] = None):
        self.directory = directory or \
            os.environ.get("TRINO_TPU_JOURNAL_DIR") or default_dir()
        self.max_bytes = max_bytes if max_bytes is not None else int(
            os.environ.get("TRINO_TPU_JOURNAL_MAX_BYTES", str(4 << 20)))
        self.max_files = max_files if max_files is not None else int(
            os.environ.get("TRINO_TPU_JOURNAL_FILES", "3"))
        # a coordinator fleet shares one TRINO_TPU_JOURNAL_DIR: each member
        # appends to its OWN stream (cross-process appends to one file would
        # race its rotation) and readers fold every member's stream
        node = os.environ.get("TRINO_TPU_HA_NODE_ID", "").strip()
        name = _FILE if not node else \
            _FILE[:-len(".jsonl")] + "-" + _safe_node(node) + ".jsonl"
        self.path = os.path.join(self.directory, name)
        self._lock = threading.Lock()
        # first write of this process checks for a torn tail line (a crash
        # mid-write); appending straight onto it would corrupt the next
        # record too, so a newline is inserted first
        self._tail_checked = False

    # ------------------------------------------------------- listener side
    def query_created(self, event: QueryCreatedEvent) -> None:
        self._write(_record_from_created(event))

    def query_completed(self, event: QueryCompletedEvent) -> None:
        self._write(_record_from_completed(event))

    def plan_stats(self, query_id: str, fingerprint: str,
                   nodes: dict, ts: float) -> None:
        """Append one observed-plan-stats record (history-based
        optimization feed; planner/history.py is both writer and reader)."""
        self._write(_record_plan_stats(query_id, fingerprint, nodes, ts))

    def _write(self, rec: dict) -> None:
        from . import metrics as tm

        line = json.dumps(rec, default=str) + "\n"
        data = line.encode("utf-8")
        with self._lock:
            os.makedirs(self.directory, exist_ok=True)
            try:
                size = os.path.getsize(self.path)
            except OSError:
                size = 0
            if not self._tail_checked:
                self._tail_checked = True
                if size:
                    with open(self.path, "rb") as f:
                        f.seek(-1, os.SEEK_END)
                        if f.read(1) != b"\n":
                            line = "\n" + line
                            data = line.encode("utf-8")
            if size and size + len(data) > self.max_bytes:
                self._rotate()
            with open(self.path, "a", encoding="utf-8") as f:
                f.write(line)
        tm.JOURNAL_RECORDS.inc()
        tm.JOURNAL_BYTES.inc(len(data))

    def _rotate(self) -> None:
        """journal.jsonl -> .1 -> .2 ... -> .max_files (dropped)."""
        from . import metrics as tm

        oldest = f"{self.path}.{self.max_files}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(self.max_files - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        os.replace(self.path, f"{self.path}.1")
        tm.JOURNAL_ROTATIONS.inc()

    # --------------------------------------------------------- reader side
    def files(self) -> list[str]:
        """This member's journal files oldest-first (rotated generations
        then current)."""
        out = [f"{self.path}.{i}" for i in range(self.max_files, 0, -1)]
        out.append(self.path)
        return [p for p in out if os.path.exists(p)]

    def fleet_files(self) -> list[str]:
        """Every fleet member's journal files under the shared directory,
        oldest-first per stream, streams in name order — the READ set.  In
        a single-coordinator deployment this is exactly :meth:`files`; in a
        fleet it additionally folds the sibling ``query_journal-*`` streams
        other coordinators rotate, so journal-seeded admission estimates
        and ``system.runtime.query_history`` see the whole fleet's memory,
        not just the local rotation set."""
        stem = _FILE[:-len(".jsonl")]
        streams: dict[str, list[tuple[int, str]]] = {}
        try:
            names = os.listdir(self.directory)
        except OSError:
            return self.files()
        for name in names:
            if not name.startswith(stem):
                continue
            base, gen = name, 0
            if ".jsonl." in name:
                base, _, suffix = name.rpartition(".")
                if not suffix.isdigit():
                    continue
                gen = int(suffix)
            if not base.endswith(".jsonl"):
                continue
            streams.setdefault(base, []).append(
                (gen, os.path.join(self.directory, name)))
        out = []
        for base in sorted(streams):
            # oldest generation first (highest .N), current (gen 0) last
            for _gen, path in sorted(streams[base], reverse=True):
                out.append(path)
        return out or self.files()

    def read(self, events: Optional[tuple] = None) -> list[dict]:
        """Every parseable record, oldest-first; a torn tail line (crash
        mid-write) is skipped, not fatal — the journal must be readable
        after any kill."""
        out: list[dict] = []
        for path in self.fleet_files():
            try:
                with open(path, encoding="utf-8") as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                        except ValueError:
                            continue
                        if not isinstance(rec, dict) or "schema" not in rec:
                            continue
                        if events is None or rec.get("event") in events:
                            out.append(rec)
            except OSError:
                continue
        return out


# ------------------------------------------------------------ process state

_SINGLETON: Optional[QueryJournal] = None
_SINGLETON_LOCK = threading.Lock()
# fingerprint → [peaks] seed map, keyed by the journal file-set signature
# it was built from: (sig, cache).  Rebuilt only when a journal file
# appears/rotates/grows — an admission decision costs a stat() per file,
# not a full re-read
_SEED_CACHE: Optional[tuple] = None
_SEED_LOCK = threading.Lock()


def _journal_signature(j: QueryJournal) -> tuple:
    # the FLEET file set: a peer coordinator's append or rotation must
    # invalidate the admission seed cache exactly like a local one
    sig = []
    for path in j.fleet_files():
        try:
            st = os.stat(path)
        except OSError:
            continue
        sig.append((path, st.st_size, st.st_mtime_ns))
    return tuple(sig)


def get_journal() -> Optional[QueryJournal]:
    """The process-wide journal (one file lock, shared by every runner in
    the process), or None when disabled via TRINO_TPU_JOURNAL=0."""
    global _SINGLETON
    if not journal_enabled():
        return None
    with _SINGLETON_LOCK:
        if _SINGLETON is None:
            _SINGLETON = QueryJournal()
        return _SINGLETON


def history() -> list[dict]:
    """Completed-query records from disk, oldest-first — the
    system.runtime.query_history feed (always re-read: restarts and other
    coordinator processes may have appended)."""
    j = get_journal()
    if j is None:
        return []
    return j.read(events=("query_completed",))


def seeded_peak(fp: str, history_len: int = 5) -> int:
    """Journal-seeded admission estimate: max peak of the fingerprint's
    most recent FINISHED runs on disk, 0 when unknown.  The seed map is
    memoized on the journal file-set signature (path, size, mtime), so
    steady-state admission does a handful of stat() calls and re-reads the
    files only when another coordinator appended or a rotation happened."""
    global _SEED_CACHE
    j = get_journal()
    if j is None:
        return 0
    with _SEED_LOCK:
        sig = _journal_signature(j)
        if _SEED_CACHE is None or _SEED_CACHE[0] != sig:
            cache: dict[str, list[int]] = {}
            for rec in j.read(events=("query_completed",)):
                if rec.get("state") != "FINISHED":
                    continue
                peak = int(rec.get("peak_memory_bytes", 0) or 0)
                if peak <= 0:
                    continue
                cache.setdefault(rec.get("fingerprint", ""), []).append(peak)
            _SEED_CACHE = (sig, cache)
        peaks = _SEED_CACHE[1].get(fp)
    if not peaks:
        return 0
    return max(peaks[-history_len:])


def reset_for_test() -> None:
    """Forget the singleton and the seed cache — the in-process stand-in
    for a coordinator restart (env changes take effect on next use)."""
    global _SINGLETON, _SEED_CACHE
    with _SINGLETON_LOCK:
        _SINGLETON = None
    _SEED_CACHE = None
