"""Process-wide metrics registry with Prometheus text exposition.

The airlift stats plane in miniature (reference: airlift ``CounterStat`` /
``TimeStat`` / ``DistributionStat`` exported per process and scraped over
HTTP): a singleton :data:`REGISTRY` of named metrics, rendered as Prometheus
text exposition format by ``GET /v1/metrics`` on both the coordinator
(server/protocol.py) and every worker (execution/worker.py).

Hot-path contract: *recording never takes a device sync or a contended
lock*.  Counters and distributions write to per-thread cells — the only
lock is taken once per (thread, metric) pair at cell creation, and again
only at snapshot/render time to sum the cells.  Gauges are a single
attribute store.  Nothing here touches jax arrays, so the SyncGuard
accounting (exec/syncguard.py) is structurally unaffected.

Distributions use fixed log-spaced buckets (``lo * growth**i``), merge by
bucket-count addition (cross-thread and, via :meth:`Distribution.merge`,
cross-process), and estimate p50/p90/p99 by linear interpolation inside
the winning bucket — the fixed-bucket ``DistributionStat`` role.

Metric naming scheme (enforced here at registration AND by
tools/lint_metric_names.py at the source level): Prometheus-legal
``[a-zA-Z_:][a-zA-Z0-9_:]*``, mandatory ``trino_`` prefix, counters end in
``_total``, distributions carry a unit suffix (``_seconds``).
"""

from __future__ import annotations

import bisect
import re
import threading
import weakref
from typing import Optional

__all__ = [
    "Counter", "Gauge", "Distribution", "MetricsRegistry", "REGISTRY",
    "observe_scan", "observe_sync", "observe_resilience", "observe_fused",
    "observe_resident", "observe_exchange", "observe_adaptive",
    "observe_encoding",
    "update_device_memory_watermark",
]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
METRIC_PREFIX = "trino_"


def _validate_name(name: str, kind: str) -> None:
    if not _NAME_RE.match(name):
        raise ValueError(f"metric name not Prometheus-legal: {name!r}")
    if not name.startswith(METRIC_PREFIX):
        raise ValueError(
            f"metric name missing the {METRIC_PREFIX!r} prefix: {name!r}")
    if kind == "counter" and not name.endswith("_total"):
        raise ValueError(f"counter name must end in '_total': {name!r}")


def _fmt(v) -> str:
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return f"{v:.10g}"


class _Cell:
    """One thread's private accumulator; folded into a retired total once
    the owning thread dies (task threads are per-query, so cells must not
    accumulate over the process lifetime)."""

    __slots__ = ("value", "thread_ref")

    def __init__(self):
        self.value = 0
        self.thread_ref = weakref.ref(threading.current_thread())


class Counter:
    """Monotonic counter; ``inc`` is a thread-local add (no contended lock,
    no device sync)."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._local = threading.local()
        self._cells: list[_Cell] = []
        self._retired = 0
        self._lock = threading.Lock()

    def inc(self, amount=1) -> None:
        cell = getattr(self._local, "cell", None)
        if cell is None:
            cell = _Cell()
            with self._lock:
                self._cells.append(cell)
            self._local.cell = cell
        cell.value += amount

    def value(self):
        with self._lock:
            live = []
            for c in self._cells:
                t = c.thread_ref()
                if t is None or not t.is_alive():
                    self._retired += c.value  # dead thread: fold and drop
                else:
                    live.append(c)
            self._cells = live
            return self._retired + sum(c.value for c in live)

    def snapshot(self) -> dict:
        return {"kind": "counter", "value": self.value()}

    def render(self) -> list[str]:
        return [f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} counter",
                f"{self.name} {_fmt(self.value())}"]


class Gauge:
    """Last-write-wins instantaneous value; ``set`` is one attribute store
    (atomic under the GIL)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0

    def set(self, value) -> None:
        self._value = value

    def value(self):
        return self._value

    def snapshot(self) -> dict:
        return {"kind": "gauge", "value": self._value}

    def render(self) -> list[str]:
        return [f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} gauge",
                f"{self.name} {_fmt(self._value)}"]


class _DistCell:
    __slots__ = ("buckets", "sum", "count", "min", "max", "thread_ref")

    def __init__(self, nbuckets: int):
        self.buckets = [0] * (nbuckets + 1)  # +1: the +Inf overflow bucket
        self.sum = 0.0
        self.count = 0
        self.min = float("inf")
        self.max = float("-inf")
        self.thread_ref = weakref.ref(threading.current_thread())


class Distribution:
    """Mergeable fixed-bucket histogram with log-spaced bounds
    (``lo * growth**i`` for i in [0, buckets)) and interpolated
    p50/p90/p99 estimates; rendered as a Prometheus histogram.

    ``record`` increments a per-thread bucket array via ``bisect`` — no
    lock, no device sync.  ``merge`` folds a foreign ``snapshot()`` dict
    (same bounds) into this instance, so worker-side distributions can be
    rolled up on a coordinator."""

    kind = "distribution"

    def __init__(self, name: str, help: str = "", lo: float = 1e-4,
                 growth: float = 2.0, buckets: int = 30):
        self.name = name
        self.help = help
        self.bounds = [lo * growth ** i for i in range(buckets)]
        self._local = threading.local()
        self._cells: list[_DistCell] = []
        self._merged: Optional[_DistCell] = None  # cross-process roll-ups
        self._lock = threading.Lock()

    def record(self, value) -> None:
        cell = getattr(self._local, "cell", None)
        if cell is None:
            cell = _DistCell(len(self.bounds))
            with self._lock:
                self._cells.append(cell)
            self._local.cell = cell
        cell.buckets[bisect.bisect_left(self.bounds, value)] += 1
        cell.sum += value
        cell.count += 1
        if value < cell.min:
            cell.min = value
        if value > cell.max:
            cell.max = value

    def _fold(self, into: _DistCell, cell) -> None:
        for i, n in enumerate(cell.buckets):
            into.buckets[i] += n
        into.sum += cell.sum
        into.count += cell.count
        into.min = min(into.min, cell.min)
        into.max = max(into.max, cell.max)

    def _total(self) -> _DistCell:
        total = _DistCell(len(self.bounds))
        with self._lock:
            if self._merged is not None:
                self._fold(total, self._merged)
            live = []
            for c in self._cells:
                t = c.thread_ref()
                if t is None or not t.is_alive():
                    if self._merged is None:
                        self._merged = _DistCell(len(self.bounds))
                    self._fold(self._merged, c)
                    self._fold(total, c)
                else:
                    live.append(c)
                    self._fold(total, c)
            self._cells = live
        return total

    def _quantile(self, total: _DistCell, q: float) -> float:
        if total.count == 0:
            return 0.0
        target = q * total.count
        cum = 0
        for i, n in enumerate(total.buckets):
            if n == 0:
                continue
            if cum + n >= target:
                lower = self.bounds[i - 1] if i > 0 else 0.0
                upper = self.bounds[i] if i < len(self.bounds) else total.max
                upper = max(upper, lower)
                frac = (target - cum) / n
                v = lower + (upper - lower) * frac
                # interpolation within a bucket can overshoot the largest
                # observed value (which sits somewhere inside the bucket)
                return min(v, total.max)
            cum += n
        return total.max

    def merge(self, snap: dict) -> None:
        """Fold a foreign ``snapshot()`` (same bucket bounds) into this
        distribution — the cross-process merge path."""
        cell = _DistCell(len(self.bounds))
        cell.buckets = list(snap["buckets"])
        if len(cell.buckets) != len(self.bounds) + 1:
            raise ValueError("bucket layout mismatch in Distribution.merge")
        cell.sum = snap["sum"]
        cell.count = snap["count"]
        cell.min = snap.get("min", float("inf"))
        cell.max = snap.get("max", float("-inf"))
        with self._lock:
            if self._merged is None:
                self._merged = _DistCell(len(self.bounds))
            self._fold(self._merged, cell)

    def snapshot(self) -> dict:
        total = self._total()
        return {
            "kind": "distribution",
            "count": total.count,
            "sum": total.sum,
            "min": total.min if total.count else 0.0,
            "max": total.max if total.count else 0.0,
            "buckets": list(total.buckets),
            "bounds": list(self.bounds),
            "p50": self._quantile(total, 0.50),
            "p90": self._quantile(total, 0.90),
            "p99": self._quantile(total, 0.99),
        }

    def render(self) -> list[str]:
        total = self._total()
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} histogram"]
        cum = 0
        for le, n in zip(self.bounds, total.buckets):
            cum += n
            lines.append(f'{self.name}_bucket{{le="{_fmt(le)}"}} {cum}')
        cum += total.buckets[-1]
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{self.name}_sum {_fmt(total.sum)}")
        lines.append(f"{self.name}_count {total.count}")
        return lines


class MetricsRegistry:
    """Named-metric registry with get-or-create semantics; re-registering a
    name as a different kind raises (one meaning per name, process-wide)."""

    def __init__(self):
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def _get_or_create(self, name: str, cls, help: str, **kwargs):
        _validate_name(name, cls.kind)
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}, "
                        f"not {cls.kind}")
                return m
            m = cls(name, help, **kwargs)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, Counter, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, help)

    def distribution(self, name: str, help: str = "", lo: float = 1e-4,
                     growth: float = 2.0, buckets: int = 30) -> Distribution:
        return self._get_or_create(name, Distribution, help, lo=lo,
                                   growth=growth, buckets=buckets)

    def snapshot(self) -> dict:
        with self._lock:
            items = sorted(self._metrics.items())
        return {name: m.snapshot() for name, m in items}

    def render_prometheus(self) -> str:
        with self._lock:
            items = sorted(self._metrics.items())
        lines: list[str] = []
        for _name, m in items:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


REGISTRY = MetricsRegistry()

# ---------------------------------------------------------------- engine set
# Every engine metric is defined EAGERLY at import so /v1/metrics exposes the
# full vocabulary (at zero) before any traffic — scrapers see a stable set.
# Registrations live ONLY here; tools/lint_metric_names.py enforces that.

# scan ingest (exec/prefetch.py counters rolled up per query)
SCAN_BYTES = REGISTRY.counter(
    "trino_scan_bytes_total", "host bytes produced by connector scans")
SCAN_ROWS = REGISTRY.counter(
    "trino_scan_rows_total", "rows produced by connector scans")
SCAN_BATCHES = REGISTRY.counter(
    "trino_scan_batches_total", "raw connector batches scanned")
SCAN_READ_SECONDS = REGISTRY.counter(
    "trino_scan_read_seconds_total", "time inside connector get_next_batch")
SCAN_WAIT_SECONDS = REGISTRY.counter(
    "trino_scan_consumer_wait_seconds_total",
    "consumer time blocked on scan prefetch")
SCAN_GBPS = REGISTRY.gauge(
    "trino_scan_gb_per_second", "scan ingest GB/s of the last observed query")

# host-sync discipline (exec/syncguard.py deltas)
SYNC_HOST = REGISTRY.counter(
    "trino_exec_host_syncs_total", "device->host scalar materializations")
SYNC_BLOCKING = REGISTRY.counter(
    "trino_exec_blocking_syncs_total", "host syncs that waited on the device")
SYNC_HOT_LOOP = REGISTRY.counter(
    "trino_exec_hot_loop_syncs_total",
    "blocking syncs inside declared hot regions (want: 0)")
EXPAND_OVERFLOWS = REGISTRY.counter(
    "trino_exec_expand_overflows_total",
    "padded-expand capacity overflows detected on device")
EXPAND_RETRIES = REGISTRY.counter(
    "trino_exec_expand_retries_total", "expand re-runs after an overflow")

# resilience (retry_policy=QUERY loop, heartbeats, exchange backoff)
RES_QUERY_RETRIES = REGISTRY.counter(
    "trino_resilience_query_retries_total", "query-level retry attempts")
RES_BACKOFF_WAITS = REGISTRY.counter(
    "trino_resilience_backoff_waits_total", "retry backoff sleeps")
RES_BACKOFF_SECONDS = REGISTRY.counter(
    "trino_resilience_backoff_seconds_total", "total retry backoff time")
RES_BLACKLISTED = REGISTRY.counter(
    "trino_resilience_blacklisted_workers_total",
    "workers blacklisted by the query retry loop")
RES_REPLACEMENTS = REGISTRY.counter(
    "trino_resilience_worker_replacements_total",
    "GONE workers replaced by respawn")
RES_HEARTBEAT_TRANSITIONS = REGISTRY.counter(
    "trino_resilience_heartbeat_transitions_total",
    "worker heartbeat state transitions")
RES_EXCHANGE_FETCH_FAILURES = REGISTRY.counter(
    "trino_resilience_exchange_fetch_failures_total",
    "transient exchange fetch failures")
RES_EXCHANGE_BACKOFF_TRIPS = REGISTRY.counter(
    "trino_resilience_exchange_backoff_trips_total",
    "exchange sources declared failed past the failure-duration budget")

# streaming straggler speculation + graceful drain (execution/speculation.py)
SPECULATIVE_STARTS = REGISTRY.counter(
    "trino_speculative_starts_total",
    "speculative twin tasks launched for streaming stragglers")
SPECULATIVE_WINS = REGISTRY.counter(
    "trino_speculative_wins_total",
    "speculative twins that won the first-commit race")
DRAINS = REGISTRY.counter(
    "trino_drains_total", "coordinator-driven worker drains started")
BLACKLISTED_WORKERS = REGISTRY.gauge(
    "trino_blacklisted_workers",
    "workers currently blacklisted by the cluster blacklist")

# fault-tolerant execution (execution/fte.py + query_state.py + spool_gc.py)
FTE_ATTEMPT_STARTS = REGISTRY.counter(
    "trino_fte_attempt_starts_total", "FTE task attempts started")
FTE_ATTEMPT_RETRIES = REGISTRY.counter(
    "trino_fte_attempt_retries_total",
    "FTE task attempts that were retries of a failed attempt")
FTE_SPECULATIVE_STARTS = REGISTRY.counter(
    "trino_fte_speculative_starts_total",
    "speculative FTE attempt chains launched against stragglers")
FTE_SPECULATIVE_WINS = REGISTRY.counter(
    "trino_fte_speculative_wins_total",
    "speculative FTE attempts that committed first")
FTE_STAGES_RESUMED = REGISTRY.counter(
    "trino_fte_stages_resumed_total",
    "stage tasks skipped on recovery because a prior coordinator "
    "already committed them")
FTE_QUERY_RECOVERIES = REGISTRY.counter(
    "trino_fte_query_recoveries_total",
    "in-flight FTE queries rehydrated from the query-state WAL after "
    "a coordinator restart")
FTE_SPOOL_CORRUPTIONS = REGISTRY.counter(
    "trino_fte_spool_corruptions_total",
    "committed spool attempts discarded on CRC mismatch / torn frames")
FTE_SPOOL_BYTES_LIVE = REGISTRY.gauge(
    "trino_fte_spool_bytes_live",
    "bytes currently retained under leased spool roots")
FTE_SPOOL_BYTES_RECLAIMED = REGISTRY.counter(
    "trino_fte_spool_bytes_reclaimed_total",
    "spool bytes reclaimed by release/TTL/budget/boot-sweep GC")

# whole-stage compilation (execution/stage_compiler.py)
FUSED_STAGES = REGISTRY.counter(
    "trino_fused_stages_total", "fused stage seams executed")
FUSED_BATCHES = REGISTRY.counter(
    "trino_fused_batches_total", "input batches absorbed by fused stages")
FUSED_JIT_CALLS = REGISTRY.counter(
    "trino_fused_jit_calls_total", "fused accumulate-program dispatches")
FUSED_COMPILES = REGISTRY.counter(
    "trino_fused_compiles_total", "distinct (program, bucket) traces")
FUSED_CACHE_HITS = REGISTRY.counter(
    "trino_fused_cache_hits_total",
    "fused dispatches served by an existing trace")
FUSED_MERGES = REGISTRY.counter(
    "trino_fused_seam_merges_total", "fused seam merge programs executed")
FUSED_FALLBACKS = REGISTRY.counter(
    "trino_fused_fallbacks_total",
    "fused-stage overflow fallbacks to the legacy path")
FUSED_COMPILE_SECONDS = REGISTRY.distribution(
    "trino_fused_compile_seconds",
    "wall time of fused-program trace+compile dispatches", lo=1e-3)

# whole-query compilation (execution/plan_compiler.py)
RESIDENT_PLANS = REGISTRY.counter(
    "trino_resident_plans_total", "maximal TPU-resident plans executed")
RESIDENT_PROGRAMS = REGISTRY.counter(
    "trino_resident_programs_total",
    "distinct (resident program, bucket) traces compiled")
RESIDENT_SEAMS = REGISTRY.counter(
    "trino_resident_seams_total",
    "interior exchange edges fused inside resident-plan programs")
RESIDENT_BATCHES = REGISTRY.counter(
    "trino_resident_batches_total",
    "probe batches absorbed by resident-plan programs")
RESIDENT_JIT_CALLS = REGISTRY.counter(
    "trino_resident_jit_calls_total",
    "whole-plan program dispatches (one per probe batch)")
RESIDENT_CODE_SEAMS = REGISTRY.counter(
    "trino_resident_code_seam_columns_total",
    "dictionary-code lanes that crossed an interior seam unmaterialized")
RESIDENT_FALLBACKS = REGISTRY.counter(
    "trino_resident_fallbacks_total",
    "resident-plan overflow/dup-key fallbacks to the legacy path")

# exchange HTTP plane (execution/remote.py HttpExchangeClient + worker serve)
EXCHANGE_BYTES = REGISTRY.counter(
    "trino_exchange_bytes_total", "exchange page bytes moved over HTTP")
EXCHANGE_PAGES = REGISTRY.counter(
    "trino_exchange_pages_total", "exchange pages moved over HTTP")
EXCHANGE_WAIT_SECONDS = REGISTRY.counter(
    "trino_exchange_wait_seconds_total",
    "client time spent inside exchange fetches")

# query/task lifecycle
QUERIES_STARTED = REGISTRY.counter(
    "trino_queries_started_total", "queries entered through a runner")
QUERIES_FINISHED = REGISTRY.counter(
    "trino_queries_finished_total", "queries that reached FINISHED")
QUERIES_FAILED = REGISTRY.counter(
    "trino_queries_failed_total", "queries that reached FAILED")
QUERY_WALL_SECONDS = REGISTRY.distribution(
    "trino_query_wall_seconds", "per-query wall time", lo=1e-3)
TASKS_CREATED = REGISTRY.counter(
    "trino_tasks_created_total", "tasks started (in-process or worker)")
TASKS_FAILED = REGISTRY.counter(
    "trino_tasks_failed_total", "tasks that reached FAILED")
TASK_WALL_SECONDS = REGISTRY.distribution(
    "trino_task_wall_seconds", "per-task wall time", lo=1e-3)
DISPATCHER_QUERIES = REGISTRY.counter(
    "trino_dispatcher_queries_total",
    "statements admitted through the HTTP dispatcher")

# device memory watermark (best-effort; jax CPU backends may not report)
DEVICE_MEMORY_IN_USE = REGISTRY.gauge(
    "trino_device_memory_bytes_in_use", "allocator bytes in use, all devices")
DEVICE_MEMORY_PEAK = REGISTRY.gauge(
    "trino_device_memory_peak_bytes",
    "allocator peak bytes in use, all devices")

# multi-tenant serving plane (execution/resource_manager.py): admission
# wait, the low-memory killer, and the coordinator's cluster memory view
ADMISSION_QUEUED_SECONDS = REGISTRY.distribution(
    "trino_admission_queued_seconds",
    "time queries wait for admission (group slot or cluster memory)")
OOM_KILLS = REGISTRY.counter(
    "trino_oom_kills_total",
    "queries killed by the cluster low-memory killer")
CLUSTER_MEMORY_RESERVED = REGISTRY.gauge(
    "trino_cluster_memory_reserved_bytes",
    "bytes reserved across all tracked query memory pools")
CLUSTER_MEMORY_FREE = REGISTRY.gauge(
    "trino_cluster_memory_free_bytes",
    "cluster memory capacity minus reservations (0 when uncapped)")

# HA control plane (execution/ha.py + server/front_tier.py): coordinator
# fleet leases, lease-based failover, front-tier routing, worker autoscaling
HA_LEASES_HELD = REGISTRY.gauge(
    "trino_ha_leases_held",
    "coordinator leases this process currently holds (its own plus any "
    "claimed from dead peers)")
HA_FLEET_COORDINATORS = REGISTRY.gauge(
    "trino_ha_fleet_coordinators",
    "live coordinators visible in the cluster directory")
HA_TAKEOVERS = REGISTRY.counter(
    "trino_ha_takeovers_total",
    "dead-coordinator WAL directories claimed by this coordinator")
HA_ADOPTED_QUERIES = REGISTRY.counter(
    "trino_ha_adopted_queries_total",
    "in-flight queries adopted from a claimed WAL directory and resumed "
    "under their original ids")
HA_REROUTES = REGISTRY.counter(
    "trino_ha_reroutes_total",
    "front-tier requests rerouted off the hash owner (owner dead or "
    "mid-failover)")
HA_AUTOSCALE_EVENTS = REGISTRY.counter(
    "trino_ha_autoscale_events_total",
    "autoscaler scale-up and drain actions applied to the worker fleet")

# query flight recorder (telemetry/profiler.py + telemetry/journal.py)
PROFILE_EVENTS = REGISTRY.counter("trino_profile_events_total",
                                  "timeline profiler events harvested "
                                  "into query profiles")
PROFILE_DROPPED = REGISTRY.counter("trino_profile_dropped_total",
                                   "profiler ring slots overwritten before "
                                   "harvest (raise TRINO_TPU_PROFILE_RING "
                                   "if nonzero)")
JOURNAL_RECORDS = REGISTRY.counter("trino_journal_records_total",
                                   "query journal records written")
JOURNAL_BYTES = REGISTRY.counter("trino_journal_bytes_total",
                                 "query journal bytes written")
JOURNAL_ROTATIONS = REGISTRY.counter("trino_journal_rotations_total",
                                     "query journal file rotations")

# three-tier cache plane (trino_tpu/caching/): Tier A logical plans,
# Tier B compiled-executable registry, Tier C versioned results
CACHE_PLAN_HITS = REGISTRY.counter(
    "trino_cache_plan_hits_total", "logical-plan cache hits")
CACHE_PLAN_MISSES = REGISTRY.counter(
    "trino_cache_plan_misses_total", "logical-plan cache misses")
CACHE_PLAN_EVICTIONS = REGISTRY.counter(
    "trino_cache_plan_evictions_total", "logical-plan cache LRU evictions")
CACHE_PLAN_INVALIDATIONS = REGISTRY.counter(
    "trino_cache_plan_invalidations_total",
    "logical-plan cache entries dropped by invalidation")
CACHE_PLAN_ENTRIES = REGISTRY.gauge(
    "trino_cache_plan_entries", "logical-plan cache resident entries")
CACHE_EXEC_HITS = REGISTRY.counter(
    "trino_cache_exec_hits_total", "executable-registry memo hits")
CACHE_EXEC_MISSES = REGISTRY.counter(
    "trino_cache_exec_misses_total",
    "executable-registry memo misses (new wrapper instantiated)")
CACHE_EXEC_EVICTIONS = REGISTRY.counter(
    "trino_cache_exec_evictions_total",
    "executable-registry LRU evictions")
CACHE_EXEC_ENTRIES = REGISTRY.gauge(
    "trino_cache_exec_entries",
    "executable-registry resident entries, all caches")
CACHE_RESULT_HITS = REGISTRY.counter(
    "trino_cache_result_hits_total", "versioned result cache hits")
CACHE_RESULT_MISSES = REGISTRY.counter(
    "trino_cache_result_misses_total", "versioned result cache misses")
CACHE_RESULT_EVICTIONS = REGISTRY.counter(
    "trino_cache_result_evictions_total",
    "result cache LRU evictions under the byte budget")
CACHE_RESULT_INVALIDATIONS = REGISTRY.counter(
    "trino_cache_result_invalidations_total",
    "result cache entries dropped by table mutation")
CACHE_RESULT_ENTRIES = REGISTRY.gauge(
    "trino_cache_result_entries", "result cache resident entries")
CACHE_RESULT_BYTES = REGISTRY.gauge(
    "trino_cache_result_bytes", "result cache resident bytes")

# adaptive execution plane (execution/adaptive.py): phased activation,
# runtime join-distribution switching, skew-aware repartitioning
ADAPTIVE_DECISIONS = REGISTRY.counter(
    "trino_adaptive_decisions_total",
    "adaptive decision points evaluated at stage activation barriers")
ADAPTIVE_BROADCAST_FLIPS = REGISTRY.counter(
    "trino_adaptive_flips_to_broadcast_total",
    "partitioned joins flipped to broadcast on observed build size")
ADAPTIVE_PARTITION_FLIPS = REGISTRY.counter(
    "trino_adaptive_flips_to_partitioned_total",
    "broadcast joins flipped to partitioned on observed build size")
ADAPTIVE_SKEW_SPLITS = REGISTRY.counter(
    "trino_adaptive_skew_splits_total",
    "heavy-hitter keys split across multiple probe tasks")
ADAPTIVE_STAGE_ACTIVATIONS = REGISTRY.counter(
    "trino_adaptive_stage_activations_total",
    "stages activated by the phased bottom-up scheduler")
ADAPTIVE_MEMO_HITS = REGISTRY.counter(
    "trino_adaptive_memo_hits_total",
    "adaptive decisions replayed from the runtime-stat-keyed memo")
ADAPTIVE_SKEW_IMBALANCE = REGISTRY.gauge(
    "trino_adaptive_skew_imbalance_ratio",
    "sketch-estimated max partition weight before the last skew split "
    "divided by after; the load-balance win a parallel host realises")


# iterative rule-engine optimizer (planner/iterative/) and history-based
# optimization (planner/history.py): the runtime-truth -> planning loop
OPTIMIZER_RUNS = REGISTRY.counter(
    "trino_optimizer_runs_total",
    "queries planned by the iterative rule-engine optimizer")
OPTIMIZER_RULE_FIRINGS = REGISTRY.counter(
    "trino_optimizer_rule_firings_total",
    "rule firings across all iterative optimizer runs")
OPTIMIZER_PLANNING_MS = REGISTRY.counter(
    "trino_optimizer_planning_ms_total",
    "wall milliseconds spent inside the iterative optimizer phases")
HBO_PLAN_LOOKUPS = REGISTRY.counter(
    "trino_hbo_plan_lookups_total",
    "plan-node fingerprint lookups against the history table at plan time")
HBO_PLAN_HITS = REGISTRY.counter(
    "trino_hbo_plan_hits_total",
    "plan-time fingerprint lookups answered by journaled observed stats")
HBO_RECORDS = REGISTRY.counter(
    "trino_hbo_records_total",
    "plan_stats journal records written at query completion")
HBO_RECORD_ERRORS = REGISTRY.counter(
    "trino_hbo_record_errors_total",
    "plan_stats recording attempts that failed (swallowed, query unaffected)")
HBO_FANOUT_ADJUSTED = REGISTRY.counter(
    "trino_hbo_fanout_adjusted_total",
    "stages whose task count was shrunk from history-observed input rows")


# compressed execution (spi/batch.py encodings + encoding-aware operators):
# dictionary / RLE / lazy columns flowing through the pipeline instead of
# flat dense arrays, gated by TRINO_TPU_ENCODED_EXEC
ENCODING_RLE_BATCHES = REGISTRY.counter(
    "trino_encoding_rle_batches_total",
    "batches carrying at least one run-length-encoded column")
ENCODING_LAZY_COLUMNS = REGISTRY.counter(
    "trino_encoding_lazy_columns_total",
    "lazy (deferred-materialization) columns created by staging")
ENCODING_LAZY_MATERIALIZED = REGISTRY.counter(
    "trino_encoding_lazy_materialized_total",
    "lazy columns whose thunk actually ran (first touch)")
ENCODING_BYTES_SAVED = REGISTRY.counter(
    "trino_encoding_bytes_saved_total",
    "bytes not staged or shipped because a column stayed encoded "
    "(flat-equivalent size minus encoded size)")
ENCODING_LAZY_SKIPPED_BYTES = REGISTRY.counter(
    "trino_encoding_lazy_skipped_bytes_total",
    "payload bytes whose transfer was deferred by lazy staging (subtract "
    "trino_encoding_lazy_materialized_bytes_total for bytes that truly "
    "never moved)")
ENCODING_LAZY_MATERIALIZED_BYTES = REGISTRY.counter(
    "trino_encoding_lazy_materialized_bytes_total",
    "deferred payload bytes that DID move in the end because the lazy "
    "column's thunk ran (first touch)")
ENCODING_DICT_SIDECAR_SENT = REGISTRY.counter(
    "trino_encoding_dict_sidecar_sent_total",
    "dictionary sidecars shipped on a serde v2 stream (once per "
    "(stream, column) — not per page)")
ENCODING_DICT_SIDECAR_REUSED = REGISTRY.counter(
    "trino_encoding_dict_sidecar_reused_total",
    "pages that referenced an already-shipped dictionary sidecar by id "
    "instead of re-sending values")
ENCODING_EXCHANGE_CODE_PAGES = REGISTRY.counter(
    "trino_encoding_exchange_code_pages_total",
    "exchange pages whose dictionary codes crossed the shuffle without "
    "a decode (repartition serde v2 or collective all_to_all)")
ENCODING_RLE_AGG_ROWS = REGISTRY.counter(
    "trino_encoding_rle_agg_rows_total",
    "input rows aggregated arithmetically from RLE runs (value * "
    "run_count) without expansion")

# Install the spi/batch.py materialization hook so every lazy-thunk first
# touch is visible engine-wide.  spi imports nothing from telemetry, so
# this direction is cycle-free.
from ..spi import batch as _spi_batch  # noqa: E402


def _on_materialize(encoding: str, nbytes: int) -> None:
    if encoding == "LAZY":
        ENCODING_LAZY_MATERIALIZED.inc()
        ENCODING_LAZY_MATERIALIZED_BYTES.inc(nbytes)


_spi_batch.set_materialize_hook(_on_materialize)


# ------------------------------------------------------------ observe hooks
def resource_group_gauges(path: str):
    """(running, queued) gauge pair for one resource group.  Group trees
    are operator config, so these names are the one sanctioned DYNAMIC
    registration: ``trino_resource_group_{running,queued}_<path>`` with the
    dotted path mangled to a Prometheus-legal suffix.  MetricsRegistry
    get-or-create semantics make repeated calls cheap and idempotent."""
    import re as _re

    suffix = _re.sub(r"[^a-zA-Z0-9_]", "_", path)
    prefix = "trino_resource_group_"
    return (
        REGISTRY.gauge(prefix + "running_" + suffix,
                       f"queries running in resource group {path}"),
        REGISTRY.gauge(prefix + "queued_" + suffix,
                       f"queries queued in resource group {path}"),
    )


def observe_scan(ingest) -> None:
    """Fold a ScanIngestStats roll-up (exec/stats.py) into the registry."""
    if ingest is None or not ingest.scan_batches:
        return
    SCAN_BYTES.inc(ingest.scan_bytes)
    SCAN_ROWS.inc(ingest.scan_rows)
    SCAN_BATCHES.inc(ingest.scan_batches)
    SCAN_READ_SECONDS.inc(ingest.source_read_s)
    SCAN_WAIT_SECONDS.inc(ingest.consumer_wait_s)
    if ingest.gbps:
        SCAN_GBPS.set(round(ingest.gbps, 3))


def observe_sync(sync) -> None:
    """Fold a SyncGuard SyncStats delta (exec/syncguard.py)."""
    if sync is None:
        return
    if sync.host_syncs:
        SYNC_HOST.inc(sync.host_syncs)
    if sync.blocking_syncs:
        SYNC_BLOCKING.inc(sync.blocking_syncs)
    if sync.hot_loop_syncs:
        SYNC_HOT_LOOP.inc(sync.hot_loop_syncs)
    if sync.expand_overflows:
        EXPAND_OVERFLOWS.inc(sync.expand_overflows)
    if sync.expand_retries:
        EXPAND_RETRIES.inc(sync.expand_retries)


def observe_resilience(res) -> None:
    """Fold a ResilienceStats delta (exec/stats.py)."""
    if res is None or not res.any:
        return
    RES_QUERY_RETRIES.inc(res.query_retries)
    RES_BACKOFF_WAITS.inc(res.backoff_waits)
    RES_BACKOFF_SECONDS.inc(res.backoff_wait_s)
    RES_BLACKLISTED.inc(res.blacklisted_workers)
    RES_REPLACEMENTS.inc(res.worker_replacements)
    RES_HEARTBEAT_TRANSITIONS.inc(res.heartbeat_transitions)
    RES_EXCHANGE_FETCH_FAILURES.inc(res.exchange_fetch_failures)
    RES_EXCHANGE_BACKOFF_TRIPS.inc(res.exchange_backoff_trips)


def observe_fused(fs) -> None:
    """Fold a FusedStageStats roll-up.  ``compiles`` is deliberately NOT
    added here: the compile site (execution/stage_compiler.py) records it
    directly, together with the compile-wall-time histogram."""
    if fs is None or not fs.any:
        return
    FUSED_STAGES.inc(fs.stages)
    FUSED_BATCHES.inc(fs.batches)
    FUSED_JIT_CALLS.inc(fs.jit_calls)
    FUSED_CACHE_HITS.inc(fs.cache_hits)
    FUSED_MERGES.inc(fs.merges)
    FUSED_FALLBACKS.inc(fs.fallbacks)


def observe_resident(rs) -> None:
    """Fold a ResidentPlanStats roll-up.  ``programs`` and
    ``code_seam_columns`` are recorded at their event sites
    (execution/plan_compiler.py), mirroring the observe_fused contract."""
    if rs is None or not rs.any:
        return
    RESIDENT_PLANS.inc(rs.plans)
    RESIDENT_SEAMS.inc(rs.seams)
    RESIDENT_BATCHES.inc(rs.batches)
    RESIDENT_JIT_CALLS.inc(rs.jit_calls)
    RESIDENT_FALLBACKS.inc(rs.fallbacks)


def observe_exchange(nbytes: int, pages: int, wait_s: float) -> None:
    """One exchange fetch/serve observation (HTTP plane)."""
    EXCHANGE_BYTES.inc(nbytes)
    EXCHANGE_PAGES.inc(pages)
    EXCHANGE_WAIT_SECONDS.inc(wait_s)


def observe_adaptive(st) -> None:
    """Fold an AdaptiveStats roll-up (exec/stats.py).  ``decisions`` and the
    per-kind counters are recorded at decision time by execution/adaptive.py;
    here only the per-query activation count folds in, so a re-run of the
    same query never double-counts flips."""
    if st is None or not st.any:
        return
    ADAPTIVE_STAGE_ACTIVATIONS.inc(st.activations)


def observe_encoding(enc) -> None:
    """Fold an EncodingStats roll-up (exec/stats.py).  ``lazy_materialized``
    is NOT folded: the spi/batch.py materialize hook records it at thunk
    time; the exchange/sidecar counters are likewise recorded at the serde
    boundary (execution/serde.py, execution/task.py)."""
    if enc is None or not enc.any:
        return
    ENCODING_RLE_BATCHES.inc(enc.rle_batches)
    ENCODING_LAZY_COLUMNS.inc(enc.lazy_columns)
    ENCODING_BYTES_SAVED.inc(enc.bytes_saved)
    ENCODING_LAZY_SKIPPED_BYTES.inc(enc.lazy_skipped_bytes)
    ENCODING_RLE_AGG_ROWS.inc(enc.rle_agg_rows)


def update_device_memory_watermark() -> Optional[int]:
    """Refresh the device-memory gauges from the jax allocator stats
    (best-effort: CPU backends often report nothing → None).  Allocator
    stats are a host-side query, not a device sync."""
    try:
        import jax

        in_use = peak = 0
        found = False
        for d in jax.devices():
            stats = getattr(d, "memory_stats", None)
            stats = stats() if callable(stats) else None
            if not stats:
                continue
            found = True
            in_use += stats.get("bytes_in_use", 0)
            peak += stats.get("peak_bytes_in_use",
                              stats.get("bytes_in_use", 0))
    except Exception:
        return None
    if not found:
        return None
    DEVICE_MEMORY_IN_USE.set(in_use)
    DEVICE_MEMORY_PEAK.set(peak)
    return peak


# ------------------------------------------------------- cluster-wide fold
# Worker processes keep their own registries; /v1/metrics?scope=cluster on
# the coordinator fetches each worker's snapshot() JSON and folds it into
# one exposition: counters and gauges summed, Distributions bucket-merged
# (the merge Distribution.merge already defines for same-bounds layouts).


def merge_snapshot(into: dict, other: dict) -> None:
    """Fold one registry ``snapshot()`` dict into another, in place.
    Unknown names are adopted; a distribution with mismatched bucket
    layout is skipped (a version-skewed worker must not corrupt the
    roll-up)."""
    import copy as _copy

    for name, s in other.items():
        m = into.get(name)
        if m is None:
            into[name] = _copy.deepcopy(s)
            continue
        if m.get("kind") != s.get("kind"):
            continue
        if s["kind"] == "distribution":
            if m.get("bounds") != s.get("bounds"):
                continue
            if s["count"]:
                m["min"] = min(m["min"], s["min"]) if m["count"] else s["min"]
                m["max"] = max(m["max"], s["max"]) if m["count"] else s["max"]
            m["count"] += s["count"]
            m["sum"] += s["sum"]
            m["buckets"] = [a + b
                            for a, b in zip(m["buckets"], s["buckets"])]
        else:
            m["value"] += s["value"]


def render_snapshot_prometheus(snap: dict, helps: Optional[dict] = None
                               ) -> str:
    """Prometheus text exposition of a (possibly merged) snapshot dict —
    the same format ``MetricsRegistry.render_prometheus`` emits from live
    metric objects."""
    helps = helps or {}
    lines: list[str] = []
    for name in sorted(snap):
        s = snap[name]
        kind = s.get("kind")
        if kind == "distribution":
            lines.append(f"# HELP {name} {helps.get(name, '')}")
            lines.append(f"# TYPE {name} histogram")
            cum = 0
            for le, n in zip(s["bounds"], s["buckets"]):
                cum += n
                lines.append(f'{name}_bucket{{le="{_fmt(le)}"}} {cum}')
            cum += s["buckets"][-1]
            lines.append(f'{name}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{name}_sum {_fmt(s['sum'])}")
            lines.append(f"{name}_count {s['count']}")
        elif kind in ("counter", "gauge"):
            lines.append(f"# HELP {name} {helps.get(name, '')}")
            lines.append(f"# TYPE {name} {kind}")
            lines.append(f"{name} {_fmt(s['value'])}")
    return "\n".join(lines) + "\n"


def render_cluster(remote_snapshots: list[dict]) -> str:
    """The coordinator's scope=cluster view: local registry snapshot plus
    every reachable worker's, folded and rendered as one exposition."""
    merged = REGISTRY.snapshot()
    for snap in remote_snapshots:
        if isinstance(snap, dict):
            merge_snapshot(merged, snap)
    with REGISTRY._lock:
        helps = {n: m.help for n, m in REGISTRY._metrics.items()}
    return render_snapshot_prometheus(merged, helps)
