"""SQL lexer + recursive-descent/Pratt parser.

Hand-written equivalent of the ANTLR pipeline in ``core/trino-grammar``
(SqlBase.g4, 1,420 lines) + ``core/trino-parser``'s AstBuilder.  Covers the
engine's supported subset (full TPC-H shape: joins, subqueries, CTEs,
aggregates, CASE, CAST, EXTRACT, BETWEEN/IN/LIKE/EXISTS, date/interval
literals) and is grown feature-by-feature with the engine.

Operator precedence follows SqlBase.g4's booleanExpression/valueExpression
nesting: OR < AND < NOT < predicate (comparison, BETWEEN, IN, LIKE, IS) <
additive < multiplicative < unary.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Optional

from . import ast

__all__ = ["parse_statement", "parse_query", "ParseError"]


class ParseError(ValueError):
    def __init__(self, message: str, position: int = -1, text: str = ""):
        ctx = ""
        if position >= 0 and text:
            line = text.count("\n", 0, position) + 1
            col = position - (text.rfind("\n", 0, position) + 1) + 1
            snippet = text[max(0, position - 20) : position + 20].replace("\n", " ")
            ctx = f" at line {line}:{col} near '...{snippet}...'"
        super().__init__(message + ctx)


# --------------------------------------------------------------------------
# lexer

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+|--[^\n]*|/\*.*?\*/)
  | (?P<number>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+(?:[eE][+-]?\d+)?|\d+[eE][+-]?\d+|\d+)
  | (?P<string>'(?:[^']|'')*')
  | (?P<qident>"(?:[^"]|"")*")
  | (?P<ident>[A-Za-z_][A-Za-z0-9_$]*)
  | (?P<op><>|!=|>=|<=|\|\||->|[=<>+\-*/%(),.;\[\]{}|?])
    """,
    re.VERBOSE | re.DOTALL,
)

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "as", "and", "or", "not", "in", "exists", "between", "like", "escape",
    "is", "null", "true", "false", "case", "when", "then", "else", "end",
    "cast", "extract", "distinct", "all", "join", "inner", "left", "right",
    "full", "outer", "cross", "on", "using", "with", "union", "except",
    "intersect", "date", "timestamp", "interval", "year", "month", "day",
    "quarter", "hour", "minute", "second", "asc", "desc", "nulls", "first",
    "last", "explain", "analyze", "create", "table", "insert", "into",
    "values", "show", "tables", "columns", "describe", "substring", "for",
    "over", "drop", "delete",
}


@dataclass
class Token:
    kind: str  # number|string|ident|qident|op|kw|eof
    text: str
    pos: int


def tokenize(sql: str) -> list[Token]:
    tokens: list[Token] = []
    pos = 0
    n = len(sql)
    while pos < n:
        m = _TOKEN_RE.match(sql, pos)
        if m is None:
            raise ParseError(f"unexpected character {sql[pos]!r}", pos, sql)
        kind = m.lastgroup
        text = m.group()
        if kind != "ws":
            if kind == "ident" and text.lower() in KEYWORDS:
                tokens.append(Token("kw", text.lower(), pos))
            elif kind == "qident":
                tokens.append(Token("ident", text[1:-1].replace('""', '"'), pos))
            elif kind == "string":
                tokens.append(Token("string", text[1:-1].replace("''", "'"), pos))
            else:
                tokens.append(Token(kind, text, pos))
        pos = m.end()
    tokens.append(Token("eof", "", n))
    return tokens


# --------------------------------------------------------------------------
# parser


class _Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.tokens = tokenize(sql)
        self.i = 0

    # -- token helpers ----------------------------------------------------
    @property
    def cur(self) -> Token:
        return self.tokens[self.i]

    def peek_kw(self, *kws: str) -> bool:
        t = self.cur
        return t.kind == "kw" and t.text in kws

    def peek_op(self, *ops: str) -> bool:
        t = self.cur
        return t.kind == "op" and t.text in ops

    def advance(self) -> Token:
        t = self.cur
        self.i += 1
        return t

    def accept_kw(self, *kws: str) -> Optional[str]:
        if self.peek_kw(*kws):
            return self.advance().text
        return None

    def accept_op(self, *ops: str) -> Optional[str]:
        if self.peek_op(*ops):
            return self.advance().text
        return None

    def expect_kw(self, kw: str) -> None:
        if not self.accept_kw(kw):
            self.fail(f"expected {kw.upper()}")

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            self.fail(f"expected '{op}'")

    def expect_ident(self) -> str:
        t = self.cur
        if t.kind == "ident":
            return self.advance().text
        # allow non-reserved keywords as identifiers where unambiguous
        if t.kind == "kw" and t.text in ("year", "month", "day", "quarter",
                                         "date", "first", "last", "tables",
                                         "columns", "values"):
            return self.advance().text
        self.fail("expected identifier")

    def fail(self, msg: str):
        raise ParseError(f"{msg}, found {self.cur.kind} {self.cur.text!r}",
                         self.cur.pos, self.sql)

    # -- statements -------------------------------------------------------
    def parse_statement(self) -> ast.Statement:
        if self.accept_kw("explain"):
            analyze = bool(self.accept_kw("analyze"))
            inner = self.parse_statement()
            return ast.Explain(inner, analyze=analyze)
        if self.peek_kw("select", "with") or self.peek_op("("):
            return ast.QueryStatement(self.parse_query())
        if self.accept_word("start"):
            self.expect_word("transaction")
            return ast.StartTransaction()
        if self.accept_word("begin"):
            return ast.StartTransaction()
        if self.accept_word("commit"):
            return ast.Commit()
        if self.accept_word("rollback"):
            return ast.Rollback()
        if self.accept_kw("values"):
            self.i -= 1  # top-level VALUES statement
            return ast.QueryStatement(self.parse_query())
        if self.accept_kw("create"):
            if self.accept_word("function"):
                return self._parse_create_function()
            replace = False
            if self.accept_word("or"):
                self.expect_word("replace")
                replace = True
            if self.accept_word("materialized"):
                self.expect_word("view")
                name = self.qualified_name()
                self.expect_kw("as")
                return ast.CreateView(name, self.parse_query(), replace, True)
            if self.accept_word("view"):
                name = self.qualified_name()
                self.expect_kw("as")
                return ast.CreateView(name, self.parse_query(), replace, False)
            if replace:
                self.fail("OR REPLACE is supported for views only")
            self.expect_kw("table")
            name = self.qualified_name()
            if self.accept_op("("):
                cols = []
                while True:
                    cname = self.expect_ident()
                    ctype = self.parse_type_name()
                    cols.append((cname, ctype))
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
                return ast.CreateTable(name, tuple(cols))
            self.expect_kw("as")
            return ast.CreateTableAsSelect(name, self.parse_query())
        if self.accept_kw("drop"):
            if self.accept_word("function"):
                return ast.DropFunction(self.qualified_name())
            materialized = bool(self.accept_word("materialized"))
            if materialized or self.peek_word("view"):
                self.expect_word("view")
                if_exists = False
                save = self.i
                if self.accept_word("if"):
                    if self.accept_word("exists"):
                        if_exists = True
                    else:
                        self.i = save
                return ast.DropView(self.qualified_name(), if_exists,
                                    materialized)
            self.expect_kw("table")
            if_exists = False
            save = self.i
            if self.accept_word("if"):
                if self.accept_word("exists"):
                    if_exists = True
                else:
                    self.i = save
            return ast.DropTable(self.qualified_name(), if_exists)
        if self.accept_kw("delete"):
            self.expect_kw("from")
            name = self.qualified_name()
            where = self.parse_expr() if self.accept_kw("where") else None
            return ast.Delete(name, where)
        if self.accept_kw("insert"):
            self.expect_kw("into")
            name = self.qualified_name()
            return ast.InsertInto(name, self.parse_query())
        if self.accept_word("refresh"):
            self.expect_word("materialized")
            self.expect_word("view")
            return ast.RefreshMaterializedView(self.qualified_name())
        if self.accept_word("set"):
            self.expect_word("session")
            name = self.qualified_name()
            self.expect_op("=")
            return ast.SetSession(name, self.parse_expr())
        if self.accept_word("call"):
            name = self.qualified_name()
            args: list = []
            self.expect_op("(")
            if not self.peek_op(")"):
                while True:
                    args.append(self.parse_expr())
                    if not self.accept_op(","):
                        break
            self.expect_op(")")
            return ast.CallProcedure(name, tuple(args))
        if self.accept_kw("analyze"):
            return ast.Analyze(self.qualified_name())
        if self.accept_kw("show"):
            if self.accept_kw("tables"):
                return ast.ShowTables()
            if self.accept_kw("columns"):
                self.expect_kw("from")
                return ast.ShowColumns(self.qualified_name())
            self.fail("expected TABLES or COLUMNS")
        if self.accept_kw("describe"):
            return ast.ShowColumns(self.qualified_name())
        self.fail("expected statement")

    def _parse_create_function(self) -> ast.Statement:
        """CREATE FUNCTION f(x bigint, ...) RETURNS type RETURN expr
        (reference: sql/routine — SqlRoutineAnalyzer; scalar RETURN-expression
        bodies, the common inlineable case)."""
        name = self.qualified_name()
        params: list[tuple[str, str]] = []
        self.expect_op("(")
        if not self.peek_op(")"):
            while True:
                pname = self.expect_ident()
                ptype = self.parse_type_name()
                params.append((pname, ptype))
                if not self.accept_op(","):
                    break
        self.expect_op(")")
        self.expect_word("returns")
        rtype = self.parse_type_name()
        self.expect_word("return")
        body = self.parse_expr()
        return ast.CreateFunction(name, tuple(params), rtype, body)

    def qualified_name(self) -> str:
        parts = [self.expect_ident()]
        while self.accept_op("."):
            parts.append(self.expect_ident())
        return ".".join(parts)

    # -- query ------------------------------------------------------------
    def parse_query(self) -> ast.Query:
        withs: list[ast.WithQuery] = []
        if self.accept_kw("with"):
            while True:
                name = self.expect_ident()
                colnames = None
                if self.accept_op("("):
                    cols = [self.expect_ident()]
                    while self.accept_op(","):
                        cols.append(self.expect_ident())
                    self.expect_op(")")
                    colnames = tuple(cols)
                self.expect_kw("as")
                self.expect_op("(")
                q = self.parse_query()
                self.expect_op(")")
                withs.append(ast.WithQuery(name, q, colnames))
                if not self.accept_op(","):
                    break
        body = self.parse_query_body()
        order_by: tuple[ast.SortItem, ...] = ()
        limit = None
        if self.accept_kw("order"):
            self.expect_kw("by")
            order_by = tuple(self.parse_sort_items())
        if self.accept_kw("limit"):
            t = self.cur
            if t.kind == "number":
                limit = int(self.advance().text)
            elif t.kind == "kw" and t.text == "all":
                self.advance()
            else:
                self.fail("expected LIMIT count")
        return ast.Query(body, order_by, limit, tuple(withs))

    def parse_sort_items(self) -> list[ast.SortItem]:
        items = []
        while True:
            e = self.parse_expr()
            asc = True
            if self.accept_kw("asc"):
                asc = True
            elif self.accept_kw("desc"):
                asc = False
            nulls_first = None
            if self.accept_kw("nulls"):
                if self.accept_kw("first"):
                    nulls_first = True
                elif self.accept_kw("last"):
                    nulls_first = False
                else:
                    self.fail("expected FIRST or LAST")
            items.append(ast.SortItem(e, asc, nulls_first))
            if not self.accept_op(","):
                return items

    def parse_query_body(self) -> ast.QueryBody:
        """Set-operation precedence per SqlBase.g4 queryTerm: INTERSECT binds
        tighter than UNION/EXCEPT; all are left-associative."""
        left = self.parse_set_term()
        while self.peek_kw("union", "except"):
            op = self.advance().text.upper()
            distinct = True
            if self.accept_kw("all"):
                distinct = False
            else:
                self.accept_kw("distinct")
            right = self.parse_set_term()
            left = ast.SetOp(op, distinct, left, right)
        return left

    def parse_set_term(self) -> ast.QueryBody:
        left = self.parse_set_primary()
        while self.peek_kw("intersect"):
            self.advance()
            distinct = True
            if self.accept_kw("all"):
                distinct = False
            else:
                self.accept_kw("distinct")
            right = self.parse_set_primary()
            left = ast.SetOp("INTERSECT", distinct, left, right)
        return left

    def parse_set_primary(self) -> ast.QueryBody:
        if self.peek_op("("):
            # parenthesized query (may carry its own ORDER BY / LIMIT)
            self.advance()
            q = self.parse_query()
            self.expect_op(")")
            return q
        if self.accept_kw("values"):
            rows = [self._parse_values_row()]
            while self.accept_op(","):
                rows.append(self._parse_values_row())
            return ast.ValuesBody(tuple(rows))
        return self.parse_query_spec()

    def _parse_values_row(self) -> tuple:
        if self.accept_op("("):
            es = [self.parse_expr()]
            while self.accept_op(","):
                es.append(self.parse_expr())
            self.expect_op(")")
            return tuple(es)
        return (self.parse_expr(),)

    def parse_query_spec(self) -> ast.QuerySpec:
        self.expect_kw("select")
        distinct = False
        if self.accept_kw("distinct"):
            distinct = True
        else:
            self.accept_kw("all")
        select = [self.parse_select_item()]
        while self.accept_op(","):
            select.append(self.parse_select_item())
        from_ = None
        if self.accept_kw("from"):
            from_ = self.parse_relation()
            while self.accept_op(","):
                right = self.parse_relation()
                from_ = ast.Join("CROSS", from_, right, None)
        where = self.parse_expr() if self.accept_kw("where") else None
        group_by: tuple = ()
        if self.accept_kw("group"):
            self.expect_kw("by")
            gb = [self.parse_grouping_element()]
            while self.accept_op(","):
                gb.append(self.parse_grouping_element())
            group_by = tuple(gb)
        having = self.parse_expr() if self.accept_kw("having") else None
        return ast.QuerySpec(tuple(select), distinct, from_, where, group_by, having)

    def parse_grouping_element(self):
        """One GROUP BY element: expr | ROLLUP(..) | CUBE(..) |
        GROUPING SETS ((..), ..).  ROLLUP/CUBE/GROUPING stay soft keywords:
        they only take this path when the following tokens disambiguate
        (SqlBase.g4 groupingElement)."""
        t = self.cur
        if (t.kind == "ident" and t.text.lower() in ("rollup", "cube")
                and self.tokens[self.i + 1].text == "("):
            name = self.advance().text.lower()
            self.expect_op("(")
            exprs = [self.parse_expr()]
            while self.accept_op(","):
                exprs.append(self.parse_expr())
            self.expect_op(")")
            return (ast.Rollup(tuple(exprs)) if name == "rollup"
                    else ast.Cube(tuple(exprs)))
        if (t.kind == "ident" and t.text.lower() == "grouping"
                and self.tokens[self.i + 1].kind == "ident"
                and self.tokens[self.i + 1].text.lower() == "sets"):
            self.advance()
            self.advance()
            self.expect_op("(")
            sets = [self._parse_grouping_set()]
            while self.accept_op(","):
                sets.append(self._parse_grouping_set())
            self.expect_op(")")
            return ast.GroupingSets(tuple(sets))
        return self.parse_expr()

    def _parse_grouping_set(self) -> tuple:
        if self.accept_op("("):
            if self.accept_op(")"):
                return ()
            es = [self.parse_expr()]
            while self.accept_op(","):
                es.append(self.parse_expr())
            self.expect_op(")")
            return tuple(es)
        return (self.parse_expr(),)

    def parse_select_item(self) -> ast.SelectItem:
        if self.accept_op("*"):
            return ast.SelectItem(None)
        # t.* handled after expr parse would be messy; look ahead
        if (self.cur.kind == "ident" and self.tokens[self.i + 1].text == "."
                and self.tokens[self.i + 2].text == "*"):
            prefix = self.advance().text
            self.advance()
            self.advance()
            return ast.SelectItem(None, star_prefix=prefix)
        e = self.parse_expr()
        alias = None
        if self.accept_kw("as"):
            alias = self.expect_ident()
        elif self.cur.kind == "ident":
            alias = self.advance().text
        return ast.SelectItem(e, alias)

    # -- relations --------------------------------------------------------
    def parse_relation(self) -> ast.Relation:
        left = self.parse_relation_primary()
        while True:
            if self.accept_kw("cross"):
                self.expect_kw("join")
                right = self.parse_relation_primary()
                left = ast.Join("CROSS", left, right, None)
                continue
            jt = None
            if self.peek_kw("join"):
                jt = "INNER"
            elif self.peek_kw("inner"):
                self.advance()
                jt = "INNER"
            elif self.peek_kw("left"):
                self.advance()
                self.accept_kw("outer")
                jt = "LEFT"
            elif self.peek_kw("right"):
                self.advance()
                self.accept_kw("outer")
                jt = "RIGHT"
            elif self.peek_kw("full"):
                self.advance()
                self.accept_kw("outer")
                jt = "FULL"
            if jt is None:
                return left
            self.expect_kw("join")
            right = self.parse_relation_primary()
            self.expect_kw("on")
            cond = self.parse_expr()
            left = ast.Join(jt, left, right, cond)

    def parse_relation_primary(self) -> ast.Relation:
        t = self.cur
        if (t.kind == "kw" and t.text == "table"
                and self.tokens[self.i + 1].text == "("):
            # TABLE(fn(args...)) — polymorphic table function invocation
            # (SqlBase.g4 tableFunctionCall)
            self.advance()
            self.expect_op("(")
            fname = self.expect_ident().lower()
            self.expect_op("(")
            args: list[ast.Expr] = []
            if not self.peek_op(")"):
                args.append(self.parse_expr())
                while self.accept_op(","):
                    args.append(self.parse_expr())
            self.expect_op(")")
            self.expect_op(")")
            alias = self._maybe_alias()
            colnames = None
            if alias is not None and self.accept_op("("):
                cols = [self.expect_ident()]
                while self.accept_op(","):
                    cols.append(self.expect_ident())
                self.expect_op(")")
                colnames = tuple(cols)
            return ast.TableFunctionRelation(fname, tuple(args), alias,
                                             colnames)
        if (t.kind == "ident" and t.text.lower() == "unnest"
                and self.tokens[self.i + 1].text == "("):
            self.advance()
            self.expect_op("(")
            exprs = [self.parse_expr()]
            while self.accept_op(","):
                exprs.append(self.parse_expr())
            self.expect_op(")")
            ordinality = False
            if self.peek_kw("with"):
                self.advance()
                self.expect_word("ordinality")
                ordinality = True
            alias = self._maybe_alias()
            colnames = None
            if alias is not None and self.accept_op("("):
                cols = [self.expect_ident()]
                while self.accept_op(","):
                    cols.append(self.expect_ident())
                self.expect_op(")")
                colnames = tuple(cols)
            return ast.UnnestRelation(tuple(exprs), ordinality, alias, colnames)
        if self.accept_op("("):
            q = self.parse_query()
            self.expect_op(")")
            alias = self._maybe_alias()
            colnames = None
            if alias is not None and self.accept_op("("):
                cols = [self.expect_ident()]
                while self.accept_op(","):
                    cols.append(self.expect_ident())
                self.expect_op(")")
                colnames = tuple(cols)
            return ast.SubqueryRelation(q, alias, colnames)
        name = self.qualified_name()
        if (self.cur.kind == "ident"
                and self.cur.text.lower() == "match_recognize"
                and self.tokens[self.i + 1].text == "("):
            return self._parse_match_recognize(ast.Table(name))
        alias = self._maybe_alias()
        return ast.Table(name, alias)

    def _parse_match_recognize(self, input_rel) -> ast.Relation:
        """MATCH_RECOGNIZE (...) suffix (SqlBase.g4 patternRecognition)."""
        self.advance()  # match_recognize
        self.expect_op("(")
        partition: tuple = ()
        if self.accept_word("partition"):
            self.expect_kw("by")
            ps = [self.parse_expr()]
            while self.accept_op(","):
                ps.append(self.parse_expr())
            partition = tuple(ps)
        order: tuple = ()
        if self.accept_kw("order"):
            self.expect_kw("by")
            order = tuple(self.parse_sort_items())
        measures: list[tuple] = []
        if self.accept_word("measures"):
            while True:
                e = self.parse_expr()
                self.expect_kw("as")
                measures.append((e, self.expect_ident()))
                if not self.accept_op(","):
                    break
        if self.accept_word("one"):
            self.expect_word("row")
            self.expect_word("per")
            self.expect_word("match")
        skip_past = True
        if self.accept_word("after"):
            self.expect_word("match")
            self.expect_word("skip")
            if self.accept_word("past"):
                self.expect_kw("last")
                self.expect_word("row")
            else:
                self.expect_word("to")
                self.expect_word("next")
                self.expect_word("row")
                skip_past = False
        self.expect_word("pattern")
        self.expect_op("(")
        # capture raw pattern text up to the balanced close paren
        depth = 1
        toks: list[str] = []
        while depth > 0:
            t = self.advance()
            if t.kind == "eof":
                self.fail("unterminated PATTERN")
            if t.kind == "op" and t.text == "(":
                depth += 1
            elif t.kind == "op" and t.text == ")":
                depth -= 1
                if depth == 0:
                    break
            toks.append(t.text)
        pattern = " ".join(toks)
        self.expect_word("define")
        defines: list[tuple] = []
        while True:
            label = self.expect_ident()
            self.expect_kw("as")
            defines.append((label, self.parse_expr()))
            if not self.accept_op(","):
                break
        self.expect_op(")")
        alias = self._maybe_alias()
        return ast.MatchRecognizeRelation(
            input_rel, partition, order, tuple(measures), pattern,
            tuple(defines), skip_past, alias)

    def _maybe_alias(self) -> Optional[str]:
        if self.accept_kw("as"):
            return self.expect_ident()
        if self.cur.kind == "ident":
            return self.advance().text
        return None

    # -- expressions (Pratt) ----------------------------------------------
    def parse_expr(self) -> ast.Expr:
        return self.parse_or()

    def parse_or(self) -> ast.Expr:
        terms = [self.parse_and()]
        while self.accept_kw("or"):
            terms.append(self.parse_and())
        if len(terms) == 1:
            return terms[0]
        return ast.LogicalOp("OR", tuple(terms))

    def parse_and(self) -> ast.Expr:
        terms = [self.parse_not()]
        while self.accept_kw("and"):
            terms.append(self.parse_not())
        if len(terms) == 1:
            return terms[0]
        return ast.LogicalOp("AND", tuple(terms))

    def parse_not(self) -> ast.Expr:
        if self.accept_kw("not"):
            return ast.Not(self.parse_not())
        return self.parse_predicate()

    def parse_predicate(self) -> ast.Expr:
        if self.peek_kw("exists"):
            self.advance()
            self.expect_op("(")
            q = self.parse_query()
            self.expect_op(")")
            return ast.Exists(q)
        left = self.parse_additive()
        while True:
            negated = False
            save = self.i
            if self.accept_kw("not"):
                negated = True
            if self.accept_kw("between"):
                low = self.parse_additive()
                self.expect_kw("and")
                high = self.parse_additive()
                left = ast.Between(left, low, high, negated)
                continue
            if self.accept_kw("in"):
                self.expect_op("(")
                if self.peek_kw("select", "with"):
                    q = self.parse_query()
                    self.expect_op(")")
                    left = ast.InSubquery(left, q, negated)
                else:
                    items = [self.parse_expr()]
                    while self.accept_op(","):
                        items.append(self.parse_expr())
                    self.expect_op(")")
                    left = ast.InList(left, tuple(items), negated)
                continue
            if self.accept_kw("like"):
                pattern = self.parse_additive()
                escape = None
                if self.accept_kw("escape"):
                    escape = self.parse_additive()
                left = ast.Like(left, pattern, escape, negated)
                continue
            if negated:
                self.i = save  # NOT belongs to something else
                break
            if self.accept_kw("is"):
                neg = bool(self.accept_kw("not"))
                self.expect_kw("null")
                left = ast.IsNull(left, neg)
                continue
            op = self.accept_op("=", "<>", "!=", "<", "<=", ">", ">=")
            if op:
                right = self.parse_additive()
                left = ast.Comparison("<>" if op == "!=" else op, left, right)
                continue
            break
        return left

    def parse_additive(self) -> ast.Expr:
        left = self.parse_multiplicative()
        while True:
            op = self.accept_op("+", "-", "||")
            if not op:
                return left
            right = self.parse_multiplicative()
            left = ast.BinaryOp(op, left, right)

    def parse_multiplicative(self) -> ast.Expr:
        left = self.parse_unary()
        while True:
            op = self.accept_op("*", "/", "%")
            if not op:
                return left
            right = self.parse_unary()
            left = ast.BinaryOp(op, left, right)

    def parse_unary(self) -> ast.Expr:
        op = self.accept_op("-", "+")
        if op:
            operand = self.parse_unary()
            if op == "-":
                if isinstance(operand, ast.IntLiteral):
                    return ast.IntLiteral(-operand.value)
                if isinstance(operand, ast.DoubleLiteral):
                    return ast.DoubleLiteral(-operand.value)
                if isinstance(operand, ast.DecimalLiteral):
                    return ast.DecimalLiteral("-" + operand.text)
                return ast.UnaryOp("-", operand)
            return operand
        return self.parse_postfix()

    def parse_postfix(self) -> ast.Expr:
        e = self.parse_primary()
        while True:
            if self.accept_op("."):
                if not isinstance(e, ast.ColumnRef):
                    self.fail("unexpected '.'")
                e = ast.ColumnRef(e.parts + (self.expect_ident(),))
                continue
            if self.accept_op("["):
                idx = self.parse_expr()
                self.expect_op("]")
                e = ast.Subscript(e, idx)
                continue
            return e

    def parse_primary(self) -> ast.Expr:
        t = self.cur
        if t.kind == "number":
            self.advance()
            if re.fullmatch(r"\d+", t.text):
                return ast.IntLiteral(int(t.text))
            if "e" in t.text.lower():
                return ast.DoubleLiteral(float(t.text))
            return ast.DecimalLiteral(t.text)
        if t.kind == "string":
            self.advance()
            return ast.StringLiteral(t.text)
        if t.kind == "kw":
            if t.text == "null":
                self.advance()
                return ast.NullLiteral()
            if t.text in ("true", "false"):
                self.advance()
                return ast.BooleanLiteral(t.text == "true")
            if t.text == "date":
                nxt = self.tokens[self.i + 1]
                if nxt.kind == "string":
                    self.advance()
                    return ast.DateLiteral(self.advance().text)
            if t.text == "timestamp":
                nxt = self.tokens[self.i + 1]
                if nxt.kind == "string":
                    self.advance()
                    return ast.TimestampLiteral(self.advance().text)
            if t.text == "interval":
                self.advance()
                neg = False
                if self.accept_op("-"):
                    neg = True
                v = self.cur
                if v.kind != "string" and v.kind != "number":
                    self.fail("expected interval value")
                self.advance()
                unit = self.cur
                if unit.kind != "kw" or unit.text not in (
                    "year", "month", "day", "hour", "minute", "second"
                ):
                    self.fail("expected interval unit")
                self.advance()
                return ast.IntervalLiteral(v.text, unit.text.upper(), neg)
            if t.text == "case":
                return self.parse_case()
            if t.text == "cast":
                self.advance()
                self.expect_op("(")
                inner = self.parse_expr()
                self.expect_kw("as")
                type_name = self.parse_type_name()
                self.expect_op(")")
                return ast.Cast(inner, type_name)
            if t.text == "extract":
                self.advance()
                self.expect_op("(")
                fld = self.cur
                if fld.kind != "kw" or fld.text not in (
                    "year", "month", "day", "quarter", "hour", "minute", "second"
                ):
                    self.fail("expected extract field")
                self.advance()
                self.expect_kw("from")
                inner = self.parse_expr()
                self.expect_op(")")
                return ast.Extract(fld.text.upper(), inner)
            if t.text == "substring":
                self.advance()
                self.expect_op("(")
                inner = self.parse_expr()
                if self.accept_kw("from"):
                    start = self.parse_expr()
                    length = self.parse_expr() if self.accept_kw("for") else None
                else:
                    self.expect_op(",")
                    start = self.parse_expr()
                    length = None
                    if self.accept_op(","):
                        length = self.parse_expr()
                self.expect_op(")")
                args = (inner, start) + ((length,) if length is not None else ())
                return ast.FunctionCall("substring", args)
            if t.text in ("year", "month", "day", "quarter", "first", "last"):
                # allow year(x) / FIRST(a.x) / LAST(a.x) call style
                nxt = self.tokens[self.i + 1]
                if nxt.kind == "op" and nxt.text == "(":
                    self.advance()
                    self.expect_op("(")
                    inner = self.parse_expr()
                    self.expect_op(")")
                    return ast.FunctionCall(t.text, (inner,))
                # bare soft keyword as a column name (a column named "day")
                self.advance()
                e: ast.Expr = ast.ColumnRef((t.text,))
                while self.accept_op("."):
                    e = ast.ColumnRef(e.parts + (self.expect_ident(),))
                return e
        if t.kind == "op" and t.text == "(":
            self.advance()
            if self.peek_kw("select", "with"):
                q = self.parse_query()
                self.expect_op(")")
                return ast.ScalarSubquery(q)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind == "ident":
            nxt = self.tokens[self.i + 1]
            if (t.text.lower() == "array" and nxt.kind == "op"
                    and nxt.text == "["):
                self.advance()
                self.advance()
                elems: list[ast.Expr] = []
                if not self.peek_op("]"):
                    elems.append(self.parse_expr())
                    while self.accept_op(","):
                        elems.append(self.parse_expr())
                self.expect_op("]")
                return ast.ArrayLiteral(tuple(elems))
            if nxt.kind == "op" and nxt.text == "(":
                name = self.advance().text.lower()
                self.expect_op("(")
                if self.accept_op("*"):
                    self.expect_op(")")
                    return self._maybe_window(
                        ast.FunctionCall(name, (), is_star=True))
                distinct = bool(self.accept_kw("distinct"))
                args: list[ast.Expr] = []
                if not self.peek_op(")"):
                    args.append(self.parse_expr())
                    while self.accept_op(","):
                        args.append(self.parse_expr())
                self.expect_op(")")
                return self._maybe_window(
                    ast.FunctionCall(name, tuple(args), distinct))
            return ast.ColumnRef((self.advance().text,))
        self.fail("expected expression")

    # -- window (OVER clause; SqlBase.g4 windowSpecification) --------------
    def accept_word(self, *words: str) -> Optional[str]:
        """Context-sensitive non-reserved word (ident or keyword token)."""
        t = self.cur
        if t.kind in ("kw", "ident") and t.text.lower() in words:
            self.advance()
            return t.text.lower()
        return None

    def peek_word(self, *words: str) -> bool:
        t = self.cur
        return t.kind in ("kw", "ident") and t.text.lower() in words

    def expect_word(self, word: str) -> None:
        if not self.accept_word(word):
            self.fail(f"expected {word.upper()}")

    def _maybe_window(self, fc: ast.FunctionCall) -> ast.Expr:
        if not self.accept_kw("over"):
            return fc
        self.expect_op("(")
        partition: tuple[ast.Expr, ...] = ()
        if self.accept_word("partition"):
            self.expect_kw("by")
            parts = [self.parse_expr()]
            while self.accept_op(","):
                parts.append(self.parse_expr())
            partition = tuple(parts)
        order: tuple[ast.SortItem, ...] = ()
        if self.accept_kw("order"):
            self.expect_kw("by")
            order = tuple(self.parse_sort_items())
        frame = None
        unit = self.accept_word("rows", "range")
        if unit:
            if self.accept_kw("between"):
                start = self._frame_bound()
                self.expect_kw("and")
                end = self._frame_bound()
            else:
                start = self._frame_bound()
                end = ast.FrameBound("CURRENT")
            frame = ast.WindowFrame(unit.upper(), start, end)
        self.expect_op(")")
        from dataclasses import replace

        return replace(fc, window=ast.WindowSpec(partition, order, frame))

    def _frame_bound(self) -> ast.FrameBound:
        if self.accept_word("unbounded"):
            d = self.accept_word("preceding", "following")
            if d is None:
                self.fail("expected PRECEDING or FOLLOWING")
            return ast.FrameBound(f"UNBOUNDED_{d.upper()}")
        if self.accept_word("current"):
            self.expect_word("row")
            return ast.FrameBound("CURRENT")
        t = self.cur
        if t.kind != "number":
            self.fail("expected frame bound")
        n = int(self.advance().text)
        d = self.accept_word("preceding", "following")
        if d is None:
            self.fail("expected PRECEDING or FOLLOWING")
        return ast.FrameBound(d.upper(), n)

    def parse_case(self) -> ast.Expr:
        self.expect_kw("case")
        operand = None
        if not self.peek_kw("when"):
            operand = self.parse_expr()
        whens = []
        while self.accept_kw("when"):
            cond = self.parse_expr()
            self.expect_kw("then")
            result = self.parse_expr()
            whens.append(ast.WhenClause(cond, result))
        default = None
        if self.accept_kw("else"):
            default = self.parse_expr()
        self.expect_kw("end")
        if not whens:
            self.fail("CASE requires at least one WHEN")
        return ast.Case(operand, tuple(whens), default)

    def parse_type_name(self) -> str:
        name = self.expect_type_word()
        if name.lower() in ("double",) and self.cur.kind == "ident" and self.cur.text.lower() == "precision":
            self.advance()
        if self.accept_op("("):
            # balanced-paren scan: covers nested/compound type arguments
            # (row(x bigint, y varchar), map(varchar, array(bigint)), ...)
            out = ""
            depth = 1
            while depth:
                if self.cur.kind == "eof":
                    self.fail("unterminated type arguments")
                t = self.advance().text
                if t == "(":
                    depth += 1
                    out += "("
                elif t == ")":
                    depth -= 1
                    if depth:
                        out += ")"
                elif t == ",":
                    out += ", "
                else:
                    if out and not out.endswith("(") and not out.endswith(", "):
                        out += " "
                    out += t
            name += f"({out})"
        return name

    def expect_type_word(self) -> str:
        t = self.cur
        if t.kind in ("ident",) or (t.kind == "kw" and t.text in ("date", "timestamp")):
            return self.advance().text
        self.fail("expected type name")


def parse_statement(sql: str) -> ast.Statement:
    p = _Parser(sql.strip().rstrip(";"))
    stmt = p.parse_statement()
    if p.cur.kind != "eof":
        p.fail("unexpected trailing input")
    return stmt


def parse_query(sql: str) -> ast.Query:
    stmt = parse_statement(sql)
    if not isinstance(stmt, ast.QueryStatement):
        raise ParseError("expected a query")
    return stmt.query
