"""Name resolution, type checking, AST-expression -> IR translation.

Plays the role of sql/analyzer/StatementAnalyzer + ExpressionAnalyzer and the
IR translation half of sql/planner/QueryPlanner (reference:
sql/analyzer/ExpressionAnalyzer.java, sql/relational/SqlToRowExpressionTranslator
pattern).  Scopes are flat channel lists with an optional parent (correlated
references become OuterRef, eliminated later by decorrelation).

Type rules (intentional, documented divergences from Trino):
- integer literals and integral columns type as BIGINT throughout;
- decimal +,-,* follow Trino scale rules (capped at precision 18);
  decimal division and AVG produce DOUBLE (Trino keeps decimal — we trade
  that for exactness-free simplicity and match the float oracle);
- VARCHAR carries no length.
"""

from __future__ import annotations

import contextvars
import dataclasses
from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from ..spi.types import (
    BIGINT,
    BOOLEAN,
    DATE,
    DOUBLE,
    TIMESTAMP,
    UNKNOWN,
    VARCHAR,
    ArrayType,
    DecimalType,
    Type,
    common_super_type,
    is_numeric,
    is_string,
    parse_type,
)
from . import ast
from .ir import Call, InputRef, Literal, OuterRef, RowExpression

__all__ = [
    "Field", "Scope", "Translator", "AggregateCollector", "WindowCollector",
    "AnalysisError", "AGG_FUNCTIONS", "WINDOW_FUNCTIONS", "cast_to",
    "rewrite_expr", "split_conjuncts", "agg_result_type",
]


class AnalysisError(ValueError):
    pass


AGG_FUNCTIONS = {"count", "sum", "avg", "min", "max", "any_value",
                 "stddev", "stddev_samp", "stddev_pop",
                 "variance", "var_samp", "var_pop"}

# aggregates rewritten onto the core set during translation
_AGG_ALIASES = {"arbitrary": "any_value", "bool_and": "min", "every": "min",
                "bool_or": "max"}

# the 3-state (sum, sum-of-squares, count) family
STAT_AGGS = {"stddev", "stddev_samp", "stddev_pop",
             "variance", "var_samp", "var_pop"}

# pure window (ranking/navigation) functions; aggregates are also legal
# with an OVER clause (reference: sql/analyzer/ExpressionAnalyzer window
# resolution + operator/window/*)
WINDOW_FUNCTIONS = {
    "rank", "dense_rank", "row_number", "ntile", "percent_rank", "cume_dist",
    "lag", "lead", "first_value", "last_value", "nth_value",
}

_SCALAR_TYPES: dict[str, str] = {
    # name -> rule tag used below
    "abs": "arg", "negate": "arg", "round": "arg",
    "sqrt": "double", "exp": "double", "ln": "double", "log10": "double",
    "power": "double", "pow": "double",
    "floor": "arg", "ceiling": "arg", "ceil": "arg",
    "year": "bigint", "month": "bigint", "day": "bigint", "quarter": "bigint",
    "day_of_week": "bigint", "dow": "bigint", "day_of_year": "bigint",
    "doy": "bigint",
    "length": "bigint", "strpos": "bigint",
    "substring": "varchar", "substr": "varchar", "upper": "varchar",
    "lower": "varchar", "trim": "varchar", "ltrim": "varchar", "rtrim": "varchar",
    "reverse": "varchar", "concat": "varchar", "replace": "varchar",
    "starts_with": "boolean", "is_nan": "boolean",
    "truncate": "arg",
    "split_part": "varchar", "lpad": "varchar", "rpad": "varchar",
    "translate": "varchar",
    "codepoint": "bigint",
    "cbrt": "double", "degrees": "double", "radians": "double",
    "sin": "double", "cos": "double", "tan": "double",
    "asin": "double", "acos": "double", "atan": "double", "atan2": "double",
    "log2": "double", "pi": "double", "e": "double",
}


# names with bespoke translation rules (not in _SCALAR_TYPES but built in)
_SPECIAL_FUNCTIONS = {
    "coalesce", "if", "mod", "nullif", "grouping", "greatest", "least",
    "sign", "date_trunc", "cardinality", "element_at", "contains",
    "array_position", "approx_distinct", "count_if", "geometric_mean",
    "json_extract", "json_extract_scalar", "json_array_length", "position",
    "repeat", "row", "map", "map_keys", "map_values",
}


def is_builtin_function(name: str) -> bool:
    """CREATE FUNCTION must not shadow engine builtins (the reference's
    LanguageFunctionManager rejects redefining global-catalog names)."""
    n = name.lower()
    return (n in _SCALAR_TYPES or n in AGG_FUNCTIONS or n in _AGG_ALIASES
            or n in STAT_AGGS or n in WINDOW_FUNCTIONS
            or n in _SPECIAL_FUNCTIONS)


@dataclass(frozen=True)
class Field:
    name: Optional[str]
    type: Type
    qualifier: Optional[str] = None  # relation alias / table name


class Scope:
    def __init__(self, fields: Sequence[Field], parent: Optional["Scope"] = None):
        self.fields = list(fields)
        self.parent = parent

    def resolve(self, parts: tuple[str, ...]) -> tuple[int, int, Field]:
        """-> (level, channel, field); level 0 = this scope."""
        level = 0
        scope: Optional[Scope] = self
        while scope is not None:
            hits = scope._match(parts)
            if len(hits) == 1:
                i = hits[0]
                return level, i, scope.fields[i]
            if len(hits) > 1:
                raise AnalysisError(f"column reference is ambiguous: {'.'.join(parts)}")
            scope = scope.parent
            level += 1
        raise AnalysisError(f"column cannot be resolved: {'.'.join(parts)}")

    def _match(self, parts: tuple[str, ...]) -> list[int]:
        if len(parts) == 1:
            return [i for i, f in enumerate(self.fields) if f.name == parts[0]]
        if len(parts) >= 2:
            q, n = parts[-2], parts[-1]
            return [
                i for i, f in enumerate(self.fields)
                if f.name == n and f.qualifier is not None and f.qualifier == q
            ]
        return []


class AggregateCollector:
    """Dedups aggregate calls; translation returns $aggref placeholders that
    the planner rewrites to post-aggregation channels."""

    def __init__(self):
        self.calls: list[tuple[str, Optional[RowExpression], bool, Type]] = []

    def add(self, fn: str, arg: Optional[RowExpression], distinct: bool, type_: Type) -> int:
        key = (fn, arg, distinct)
        for i, (f, a, d, _) in enumerate(self.calls):
            if (f, a, d) == key:
                return i
        self.calls.append((fn, arg, distinct, type_))
        return len(self.calls) - 1


@dataclass(frozen=True)
class WindowOrderKey:
    expr: RowExpression
    ascending: bool = True
    nulls_first: bool = False


@dataclass(frozen=True)
class WindowCallSpec:
    """A fully-translated window call awaiting planning."""

    fn: str
    args: tuple[RowExpression, ...]  # value exprs (lag/lead default last)
    offset: int  # lag/lead offset, ntile count, nth_value position
    partition: tuple[RowExpression, ...]
    order: tuple[WindowOrderKey, ...]
    frame: tuple  # (unit, start_kind, start_val, end_kind, end_val)
    type: Type


class WindowCollector:
    """Dedups window calls; translation returns $winref placeholders the
    planner rewrites to Window-node output channels."""

    def __init__(self):
        self.calls: list[WindowCallSpec] = []

    def add(self, spec: WindowCallSpec) -> int:
        for i, s in enumerate(self.calls):
            if s == spec:
                return i
        self.calls.append(spec)
        return len(self.calls) - 1


def agg_result_type(fn: str, arg_type: Optional[Type]) -> Type:
    if fn == "count":
        return BIGINT
    if fn == "avg" or fn in STAT_AGGS:
        # Trino: avg(decimal(p,s)) -> decimal(38,s); the long-decimal limb
        # path keeps it exact.  Short decimals keep the engine's historical
        # f64 avg (exactness preserved by the scale-free sum state).
        if (isinstance(arg_type, DecimalType) and arg_type.precision > 18
                and fn == "avg"):
            return DecimalType(38, arg_type.scale)
        return DOUBLE
    if fn == "sum":
        if isinstance(arg_type, DecimalType):
            # sum(decimal(p,s)) -> decimal(38,s) when the input is long
            return DecimalType(38 if arg_type.precision > 18 else 18,
                               arg_type.scale)
        if arg_type is not None and arg_type.name == "real":
            return arg_type  # sum(real) -> real (Trino semantics)
        if arg_type in (DOUBLE,):
            return DOUBLE
        return BIGINT
    return arg_type  # min/max/any_value


def cast_to(e: RowExpression, t: Type) -> RowExpression:
    if e.type == t:
        return e
    if isinstance(e, Literal) and e.value is None:
        return Literal(t, None)
    return Call(t, "$cast", (e,))


def _decimal_of(t: Type) -> DecimalType:
    if isinstance(t, DecimalType):
        return t
    return DecimalType(18, 0)


def split_conjuncts(e: ast.Expr) -> list[ast.Expr]:
    if isinstance(e, ast.LogicalOp) and e.op == "AND":
        out: list[ast.Expr] = []
        for t in e.terms:
            out.extend(split_conjuncts(t))
        return out
    return [e]


def rewrite_expr(e: RowExpression, mapping: dict[RowExpression, RowExpression]) -> RowExpression:
    """Structural bottom-up rewrite (used to map group-by expressions and
    $aggref placeholders onto post-aggregation channels)."""
    if e in mapping:
        return mapping[e]
    if isinstance(e, Call):
        new_args = tuple(rewrite_expr(a, mapping) for a in e.args)
        if new_args != e.args:
            new = Call(e.type, e.name, new_args)
            return mapping.get(new, new)
    return e


# CREATE FUNCTION registry for the current planning thread: name ->
# (params, return_type_str, body AST).  Set by LogicalPlanner.plan from
# catalog.sql_functions (reference: metadata/GlobalFunctionCatalog +
# LanguageFunctionManager resolving SQL routines during analysis)
SQL_FUNCTIONS: contextvars.ContextVar[dict] = contextvars.ContextVar(
    "sql_functions", default={})


def _subst_params(e: ast.Expr, binding: dict[str, ast.Expr]) -> ast.Expr:
    """Replace unqualified ColumnRefs naming a parameter with the bound
    argument AST, recursively over the (frozen dataclass) expression tree."""
    if isinstance(e, ast.ColumnRef):
        if len(e.parts) == 1 and e.parts[0].lower() in binding:
            return binding[e.parts[0].lower()]
        return e
    if not dataclasses.is_dataclass(e):
        return e
    changes = {}
    for f in dataclasses.fields(e):
        v = getattr(e, f.name)
        if isinstance(v, ast.Expr):
            nv = _subst_params(v, binding)
            if nv is not v:
                changes[f.name] = nv
        elif isinstance(v, tuple):
            nv = tuple(
                _subst_params(x, binding) if isinstance(x, ast.Expr)
                else (dataclasses.replace(
                    x, **{g.name: _subst_params(getattr(x, g.name), binding)
                          for g in dataclasses.fields(x)
                          if isinstance(getattr(x, g.name), ast.Expr)})
                      if dataclasses.is_dataclass(x) else x)
                for x in v)
            if nv != v:
                changes[f.name] = nv
    return dataclasses.replace(e, **changes) if changes else e


class Translator:
    """AST expression -> typed IR over a scope.

    ``subquery_cb(node) -> RowExpression`` lets the planner splice subquery
    results in (joins appended to the current relation); ``aggregates`` makes
    aggregate calls legal, emitting ``$aggref`` placeholder calls.
    """

    def __init__(
        self,
        scope: Scope,
        aggregates: Optional[AggregateCollector] = None,
        subquery_cb: Optional[Callable[[ast.Expr], RowExpression]] = None,
        windows: Optional["WindowCollector"] = None,
    ):
        self.scope = scope
        self.aggregates = aggregates
        self.subquery_cb = subquery_cb
        self.windows = windows
        self._routine_stack: set[str] = set()

    # -- entry -------------------------------------------------------------
    def translate(self, e: ast.Expr) -> RowExpression:
        m = getattr(self, f"_t_{type(e).__name__}", None)
        if m is None:
            raise AnalysisError(f"unsupported expression: {type(e).__name__}")
        return m(e)

    # -- leaves ------------------------------------------------------------
    def _t_ColumnRef(self, e: ast.ColumnRef) -> RowExpression:
        from ..spi.types import RowType

        try:
            level, idx, field = self.scope.resolve(e.parts)
        except AnalysisError:
            # row field access: `col.field` parses as a qualified name; if
            # the prefix resolves to a ROW-typed column, the last part is a
            # field selector (reference: sql/tree/DereferenceExpression)
            if len(e.parts) >= 2:
                try:
                    level, idx, field = self.scope.resolve(e.parts[:-1])
                except AnalysisError:
                    raise AnalysisError(
                        f"column cannot be resolved: {'.'.join(e.parts)}")
                if isinstance(field.type, RowType):
                    base = (InputRef(field.type, idx) if level == 0
                            else OuterRef(field.type, idx, level))
                    fi = field.type.field_index(e.parts[-1])
                    ft = field.type.fields[fi][1]
                    return Call(ft, "$row_field", (base, Literal(BIGINT, fi)))
            raise
        if level == 0:
            return InputRef(field.type, idx)
        return OuterRef(field.type, idx, level)

    def _t_IntLiteral(self, e):
        return Literal(BIGINT, e.value)

    def _t_DecimalLiteral(self, e):
        text = e.text.lstrip("-")
        scale = len(text.split(".")[1]) if "." in text else 0
        digits = len(text.replace(".", "").lstrip("0")) or 1
        # literals type long (the dictionary-encoded int128 path) only when
        # the scaled value genuinely exceeds int64 — a 19-digit value that
        # still fits keeps the proven short-decimal kernels, so mixed
        # literal-vs-short-column expressions behave exactly as before
        precision = 18
        if digits > 18:
            scaled = int(text.replace(".", ""))
            if scaled > (1 << 63) - 1:
                precision = min(38, digits)
        return Literal(DecimalType(precision, scale), e.text)

    def _t_DoubleLiteral(self, e):
        return Literal(DOUBLE, e.value)

    def _t_StringLiteral(self, e):
        return Literal(VARCHAR, e.value)

    def _t_BooleanLiteral(self, e):
        return Literal(BOOLEAN, e.value)

    def _t_NullLiteral(self, e):
        return Literal(UNKNOWN, None)

    def _t_DateLiteral(self, e):
        return Literal(DATE, e.text)

    def _t_TimestampLiteral(self, e):
        return Literal(TIMESTAMP, e.text)

    def _t_IntervalLiteral(self, e):
        raise AnalysisError("interval literal only valid in date arithmetic")

    # -- arithmetic --------------------------------------------------------
    _OPNAMES = {"+": "add", "-": "subtract", "*": "multiply", "/": "divide",
                "%": "modulus"}

    def _t_BinaryOp(self, e: ast.BinaryOp) -> RowExpression:
        if e.op == "||":
            left = self.translate(e.left)
            right = self.translate(e.right)
            if not (is_string(left.type) and is_string(right.type)):
                raise AnalysisError("|| requires varchar operands")
            return Call(VARCHAR, "concat", (left, right))
        # date +- interval
        if isinstance(e.right, ast.IntervalLiteral):
            left = self.translate(e.left)
            if left.type not in (DATE, TIMESTAMP):
                raise AnalysisError("interval arithmetic requires a date")
            n = int(e.right.value)
            if e.right.negative:
                n = -n
            if e.op == "-":
                n = -n
            unit = e.right.unit
            if unit == "DAY":
                return Call(left.type, "add" if n >= 0 else "subtract",
                            (left, Literal(BIGINT, abs(n))))
            months = n * (12 if unit == "YEAR" else 1)
            if unit not in ("YEAR", "MONTH"):
                raise AnalysisError(f"unsupported interval unit {unit}")
            return Call(left.type, "add_months", (left, Literal(BIGINT, months)))
        left = self.translate(e.left)
        right = self.translate(e.right)
        name = self._OPNAMES[e.op]
        # an untyped NULL operand takes the other side's type (both NULL ->
        # bigint), so `1 / null` analyzes as bigint NULL instead of erroring
        if left.type == UNKNOWN:
            left = cast_to(left, right.type if right.type != UNKNOWN else BIGINT)
        if right.type == UNKNOWN:
            right = cast_to(right, left.type)
        lt, rt = left.type, right.type
        if lt == DATE and rt == DATE and name == "subtract":
            return Call(BIGINT, "subtract",
                        (cast_to(left, BIGINT), cast_to(right, BIGINT)))
        if not (is_numeric(lt) or lt == DATE) or not (is_numeric(rt) or rt == DATE):
            raise AnalysisError(f"cannot apply {e.op} to {lt}, {rt}")
        if lt == DATE or rt == DATE:  # date + days
            return Call(DATE, name, (left, right))
        if DOUBLE in (lt, rt) or lt.name == "real" or rt.name == "real":
            return Call(DOUBLE, name, (cast_to(left, DOUBLE), cast_to(right, DOUBLE)))
        if isinstance(lt, DecimalType) or isinstance(rt, DecimalType):
            ld, rd = _decimal_of(lt), _decimal_of(rt)
            long_in = ld.precision > 18 or rd.precision > 18
            if name == "divide":
                if long_in:
                    # exact long-decimal division (Trino: decimal / decimal
                    # stays decimal); the limb/dictionary path keeps it exact
                    out = DecimalType(38, max(ld.scale, rd.scale))
                    return Call(out, name, (left, right))
                return Call(DOUBLE, name, (cast_to(left, DOUBLE), cast_to(right, DOUBLE)))
            # precision widens only when an INPUT is already long: short
            # expressions keep the int64 kernels
            cap = 38 if long_in else 18
            if name in ("add", "subtract"):
                out = DecimalType(cap, max(ld.scale, rd.scale))
            elif name == "multiply":
                out = DecimalType(cap, min(ld.scale + rd.scale, 38))
            else:  # modulus
                out = DecimalType(cap, max(ld.scale, rd.scale))
            return Call(out, name, (cast_to(left, ld) if not isinstance(lt, DecimalType) else left,
                                    cast_to(right, rd) if not isinstance(rt, DecimalType) else right))
        return Call(BIGINT, name, (cast_to(left, BIGINT), cast_to(right, BIGINT)))

    def _t_UnaryOp(self, e: ast.UnaryOp) -> RowExpression:
        operand = self.translate(e.operand)
        if e.op == "-":
            return Call(operand.type, "negate", (operand,))
        return operand

    # -- predicates --------------------------------------------------------
    def _promote_pair(self, left: RowExpression, right: RowExpression):
        lt, rt = left.type, right.type
        if lt == rt:
            return left, right
        common = common_super_type(lt, rt)
        if common is None:
            raise AnalysisError(f"cannot compare {lt} and {rt}")
        return cast_to(left, common), cast_to(right, common)

    _CMPNAMES = {"=": "eq", "<>": "ne", "<": "lt", "<=": "le", ">": "gt", ">=": "ge"}

    def _t_Comparison(self, e: ast.Comparison) -> RowExpression:
        if isinstance(e.right, (ast.ScalarSubquery,)) or isinstance(e.left, ast.ScalarSubquery):
            if self.subquery_cb is None:
                raise AnalysisError("subquery not allowed here")
            left = (self.subquery_cb(e.left) if isinstance(e.left, ast.ScalarSubquery)
                    else self.translate(e.left))
            right = (self.subquery_cb(e.right) if isinstance(e.right, ast.ScalarSubquery)
                     else self.translate(e.right))
        else:
            left = self.translate(e.left)
            right = self.translate(e.right)
        left, right = self._promote_pair(left, right)
        return Call(BOOLEAN, self._CMPNAMES[e.op], (left, right))

    def _t_LogicalOp(self, e: ast.LogicalOp) -> RowExpression:
        terms = tuple(cast_to(self.translate(t), BOOLEAN) for t in e.terms)
        return Call(BOOLEAN, "$and" if e.op == "AND" else "$or", terms)

    def _t_Not(self, e: ast.Not) -> RowExpression:
        return Call(BOOLEAN, "$not", (cast_to(self.translate(e.operand), BOOLEAN),))

    def _t_IsNull(self, e: ast.IsNull) -> RowExpression:
        inner = Call(BOOLEAN, "$is_null", (self.translate(e.operand),))
        return Call(BOOLEAN, "$not", (inner,)) if e.negated else inner

    def _t_Between(self, e: ast.Between) -> RowExpression:
        operand = self.translate(e.operand)
        low = self.translate(e.low)
        high = self.translate(e.high)
        a, lo = self._promote_pair(operand, low)
        b, hi = self._promote_pair(operand, high)
        out = Call(BOOLEAN, "$and", (
            Call(BOOLEAN, "ge", (a, lo)),
            Call(BOOLEAN, "le", (b, hi)),
        ))
        return Call(BOOLEAN, "$not", (out,)) if e.negated else out

    def _t_InList(self, e: ast.InList) -> RowExpression:
        operand = self.translate(e.operand)
        items = [self.translate(i) for i in e.items]
        if is_string(operand.type):
            cast_items = items
        else:
            common = operand.type
            for i in items:
                c = common_super_type(common, i.type)
                if c is None:
                    raise AnalysisError(f"IN list type mismatch: {common} vs {i.type}")
                common = c
            operand = cast_to(operand, common)
            cast_items = [cast_to(i, common) for i in items]
        out = Call(BOOLEAN, "$in", (operand, *cast_items))
        return Call(BOOLEAN, "$not", (out,)) if e.negated else out

    def _t_Like(self, e: ast.Like) -> RowExpression:
        args = [self.translate(e.operand), self.translate(e.pattern)]
        if e.escape is not None:
            args.append(self.translate(e.escape))
        out = Call(BOOLEAN, "$like", tuple(args))
        return Call(BOOLEAN, "$not", (out,)) if e.negated else out

    def _t_InSubquery(self, e: ast.InSubquery) -> RowExpression:
        if self.subquery_cb is None:
            raise AnalysisError("IN subquery not allowed here")
        return self.subquery_cb(e)

    def _t_Exists(self, e: ast.Exists) -> RowExpression:
        if self.subquery_cb is None:
            raise AnalysisError("EXISTS not allowed here")
        return self.subquery_cb(e)

    def _t_ScalarSubquery(self, e: ast.ScalarSubquery) -> RowExpression:
        if self.subquery_cb is None:
            raise AnalysisError("scalar subquery not allowed here")
        return self.subquery_cb(e)

    # -- conditionals ------------------------------------------------------
    def _t_Case(self, e: ast.Case) -> RowExpression:
        # result type = common super of branches
        results = [self.translate(w.result) for w in e.whens]
        default = self.translate(e.default) if e.default is not None else Literal(UNKNOWN, None)
        out_t = default.type
        for r in results:
            c = common_super_type(out_t, r.type)
            if c is None:
                raise AnalysisError(f"CASE branch types differ: {out_t} vs {r.type}")
            out_t = c
        if out_t == UNKNOWN:
            raise AnalysisError("cannot determine CASE type")
        results = [cast_to(r, out_t) for r in results]
        default = cast_to(default, out_t)
        expr = default
        operand = self.translate(e.operand) if e.operand is not None else None
        for w, r in zip(reversed(e.whens), reversed(results)):
            if operand is not None:
                cmp_l, cmp_r = self._promote_pair(operand, self.translate(w.condition))
                cond = Call(BOOLEAN, "eq", (cmp_l, cmp_r))
            else:
                cond = cast_to(self.translate(w.condition), BOOLEAN)
            expr = Call(out_t, "$if", (cond, r, expr))
        return expr

    def _t_Cast(self, e: ast.Cast) -> RowExpression:
        inner = self.translate(e.operand)
        return cast_to(inner, parse_type(e.type_name))

    # -- arrays ------------------------------------------------------------
    def _t_ArrayLiteral(self, e: ast.ArrayLiteral) -> RowExpression:
        elems = [self.translate(x) for x in e.elements]
        if not all(isinstance(x, Literal) for x in elems):
            raise AnalysisError(
                "ARRAY elements must be constants (array values live in a "
                "host-side dictionary; see spi/types.ArrayType)")
        et = UNKNOWN
        for x in elems:
            c = common_super_type(et, x.type)
            if c is None:
                raise AnalysisError("ARRAY element types differ")
            et = c
        # ARRAY[] / all-NULL keeps element UNKNOWN; coercion against other
        # rows/columns resolves it (common_super_type recurses per element)
        return Literal(ArrayType(et), tuple(x.value for x in elems))

    def _t_Subscript(self, e: ast.Subscript) -> RowExpression:
        from ..spi.types import MapType, RowType

        base = self.translate(e.base)
        if isinstance(base.type, MapType):
            key = self.translate(e.index)
            return Call(base.type.value, "element_at", (base, key))
        if isinstance(base.type, RowType):
            idx = self.translate(e.index)
            if not isinstance(idx, Literal) or not isinstance(idx.value, int):
                raise AnalysisError("row subscript must be an integer literal")
            fi = idx.value - 1  # SQL row fields are 1-based
            if not (0 <= fi < len(base.type.fields)):
                raise AnalysisError("row subscript out of range")
            ft = base.type.fields[fi][1]
            return Call(ft, "$row_field", (base, Literal(BIGINT, fi)))
        if not isinstance(base.type, ArrayType):
            raise AnalysisError("subscript requires an array, map or row")
        idx = cast_to(self.translate(e.index), BIGINT)
        return Call(base.type.element, "element_at", (base, idx))

    def _t_Extract(self, e: ast.Extract) -> RowExpression:
        inner = self.translate(e.operand)
        fn = e.field_.lower()
        if fn not in ("year", "month", "day", "quarter"):
            raise AnalysisError(f"EXTRACT({e.field_}) not supported")
        return Call(BIGINT, fn, (inner,))

    # -- function calls ----------------------------------------------------
    def _t_FunctionCall(self, e: ast.FunctionCall) -> RowExpression:
        name = e.name.lower()
        if e.window is not None:
            return self._t_window_call(e)
        if name in WINDOW_FUNCTIONS:
            raise AnalysisError(f"{name} requires an OVER clause")
        if name in _AGG_ALIASES or name in ("approx_distinct", "count_if",
                                            "geometric_mean"):
            return self._t_agg_special(e, name)
        if name in AGG_FUNCTIONS or (name == "count" and e.is_star):
            if self.aggregates is None:
                raise AnalysisError(f"aggregate {name} not allowed here")
            if e.is_star or not e.args:
                if name != "count":
                    raise AnalysisError(f"{name} requires an argument")
                idx = self.aggregates.add("count", None, False, BIGINT)
                return Call(BIGINT, "$aggref", (Literal(BIGINT, idx),))
            arg = self.translate(e.args[0])
            if name in STAT_AGGS:
                if e.distinct:
                    raise AnalysisError(f"DISTINCT {name} not supported")
                arg = cast_to(arg, DOUBLE)
            out_t = agg_result_type(name, arg.type)
            idx = self.aggregates.add(name, arg, e.distinct, out_t)
            return Call(out_t, "$aggref", (Literal(BIGINT, idx),))
        if name == "position":
            # position(needle, haystack) = strpos(haystack, needle)
            a = self.translate(e.args[0])
            b = self.translate(e.args[1])
            return Call(BIGINT, "strpos", (b, a))
        if name == "coalesce":
            return self._t_coalesce(e)
        if name == "grouping":
            # grouping(a, b): bitmask of arguments NOT present in the row's
            # grouping set (reference: sql/analyzer/AggregationAnalyzer +
            # planner GroupingOperationRewriter).  The planner rewrites the
            # $grouping marker onto the GroupId channel.
            if self.aggregates is None:
                raise AnalysisError("grouping() not allowed here")
            if not e.args:
                raise AnalysisError("grouping() requires arguments")
            return Call(BIGINT, "$grouping",
                        tuple(self.translate(a) for a in e.args))
        return self._t_scalar_call(e)

    def _t_agg_special(self, e: ast.FunctionCall, name: str) -> RowExpression:
        """Aggregates that rewrite onto the core set (reference: these are
        standalone AccumulatorFactories in operator/aggregation/; here
        bool_and = min over booleans, approx_distinct = exact distinct count
        (zero-error 'approximation'), count_if = count over a nullable
        marker, geometric_mean = exp(avg(ln x)))."""
        if self.aggregates is None:
            raise AnalysisError(f"aggregate {name} not allowed here")
        if name in _AGG_ALIASES:
            core = _AGG_ALIASES[name]
            arg = self.translate(e.args[0])
            if name in ("bool_and", "bool_or", "every"):
                arg = cast_to(arg, BOOLEAN)
            out_t = agg_result_type(core, arg.type)
            idx = self.aggregates.add(core, arg, e.distinct, out_t)
            return Call(out_t, "$aggref", (Literal(BIGINT, idx),))
        if name == "approx_distinct":
            arg = self.translate(e.args[0])
            idx = self.aggregates.add("count", arg, True, BIGINT)
            return Call(BIGINT, "$aggref", (Literal(BIGINT, idx),))
        if name == "count_if":
            cond = cast_to(self.translate(e.args[0]), BOOLEAN)
            marker = Call(BIGINT, "$if",
                          (cond, Literal(BIGINT, 1), Literal(BIGINT, None)))
            idx = self.aggregates.add("count", marker, False, BIGINT)
            return Call(BIGINT, "$aggref", (Literal(BIGINT, idx),))
        # geometric_mean
        arg = cast_to(self.translate(e.args[0]), DOUBLE)
        idx = self.aggregates.add("avg", Call(DOUBLE, "ln", (arg,)), False,
                                  DOUBLE)
        return Call(DOUBLE, "exp",
                    (Call(DOUBLE, "$aggref", (Literal(BIGINT, idx),)),))

    def _t_coalesce(self, e: ast.FunctionCall) -> RowExpression:
        args = [self.translate(a) for a in e.args]
        out_t = UNKNOWN
        for a in args:
            c = common_super_type(out_t, a.type)
            if c is None:
                raise AnalysisError("COALESCE argument types differ")
            out_t = c
        return Call(out_t, "$coalesce", tuple(cast_to(a, out_t) for a in args))

    def _t_scalar_call(self, e: ast.FunctionCall) -> RowExpression:
        name = e.name.lower()
        if name == "mod":
            return self._t_BinaryOp(ast.BinaryOp("%", e.args[0], e.args[1]))
        if name == "if":
            cond = cast_to(self.translate(e.args[0]), BOOLEAN)
            t = self.translate(e.args[1])
            f = (self.translate(e.args[2]) if len(e.args) > 2
                 else Literal(UNKNOWN, None))
            common = common_super_type(t.type, f.type)
            if common is None or common == UNKNOWN:
                raise AnalysisError("IF branch types differ")
            return Call(common, "$if",
                        (cond, cast_to(t, common), cast_to(f, common)))
        if name == "date_trunc":
            if not isinstance(e.args[0], ast.StringLiteral):
                raise AnalysisError("date_trunc unit must be a string literal")
            unit = e.args[0].value.lower()
            if unit not in ("year", "quarter", "month", "week", "day"):
                raise AnalysisError(f"date_trunc unit not supported: {unit}")
            operand = self.translate(e.args[1])
            if operand.type not in (DATE, TIMESTAMP):
                raise AnalysisError("date_trunc requires a date or timestamp")
            return Call(operand.type, f"date_trunc_{unit}", (operand,))
        if name in ("greatest", "least"):
            args = [self.translate(a) for a in e.args]
            if any(is_string(a.type) for a in args):
                raise AnalysisError(
                    f"{name} over varchar not supported (dictionary codes "
                    "have no cross-column order)")
            common = args[0].type
            for a in args[1:]:
                c = common_super_type(common, a.type)
                if c is None:
                    raise AnalysisError(f"{name} argument types differ")
                common = c
            return Call(common, name, tuple(cast_to(a, common) for a in args))
        if name == "sign":
            a = self.translate(e.args[0])
            out = DOUBLE if a.type == DOUBLE else BIGINT
            return Call(out, "sign", (a,))
        if name == "nullif":
            a = self.translate(e.args[0])
            b = self.translate(e.args[1])
            pa, pb = self._promote_pair(a, b)
            return Call(a.type, "$if",
                        (Call(BOOLEAN, "eq", (pa, pb)), Literal(a.type, None), a))
        if name in ("json_extract", "json_extract_scalar",
                    "json_array_length"):
            a = self.translate(e.args[0])
            if not is_string(a.type):
                raise AnalysisError(f"{name} requires a varchar argument")
            if name == "json_array_length":
                return Call(BIGINT, name, (a,))
            return Call(VARCHAR, name,
                        (a, cast_to(self.translate(e.args[1]), VARCHAR)))
        if name == "repeat":
            # repeat(element, count) -> array(T)
            # (reference: operator/scalar/RepeatFunction.java — NOT a string
            # repetition; Trino has no string repeat)
            a = self.translate(e.args[0])
            b = self.translate(e.args[1])
            return Call(ArrayType(a.type), "repeat", (a, cast_to(b, BIGINT)))
        if name in ("row", "map"):
            # constant constructors -> dictionary-encoded literals
            # (reference: sql/tree/Row, MapConstructor; non-constant
            # construction would need device->dictionary materialization)
            from ..spi.types import MapType, RowType

            args = [self.translate(x) for x in e.args]
            if not all(isinstance(x, Literal) for x in args):
                raise AnalysisError(
                    f"{name.upper()} constructor arguments must be constants")
            if name == "row":
                t = RowType(tuple((None, x.type) for x in args))
                return Literal(t, tuple(x.value for x in args))
            if len(args) != 2 or not all(
                    isinstance(x.type, ArrayType) for x in args):
                raise AnalysisError("MAP(keys_array, values_array) expected")
            ks, vs = args[0].value, args[1].value
            if ks is None or vs is None or len(ks) != len(vs):
                raise AnalysisError("MAP arrays must be equal length")
            t = MapType(args[0].type.element, args[1].type.element)
            return Literal(t, tuple(sorted(zip(ks, vs))))
        if name in ("map_keys", "map_values"):
            from ..spi.types import MapType

            a = self.translate(e.args[0])
            if not isinstance(a.type, MapType):
                raise AnalysisError(f"{name} requires a map argument")
            et = a.type.key if name == "map_keys" else a.type.value
            return Call(ArrayType(et), name, (a,))
        if name in ("cardinality", "element_at", "contains", "array_position"):
            from ..spi.types import MapType

            a = self.translate(e.args[0])
            if isinstance(a.type, MapType):
                if name == "cardinality":
                    return Call(BIGINT, "cardinality", (a,))
                if name == "element_at":
                    b = self.translate(e.args[1])
                    return Call(a.type.value, "element_at",
                                (a, cast_to(b, a.type.key)))
                raise AnalysisError(f"{name} not defined for maps")
            if not isinstance(a.type, ArrayType):
                raise AnalysisError(f"{name} requires an array argument")
            if name == "cardinality":
                return Call(BIGINT, "cardinality", (a,))
            b = self.translate(e.args[1])
            if name == "element_at":
                return Call(a.type.element, "element_at",
                            (a, cast_to(b, BIGINT)))
            out_t = BOOLEAN if name == "contains" else BIGINT
            return Call(out_t, name, (a, b))
        udf = SQL_FUNCTIONS.get().get(name)
        if udf is not None:
            return self._t_sql_routine(name, udf, e.args)
        if name not in _SCALAR_TYPES:
            raise AnalysisError(f"function not registered: {name}")
        args = tuple(self.translate(a) for a in e.args)
        rule = _SCALAR_TYPES[name]
        if rule == "arg":
            out_t = args[0].type
        elif rule == "double":
            out_t = DOUBLE
            args = tuple(cast_to(a, DOUBLE) for a in args)
        elif rule == "bigint":
            out_t = BIGINT
        elif rule == "boolean":
            out_t = BOOLEAN
        else:
            out_t = VARCHAR
        return Call(out_t, name, args)

    def _t_sql_routine(self, name: str, udf, arg_asts) -> RowExpression:
        """Inline a CREATE FUNCTION body: substitute parameter references
        with the (type-cast) argument ASTs, then translate in the calling
        scope (reference: sql/routine/SqlRoutinePlanner inlining scalar
        RETURN bodies; recursion is rejected like the reference's analyzer)."""
        params, return_type, body = udf
        if len(arg_asts) != len(params):
            raise AnalysisError(
                f"{name} expects {len(params)} arguments, got {len(arg_asts)}")
        if name in self._routine_stack:
            raise AnalysisError(f"recursive SQL function: {name}")
        binding = {
            pname.lower(): ast.Cast(a, ptype)
            for (pname, ptype), a in zip(params, arg_asts)}
        inlined = ast.Cast(_subst_params(body, binding), return_type)
        self._routine_stack.add(name)
        try:
            return self.translate(inlined)
        finally:
            self._routine_stack.discard(name)

    # -- window calls ------------------------------------------------------
    def _const_int(self, e: ast.Expr, what: str) -> int:
        ir = self.translate(e)
        if isinstance(ir, Literal) and isinstance(ir.value, int):
            return ir.value
        raise AnalysisError(f"{what} must be an integer constant")

    def _t_window_call(self, e: ast.FunctionCall) -> RowExpression:
        if self.windows is None:
            raise AnalysisError("window function not allowed here")
        name = e.name.lower()
        w = e.window
        partition = tuple(self.translate(p) for p in w.partition_by)
        order = tuple(
            WindowOrderKey(
                self.translate(s.expr), s.ascending,
                s.nulls_first if s.nulls_first is not None else not s.ascending)
            for s in w.order_by)
        if w.frame is not None:
            if w.frame.start.kind == "UNBOUNDED_FOLLOWING" or \
                    w.frame.end.kind == "UNBOUNDED_PRECEDING":
                raise AnalysisError("invalid window frame bounds")
            fr = (w.frame.unit, w.frame.start.kind, w.frame.start.value,
                  w.frame.end.kind, w.frame.end.value)
        else:
            fr = ("RANGE", "UNBOUNDED_PRECEDING", None, "CURRENT", None)
        args: tuple[RowExpression, ...] = ()
        offset = 1
        if name in ("rank", "dense_rank", "row_number", "percent_rank",
                    "cume_dist"):
            if e.args:
                raise AnalysisError(f"{name} takes no arguments")
            out_t = DOUBLE if name in ("percent_rank", "cume_dist") else BIGINT
        elif name == "ntile":
            offset = self._const_int(e.args[0], "ntile bucket count")
            if offset <= 0:
                raise AnalysisError("ntile bucket count must be positive")
            out_t = BIGINT
        elif name in ("lag", "lead"):
            arg = self.translate(e.args[0])
            if len(e.args) > 1:
                offset = self._const_int(e.args[1], f"{name} offset")
            out_t = arg.type
            args = (arg,)
            if len(e.args) > 2:
                d = self.translate(e.args[2])
                common = common_super_type(out_t, d.type)
                if common is None:
                    raise AnalysisError(f"{name} default type mismatch")
                out_t = common
                args = (cast_to(arg, common), cast_to(d, common))
        elif name in ("first_value", "last_value"):
            arg = self.translate(e.args[0])
            out_t = arg.type
            args = (arg,)
        elif name == "nth_value":
            arg = self.translate(e.args[0])
            offset = self._const_int(e.args[1], "nth_value position")
            if offset <= 0:
                raise AnalysisError("nth_value position must be positive")
            out_t = arg.type
            args = (arg,)
        elif name == "count" and (e.is_star or not e.args):
            name = "count_star"
            out_t = BIGINT
        elif name in AGG_FUNCTIONS:
            if e.distinct:
                raise AnalysisError("DISTINCT window aggregates not supported")
            if name in STAT_AGGS:
                raise AnalysisError(f"{name} OVER (...) not supported yet")
            arg = self.translate(e.args[0])
            if name == "avg":
                out_t = DOUBLE
                args = (cast_to(arg, DOUBLE),)
            elif name == "any_value":
                name = "first_value"
                out_t = arg.type
                args = (arg,)
            else:
                out_t = agg_result_type(name, arg.type)
                args = (arg,)
        else:
            raise AnalysisError(f"not a window function: {name}")
        spec = WindowCallSpec(name, args, offset, partition, order, fr, out_t)
        idx = self.windows.add(spec)
        return Call(out_t, "$winref", (Literal(BIGINT, idx),))
