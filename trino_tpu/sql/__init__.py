"""SQL frontend: lexer/parser/AST, analyzer, row-expression IR.

Re-expresses core/trino-parser + core/trino-main sql/analyzer + sql/relational
(see module docstrings).  Pure Python, jax-free — lowering lives in
``trino_tpu.ops``.
"""
