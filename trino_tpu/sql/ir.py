"""Row-expression IR.

The analogue of Trino's ``io.trino.sql.relational.RowExpression`` family
(reference: core/trino-main sql/relational/RowExpression.java — CallExpression /
ConstantExpression / InputReferenceExpression / SpecialForm).  Where Trino
compiles this IR to JVM bytecode (sql/gen/PageFunctionCompiler.java:104), we
lower it to a jaxpr via tracing (trino_tpu/ops/expr.py).

Special forms are spelled as ``Call`` with ``$``-prefixed names so the IR stays
two-node-kinds simple: ``$and $or $not $if $coalesce $in $is_null $cast
$like $between``.  NULL semantics are SQL three-valued logic; every lowered
expression produces a (value, validity) pair.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from ..spi.types import Type

__all__ = ["RowExpression", "InputRef", "Literal", "Call", "call"]


@dataclass(frozen=True)
class RowExpression:
    type: Type


@dataclass(frozen=True)
class InputRef(RowExpression):
    """Reference to input channel ``index`` of the operator's batch."""

    index: int = 0

    def __str__(self) -> str:
        return f"#{self.index}"


@dataclass(frozen=True)
class Literal(RowExpression):
    """A constant.  ``None`` value = typed SQL NULL.  Strings stay python
    str here; the lowering resolves them against column dictionaries."""

    value: Any = None

    def __str__(self) -> str:
        return repr(self.value)


@dataclass(frozen=True)
class Call(RowExpression):
    name: str = ""
    args: tuple[RowExpression, ...] = ()

    def __str__(self) -> str:
        return f"{self.name}({', '.join(map(str, self.args))})"


@dataclass(frozen=True)
class OuterRef(RowExpression):
    """Reference to field ``index`` of an enclosing query's scope, ``level``
    scopes up.  Only appears transiently while planning subqueries; the
    decorrelation rewrites (planner/logical.py) eliminate every OuterRef
    before execution — mirrors Trino's ApplyNode + correlation symbols
    (reference: sql/planner/plan/ApplyNode.java, optimizer rules
    TransformCorrelated*.java)."""

    index: int = 0
    level: int = 1

    def __str__(self) -> str:
        return f"outer{self.level}#{self.index}"


def call(name: str, type_: Type, *args: RowExpression) -> Call:
    return Call(type_, name, tuple(args))


def walk(expr: RowExpression):
    """Pre-order traversal."""
    yield expr
    if isinstance(expr, Call):
        for a in expr.args:
            yield from walk(a)


def referenced_inputs(expr: RowExpression) -> set[int]:
    return {e.index for e in walk(expr) if isinstance(e, InputRef)}
