"""SQL AST node definitions.

The analogue of ``core/trino-parser``'s tree package (reference:
core/trino-parser/src/main/java/io/trino/sql/tree — Query,
QuerySpecification, Select, Join, ComparisonExpression, ...), trimmed to the
grammar subset the engine supports and grown alongside it.  Pure dataclasses;
no behavior beyond printing.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Union

# --------------------------------------------------------------------------
# expressions


@dataclass(frozen=True)
class Expr:
    pass


@dataclass(frozen=True)
class ColumnRef(Expr):
    parts: tuple[str, ...]  # e.g. ("lineitem", "l_orderkey") or ("l_orderkey",)

    def __str__(self):
        return ".".join(self.parts)


@dataclass(frozen=True)
class IntLiteral(Expr):
    value: int


@dataclass(frozen=True)
class DecimalLiteral(Expr):
    text: str  # keep exact text; analyzer decides decimal(p,s)


@dataclass(frozen=True)
class DoubleLiteral(Expr):
    value: float


@dataclass(frozen=True)
class StringLiteral(Expr):
    value: str


@dataclass(frozen=True)
class BooleanLiteral(Expr):
    value: bool


@dataclass(frozen=True)
class NullLiteral(Expr):
    pass


@dataclass(frozen=True)
class DateLiteral(Expr):
    text: str  # 'YYYY-MM-DD'


@dataclass(frozen=True)
class TimestampLiteral(Expr):
    text: str


@dataclass(frozen=True)
class IntervalLiteral(Expr):
    value: str  # e.g. '3'
    unit: str  # DAY | MONTH | YEAR
    negative: bool = False


@dataclass(frozen=True)
class BinaryOp(Expr):
    op: str  # + - * / %
    left: Expr
    right: Expr


@dataclass(frozen=True)
class UnaryOp(Expr):
    op: str  # - +
    operand: Expr


@dataclass(frozen=True)
class Comparison(Expr):
    op: str  # = <> < <= > >=
    left: Expr
    right: Expr


@dataclass(frozen=True)
class LogicalOp(Expr):
    op: str  # AND | OR
    terms: tuple[Expr, ...]


@dataclass(frozen=True)
class Not(Expr):
    operand: Expr


@dataclass(frozen=True)
class IsNull(Expr):
    operand: Expr
    negated: bool = False


@dataclass(frozen=True)
class Between(Expr):
    operand: Expr
    low: Expr
    high: Expr
    negated: bool = False


@dataclass(frozen=True)
class InList(Expr):
    operand: Expr
    items: tuple[Expr, ...]
    negated: bool = False


@dataclass(frozen=True)
class InSubquery(Expr):
    operand: Expr
    query: "Query"
    negated: bool = False


@dataclass(frozen=True)
class Exists(Expr):
    query: "Query"
    negated: bool = False


@dataclass(frozen=True)
class ScalarSubquery(Expr):
    query: "Query"


@dataclass(frozen=True)
class Like(Expr):
    operand: Expr
    pattern: Expr
    escape: Optional[Expr] = None
    negated: bool = False


@dataclass(frozen=True)
class FrameBound:
    """One window-frame endpoint: kind in (UNBOUNDED_PRECEDING, PRECEDING,
    CURRENT, FOLLOWING, UNBOUNDED_FOLLOWING); value set for the offset kinds."""

    kind: str
    value: Optional[int] = None


@dataclass(frozen=True)
class WindowFrame:
    unit: str  # ROWS | RANGE
    start: FrameBound = FrameBound("UNBOUNDED_PRECEDING")
    end: FrameBound = FrameBound("CURRENT")


@dataclass(frozen=True)
class WindowSpec:
    partition_by: tuple[Expr, ...] = ()
    order_by: tuple["SortItem", ...] = ()
    frame: Optional[WindowFrame] = None


@dataclass(frozen=True)
class FunctionCall(Expr):
    name: str
    args: tuple[Expr, ...]
    distinct: bool = False
    is_star: bool = False  # count(*)
    window: Optional[WindowSpec] = None  # fn(...) OVER (...)


@dataclass(frozen=True)
class ArrayLiteral(Expr):
    """ARRAY[e1, e2, ...] constructor (reference: sql/tree/Array.java)."""

    elements: tuple[Expr, ...]


@dataclass(frozen=True)
class Subscript(Expr):
    """base[index] — array element access (reference:
    sql/tree/SubscriptExpression.java)."""

    base: Expr
    index: Expr


@dataclass(frozen=True)
class Cast(Expr):
    operand: Expr
    type_name: str


@dataclass(frozen=True)
class Extract(Expr):
    field_: str  # YEAR | MONTH | DAY | QUARTER
    operand: Expr


@dataclass(frozen=True)
class WhenClause:
    condition: Expr  # for simple case: the comparand value
    result: Expr


@dataclass(frozen=True)
class Case(Expr):
    operand: Optional[Expr]  # simple CASE has an operand; searched has None
    whens: tuple[WhenClause, ...]
    default: Optional[Expr]


# --------------------------------------------------------------------------
# relations


@dataclass(frozen=True)
class Relation:
    pass


@dataclass(frozen=True)
class Table(Relation):
    name: str
    alias: Optional[str] = None


@dataclass(frozen=True)
class SubqueryRelation(Relation):
    query: "Query"
    alias: Optional[str] = None
    column_names: Optional[tuple[str, ...]] = None  # AS v(a, b, c)


@dataclass(frozen=True)
class MatchRecognizeRelation(Relation):
    """input MATCH_RECOGNIZE (PARTITION BY ... ORDER BY ... MEASURES ...
    PATTERN (...) DEFINE ...) (reference: sql/tree/PatternRecognitionRelation
    .java; SqlBase.g4 patternRecognition)."""

    input: Relation
    partition_by: tuple[Expr, ...]
    order_by: tuple["SortItem", ...]
    measures: tuple[tuple[Expr, str], ...]  # (expr, output name)
    pattern: str
    defines: tuple[tuple[str, Expr], ...]  # (label, condition)
    skip_past: bool = True  # AFTER MATCH SKIP PAST LAST ROW (default)
    alias: Optional[str] = None


@dataclass(frozen=True)
class TableFunctionRelation(Relation):
    """TABLE(fn(args...)) (reference: spi/function/table/
    ConnectorTableFunction.java; executed by LeafTableFunctionOperator)."""

    name: str
    args: tuple[Expr, ...]
    alias: Optional[str] = None
    column_names: Optional[tuple[str, ...]] = None


@dataclass(frozen=True)
class UnnestRelation(Relation):
    """UNNEST(arr, ...) [WITH ORDINALITY] (reference: sql/tree/Unnest.java;
    planned as UnnestNode, executed by operator/unnest/UnnestOperator.java:42).
    Array arguments may reference columns of relations to the left (lateral
    implicit join, SQL:2016 7.6 <table reference>)."""

    exprs: tuple[Expr, ...]
    ordinality: bool = False
    alias: Optional[str] = None
    column_names: Optional[tuple[str, ...]] = None


@dataclass(frozen=True)
class Join(Relation):
    join_type: str  # INNER | LEFT | RIGHT | FULL | CROSS
    left: Relation
    right: Relation
    condition: Optional[Expr] = None  # ON expr; None for CROSS / implicit


# --------------------------------------------------------------------------
# query structure


@dataclass(frozen=True)
class SelectItem:
    expr: Optional[Expr]  # None => * (all columns)
    alias: Optional[str] = None
    star_prefix: Optional[str] = None  # t.* support


@dataclass(frozen=True)
class SortItem:
    expr: Expr
    ascending: bool = True
    nulls_first: Optional[bool] = None


@dataclass(frozen=True)
class QuerySpec:
    select: tuple[SelectItem, ...]
    distinct: bool = False
    from_: Optional[Relation] = None
    where: Optional[Expr] = None
    # elements are plain Exprs or GroupingSets/Rollup/Cube grouping elements
    group_by: tuple = ()
    having: Optional[Expr] = None


@dataclass(frozen=True)
class GroupingSets:
    """GROUP BY GROUPING SETS ((a, b), (a), ()) element (reference:
    sql/tree/GroupingSets.java; SqlBase.g4 groupingElement)."""

    sets: tuple[tuple[Expr, ...], ...]


@dataclass(frozen=True)
class Rollup:
    """GROUP BY ROLLUP (a, b) — prefix hierarchy of grouping sets."""

    exprs: tuple[Expr, ...]


@dataclass(frozen=True)
class Cube:
    """GROUP BY CUBE (a, b) — all subsets as grouping sets."""

    exprs: tuple[Expr, ...]


@dataclass(frozen=True)
class SetOp:
    """UNION / INTERSECT / EXCEPT (reference: sql/tree/Union.java,
    Intersect.java, Except.java; planned via SetOperationNode)."""

    op: str  # UNION | INTERSECT | EXCEPT
    distinct: bool
    left: "QueryBody"
    right: "QueryBody"


@dataclass(frozen=True)
class WithQuery:
    name: str
    query: "Query"
    column_names: Optional[tuple[str, ...]] = None


# a query body is a SELECT spec, a set operation over bodies, or a nested
# parenthesized query (which may carry its own ORDER BY / LIMIT)
@dataclass(frozen=True)
class ValuesBody:
    """VALUES (a, b), (c, d) as a query body (reference: sql/tree/Values.java;
    SqlBase.g4 queryPrimary -> VALUES expression*)."""

    rows: tuple[tuple[Expr, ...], ...]


QueryBody = Union["QuerySpec", "SetOp", "Query", "ValuesBody"]


@dataclass(frozen=True)
class Query:
    body: QueryBody
    order_by: tuple[SortItem, ...] = ()
    limit: Optional[int] = None
    with_: tuple[WithQuery, ...] = ()


# --------------------------------------------------------------------------
# statements


@dataclass(frozen=True)
class Statement:
    pass


@dataclass(frozen=True)
class QueryStatement(Statement):
    query: Query


@dataclass(frozen=True)
class Explain(Statement):
    statement: Statement
    analyze: bool = False
    type_: str = "LOGICAL"  # LOGICAL | DISTRIBUTED


@dataclass(frozen=True)
class CreateTableAsSelect(Statement):
    table: str
    query: Query


@dataclass(frozen=True)
class CreateTable(Statement):
    """CREATE TABLE t (col type, ...) — columns as (name, type_text)."""

    table: str
    columns: tuple[tuple[str, str], ...] = ()


@dataclass(frozen=True)
class DropTable(Statement):
    table: str
    if_exists: bool = False


@dataclass(frozen=True)
class Delete(Statement):
    table: str
    where: Optional[Expr] = None


@dataclass(frozen=True)
class InsertInto(Statement):
    table: str
    query: Query


@dataclass(frozen=True)
class StartTransaction(Statement):
    """START TRANSACTION / BEGIN (reference: sql/tree/StartTransaction.java)."""


@dataclass(frozen=True)
class Commit(Statement):
    pass


@dataclass(frozen=True)
class Rollback(Statement):
    pass


@dataclass(frozen=True)
class CreateFunction(Statement):
    """CREATE FUNCTION with a scalar RETURN-expression body (reference:
    sql/routine/SqlRoutineAnalyzer — the inlineable subset)."""

    name: str
    params: tuple[tuple[str, str], ...]  # (name, type string)
    return_type: str
    body: Expr


@dataclass(frozen=True)
class DropFunction(Statement):
    name: str


@dataclass(frozen=True)
class ShowTables(Statement):
    pass


@dataclass(frozen=True)
class ShowColumns(Statement):
    table: str = ""


@dataclass(frozen=True)
class CreateView(Statement):
    """CREATE [OR REPLACE] [MATERIALIZED] VIEW (reference:
    execution/CreateViewTask.java, CreateMaterializedViewTask.java)."""

    name: str = ""
    query: "Query" = None
    replace: bool = False
    materialized: bool = False


@dataclass(frozen=True)
class DropView(Statement):
    name: str = ""
    if_exists: bool = False
    materialized: bool = False


@dataclass(frozen=True)
class RefreshMaterializedView(Statement):
    """REFRESH MATERIALIZED VIEW (reference:
    operator/RefreshMaterializedViewOperator.java:27)."""

    name: str = ""


@dataclass(frozen=True)
class SetSession(Statement):
    """SET SESSION prop = value (reference: execution/SetSessionTask.java)."""

    name: str = ""
    value: Expr = None


@dataclass(frozen=True)
class CallProcedure(Statement):
    """CALL proc(args) (reference: spi/procedure/Procedure.java,
    execution/CallTask.java)."""

    name: str = ""
    args: tuple = ()


@dataclass(frozen=True)
class Analyze(Statement):
    """ANALYZE table (reference: execution/AnalyzeTask-equivalent flow via
    StatisticsWriterOperator.java:35)."""

    table: str = ""
