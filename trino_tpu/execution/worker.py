"""Worker process: the engine's task control + data plane over HTTP.

The real process boundary the round-3 engine lacked (VERDICT item #3).
Mirrors the reference's worker surface (reference:
core/trino-main/src/main/java/io/trino/server/TaskResource.java):

- ``POST /v1/task/{task_id}``   create + start a task (TaskResource.java:140)
- ``GET  /v1/task/{task_id}/results/{buffer_id}/{token}``   pull-token page
  stream; a read at token T implicitly acks every earlier page
  (TaskResource.java:333, execution/buffer/ClientBuffer.java:318)
- ``GET  /v1/task/{task_id}/status``   long-pollable task state
- ``DELETE /v1/task/{task_id}``   cancel/abort (TaskResource.java:294)
- ``GET  /v1/info``   node liveness (the heartbeat target)
- ``PUT  /v1/shutdown``   graceful drain-and-exit
  (server/GracefulShutdownHandler.java:42)

The task descriptor travels as a zlib-compressed pickle (the trust domain is
the cluster's own coordinator, matching the reference's JSON-over-HTTP
between mutually-trusted nodes); pages travel as the serde wire format
(execution/serde.py — PageSerializer.java:58's role).

Run as ``python -m trino_tpu.execution.worker --port 0``; prints
``LISTENING <port>`` on stdout when ready.
"""

from __future__ import annotations

import argparse
import importlib
import json
import pickle
import sys
import threading
import time
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

__all__ = ["TaskServer", "encode_descriptor", "decode_descriptor", "main"]


def encode_descriptor(desc: dict) -> bytes:
    return zlib.compress(pickle.dumps(desc), level=1)


def decode_descriptor(data: bytes) -> dict:
    return pickle.loads(zlib.decompress(data))


class _TaskCanceled(Exception):
    """Internal unwind signal: the task was cancelled (DELETE or drain
    escalation) while sitting in an injected stall — terminal state is
    CANCELED, not FAILED, and no error classification applies."""


def build_catalog(spec: dict):
    """spec: {"factory": "module:callable", "kwargs": {...}} — the worker
    reconstructs its catalog locally (split generation happens worker-side;
    only control metadata crosses the wire)."""
    mod, fn = spec["factory"].split(":")
    factory = getattr(importlib.import_module(mod), fn)
    return factory(**spec.get("kwargs", {}))


class _Task:
    def __init__(self, task_id: str):
        self.task_id = task_id
        self.state = "RUNNING"
        self.error: Optional[str] = None
        # spi/errors.py classification of the failure, reported in status
        # JSON so the coordinator can decide fail-fast vs retry without
        # parsing message strings
        self.error_type: Optional[str] = None
        self.error_code: Optional[str] = None
        self.buffer = None  # OutputBuffer, set when planning completes
        # finished task span subtree (tracing.Span.to_dict) — published
        # BEFORE the terminal state so a status read that observes
        # FINISHED/FAILED always sees the span too
        self.span: Optional[dict] = None
        self.ready = threading.Event()
        self.thread: Optional[threading.Thread] = None
        # cluster memory feed: the owning query + the task's live HBM pool
        # (exec/revoking.TaskMemoryContext), reported per status sweep so
        # the coordinator's ClusterMemoryManager can aggregate reservations
        self.query_id: Optional[str] = None
        self.memory = None
        # flight-recorder ring slice for this task (telemetry/profiler.py),
        # harvested just before the terminal state and shipped alongside
        # the span so the coordinator can merge the device timeline
        self.profile: Optional[list] = None

    def status_json(self, include_span: bool = False) -> dict:
        mem = self.memory
        reserved = 0
        if mem is not None:
            reserved = int(mem.pool.reserved + mem.pool.reserved_revocable)
        out = {"state": self.state, "error": self.error,
               "error_type": self.error_type, "error_code": self.error_code,
               "query_id": self.query_id,
               "memory_reserved_bytes": reserved,
               # progress feed for the coordinator's drain/straggler logic:
               # planning done + pages produced so far
               "ready": self.ready.is_set(),
               "pages_out": getattr(self.buffer, "pages_enqueued", 0)}
        if include_span and self.span is not None:
            out["span"] = self.span
        if include_span and self.profile:
            out["profile"] = self.profile
        return out


class TaskServer:
    def __init__(self, port: int = 0):
        import os

        from .tracing import Tracer

        self.tasks: dict[str, _Task] = {}
        self._lock = threading.Lock()
        self._draining = False
        # set when a drain had to abandon running tasks at the deadline —
        # the process then exits with code 9 (vs 0 for a clean drain) so
        # the coordinator/operator can tell the two apart
        self.drain_timed_out = False
        # worker-local span collector: task spans are remote-parented from
        # the coordinator's traceparent header and shipped back (serialized)
        # with task completion
        self.tracer = Tracer(keep=200)
        # per-spawn shared secret (reference: InternalCommunicationConfig
        # sharedSecret): descriptors are pickles, so only the process tree
        # holding the secret may reach any endpoint that decodes or mutates
        self.secret = os.environ.get("TRINO_TPU_INTERNAL_SECRET")
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: bytes = b"",
                      content_type: str = "application/json",
                      headers: Optional[dict] = None) -> None:
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    server._get(self)
                except BrokenPipeError:
                    pass
                except Exception as e:  # noqa: BLE001
                    try:
                        self._send(500, json.dumps(
                            {"error": repr(e)}).encode())
                    # tpulint: disable=error-taxonomy -- double fault: peer hung up while we sent the 500
                    except Exception:
                        pass

            def do_POST(self):
                try:
                    server._post(self)
                except Exception as e:  # noqa: BLE001
                    self._send(500, json.dumps({"error": repr(e)}).encode())

            def do_DELETE(self):
                try:
                    server._delete(self)
                except Exception as e:  # noqa: BLE001
                    self._send(500, json.dumps({"error": repr(e)}).encode())

            def do_PUT(self):
                try:
                    server._put(self)
                except Exception as e:  # noqa: BLE001
                    self._send(500, json.dumps({"error": repr(e)}).encode())

        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]

    # ------------------------------------------------------------ handlers
    def _authorized(self, h) -> bool:
        import hmac

        if self.secret is None:
            return True
        if hmac.compare_digest(
                h.headers.get("X-Trino-Internal-Bearer") or "", self.secret):
            return True
        h._send(401, b'{"error": "missing or bad internal secret"}')
        return False

    def _get(self, h) -> None:
        from urllib.parse import parse_qs, urlsplit

        url = urlsplit(h.path)
        query = parse_qs(url.query)
        parts = [p for p in url.path.split("/") if p]
        if parts == ["v1", "info"]:
            h._send(200, json.dumps({
                "state": "SHUTTING_DOWN" if self._draining else "ACTIVE",
                "tasks": len(self.tasks)}).encode())
            return
        if parts == ["v1", "metrics"]:
            from ..telemetry.metrics import REGISTRY

            # ?format=json ships the raw registry snapshot — the structured
            # form the coordinator's scope=cluster fold merges (Prometheus
            # text can't be merged without re-parsing)
            if query.get("format", [""])[0] == "json":
                h._send(200, json.dumps(REGISTRY.snapshot()).encode())
                return
            # Prometheus text exposition of the worker-process registry
            h._send(200, REGISTRY.render_prometheus().encode(),
                    "text/plain; version=0.0.4")
            return
        if parts == ["v1", "status"]:
            # the heartbeat target: node state + EVERY task's state in one
            # payload, so the coordinator sweeps one poll per worker
            # (failure_detector.py caches this).  Spans stay out of the
            # sweep — they're fetched per task on completion.
            h._send(200, json.dumps({
                "state": "SHUTTING_DOWN" if self._draining else "ACTIVE",
                "tasks": {tid: t.status_json()
                          for tid, t in list(self.tasks.items())},
            }).encode())
            return
        if len(parts) == 4 and parts[:2] == ["v1", "task"] and \
                parts[3] == "status":
            t = self.tasks.get(parts[2])
            if t is None:
                h._send(404, b'{"error": "no such task"}')
                return
            h._send(200, json.dumps(t.status_json(
                include_span=True)).encode())
            return
        if len(parts) == 6 and parts[:2] == ["v1", "task"] and \
                parts[3] == "results":
            if not self._authorized(h):
                return
            # ?maxwait= bounds the server-side long-poll so short
            # non-blocking client polls return promptly (default keeps the
            # historical 5 s long-poll)
            try:
                maxwait = float(query.get("maxwait", ["5.0"])[0])
            except ValueError:
                maxwait = 5.0
            maxwait = min(max(maxwait, 0.0), 5.0)
            self._get_results(h, parts[2], int(parts[4]), int(parts[5]),
                              maxwait)
            return
        h._send(404, b'{"error": "not found"}')

    def _get_results(self, h, task_id: str, buffer_id: int,
                     token: int, maxwait: float = 5.0) -> None:
        """Pull-token page read (TaskResource.getResults equivalent): body
        is length-prefixed serde frames; X-Next-Token / X-Done carry the
        protocol state.  ``maxwait`` bounds both blocking waits so the
        handler never outlives the client's own poll budget."""
        import struct

        t = self.tasks.get(task_id)
        if t is None:
            h._send(404, b'{"error": "no such task"}')
            return
        if t.state == "FAILED":
            h._send(500, json.dumps({
                "error": t.error, "error_type": t.error_type,
                "error_code": t.error_code}).encode())
            return
        if t.state == "CANCELED":
            # e.g. abandoned by a timed-out drain: report a retryable
            # EXTERNAL failure so retry_policy=QUERY re-runs the query
            # instead of waiting on a stream that will never finish
            h._send(500, json.dumps({
                "error": t.error or f"task {task_id} canceled on worker",
                "error_type": "EXTERNAL",
                "error_code": "REMOTE_TASK_ERROR"}).encode())
            return
        if not t.ready.wait(timeout=maxwait) or t.buffer is None:
            h._send(200, b"", "application/x-trino-pages",
                    {"X-Next-Token": token, "X-Done": 0})
            return
        pages, next_token, done = t.buffer.get(
            buffer_id, token, timeout=min(maxwait, 1.0))
        if done and t.buffer.aborted:
            # an aborted stream NEVER reads as a clean end-of-stream: the
            # producer is failing or was cancelled, but its thread may not
            # have recorded the verdict yet (buffer.abort() precedes the
            # state flip).  Wait briefly for the real error, else report a
            # retryable transport error — otherwise the consumer completes
            # the query with a truncated/empty "successful" result.
            deadline = time.monotonic() + min(maxwait, 2.0)
            while t.state == "RUNNING" and time.monotonic() < deadline:
                time.sleep(0.01)
            h._send(500, json.dumps({
                "error": t.error or f"task {task_id} output aborted",
                "error_type": t.error_type or "EXTERNAL",
                "error_code": t.error_code or "REMOTE_TASK_ERROR",
            }).encode())
            return
        body = bytearray()
        for p in pages:
            raw = p.data if hasattr(p, "data") else None
            if raw is None:  # unserialized batch (non-serde sink): encode
                from .serde import serialize_batch

                raw = serialize_batch(p)
            body += struct.pack("<I", len(raw))
            body += raw
        h._send(200, bytes(body), "application/x-trino-pages",
                {"X-Next-Token": next_token, "X-Done": int(done)})

    def _post(self, h) -> None:
        if not self._authorized(h):
            return
        parts = [p for p in h.path.split("/") if p]
        if len(parts) == 3 and parts[:2] == ["v1", "task"]:
            if self._draining:
                h._send(503, b'{"error": "shutting down"}')
                return
            n = int(h.headers.get("Content-Length", 0))
            desc = decode_descriptor(h.rfile.read(n))
            task_id = parts[2]
            with self._lock:
                if task_id in self.tasks:
                    h._send(200, b'{"state": "RUNNING"}')
                    return
                t = _Task(task_id)
                self.tasks[task_id] = t
            t.thread = threading.Thread(
                target=self._run_task,
                args=(t, desc, h.headers.get("traceparent")), daemon=True,
                name=f"task-{task_id}")
            t.thread.start()
            h._send(200, b'{"state": "RUNNING"}')
            return
        h._send(404, b'{"error": "not found"}')

    def _delete(self, h) -> None:
        if not self._authorized(h):
            return
        parts = [p for p in h.path.split("/") if p]
        if len(parts) == 3 and parts[:2] == ["v1", "task"]:
            t = self.tasks.get(parts[2])
            if t is not None:
                if t.buffer is not None:
                    t.buffer.abort()
                t.state = "CANCELED" if t.state == "RUNNING" else t.state
                h._send(200, b'{"state": "CANCELED"}')
                return
        h._send(404, b'{"error": "not found"}')

    def _put(self, h) -> None:
        from urllib.parse import parse_qs, urlsplit

        if not self._authorized(h):
            return
        url = urlsplit(h.path)
        parts = [p for p in url.path.split("/") if p]
        if parts == ["v1", "shutdown"]:
            # graceful drain: refuse new tasks, exit once current ones end
            # (bounded — ?timeout_s= overrides TRINO_TPU_DRAIN_TIMEOUT_S)
            import os

            try:
                timeout_s = float(parse_qs(url.query).get(
                    "timeout_s",
                    [os.environ.get("TRINO_TPU_DRAIN_TIMEOUT_S", "300")])[0])
            except ValueError:
                timeout_s = 300.0
            self._draining = True
            h._send(200, b'{"state": "SHUTTING_DOWN"}')
            threading.Thread(target=self._drain_and_exit,
                             args=(timeout_s,), daemon=True).start()
            return
        h._send(404, b'{"error": "not found"}')

    def _task_drained(self, t: _Task) -> bool:
        # a task may leave the drain only when it stopped running AND its
        # unfetched output is gone (fully acked or aborted) — exiting on
        # state alone would drop pages a consumer has not pulled yet
        if t.state == "RUNNING":
            return False
        b = t.buffer
        return b is None or b.drained

    def _drain_and_exit(self, timeout_s: float = 300.0) -> None:
        deadline = time.monotonic() + max(0.0, timeout_s)
        while time.monotonic() < deadline:
            if all(self._task_drained(t) for t in list(self.tasks.values())):
                break
            time.sleep(0.05)
        else:
            abandoned = [tid for tid, t in list(self.tasks.items())
                         if not self._task_drained(t)]
            if abandoned:
                self.drain_timed_out = True
                print(f"DRAIN TIMEOUT after {timeout_s:.1f}s "
                      f"abandoning tasks: {sorted(abandoned)}",
                      file=sys.stderr, flush=True)
                for tid in abandoned:
                    t = self.tasks.get(tid)
                    if t is None:
                        continue
                    if t.state == "RUNNING":
                        t.state = "CANCELED"
                    if t.buffer is not None:
                        t.buffer.abort()
        self.httpd.shutdown()

    # ------------------------------------------------------------ execution
    def _run_task(self, t: _Task, desc: dict,
                  traceparent_header: Optional[str] = None) -> None:
        import time as _time

        from ..telemetry import metrics as tm
        from ..telemetry import runtime as rt
        from .tracing import annotate_scan_span, parse_traceparent

        tm.TASKS_CREATED.inc()
        worker_addr = f"127.0.0.1:{self.port}"
        trec = rt.task_started(
            str(desc.get("query_id", "")), t.task_id,
            getattr(desc.get("fragment"), "id", -1),
            desc.get("task_index", -1), worker_addr)
        t0 = _time.perf_counter()
        # flight recorder: this thread's ring events attribute to the
        # coordinator-assigned (worker-visible) query id + this task
        from ..telemetry import profiler

        profiler.set_context(str(desc.get("query_id", "")), t.task_id)
        pt0 = profiler.now()
        # remote-parented span: the coordinator's traceparent header makes
        # this a local root carrying the query's trace identity; the ctx is
        # entered/exited explicitly so the span can close (and publish to
        # t.span) BEFORE the terminal state becomes visible
        ctx = self.tracer.span(
            "trino.task", remote=parse_traceparent(traceparent_header),
            **{"trino.task.id": t.task_id,
               "trino.task.worker": worker_addr})
        sp = ctx.__enter__()
        writer = None
        local = None
        state = "FINISHED"
        try:
            from ..exec.driver import run_pipelines
            from ..exec.local_planner import LocalPlanner
            from .durable_spool import DurableSpoolClient, DurableSpoolWriter
            from .exchange import OutputBuffer
            from .failure_injector import (
                GET_RESULTS_FAILURE,
                PROCESS_EXIT,
                TASK_FAILURE,
                TASK_OOM,
                TASK_STALL,
                InjectedFailure,
                check_wire_rules,
                match_wire_rule,
                sleep_with_cancel,
            )
            from .remote import HttpExchangeClient
            from .task import PartitionedOutputSink

            catalog = build_catalog(desc["catalog"])
            fragment = desc["fragment"]
            task_index = desc["task_index"]
            t.query_id = desc.get("query_id")
            # streaming descriptors carry the query-retry attempt at the top
            # level; FTE descriptors keep it inside the spool block
            attempt = desc.get(
                "attempt", desc.get("spool", {}).get("attempt", 0))
            rules = desc.get("failure_rules", [])
            if check_wire_rules(rules, PROCESS_EXIT, fragment.id,
                                task_index, attempt):
                # the real "node died" case: kill the whole worker process
                import os as _os

                _os._exit(17)
            if check_wire_rules(rules, TASK_FAILURE, fragment.id,
                                task_index, attempt):
                raise InjectedFailure(
                    f"injected TASK_FAILURE f{fragment.id}.t{task_index} "
                    f"attempt {attempt}")
            if check_wire_rules(rules, TASK_OOM, fragment.id, task_index,
                                attempt):
                from ..spi.memory import ExceededMemoryLimitError

                raise ExceededMemoryLimitError(
                    f"injected-oom f{fragment.id}.t{task_index}", 1 << 40, 0)
            stall = match_wire_rule(rules, TASK_STALL, fragment.id,
                                    task_index, attempt)
            if stall is not None and stall.get("stall_s"):
                # the stall polls the task's cancel flag (DELETE handler /
                # drain escalation both flip state off RUNNING) so an
                # injected straggler cannot outlive its query
                sleep_with_cancel(float(stall["stall_s"]),
                                  lambda: t.state != "RUNNING")
                if t.state != "RUNNING":
                    raise _TaskCanceled()
            if desc.get("upstream") and check_wire_rules(
                    rules, GET_RESULTS_FAILURE, fragment.id, task_index,
                    attempt):
                # streaming analogue of the FTE spool-read fault: the task's
                # exchange fetch from its producers fails
                raise InjectedFailure(
                    f"injected GET_RESULTS_FAILURE f{fragment.id}."
                    f"t{task_index} attempt {attempt}")

            clients = {}
            if "spool_upstream" in desc and desc["spool_upstream"]:
                def on_read(_d, _f=fragment.id, _t=task_index, _a=attempt):
                    if check_wire_rules(rules, GET_RESULTS_FAILURE, _f, _t,
                                        _a):
                        raise InjectedFailure("injected GET_RESULTS_FAILURE")

                for src_id, info in desc["spool_upstream"].items():
                    if info.get("merge"):
                        clients[src_id] = [
                            DurableSpoolClient([d], task_index, on_read)
                            for d in info["dirs"]
                        ]
                    else:
                        clients[src_id] = DurableSpoolClient(
                            info["dirs"], task_index, on_read)
            backoff_cfg = desc.get("exchange_backoff")
            # this task's exchange fetches carry ITS span as the trace
            # context (trace_id stays the query's)
            from .tracing import traceparent as _tp

            task_tp = _tp(sp)
            for src_id, info in desc.get("upstream", {}).items():
                uris = info["uris"]
                if info.get("merge"):
                    clients[src_id] = [
                        HttpExchangeClient([u], task_index,
                                           backoff=backoff_cfg,
                                           traceparent=task_tp)
                        for u in uris
                    ]
                else:
                    clients[src_id] = HttpExchangeClient(
                        uris, task_index, backoff=backoff_cfg,
                        traceparent=task_tp)
            planner = LocalPlanner(
                catalog,
                splits_per_node=desc.get("splits_per_node", 4),
                node_count=desc.get("node_count", 1),
                task_index=task_index,
                task_count=desc["task_count"],
                remote_clients=clients,
                dynamic_filtering=desc.get("dynamic_filtering", True),
                hbm_limit_bytes=desc.get("hbm_limit_bytes", 16 << 30),
            )
            t.memory = planner.memory
            local = planner.plan(fragment.root)
            if "spool" in desc:  # FTE: durable on-disk attempt spool
                spool = desc["spool"]
                writer = DurableSpoolWriter(
                    spool["task_dir"], spool["attempt"],
                    spool["num_partitions"])
                out = writer
            else:
                out = OutputBuffer(desc["num_partitions"])
            sink = PartitionedOutputSink(
                out,
                fragment.output_kind if fragment.output_kind != "OUTPUT"
                else "GATHER",
                fragment.output_keys, serde=True)
            local.pipelines[-1][-1] = sink
            if writer is None:
                t.buffer = out
            t.ready.set()
            run_pipelines(local.pipelines)
        except _TaskCanceled:
            state = "CANCELED"
            sp.set("canceled", True)
            if t.buffer is not None:
                t.buffer.abort()
            if writer is not None:
                writer.abort()
            t.ready.set()
        except BaseException as e:  # noqa: BLE001 — reported to coordinator
            from ..spi.errors import classify

            te = classify(e)
            t.error = f"{type(e).__name__}: {e}"
            t.error_type = te.error_type
            t.error_code = te.code.name
            state = "FAILED"
            sp.set("error", type(e).__name__)
            if t.buffer is not None:
                t.buffer.abort()
            if writer is not None:
                writer.abort()
            t.ready.set()
        try:
            if local is not None:
                from ..exec.driver import (collect_encoding_stats,
                                           collect_scan_stats)

                ingest = collect_scan_stats(local.pipelines)
                annotate_scan_span(sp, ingest)
                tm.observe_scan(ingest)
                tm.observe_encoding(collect_encoding_stats(local.pipelines))
        # tpulint: disable=error-taxonomy -- stats never fail a task
        except Exception:  # noqa: BLE001
            pass
        try:
            ctx.__exit__(None, None, None)
            t.span = sp.to_dict()  # span visible before terminal state read
            profiler.event(profiler.TASK, t.task_id, pt0, state=state)
            # sweep the ring slice for this task (run_pipelines group
            # threads inherited the context, so their operator events are
            # included) BEFORE the terminal state so a status read that
            # observes FINISHED/FAILED always sees the profile too
            t.profile = profiler.take_task_events(
                str(desc.get("query_id", "")), t.task_id)
            tm.TASK_WALL_SECONDS.record(_time.perf_counter() - t0)
            if state == "FAILED":
                tm.TASKS_FAILED.inc()
            rt.task_finished(trec, state, error=t.error)
        finally:
            # the terminal state MUST always land: a coordinator polling
            # status would otherwise wait on a RUNNING task forever
            t.state = state

    def serve_forever(self) -> None:
        self.httpd.serve_forever()


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args(argv)
    # the sitecustomize-preloaded jax ignores late env platform selection;
    # apply it through the config API before any backend use
    import os

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            import jax

            jax.config.update("jax_platforms", plat)
        # tpulint: disable=error-taxonomy -- platform override is advisory; default backend still boots
        except Exception:
            pass
    if os.environ.get("TRINO_TPU_TEST_BOOT_FAIL"):
        # deterministic boot-failure hook for WorkerProcess boot-timeout
        # tests: die with a diagnostic BEFORE printing LISTENING
        print("TRINO_TPU_TEST_BOOT_FAIL: injected boot failure",
              file=sys.stderr, flush=True)
        sys.exit(3)
    # Tier B persistence: point XLA at the on-disk compile cache and replay
    # the warm-key journal so the hottest shape buckets have live wrappers
    # (whose first invocation loads from disk, not a cold compile) before
    # the first task arrives
    from ..caching import executable_cache

    executable_cache.init_compile_cache()
    try:
        executable_cache.warm_at_boot()
    # tpulint: disable=error-taxonomy -- warming must never block boot
    except Exception:  # noqa: BLE001
        pass
    server = TaskServer(args.port)
    print(f"LISTENING {server.port}", flush=True)
    server.serve_forever()
    # serve_forever returns when a drain shut the server down; exit code 9
    # distinguishes "drain abandoned tasks at the deadline" from a clean 0
    sys.exit(9 if server.drain_timed_out else 0)


if __name__ == "__main__":
    main()
