"""Fault-tolerant execution: DURABLE spooled exchange + task retry.

The miniature of the reference's FTE mode (execution/scheduler/
faulttolerant/EventDrivenFaultTolerantQueryScheduler.java:201 +
spi/exchange/ExchangeManager.java:39 spooling):

- fragments run in topological order (producers complete before consumers
  start); every task's output is spooled TO DISK per consumer partition
  with atomic attempt commit (execution/durable_spool.py — the
  FileSystemExchangeManager role), so the unit of recovery genuinely
  survives task AND worker-process death;
- a failed task attempt is retried up to ``task_retry_attempts`` times with
  a fresh attempt directory (tasks are deterministic in (fragment,
  task_index, committed inputs), so re-execution is exact);
- consumers read only committed attempts — a mid-stream producer death can
  never poison a downstream task, which is exactly the property the
  streaming pipelined scheduler gives up;
- engine-level failure injection (execution/failure_injector.py, the
  FailureInjector.java:35 hook) targets task bodies, spool reads, spool
  bytes on disk, or the hosting worker process itself.

r15 additions — the coordinator is no longer the single point of failure:

- every FTE query appends to a write-ahead query-state log
  (execution/query_state.py): the plan snapshot at ``begin``, an
  ``attempt_start`` per attempt, and an fsync'd ``attempt_committed`` per
  first-winning commit.  ``run_fte_query(..., resume=pq)`` re-enters a
  half-finished query from that map: committed tasks are seeded as already
  resolved and are NEVER re-executed;
- the stage barrier is a ``threading.Condition`` — ``commit()`` and
  failure recording wake it immediately (the old 10 ms poll put a latency
  floor under every small stage);
- spool CRC failures (serde.SpoolCorruptionError — bit flips / torn
  frames that slipped past atomic rename) repair themselves: the corrupt
  committed attempt is discarded and its *producer* task re-runs, bounded
  by a per-query repair budget;
- the end-of-query ``shutil.rmtree`` became ``spool_gc.release`` — the
  same immediate reclamation on a clean finish, but leased so a crashed
  coordinator's root survives for recovery and the boot sweep (rather
  than leaking forever or vanishing mid-recovery).

The trade (identical to Trino FTE): no cross-stage streaming overlap, in
exchange for retryability.  ``Session(retry_policy="TASK")`` selects it.
"""

from __future__ import annotations

import os
import re
import shutil
import threading
import time
from typing import Optional

from . import query_state, spool_gc
from .durable_spool import make_spool_root
from .fragmenter import SubPlan
from .serde import SpoolCorruptionError
from .task import maybe_deserialize

__all__ = ["run_fte_query", "TaskFailure"]

_TASK_DIR = re.compile(r"^f(\d+)_t(\d+)$")
# bounded spool-corruption repairs per query: each repair re-runs exactly
# one producer task, so a disk actively eating data cannot loop forever
_MAX_REPAIRS = 3


class TaskFailure(RuntimeError):
    def __init__(self, fragment_id: int, task_index: int, attempts: int,
                 cause: BaseException):
        super().__init__(
            f"fragment {fragment_id} task {task_index} failed after "
            f"{attempts} attempts: {cause}")
        self.cause = cause


def fte_task_dir(spool_root: str, fragment_id: int, task_index: int) -> str:
    return os.path.join(spool_root, f"f{fragment_id}_t{task_index}")


def _attempt_number(attempt_dir: str) -> int:
    try:
        return int(os.path.basename(attempt_dir).rsplit("-", 1)[1])
    except (IndexError, ValueError):
        return -1


def _find_corruption(exc: BaseException) -> Optional[SpoolCorruptionError]:
    """First SpoolCorruptionError in the cause chain (TaskFailure.cause or
    the standard __cause__/__context__ links), if any."""
    seen = 0
    while exc is not None and seen < 10:
        if isinstance(exc, SpoolCorruptionError):
            return exc
        exc = (getattr(exc, "cause", None) or exc.__cause__
               or exc.__context__)
        seen += 1
    return None


def run_fte_query(runner, subplan: SubPlan,
                  stats_sink: Optional[list] = None,
                  resume: Optional["query_state.PendingQuery"] = None
                  ) -> list:
    """Execute the subplan stage-by-stage with task retry over a durable
    spool; returns the root fragment's output batches.  ``resume`` re-
    enters a recovered query from its WAL's committed-attempt map."""
    from ..telemetry import metrics as tm
    from ..telemetry import profiler
    from ..telemetry import runtime as rt

    session = runner.session
    attempts_allowed = 1 + getattr(session, "task_retry_attempts", 2)
    fragments = subplan.all_fragments()  # children first = topological

    task_counts, consumer_tasks = runner.stage_task_counts(fragments)
    output_kinds = {f.id: f.output_kind for f in fragments}

    rec = rt.current_record()
    qid = resume.query_id if resume is not None else (
        rec.query_id if rec is not None else "")
    sql = resume.sql if resume is not None else (
        rec.sql if rec is not None else "")

    if (resume is not None and resume.spool_root
            and os.path.isdir(resume.spool_root)):
        spool_root = resume.spool_root
    else:
        spool_root = make_spool_root(getattr(session, "fte_spool_dir", None))
    spool_gc.acquire(spool_root, qid or "adhoc")

    wal: Optional[query_state.QueryStateLog] = None
    if qid and query_state.enabled():
        # a resumed query keeps appending to the WAL it was recovered
        # from — under HA lease takeover that directory belongs to the
        # DEAD coordinator's claimed custody, not this process's own
        # state dir, and writing anywhere else would strand the log
        wal_dir = (os.path.dirname(resume.path)
                   if resume is not None and getattr(resume, "path", None)
                   else None)
        wal = query_state.QueryStateLog(qid, dir=wal_dir)
        if resume is None:
            wal.begin(sql, subplan, spool_root, session,
                      task_counts=task_counts,
                      consumer_tasks=consumer_tasks)

    speculative = getattr(session, "fte_speculative", True)
    spec_min_delay = getattr(session, "fte_speculative_delay_s", 0.25)
    mem_growth = getattr(session, "fte_memory_growth", 2.0)
    # observability: ("commit", frag, task, kind) / ("memory_retry", frag,
    # task, multiplier) / ("speculative_start", frag, task) /
    # ("resumed", frag, task) / ("spool_corruption", frag, task)
    events = getattr(session, "fte_events", None)

    # fragment id -> {task -> committed attempt dir}; survives stage
    # failures so a corruption repair can re-run ONE producer task and a
    # resumed query can skip everything a dead coordinator already paid for
    stage_commits: dict[int, dict[int, str]] = {f.id: {} for f in fragments}
    if resume is not None:
        shape_ok = resume.shape_matches(task_counts, consumer_tasks)
        for (fid, t), d in resume.committed_dirs().items():
            if (shape_ok and fid in stage_commits and isinstance(t, int)
                    and 0 <= t < task_counts.get(fid, 0)
                    and d and os.path.isdir(d)):
                stage_commits[fid][t] = d
                tm.FTE_STAGES_RESUMED.inc()
                if events is not None:
                    events.append(("resumed", fid, t))
                profiler.instant(profiler.RECOVERY, "task-resumed",
                                 fragment=fid, task=t)

    def run_stage(f, tc: int, nparts: int, upstream: dict,
                  already: dict[int, str]) -> None:
        """One stage with retry + speculation.  A SEPARATE function scope
        per stage: a zombie thread (e.g. a stalled standard attempt whose
        speculative twin already won) closes over THIS stage's state and can
        never corrupt a later stage's bookkeeping (late-binding loop
        closures did exactly that in the first r5 cut).  ``already`` holds
        tasks committed by a previous coordinator generation (or an earlier
        pass of this one) — they are seeded resolved, never re-run."""
        frag_commits: list[Optional[str]] = [None] * tc
        for t, d in already.items():
            frag_commits[t] = d
        if all(d is not None for d in frag_commits):
            return
        failures: list[Optional[TaskFailure]] = [None] * tc
        commit_lock = threading.Lock()
        # the stage barrier: commit() and failure recording notify, so the
        # event loop below wakes the moment a task resolves instead of
        # rediscovering it on a 10ms poll
        barrier = threading.Condition(commit_lock)
        stage_t0 = time.perf_counter()
        durations: list[float] = []

        def commit(t: int, d: str, kind: str) -> None:
            """First committed attempt wins (the spool's atomic-rename
            dedup makes the loser's directory inert)."""
            with barrier:
                if frag_commits[t] is None:
                    frag_commits[t] = d
                    already[t] = d
                    durations.append(time.perf_counter() - stage_t0)
                    if events is not None:
                        events.append(("commit", f.id, t, kind))
                    if kind == "SPECULATIVE":
                        tm.FTE_SPECULATIVE_WINS.inc()
                    if wal is not None:
                        wal.attempt_committed(f.id, t, _attempt_number(d),
                                              d, kind)
                    barrier.notify_all()

        def record_failure(t: int, tf: TaskFailure) -> None:
            with barrier:
                failures[t] = tf
                barrier.notify_all()

        def run_attempts(t: int, attempt_base: int, kind: str) -> None:
            """One retry chain (STANDARD or SPECULATIVE execution class —
            TaskExecutionClass.java:19).  A memory failure grows the
            task's budget exponentially on the next attempt
            (ExponentialGrowthPartitionMemoryEstimator.java:55)."""
            from ..spi.memory import ExceededMemoryLimitError

            last: Optional[Exception] = None
            mem_mult = 1.0
            for attempt in range(attempts_allowed):
                if frag_commits[t] is not None:
                    return  # the twin already won
                tm.FTE_ATTEMPT_STARTS.inc()
                if attempt > 0:
                    tm.FTE_ATTEMPT_RETRIES.inc()
                if wal is not None:
                    wal.attempt_start(f.id, t, attempt_base + attempt, kind)
                try:
                    d = runner.fte_run_attempt(
                        f, t, tc, nparts, upstream, spool_root,
                        attempt_base + attempt, stats_sink,
                        memory_multiplier=mem_mult)
                    commit(t, d, kind)
                    return
                except Exception as e:  # retried; interrupts propagate
                    last = e
                    from ..spi.errors import classify

                    if isinstance(e, SpoolCorruptionError):
                        # retrying would reread the same corrupt bytes;
                        # surface NOW so the query loop can repair the
                        # producer instead of burning the attempt budget
                        if kind == "STANDARD":
                            record_failure(t, TaskFailure(
                                f.id, t, attempt + 1, e))
                        return
                    if not classify(e).is_retryable():
                        # USER-classified failure: re-running re-runs the
                        # same bug — fail the task NOW, no retry chain
                        if kind == "STANDARD":
                            record_failure(t, TaskFailure(
                                f.id, t, attempt + 1, last))
                        return
                    if isinstance(e, ExceededMemoryLimitError):
                        mem_mult *= mem_growth
                        if events is not None:
                            events.append(
                                ("memory_retry", f.id, t, mem_mult))
                    time.sleep(0.01 * attempt)
            if kind == "STANDARD":
                record_failure(t, TaskFailure(f.id, t, attempts_allowed,
                                              last))

        # stage barrier between fragments, but a stage's tasks still run
        # concurrently (matching Trino FTE's intra-stage parallelism)
        threads = {t: threading.Thread(
            target=run_attempts, args=(t, 0, "STANDARD"),
            name=f"fte-{f.id}.{t}", daemon=True)
            for t in range(tc) if t not in already}
        for th in threads.values():
            th.start()

        # event loop: resolve tasks as they land; once half the stage
        # committed, stragglers get a SPECULATIVE attempt chain (first
        # commit wins).  A stalled standard attempt no longer holds the
        # stage barrier hostage — its thread is left to die in the
        # background (EventDrivenFaultTolerantQueryScheduler speculative
        # semantics).
        spec_threads: dict[int, threading.Thread] = {}
        with barrier:
            while True:
                resolved = [
                    t for t in range(tc)
                    if frag_commits[t] is not None
                    or (failures[t] is not None
                        and not (t in spec_threads
                                 and spec_threads[t].is_alive()))
                ]
                if len(resolved) == tc:
                    break
                all_dead = all(
                    not th.is_alive() for th in threads.values()) and all(
                    not th.is_alive() for th in spec_threads.values())
                if all_dead:
                    break
                if speculative and durations and len(
                        [t for t in range(tc)
                         if frag_commits[t] is not None]) * 2 >= tc:
                    med = sorted(durations)[len(durations) // 2]
                    cutoff = max(2.0 * med, spec_min_delay)
                    now = time.perf_counter() - stage_t0
                    for t in range(tc):
                        if (frag_commits[t] is None and t not in spec_threads
                                and now > cutoff):
                            if events is not None:
                                events.append(
                                    ("speculative_start", f.id, t))
                            tm.FTE_SPECULATIVE_STARTS.inc()
                            th = threading.Thread(
                                target=run_attempts,
                                args=(t, 1000, "SPECULATIVE"),
                                name=f"fte-spec-{f.id}.{t}", daemon=True)
                            spec_threads[t] = th
                            th.start()
                # commits/failures notify immediately; the timeout only
                # drives the speculation cutoff clock and dead-thread
                # detection
                barrier.wait(0.05 if speculative and durations else 0.25)

        for t in range(tc):
            if frag_commits[t] is None:
                raise failures[t] or TaskFailure(
                    f.id, t, attempts_allowed,
                    RuntimeError("task did not complete"))

    def upstream_for(f) -> dict:
        return {
            src: {"dirs": [stage_commits[src][t]
                           for t in sorted(stage_commits[src])],
                  "merge": output_kinds[src] == "MERGE"}
            for src in f.source_fragments
        }

    def repair_corruption(sce: SpoolCorruptionError, repairs_left: int,
                          failure: BaseException) -> int:
        """Discard the corrupt committed attempt and return the fragment
        list index to re-enter the stage loop at (the producer's).  Re-
        raises ``failure`` when the corruption cannot be mapped back to a
        committed task or the repair budget ran out."""
        if repairs_left <= 0:
            raise failure
        rel = os.path.relpath(sce.path, spool_root)
        parts = rel.split(os.sep)
        m = _TASK_DIR.match(parts[0]) if parts and ".." not in parts \
            else None
        if m is None or len(parts) < 2:
            raise failure
        fid, t = int(m.group(1)), int(m.group(2))
        if stage_commits.get(fid, {}).get(t) is None:
            raise failure
        attempt_dir = os.path.join(spool_root, parts[0], parts[1])
        stage_commits[fid].pop(t, None)
        shutil.rmtree(attempt_dir, ignore_errors=True)
        tm.FTE_SPOOL_CORRUPTIONS.inc()
        profiler.instant(profiler.RECOVERY, "spool-corruption-repair",
                         fragment=fid, task=t,
                         path=os.path.basename(sce.path))
        if events is not None:
            events.append(("spool_corruption", fid, t))
        if wal is not None:
            wal.attempt_discarded(fid, t, "crc-mismatch")
        for i, f in enumerate(fragments):
            if f.id == fid:
                return i
        raise failure

    try:
        repairs_left = _MAX_REPAIRS
        i = 0
        out: Optional[list] = None
        while out is None:
            while i < len(fragments):
                f = fragments[i]
                try:
                    run_stage(f, task_counts[f.id],
                              consumer_tasks.get(f.id, 1), upstream_for(f),
                              stage_commits[f.id])
                    i += 1
                except TaskFailure as tf:
                    sce = _find_corruption(tf.cause)
                    if sce is None:
                        raise
                    i = repair_corruption(sce, repairs_left, tf)
                    repairs_left -= 1

            from .durable_spool import DurableSpoolClient

            root = stage_commits[subplan.fragment.id]
            client = DurableSpoolClient([root[t] for t in sorted(root)], 0)
            batches = []
            try:
                while True:
                    page = client.poll()
                    if page is None:
                        break
                    batches.append(maybe_deserialize(page))
                out = batches
            except SpoolCorruptionError as sce:
                i = repair_corruption(sce, repairs_left, sce)
                repairs_left -= 1
        if wal is not None:
            wal.end("FINISHED")
        return out
    except BaseException as e:
        if wal is not None:
            wal.end("FAILED", error=str(e)[:500])
        raise
    finally:
        if wal is not None:
            wal.close()
        # happy-path GC: the query is over (either outcome), reclaim now.
        # A coordinator killed before this line leaves a leased root the
        # boot-time recovery + sweep will either resume from or reclaim.
        spool_gc.release(spool_root)
