"""Fault-tolerant execution: DURABLE spooled exchange + task retry.

The miniature of the reference's FTE mode (execution/scheduler/
faulttolerant/EventDrivenFaultTolerantQueryScheduler.java:201 +
spi/exchange/ExchangeManager.java:39 spooling):

- fragments run in topological order (producers complete before consumers
  start); every task's output is spooled TO DISK per consumer partition
  with atomic attempt commit (execution/durable_spool.py — the
  FileSystemExchangeManager role), so the unit of recovery genuinely
  survives task AND worker-process death;
- a failed task attempt is retried up to ``task_retry_attempts`` times with
  a fresh attempt directory (tasks are deterministic in (fragment,
  task_index, committed inputs), so re-execution is exact);
- consumers read only committed attempts — a mid-stream producer death can
  never poison a downstream task, which is exactly the property the
  streaming pipelined scheduler gives up;
- engine-level failure injection (execution/failure_injector.py, the
  FailureInjector.java:35 hook) targets task bodies, spool reads, or the
  hosting worker process itself.

The trade (identical to Trino FTE): no cross-stage streaming overlap, in
exchange for retryability.  ``Session(retry_policy="TASK")`` selects it.
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from typing import Optional

from .durable_spool import make_spool_root
from .fragmenter import SubPlan
from .task import maybe_deserialize

__all__ = ["run_fte_query", "TaskFailure"]


class TaskFailure(RuntimeError):
    def __init__(self, fragment_id: int, task_index: int, attempts: int,
                 cause: BaseException):
        super().__init__(
            f"fragment {fragment_id} task {task_index} failed after "
            f"{attempts} attempts: {cause}")
        self.cause = cause


def fte_task_dir(spool_root: str, fragment_id: int, task_index: int) -> str:
    return os.path.join(spool_root, f"f{fragment_id}_t{task_index}")


def run_fte_query(runner, subplan: SubPlan,
                  stats_sink: Optional[list] = None) -> list:
    """Execute the subplan stage-by-stage with task retry over a durable
    spool; returns the root fragment's output batches."""
    session = runner.session
    attempts_allowed = 1 + getattr(session, "task_retry_attempts", 2)
    fragments = subplan.all_fragments()  # children first = topological

    task_counts, consumer_tasks = runner.stage_task_counts(fragments)
    output_kinds = {f.id: f.output_kind for f in fragments}
    spool_root = make_spool_root(getattr(session, "fte_spool_dir", None))

    # fragment id -> list of committed attempt dirs (one per task)
    committed: dict[int, list[str]] = {}
    try:
        for f in fragments:
            tc = task_counts[f.id]
            nparts = consumer_tasks.get(f.id, 1)
            upstream = {
                src: {"dirs": committed[src],
                      "merge": output_kinds[src] == "MERGE"}
                for src in f.source_fragments
            }

            frag_commits: list[Optional[str]] = [None] * tc
            failures: list[Optional[TaskFailure]] = [None] * tc

            def run_with_retry(t: int) -> None:
                last: Optional[Exception] = None
                for attempt in range(attempts_allowed):
                    try:
                        frag_commits[t] = runner.fte_run_attempt(
                            f, t, tc, nparts, upstream, spool_root,
                            attempt, stats_sink)
                        return
                    except Exception as e:  # retried; interrupts propagate
                        last = e
                        time.sleep(0.01 * attempt)
                failures[t] = TaskFailure(f.id, t, attempts_allowed, last)

            # stage barrier between fragments, but a stage's tasks still run
            # concurrently (matching Trino FTE's intra-stage parallelism)
            threads = [threading.Thread(target=run_with_retry, args=(t,),
                                        name=f"fte-{f.id}.{t}", daemon=True)
                       for t in range(tc)]
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            for fail in failures:
                if fail is not None:
                    raise fail
            committed[f.id] = [d for d in frag_commits if d is not None]

        from .durable_spool import DurableSpoolClient

        client = DurableSpoolClient(committed[subplan.fragment.id], 0)
        out = []
        while True:
            page = client.poll()
            if page is None:
                break
            out.append(maybe_deserialize(page))
        return out
    finally:
        shutil.rmtree(spool_root, ignore_errors=True)
