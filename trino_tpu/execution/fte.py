"""Fault-tolerant execution: DURABLE spooled exchange + task retry.

The miniature of the reference's FTE mode (execution/scheduler/
faulttolerant/EventDrivenFaultTolerantQueryScheduler.java:201 +
spi/exchange/ExchangeManager.java:39 spooling):

- fragments run in topological order (producers complete before consumers
  start); every task's output is spooled TO DISK per consumer partition
  with atomic attempt commit (execution/durable_spool.py — the
  FileSystemExchangeManager role), so the unit of recovery genuinely
  survives task AND worker-process death;
- a failed task attempt is retried up to ``task_retry_attempts`` times with
  a fresh attempt directory (tasks are deterministic in (fragment,
  task_index, committed inputs), so re-execution is exact);
- consumers read only committed attempts — a mid-stream producer death can
  never poison a downstream task, which is exactly the property the
  streaming pipelined scheduler gives up;
- engine-level failure injection (execution/failure_injector.py, the
  FailureInjector.java:35 hook) targets task bodies, spool reads, or the
  hosting worker process itself.

The trade (identical to Trino FTE): no cross-stage streaming overlap, in
exchange for retryability.  ``Session(retry_policy="TASK")`` selects it.
"""

from __future__ import annotations

import os
import shutil
import threading
import time
from typing import Optional

from .durable_spool import make_spool_root
from .fragmenter import SubPlan
from .task import maybe_deserialize

__all__ = ["run_fte_query", "TaskFailure"]


class TaskFailure(RuntimeError):
    def __init__(self, fragment_id: int, task_index: int, attempts: int,
                 cause: BaseException):
        super().__init__(
            f"fragment {fragment_id} task {task_index} failed after "
            f"{attempts} attempts: {cause}")
        self.cause = cause


def fte_task_dir(spool_root: str, fragment_id: int, task_index: int) -> str:
    return os.path.join(spool_root, f"f{fragment_id}_t{task_index}")


def run_fte_query(runner, subplan: SubPlan,
                  stats_sink: Optional[list] = None) -> list:
    """Execute the subplan stage-by-stage with task retry over a durable
    spool; returns the root fragment's output batches."""
    session = runner.session
    attempts_allowed = 1 + getattr(session, "task_retry_attempts", 2)
    fragments = subplan.all_fragments()  # children first = topological

    task_counts, consumer_tasks = runner.stage_task_counts(fragments)
    output_kinds = {f.id: f.output_kind for f in fragments}
    spool_root = make_spool_root(getattr(session, "fte_spool_dir", None))

    speculative = getattr(session, "fte_speculative", True)
    spec_min_delay = getattr(session, "fte_speculative_delay_s", 0.25)
    mem_growth = getattr(session, "fte_memory_growth", 2.0)
    # observability: ("commit", frag, task, kind) / ("memory_retry", frag,
    # task, multiplier) / ("speculative_start", frag, task)
    events = getattr(session, "fte_events", None)

    def run_stage(f, tc: int, nparts: int, upstream: dict) -> list[str]:
        """One stage with retry + speculation.  A SEPARATE function scope
        per stage: a zombie thread (e.g. a stalled standard attempt whose
        speculative twin already won) closes over THIS stage's state and can
        never corrupt a later stage's bookkeeping (late-binding loop
        closures did exactly that in the first r5 cut)."""
        frag_commits: list[Optional[str]] = [None] * tc
        failures: list[Optional[TaskFailure]] = [None] * tc
        commit_lock = threading.Lock()
        stage_t0 = time.perf_counter()
        durations: list[float] = []

        def commit(t: int, d: str, kind: str) -> None:
            """First committed attempt wins (the spool's atomic-rename
            dedup makes the loser's directory inert)."""
            with commit_lock:
                if frag_commits[t] is None:
                    frag_commits[t] = d
                    durations.append(time.perf_counter() - stage_t0)
                    if events is not None:
                        events.append(("commit", f.id, t, kind))

        def run_attempts(t: int, attempt_base: int, kind: str) -> None:
            """One retry chain (STANDARD or SPECULATIVE execution class —
            TaskExecutionClass.java:19).  A memory failure grows the
            task's budget exponentially on the next attempt
            (ExponentialGrowthPartitionMemoryEstimator.java:55)."""
            from ..spi.memory import ExceededMemoryLimitError

            last: Optional[Exception] = None
            mem_mult = 1.0
            for attempt in range(attempts_allowed):
                if frag_commits[t] is not None:
                    return  # the twin already won
                try:
                    d = runner.fte_run_attempt(
                        f, t, tc, nparts, upstream, spool_root,
                        attempt_base + attempt, stats_sink,
                        memory_multiplier=mem_mult)
                    commit(t, d, kind)
                    return
                except Exception as e:  # retried; interrupts propagate
                    last = e
                    from ..spi.errors import classify

                    if not classify(e).is_retryable():
                        # USER-classified failure: re-running re-runs the
                        # same bug — fail the task NOW, no retry chain
                        if kind == "STANDARD":
                            failures[t] = TaskFailure(
                                f.id, t, attempt + 1, last)
                        return
                    if isinstance(e, ExceededMemoryLimitError):
                        mem_mult *= mem_growth
                        if events is not None:
                            events.append(
                                ("memory_retry", f.id, t, mem_mult))
                    time.sleep(0.01 * attempt)
            if kind == "STANDARD":
                failures[t] = TaskFailure(f.id, t, attempts_allowed, last)

        # stage barrier between fragments, but a stage's tasks still run
        # concurrently (matching Trino FTE's intra-stage parallelism)
        threads = [threading.Thread(
            target=run_attempts, args=(t, 0, "STANDARD"),
            name=f"fte-{f.id}.{t}", daemon=True) for t in range(tc)]
        for th in threads:
            th.start()

        # event loop: resolve tasks as they land; once half the stage
        # committed, stragglers get a SPECULATIVE attempt chain (first
        # commit wins).  A stalled standard attempt no longer holds the
        # stage barrier hostage — its thread is left to die in the
        # background (EventDrivenFaultTolerantQueryScheduler speculative
        # semantics).
        spec_threads: dict[int, threading.Thread] = {}
        while True:
            resolved = [
                t for t in range(tc)
                if frag_commits[t] is not None
                or (failures[t] is not None
                    and not (t in spec_threads
                             and spec_threads[t].is_alive()))
            ]
            if len(resolved) == tc:
                break
            all_dead = all(not th.is_alive() for th in threads) and all(
                not th.is_alive() for th in spec_threads.values())
            if all_dead:
                break
            if speculative and durations and len(
                    [t for t in range(tc)
                     if frag_commits[t] is not None]) * 2 >= tc:
                med = sorted(durations)[len(durations) // 2]
                cutoff = max(2.0 * med, spec_min_delay)
                now = time.perf_counter() - stage_t0
                for t in range(tc):
                    if (frag_commits[t] is None and t not in spec_threads
                            and now > cutoff):
                        if events is not None:
                            events.append(("speculative_start", f.id, t))
                        th = threading.Thread(
                            target=run_attempts,
                            args=(t, 1000, "SPECULATIVE"),
                            name=f"fte-spec-{f.id}.{t}", daemon=True)
                        spec_threads[t] = th
                        th.start()
            time.sleep(0.01)

        for t in range(tc):
            if frag_commits[t] is None:
                raise failures[t] or TaskFailure(
                    f.id, t, attempts_allowed,
                    RuntimeError("task did not complete"))
        return [d for d in frag_commits if d is not None]

    # fragment id -> list of committed attempt dirs (one per task)
    committed: dict[int, list[str]] = {}
    try:
        for f in fragments:
            upstream = {
                src: {"dirs": committed[src],
                      "merge": output_kinds[src] == "MERGE"}
                for src in f.source_fragments
            }
            committed[f.id] = run_stage(
                f, task_counts[f.id], consumer_tasks.get(f.id, 1), upstream)

        from .durable_spool import DurableSpoolClient

        client = DurableSpoolClient(committed[subplan.fragment.id], 0)
        out = []
        while True:
            page = client.poll()
            if page is None:
                break
            out.append(maybe_deserialize(page))
        return out
    finally:
        shutil.rmtree(spool_root, ignore_errors=True)
