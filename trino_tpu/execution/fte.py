"""Fault-tolerant execution: stage-by-stage spooled exchange + task retry.

The miniature of the reference's FTE mode (execution/scheduler/
faulttolerant/EventDrivenFaultTolerantQueryScheduler.java:201 +
spi/exchange/ExchangeManager.java:39 spooling):

- fragments run in topological order (producers complete before consumers
  start), every task's output fully *spooled* per consumer partition;
- a failed task attempt is retried up to ``task_retry_attempts`` times with
  a fresh output spool (tasks are deterministic in (fragment, task_index,
  spooled inputs), so re-execution is exact);
- consumers read the winning attempt's spool — a mid-stream producer death
  can never poison a downstream task, which is exactly the property the
  streaming pipelined scheduler gives up.

The trade (identical to Trino FTE): no cross-stage streaming overlap, in
exchange for retryability.  ``Session(retry_policy="TASK")`` selects it.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

from ..exec.driver import run_pipelines
from ..exec.local_planner import LocalPlanner
from ..exec.stats import QueryStats
from .fragmenter import SubPlan
from .task import PartitionedOutputSink, maybe_deserialize

__all__ = ["SpoolBuffer", "SpooledExchangeClient", "run_fte_query"]


class SpoolBuffer:
    """Collects a task's full output per consumer partition (duck-types the
    OutputBuffer surface PartitionedOutputSink uses)."""

    def __init__(self, num_partitions: int):
        self.num_partitions = num_partitions
        self.pages: list[list] = [[] for _ in range(num_partitions)]
        self.finished = False

    def enqueue(self, partition: int, page) -> None:
        self.pages[partition].append(page)

    def set_finished(self) -> None:
        self.finished = True


class SpooledExchangeClient:
    """Reads one consumer partition from every producer task's finished
    spool (duck-types ExchangeClient for RemoteExchangeSourceOperator)."""

    def __init__(self, spools: Sequence[SpoolBuffer], partition: int):
        pages = []
        for s in spools:
            pages.extend(s.pages[partition])
        self._pages = pages
        self._i = 0

    def poll(self, timeout: float = 0.0):
        if self._i < len(self._pages):
            page = self._pages[self._i]
            self._i += 1
            return page
        return None

    def is_finished(self) -> bool:
        return self._i >= len(self._pages)


class TaskFailure(RuntimeError):
    def __init__(self, fragment_id: int, task_index: int, attempts: int,
                 cause: BaseException):
        super().__init__(
            f"fragment {fragment_id} task {task_index} failed after "
            f"{attempts} attempts: {cause}")
        self.cause = cause


def run_fte_query(runner, subplan: SubPlan,
                  stats_sink: Optional[list] = None) -> list:
    """Execute the subplan stage-by-stage with task retry; returns the root
    fragment's output batches."""
    session = runner.session
    attempts_allowed = 1 + getattr(session, "task_retry_attempts", 2)
    fragments = subplan.all_fragments()  # children first = topological

    task_counts, consumer_tasks = runner.stage_task_counts(fragments)
    output_kinds = {f.id: f.output_kind for f in fragments}

    spools: dict[int, list[SpoolBuffer]] = {}
    for f in fragments:
        tc = task_counts[f.id]
        nparts = consumer_tasks.get(f.id, 1)

        def run_attempt(task_index: int) -> SpoolBuffer:
            clients = {}
            for src in f.source_fragments:
                if output_kinds[src] == "MERGE":
                    clients[src] = [
                        SpooledExchangeClient([s], task_index)
                        for s in spools[src]
                    ]
                else:
                    clients[src] = SpooledExchangeClient(
                        spools[src], task_index)
            planner = LocalPlanner(
                runner.catalog,
                splits_per_node=session.splits_per_node,
                node_count=runner.worker_count,
                task_index=task_index,
                task_count=tc,
                remote_clients=clients,
                dynamic_filtering=session.dynamic_filtering,
                hbm_limit_bytes=session.hbm_limit_bytes,
            )
            local = planner.plan(f.root)
            buf = SpoolBuffer(nparts)
            sink = PartitionedOutputSink(
                buf, f.output_kind if f.output_kind != "OUTPUT" else "GATHER",
                f.output_keys, serde=session.exchange_serde)
            local.pipelines[-1][-1] = sink
            stats = None
            if stats_sink is not None:
                stats = QueryStats(
                    label=f"fragment {f.id} task {task_index}:")
            run_pipelines(local.pipelines, stats)
            if stats is not None:
                stats_sink.append(stats)
            return buf

        # stage barrier between fragments, but a stage's tasks still run
        # concurrently (matching Trino FTE's intra-stage parallelism)
        frag_spools: list[Optional[SpoolBuffer]] = [None] * tc
        failures: list[Optional[TaskFailure]] = [None] * tc

        def run_with_retry(t: int) -> None:
            last: Optional[Exception] = None
            for attempt in range(attempts_allowed):
                try:
                    frag_spools[t] = run_attempt(t)
                    return
                except Exception as e:  # retried; interrupts propagate
                    last = e
                    time.sleep(0.01 * attempt)
            failures[t] = TaskFailure(f.id, t, attempts_allowed, last)

        threads = [threading.Thread(target=run_with_retry, args=(t,),
                                    name=f"fte-{f.id}.{t}", daemon=True)
                   for t in range(tc)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        for fail in failures:
            if fail is not None:
                raise fail
        spools[f.id] = frag_spools

    root = spools[subplan.fragment.id]
    out = []
    for s in root:
        for page in s.pages[0]:
            out.append(maybe_deserialize(page))
    return out
