"""Task-side exchange operators + task execution.

RemoteExchangeSourceOperator = operator/ExchangeOperator.java:44 (pulls
upstream pages through an ExchangeClient); PartitionedOutputSink =
operator/output/PartitionedOutputOperator.java:47 + TaskOutputOperator
(hash/broadcast/gather placement into the task's OutputBuffer).
"""

from __future__ import annotations

import os
import time
import zlib
from typing import Optional, Sequence

import numpy as np

from ..exec import kernels as K
from ..exec.operators import Operator
from ..spi.batch import Column, ColumnBatch, encoded_exec
from .exchange import ExchangeClient, OutputBuffer
from .serde import PageStreamEncoder, deserialize_batch, serialize_batch

__all__ = ["RemoteExchangeSourceOperator", "PartitionedOutputSink",
           "SerializedPage", "maybe_deserialize"]

# How long an exchange consumer waits with NO upstream page before declaring
# a stall.  First-run XLA compiles at large shapes can exceed several
# minutes on CPU (the self-measured bench baseline), so the default is
# generous; tests that probe deadlocks can lower it via the env knob.
STALL_TIMEOUT_S = float(os.environ.get("TRINO_TPU_EXCHANGE_STALL_S", "1800"))


class SerializedPage:
    """A batch serialized to wire bytes (execution/serde.py) — what a real
    network transport would carry (buffer/PageSerializer.java:58)."""

    __slots__ = ("data",)

    def __init__(self, data: bytes):
        self.data = data

    @property
    def nbytes(self) -> int:
        return len(self.data)


def maybe_deserialize(page):
    if isinstance(page, SerializedPage):
        return deserialize_batch(page.data)
    return page


def _dict_value_hashes(dictionary: np.ndarray) -> np.ndarray:
    """Deterministic per-value hash of a string dictionary (crc32 over
    utf-8).  Partition routing must hash VALUES, not dictionary codes: code
    3 in one producer's dictionary is a different string than code 3 in
    another's, and all producers of a stage must route equal values to the
    same consumer task."""
    return np.array([zlib.crc32(str(s).encode()) for s in dictionary],
                    dtype=np.int64)


def _partition_key_tuple(c: Column):
    data = np.asarray(c.data)
    valid = None if c.valid is None else np.asarray(c.valid)
    if c.dictionary is not None:
        vh = _dict_value_hashes(c.dictionary)
        data = vh[data] if len(vh) else np.zeros(len(data), np.int64)
    return data, valid


class RemoteExchangeSourceOperator(Operator):
    # blocking=True: wait in place for upstream pages (thread-per-task mode).
    # The time-sharing executor flips this off so a waiting consumer parks
    # (yields its worker) instead of pinning it.
    blocking = True

    def __init__(self, client: ExchangeClient):
        self.client = client
        self.input_done = True

    def needs_input(self) -> bool:
        return False

    def get_output(self) -> Optional[ColumnBatch]:
        if self._closed:
            return None
        if not self.blocking:
            page = self.client.poll(timeout=0)
            return maybe_deserialize(page) if page is not None else None
        # block until a page or all upstream producers finish; the driver
        # treats a None from a non-finished source as "try again"
        deadline = time.monotonic() + STALL_TIMEOUT_S
        while not self.client.is_finished():
            page = self.client.poll(timeout=0.2)
            if page is not None:
                return maybe_deserialize(page)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"exchange source stalled >{STALL_TIMEOUT_S:.0f}s")
        return None

    def is_finished(self) -> bool:
        return self._closed or self.client.is_finished()


class MergeSourceOperator(Operator):
    """Order-preserving gather of pre-sorted per-producer streams (the
    MergeOperator.java:46 consumer of a MERGE exchange).

    Small results (client-facing ORDER BY outputs) k-way heap-merge the
    producer streams row-wise, reproducing the global order without a
    re-sort; beyond ``MERGE_ROW_LIMIT`` rows the operator falls back to the
    vectorized sort kernel over the concatenated streams (same result,
    O(n log n) on device instead of Python-per-row)."""

    blocking = True  # executor flips off: parks instead of pinning a worker
    MERGE_ROW_LIMIT = 100_000

    def __init__(self, producer_clients, sort_keys, names, types):
        self.clients = list(producer_clients)
        self.sort_keys = list(sort_keys)
        self.names = list(names)
        self.types = list(types)
        self.input_done = True
        self._streams: list[list] = [[] for _ in self.clients]
        self._emitted = False

    def needs_input(self) -> bool:
        return False

    def _poll_all(self, wait: bool) -> bool:
        """Accumulate available pages; True when every stream is complete."""
        deadline = time.monotonic() + STALL_TIMEOUT_S
        while True:
            all_done = True
            progressed = False
            for i, c in enumerate(self.clients):
                if c.is_finished():
                    continue
                page = c.poll(timeout=0.05 if wait else 0)
                if page is not None:
                    self._streams[i].append(maybe_deserialize(page))
                    progressed = True
                if not c.is_finished():
                    all_done = False
            if all_done or not wait:
                return all_done
            if progressed:
                deadline = time.monotonic() + STALL_TIMEOUT_S  # reset on activity
            elif time.monotonic() > deadline:
                raise TimeoutError(
                    f"merge source stalled >{STALL_TIMEOUT_S:.0f}s")

    def _row_key(self, row):
        key = []
        for k in self.sort_keys:
            v = row[k.channel]
            null_rank = (0 if k.nulls_first else 1) if v is None else \
                (1 if k.nulls_first else 0)
            if v is None:
                key.append((null_rank, 0, _MIN_TOKEN))
                continue
            nan = isinstance(v, float) and v != v
            nan_rank = (1 if k.ascending else 0) if nan else (
                0 if k.ascending else 1)
            key.append((null_rank, nan_rank,
                        _Reversed(v) if not k.ascending and not nan else
                        (_MIN_TOKEN if nan else v)))
        return tuple(key)

    def _merge(self) -> Optional[ColumnBatch]:
        batches = [b for s in self._streams for b in s]
        if not batches:
            return None
        total = sum(b.num_rows for b in batches)
        if total > self.MERGE_ROW_LIMIT:
            # vectorized fallback: one kernel re-sort of the gathered runs
            from ..exec import kernels as K
            from ..exec.operators import _sort_key_tuples

            inp = ColumnBatch.concat(batches)
            perm = K.sort_perm(_sort_key_tuples(inp, self.sort_keys))
            return inp.take(perm).rename(self.names)
        import heapq

        streams = []
        for s in self._streams:
            rows: list = []
            for b in s:
                rows.extend(b.to_pylist())
            streams.append(rows)
        merged = list(heapq.merge(*streams, key=self._row_key))
        if not merged:
            return None
        cols = [Column.from_values(t, [r[i] for r in merged])
                for i, t in enumerate(self.types)]
        return ColumnBatch(self.names, cols)

    def get_output(self):
        if self._emitted or self._closed:
            return None
        if not self._poll_all(wait=self.blocking):
            return None  # parked; the executor reschedules us
        self._emitted = True
        return self._merge()

    def is_finished(self) -> bool:
        return self._emitted or self._closed


class _Reversed:
    """Inverts comparison order for DESC sort keys in the merge heap."""

    __slots__ = ("v",)

    def __init__(self, v):
        self.v = v

    def __lt__(self, other):
        return other.v < self.v

    def __eq__(self, other):
        return self.v == other.v


class _MinToken:
    __slots__ = ()

    def __lt__(self, other):
        return not isinstance(other, _MinToken)

    def __eq__(self, other):
        return isinstance(other, _MinToken)


_MIN_TOKEN = _MinToken()


class PartitionedOutputSink(Operator):
    """Routes task output into the OutputBuffer: REPARTITION hashes on the
    output keys, BROADCAST replicates, GATHER/OUTPUT lands in partition 0."""

    # blocking=True: wait inside OutputBuffer.enqueue when the byte budget
    # is exhausted (thread-per-task mode).  The time-sharing executor flips
    # this off; the sink then refuses input via ``needs_input`` and its
    # driver parks until consumer acks free capacity — quantum-pinning is
    # never traded for unbounded buffer growth.
    blocking = True

    def __init__(self, buffer: OutputBuffer, kind: str,
                 keys: Sequence[int] = (), serde: bool = False,
                 sketch=None, sketch_keys: Sequence[int] = (),
                 coalesce_rows: int = 0):
        self.buffer = buffer
        self.kind = kind
        self.keys = list(keys)
        self.serde = serde  # serialize pages to wire bytes (network mode)
        self._rr = 0  # ROUND_ROBIN rotation cursor
        # adaptive deferred edges: a HeavyHitterSketch fed the join-key
        # hashes of every row so the coordinator can fold per-task key
        # distributions at the consumer's activation barrier
        self.sketch = sketch
        self.sketch_keys = list(sketch_keys)
        # >0: REPARTITION buffers each partition's slivers and releases
        # ~coalesce_rows-row pages — a page split n ways otherwise hands
        # the consumer one operator dispatch per sliver
        self.coalesce_rows = coalesce_rows
        self._pend: dict[int, list] = {}  # partition -> [rows, [slivers]]
        # compressed execution: each partition's page stream gets its own
        # sidecar context, so dictionaries ship once per (task, partition).
        # Only the in-memory HTTP exchange plane guarantees the in-order,
        # from-the-start delivery the def/ref protocol needs — FTE durable
        # spools and speculation tees (facade buffers) replay frames across
        # attempts and stay on v1 pages.  BROADCAST serializes one page for
        # all partitions, which would share one stream across consumers.
        self._encode_pages = (serde and kind != "BROADCAST"
                              and isinstance(buffer, OutputBuffer)
                              and encoded_exec())
        self._encoders: dict[int, PageStreamEncoder] = {}

    def needs_input(self) -> bool:
        if (not self.blocking and hasattr(self.buffer, "has_capacity")
                and not self.buffer.has_capacity()):
            return False
        return super().needs_input()

    def _enqueue(self, partition: int, page) -> None:
        # block= is only passed on the non-blocking path: FTE wraps a
        # DurableSpoolWriter in this sink, whose enqueue has no such kwarg
        # (and is never flipped non-blocking — FTE bypasses the executor)
        if self.blocking:
            self.buffer.enqueue(partition, page)
        else:
            self.buffer.enqueue(partition, page, block=False)

    def _page(self, batch: ColumnBatch, partition: Optional[int] = None):
        if self.serde:
            ctx = None
            if self._encode_pages and partition is not None:
                ctx = self._encoders.get(partition)
                if ctx is None:
                    ctx = self._encoders[partition] = PageStreamEncoder()
            return SerializedPage(serialize_batch(batch, ctx=ctx))
        return batch

    def add_input(self, batch: ColumnBatch) -> None:
        # the exchange is a host/network boundary: densify device batches
        batch = batch.compact()
        if batch.num_rows == 0:
            return
        if self.sketch is not None and self.sketch_keys:
            h = K.partition_key_hashes(
                [_partition_key_tuple(batch.columns[k])
                 for k in self.sketch_keys])
            self.sketch.update(h)
        n = self.buffer.num_partitions
        if self.kind == "REPARTITION" and n > 1:
            cols = [batch.columns[k] for k in self.keys]
            parts = K.partition_assignments(
                [_partition_key_tuple(c) for c in cols], n)
            for p in range(n):
                sub = batch.filter(parts == p)
                if not sub.num_rows:
                    continue
                if self.coalesce_rows:
                    self._buffer_sliver(p, sub)
                else:
                    self._enqueue(p, self._page(sub, p))
        elif self.kind == "BROADCAST" and n > 1:
            page = self._page(batch)
            for p in range(n):
                self._enqueue(p, page)
        elif self.kind == "ROUND_ROBIN" and n > 1:
            # batch-granular rotation (RandomExchanger / ArbitraryOutputBuffer
            # role: balance load without any key)
            p = self._rr % n
            self._enqueue(p, self._page(batch, p))
            self._rr += 1
        else:
            self._enqueue(0, self._page(batch, 0))

    def _buffer_sliver(self, p: int, sub: ColumnBatch) -> None:
        ent = self._pend.get(p)
        if ent is None:
            ent = self._pend[p] = [0, []]
        ent[0] += sub.num_rows
        ent[1].append(sub)
        if ent[0] >= self.coalesce_rows:
            self._flush_pending(p)

    def _flush_pending(self, p: int) -> None:
        ent = self._pend.pop(p, None)
        if ent is not None and ent[1]:
            self._enqueue(p, self._page(ColumnBatch.concat(ent[1]), p))

    def finish_input(self) -> None:
        super().finish_input()
        for p in list(self._pend):
            self._flush_pending(p)
        self.buffer.set_finished()

    def is_finished(self) -> bool:
        return self.input_done
