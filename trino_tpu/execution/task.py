"""Task-side exchange operators + task execution.

RemoteExchangeSourceOperator = operator/ExchangeOperator.java:44 (pulls
upstream pages through an ExchangeClient); PartitionedOutputSink =
operator/output/PartitionedOutputOperator.java:47 + TaskOutputOperator
(hash/broadcast/gather placement into the task's OutputBuffer).
"""

from __future__ import annotations

import time
import zlib
from typing import Optional, Sequence

import numpy as np

from ..exec import kernels as K
from ..exec.operators import Operator
from ..spi.batch import Column, ColumnBatch
from .exchange import ExchangeClient, OutputBuffer
from .serde import deserialize_batch, serialize_batch

__all__ = ["RemoteExchangeSourceOperator", "PartitionedOutputSink",
           "SerializedPage", "maybe_deserialize"]


class SerializedPage:
    """A batch serialized to wire bytes (execution/serde.py) — what a real
    network transport would carry (buffer/PageSerializer.java:58)."""

    __slots__ = ("data",)

    def __init__(self, data: bytes):
        self.data = data

    @property
    def nbytes(self) -> int:
        return len(self.data)


def maybe_deserialize(page):
    if isinstance(page, SerializedPage):
        return deserialize_batch(page.data)
    return page


def _dict_value_hashes(dictionary: np.ndarray) -> np.ndarray:
    """Deterministic per-value hash of a string dictionary (crc32 over
    utf-8).  Partition routing must hash VALUES, not dictionary codes: code
    3 in one producer's dictionary is a different string than code 3 in
    another's, and all producers of a stage must route equal values to the
    same consumer task."""
    return np.array([zlib.crc32(str(s).encode()) for s in dictionary],
                    dtype=np.int64)


def _partition_key_tuple(c: Column):
    data = np.asarray(c.data)
    valid = None if c.valid is None else np.asarray(c.valid)
    if c.dictionary is not None:
        vh = _dict_value_hashes(c.dictionary)
        data = vh[data] if len(vh) else np.zeros(len(data), np.int64)
    return data, valid


class RemoteExchangeSourceOperator(Operator):
    # blocking=True: wait in place for upstream pages (thread-per-task mode).
    # The time-sharing executor flips this off so a waiting consumer parks
    # (yields its worker) instead of pinning it.
    blocking = True

    def __init__(self, client: ExchangeClient):
        self.client = client
        self.input_done = True

    def needs_input(self) -> bool:
        return False

    def get_output(self) -> Optional[ColumnBatch]:
        if self._closed:
            return None
        if not self.blocking:
            page = self.client.poll(timeout=0)
            return maybe_deserialize(page) if page is not None else None
        # block until a page or all upstream producers finish; the driver
        # treats a None from a non-finished source as "try again"
        deadline = time.monotonic() + 300.0
        while not self.client.is_finished():
            page = self.client.poll(timeout=0.2)
            if page is not None:
                return maybe_deserialize(page)
            if time.monotonic() > deadline:
                raise TimeoutError("exchange source stalled >300s")
        return None

    def is_finished(self) -> bool:
        return self._closed or self.client.is_finished()


class PartitionedOutputSink(Operator):
    """Routes task output into the OutputBuffer: REPARTITION hashes on the
    output keys, BROADCAST replicates, GATHER/OUTPUT lands in partition 0."""

    def __init__(self, buffer: OutputBuffer, kind: str,
                 keys: Sequence[int] = (), serde: bool = False):
        self.buffer = buffer
        self.kind = kind
        self.keys = list(keys)
        self.serde = serde  # serialize pages to wire bytes (network mode)

    def _page(self, batch: ColumnBatch):
        if self.serde:
            return SerializedPage(serialize_batch(batch))
        return batch

    def add_input(self, batch: ColumnBatch) -> None:
        # the exchange is a host/network boundary: densify device batches
        batch = batch.compact()
        if batch.num_rows == 0:
            return
        n = self.buffer.num_partitions
        if self.kind == "REPARTITION" and n > 1:
            cols = [batch.columns[k] for k in self.keys]
            parts = K.partition_assignments(
                [_partition_key_tuple(c) for c in cols], n)
            for p in range(n):
                sub = batch.filter(parts == p)
                if sub.num_rows:
                    self.buffer.enqueue(p, self._page(sub))
        elif self.kind == "BROADCAST" and n > 1:
            page = self._page(batch)
            for p in range(n):
                self.buffer.enqueue(p, page)
        else:
            self.buffer.enqueue(0, self._page(batch))

    def finish_input(self) -> None:
        super().finish_input()
        self.buffer.set_finished()

    def is_finished(self) -> bool:
        return self.input_done
