"""Engine-level failure injection (reference: execution/FailureInjector.java:35).

The round-3 fault injection lived in a test-local connector wrapper; this is
the engine hook: rules target (fragment_id, task_index, attempt) at named
injection points and fire a bounded number of times.  Kinds mirror the
reference's enum (FailureInjector.java:51):

- ``TASK_FAILURE``               raise inside the task body
- ``GET_RESULTS_FAILURE``        raise while reading an upstream spool/page
- ``PROCESS_EXIT``               hard-kill the hosting process (worker mode
                                 only — the real "node died" case)

Rules travel inside task descriptors to worker processes, so process-mode
FTE can deterministically lose a worker mid-stage.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Optional

__all__ = ["FailureInjector", "InjectedFailure",
           "TASK_FAILURE", "GET_RESULTS_FAILURE", "PROCESS_EXIT",
           "TASK_STALL", "TASK_OOM", "SPOOL_CORRUPTION",
           "match_wire_rule", "check_wire_rules", "sleep_with_cancel"]

TASK_FAILURE = "TASK_FAILURE"
GET_RESULTS_FAILURE = "GET_RESULTS_FAILURE"
PROCESS_EXIT = "PROCESS_EXIT"
# r5 additions for FTE tier 2 (reference: TaskExecutionClass.java:19
# speculation is exercised with stalled tasks; memory-aware retry with
# injected OOM — ExponentialGrowthPartitionMemoryEstimator.java:55):
TASK_STALL = "TASK_STALL"  # sleep stall_s inside the task body
TASK_OOM = "TASK_OOM"  # raise ExceededMemoryLimitError inside the task body
# r15: flip a byte inside a committed spool part file right before a
# consumer reads it — the on-disk bit-rot / torn-sector case the CRC frame
# checksums exist to catch (the read then raises SpoolCorruptionError and
# the FTE loop re-executes the corrupted producer attempt)
SPOOL_CORRUPTION = "SPOOL_CORRUPTION"


class InjectedFailure(RuntimeError):
    pass


@dataclass
class _Rule:
    kind: str
    fragment_id: Optional[int] = None  # None = any
    task_index: Optional[int] = None
    attempt: Optional[int] = None
    times: int = 1
    fired: int = 0
    stall_s: float = 0.0  # TASK_STALL only

    def matches(self, kind: str, fragment_id: int, task_index: int,
                attempt: int) -> bool:
        return (self.fired < self.times and self.kind == kind
                and (self.fragment_id is None
                     or self.fragment_id == fragment_id)
                and (self.task_index is None
                     or self.task_index == task_index)
                and (self.attempt is None or self.attempt == attempt))


@dataclass
class FailureInjector:
    rules: list = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def inject(self, kind: str, fragment_id: Optional[int] = None,
               task_index: Optional[int] = None,
               attempt: Optional[int] = None, times: int = 1,
               stall_s: float = 0.0) -> None:
        self.rules.append(_Rule(kind, fragment_id, task_index, attempt,
                                times, stall_s=stall_s))

    def consume_for(self, fragment_id: int, task_index: int,
                    attempt: int, unreachable: frozenset = frozenset()
                    ) -> list[dict]:
        """Wire form for ONE task-attempt descriptor.  A rule whose scope
        matches this attempt is counted as fired at export time (the worker
        cannot report back — it may be dead), so ``times`` bounds hold
        identically in-process and across processes.  ``unreachable`` names
        injection points this attempt can never reach (e.g. a leaf task
        never reads upstream results); those rules are NOT consumed, so
        they stay armed for an attempt that can hit them (advisor r4: an
        exported-but-unreachable rule silently burned its ``times``
        budget).  Unlisted/new kinds export by default."""
        out = []
        with self._lock:
            for r in self.rules:
                if r.fired >= r.times:
                    continue
                if r.kind in unreachable:
                    continue
                if ((r.fragment_id is None or r.fragment_id == fragment_id)
                        and (r.task_index is None
                             or r.task_index == task_index)
                        and (r.attempt is None or r.attempt == attempt)):
                    r.fired += 1
                    out.append({"kind": r.kind, "fragment_id": fragment_id,
                                "task_index": task_index,
                                "attempt": attempt,
                                "stall_s": r.stall_s})
        return out

    def maybe_fail(self, kind: str, fragment_id: int, task_index: int,
                   attempt: int = 0) -> None:
        # TASK_OOM fires at the task-body injection point (same site as
        # TASK_FAILURE, different exception class)
        kinds = (kind, TASK_OOM) if kind == TASK_FAILURE else (kind,)
        with self._lock:
            for r in self.rules:
                if any(r.matches(k, fragment_id, task_index, attempt)
                       for k in kinds):
                    r.fired += 1
                    if r.kind == TASK_OOM:
                        from ..spi.memory import ExceededMemoryLimitError

                        raise ExceededMemoryLimitError(
                            f"injected-oom f{fragment_id}.t{task_index}",
                            1 << 40, 0)
                    raise InjectedFailure(
                        f"injected {kind} at f{fragment_id}.t{task_index} "
                        f"attempt {attempt}")

    def maybe_corrupt_spool(self, attempt_dir: str, fragment_id: int,
                            task_index: int, attempt: int = 0) -> None:
        """When a SPOOL_CORRUPTION rule matches the READING task's
        coordinates, flip one payload byte of the part file that task is
        about to consume from ``attempt_dir`` (deterministic offset: the
        first byte after the stream header + frame header).  The torn/
        flipped frame then fails its CRC at read time."""
        matched = False
        with self._lock:
            for r in self.rules:
                if r.matches(SPOOL_CORRUPTION, fragment_id, task_index,
                             attempt):
                    r.fired += 1
                    matched = True
                    break
        if not matched:
            return
        corrupt_spool_file(attempt_dir, task_index)

    def maybe_stall(self, fragment_id: int, task_index: int,
                    attempt: int = 0, should_cancel=None) -> None:
        """Sleep (outside the lock) when a TASK_STALL rule matches — the
        deterministic straggler for speculative-execution tests.  The sleep
        polls ``should_cancel`` every 50ms so a stall cannot outlive its
        query: a cancelled/aborted/speculatively-lost task exits the stall
        immediately instead of wedging a drain or OOM-kill."""
        delay = 0.0
        with self._lock:
            for r in self.rules:
                if r.kind == TASK_STALL and r.matches(
                        TASK_STALL, fragment_id, task_index, attempt):
                    r.fired += 1
                    delay = max(delay, r.stall_s)
        if delay:
            sleep_with_cancel(delay, should_cancel)


def corrupt_spool_file(attempt_dir: str, partition: int) -> bool:
    """XOR one payload byte of ``part-<partition>.bin`` under
    ``attempt_dir`` (falling back to any part file large enough).  Returns
    True if a byte was flipped.  Shared by the injector and the chaos
    harness's standalone torn-write drills."""
    candidates = [os.path.join(attempt_dir, f"part-{partition}.bin")]
    try:
        candidates += sorted(
            os.path.join(attempt_dir, n) for n in os.listdir(attempt_dir)
            if n.startswith("part-") and n.endswith(".bin"))
    except OSError:
        return False
    # byte 12 = stream magic (4) + frame length (4) + frame crc (4): the
    # first payload byte, so the flip damages data, not framing
    offset = 12
    for path in candidates:
        try:
            if os.path.getsize(path) <= offset:
                continue
            with open(path, "r+b") as f:
                f.seek(offset)
                b = f.read(1)
                f.seek(offset)
                f.write(bytes([b[0] ^ 0xFF]))
            return True
        except OSError:
            continue
    return False


def sleep_with_cancel(delay: float, should_cancel=None,
                      slice_s: float = 0.05) -> bool:
    """Sleep up to ``delay`` seconds in small slices, bailing out as soon
    as ``should_cancel()`` turns true.  Returns True if cancelled early."""
    import time

    if should_cancel is None:
        time.sleep(delay)
        return False
    deadline = time.monotonic() + delay
    while time.monotonic() < deadline:
        if should_cancel():
            return True
        time.sleep(min(slice_s, max(0.0, deadline - time.monotonic())))
    return bool(should_cancel())


def match_wire_rule(rules: list[dict], kind: str, fragment_id: int,
                    task_index: int, attempt: int) -> Optional[dict]:
    """Worker-side rule match over descriptor-carried rules.  Returns the
    full matched rule dict (so callers can read ``stall_s`` etc.) or None.
    Attempt-scoped rules make one-shot semantics deterministic without
    shared state: the retry carries attempt+1 which no longer matches."""
    for r in rules:
        if (r["kind"] == kind
                and (r["fragment_id"] is None
                     or r["fragment_id"] == fragment_id)
                and (r["task_index"] is None
                     or r["task_index"] == task_index)
                and (r["attempt"] is None or r["attempt"] == attempt)):
            return r
    return None


def check_wire_rules(rules: list[dict], kind: str, fragment_id: int,
                     task_index: int, attempt: int) -> Optional[str]:
    r = match_wire_rule(rules, kind, fragment_id, task_index, attempt)
    return r["kind"] if r is not None else None
