"""Streaming-path straggler speculation + the cross-query cluster blacklist.

Extends the FTE speculative-twin machinery (execution/fte.py run_stage —
reference: TaskExecutionClass.java:19 STANDARD/SPECULATIVE) to the streaming
pipelined scheduler: once half of a stage's tasks have committed, a task
whose wall time exceeds ``max(lag_multiplier x stage median, min_delay)``
without producing a single page gets a SPECULATIVE twin.  The twin races the
primary under first-commit-wins: both attempts write through a
:class:`TaskGate` guarding the task's shared OutputBuffer — the first
attempt to enqueue a page (or finish empty) owns the stream, the loser's
first write raises :class:`SpeculationLost` and its attempt unwinds quietly
(no query error, no double-commit: every page of exactly one attempt flows
downstream).

Scope: tasks whose fragment has no remote sources (leaf stages) and whose
sink is a plain OutputBuffer re-execute for free — a leaf twin re-reads its
splits from the connector.  A non-leaf streaming twin has to re-read its
producers' page streams, but the streaming exchange frees pages on ack
(execution/exchange.py) — there is nothing durable to re-read.  That
retention is exactly what FTE's spool buys, and since r15 the streaming
path can buy it too: with ``TRINO_TPU_SPECULATION_NONLEAF`` on, producers
feeding an eligible non-leaf stage tee their (winner-only) pages through
:class:`SpoolTeeBuffer` into a :class:`StreamingSpoolTee` — per-task
durable spool dirs committed by atomic rename, exactly the FTE sink
contract.  Once EVERY source task of a non-leaf stage has committed its
tee, the stage becomes twin-eligible; a straggler's SPECULATIVE attempt
re-reads the committed tee dirs through DurableSpoolClient instead of the
(already-drained) streaming exchange.  MapReduce draws the same line (maps
re-execute from durable input; reducers re-read retained map output —
Dean & Ghemawat, OSDI'04).

:class:`ClusterBlacklist` is the coordinator-held, cross-query companion:
the per-query retry blacklist (distributed_runner._run_query_retry) dies
with the query, so a flaky worker gets one task from EVERY new query.  Here
each recorded failure scores against the worker with a TTL; once the decayed
score crosses the threshold the worker stops receiving tasks across queries
(execution/remote.py _placement_workers) until its entries expire.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Optional

__all__ = ["ClusterBlacklist", "SpeculationLost", "TaskGate", "GatedBuffer",
           "StreamingSpeculation", "StreamingSpoolTee", "SpoolTeeBuffer",
           "speculation_enabled", "nonleaf_speculation_enabled",
           "drain_timeout_s", "STANDARD", "SPECULATIVE"]

STANDARD = "STANDARD"
SPECULATIVE = "SPECULATIVE"


def speculation_enabled(session) -> bool:
    """Session tri-state first (SET SESSION speculation = true), then the
    TRINO_TPU_SPECULATION env knob; off by default."""
    v = getattr(session, "speculation", None)
    if v is None:
        return os.environ.get("TRINO_TPU_SPECULATION", "0").strip().lower() \
            in ("1", "true", "on")
    return bool(v)


def nonleaf_speculation_enabled(session) -> bool:
    """Non-leaf twin eligibility (requires the spool tee): session
    tri-state, then the TRINO_TPU_SPECULATION_NONLEAF knob; off by
    default.  Only meaningful when :func:`speculation_enabled` is on."""
    v = getattr(session, "speculation_nonleaf", None)
    if v is None:
        from ..spi.knobs import get_bool

        return get_bool("TRINO_TPU_SPECULATION_NONLEAF")
    return bool(v)


def drain_timeout_s(session=None, default: float = 30.0) -> float:
    """Bounded graceful-drain budget: session knob, then
    TRINO_TPU_DRAIN_TIMEOUT_S, then ``default``."""
    v = getattr(session, "drain_timeout_s", None) if session is not None \
        else None
    if v:
        return float(v)
    env = os.environ.get("TRINO_TPU_DRAIN_TIMEOUT_S")
    return float(env) if env else float(default)


class SpeculationLost(Exception):
    """Raised inside a racing attempt whose twin already claimed the task's
    output gate; the attempt unwinds without reporting a query error."""


class TaskGate:
    """First-commit-wins ownership of one task's output stream.  ``claim``
    is called on every write: the first caller becomes the owner, later
    callers of the other kind are losers.  ``finish`` marks the owning
    attempt complete (feeds the stage-median straggler cutoff)."""

    def __init__(self, on_claim: Optional[Callable[[str], None]] = None,
                 on_finish: Optional[Callable[[str], None]] = None):
        self._lock = threading.Lock()
        self.owner: Optional[str] = None
        self.finished = False
        self._on_claim = on_claim
        self._on_finish = on_finish

    def claim(self, kind: str) -> bool:
        first = False
        with self._lock:
            if self.owner is None:
                self.owner = kind
                first = True
            ok = self.owner == kind
        if first and self._on_claim is not None:
            self._on_claim(kind)
        return ok

    def finish(self, kind: str) -> None:
        with self._lock:
            if self.owner != kind or self.finished:
                return
            self.finished = True
        if self._on_finish is not None:
            self._on_finish(kind)


class GatedBuffer:
    """OutputBuffer facade for one racing attempt: every write must hold the
    gate.  The loser's first write raises :class:`SpeculationLost`, so all
    pages downstream consumers ever see come from exactly one attempt (the
    sink-buffer byte accounting never sees the loser either)."""

    def __init__(self, inner, gate: TaskGate, kind: str):
        self._inner = inner
        self._gate = gate
        self.kind = kind

    @property
    def num_partitions(self) -> int:
        return self._inner.num_partitions

    @property
    def aborted(self) -> bool:
        return self._inner.aborted

    def enqueue(self, partition: int, batch, **kw) -> None:
        if not self._gate.claim(self.kind):
            raise SpeculationLost(self.kind)
        self._inner.enqueue(partition, batch, **kw)

    def has_capacity(self) -> bool:
        return self._inner.has_capacity()

    def set_finished(self) -> None:
        # an empty output commits here: first to FINISH an empty stream wins
        if not self._gate.claim(self.kind):
            raise SpeculationLost(self.kind)
        self._inner.set_finished()
        self._gate.finish(self.kind)

    def abort(self) -> None:
        self._inner.abort()


class StreamingSpoolTee:
    """Per-query durable tee of streaming producer outputs (the retention
    layer non-leaf speculation needs).  ``want()`` marks a producer
    fragment as teed; its tasks' sinks wrap in :class:`SpoolTeeBuffer`,
    which lands every winner page under
    ``<root>/f<fid>_t<t>/attempt-<n>`` via DurableSpoolWriter (atomic
    rename on commit — identical on-disk layout to the FTE spool, so
    DurableSpoolClient reads it unchanged).  ``ready(srcs)`` answers the
    twin-eligibility question: has every task of every source fragment
    committed its tee?  Callers lease ``root`` through
    :mod:`.spool_gc` (release at query end; boot sweep catches leaks)."""

    def __init__(self, root: str):
        self.root = root
        self._lock = threading.Lock()
        self._want: dict[int, int] = {}            # fid -> task count
        self._committed: dict[int, dict[int, str]] = {}  # fid -> {t: dir}

    def want(self, fid: int, task_count: int) -> None:
        with self._lock:
            self._want[fid] = task_count
            self._committed.setdefault(fid, {})

    def wants(self, fid: int) -> bool:
        with self._lock:
            return fid in self._want

    def writer(self, fid: int, t: int, num_partitions: int,
               attempt: int = 0):
        from .durable_spool import DurableSpoolWriter
        from .fte import fte_task_dir

        task_dir = fte_task_dir(self.root, fid, t)
        os.makedirs(task_dir, exist_ok=True)
        return DurableSpoolWriter(task_dir, attempt, num_partitions)

    def mark_committed(self, fid: int, t: int, attempt_dir: str) -> None:
        with self._lock:
            self._committed.setdefault(fid, {})[t] = attempt_dir

    def ready(self, fids) -> bool:
        with self._lock:
            return all(
                len(self._committed.get(f, ())) >= self._want.get(f, 1 << 30)
                for f in fids)

    def committed_dirs(self, fid: int) -> Optional[list]:
        """Task-ordered committed attempt dirs, or None while incomplete."""
        with self._lock:
            got = self._committed.get(fid, {})
            if len(got) < self._want.get(fid, 1 << 30):
                return None
            return [got[t] for t in sorted(got)]


class SpoolTeeBuffer:
    """Sink facade teeing every page that clears ``inner`` (the gated or
    plain OutputBuffer) into a durable spool writer.  The tee sits OUTSIDE
    the gate: a losing attempt's enqueue raises SpeculationLost before the
    tee sees the page, so the committed tee holds exactly the winner's
    stream."""

    def __init__(self, inner, writer, on_commit: Callable[[str], None]):
        self._inner = inner
        self._writer = writer
        self._on_commit = on_commit

    @property
    def num_partitions(self) -> int:
        return self._inner.num_partitions

    @property
    def aborted(self) -> bool:
        return self._inner.aborted

    def enqueue(self, partition: int, batch, **kw) -> None:
        self._inner.enqueue(partition, batch, **kw)
        self._writer.enqueue(partition, batch)

    def has_capacity(self) -> bool:
        return self._inner.has_capacity()

    def set_finished(self) -> None:
        self._inner.set_finished()  # loser raises here; tee stays .tmp
        self._writer.set_finished()
        self._on_commit(self._writer.committed)

    def abort(self) -> None:
        try:
            self._inner.abort()
        finally:
            self._writer.abort()


class _TaskTrack:
    __slots__ = ("gate", "twin_started", "cancel", )

    def __init__(self):
        # cancel[kind] is set when the OTHER kind wins; racing attempts poll
        # it from injected stalls (failure_injector.maybe_stall) and before
        # planning, so a losing straggler exits early instead of sleeping
        # out its injected stall
        self.gate: Optional[TaskGate] = None
        self.twin_started = False
        self.cancel = {STANDARD: threading.Event(),
                       SPECULATIVE: threading.Event()}


class _StageTrack:
    __slots__ = ("fid", "tc", "t0", "tasks", "durations", "eligible")

    def __init__(self, fid: int, tc: int, t0: float, eligible=None):
        self.fid = fid
        self.tc = tc
        self.t0 = t0
        self.tasks: dict[int, _TaskTrack] = {}
        self.durations: list[float] = []
        # optional gate on twin launches: non-leaf stages pass a predicate
        # ("are all my sources' tee spools committed?") that must hold
        # before any twin spawns — a twin with an incomplete upstream tee
        # would re-read a truncated stream
        self.eligible = eligible


class StreamingSpeculation:
    """Per-query controller: tracks eligible stages, detects stragglers on
    the coordinator's join-poll cadence, and launches twins.  All bookkeeping
    is query-local; cumulative counters land in telemetry + the runner's
    resilience event log."""

    def __init__(self, lag_multiplier: float = 2.0,
                 min_delay_s: float = 0.25,
                 events: Optional[list] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.lag_multiplier = max(1.0, float(lag_multiplier))
        self.min_delay_s = float(min_delay_s)
        self.events = events if events is not None else []
        self._clock = clock
        self._lock = threading.Lock()
        self._stages: dict[int, _StageTrack] = {}
        self.starts = 0
        self.wins = 0

    # --------------------------------------------------------- registration
    def register_stage(self, fid: int, tc: int, eligible=None) -> None:
        with self._lock:
            self._stages[fid] = _StageTrack(fid, tc, self._clock(),
                                            eligible=eligible)

    def register_task(self, fid: int, t: int) -> TaskGate:
        """Create the task's gate; returns it for sink wrapping."""
        with self._lock:
            st = self._stages[fid]
            tr = _TaskTrack()
            st.tasks[t] = tr
        tr.gate = TaskGate(
            on_claim=lambda kind, _f=fid, _t=t: self._claimed(_f, _t, kind),
            on_finish=lambda kind, _f=fid, _t=t: self._finished(_f, _t))
        return tr.gate

    def cancel_event(self, fid: int, t: int, kind: str) -> threading.Event:
        with self._lock:
            return self._stages[fid].tasks[t].cancel[kind]

    # ------------------------------------------------------------ callbacks
    def _claimed(self, fid: int, t: int, kind: str) -> None:
        from ..telemetry import metrics as tm

        with self._lock:
            tr = self._stages[fid].tasks[t]
            had_twin = tr.twin_started
        loser = STANDARD if kind == SPECULATIVE else SPECULATIVE
        tr.cancel[loser].set()
        if kind == SPECULATIVE:
            with self._lock:
                self.wins += 1
            tm.SPECULATIVE_WINS.inc()
            self.events.append(("speculative_win", fid, t))
            from ..telemetry import profiler

            profiler.instant(profiler.SPECULATION,
                             f"speculative-win[f{fid}.t{t}]")
        if had_twin:
            self.events.append(("speculative_cancelled", fid, t, loser))

    def _finished(self, fid: int, t: int) -> None:
        now = self._clock()
        with self._lock:
            st = self._stages[fid]
            st.durations.append(now - st.t0)

    # ------------------------------------------------------------ detection
    def tick(self, spawn: Callable[[int, int], object]) -> list:
        """One straggler sweep: for every stage with >= half its tasks
        committed, twin each unclaimed task past the lag cutoff.  ``spawn``
        launches the SPECULATIVE attempt and returns its thread; the list of
        new threads is handed back so the join loop tracks them."""
        from ..telemetry import metrics as tm

        now = self._clock()
        out = []
        with self._lock:
            stages = list(self._stages.values())
        for st in stages:
            if st.eligible is not None and not st.eligible():
                continue
            with self._lock:
                committed = len(st.durations)
                if st.tc < 2 or committed * 2 < st.tc:
                    continue
                med = sorted(st.durations)[committed // 2]
                cutoff = max(self.lag_multiplier * med, self.min_delay_s)
                lagging = [
                    (t, tr) for t, tr in st.tasks.items()
                    if tr.gate is not None and tr.gate.owner is None
                    and not tr.twin_started and now - st.t0 > cutoff
                ]
                for _t, tr in lagging:
                    tr.twin_started = True
                    self.starts += 1
            for t, _tr in lagging:
                tm.SPECULATIVE_STARTS.inc()
                self.events.append(("speculative_start", st.fid, t))
                from ..telemetry import profiler

                profiler.instant(profiler.SPECULATION,
                                 f"speculative-start[f{st.fid}.t{t}]")
                th = spawn(st.fid, t)
                if th is not None:
                    out.append(th)
        return out


class ClusterBlacklist:
    """Coordinator-held cross-query worker blacklist with TTL decay.

    Each failure records ``(timestamp, weight)`` against the worker; the
    score is the weight sum of unexpired entries, and a worker is
    blacklisted while ``score >= threshold``.  Entries expire after
    ``ttl_s`` — a worker that stops failing regains placement without any
    operator action.  Thread-safe; the ``trino_blacklisted_workers`` gauge
    tracks the current blacklisted set size."""

    def __init__(self, ttl_s: Optional[float] = None,
                 threshold: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 persist: bool = False,
                 path: Optional[str] = None):
        if ttl_s is None:
            ttl_s = float(os.environ.get("TRINO_TPU_BLACKLIST_TTL_S", "300"))
        if threshold is None:
            threshold = float(
                os.environ.get("TRINO_TPU_BLACKLIST_THRESHOLD", "2"))
        self.ttl_s = float(ttl_s)
        self.threshold = max(1.0, float(threshold))
        self._clock = clock
        # persist=False keeps unit tests with fake clocks from polluting
        # (or being polluted by) the process journal
        self._persist = persist
        self._lock = threading.Lock()
        # worker -> list of (monotonic ts, weight, reason)
        self._entries: dict[str, list[tuple[float, float, str]]] = {}
        # fleet-shared durable store (execution/resilience.py): when the
        # whole coordinator fleet points TRINO_TPU_BLACKLIST_PATH at one
        # file, strikes are appended there and merged on every read — a
        # worker that fails under coordinator A is blacklisted under B too,
        # and concurrent writers interleave instead of clobbering
        self._store = None
        if persist:
            from .resilience import SharedBlacklistStore, blacklist_path

            shared = path if path is not None else blacklist_path()
            if shared:
                self._store = SharedBlacklistStore(shared)
                self._merge_store()
            else:
                self.seed_from_journal()

    def _merge_store(self) -> None:
        """Fold every strike appended to the shared store since the last
        merge (ours and our peers') into the in-memory table, back-dated on
        this process's monotonic clock so TTL decay expires each entry at
        the same wall moment fleet-wide."""
        if self._store is None:
            return
        recs = self._store.poll()
        if not recs:
            return
        now_wall = time.time()
        now = self._clock()
        with self._lock:
            for rec in recs:
                try:
                    age = now_wall - float(rec["ts"])
                    worker = rec["worker"]
                    weight = float(rec.get("weight", 1.0))
                except (KeyError, TypeError, ValueError):
                    continue
                if not 0 <= age < self.ttl_s:
                    continue
                self._entries.setdefault(worker, []).append(
                    (now - age, weight, str(rec.get("reason", ""))))
            self._prune_locked(now)

    def _prune_locked(self, now: float) -> None:
        horizon = now - self.ttl_s
        for w in list(self._entries):
            kept = [e for e in self._entries[w] if e[0] > horizon]
            if kept:
                self._entries[w] = kept
            else:
                del self._entries[w]

    def record_failure(self, worker: str, reason: str = "",
                       weight: float = 1.0, query_id: str = "") -> float:
        if self._store is not None:
            # the shared file is the single source of truth: append the
            # strike there and read it back through the ordinary merge (no
            # separate local insert — that would double-count our own rows)
            self._store.append(worker, weight, reason, query_id)
            self._merge_store()
            with self._lock:
                score = sum(e[1] for e in self._entries.get(worker, ()))
            self._refresh_gauge()
            return score
        now = self._clock()
        with self._lock:
            self._prune_locked(now)
            self._entries.setdefault(worker, []).append(
                (now, float(weight), reason))
            score = sum(e[1] for e in self._entries[worker])
        self._refresh_gauge()
        if self._persist:
            self._journal_entry(worker, weight, reason, query_id)
        return score

    # ----------------------------------------------------------- durability
    def _journal_entry(self, worker: str, weight: float, reason: str,
                       query_id: str) -> None:
        """Append the failure to the durable query journal so a restarted
        coordinator re-seeds the blacklist instead of handing the flaky
        worker one task from every post-restart query."""
        from ..telemetry import journal as tj

        j = tj.get_journal()
        if j is None:
            return
        j._write({
            "schema": tj.SCHEMA_VERSION,
            "event": "blacklist_entry",
            "ts": time.time(),  # wall clock: must survive process restarts
            "query_id": query_id,
            "worker": worker,
            "weight": float(weight),
            "reason": reason,
        })

    def seed_from_journal(self) -> int:
        """Boot-time re-seed with TTL decay: journal entries younger than
        ``ttl_s`` (by wall clock) re-enter the in-memory table back-dated on
        this blacklist's monotonic clock, so they expire at the same wall
        moment they would have without the restart.  Returns entries kept."""
        from ..telemetry import journal as tj

        j = tj.get_journal()
        if j is None:
            return 0
        now_wall = time.time()
        now = self._clock()
        kept = 0
        with self._lock:
            for rec in j.read(events=("blacklist_entry",)):
                try:
                    age = now_wall - float(rec["ts"])
                    worker = rec["worker"]
                    weight = float(rec.get("weight", 1.0))
                except (KeyError, TypeError, ValueError):
                    continue
                if not 0 <= age < self.ttl_s:
                    continue
                self._entries.setdefault(worker, []).append(
                    (now - age, weight, str(rec.get("reason", ""))))
                kept += 1
            self._prune_locked(now)
        if kept:
            self._refresh_gauge()
        return kept

    def score(self, worker: str) -> float:
        self._merge_store()
        now = self._clock()
        with self._lock:
            self._prune_locked(now)
            return sum(e[1] for e in self._entries.get(worker, ()))

    def is_blacklisted(self, worker: str) -> bool:
        return self.score(worker) >= self.threshold

    def blacklisted(self) -> frozenset:
        self._merge_store()
        now = self._clock()
        with self._lock:
            self._prune_locked(now)
            out = frozenset(
                w for w, es in self._entries.items()
                if sum(e[1] for e in es) >= self.threshold)
        self._refresh_gauge()
        return out

    def snapshot(self) -> dict[str, float]:
        """worker -> current score (system.runtime.workers feed)."""
        self._merge_store()
        now = self._clock()
        with self._lock:
            self._prune_locked(now)
            return {w: sum(e[1] for e in es)
                    for w, es in self._entries.items()}

    def _refresh_gauge(self) -> None:
        from ..telemetry import metrics as tm

        with self._lock:
            n = sum(1 for es in self._entries.values()
                    if sum(e[1] for e in es) >= self.threshold)
        tm.BLACKLISTED_WORKERS.set(n)
