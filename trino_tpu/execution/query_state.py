"""Write-ahead query-state log: FTE queries survive the coordinator.

PR 9's chaos soak certified worker death; the coordinator itself remained
the single point of failure — a crash lost every in-flight query even
though all committed stage outputs were already durable on disk
(execution/durable_spool.py).  This module is the missing piece of the
reference's spooled-execution story (EventDrivenFaultTolerantQueryScheduler
+ FileSystemExchangeManager): the *coordinator's* scheduling state becomes
recoverable too.

One JSONL file per ``retry_policy="TASK"`` query, in the same torn-tail-
tolerant style as telemetry/journal.py:

- ``begin``             sql, plan fingerprint + the zlib-pickled fragment
                        tree (the exact idiom worker.py uses to ship
                        fragments across process boundaries), the spool
                        root, and the JSON-able session fields that shape
                        FTE execution — everything a fresh coordinator
                        needs to re-materialize the query;
- ``attempt_start``     appended before every task attempt (the counters
                        that make "committed attempts are never
                        re-executed" *assertable*, not just claimed);
- ``attempt_committed`` appended + fsync'd inside ``commit()`` — after the
                        spool's atomic rename, so a record always points at
                        a directory that exists and is complete;
- ``end``               terminal state; a file with no ``end`` is an
                        in-flight query the next boot must resume.

Recovery (server/protocol.py at dispatcher boot → ``resume_fte_query`` in
distributed_runner.py) replays the committed-attempt map and re-runs only
what is missing; clients reattach by query id through the unchanged
``GET /v1/statement`` polling surface.
"""

from __future__ import annotations

import base64
import dataclasses
import hashlib
import json
import os
import pickle
import re
import tempfile
import threading
import time
import zlib
from dataclasses import dataclass, field
from typing import Optional

__all__ = ["QueryStateLog", "PendingQuery", "enabled", "state_dir",
           "encode_plan", "decode_plan", "load", "pending", "discard",
           "prune_ended", "restore_session"]

SCHEMA_VERSION = 1
_SAFE_QID = re.compile(r"[^A-Za-z0-9_.-]")

# Session fields recorded at begin() and replayed through
# dataclasses.replace on recovery: the JSON-able knobs that change what an
# FTE re-run would execute.  Process-local handles (failure_injector,
# transaction, ...) deliberately do NOT survive a coordinator death.
SESSION_FIELDS = (
    "default_catalog", "user", "splits_per_node", "node_count",
    "dynamic_filtering", "exchange_serde", "retry_policy",
    "task_retry_attempts", "fte_speculative", "fte_speculative_delay_s",
    "fte_memory_growth", "task_concurrency", "task_scheduler",
    "executor_workers", "scale_writers", "writer_task_limit",
)


def enabled() -> bool:
    from ..spi.knobs import get_bool

    return get_bool("TRINO_TPU_QUERY_STATE")


def default_dir() -> str:
    try:
        uid = os.getuid()
    except AttributeError:  # non-posix
        uid = 0
    return os.path.join(tempfile.gettempdir(),
                        f"trino-tpu-query-state-{uid}")


def state_dir() -> str:
    from ..spi.knobs import get_str

    return get_str("TRINO_TPU_QUERY_STATE_DIR") or default_dir()


def _wal_path(query_id: str, dir: Optional[str] = None) -> str:
    safe = _SAFE_QID.sub("_", query_id) or "query"
    return os.path.join(dir or state_dir(), safe + ".wal")


def encode_plan(subplan) -> tuple[str, str]:
    """-> (base64 of zlib-pickled SubPlan, sha256 fingerprint).  Fragments
    already pickle across the worker process boundary (execution/worker.py
    encode_task), so the WAL reuses the identical envelope."""
    raw = zlib.compress(pickle.dumps(subplan), level=1)
    return (base64.b64encode(raw).decode("ascii"),
            hashlib.sha256(raw).hexdigest()[:16])


def decode_plan(plan_b64: str):
    return pickle.loads(zlib.decompress(base64.b64decode(plan_b64)))


class QueryStateLog:
    """Append-only per-query WAL.  ``attempt_committed`` and the begin/end
    bracket are fsync'd (they are the recovery contract); ``attempt_start``
    is flushed only — it exists for re-execution accounting, and a lost
    tail start record can only *under*-count work the dying coordinator
    did, never resurrect it."""

    def __init__(self, query_id: str, dir: Optional[str] = None):
        self.query_id = query_id
        self.path = _wal_path(query_id, dir)
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        self._f = open(self.path, "a", encoding="utf-8")
        self._lock = threading.Lock()

    def _append(self, record: dict, fsync: bool) -> None:
        record.setdefault("ts", time.time())
        line = json.dumps(record, separators=(",", ":"))
        with self._lock:
            if self._f.closed:
                return
            self._f.write(line + "\n")
            self._f.flush()
            if fsync:
                os.fsync(self._f.fileno())

    def begin(self, sql: str, subplan, spool_root: str, session,
              task_counts: Optional[dict] = None,
              consumer_tasks: Optional[dict] = None) -> None:
        plan_b64, fingerprint = encode_plan(subplan)
        sess = {}
        for name in SESSION_FIELDS:
            v = getattr(session, name, None)
            if isinstance(v, (str, int, float, bool)) or v is None:
                sess[name] = v
        self._append({
            "schema": SCHEMA_VERSION, "event": "begin",
            "query_id": self.query_id, "sql": sql,
            "fingerprint": fingerprint, "spool_root": spool_root,
            "session": sess, "plan": plan_b64,
            # the stage shape the committed dirs were produced under: a
            # resumed run whose worker topology changed these counts must
            # NOT reuse them (the per-partition files would be misshapen)
            "task_counts": {str(k): v for k, v in (task_counts or {})
                            .items()},
            "consumer_tasks": {str(k): v for k, v in (consumer_tasks or {})
                               .items()},
        }, fsync=True)

    def attempt_start(self, fragment_id: int, task_index: int,
                      attempt: int, kind: str) -> None:
        self._append({"event": "attempt_start", "fragment": fragment_id,
                      "task": task_index, "attempt": attempt,
                      "kind": kind}, fsync=False)

    def attempt_committed(self, fragment_id: int, task_index: int,
                          attempt: int, dir: str, kind: str) -> None:
        self._append({"event": "attempt_committed", "fragment": fragment_id,
                      "task": task_index, "attempt": attempt, "dir": dir,
                      "kind": kind}, fsync=True)

    def attempt_discarded(self, fragment_id: int, task_index: int,
                          reason: str) -> None:
        """A previously-committed attempt was invalidated (spool
        corruption); its producer will re-run."""
        self._append({"event": "attempt_discarded", "fragment": fragment_id,
                      "task": task_index, "reason": reason}, fsync=True)

    def end(self, state: str, error: Optional[str] = None) -> None:
        rec = {"event": "end", "state": state}
        if error:
            rec["error"] = error
        self._append(rec, fsync=True)

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


@dataclass
class PendingQuery:
    """One parsed WAL file (in-flight unless ``ended``)."""

    query_id: str
    path: str
    sql: str = ""
    fingerprint: str = ""
    spool_root: str = ""
    session_fields: dict = field(default_factory=dict)
    plan_b64: str = ""
    task_counts: dict = field(default_factory=dict)      # str(fid) -> tc
    consumer_tasks: dict = field(default_factory=dict)   # str(fid) -> tc
    ended: Optional[str] = None        # terminal state string, if any
    # (fragment, task) -> {"attempt": n, "dir": path, "kind": ...} with
    # later records superseding earlier ones (a discard removes the entry)
    committed: dict = field(default_factory=dict)
    # (fragment, task) -> number of attempt_start records (re-execution
    # accounting across coordinator generations)
    attempt_counts: dict = field(default_factory=dict)

    @property
    def resumable(self) -> bool:
        return self.ended is None and bool(self.plan_b64)

    def committed_dirs(self) -> dict:
        return {k: v["dir"] for k, v in self.committed.items()}

    def shape_matches(self, task_counts: dict, consumer_tasks: dict) -> bool:
        """Committed dirs are reusable only when the resumed plan's stage
        shape equals the recorded one (worker replacement between boots can
        change task fan-out, which changes partition-file layout)."""
        if not self.task_counts:
            return True  # legacy record without shapes: trust the caller
        return (self.task_counts == {str(k): v
                                     for k, v in task_counts.items()}
                and self.consumer_tasks == {str(k): v for k, v
                                            in consumer_tasks.items()})


def load(path: str) -> Optional[PendingQuery]:
    """Parse one WAL file; unparseable lines (torn tail from a kill -9 mid
    write) are skipped, mirroring telemetry/journal.py reader semantics."""
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError:
        return None
    pq = PendingQuery(query_id=os.path.basename(path)[:-len(".wal")],
                      path=path)
    for line in lines:
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        ev = rec.get("event")
        if ev == "begin":
            pq.query_id = rec.get("query_id", pq.query_id)
            pq.sql = rec.get("sql", "")
            pq.fingerprint = rec.get("fingerprint", "")
            pq.spool_root = rec.get("spool_root", "")
            pq.session_fields = rec.get("session", {}) or {}
            pq.plan_b64 = rec.get("plan", "")
            pq.task_counts = rec.get("task_counts", {}) or {}
            pq.consumer_tasks = rec.get("consumer_tasks", {}) or {}
            pq.ended = None
        elif ev == "attempt_start":
            key = (rec.get("fragment"), rec.get("task"))
            pq.attempt_counts[key] = pq.attempt_counts.get(key, 0) + 1
        elif ev == "attempt_committed":
            key = (rec.get("fragment"), rec.get("task"))
            pq.committed[key] = {"attempt": rec.get("attempt"),
                                 "dir": rec.get("dir"),
                                 "kind": rec.get("kind")}
        elif ev == "attempt_discarded":
            pq.committed.pop((rec.get("fragment"), rec.get("task")), None)
        elif ev == "end":
            pq.ended = rec.get("state", "FINISHED")
    return pq


def pending(dir: Optional[str] = None) -> list[PendingQuery]:
    """Every in-flight resumable query recorded under ``dir`` (the boot-
    time recovery work list), oldest WAL first."""
    d = dir or state_dir()
    if not os.path.isdir(d):
        return []
    out = []
    for name in sorted(os.listdir(d)):
        if not name.endswith(".wal"):
            continue
        pq = load(os.path.join(d, name))
        if pq is not None and pq.resumable:
            out.append(pq)
    out.sort(key=lambda p: _mtime(p.path))
    return out


def _mtime(path: str) -> float:
    try:
        return os.path.getmtime(path)
    except OSError:
        return 0.0


def prune_ended(dir: Optional[str] = None) -> int:
    """Delete WAL files whose query reached a terminal state (boot-time
    hygiene: only in-flight queries deserve durable state).  Returns the
    number removed."""
    d = dir or state_dir()
    if not os.path.isdir(d):
        return 0
    removed = 0
    for name in sorted(os.listdir(d)):
        if not name.endswith(".wal"):
            continue
        path = os.path.join(d, name)
        pq = load(path)
        if pq is not None and pq.ended is not None:
            try:
                os.remove(path)
                removed += 1
            except OSError:
                pass
    return removed


def discard(query_id: str, dir: Optional[str] = None) -> None:
    try:
        os.remove(_wal_path(query_id, dir))
    except OSError:
        pass


def restore_session(pq: PendingQuery, base=None):
    """Rebuild a Session for the resumed run: the recorded FTE-shaping
    fields over a fresh (or caller-provided) base."""
    from ..runner import Session

    base = base if base is not None else Session()
    known = {f.name for f in dataclasses.fields(Session)}
    fields = {k: v for k, v in pq.session_fields.items() if k in known}
    return dataclasses.replace(base, **fields)
