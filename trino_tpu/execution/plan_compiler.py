"""Whole-query GSPMD compilation: one jitted program per maximal
TPU-resident plan.

The fragmenter coalesces maximal connected subtrees of device-resident
fragments — a broadcast multi-join tree under an already-fusable
PARTIAL->FINAL agg seam — into a ``ResidentPlan`` record carrying a
per-edge PartitionSpec contract (execution/fragmenter.py).  This module
lowers each record to ONE per-batch jitted program plus the inherited
seam-merge shard_map:

1. **Build prep** (once per build fragment, per query): every build
   task's deposited batches ride an in-program ``shard_map``
   ``all_gather`` over the named mesh — the BROADCAST interior edge,
   in_spec ``P("x")`` / out_spec ``P()`` (replicated) — then sort by key
   with dead lanes pushed to an int64 sentinel.  Dictionary codes cross
   this seam AS CODES: the tiny dictionaries unify host-side, the code
   lanes gather and permute on device, nothing materializes to values
   (PR 16's deferred follow-up).  Duplicate live build keys trip a
   replicated flag and the plan falls back (the sorted-probe inlined
   below has 1-match semantics).

2. **Whole-plan accumulate** (one call per probe batch, per task): the
   scan feed's batch probes every build via ``searchsorted`` on the
   replicated sorted keys, the Filter/Project chain and the partial
   aggregation + carried-state merge run inline — the whole multi-join
   tree is ONE ``jax.jit`` dispatch with the state pytree donated.
   Missing valid masks and absent live lanes normalize INSIDE the
   program, so launches/batch is ~1 (vs ~2.4 for the PR 6 fused seam).
   The program is cached via the PR 12 ``jit_memo`` registry under a
   JSON-able key (base64 of the zlib-pickled plan payload — same serde
   as query_state.encode_plan), so ``exec_warm.json`` boot replay warms
   resident programs too, unlike the id()-keyed fused accumulate memo.

3. **Seam merge** (inherited from FusedStageExec): the terminal
   REPARTITION edge stays the PR 6 shard_map all_to_all with matched
   ``P("x")`` in/out specs.

Multi-process: ``init_distributed`` wires ``jax.distributed`` with the
gloo CPU-collectives backend so one program spans hosts on a CPU mesh
(``--xla_force_host_platform_device_count`` per process in CI; real ICI
on hardware).

``TRINO_TPU_RESIDENT_PLAN={auto,1,0}``: 0 keeps the task-per-worker
fused/legacy path bit-for-bit.  Overflow, duplicate build keys, or any
build failure raise ``ResidentPlanOverflow`` and the runner re-runs the
subplan on the non-resident path (same contract as FusedStageOverflow).
"""

from __future__ import annotations

import base64
import os
import pickle
import threading
import zlib
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..caching.executable_cache import jit_memo
from ..exec import kernels as K
from ..exec import syncguard as SG
from ..exec.operators import Operator
from ..exec.stats import FusedStageStats, ResidentPlanStats
from ..parallel.compat import shard_map
from ..planner import plan as PL
from ..spi.batch import ColumnBatch
from ..spi.errors import PAGE_TRANSPORT_TIMEOUT, TrinoError
from ..sql.ir import InputRef
from .stage_compiler import (
    _AXIS,
    FusedStageExec,
    FusedStageOverflow,
    FusedStageSpec,
    _AccumulateProgram,
    _ingest_program,
    _pad_table,
    build_fused_spec,
    fused_cap,
    fused_stage_mode,
)

__all__ = ["ResidentPlanExec", "ResidentPlanOverflow", "ResidentPlanSpec",
           "ResidentBuildHandle", "ResidentBuildSinkOperator",
           "ResidentPlanSinkOperator", "build_resident_spec",
           "plan_resident_plans", "resident_plan_mode",
           "resident_max_fragments", "init_distributed"]

_KEY_SENTINEL = np.iinfo(np.int64).max


def resident_plan_mode() -> str:
    """TRINO_TPU_RESIDENT_PLAN: auto (default, compile eligible resident
    plans), 1 (same), 0 (task-per-worker fused/legacy path, bit-for-bit)."""
    v = os.environ.get("TRINO_TPU_RESIDENT_PLAN", "auto").strip().lower()
    return v if v in ("auto", "1", "0") else "auto"


def resident_max_fragments() -> int:
    """Largest fragment count a single resident program may absorb
    (TRINO_TPU_RESIDENT_MAX_FRAGMENTS)."""
    return int(os.environ.get("TRINO_TPU_RESIDENT_MAX_FRAGMENTS", "8"))


def _mesh_device_cap() -> int:
    """TRINO_TPU_MESH_SHAPE override ("8" or "2x4"): product caps the
    mesh width a resident plan may claim; 0 = no override."""
    v = os.environ.get("TRINO_TPU_MESH_SHAPE", "").strip().lower()
    if not v:
        return 0
    try:
        dims = [int(p) for p in v.replace("x", " ").split()]
    except ValueError:
        return 0
    n = 1
    for d in dims:
        if d <= 0:
            return 0
        n *= d
    return n


def init_distributed(coordinator_address: str, num_processes: int,
                     process_id: int) -> None:
    """jax.distributed bring-up for multi-host resident plans.  The gloo
    CPU-collectives backend MUST be selected before initialize: the
    default XLA CPU backend rejects multi-process collectives outright."""
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


class ResidentPlanOverflow(FusedStageOverflow):
    """A resident plan can't hold (state overflow, duplicate build keys,
    build failure); the runner re-runs the subplan with resident+fused
    compilation disabled."""


# ---------------------------------------------------------------------------
# spec: what the fragmenter's ResidentPlan lowers to


@dataclass
class ResidentPlanSpec(FusedStageSpec):
    """FusedStageSpec plus the inlined broadcast joins.  ``feed`` is the
    scan chain BELOW the join spine (what the legacy operator pipeline
    executes per task); ``joins`` apply bottom-up, each widening the
    probe schema by its build fragment's output columns."""

    joins: tuple = ()              # tuple[fragmenter.ResidentJoin, ...]
    build_types: tuple = ()        # per-join tuple of build output types
    plan: object = None            # the fragmenter.ResidentPlan record


def build_resident_spec(frag, frags_by_id: dict, n_tasks: int,
                        cap: int) -> ResidentPlanSpec:
    """Lower a fragmenter-marked ResidentPlan into the executable spec."""
    rp = frag.resident_plan
    base = build_fused_spec(frag, frags_by_id[rp.consumer_fid], n_tasks, cap)
    feed = base.feed               # the topmost Join of the probe spine
    for _ in rp.joins:
        feed = feed.left
    build_types = tuple(
        tuple(frags_by_id[j.build_fid].root.output_types) for j in rp.joins)
    return ResidentPlanSpec(
        producer_fid=base.producer_fid, consumer_fid=base.consumer_fid,
        n_tasks=n_tasks, feed=feed, chain=base.chain, partial=base.partial,
        final=base.final, nk=base.nk, cap=cap, state_specs=base.state_specs,
        joins=tuple(rp.joins), build_types=build_types, plan=rp)


def _key_origins(spec: ResidentPlanSpec) -> list:
    """For each group key, its channel in the post-join (chain-input)
    schema, or None when the key is a computed expression.  Drives the
    sink's dictionary-drift handling: feed-origin dict keys drift per
    batch, build-origin dict keys are stable for the whole query."""
    if spec.chain:
        width = len(spec.chain[0].source.output_types)
    else:
        width = len(spec.partial.source.output_types)
    idx: list = list(range(width))
    for node in spec.chain:
        if isinstance(node, PL.Project):
            idx = [idx[e.index] if isinstance(e, InputRef) else None
                   for e in node.expressions]
    return [idx[c] for c in spec.partial.group_keys]


# ---------------------------------------------------------------------------
# the whole-plan program: probe every build + chain + partial agg + state
# merge, ONE jit call per batch


class _ResidentProgram(_AccumulateProgram):
    """The per-batch resident-plan program.  Joins are sorted-probe
    lookups against the replicated build tables (1-match semantics —
    duplicate build keys fall back at prep); the Filter/Project chain and
    the aggregation tail reuse the fused accumulate bodies.  Expressions
    compile WITHOUT dictionaries (eligibility guarantees the chain is
    dict-free; codes pass through as bare lanes), so the program is
    dictionary-independent and its memo key is a pure value."""

    def __init__(self, spec: ResidentPlanSpec):
        self.spec = spec
        if spec.chain:
            in_types = list(spec.chain[0].source.output_types)
        else:
            in_types = list(spec.partial.source.output_types)
        self._compile_chain(in_types, [None] * len(in_types))
        self._fn = jax.jit(self._run, donate_argnums=(0,))
        self._init_fn = jax.jit(self._initial_state)

    def __call__(self, state, feed_cols, live, builds, batch_remaps,
                 state_remaps):
        return self._fn(state, feed_cols, live, builds, batch_remaps,
                        state_remaps)

    def _run(self, state, feed_cols, live, builds, batch_remaps,
             state_remaps):
        n = feed_cols[0][0].shape[0]
        # normalize IN-program: no ingest launch ahead of the dispatch
        cols = [(d, v if v is not None else jnp.ones(n, jnp.bool_))
                for d, v in feed_cols]
        if live is None:
            live = jnp.ones(n, jnp.bool_)
        for join, (bk, blive, payload) in zip(self.spec.joins, builds):
            pk_d, pk_v = cols[join.probe_key]
            pk = pk_d.astype(jnp.int64)
            idx = jnp.clip(jnp.searchsorted(bk, pk),
                           0, bk.shape[0] - 1).astype(jnp.int32)
            hit = (bk[idx] == pk) & blive[idx] & pk_v
            for d, v in payload:
                cols.append((d[idx], hit if v is None else (v[idx] & hit)))
            if join.join_type == "INNER":
                live = live & hit
        cols, live, batch_err = self._apply_chain(cols, live, n)
        return self._agg_merge(state, cols, live, batch_remaps,
                               state_remaps, n, batch_err)


def _encode_resident_payload(spec: ResidentPlanSpec) -> str:
    """Value-serialize everything the program depends on — same base64 /
    zlib / pickle serde as query_state.encode_plan.  This string IS the
    jit_memo key: JSON-able, so exec_warm.json replay rebuilds resident
    programs at boot (the fused accumulate memo keys on id() and can't)."""
    raw = pickle.dumps((tuple(spec.chain), spec.partial, tuple(spec.joins),
                        tuple(spec.state_specs)), protocol=4)
    return base64.b64encode(zlib.compress(raw)).decode("ascii")


@jit_memo("resident._program", maxsize=64)
def _resident_program(spec_b64: str, cap: int) -> _ResidentProgram:
    chain, partial, joins, state_specs = pickle.loads(
        zlib.decompress(base64.b64decode(spec_b64)))
    feed = chain[0].source if chain else partial.source
    spec = ResidentPlanSpec(
        producer_fid=-1, consumer_fid=-1, n_tasks=0, feed=feed,
        chain=tuple(chain), partial=partial, final=partial,
        nk=len(partial.group_keys), cap=cap, state_specs=tuple(state_specs),
        joins=tuple(joins))
    return _ResidentProgram(spec)


# compile counting for resident dispatches (same TLS-free set discipline
# as stage_compiler._TRACE_SIGS)
_RES_TRACE_SIGS: set = set()
_RES_LOCK = threading.Lock()


# ---------------------------------------------------------------------------
# build prep: the in-program BROADCAST interior edge


@jit_memo("resident._build_prep")
def _build_prep_program(n_dev: int, n_payload: int):
    """ONE jitted shard_map per (mesh width, payload width): every build
    lane all_gathers over the mesh axis (the BROADCAST edge of the
    ResidentPlan contract — in_spec P("x"), out_spec P() replicated),
    dead/NULL-key lanes push to the int64 sentinel, one argsort orders
    the table for the sorted probe, and adjacent live duplicates raise a
    replicated flag (fallback: the probe is 1-match)."""
    mesh = Mesh(jax.devices()[:n_dev], (_AXIS,))

    def local(key, live, *payload_flat):
        gk = jax.lax.all_gather(key, _AXIS, tiled=True)
        gl = jax.lax.all_gather(live, _AXIS, tiled=True)
        sk = jnp.where(gl, gk, _KEY_SENTINEL)
        perm = jnp.argsort(sk)
        sk = sk[perm]
        sl = gl[perm]
        outs = [sk, sl]
        for arr in payload_flat:
            g = jax.lax.all_gather(arr, _AXIS, tiled=True)
            outs.append(g[perm])
        dup = jnp.any((sk[1:] == sk[:-1]) & sl[1:] & sl[:-1])
        outs.append(dup)
        return tuple(outs)

    n_in = 2 + 2 * n_payload
    return mesh, jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=tuple([P(_AXIS)] * n_in),
        out_specs=tuple([P()] * (n_in + 1)),
        check_vma=False,
    ))


# ---------------------------------------------------------------------------
# rendezvous


class ResidentPlanExec(FusedStageExec):
    """Rendezvous for one resident plan: build sinks deposit their
    fragments' batches (last depositor runs the broadcast prep), probe
    sinks wait for every build then absorb batches with one whole-plan
    dispatch each, and the inherited FusedStageExec seam merge + take
    serve the consumer.  The terminal REPARTITION edge keeps the PR 6
    P("x")->P("x") contract unchanged."""

    def __init__(self, spec: ResidentPlanSpec):
        super().__init__(spec)
        self.rstats = ResidentPlanStats(plans=1, seams=len(spec.joins) + 1)
        self.spec_b64 = _encode_resident_payload(spec)
        self.key_origins = _key_origins(spec)
        self.n_feed = len(spec.feed.output_types)
        self._build_lock = threading.Lock()
        self._builds: dict = {}
        for j in spec.joins:
            self._builds[j.build_fid] = {
                "deposits": [None] * spec.n_tasks, "count": 0,
                "ready": threading.Event(), "table": None, "dicts": None}

    # ------------------------------------------------------------ build side
    def build_deposit(self, build_fid: int, task_index: int,
                      batches: list) -> None:
        slot = self._builds[build_fid]
        run_prep = False
        with self._build_lock:
            slot["deposits"][task_index] = batches
            slot["count"] += 1
            run_prep = slot["count"] == self.spec.n_tasks
        if run_prep:
            try:
                self._prep_build(build_fid)
            except BaseException as e:
                self._fail(e)
            slot["ready"].set()

    def _fail(self, e: BaseException) -> None:
        self._error = e
        for slot in self._builds.values():
            slot["ready"].set()
        self._done.set()

    def abort(self) -> None:
        self._error = RuntimeError("resident plan aborted")
        for slot in self._builds.values():
            slot["ready"].set()
        self._done.set()

    def _prep_build(self, build_fid: int) -> None:
        from ..telemetry import metrics as tm
        from ..telemetry import profiler

        spec = self.spec
        t0 = profiler.now() if profiler.enabled() else 0.0
        ji = next(i for i, j in enumerate(spec.joins)
                  if j.build_fid == build_fid)
        join = spec.joins[ji]
        col_types = spec.build_types[ji]
        ncols = len(col_types)
        n = spec.n_tasks
        slot = self._builds[build_fid]
        per_task = [list(slot["deposits"][t] or []) for t in range(n)]
        all_batches = [b for bs in per_task for b in bs]

        # unify dictionaries per column across every deposited batch: the
        # tiny dictionaries merge host-side, the code LANES stay codes all
        # the way through the broadcast gather below
        merged_dicts: list = [None] * ncols
        for c in range(ncols):
            dicts = [b.columns[c].dictionary for b in all_batches]
            dicts = [d for d in dicts if d is not None]
            if not dicts:
                continue
            first = dicts[0]
            if all(d is first or (d.shape == first.shape and (d == first).all())
                   for d in dicts):
                merged_dicts[c] = first
            else:
                merged_dicts[c] = np.unique(np.concatenate(dicts))
        n_code_cols = sum(1 for d in merged_dicts if d is not None)

        # host assembly per task lane: concat rows, remap codes into the
        # merged dictionary space, key-validity folds into the live lane
        rows = [sum(b.num_rows for b in bs) for bs in per_task]
        pcap = K.bucket(max(max(rows, default=0), 1))

        def padded(parts, dtype):
            a = (np.concatenate(parts) if parts
                 else np.zeros(0, dtype)).astype(dtype, copy=False)
            out = np.zeros(pcap, dtype)
            out[:len(a)] = a
            return out

        keys, lives = [], []
        data: list = [[] for _ in range(ncols)]
        valid: list = [[] for _ in range(ncols)]
        for t in range(n):
            kparts, lparts = [], []
            dparts: list = [[] for _ in range(ncols)]
            vparts: list = [[] for _ in range(ncols)]
            for b in per_task[t]:
                m = b.num_rows
                bl = (np.asarray(b.live) if b.live is not None
                      else np.ones(m, bool))
                kc = b.columns[join.build_key]
                lv = bl if kc.valid is None else bl & np.asarray(kc.valid)
                kparts.append(np.asarray(kc.data).astype(np.int64))
                lparts.append(lv)
                for c in range(ncols):
                    col = b.columns[c]
                    d = np.asarray(col.data)
                    md = merged_dicts[c]
                    if md is not None and col.dictionary is not None \
                            and col.dictionary is not md:
                        d = np.searchsorted(
                            md, col.dictionary).astype(np.int32)[d]
                    dparts[c].append(d)
                    vparts[c].append(
                        np.asarray(col.valid) if col.valid is not None
                        else np.ones(m, bool))
            keys.append(padded(kparts, np.int64))
            lives.append(padded(lparts, np.bool_))
            for c in range(ncols):
                dt = (np.int32 if merged_dicts[c] is not None
                      else np.dtype(col_types[c].storage_dtype))
                data[c].append(padded(dparts[c], dt))
                valid[c].append(padded(vparts[c], np.bool_))

        mesh, prog = _build_prep_program(n, ncols)
        srcs = [keys, lives]
        for c in range(ncols):
            srcs.append(data[c])
            srcs.append(valid[c])
        moved = jax.device_put(
            srcs, [[mesh.devices[i] for i in range(n)] for _ in srcs])
        flat = [
            jax.make_array_from_single_device_arrays(
                (n * pcap,), NamedSharding(mesh, P(_AXIS)), shards)
            for shards in moved]
        outs = prog(*flat)

        def rep(g):
            return g.addressable_shards[0].data

        bk, blive = rep(outs[0]), rep(outs[1])
        payload = tuple((rep(outs[2 + 2 * c]), rep(outs[3 + 2 * c]))
                        for c in range(ncols))
        dup = int(SG.fetch(outs[-1], "resident.build-dup"))
        if dup:
            raise ResidentPlanOverflow(
                f"resident plan f{spec.producer_fid}: build f{build_fid} "
                "has duplicate join keys (sorted probe is 1-match); "
                "falling back to the task-per-worker path")
        slot["table"] = (bk, blive, payload)
        slot["dicts"] = merged_dicts
        with self._build_lock:
            self.rstats.code_seam_columns += n_code_cols
        if n_code_cols:
            tm.RESIDENT_CODE_SEAMS.inc(n_code_cols)
        if t0:
            profiler.event(
                profiler.RESIDENT,
                f"resident-build[f{build_fid}->f{spec.producer_fid}]", t0,
                rows=sum(rows), code_columns=n_code_cols)

    # ------------------------------------------------------------ probe side
    def wait_builds(self) -> None:
        from .task import STALL_TIMEOUT_S

        for fid, slot in self._builds.items():
            if not slot["ready"].wait(STALL_TIMEOUT_S):
                raise TrinoError(
                    PAGE_TRANSPORT_TIMEOUT,
                    f"resident build f{fid} stalled after "
                    f"{STALL_TIMEOUT_S:.0f}s")
        if self._error is not None:
            raise self._error

    def build_tables(self) -> tuple:
        return tuple(self._builds[j.build_fid]["table"]
                     for j in self.spec.joins)

    def _build_col_dict(self, post_join_channel: int):
        """Merged dictionary of a build-origin post-join channel."""
        off = post_join_channel - self.n_feed
        for ji, types in enumerate(self.spec.build_types):
            if off < len(types):
                dicts = self._builds[self.spec.joins[ji].build_fid]["dicts"]
                return dicts[off] if dicts is not None else None
            off -= len(types)
        return None

    def initial_key_dicts(self) -> list:
        """Starting key dictionaries for a probe sink's carried state:
        build-origin dict keys are pinned to the merged build dictionary
        (stable all query); feed-origin keys start None and track batch
        drift in the sink."""
        out: list = [None] * self.spec.nk
        for j, o in enumerate(self.key_origins):
            if o is not None and o >= self.n_feed:
                out[j] = self._build_col_dict(o)
        return out

    # ------------------------------------------------------------- producers
    def deposit(self, task_index: int, state, key_dicts,
                sink_stats: FusedStageStats) -> None:
        with self._build_lock:
            self.rstats.batches += sink_stats.batches
            self.rstats.jit_calls += sink_stats.jit_calls
            self.rstats.programs += sink_stats.compiles
            self.rstats.cache_hits += sink_stats.cache_hits
            self.rstats.input_rows += sink_stats.input_rows
        super().deposit(task_index, state, key_dicts, sink_stats)

    def _run_merge(self) -> None:
        super()._run_merge()
        self.rstats.merges += 1


class ResidentBuildHandle:
    """Edge value for a build fragment folded into a resident plan: its
    tasks terminate in a ResidentBuildSinkOperator that deposits into the
    owning ResidentPlanExec."""

    def __init__(self, exchange: ResidentPlanExec, build_fid: int):
        self.exchange = exchange
        self.build_fid = build_fid

    def abort(self) -> None:
        self.exchange.abort()


class ResidentBuildSinkOperator(Operator):
    """Build-side terminal: batches stay exactly as produced (codes and
    all) and hand off to the broadcast prep at finish."""

    def __init__(self, handle: ResidentBuildHandle, task_index: int):
        self.handle = handle
        self.task_index = task_index
        self._batches: list = []

    def add_input(self, batch: ColumnBatch) -> None:
        if batch.num_rows:
            self._batches.append(batch)

    def finish_input(self) -> None:
        super().finish_input()
        self.handle.exchange.build_deposit(
            self.handle.build_fid, self.task_index, self._batches)

    def is_finished(self) -> bool:
        return self.input_done


class ResidentPlanSinkOperator(Operator):
    """Probe-side terminal of a resident plan: one whole-plan jitted
    dispatch per feed batch (SyncGuard hot region — the joins, chain,
    partial agg and state merge are all inside), overflow checked once at
    finish, state deposited into the inherited seam rendezvous."""

    def __init__(self, exchange: ResidentPlanExec, task_index: int):
        self.exchange = exchange
        self.task_index = task_index
        self.spec: ResidentPlanSpec = exchange.spec
        self._state: Optional[dict] = None
        self._key_dicts: Optional[list] = None
        self._remap_cache: dict = {}
        self._builds: Optional[tuple] = None
        self.stats = FusedStageStats()
        self.pending_errors: list = []

    def add_input(self, batch: ColumnBatch) -> None:
        if batch.num_rows == 0:
            return
        if self._builds is None:
            self.exchange.wait_builds()  # blocks OUTSIDE the hot region
            self._builds = self.exchange.build_tables()
        from ..telemetry import profiler

        t0 = profiler.now() if profiler.enabled() else 0.0
        with SG.hot_region():
            self._accumulate(batch)
        if t0:
            profiler.event(
                profiler.RESIDENT,
                f"resident-accumulate[f{self.spec.producer_fid}]", t0,
                rows=batch.num_rows)

    def _accumulate(self, batch: ColumnBatch) -> None:
        spec = self.spec
        raw_n = batch.num_rows
        n = raw_n if batch.live is not None else K.bucket(raw_n)
        prog = _resident_program(self.exchange.spec_b64, spec.cap)
        if self._state is None:
            self._state = prog.initial_state()
            self._key_dicts = self.exchange.initial_key_dicts()
        # feed-origin dictionary drift: lift carried-state codes and batch
        # codes into a merged dictionary before the (donated) state combine
        batch_remaps: list = [None] * spec.nk
        state_remaps: list = [None] * spec.nk
        n_feed = self.exchange.n_feed
        for j, origin in enumerate(self.exchange.key_origins):
            if origin is None or origin >= n_feed:
                continue
            bd = batch.columns[origin].dictionary
            if bd is None:
                continue
            cur = self._key_dicts[j]
            if cur is None:
                self._key_dicts[j] = bd
                continue
            if bd is cur:
                continue
            ck = (id(bd), id(cur))
            hit = self._remap_cache.get(ck)
            if hit is None:
                if bd.shape == cur.shape and (bd == cur).all():
                    hit = (None, None, cur)
                else:
                    merged = np.unique(np.concatenate([cur, bd]))
                    hit = (_pad_table(np.searchsorted(merged, bd)),
                           _pad_table(np.searchsorted(merged, cur)), merged)
                self._remap_cache[ck] = hit
            batch_remaps[j], state_remaps[j], merged = hit
            self._key_dicts[j] = merged
        miss_valid = tuple(c.valid is None for c in batch.columns)
        has_live = batch.live is not None
        if has_live or raw_n == n:
            # the common path: the program normalizes valids/live itself,
            # so the whole batch is exactly ONE dispatch
            feed_cols = tuple((c.data, c.valid) for c in batch.columns)
            live = batch.live
        else:
            ingest = _ingest_program(n, miss_valid, has_live)
            feed_cols, live = ingest(
                tuple((c.data, c.valid) for c in batch.columns), batch.live)
            miss_valid = tuple(False for _ in batch.columns)
            has_live = True
        sig = (id(prog), raw_n, n, miss_valid, has_live,
               tuple(None if r is None else len(r) for r in batch_remaps),
               tuple(None if r is None else len(r) for r in state_remaps))
        with _RES_LOCK:
            if sig in _RES_TRACE_SIGS:
                fresh = False
                self.stats.cache_hits += 1
            else:
                fresh = True
                _RES_TRACE_SIGS.add(sig)
                self.stats.compiles += 1
        if fresh:
            import time as _time

            from ..telemetry import metrics as tm

            t0 = _time.perf_counter()
            self._state = prog(self._state, feed_cols, live, self._builds,
                               tuple(batch_remaps), tuple(state_remaps))
            tm.RESIDENT_PROGRAMS.inc()
            tm.FUSED_COMPILE_SECONDS.record(_time.perf_counter() - t0)
        else:
            self._state = prog(self._state, feed_cols, live, self._builds,
                               tuple(batch_remaps), tuple(state_remaps))
        self.stats.jit_calls += 1
        self.stats.batches += 1
        self.stats.input_rows += n

    def finish_input(self) -> None:
        super().finish_input()
        if self._state is not None:
            # the one data-dependent scalar, pulled OUTSIDE the hot region,
            # once per task (not per batch)
            ovf = int(SG.fetch(self._state["ovf"], "resident.overflow"))
            if ovf > self.spec.cap:
                raise ResidentPlanOverflow(
                    f"resident plan f{self.spec.producer_fid}: {ovf} groups "
                    f"exceed the {self.spec.cap}-slot state "
                    f"(TRINO_TPU_FUSED_CAP); falling back to the "
                    f"task-per-worker path")
            self.pending_errors.append(self._state["err"])
        self.exchange.deposit(self.task_index, self._state, self._key_dicts,
                              self.stats)

    def is_finished(self) -> bool:
        return self.input_done


# ---------------------------------------------------------------------------
# runtime planning gate


def plan_resident_plans(fragments, session, task_counts: dict,
                        consumer_tasks: dict) -> dict:
    """Runtime gate over fragmenter-coalesced resident plans: returns
    {core_fid: ResidentPlanExec} plus {build_fid: ResidentBuildHandle}
    for plans where the mesh exists and every participating fragment's
    task count matches the mesh width (same conditions as the fused
    seam, extended over the whole subtree)."""
    if (resident_plan_mode() == "0" or fused_stage_mode() == "0"
            or not getattr(session, "use_collectives", True)):
        return {}
    from .collective_exchange import collectives_available

    by_id = {f.id: f for f in fragments}
    max_frags = resident_max_fragments()
    cap_dev = _mesh_device_cap()
    out: dict = {}
    for f in fragments:
        rp = getattr(f, "resident_plan", None)
        if rp is None or not getattr(f, "device_resident", False):
            continue
        if len(rp.fragment_ids) > max_frags:
            continue
        tc = task_counts.get(f.id)
        if (tc is None or consumer_tasks.get(f.id) != tc
                or task_counts.get(rp.consumer_fid) != tc
                or not collectives_available(tc)):
            continue
        if cap_dev and tc > cap_dev:
            continue
        if any(task_counts.get(j.build_fid) != tc for j in rp.joins):
            continue
        ex = ResidentPlanExec(
            build_resident_spec(f, by_id, tc, fused_cap()))
        out[f.id] = ex
        for j in rp.joins:
            out[j.build_fid] = ResidentBuildHandle(ex, j.build_fid)
    return out
