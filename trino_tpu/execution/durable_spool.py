"""Durable on-disk FTE spool: stage outputs survive task AND process death.

The round-3 FTE spool was Python lists in RAM — "retry" only worked because
failed tasks were threads that could not actually lose state (VERDICT item
#4).  This module is the engine's FileSystemExchangeManager miniature
(reference: plugin/trino-exchange-filesystem/.../FileSystemExchangeManager.
java:40, FileSystemExchangeSink):

- a task attempt writes its output as per-partition serde page files under
  ``<spool_root>/f<fragment>_t<task>/attempt-<n>.tmp/part-<p>.bin``;
- ``commit()`` atomically renames ``attempt-<n>.tmp`` -> ``attempt-<n>`` —
  only committed attempts are ever read, so a torn write from a dying
  process is invisible (the reference's exactly-once sink contract);
- readers stream frames from the committed directory; a worker-process
  death after commit loses nothing because the pages live on shared disk.

Part files carry the serde v2 CRC-checked stream framing (TTS2 header +
per-frame CRC32) so post-commit corruption — a bit flip or a torn sector —
surfaces as a retryable :class:`~.serde.SpoolCorruptionError` instead of
silently deserializing garbage; pre-CRC part files remain readable.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from typing import Iterator, Optional

from ..spi.batch import ColumnBatch
from .serde import (deserialize_batch, iter_frames, serialize_batch,
                    write_frame_crc, write_stream_header)

__all__ = ["DurableSpoolWriter", "DurableSpoolClient", "make_spool_root"]


def make_spool_root(base: Optional[str] = None) -> str:
    """New per-query spool root under ``base``, the TRINO_TPU_SPOOL_DIR
    knob, or the system tempdir (first one set wins).  Callers register
    the root with :mod:`.spool_gc` so retention and the boot-time leak
    sweep know about it."""
    if base is None:
        from ..spi.knobs import get_str

        base = get_str("TRINO_TPU_SPOOL_DIR") or None
        if base:
            os.makedirs(base, exist_ok=True)
    return tempfile.mkdtemp(prefix="trino-tpu-spool-", dir=base)


class DurableSpoolWriter:
    """Duck-types the OutputBuffer surface PartitionedOutputSink uses
    (enqueue / set_finished) but lands every page on disk."""

    def __init__(self, task_dir: str, attempt: int, num_partitions: int):
        self.num_partitions = num_partitions
        self._final = os.path.join(task_dir, f"attempt-{attempt}")
        self._tmp = self._final + ".tmp"
        if os.path.exists(self._tmp):  # leftovers from a crashed twin
            shutil.rmtree(self._tmp)
        os.makedirs(self._tmp)
        self._files = [
            open(os.path.join(self._tmp, f"part-{p}.bin"), "wb")
            for p in range(num_partitions)
        ]
        for f in self._files:
            write_stream_header(f)
        self.committed: Optional[str] = None

    def enqueue(self, partition: int, page) -> None:
        raw = page.data if hasattr(page, "data") else serialize_batch(page)
        write_frame_crc(self._files[partition], raw)

    def set_finished(self) -> None:
        if self.committed is not None:  # idempotent (sink + runner both call)
            return
        for f in self._files:
            f.flush()
            os.fsync(f.fileno())
            f.close()
        # atomic commit: a crash before this rename leaves only a .tmp that
        # no reader will ever open
        if os.path.exists(self._final):
            shutil.rmtree(self._tmp)
        else:
            os.rename(self._tmp, self._final)
        self.committed = self._final

    def abort(self) -> None:
        for f in self._files:
            try:
                f.close()
            # tpulint: disable=error-taxonomy -- abort cleanup is best-effort; rmtree below removes the spool
            except Exception:
                pass
        shutil.rmtree(self._tmp, ignore_errors=True)


def _iter_partition(attempt_dir: str, partition: int) -> Iterator[ColumnBatch]:
    path = os.path.join(attempt_dir, f"part-{partition}.bin")
    if not os.path.exists(path):
        return
    with open(path, "rb") as f:
        for frame in iter_frames(f, path):
            yield deserialize_batch(frame)


class DurableSpoolClient:
    """Duck-types ExchangeClient (poll / is_finished) over the committed
    spools of every producer task of one fragment."""

    def __init__(self, attempt_dirs: list[str], partition: int,
                 on_read=None):
        self._dirs = list(attempt_dirs)
        self.partition = partition
        self._iter = None
        self._pushback = None  # one-slot peek buffer (is_finished look-ahead)
        self._on_read = on_read  # failure-injection hook

    def _pages(self):
        for d in self._dirs:
            if self._on_read is not None:
                self._on_read(d)
            yield from _iter_partition(d, self.partition)

    def poll(self, timeout: float = 0.0):
        if self._pushback is not None:
            page, self._pushback = self._pushback, None
            return page
        if self._iter is None:
            self._iter = self._pages()
        return next(self._iter, None)

    def is_finished(self) -> bool:
        if self._pushback is not None:
            return False
        if self._iter is None:
            self._iter = self._pages()
        self._pushback = next(self._iter, None)
        return self._pushback is None
