"""Coordinator-side remote execution: worker processes over HTTP.

The process/network boundary of VERDICT round-3 item #3: the coordinator
spawns N worker processes (execution/worker.py), mirrors each task with an
:class:`HttpRemoteTask` (reference: server/remotetask/HttpRemoteTask.java:132
— create POST, status polling, cancel), and pages move worker->worker and
worker->coordinator through :class:`HttpExchangeClient` speaking the
pull-token results protocol (operator/HttpPageBufferClient.java:355,
operator/DirectExchangeClient.java:56).

``ProcessDistributedQueryRunner`` keeps the in-process
``DistributedQueryRunner`` planning/DDL surface and swaps the execution
backend: every fragment task runs in a real worker process; killing a
worker kills its tasks for real (the FTE recovery story becomes testable).
"""

from __future__ import annotations

import json
import os
import struct
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Optional

from ..runner import QueryResult, Session
from ..spi.batch import ColumnBatch
from .distributed_runner import DistributedQueryRunner
from .failure_injector import GET_RESULTS_FAILURE
from .fragmenter import SubPlan
from .serde import deserialize_batch
from .worker import encode_descriptor

__all__ = ["HttpExchangeClient", "HttpRemoteTask",
           "ProcessDistributedQueryRunner", "WorkerProcess"]


def _http(method: str, url: str, data: Optional[bytes] = None,
          timeout: float = 30.0):
    req = urllib.request.Request(url, data=data, method=method)
    # per-spawn internal shared secret (reference: server/
    # InternalCommunicationConfig.java:33 sharedSecret) — every node in the
    # cluster process tree carries it via env; the worker rejects mutating
    # or descriptor-decoding requests without it
    secret = os.environ.get("TRINO_TPU_INTERNAL_SECRET")
    if secret:
        req.add_header("X-Trino-Internal-Bearer", secret)
    return urllib.request.urlopen(req, timeout=timeout)


class HttpExchangeClient:
    """Pulls one partition from many upstream task result URIs; same
    poll/is_finished surface as the in-process ExchangeClient so operators
    are transport-agnostic."""

    def __init__(self, task_uris: list[str], partition: int):
        # [uri, token, done]
        self._sources = [[u, 0, False] for u in task_uris]
        self.partition = partition
        self._ready: list[ColumnBatch] = []

    def _fetch(self, s, timeout: float) -> int:
        uri, token, _done = s
        url = f"{uri}/results/{self.partition}/{token}"
        try:
            with _http("GET", url, timeout=max(timeout, 5.0)) as resp:
                body = resp.read()
                next_token = int(resp.headers.get("X-Next-Token", token))
                done = bool(int(resp.headers.get("X-Done", 0)))
        except urllib.error.HTTPError as e:
            if e.code == 404:  # task not created yet: transient
                return 0
            raise RuntimeError(
                f"exchange fetch failed ({e.code}): "
                f"{e.read()[:500]!r}") from e
        except (urllib.error.URLError, ConnectionError, TimeoutError):
            # worker unreachable: no-progress here; the coordinator's task
            # status sweep decides whether the producer is GONE and fails
            # the query (HttpPageBufferClient's backoff role)
            return 0
        count = 0
        pos = 0
        while pos + 4 <= len(body):
            (n,) = struct.unpack("<I", body[pos:pos + 4])
            pos += 4
            self._ready.append(deserialize_batch(body[pos:pos + n]))
            pos += n
            count += 1
        s[1] = next_token
        s[2] = done
        return count

    def poll(self, timeout: float = 0.05) -> Optional[ColumnBatch]:
        if self._ready:
            return self._ready.pop(0)
        for s in self._sources:
            if s[2]:
                continue
            if self._fetch(s, timeout):
                return self._ready.pop(0)
        return None

    def is_finished(self) -> bool:
        return not self._ready and all(done for _, _, done in self._sources)


class HttpRemoteTask:
    """Coordinator-side mirror of one worker task."""

    def __init__(self, worker_url: str, task_id: str):
        self.worker_url = worker_url
        self.task_id = task_id
        self.uri = f"{worker_url}/v1/task/{task_id}"

    def create(self, descriptor: dict) -> None:
        with _http("POST", self.uri, encode_descriptor(descriptor),
                   timeout=60.0) as resp:
            assert resp.status == 200

    def status(self) -> dict:
        try:
            with _http("GET", f"{self.uri}/status", timeout=10.0) as resp:
                return json.loads(resp.read())
        except (urllib.error.URLError, ConnectionError) as e:
            return {"state": "GONE", "error": str(e)}

    def cancel(self) -> None:
        try:
            _http("DELETE", self.uri, timeout=5.0).read()
        except Exception:
            pass


_SECRET_LOCK = threading.Lock()


class WorkerProcess:
    """One spawned worker (python -m trino_tpu.execution.worker)."""

    def __init__(self, env_overrides: Optional[dict] = None):
        # one shared secret per cluster process tree: minted on first spawn,
        # inherited by every worker and by worker->worker exchange fetches
        with _SECRET_LOCK:
            if "TRINO_TPU_INTERNAL_SECRET" not in os.environ:
                import secrets

                os.environ["TRINO_TPU_INTERNAL_SECRET"] = secrets.token_hex(16)
        env = dict(os.environ)
        env.update(env_overrides or {})
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "trino_tpu.execution.worker", "--port", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))))
        line = self.proc.stdout.readline()
        if not line.startswith("LISTENING"):
            raise RuntimeError(f"worker failed to boot: {line!r}")
        self.port = int(line.split()[1])
        self.url = f"http://127.0.0.1:{self.port}"

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        self.proc.kill()
        self.proc.wait(timeout=10)

    def shutdown(self) -> None:
        try:
            _http("PUT", f"{self.url}/v1/shutdown", timeout=5.0).read()
        except Exception:
            pass
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self.kill()


class ProcessDistributedQueryRunner(DistributedQueryRunner):
    """DistributedQueryRunner whose tasks run in real worker processes.

    ``catalog_spec`` = {"factory": "module:callable", "kwargs": {...}}
    reconstructs the catalog inside each worker (split generation is
    worker-side; only plan fragments and pages cross the wire)."""

    def __init__(self, catalog_spec: dict, worker_count: int = 2,
                 session: Optional[Session] = None,
                 env_overrides: Optional[dict] = None):
        from .worker import build_catalog

        super().__init__(build_catalog(catalog_spec),
                         worker_count=worker_count, session=session)
        self.catalog_spec = catalog_spec
        self.workers = [WorkerProcess(env_overrides)
                        for _ in range(worker_count)]
        self._query_seq = 0

    def close(self) -> None:
        for w in self.workers:
            w.shutdown()

    def __del__(self):  # best effort
        try:
            for w in self.workers:
                if w.alive():
                    w.proc.kill()
        except Exception:
            pass

    def fte_run_attempt(self, fragment, task_index: int, task_count: int,
                        nparts: int, upstream: dict, spool_root: str,
                        attempt: int, stats_sink: Optional[list],
                        memory_multiplier: float = 1.0) -> str:
        """Dispatch ONE FTE task attempt to a live worker PROCESS; the
        worker writes the durable spool (shared filesystem) and commits
        atomically.  A worker death mid-attempt surfaces here as GONE and
        the FTE retry loop re-dispatches to a surviving worker — recovery
        from real process loss, off the committed on-disk spools."""
        import os as _os

        from .fte import fte_task_dir

        alive = [w for w in self.workers if w.alive()]
        if not alive:
            raise RuntimeError("no live workers")
        w = alive[(fragment.id * 31 + task_index + attempt) % len(alive)]
        self._query_seq += 1
        task_dir = fte_task_dir(spool_root, fragment.id, task_index)
        _os.makedirs(task_dir, exist_ok=True)
        injector = getattr(self.session, "failure_injector", None)
        desc = {
            "fragment": fragment,
            "task_index": task_index,
            "task_count": task_count,
            "num_partitions": nparts,
            "upstream": {},
            "catalog": self.catalog_spec,
            "splits_per_node": self.session.splits_per_node,
            "node_count": self.worker_count,
            "dynamic_filtering": self.session.dynamic_filtering,
            "hbm_limit_bytes": int(
                self.session.hbm_limit_bytes * memory_multiplier),
            "spool": {"task_dir": task_dir, "attempt": attempt,
                      "num_partitions": nparts},
            "spool_upstream": upstream,
            "failure_rules": (
                injector.consume_for(
                    fragment.id, task_index, attempt,
                    # a leaf attempt (no upstream) never reaches the
                    # results-read injection point; new kinds export by
                    # default
                    unreachable=(set() if upstream
                                 else {GET_RESULTS_FAILURE}))
                if injector is not None else []),
        }
        rt = HttpRemoteTask(
            w.url, f"fte{self._query_seq}_f{fragment.id}_t{task_index}"
                   f"_a{attempt}")
        rt.create(desc)
        deadline = time.monotonic() + 600
        while True:
            st = rt.status()
            if st["state"] == "FINISHED":
                break
            if st["state"] in ("FAILED", "GONE", "CANCELED"):
                raise RuntimeError(
                    f"attempt failed ({st['state']}): {st.get('error')}")
            if time.monotonic() > deadline:
                rt.cancel()
                raise TimeoutError("fte attempt stalled")
            time.sleep(0.05)
        expected = _os.path.join(task_dir, f"attempt-{attempt}")
        if not _os.path.isdir(expected):
            raise RuntimeError("attempt reported FINISHED but no committed "
                               "spool found")
        if stats_sink is not None:
            from ..exec.stats import QueryStats

            stats_sink.append(QueryStats(
                label=f"fragment {fragment.id} task {task_index}: "
                      f"(remote worker {w.url})"))
        return expected

    # ------------------------------------------------------------- execution
    def _execute_subplan(self, subplan: SubPlan,
                         stats_sink: Optional[list]) -> QueryResult:
        if self.session.retry_policy == "TASK":
            from .fte import run_fte_query

            return self._to_result(
                subplan, run_fte_query(self, subplan, stats_sink))
        return self._run_remote(subplan)

    def _run_remote(self, subplan: SubPlan) -> QueryResult:
        self._query_seq += 1
        qid = f"pq{self._query_seq}"
        fragments = subplan.all_fragments()
        task_counts, consumer_tasks = self.stage_task_counts(fragments)
        alive = [w for w in self.workers if w.alive()]
        if not alive:
            raise RuntimeError("no live workers")

        # deterministic placement: task t of fragment f -> alive worker
        # (f*31 + t) % n  (UniformNodeSelector's role, minus locality)
        tasks: dict[tuple[int, int], HttpRemoteTask] = {}
        for f in fragments:
            for t in range(task_counts[f.id]):
                w = alive[(f.id * 31 + t) % len(alive)]
                tasks[(f.id, t)] = HttpRemoteTask(w.url, f"{qid}_f{f.id}_t{t}")

        by_id = {f.id: f for f in fragments}
        for f in fragments:
            tc = task_counts[f.id]
            for t in range(tc):
                upstream = {}
                for src in f.source_fragments:
                    src_tasks = [tasks[(src, i)].uri
                                 for i in range(task_counts[src])]
                    upstream[src] = {
                        "uris": src_tasks,
                        "merge": by_id[src].output_kind == "MERGE",
                    }
                desc = {
                    "fragment": f,
                    "task_index": t,
                    "task_count": tc,
                    "num_partitions": consumer_tasks.get(f.id, 1),
                    "upstream": upstream,
                    "catalog": self.catalog_spec,
                    "splits_per_node": self.session.splits_per_node,
                    "node_count": self.worker_count,
                    "dynamic_filtering": self.session.dynamic_filtering,
                    "hbm_limit_bytes": self.session.hbm_limit_bytes,
                }
                tasks[(f.id, t)].create(desc)

        # drain the root fragment's partition 0 as the client, watching
        # task statuses (fail fast on any FAILED task)
        root = subplan.fragment
        root_uris = [tasks[(root.id, t)].uri
                     for t in range(task_counts[root.id])]
        client = HttpExchangeClient(root_uris, 0)
        batches: list[ColumnBatch] = []
        deadline = time.monotonic() + 600
        last_status = 0.0
        try:
            while not client.is_finished():
                b = client.poll(timeout=0.2)
                if b is not None:
                    batches.append(b)
                    continue
                now = time.monotonic()
                if now - last_status > 1.0:
                    last_status = now
                    for (fid, t), rt in tasks.items():
                        st = rt.status()
                        if st["state"] in ("FAILED", "GONE"):
                            raise RuntimeError(
                                f"task f{fid}.t{t} {st['state']}: "
                                f"{st.get('error')}")
                if now > deadline:
                    raise TimeoutError("remote query stalled")
        except BaseException:
            for rt in tasks.values():
                rt.cancel()
            raise
        return self._to_result(subplan, batches)
